"""The HLO identity ledger: a declarative registry of flag-off programs.

Every opt-in subsystem in this repo ships with the same promise: *off
means off* — with the flag at its default, the lowered program is the
exact historical one, no callbacks, no collectives, no preconditioner
machinery. PRs 9–12 each pinned that promise with a hand-rolled
verbatim-reconstruction test; this module replaces the pattern with one
harness: each :class:`ProgramSpec` below names a flag-off program,
lowers it through the real entry point, canonicalizes the StableHLO
(``contracts.hlo``), fingerprints it, and checks **structural
assertions** (no ``custom_call``/callback with flags off, no
``shard_map``/``psum`` with ``mesh=None``, no ``dot_general`` under
jacobi — the MG coarse solve is a dense matmul) against the committed
ledger file ``poisson_tpu/contracts/ledger.json``.

A fingerprint mismatch means the flag-off lowering CHANGED — either an
intentional refactor (review the diff, run ``python -m
poisson_tpu.contracts --update-ledger``, commit the new ledger) or
exactly the drift class this gate exists to catch. Structural
violations are never ledgerable: a callback in a flag-off program is
wrong no matter what the committed fingerprint says.

Fingerprints are environment-sensitive (jax version, platform): the
ledger records both, and the check reports an environment mismatch
distinctly from genuine drift so a CPU ledger is never silently
"confirmed" by a TPU run.

Also here: the registry-drift allowlists (``ATTRIBUTION_ONLY_DETAIL``,
``POLICY_COVERAGE_EXEMPT``) — every exemption carries a reason string,
mirroring the lint's suppression contract.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Callable, Optional, Tuple

from poisson_tpu.contracts.hlo import (
    find_forbidden,
    hlo_fingerprint,
    markers_for,
    strip_hlo_metadata,
)

LEDGER_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "ledger.json")
LEDGER_SCHEMA = "poisson_tpu.contracts.ledger/1"


@dataclass(frozen=True)
class ProgramSpec:
    """One flag-off program under ledger protection.

    ``build`` returns the lowered StableHLO text via the real entry
    point (lazy jax import — the lint/drift half of the checker never
    pays for it). ``forbid`` names marker sets from ``contracts.hlo``
    (symbolic, so the marker vocabulary evolves in one place).
    """

    name: str
    description: str
    forbid: Tuple[str, ...]
    build: Callable[[], str]


# -- program builders (lazy imports; 20×24 f64 / 20×24 f32-scaled keep
# lowering fast while exercising every default-off flag) ---------------

def _problem():
    from poisson_tpu.config import Problem

    return Problem(M=20, N=24)


def _setup(dtype_name: str, scaled: bool):
    from poisson_tpu.solvers.pcg import host_setup

    return host_setup(_problem(), dtype_name, scaled)


def _build_solve_jacobi_f64() -> str:
    from poisson_tpu.solvers.pcg import _solve

    a, b, rhs, aux = _setup("float64", False)
    return _solve.lower(_problem(), False, 0, 0, 0.0, False, 0,
                        a, b, rhs, aux).as_text()


def _build_solve_scaled_f32() -> str:
    from poisson_tpu.solvers.pcg import _solve

    a, b, rhs, aux = _setup("float32", True)
    return _solve.lower(_problem(), True, 0, 0, 0.0, False, 0,
                        a, b, rhs, aux).as_text()


def _build_solve_history_f64() -> str:
    """The history-ON solve (``history_every=5``) — the forecast seam's
    opt-in program. Pinned so the convergence-history callback's shape
    is itself a contract: drift here means the traced telemetry
    changed, not just the flag-off byte-pin."""
    from poisson_tpu.solvers.pcg import _solve

    a, b, rhs, aux = _setup("float64", False)
    return _solve.lower(_problem(), False, 0, 0, 0.0, False, 5,
                        a, b, rhs, aux).as_text()


def _build_batched_mesh_none() -> str:
    import functools

    import jax
    import numpy as np

    from poisson_tpu.solvers.batched import _solve_batched

    p = _problem()
    a, b, rhs, aux = _setup("float64", False)
    stack = np.stack([np.asarray(rhs), np.asarray(rhs) * 1.1])
    return jax.jit(
        functools.partial(_solve_batched.__wrapped__, p, False, 0, 0.0)
    ).lower(a, b, stack, aux).as_text()


def _build_lanes_step_geometry_off() -> str:
    import jax
    import jax.numpy as jnp

    from poisson_tpu.solvers.lanes import _step_lanes
    from poisson_tpu.solvers.pcg import init_state, single_device_ops

    p = _problem()
    a, b, rhs, aux = _setup("float64", False)
    member = init_state(single_device_ops(p, a, b, aux), rhs)
    stacked = jax.tree_util.tree_map(lambda x: jnp.stack([x, x]), member)
    return _step_lanes.lower(p, False, 25, a, b, aux, stacked).as_text()


def _build_chunk_verify_off() -> str:
    from poisson_tpu.solvers.checkpoint import _run_chunk
    from poisson_tpu.solvers.pcg import init_state, single_device_ops

    p = _problem()
    a, b, rhs, aux = _setup("float64", False)
    state = init_state(single_device_ops(p, a, b, aux), rhs)
    return _run_chunk.lower(p, False, 50, 0, 0, 0, 0.0,
                            a, b, aux, None, state).as_text()


def _build_member_init() -> str:
    from poisson_tpu.solvers.lanes import _member_init

    p = _problem()
    a, b, rhs, aux = _setup("float64", False)
    return _member_init.lower(p, False, a, b, aux, rhs).as_text()


def _build_batched_mode_independent() -> str:
    """solve_batched's mode="independent" default resolved through the
    REAL entry-point branch (poisson_tpu.krylov threading, PR 14): the
    mode dispatch is host-side, so the lowered program must be the
    byte-identical historical bucket executable — this entry's
    fingerprint must EQUAL batched.mesh_none_f64's (asserted by
    tests/test_krylov.py on the committed ledger)."""
    import functools

    import jax
    import numpy as np

    from poisson_tpu.krylov import KRYLOV_INDEPENDENT, resolve_krylov
    from poisson_tpu.krylov import KrylovPolicy
    from poisson_tpu.solvers.batched import _solve_batched

    # The default policy must resolve to the independent mode (the
    # flag-off contract of the whole krylov subsystem)…
    assert resolve_krylov(None).mode == KRYLOV_INDEPENDENT
    assert KrylovPolicy().mode == KRYLOV_INDEPENDENT
    # …and the program it dispatches is the historical one.
    p = _problem()
    a, b, rhs, aux = _setup("float64", False)
    stack = np.stack([np.asarray(rhs), np.asarray(rhs) * 1.1])
    return jax.jit(
        functools.partial(_solve_batched.__wrapped__, p, False, 0, 0.0)
    ).lower(a, b, stack, aux).as_text()


def _build_stencil_apply_A() -> str:
    import jax
    import numpy as np

    from poisson_tpu.ops.stencil import apply_A

    p = _problem()
    a, b, _, _ = _setup("float64", False)
    w = np.zeros((p.M + 1, p.N + 1))
    return jax.jit(
        lambda w_, a_, b_: apply_A(w_, a_, b_, p.h1, p.h2)
    ).lower(w, np.asarray(a), np.asarray(b)).as_text()


def _build_session_step_cold() -> str:
    """A durable session's cold Poisson step resolved through the REAL
    entry point: ``solvers.session.session_step_solve`` with no warm
    iterate calls the literal historical ``pcg_solve``, so the lowered
    program must be the byte-identical flags-off executable — this
    entry's fingerprint must EQUAL solve.jacobi_f64's (asserted by
    tests/test_session.py on the committed ledger)."""
    from poisson_tpu.solvers.pcg import _solve

    a, b, rhs, aux = _setup("float64", False)
    return _solve.lower(_problem(), False, 0, 0, 0.0, False, 0,
                        a, b, rhs, aux).as_text()


def _build_session_warm_f64() -> str:
    import numpy as np

    from poisson_tpu.solvers.session import _solve_warm

    p = _problem()
    a, b, rhs, aux = _setup("float64", False)
    w0 = np.zeros((p.M + 1, p.N + 1))
    return _solve_warm.lower(p, False, a, b, rhs, aux, w0).as_text()


def _heat_operands():
    import numpy as np

    from poisson_tpu.solvers.session import shifted_setup

    p = _problem()
    a, b, rhs0, aux = shifted_setup(p, None, "float64", False, 0.5)
    u = np.zeros((p.M + 1, p.N + 1))
    return p, a, b, rhs0, aux, np.asarray(0.5, np.float64), u


def _build_session_heat_cold() -> str:
    from poisson_tpu.solvers.session import _solve_shifted

    p, a, b, rhs0, aux, m, u = _heat_operands()
    return _solve_shifted.lower(p, False, False, a, b, rhs0, aux,
                                m, u, u).as_text()


def _build_session_heat_warm() -> str:
    from poisson_tpu.solvers.session import _solve_shifted

    p, a, b, rhs0, aux, m, u = _heat_operands()
    return _solve_shifted.lower(p, False, True, a, b, rhs0, aux,
                                m, u, u).as_text()


def _build_serve_routed_default() -> str:
    from poisson_tpu.serve.router import executor_backend
    from poisson_tpu.solvers.pcg import _solve

    # The router is an OBSERVATION-plane chooser: whatever arm it
    # names, execution runs through the xla executor gate until a
    # future PR lands real pallas dispatch. If that gate ever opens,
    # this program is no longer the flags-off lowering and the pin
    # below must be revisited deliberately, not silently.
    for arm in ("xla", "pallas_resident", "pallas_ca"):
        if executor_backend(arm) != "xla":
            raise AssertionError(
                f"executor_backend({arm!r}) no longer gates to xla — "
                "the routed default program is not the flags-off "
                "lowering any more")
    a, b, rhs, aux = _setup("float64", False)
    return _solve.lower(_problem(), False, 0, 0, 0.0, False, 0,
                        a, b, rhs, aux).as_text()


_ALL_OFF = ("callbacks", "collectives", "mg")

PROGRAMS: Tuple[ProgramSpec, ...] = (
    ProgramSpec(
        name="solve.jacobi_f64",
        description="pcg_solve default path (jacobi, stream/verify/"
                    "abft off, f64 unscaled) — the flagship flag-off "
                    "executable every golden count rests on",
        forbid=_ALL_OFF,
        build=_build_solve_jacobi_f64,
    ),
    ProgramSpec(
        name="solve.scaled_f32",
        description="pcg_solve scaled-f32 path (the TPU default "
                    "precision policy), all flags off",
        forbid=_ALL_OFF,
        build=_build_solve_scaled_f32,
    ),
    ProgramSpec(
        name="solve.history_f64",
        description="pcg_solve with history_every=5 — the forecast "
                    "residual-history seam's opt-in program (callbacks "
                    "legal here; collectives/mg still forbidden)",
        forbid=("collectives", "mg"),
        build=_build_solve_history_f64,
    ),
    ProgramSpec(
        name="batched.mesh_none_f64",
        description="solve_batched with mesh=None — the single-device "
                    "bucket executable family (no shard_map/psum ever)",
        forbid=_ALL_OFF,
        build=_build_batched_mesh_none,
    ),
    ProgramSpec(
        name="lanes.step_geometry_off",
        description="LaneBatch chunk stepping, geometry/verify off — "
                    "the continuous engine's flag-off lane program",
        forbid=_ALL_OFF,
        build=_build_lanes_step_geometry_off,
    ),
    ProgramSpec(
        name="chunk.verify_off",
        description="checkpoint _run_chunk with stream/verify off — "
                    "the chunked drivers' flag-off advance program",
        forbid=_ALL_OFF,
        build=_build_chunk_verify_off,
    ),
    ProgramSpec(
        name="lanes.member_init",
        description="jitted member init (splice seam) — byte-identical "
                    "state construction for every spliced member",
        forbid=_ALL_OFF,
        build=_build_member_init,
    ),
    ProgramSpec(
        name="batched.mode_independent_f64",
        description="solve_batched mode='independent' (the krylov "
                    "flag-off default) — must lower to the byte-"
                    "identical historical bucket executable "
                    "(fingerprint equals batched.mesh_none_f64)",
        forbid=_ALL_OFF,
        build=_build_batched_mode_independent,
    ),
    ProgramSpec(
        name="stencil.apply_A_unbatched",
        description="the unbatched 5-point stencil application — the "
                    "PR 9 batch-polymorphism pin (2D HLO unchanged)",
        forbid=_ALL_OFF,
        build=_build_stencil_apply_A,
    ),
    ProgramSpec(
        name="session.step_cold_f64",
        description="a durable session's cold Poisson step (no warm "
                    "iterate offered) — must lower to the byte-"
                    "identical historical flags-off executable "
                    "(fingerprint equals solve.jacobi_f64)",
        forbid=_ALL_OFF,
        build=_build_session_step_cold,
    ),
    ProgramSpec(
        name="session.warm_f64",
        description="the warm-started session step (restart_state "
                    "from the previous iterate instead of zero init; "
                    "same flags-off PCG body)",
        forbid=_ALL_OFF,
        build=_build_session_warm_f64,
    ),
    ProgramSpec(
        name="session.heat_cold_f64",
        description="one implicit-Euler heat step (A + m*I, transient "
                    "RHS composed in-graph), zero init — the cold "
                    "shifted-operator program every heat session "
                    "stream compiles once",
        forbid=_ALL_OFF,
        build=_build_session_heat_cold,
    ),
    ProgramSpec(
        name="session.heat_warm_f64",
        description="the warm implicit-Euler heat step (restart from "
                    "the previous time level) — the steady-state "
                    "program of a converging transient stream",
        forbid=_ALL_OFF,
        build=_build_session_heat_warm,
    ),
    ProgramSpec(
        name="serve.routed_default_f64",
        description="the program a router-enabled service actually "
                    "executes on the default path: every routed arm "
                    "gates through the xla executor, so the lowering "
                    "must stay byte-identical to the historical "
                    "flags-off executable (fingerprint equals "
                    "solve.jacobi_f64) — the router may only ever "
                    "change attribution, never numerics",
        forbid=_ALL_OFF,
        build=_build_serve_routed_default,
    ),
)


def _environment() -> dict:
    import jax

    return {
        "jax_version": jax.__version__,
        "platform": jax.default_backend(),
    }


def lower_program(spec: ProgramSpec) -> str:
    """Lower one registered program (enables x64 first — the f64
    entries are the oracle-parity lowerings and must not silently
    truncate to f32)."""
    import jax

    jax.config.update("jax_enable_x64", True)
    return spec.build()


def load_ledger(path: Optional[str] = None) -> Optional[dict]:
    path = path or LEDGER_PATH
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def run_ledger_check(update: bool = False,
                     path: Optional[str] = None) -> dict:
    """Lower every registered program, check structure + fingerprint
    against the committed ledger. Returns a report dict with
    ``problems`` (each ``{kind, program, message}``) — empty means the
    contract holds. ``update=True`` rewrites the ledger from the
    current tree (structural violations still fail: they are never
    ledgerable)."""
    path = path or LEDGER_PATH
    env = _environment()
    ledger = load_ledger(path)
    problems: list = []
    entries: dict = {}
    if ledger is None and not update:
        # A gate that silently stopped producing evidence is not a
        # passing gate: an absent/corrupt committed ledger must FAIL,
        # not degrade into "nothing to compare against".
        problems.append({
            "kind": "ledger-absent", "program": "*",
            "message": (
                f"committed ledger missing or unreadable at {path} — "
                f"restore it from version control, or mint a reviewed "
                f"one with --update-ledger"),
        })
    for spec in PROGRAMS:
        try:
            text = lower_program(spec)
        except Exception as e:  # a program that no longer lowers IS drift
            problems.append({
                "kind": "lowering-error", "program": spec.name,
                "message": f"entry point failed to lower: {e!r}",
            })
            continue
        violations = find_forbidden(text, markers_for(spec.forbid))
        if violations:
            problems.append({
                "kind": "hlo-structure", "program": spec.name,
                "message": (
                    f"forbidden op marker(s) {violations} in the "
                    f"flag-off lowering — never ledgerable"),
            })
        fp = hlo_fingerprint(text)
        entries[spec.name] = {
            "fingerprint": fp,
            "canonical_bytes": len(strip_hlo_metadata(text)),
            "forbid": list(spec.forbid),
            "description": spec.description,
        }
        if update or ledger is None:
            continue
        committed = (ledger.get("entries") or {}).get(spec.name)
        if committed is None:
            problems.append({
                "kind": "ledger-missing", "program": spec.name,
                "message": (
                    "program is registered but absent from the "
                    "committed ledger — run --update-ledger and commit"),
            })
        elif committed.get("fingerprint") != fp:
            env_committed = {k: ledger.get(k) for k in
                            ("jax_version", "platform")}
            env_note = ("" if env_committed == env else
                        f" (environment differs: ledger {env_committed} "
                        f"vs current {env} — re-run where the ledger "
                        f"was minted before judging)")
            problems.append({
                "kind": "ledger-drift", "program": spec.name,
                "message": (
                    f"flag-off lowering changed: committed "
                    f"{committed.get('fingerprint', '?')[:16]}…, "
                    f"current {fp[:16]}… — an intentional refactor "
                    f"needs --update-ledger + review; anything else is "
                    f"the drift this gate exists for{env_note}"),
            })
    stale = set((ledger or {}).get("entries") or {}) - {
        s.name for s in PROGRAMS}
    for name in sorted(stale):
        problems.append({
            "kind": "ledger-stale", "program": name,
            "message": "ledger entry has no registered program — "
                       "remove it via --update-ledger",
        })
    report = {
        "schema": "poisson_tpu.contracts.ledger-check/1",
        "ledger": path,
        "environment": env,
        "programs": len(PROGRAMS),
        "entries": entries,
        "problems": problems,
        "updated": False,
    }
    if update and not any(p["kind"] in ("hlo-structure", "lowering-error")
                          for p in problems):
        with open(path, "w") as f:
            json.dump({"schema": LEDGER_SCHEMA, **env,
                       "entries": entries}, f, indent=1, sort_keys=True)
            f.write("\n")
        report["updated"] = True
        # drift/missing/stale problems are resolved by the rewrite
        report["problems"] = [p for p in problems if p["kind"]
                              in ("hlo-structure", "lowering-error")]
    return report


# -- registry-drift allowlists (reason strings required) ---------------

# bench.py detail keys that are deliberately attribution/diagnosis
# payload, NOT experiment identity — everything else a bench mode emits
# must join benchmarks/regress.py's cohort key (see contracts.drift).
ATTRIBUTION_ONLY_DETAIL = {
    # measurement payload & derived readings
    "iterations": "the measured quantity, not identity",
    "iterations_match_sequential": "parity verdict on the measurement",
    "converged": "outcome tally of the measurement",
    "batch_seconds": "raw timing payload",
    "sequential_solve_seconds": "raw timing payload",
    "first_run_seconds": "compile-time payload",
    "solve_seconds": "raw timing payload",
    "warmup_seconds": "compile-time payload",
    "makespan_seconds": "raw timing payload",
    "p50_seconds": "latency payload (p99 is the record's own metric)",
    "p99_seconds": "latency payload",
    "forecast_calibration_err_pct":
        "measured forecaster error, not identity — records_from_result "
        "lifts it into its own obs.forecast.calibration_err_pct "
        "sentinel record (lower-is-better), it never splits the "
        "primary record's cohort",
    "verify_overhead": "the A/B delta is the record's payload",
    "preconditioner_ab": "both-arm A/B payload (cohort key carries "
                         "detail.preconditioner)",
    # request-mix tallies (outcomes, not offered-load identity)
    "requests": "offered count; arrival_rate is the identity",
    "completed": "outcome tally",
    "errors": "outcome tally",
    "shed": "outcome tally",
    "lost": "invariant check (bench exits 1 when nonzero)",
    "quarantines": "churn outcome tally",
    "device_losses": "churn outcome tally",
    "placement_rebinds": "churn outcome tally",
    "kill_fired": "whether the injected fault actually fired (fault_"
                  "load is relabeled clean when it did not)",
    "kill_worker_at": "fault timing detail under fault_load",
    "kill_device_at": "fault timing detail under fault_load",
    "scheduling": "engine name is carried by the metric itself "
                  "(sustained vs drain gauges)",
    "batch": "solve_batched pads to detail.bucket; grid+bucket are "
             "the executable identity",
    "bucket": "executable width, derivable from batch; grid is the "
              "cohort axis",
    "geometry_fingerprints": "operand identity, never cohort identity "
                             "(the PR 9 invariant)",
    "geom_cache_hits": "cache telemetry snapshot",
    "geom_cache_misses": "cache telemetry snapshot",
    "bucket_cache_hits": "cache telemetry snapshot",
    "bucket_cache_misses": "cache telemetry snapshot",
    "refill_splices": "refill telemetry snapshot",
    "warmed_buckets": "warm-up inventory",
    "device_kind": "device_topology/devices carry the cohort "
                   "topology; kind is diagnosis",
    "placement": "registry snapshot payload",
    "p99_exemplar": "flight-recorder trace id (pinned attribution-only "
                    "by tests/test_flight.py)",
    "slowest_requests": "flight-recorder decompositions (pinned "
                        "attribution-only by tests/test_flight.py)",
    # A/B second-arm payload: the record's value/cohort is the
    # continuous arm; the drain arm rides along for the comparison.
    "continuous_beats_drain": "A/B verdict over both arms",
    "drain_solves_per_sec": "drain-arm payload (its own gauge exists)",
    "drain_p50_seconds": "drain-arm latency payload",
    "drain_p99_seconds": "drain-arm latency payload",
    "drain_makespan_seconds": "drain-arm timing payload",
    "idle_lane_steps": "refill telemetry snapshot",
    # fleet-churn outcome tallies and invariant verdicts
    "device_loss_fired": "whether the injected loss actually fired "
                         "(fault_load relabels clean when not)",
    "every_request_accounted": "ledger-invariant verdict (bench exits "
                               "1 when false)",
    "recovered_requests": "churn outcome tally",
    "restarts": "churn outcome tally",
    "sticky_hits": "routing telemetry snapshot",
    # single-solve / verify-A/B measurement payload
    "final_diff": "convergence payload of the measurement",
    "l2_error_vs_analytic": "accuracy payload of the measurement",
    "serial_reduce": "timing-methodology note",
    "iterations_baseline": "unverified-arm payload of the A/B record",
    # Krylov-memory A/B and repeat-fingerprint payload (cohort key
    # carries detail.krylov_mode / detail.deflation /
    # detail.repeat_fingerprint)
    "krylov_block_ab": "both-arm A/B payload (cohort key carries "
                       "detail.krylov_mode)",
    "cold_requests": "arm-size tally of the one run",
    "warm_requests": "arm-size tally of the one run",
    "cold_p50_seconds": "cold-arm latency payload (the record's value "
                        "is the run's sustained throughput)",
    "cold_p99_seconds": "cold-arm latency payload",
    "warm_p50_seconds": "warm-arm latency payload",
    "warm_p99_seconds": "warm-arm latency payload",
    "krylov_hit_rate": "basis-cache telemetry snapshot",
    "krylov_harvests": "basis-cache telemetry snapshot",
    "krylov_iterations_saved": "basis-cache telemetry snapshot",
    "krylov_fallbacks": "basis-cache telemetry snapshot",
    "deflated_bytes_per_iter_model": "analytic cost-model reading "
                                     "(obs.costs.krylov_deflated_cost)",
    # durable-session A/B payload (cohort key carries detail.session /
    # detail.warm_start; detail.steps is run length, not identity —
    # steps/sec already normalizes by it)
    "steps": "run length; the per-step rate is the record's value",
    "session_ab": "both-arm A/B payload (cohort key carries "
                  "detail.session and detail.warm_start)",
    # backend-router attribution (cohort split rides on
    # detail.routed_backend, which regress.py lifts into the key)
    "router": "decision-mix / sentinel / measured-fraction / roofline-"
              "calibration snapshot; detail.routed_backend is the "
              "cohort discriminator regress.py lifts",
    # serve-mode latency/throughput payload beside the record's value
    "p95_seconds": "latency payload",
    "shed_rate": "outcome-rate payload (its own gauge exists)",
    "throughput_rps": "derived reading of the same run",
    "wall_seconds": "raw timing payload",
    # mixed-tenant attribution (cohort split rides on
    # detail.tenant_mix, which regress.py lifts into the key)
    "tenants": "per-tenant p99/shed-rate/share attribution block; "
               "detail.tenant_mix is the cohort discriminator "
               "regress.py lifts",
    "tenant_promotions": "fair-queue telemetry snapshot",
}

# ServicePolicy/FleetPolicy fields a chaos scenario need not exercise —
# each with the reason it is exempt. Everything else must appear in at
# least one scenario (kwarg or attribute) in testing/chaos.py.
POLICY_COVERAGE_EXEMPT = {
    "ServicePolicy.slo": "SLO accounting is scored by the flight "
                         "recorder over ordinary outcomes; burn-driven "
                         "degradation is opt-in and covered by "
                         "tests/test_flight.py, deliberately not by "
                         "the deterministic chaos campaign (default "
                         "OFF keeps scenario outcomes seed-stable)",
    "ServicePolicy.preconditioner": "the MG service default changes "
                                    "numerics, not failure handling; "
                                    "serve-side MG is exercised by "
                                    "tests/test_mg.py cohort-split "
                                    "tests",
}
