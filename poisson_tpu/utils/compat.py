"""Version portability shims for the narrow slice of JAX API this
framework depends on.

``shard_map`` graduated from ``jax.experimental.shard_map`` (where its
replication check is spelled ``check_rep``) to ``jax.shard_map`` (where it
is spelled ``check_vma``). The parallel stack is written against the new
spelling; this shim keeps it running on JAX versions that only ship the
experimental entry point — a resilience concern in its own right: the
sharded solvers (and their checkpoint/recovery paths) must not be the
first thing to break when the environment pins an older JAX.
"""

from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` when available, else the experimental API with
    ``check_vma`` mapped onto its older ``check_rep`` spelling."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )
