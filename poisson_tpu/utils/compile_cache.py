"""JAX persistent compilation cache, env-driven, with hit/miss counters.

Compile time is the dominant fixed cost of every cold start in this stack
(the flagship solve compiles in seconds; the solve itself runs in under
one) — and the batched driver multiplies the stakes: one bucket executable
serves hundreds of solves, so persisting it across processes turns every
warm start into pure execute time. ``POISSON_TPU_COMPILE_CACHE=<dir>``
points JAX's persistent compilation cache at ``<dir>``; both entry points
(``poisson_tpu.cli`` and ``bench.py``) call :func:`enable_from_env` before
their first trace.

Cache traffic is surfaced through the unified telemetry counters
(``obs.metrics``): JAX publishes ``/jax/compilation_cache/cache_hits`` /
``…/cache_misses`` on its ``jax.monitoring`` bus, and the listener
registered here folds them into ``compile_cache.hits`` /
``compile_cache.misses`` — landing in the same snapshot as
``time.compile_seconds``, so a metrics file alone answers "did this run
pay for its compiles or reuse them?".
"""

from __future__ import annotations

import os

ENV_VAR = "POISSON_TPU_COMPILE_CACHE"

_LISTENER_INSTALLED = False

# jax.monitoring event names → our counter names (low cardinality, dotted —
# the obs.metrics convention).
_EVENT_COUNTERS = {
    "/jax/compilation_cache/cache_hits": "compile_cache.hits",
    "/jax/compilation_cache/cache_misses": "compile_cache.misses",
}


def _listener(event: str, **kwargs) -> None:
    name = _EVENT_COUNTERS.get(event)
    if name is not None:
        from poisson_tpu.obs import metrics

        metrics.inc(name)


def install_counters() -> bool:
    """Register the monitoring listener (idempotent). Separate from
    :func:`enable_from_env` so tests can exercise the counter wiring
    without touching the process-wide cache config. Returns False when
    this JAX build has no monitoring bus."""
    global _LISTENER_INSTALLED
    if _LISTENER_INSTALLED:
        return True
    try:
        from jax import monitoring
    except ImportError:
        return False
    monitoring.register_event_listener(_listener)
    _LISTENER_INSTALLED = True
    return True


def enable_from_env() -> bool:
    """Enable the persistent compilation cache when ``ENV_VAR`` is set.

    Points ``jax_compilation_cache_dir`` at the directory (created if
    missing) and zeroes the persistence thresholds so even the small/fast
    programs this stack compiles are persisted (the defaults skip entries
    below a minimum size and compile time). Installs the hit/miss
    counters whenever the env var is set, even if the config update then
    fails (the counters are how that failure gets noticed). Returns True
    iff the cache was enabled; unset env or a failing config update (an
    exotic JAX build) degrades to False, never to an exception — a cache
    problem must not take the solve down.
    """
    path = os.environ.get(ENV_VAR)
    if not path:
        return False
    import jax

    install_counters()
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception:
        return False
    from poisson_tpu.obs import metrics

    metrics.gauge("compile_cache.dir", path)
    return True
