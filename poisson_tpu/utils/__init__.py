from poisson_tpu.utils.timing import PhaseTimer, SolveReport, mlups, solve_report

__all__ = ["PhaseTimer", "SolveReport", "mlups", "solve_report"]
