"""Instrumentation: phase timing and the solve report.

TPU-native equivalent of stage4's manual ``MPI_Wtime`` bracketing
(``stage4-mpi+cuda/poisson_mpi_cuda_f.cu:696-701,956-980``: five accumulators
gpu/copy/comm/precond/dot, MPI_Reduce(MAX), rank-0 table; plus the
init/solver/finalize phase split in ``main``, ``…cu:1010-1034``).

Under XLA there is no per-op host bracketing — the whole solve is one fused
device program, which is the point (stage4 lost 20%+ to per-op sync, BASELINE
Table 2). What remains meaningful on the host side:

- phase wall-clock (trace/compile vs execute, init vs solve), via
  :class:`PhaseTimer` with explicit ``block_until_ready`` fencing — the
  ``MPI_Barrier``+``MPI_Wtime`` pattern of ``stage2:…cpp:483-490``;
- derived throughput (MLUPS = interior points × iterations / second — the
  BASELINE.json metric);
- for intra-program category breakdown, ``jax.profiler.trace`` captures a
  device timeline (stage4's per-category table, done by the profiler instead
  of hand-inserted timers).
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Optional

import jax

from poisson_tpu.config import Problem


def fence(tree) -> None:
    """Wait until every array in ``tree`` is actually computed.

    ``block_until_ready`` alone is not trusted: on experimental/tunneled
    platforms (e.g. the axon TPU transport) it can return while execution is
    still in flight, which made 989-iteration solves appear to take 0 s.
    Fetching a value to the host cannot lie, so after blocking this pulls
    each array's first element (scalars whole) — a few bytes per leaf.
    """
    jax.block_until_ready(tree)
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "ravel") and getattr(leaf, "size", 0) > 0:
            jax.device_get(leaf.ravel()[0])


class PhaseTimer:
    """Named wall-clock phases with device fencing.

    Thin compatibility shim over the unified span API
    (``poisson_tpu.obs``): each phase is an ``obs`` span (fenced at exit
    — the MPI_Barrier+Wtime idiom, stage2:…cpp:483-490), so when
    telemetry is configured the phase lands on the Perfetto timeline and
    in the event log; the accumulated ``times`` dict keeps the historical
    interface either way.

    >>> t = PhaseTimer()
    >>> with t.phase("solve"):
    ...     result = pcg_solve(problem)   # doctest: +SKIP
    >>> t.times["solve"]                  # doctest: +SKIP
    """

    def __init__(self) -> None:
        self.times: dict[str, float] = {}

    def phase(self, name: str):
        timer = self

        class _Ctx:
            def __enter__(self):
                from poisson_tpu import obs

                self._span = obs.span(name, fence=False)
                self._span.__enter__()
                self._t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                # Fence outstanding device work so the phase boundary is
                # real (the MPI_Barrier+Wtime idiom, stage2:…cpp:483-490)
                # — done here, before the span closes, so both the span's
                # recorded duration and ``times`` include the fence, and
                # the fence still runs when telemetry is unconfigured.
                try:
                    jax.effects_barrier()
                except Exception:
                    pass
                self._span.__exit__(*exc)
                timer.times[name] = timer.times.get(name, 0.0) + (
                    time.perf_counter() - self._t0
                )

        return _Ctx()


def mlups(problem: Problem, iterations: int, seconds: float) -> float:
    """Million lattice-site updates per second: interior·iters/time/1e6 —
    the BASELINE.json throughput metric."""
    return problem.interior_points * iterations / seconds / 1e6


@dataclasses.dataclass
class SolveReport:
    """Stage4-style result report (``…cu:969-980`` and the rank-0 result
    line ``stage2:…cpp:493-498``), as structured data."""

    M: int
    N: int
    iterations: int
    solve_seconds: float
    compile_seconds: float
    mlups: float
    final_diff: float
    dtype: str
    devices: int
    mesh: Optional[tuple[int, int]] = None
    l2_error: Optional[float] = None
    # Termination verdict name (solvers.pcg.FLAG_NAMES) when the solver
    # stopped for a reason other than convergence; None otherwise.
    stopped: Optional[str] = None
    # Which solve path ran, and on what silicon — makes CLI records
    # joinable with bench session records (which already log both).
    backend: Optional[str] = None
    device_kind: Optional[str] = None
    # Recovery provenance (resilient solves): attempts taken and the
    # (iteration, verdict, action) history — surfaced on SUCCESS too,
    # not only inside DivergenceError.
    restarts: Optional[int] = None
    recovery: Optional[tuple] = None
    # Batched solves: batch size and the per-member iteration vector
    # (``iterations`` above then holds the scalar max the fused loop ran).
    batch: Optional[int] = None
    iterations_per_member: Optional[list] = None
    # Performance attribution (obs.costs): the backend's effective
    # bytes/iteration model, the HBM bandwidth this run achieved, and
    # the fraction of the platform ceiling that represents (None when
    # the backend has no pass model or the ceiling is unknown — an
    # honest gap, never a made-up number).
    bytes_per_iter_model: Optional[float] = None
    achieved_gbps: Optional[float] = None
    roofline_fraction: Optional[float] = None

    def json_line(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    def table(self) -> str:
        rows = [
            f"M={self.M}, N={self.N} | Iter={self.iterations} "
            + (f"(max of {self.batch} members) " if self.batch else "")
            + f"| Time={self.solve_seconds:.4f} s",
            f"  compile: {self.compile_seconds:.2f} s   dtype: {self.dtype}"
            f"   devices: {self.devices}"
            + (f"   mesh: {self.mesh[0]}x{self.mesh[1]}" if self.mesh else "")
            + (f"   backend: {self.backend}" if self.backend else "")
            + (f" [{self.device_kind}]" if self.device_kind else ""),
            f"  throughput: {self.mlups:.0f} MLUPS   final ||dw||: "
            f"{self.final_diff:.3e}"
            + (
                f"   L2 err vs analytic: {self.l2_error:.3e}"
                if self.l2_error is not None
                else ""
            ),
        ]
        if self.achieved_gbps is not None:
            rows.append(
                f"  attribution: {self.achieved_gbps:.1f} GB/s effective"
                + (
                    f" = {self.roofline_fraction:.0%} of roofline"
                    if self.roofline_fraction is not None
                    else " (no bandwidth ceiling on file for this "
                         "device; set POISSON_TPU_PEAK_GBPS)"
                )
            )
        if self.restarts:
            detail = "; ".join(
                f"iter {k}: {verdict} -> {action}"
                for k, verdict, action in (self.recovery or ())
            )
            rows.append(
                f"  recovered: {self.restarts} restart(s)"
                + (f" ({detail})" if detail else "")
            )
        if self.stopped is not None:
            rows.append(f"  WARNING: solve stopped without converging "
                        f"({self.stopped})")
        return "\n".join(rows)


def solve_report(
    problem: Problem,
    result,
    solve_seconds: float,
    compile_seconds: float,
    dtype: str,
    devices: int = 1,
    mesh: Optional[tuple[int, int]] = None,
    l2_error: Optional[float] = None,
    backend: Optional[str] = None,
    device_kind: Optional[str] = None,
) -> SolveReport:
    import numpy as np

    from poisson_tpu import obs
    from poisson_tpu.solvers.pcg import iterations_scalar

    # Batched results carry per-member vectors; the report's scalar slots
    # hold the honest wall-clock values (the fused loop's max) and the
    # per-member vector rides alongside.
    iters_arr = np.asarray(result.iterations)
    batched = iters_arr.ndim > 0
    iters = iterations_scalar(result.iterations)
    # Verdict-tracking solvers (PCGResult.flag) surface abnormal stops in
    # the report; converged/untracked results stay quiet.
    stopped = None
    flag = getattr(result, "flag", None)
    flag_name = "untracked"
    if flag is not None:
        from poisson_tpu.solvers.pcg import FLAG_CONVERGED, FLAG_NAMES, \
            FLAG_NONE

        # Vector flags: the worst member wins, by severity — failure
        # verdicts (breakdown/nonfinite/stagnated) first, then
        # done-without-verdict (FLAG_NONE, e.g. a budget-exhausted
        # member), then converged. A plain max() would rank FLAG_NONE (0)
        # below FLAG_CONVERGED (1) and report a cap-hit batch as
        # converged.
        flags = np.asarray(flag).ravel()
        failures = flags[(flags != FLAG_NONE) & (flags != FLAG_CONVERGED)]
        if failures.size:
            flag = int(failures.max())
        elif (flags == FLAG_NONE).any():
            flag = FLAG_NONE
        else:
            flag = int(flags.max()) if flags.size else FLAG_NONE
        flag_name = FLAG_NAMES.get(flag, str(flag))
        if flag == FLAG_NONE:
            # done-without-verdict (cap hit, or a verdict-less solver
            # path): count it as what the historical reading was.
            flag_name = "running"
        if flag not in (FLAG_NONE, FLAG_CONVERGED):
            stopped = FLAG_NAMES.get(flag, str(flag))
    # Solve-level counters: solves and iterations by stop verdict, plus
    # compile vs execute seconds (accumulating float counters).
    obs.inc(f"pcg.solves.{flag_name}")
    obs.inc(f"pcg.iterations.{flag_name}", iters)
    obs.inc("time.compile_seconds", max(0.0, compile_seconds))
    obs.inc("time.execute_seconds", max(0.0, solve_seconds))
    restarts = getattr(result, "restarts", None)
    recovery = getattr(result, "recovery_history", None)
    # Roofline attribution (obs.costs): achieved bandwidth against the
    # backend's pass model and the platform ceiling. Advisory — any
    # failure (exotic dtype name, no pass model for this backend) leaves
    # the fields None rather than touching the report's core job.
    useful_iters = int(iters_arr.sum()) if batched else iters
    bytes_per_iter = achieved_gbps = fraction = None
    try:
        from poisson_tpu.obs.costs import roofline_summary

        rl = roofline_summary(
            problem, backend, np.dtype(dtype).itemsize, useful_iters,
            solve_seconds, device_kind=device_kind, devices=max(1, devices),
        )
        bytes_per_iter = rl["bytes_per_iter_model"]
        achieved_gbps = rl["achieved_gbps"]
        fraction = rl["fraction"]
    except Exception:
        pass
    return SolveReport(
        M=problem.M,
        N=problem.N,
        iterations=iters,
        solve_seconds=solve_seconds,
        compile_seconds=compile_seconds,
        # Batched: throughput counts every member's useful updates
        # (Σ member iterations, same numerator the roofline attribution
        # above uses), not just the slowest member's — a B=64 batch's
        # MLUPS must be comparable with B=64 sequential reports, not
        # ~64× under them.
        mlups=mlups(problem, useful_iters, solve_seconds),
        final_diff=float(np.max(np.asarray(result.diff))),
        batch=(int(iters_arr.shape[0]) if batched else None),
        iterations_per_member=(
            [int(k) for k in iters_arr] if batched else None
        ),
        dtype=dtype,
        devices=devices,
        mesh=mesh,
        l2_error=l2_error,
        stopped=stopped,
        backend=backend,
        device_kind=device_kind,
        restarts=(int(restarts) if restarts else None),
        recovery=(tuple(recovery) if restarts and recovery else None),
        bytes_per_iter_model=bytes_per_iter,
        achieved_gbps=achieved_gbps,
        roofline_fraction=fraction,
    )
