"""Instrumentation: phase timing and the solve report.

TPU-native equivalent of stage4's manual ``MPI_Wtime`` bracketing
(``stage4-mpi+cuda/poisson_mpi_cuda_f.cu:696-701,956-980``: five accumulators
gpu/copy/comm/precond/dot, MPI_Reduce(MAX), rank-0 table; plus the
init/solver/finalize phase split in ``main``, ``…cu:1010-1034``).

Under XLA there is no per-op host bracketing — the whole solve is one fused
device program, which is the point (stage4 lost 20%+ to per-op sync, BASELINE
Table 2). What remains meaningful on the host side:

- phase wall-clock (trace/compile vs execute, init vs solve), via
  :class:`PhaseTimer` with explicit ``block_until_ready`` fencing — the
  ``MPI_Barrier``+``MPI_Wtime`` pattern of ``stage2:…cpp:483-490``;
- derived throughput (MLUPS = interior points × iterations / second — the
  BASELINE.json metric);
- for intra-program category breakdown, ``jax.profiler.trace`` captures a
  device timeline (stage4's per-category table, done by the profiler instead
  of hand-inserted timers).
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Optional

import jax

from poisson_tpu.config import Problem


def fence(tree) -> None:
    """Wait until every array in ``tree`` is actually computed.

    ``block_until_ready`` alone is not trusted: on experimental/tunneled
    platforms (e.g. the axon TPU transport) it can return while execution is
    still in flight, which made 989-iteration solves appear to take 0 s.
    Fetching a value to the host cannot lie, so after blocking this pulls
    each array's first element (scalars whole) — a few bytes per leaf.
    """
    jax.block_until_ready(tree)
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "ravel") and getattr(leaf, "size", 0) > 0:
            jax.device_get(leaf.ravel()[0])


class PhaseTimer:
    """Named wall-clock phases with device fencing.

    >>> t = PhaseTimer()
    >>> with t.phase("solve"):
    ...     result = pcg_solve(problem)   # doctest: +SKIP
    >>> t.times["solve"]                  # doctest: +SKIP
    """

    def __init__(self) -> None:
        self.times: dict[str, float] = {}

    def phase(self, name: str):
        timer = self

        class _Ctx:
            def __enter__(self):
                self._t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                # Fence outstanding device work so the phase boundary is
                # real (the MPI_Barrier+Wtime idiom, stage2:…cpp:483-490).
                try:
                    jax.effects_barrier()
                except Exception:
                    pass
                timer.times[name] = timer.times.get(name, 0.0) + (
                    time.perf_counter() - self._t0
                )

        return _Ctx()


def mlups(problem: Problem, iterations: int, seconds: float) -> float:
    """Million lattice-site updates per second: interior·iters/time/1e6 —
    the BASELINE.json throughput metric."""
    return problem.interior_points * iterations / seconds / 1e6


@dataclasses.dataclass
class SolveReport:
    """Stage4-style result report (``…cu:969-980`` and the rank-0 result
    line ``stage2:…cpp:493-498``), as structured data."""

    M: int
    N: int
    iterations: int
    solve_seconds: float
    compile_seconds: float
    mlups: float
    final_diff: float
    dtype: str
    devices: int
    mesh: Optional[tuple[int, int]] = None
    l2_error: Optional[float] = None
    # Termination verdict name (solvers.pcg.FLAG_NAMES) when the solver
    # stopped for a reason other than convergence; None otherwise.
    stopped: Optional[str] = None

    def json_line(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    def table(self) -> str:
        rows = [
            f"M={self.M}, N={self.N} | Iter={self.iterations} "
            f"| Time={self.solve_seconds:.4f} s",
            f"  compile: {self.compile_seconds:.2f} s   dtype: {self.dtype}"
            f"   devices: {self.devices}"
            + (f"   mesh: {self.mesh[0]}x{self.mesh[1]}" if self.mesh else ""),
            f"  throughput: {self.mlups:.0f} MLUPS   final ||dw||: "
            f"{self.final_diff:.3e}"
            + (
                f"   L2 err vs analytic: {self.l2_error:.3e}"
                if self.l2_error is not None
                else ""
            ),
        ]
        if self.stopped is not None:
            rows.append(f"  WARNING: solve stopped without converging "
                        f"({self.stopped})")
        return "\n".join(rows)


def solve_report(
    problem: Problem,
    result,
    solve_seconds: float,
    compile_seconds: float,
    dtype: str,
    devices: int = 1,
    mesh: Optional[tuple[int, int]] = None,
    l2_error: Optional[float] = None,
) -> SolveReport:
    iters = int(result.iterations)
    # Verdict-tracking solvers (PCGResult.flag) surface abnormal stops in
    # the report; converged/untracked results stay quiet.
    stopped = None
    flag = getattr(result, "flag", None)
    if flag is not None:
        from poisson_tpu.solvers.pcg import FLAG_CONVERGED, FLAG_NAMES, \
            FLAG_NONE

        flag = int(flag)
        if flag not in (FLAG_NONE, FLAG_CONVERGED):
            stopped = FLAG_NAMES.get(flag, str(flag))
    return SolveReport(
        M=problem.M,
        N=problem.N,
        iterations=iters,
        solve_seconds=solve_seconds,
        compile_seconds=compile_seconds,
        mlups=mlups(problem, iters, solve_seconds),
        final_diff=float(result.diff),
        dtype=dtype,
        devices=devices,
        mesh=mesh,
        l2_error=l2_error,
        stopped=stopped,
    )
