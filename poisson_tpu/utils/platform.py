"""Platform-selection hardening shared by every entry point.

JAX resolves the platform from ``jax.config.jax_platforms`` first and the
``JAX_PLATFORMS`` env var second — so a ``sitecustomize`` startup hook that
rewrites the config (remote-accelerator PJRT plugins do) silently overrides
the user's env var, and the first ``jax.devices()`` can then hang on an
unreachable remote backend the user explicitly opted out of. Every CLI/
benchmark entry point calls :func:`honor_jax_platforms_env` before its
first device touch to make the env var authoritative again.
"""

from __future__ import annotations

import os


def honor_jax_platforms_env() -> None:
    """Re-assert an explicitly-set ``JAX_PLATFORMS`` into ``jax.config``
    (config beats env; see module docstring). No-op when the var is unset."""
    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        import jax

        jax.config.update("jax_platforms", platforms)
