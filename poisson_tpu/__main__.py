from poisson_tpu.cli import main

raise SystemExit(main())
