"""Batched-path smoke check: ``python -m poisson_tpu.solvers.batched_selfcheck``.

The ``obs.selfcheck`` pattern applied to the multi-RHS driver: a tiny
batch with distinct RHS gates must reproduce the sequential solver
bit-for-bit per member (iterates, flags, iteration counts — the masked
freeze working), pad to its bucket invisibly, and count its bucket-cache
traffic in ``obs.metrics``. Exit 0 on success, 1 with a reason on the
first failure — a few CPU seconds, so CI can prove the batched pipeline
end to end.
"""

from __future__ import annotations

import sys


def run_selfcheck() -> int:
    import numpy as np

    from poisson_tpu.config import Problem
    from poisson_tpu.obs import metrics
    from poisson_tpu.solvers.batched import bucket_size, solve_batched
    from poisson_tpu.solvers.pcg import FLAG_CONVERGED, pcg_solve

    def fail(reason: str) -> int:
        print(f"batched selfcheck FAILED: {reason}", file=sys.stderr)
        return 1

    problem = Problem(M=40, N=40)
    gates = (0.25, 1.0, 4.0)
    seq = [pcg_solve(problem, rhs_gate=g) for g in gates]
    bat = solve_batched(problem, rhs_gates=gates)

    iters = np.asarray(bat.iterations)
    if iters.shape != (len(gates),):
        return fail(f"iterations not per-member: shape {iters.shape}")
    for i, r in enumerate(seq):
        if int(iters[i]) != int(r.iterations):
            return fail(f"member {i}: iterations {int(iters[i])} != "
                        f"sequential {int(r.iterations)}")
        if int(np.asarray(bat.flag)[i]) != int(r.flag):
            return fail(f"member {i}: flag mismatch")
        if not np.array_equal(np.asarray(bat.w)[i], np.asarray(r.w)):
            return fail(f"member {i}: solution not bit-identical")
    if len({int(k) for k in iters}) < 2:
        return fail("gates did not produce distinct iteration counts — "
                    "the masked freeze went unexercised")
    if not (np.asarray(bat.flag) == FLAG_CONVERGED).all():
        # Equality, not min(): the failure flags (breakdown/nonfinite/
        # stagnated) rank ABOVE converged numerically.
        return fail("not every member converged")
    if int(bat.max_iterations) != max(int(r.iterations) for r in seq):
        return fail("max_iterations disagrees with the member vector")
    if bucket_size(len(gates)) != 4:
        return fail("bucket ladder changed: 3 members should bucket to 4")
    hits0 = metrics.get("batched.bucket_cache.hits")
    solve_batched(problem, rhs_gates=gates)   # same bucket: a cache hit
    if metrics.get("batched.bucket_cache.hits") <= hits0:
        return fail("bucket-cache hit not counted on reuse")
    print(f"batched selfcheck OK: {len(gates)} members (bucket 4), "
          f"iterations {[int(k) for k in iters]}, all converged "
          "bit-identical to sequential")
    return 0


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m poisson_tpu.solvers.batched_selfcheck",
        description=__doc__.splitlines()[0],
    )
    ap.parse_args(argv)
    from poisson_tpu.utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()
    return run_selfcheck()


if __name__ == "__main__":
    sys.exit(main())
