"""Batched multi-RHS solves: one traced program, many Poisson problems.

Every path in the framework — like all five reference implementations
(SURVEY §0) — solved exactly one right-hand side per dispatch. This driver
applies the block-CG insight (O'Leary 1980, PAPERS.md) as a *hardware
batching* transform rather than a Krylov-subspace change: the operator is
identical across members, so B right-hand sides stack on a leading batch
axis and the shared PCG body (``solvers.pcg.make_pcg_body``) is ``vmap``-ed
over it. One compile, one ``lax.while_loop``, one kernel launch sequence —
compile time, dispatch overhead, and coefficient-field memory traffic are
paid once for the whole batch, the same throughput move every inference
serving stack makes (Orca, PAPERS.md).

Per-member convergence masking keeps the iterate sequences honest: each
member carries its own ``flag``/``k``, a member that stops (converged,
breakdown, non-finite, budget) is *frozen* — the vmapped body still computes
its would-be update, a per-member select discards it — and the fused loop
exits when every member has stopped. A member's iterates, flags, and
iteration counts therefore match the sequential ``pcg_loop`` bit-for-bit
(tests/test_batched.py asserts exactly this, f32 and f64).

Ragged request sets are padded to a bucket size so one compiled executable
serves many batch sizes: a zero RHS converges degenerately at iteration 1
(ζ₀ = 0 trips the |（Ap,p)| guard), so padding members cost one masked
iteration and are sliced off before returning. Bucket-cache reuse is
surfaced via ``obs.metrics`` (``batched.bucket_cache.hits``/``.misses``).

Composition with the sharded path (``mesh=``): the batch axis is vmapped
*outside* ``shard_map`` — members stay whole-grid, the mesh splits the
grid, not the batch. One dispatch then solves B right-hand sides on an
N-device mesh (``parallel.pcg_sharded.solve_batched_sharded``): the
vmapped body runs per shard over the local block stack, every
per-member reduction is a ``psum``-replicated mesh scalar, and the halo
exchange + coefficient traffic of each iteration are paid once for the
whole batch. ``mesh=None`` (the default) keeps the single-device
programs byte-for-byte. Executable families that have no sharded
program yet (per-member geometries, MG, the in-loop integrity probe)
are rejected loudly when combined with ``mesh=``.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from poisson_tpu import obs
from poisson_tpu.config import Problem
from poisson_tpu.solvers.pcg import (
    PCGOps,
    PCGResult,
    PCGState,
    host_setup,
    init_state,
    make_pcg_body,
    resolve_dtype,
    resolve_scaled,
    scaled_single_device_ops,
    single_device_ops,
    solve_setup,
)

# Bucket ladder for padding ragged batch sizes onto a small set of compiled
# executables. Powers of two up to 256: request sets beyond the top bucket
# compile at their exact size (a deliberate escape hatch, not an error).
DEFAULT_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256)

# Shapes this process has already traced, keyed like the jit cache
# ((bucket, M, N, dtype, scaled, weighted, delta, cap)). Mirrors XLA's own
# compile cache so the hit/miss counters in obs.metrics tell the serving
# story (a ragged arrival pattern that buckets well shows hits >> misses).
_TRACED: set = set()


def reset_bucket_cache() -> None:
    """Forget which bucket shapes this process has traced (tests; a
    library user pairing it with ``obs.metrics.reset()`` — the counters
    and this set must move together or hit/miss arithmetic goes stale)."""
    _TRACED.clear()


def bucket_size(n: int, buckets: Sequence[int] = DEFAULT_BUCKETS) -> int:
    """Smallest bucket ≥ n (n itself beyond the ladder)."""
    if n < 1:
        raise ValueError(f"batch size must be >= 1, got {n}")
    for b in buckets:
        if n <= b:
            return int(b)
    return int(n)


def pcg_loop_batched(ops: PCGOps, rhs_stack, *, delta: float, max_iter: int,
                     weighted_norm: bool, h1: float, h2: float,
                     stagnation_window: int = 0, verify_every: int = 0,
                     verify_tol: float = 0.0,
                     preconditioner: str = "jacobi") -> PCGState:
    """Run the shared PCG body over a (B, M+1, N+1) RHS stack in ONE fused
    ``while_loop`` with per-member convergence masking.

    The body is the exact sequential body (``make_pcg_body``) vmapped over
    the batch axis; each iteration then freezes every member whose previous
    state was already stopped (done, or at the iteration cap) by selecting
    its old state over the computed update — so a member's trajectory is
    identical to what ``pcg_loop`` would have produced, including its
    final ``k`` and ``flag``. The loop exits when no member can advance.

    Streaming (``stream_every``) is deliberately not plumbed here: the
    host callback is per-iteration scalar telemetry and has no meaningful
    vmapped form; the batched path reports per-member outcomes instead.

    ``verify_every`` > 0 arms the in-loop integrity probe PER MEMBER
    (``poisson_tpu.integrity``): the body's pair form
    (``make_pcg_member_body``) is vmapped with the RHS stack so every
    member's true residual is checked against its OWN right-hand side —
    a flipped bit stops only the corrupted member with FLAG_INTEGRITY;
    its batchmates' trajectories are untouched (masked, like every
    other per-member stop). At 0 the program is the exact historical
    one.
    """
    if verify_every > 0:
        from poisson_tpu.solvers.pcg import make_pcg_member_body

        member = make_pcg_member_body(
            ops, delta=delta, weighted_norm=weighted_norm, h1=h1, h2=h2,
            stagnation_window=stagnation_window,
            verify_every=verify_every, verify_tol=verify_tol,
            preconditioner=preconditioner,
        )
        vpair = jax.vmap(member, in_axes=(0, 0))
        vbody = lambda s: vpair(s, rhs_stack)
    else:
        body = make_pcg_body(
            ops, delta=delta, weighted_norm=weighted_norm, h1=h1, h2=h2,
            stagnation_window=stagnation_window,
        )
        vbody = jax.vmap(body)
    init = jax.vmap(functools.partial(init_state, ops))(rhs_stack)

    def masked_body(s: PCGState) -> PCGState:
        stepped = vbody(s)
        frozen = s.done | (s.k >= max_iter)

        def keep(old, new):
            pred = frozen.reshape(frozen.shape + (1,) * (new.ndim - 1))
            return jnp.where(pred, old, new)

        return jax.tree_util.tree_map(keep, s, stepped)

    def cond(s: PCGState):
        return jnp.any((~s.done) & (s.k < max_iter))

    return lax.while_loop(cond, masked_body, init)


def member_field_ops(problem: Problem, scaled: bool):
    """Per-member ops factory for stacked-canvas programs. ONE
    construction shared by the fused batched solve and the lane stepping
    engine — their bit-parity contract rests on it."""

    def member_ops(a, b, aux):
        return (
            scaled_single_device_ops(problem, a, b, aux)
            if scaled
            else single_device_ops(problem, a, b, aux)
        )

    return member_ops


def pcg_step_batched_fields(problem: Problem, scaled: bool, a_stack,
                            b_stack, aux_stack, state: PCGState,
                            stop_at, *, delta: float,
                            weighted_norm: bool, h1: float,
                            h2: float, verify_every: int = 0,
                            verify_tol: float = 0.0,
                            rhs_stack=None) -> PCGState:
    """Masked vmapped stepping over PER-MEMBER coefficient canvases:
    every member solves its OWN fictitious domain with the shared PCG
    body until it reaches ``stop_at`` — a scalar cap for the fused
    solve, a per-member stop line for the lane engine
    (:mod:`poisson_tpu.solvers.lanes`). Stopped/frozen members keep
    their state via per-member select, exactly like
    :func:`pcg_loop_batched`. ``verify_every`` > 0 arms the per-member
    integrity probe (``rhs_stack`` — each member's OWN RHS — is then
    required and vmapped alongside its canvases)."""
    member_ops = member_field_ops(problem, scaled)

    if verify_every > 0:
        from poisson_tpu.solvers.pcg import make_pcg_member_body

        if rhs_stack is None:
            raise ValueError("verify_every > 0 needs rhs_stack — the "
                             "per-member probe checks each member's own "
                             "true residual")

        def member_body_v(s: PCGState, a, b, aux, rhs) -> PCGState:
            body = make_pcg_member_body(
                member_ops(a, b, aux), delta=delta,
                weighted_norm=weighted_norm, h1=h1, h2=h2,
                verify_every=verify_every, verify_tol=verify_tol,
            )
            return body(s, rhs)

        vbody_v = jax.vmap(member_body_v)
        step = lambda s: vbody_v(s, a_stack, b_stack, aux_stack,
                                 rhs_stack)
    else:
        def member_body(s: PCGState, a, b, aux) -> PCGState:
            body = make_pcg_body(
                member_ops(a, b, aux), delta=delta,
                weighted_norm=weighted_norm, h1=h1, h2=h2,
            )
            return body(s)

        vbody = jax.vmap(member_body)
        step = lambda s: vbody(s, a_stack, b_stack, aux_stack)

    def masked_body(s: PCGState) -> PCGState:
        stepped = step(s)
        frozen = s.done | (s.k >= stop_at)

        def keep(old, new):
            pred = frozen.reshape(frozen.shape + (1,) * (new.ndim - 1))
            return jnp.where(pred, old, new)

        return jax.tree_util.tree_map(keep, s, stepped)

    def cond(s: PCGState):
        return jnp.any((~s.done) & (s.k < stop_at))

    return lax.while_loop(cond, masked_body, state)


def pcg_loop_batched_fields(problem: Problem, scaled: bool, a_stack,
                            b_stack, aux_stack, rhs_stack, *,
                            delta: float, max_iter: int,
                            weighted_norm: bool, h1: float,
                            h2: float, verify_every: int = 0,
                            verify_tol: float = 0.0) -> PCGState:
    """:func:`pcg_loop_batched` with PER-MEMBER coefficient canvases:
    a/b/aux carry a leading (B, …) axis and are vmapped alongside the
    state, so every member solves its OWN fictitious domain inside the
    one fused ``while_loop`` (mixed-geometry co-batching,
    ``poisson_tpu.geometry``). Member *i*'s arithmetic is the exact
    sequential solve of its canvases — per-member reductions make lane
    trajectories independent — so iterates/flags/counts match
    ``pcg_solve(problem, geometry=g_i)`` bit-for-bit (asserted in
    tests)."""
    member_ops = member_field_ops(problem, scaled)
    init = jax.vmap(
        lambda rhs, a, b, aux: init_state(member_ops(a, b, aux), rhs)
    )(rhs_stack, a_stack, b_stack, aux_stack)
    return pcg_step_batched_fields(
        problem, scaled, a_stack, b_stack, aux_stack, init, max_iter,
        delta=delta, weighted_norm=weighted_norm, h1=h1, h2=h2,
        verify_every=verify_every, verify_tol=verify_tol,
        rhs_stack=(rhs_stack if verify_every > 0 else None))


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3))
def _solve_batched_geo(problem: Problem, scaled: bool, verify_every: int,
                       verify_tol: float, a_stack, b_stack,
                       rhs_stack, aux_stack) -> PCGResult:
    """jitted mixed-geometry batched solve: one executable per
    (bucket, grid, dtype, scaled) — the SAME executable no matter which
    geometries occupy the members (canvases are operands, never part of
    the jit key), which is what lets a second geometry family land as a
    bucket-cache hit with zero recompiles. ``verify_every``/``verify_tol``
    are the static per-member integrity-probe knobs (0 = the exact
    historical program)."""
    s = pcg_loop_batched_fields(
        problem, scaled, a_stack, b_stack, aux_stack, rhs_stack,
        delta=problem.delta, max_iter=problem.iteration_cap,
        weighted_norm=problem.weighted_norm,
        h1=problem.h1, h2=problem.h2,
        verify_every=verify_every, verify_tol=verify_tol,
    )
    w = s.w * aux_stack if scaled else s.w   # per-member unscale
    return PCGResult(w=w, iterations=s.k, diff=s.diff, residual_dot=s.zr,
                     flag=s.flag, max_iterations=jnp.max(s.k))


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3))
def _solve_batched(problem: Problem, scaled: bool, verify_every: int,
                   verify_tol: float, a, b, rhs_stack,
                   aux) -> PCGResult:
    """jitted batched solve over a (B, M+1, N+1) RHS stack; compiled once
    per (bucket, grid, dtype, scaled) — the executable every padded
    request set of the same bucket reuses. ``verify_every``/``verify_tol``
    are the static per-member integrity-probe knobs (0 = the exact
    historical program)."""
    ops = (
        scaled_single_device_ops(problem, a, b, aux)
        if scaled
        else single_device_ops(problem, a, b, aux)
    )
    s = pcg_loop_batched(
        ops, rhs_stack,
        delta=problem.delta, max_iter=problem.iteration_cap,
        weighted_norm=problem.weighted_norm,
        h1=problem.h1, h2=problem.h2,
        verify_every=verify_every, verify_tol=verify_tol,
    )
    w = s.w * aux if scaled else s.w   # aux broadcasts over the batch axis
    return PCGResult(w=w, iterations=s.k, diff=s.diff, residual_dot=s.zr,
                     flag=s.flag, max_iterations=jnp.max(s.k))


def _shared_base(problems: Sequence[Problem]) -> Problem:
    """Validate that every member shares the operator (everything except
    the RHS magnitude ``f_val``) and return the shared base problem."""
    if not problems:
        raise ValueError("solve_batched needs at least one problem")
    base = problems[0]
    for i, p in enumerate(problems[1:], start=1):
        if p.with_(f_val=base.f_val) != base:
            raise ValueError(
                "batched members must share the operator — every Problem "
                "field except f_val must match member 0; member "
                f"{i} differs: {p} vs {base}"
            )
    return base


def _count_bucket(key: tuple, batch: int, bucket: int) -> None:
    if key in _TRACED:
        obs.inc("batched.bucket_cache.hits")
    else:
        _TRACED.add(key)
        obs.inc("batched.bucket_cache.misses")
    obs.inc("batched.solves", batch)
    obs.inc("batched.padding_members", bucket - batch)
    obs.gauge("batched.last_bucket", bucket)


def solve_batched(problems=None, *, rhs_stack=None, rhs_gates=None,
                  dtype=None, scaled=None, mesh=None,
                  buckets: Sequence[int] = DEFAULT_BUCKETS,
                  bucket: Optional[int] = None,
                  member_ids: Optional[Sequence] = None,
                  geometries: Optional[Sequence] = None,
                  verify_every: int = 0,
                  verify_tol=None,
                  preconditioner: str = "jacobi",
                  mg_config=None,
                  mode: str = "independent") -> PCGResult:
    """Solve a batch of Poisson problems in one fused device program.

    Input forms (exactly one):

    - ``solve_batched([p0, p1, …])`` — a sequence of :class:`Problem`
      sharing everything but ``f_val`` (the operator must be shared; the
      RHS may differ member-to-member). Each member's RHS is built by the
      same fp64 host setup the sequential solver uses, so member ``i``
      reproduces ``pcg_solve(p_i)`` bit-for-bit.
    - ``solve_batched(p, rhs_gates=[g0, g1, …])`` — one problem, B scalar
      RHS multipliers (the batched mirror of ``pcg_solve``'s ``rhs_gate``;
      also the bench/CLI chaining hook — gates may be traced scalars).
    - ``solve_batched(p, rhs_stack=B_array)`` — one problem, an explicit
      (B, M+1, N+1) stack of physical right-hand sides (zero Dirichlet
      ring; internally mapped to the scaled system when ``scaled``).

    The batch is zero-padded to :func:`bucket_size` (``bucket`` pins an
    explicit size ≥ B) so ragged request sets reuse one compiled
    executable per bucket; padding members stop degenerately at iteration
    1 and are sliced off before returning. Returns a :class:`PCGResult`
    whose ``w``/``iterations``/``diff``/``residual_dot``/``flag`` carry a
    leading batch axis (``iterations`` is the per-member truth) plus the
    scalar ``max_iterations`` the fused loop actually ran.

    ``dtype``/``scaled`` follow ``pcg_solve``'s precision policy.

    ``mesh`` (a :class:`jax.sharding.Mesh` from
    ``parallel.mesh.make_solver_mesh``) runs the whole bucket as ONE
    sharded dispatch — vmap outside ``shard_map``: members stay
    whole-grid, the mesh splits the grid, halo exchange amortizes over
    the batch. Per-member iteration counts and stop flags reproduce the
    unsharded batched driver (iterates agree to reduction-order ULPs —
    ``psum`` of shard-local sums associates differently than one
    full-grid sum; pinned by tests/test_placement.py). ``mesh=None``
    keeps the historical single-device executables byte-for-byte.
    Combinations without a sharded program (``geometries``, MG,
    ``verify_every`` > 0) are rejected loudly.

    ``member_ids`` (optional, one hashable id per member) rides through
    padding and slicing onto ``PCGResult.origin``, so position ``i`` of
    every returned per-member field is attributable to ``origin[i]`` no
    matter how the batch was padded or re-formed. Default: ``(0, …, B−1)``.
    This is the requeue seam the solve service (``poisson_tpu.serve``)
    needs — a member re-enqueued into a *different* bucket after a fault
    keeps its request identity — and is useful standalone (aggregate
    bucket stats are no longer the only per-dispatch record).

    ``geometries`` (optional, one :mod:`poisson_tpu.geometry` spec or
    None per member) gives each member its OWN fictitious domain:
    coefficient canvases stack on a leading batch axis and the shared
    body is vmapped over them too, so *different geometries on the same
    grid co-batch in one bucket executable* — the executable is keyed by
    shapes alone, never by which domains occupy it (a second geometry
    family is a ``geom.cache.miss`` + ``batched.bucket_cache.hit``, zero
    recompiles). A None entry is the problem's default (the reference
    ellipse); member *i* reproduces
    ``pcg_solve(problem, geometry=g_i, rhs_gate=…)`` bit-for-bit.
    Padding members reuse member 0's canvases with a zero RHS (they
    stop degenerately at iteration 1 as before).

    ``verify_every`` > 0 arms the PER-MEMBER in-loop integrity probe
    (``poisson_tpu.integrity``; ``verify_tol`` defaults dtype-aware):
    a silently corrupted member stops alone with FLAG_INTEGRITY while
    its batchmates solve on untouched — the masking that already
    isolates per-member convergence isolates per-member corruption
    verdicts too. The stride is part of the executable identity, so
    verified buckets form their own bucket-cache key family and
    ``verify_every=0`` keeps the historical executables byte-for-byte.

    ``mode`` selects the batched recurrence (``poisson_tpu.krylov``):
    ``"independent"`` (the default) is the historical vmapped-member
    program — byte-identical executables, golden counts bit-for-bit;
    ``"block"`` carries the (n × B) block iterate with B×B recurrences
    (:mod:`poisson_tpu.krylov.block` — breakdown-free block CG), so
    members share spectral information and total iterations drop on
    clustered RHS batches. Block mode requires ONE shared operator:
    ``geometries`` entries, if given, must all carry the same
    fingerprint (the single shared domain); ``mesh``/MG/``verify_every``
    have no block program yet and are rejected loudly. Block dispatches
    compile at the EXACT batch size (no zero-RHS padding — a zero
    column is pure rank deficiency, wasted width by construction) and
    their bucket-cache keys carry a ``("block",)`` marker so block
    executables never claim reuse of the independent family. Block
    iteration counts are per-member first-δ-crossings of a coupled
    recurrence — NOT comparable to the independent mode's — so block
    mode is gated by the manufactured-solution L2 oracle
    (``geometry.manufactured.manufactured_error(krylov=…)``), not by
    golden-count parity. ``PCGResult.deficient`` reports whether the
    B×B solves truncated a rank-deficient direction (graceful
    degradation — the ``krylov.block.rank_deficient`` counter).

    ``preconditioner="mg"`` runs every member with the geometric
    V-cycle preconditioner (:mod:`poisson_tpu.mg`): the shared member
    body — V-cycle inside ``apply_Dinv`` — is vmapped exactly like the
    Jacobi body and the hierarchy canvases broadcast across the batch
    (one coefficient load for B members). Parity contract: the MG
    *apply* (one V-cycle) is bit-identical under ``vmap`` and member
    *i* reproduces ``pcg_solve(..., preconditioner="mg")``'s iteration
    count and stop flag exactly, with iterates agreeing to a few ULPs —
    XLA's FMA-contraction choices inside the deep fused cycle+body
    program differ between the solo and vmapped layouts, which the
    elementwise Jacobi body never exposed (both pinned by
    tests/test_mg.py). MG buckets are their own executable family (the
    bucket-cache key carries the cycle config); mixed per-member
    ``geometries`` do not co-batch with MG yet — each member would
    need its own level hierarchy — and are rejected loudly (the solve
    service dispatches geometry+MG requests solo).
    """
    from poisson_tpu.krylov import KRYLOV_BLOCK, KRYLOV_MODES

    if mode not in KRYLOV_MODES:
        raise ValueError(
            f"unknown mode {mode!r} — expected one of {KRYLOV_MODES}")
    use_block = mode == KRYLOV_BLOCK
    if use_block:
        # The block recurrence couples members through B×B solves, so
        # it is only defined for ONE shared operator; the orthogonal
        # executable families have no block program yet:
        if mesh is not None:
            raise ValueError(
                "mode='block' has no sharded program yet; drop mesh= "
                "or use mode='independent'")
        if preconditioner not in (None, "jacobi"):
            raise ValueError(
                "mode='block' composes with the jacobi (symmetric-"
                f"scaling) body only; preconditioner={preconditioner!r} "
                "has no block program — use mode='independent'")
        if int(verify_every) > 0:
            raise ValueError(
                "mode='block' does not trace the per-member integrity "
                "probe yet; run verify_every=0 or mode='independent'")
    if mesh is not None:
        # The batch×mesh composition (vmap outside shard_map — members
        # stay whole-grid, the mesh splits the grid) is wired for the
        # plain multi-RHS forms. The orthogonal executable families are
        # rejected loudly until each grows its own sharded program:
        if geometries is not None and any(g is not None
                                          for g in geometries):
            raise ValueError(
                "solve_batched(mesh=) does not carry per-member "
                "geometries yet (stacked canvases need sharded blocks "
                "per member); drop geometries= or dispatch on a single "
                "device")
        if preconditioner not in (None, "jacobi"):
            raise ValueError(
                "solve_batched(mesh=) composes with the Jacobi "
                "(symmetric-scaling) body only; preconditioner="
                f"{preconditioner!r} needs a sharded hierarchy — "
                "dispatch MG batches on a single device")
        if int(verify_every) > 0:
            raise ValueError(
                "solve_batched(mesh=) does not trace the per-member "
                "integrity probe yet; run verify_every=0 on the mesh "
                "or verified buckets on a single device")
    forms = sum(x is not None for x in (rhs_stack, rhs_gates))
    if problems is None:
        raise ValueError("solve_batched needs problems (a Problem or a "
                         "sequence of Problems)")
    if isinstance(problems, Problem):
        problem = problems
        if forms != 1:
            raise ValueError(
                "with a single Problem, pass exactly one of rhs_gates or "
                "rhs_stack (a sequence of Problems is the third form)"
            )
        member_problems = None
    else:
        if forms != 0:
            raise ValueError(
                "rhs_gates/rhs_stack apply to the single-Problem form; a "
                "sequence of Problems already defines every member's RHS"
            )
        member_problems = list(problems)
        problem = _shared_base(member_problems)

    dtype_name = resolve_dtype(dtype)
    use_scaled = resolve_scaled(scaled, dtype_name)

    # f_val never enters the traced program (the RHS arrives as a traced
    # array; the jitted solve reads only delta/cap/norm/h1/h2), so the jit
    # static key — and the bucket-cache key that mirrors it — normalizes
    # it away: batches differing only in RHS magnitude share one compiled
    # executable per bucket.
    jit_problem = problem.with_(f_val=1.0)
    if preconditioner not in (None, "jacobi"):
        from poisson_tpu.mg import resolve_preconditioner

        resolve_preconditioner(preconditioner)   # raises on unknown
        if geometries is not None:
            if any(g is not None for g in geometries):
                raise ValueError(
                    "preconditioner='mg' does not co-batch per-member "
                    "geometries yet (each member would need its own "
                    "level hierarchy); dispatch geometry+MG requests "
                    "solo via pcg_solve(geometry=..., "
                    "preconditioner='mg')"
                )
            geometries = None   # all-None entries: the default domain
        use_mg = True
    else:
        use_mg = False
    geo = setups = None
    if geometries is not None:
        from poisson_tpu.geometry.dsl import parse_geometry

        geo = [None if g is None else parse_geometry(g)
               for g in geometries]
        if use_block:
            from poisson_tpu.geometry.dsl import fingerprint_of

            fps = {fingerprint_of(g) for g in geo}
            if len(fps) != 1:
                raise ValueError(
                    "mode='block' needs ONE shared operator: every "
                    "geometries entry must carry the same fingerprint "
                    f"(got {len(fps)} distinct domains) — mixed-domain "
                    "batches use mode='independent'")

    def _geo_setups(base_problem, n, per_member_problems=None):
        """One (a, b, rhs, aux) per member — fingerprint-cached device
        canvases (``geometry.canvas``); None entries are the problem's
        default ellipse via the exact host_setup arrays."""
        if len(geo) != n:
            raise ValueError(
                f"geometries must have one entry per member: got "
                f"{len(geo)} specs for batch {n}")
        probs = per_member_problems or [base_problem] * n
        return [solve_setup(p, dtype_name, use_scaled, geometry=g)
                for p, g in zip(probs, geo)]

    if member_problems is not None:
        if geo is not None:
            # Per-member setup (each member's canvases AND f_val-scaled
            # RHS come from its own spec/problem — bit-parity with
            # pcg_solve(p_i, geometry=g_i)).
            setups = _geo_setups(problem, len(member_problems),
                                 member_problems)
            rhs_stack = jnp.stack([s[2] for s in setups])
            batch = len(member_problems)
        else:
            from poisson_tpu.solvers.pcg import host_fields64

            # One shared setup (a/b/aux are f_val-independent) plus
            # per-member RHS by exact fp64 scaling of the unit-f_val
            # base — NOT B full host setups (which would also thrash
            # host_setup's small LRU). Bit-exactness vs host_setup(p_i):
            # the indicator is 0/1 and the scaling is a single fp64
            # product either way (f·1[D]·D^{-1/2} associates without
            # extra roundings), then the same cast.
            a, b, _, aux = host_setup(jit_problem, dtype_name, use_scaled)
            base64 = host_fields64(jit_problem, use_scaled)[2]
            dt = jnp.dtype(dtype_name)
            rhs_stack = jnp.stack([jnp.asarray(base64 * p.f_val, dt)
                                   for p in member_problems])
            batch = len(member_problems)
    elif rhs_gates is not None:
        if geo is None:
            a, b, rhs, aux = host_setup(problem, dtype_name, use_scaled)
        gate_dt = jnp.dtype(dtype_name)
        if hasattr(rhs_gates, "ndim"):
            # An existing (B,) array — possibly data-dependent on a prior
            # result (the bench's chaining trick: gates of exactly 1.0
            # computed from the previous solve serialize back-to-back
            # batched solves without changing any bit).
            gates = jnp.asarray(rhs_gates, gate_dt).reshape(-1)
        else:
            gates = jnp.stack([jnp.asarray(g, gate_dt).reshape(())
                               for g in rhs_gates])
        batch = gates.shape[0]
        if batch < 1:
            raise ValueError("rhs_gates must have at least one member")
        if geo is not None:
            # Per-member unit canvases × the member's gate — exactly
            # pcg_solve(problem, geometry=g, rhs_gate=gate)'s multiply.
            setups = _geo_setups(problem, batch)
            rhs_stack = jnp.stack([s[2] for s in setups]
                                  ) * gates[:, None, None]
        else:
            # Per-member rhs * gate — elementwise, exactly pcg_solve's
            # rhs_gate multiply, so gated members stay bit-identical to
            # the sequential gated solve.
            rhs_stack = rhs[None] * gates[:, None, None]
    else:
        a, b, _, aux = host_setup(jit_problem, dtype_name, use_scaled)
        rhs_stack = jnp.asarray(rhs_stack, jnp.dtype(dtype_name))
        if rhs_stack.ndim != 3 or rhs_stack.shape[1:] != problem.grid_shape:
            raise ValueError(
                f"rhs_stack must be (B, {problem.grid_shape[0]}, "
                f"{problem.grid_shape[1]}), got {rhs_stack.shape}"
            )
        batch = rhs_stack.shape[0]
        if geo is not None:
            setups = _geo_setups(jit_problem, batch)
            if use_scaled:
                # Physical B_i → member-scaled b̃_i = D_i^{-1/2}·B_i.
                rhs_stack = rhs_stack * jnp.stack([s[3] for s in setups])
        elif use_scaled:
            # Physical B → scaled b̃ = D^{-1/2}·B; aux IS D^{-1/2} on the
            # full grid (zero ring), so one broadcast multiply.
            rhs_stack = rhs_stack * aux

    if member_ids is not None:
        origin = tuple(member_ids)
        if len(origin) != batch:
            raise ValueError(
                f"member_ids must have one id per member: got "
                f"{len(origin)} ids for batch {batch}"
            )
    else:
        origin = tuple(range(batch))

    if use_block:
        # Block dispatches compile at the EXACT batch size: a zero-RHS
        # padding column is pure rank deficiency — width the coupled
        # recurrence would pay for and truncate every iteration.
        if bucket is not None and int(bucket) != batch:
            raise ValueError(
                f"mode='block' dispatches exact-size blocks; bucket="
                f"{bucket} cannot pad a batch of {batch}")
        size = batch
    else:
        size = (bucket_size(batch, buckets) if bucket is None
                else int(bucket))
    if size < batch:
        raise ValueError(f"bucket {size} smaller than batch {batch}")
    if size > batch:
        pad = jnp.zeros((size - batch,) + tuple(rhs_stack.shape[1:]),
                        rhs_stack.dtype)
        rhs_stack = jnp.concatenate([rhs_stack, pad])

    # Keyed exactly like the jit call below ((static problem, scaled) +
    # the shapes/dtype the stacked operands carry), so the hit/miss
    # counters report real executable reuse, not an approximation of it.
    # The geometry path adds one marker — stacked canvases are a
    # different operand signature, hence a different executable family —
    # but NEVER the fingerprints: every geometry mix of a bucket shares
    # one executable, which is the whole point of co-batching.
    from poisson_tpu.solvers.pcg import resolve_verify_tol

    verify_every = int(verify_every)
    v_tol = (resolve_verify_tol(verify_tol, dtype_name)
             if verify_every > 0 else 0.0)
    # The verify stride is executable identity (a static jit arg), so
    # the bucket-cache key mirrors it — but ONLY when verifying: the
    # flag-off key keeps its historical shape and counter arithmetic.
    verify_key = (("verify", verify_every, v_tol)
                  if verify_every > 0 else None)
    if use_block:
        from poisson_tpu.krylov.block import _solve_block

        if geo is not None:
            # One shared domain (fingerprint-uniform, validated above):
            # the block runs on its canvases, unbatched — the shared
            # operator is the whole point.
            a, b, aux = setups[0][0], setups[0][1], setups[0][3]
        key = (size, jit_problem, dtype_name, use_scaled, ("block",))
        if geo is not None:
            key = key + ("geo",)
        _count_bucket(key, batch, size)
        obs.inc("krylov.block.solves", batch)
        result = _solve_block(jit_problem, use_scaled, a, b, rhs_stack,
                              aux)
        return result._replace(origin=origin)
    if mesh is not None:
        from poisson_tpu.parallel.mesh import X_AXIS, Y_AXIS, block_size
        from poisson_tpu.parallel.pcg_sharded import (
            _host_shard_blocks,
            shard_rhs_stack,
            solve_batched_sharded,
        )

        px_size = mesh.shape[X_AXIS]
        py_size = mesh.shape[Y_AXIS]
        m_blk = block_size(problem.M - 1, px_size)
        n_blk = block_size(problem.N - 1, py_size)
        # The mesh shape is executable identity (the shard program is
        # compiled per topology), so sharded buckets form their own
        # bucket-cache key family — a mesh dispatch never claims to
        # reuse a single-device executable, and vice versa.
        key = (size, jit_problem, dtype_name, use_scaled,
               ("mesh", px_size, py_size))
        _count_bucket(key, batch, size)
        a_blk, b_blk, _, aux_blk = _host_shard_blocks(
            jit_problem, px_size, py_size, m_blk, n_blk, dtype_name,
            use_scaled)
        rhs_blk = shard_rhs_stack(rhs_stack, px_size, py_size, m_blk,
                                  n_blk)
        result = solve_batched_sharded(jit_problem, mesh, dtype_name,
                                       use_scaled, a_blk, b_blk,
                                       rhs_blk, aux_blk)
    elif geo is not None:
        def stack_pad(idx):
            stack = jnp.stack([s[idx] for s in setups])
            if size > batch:
                # Padding members reuse member 0's canvases (any valid
                # operator works: their RHS is zero, they stop at k=1).
                stack = jnp.concatenate(
                    [stack, jnp.broadcast_to(
                        stack[:1], (size - batch,) + stack.shape[1:])])
            return stack

        key = (size, jit_problem, dtype_name, use_scaled, "geo")
        if verify_key:
            key = key + (verify_key,)
        _count_bucket(key, batch, size)
        result = _solve_batched_geo(jit_problem, use_scaled,
                                    verify_every, v_tol,
                                    stack_pad(0), stack_pad(1),
                                    rhs_stack, stack_pad(3))
    elif use_mg:
        from poisson_tpu import obs as _obs
        from poisson_tpu.mg import DEFAULT_MG, validate_mg_problem
        from poisson_tpu.mg.hierarchy import device_hierarchy
        from poisson_tpu.mg.preconditioner import _solve_batched_mg

        cfg = mg_config or DEFAULT_MG
        validate_mg_problem(problem, cfg)
        # MG buckets are their own executable family: the cycle config
        # is operand/static identity exactly like the verify stride.
        key = (size, jit_problem, dtype_name, use_scaled, ("mg", cfg))
        if verify_key:
            key = key + (verify_key,)
        _count_bucket(key, batch, size)
        hier = device_hierarchy(problem, dtype_name, use_scaled,
                                config=cfg)
        _obs.inc("mg.solves", batch)
        result = _solve_batched_mg(jit_problem, use_scaled, cfg,
                                   verify_every, v_tol,
                                   a, b, rhs_stack, aux, hier)
    else:
        key = (size, jit_problem, dtype_name, use_scaled)
        if verify_key:
            key = key + (verify_key,)
        _count_bucket(key, batch, size)
        result = _solve_batched(jit_problem, use_scaled, verify_every,
                                v_tol, a, b, rhs_stack, aux)
    if size == batch:
        return result._replace(origin=origin)
    # Slice padding members off every batched field; max_iterations is
    # recomputed over the real members (padding stops at k=1, so the
    # fused-loop max is unchanged unless every member was padding).
    # ``origin`` was never padded — position i stays member_ids[i].
    return PCGResult(
        w=result.w[:batch],
        iterations=result.iterations[:batch],
        diff=result.diff[:batch],
        residual_dot=result.residual_dot[:batch],
        flag=result.flag[:batch],
        max_iterations=jnp.max(result.iterations[:batch]),
        origin=origin,
    )


# Smoke check: ``python -m poisson_tpu.solvers.batched_selfcheck`` (its own
# module so runpy never re-executes this one, which the package __init__
# already imports).
