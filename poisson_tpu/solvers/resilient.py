"""Self-healing single-device solve: divergence recovery with precision
escalation.

The reference pipeline assumes every iteration succeeds; the in-loop
classification in ``solvers.pcg`` only *detects* that one didn't. This
module closes the loop. The solve runs in chunks (the checkpoint
machinery's chunk driver), and after every chunk the host inspects the
termination verdict:

- **converged** — done; the checkpoint (if any) is cleaned up;
- **non-finite / breakdown / stagnation** — the Krylov history is what
  went bad, so it is discarded and CG is restarted from the last good
  iterate (``solvers.pcg.restart_state``): the accumulated solution ``w``
  is kept, r/z/p/ζ are re-derived from it. CG restarted from a good
  iterate converges from where it left off;
- **repeated failure at the same precision** — the precision itself is
  the likely culprit (the fp32 viability of this problem class is
  conditional on symmetric scaling; bf16 is never more than a gamble), so
  the state is escalated one rung up the bf16 → f32 → f64 ladder and
  restarted there;
- **restart budget exhausted** — :class:`DivergenceError`, carrying full
  diagnostics, rather than an endless restart loop.

Faults are injected between chunks via the same ``on_chunk`` hook the
checkpointed solvers take (``testing.faults``), which is how the recovery
path is exercised on CPU in tier-1.
"""

from __future__ import annotations

import dataclasses
import os
import warnings
from typing import Optional

import jax
import jax.numpy as jnp

from poisson_tpu import obs
from poisson_tpu.config import Problem
from poisson_tpu.solvers.checkpoint import (
    _fingerprint,
    _run_chunk,
    remove_generations,
    save_state,
)
from poisson_tpu.solvers.pcg import (
    FLAG_CONVERGED,
    FLAG_DEADLINE,
    FLAG_NAMES,
    FLAG_NONE,
    FLAG_NONFINITE,
    PCGResult,
    host_setup,
    init_state,
    iterations_scalar,
    restart_state,
    resolve_dtype,
    resolve_scaled,
    scaled_single_device_ops,
    single_device_ops,
)

# Escalation ladder, low to high. A resilient solve enters at its
# requested dtype and only ever moves up.
_LADDER = ("bfloat16", "float32", "float64")


class DivergenceError(RuntimeError):
    """The solve kept failing after every recovery the policy allows.
    ``diagnostics`` records the restart/escalation history for the
    post-mortem."""

    def __init__(self, message: str, diagnostics: Optional[dict] = None):
        super().__init__(message)
        self.diagnostics = diagnostics or {}


@dataclasses.dataclass(frozen=True)
class RecoveryPolicy:
    """What the resilient driver may do about a failing solve.

    max_restarts: total recovery attempts (restarts + escalations) before
        giving up with DivergenceError.
    escalate: allow moving up the precision ladder after a repeated
        failure at the same precision (f64 requires jax_enable_x64; an
        unavailable rung is skipped).
    stagnation_window: in-loop stagnation detection — iterations without
        a new best ‖Δw‖ before the loop stops with FLAG_STAGNATED
        (0 disables; see ``solvers.pcg.make_pcg_body``).
    """

    max_restarts: int = 3
    escalate: bool = True
    stagnation_window: int = 200


def _rungs_above(dtype_name: str) -> list:
    """Ladder rungs strictly above ``dtype_name`` that this runtime can
    actually execute (f64 needs x64)."""
    if dtype_name not in _LADDER:
        return []
    rungs = list(_LADDER[_LADDER.index(dtype_name) + 1:])
    if not jax.config.jax_enable_x64:
        rungs = [r for r in rungs if r != "float64"]
    return rungs


def _build(problem: Problem, dtype_name: str, scaled: bool):
    a, b, rhs, aux = host_setup(problem, dtype_name, scaled)
    ops = (
        scaled_single_device_ops(problem, a, b, aux)
        if scaled
        else single_device_ops(problem, a, b, aux)
    )
    return a, b, rhs, aux, ops


def _load_any_rung(path: str, problem: Problem, dtype_name: str,
                   scaled: bool, keep_last: int):
    """Resume across an earlier run's escalation: accept the NEWEST
    loadable generation whose fingerprint matches the requested precision
    or any higher rung (a previous resilient run may have escalated before
    it was interrupted — its escalated checkpoint outranks the stale
    pre-escalation generation behind it, so generations are walked outermost
    and rungs innermost — exactly ``load_state_any``'s walk order)."""
    from poisson_tpu.solvers.checkpoint import load_state_any

    rungs = [dtype_name] + _rungs_above(dtype_name)
    found = load_state_any(
        path, [_fingerprint(problem, dn, scaled) for dn in rungs],
        keep_last,
    )
    if found is None:
        return None, dtype_name
    state, index = found
    return state, rungs[index]


def pcg_solve_resilient(problem: Problem, dtype=None, scaled=None,
                        chunk: int = 100,
                        policy: Optional[RecoveryPolicy] = None,
                        checkpoint_path: Optional[str] = None,
                        keep_last: int = 2,
                        keep_checkpoint: bool = False,
                        stream_every: int = 0,
                        watchdog=None,
                        on_chunk=None,
                        deadline=None) -> PCGResult:
    """Single-device solve that survives NaN blow-ups, Krylov breakdowns
    and stagnation by restarting from the last good iterate, escalating
    precision when a restart alone does not help.

    Converging solves run the exact same iterations as ``pcg_solve`` —
    recovery only engages on states that could no longer converge. With
    ``checkpoint_path`` the solve additionally persists hardened
    checkpoints every ``chunk`` iterations (and resumes from them, even
    ones written at an escalated precision by an interrupted earlier run).
    ``watchdog``/``on_chunk`` are the chunk-boundary hooks documented on
    ``solvers.checkpoint.run_chunked``. ``deadline`` (duck-typed:
    ``expired() -> bool``) bounds the whole recovery effort: once it
    expires at a chunk boundary, no further chunk or restart is started
    and the partial iterate returns with ``flag == FLAG_DEADLINE`` — a
    deadline never turns into a DivergenceError, and recovery never runs
    on borrowed time.
    """
    if chunk < 1:
        raise ValueError(f"chunk must be >= 1, got {chunk}")
    policy = policy or RecoveryPolicy()
    dtype_name = resolve_dtype(dtype)
    use_scaled = resolve_scaled(scaled, dtype_name)

    if checkpoint_path:
        saved, dtype_name = _load_any_rung(
            checkpoint_path, problem, dtype_name, use_scaled, keep_last
        )
    else:
        saved = None

    a, b, rhs, aux, ops = _build(problem, dtype_name, use_scaled)
    state = saved if saved is not None else init_state(ops, rhs)

    cap = problem.iteration_cap
    restarts = 0
    restarts_at_dtype = 0
    history = []            # (iteration, verdict, action)
    last_good = (state.w, int(state.k))   # device-resident (immutable)
    fp = _fingerprint(problem, dtype_name, use_scaled)
    chunks_done = 0

    def diagnostics(flag: int) -> dict:
        # iterations_scalar / jnp.max: format scalar AND per-member-vector
        # states (a batched result fed back through this driver's reporting
        # must degrade to the honest max, not crash the post-mortem).
        return {
            "problem": f"{problem.M}x{problem.N}",
            "verdict": FLAG_NAMES.get(flag, str(flag)),
            "iteration": iterations_scalar(state.k),
            "dtype": dtype_name,
            "restarts": restarts,
            "history": list(history),
            "diff": float(jnp.max(state.diff)),
            "residual_dot": float(jnp.max(state.zr)),
        }

    deadline_hit = False
    if watchdog is not None:
        watchdog.start()
    try:
        while True:
            if deadline is not None and deadline.expired():
                # Checked before a chunk OR a recovery starts: recovery on
                # borrowed time would just blow the deadline further.
                deadline_hit = True
                obs.inc("resilient.deadline_stops")
                obs.event("resilient.deadline_stop", iteration=int(state.k),
                          restarts=restarts, chunks=chunks_done)
                break
            state = _run_chunk(problem, use_scaled, chunk,
                               policy.stagnation_window, int(stream_every),
                               a, b, aux, state)
            jax.block_until_ready(state)
            chunks_done += 1
            if watchdog is not None:
                watchdog.beat(k=int(state.k), diff=float(state.diff),
                              dtype=dtype_name, restarts=restarts)
            flag = int(state.flag)

            if flag == FLAG_CONVERGED:
                break
            if flag == FLAG_NONE:
                # The in-loop checks watch the reduced scalars (diff, ζ);
                # a NaN confined to the solution grid w never enters a
                # reduction, so validate the would-be snapshot — as a
                # device-side reduction (one scalar crosses to the host,
                # not the grid) — before trusting it as "last good".
                if not bool(jnp.isfinite(state.w).all()):
                    flag = FLAG_NONFINITE
            if flag == FLAG_NONE:
                # Healthy chunk boundary: snapshot, persist, inject.
                # jax arrays are immutable, so holding the reference is a
                # free device-resident snapshot; it only crosses to the
                # host if a restart or checkpoint write needs it.
                last_good = (state.w, int(state.k))
                if checkpoint_path:
                    save_state(checkpoint_path, state, fp,
                               keep_last=keep_last)
                if on_chunk is not None:
                    replacement = on_chunk(state, chunks_done)
                    if replacement is not None:
                        state = replacement
                if int(state.k) >= cap:
                    break  # budget exhausted, unconverged: like pcg_solve
                continue

            # flag is a failure verdict: recover or give up.
            restarts += 1
            restarts_at_dtype += 1
            if restarts > policy.max_restarts:
                diag = diagnostics(flag)
                raise DivergenceError(
                    f"solve failed ({FLAG_NAMES.get(flag, flag)} at "
                    f"iteration {iterations_scalar(state.k)}, "
                    f"dtype {dtype_name}) and "
                    f"the recovery budget ({policy.max_restarts} restarts) "
                    f"is exhausted",
                    diagnostics=diag,
                )
            escalated = False
            if policy.escalate and restarts_at_dtype > 1:
                rungs = _rungs_above(dtype_name)
                if rungs:
                    dtype_name = rungs[0]
                    a, b, rhs, aux, ops = _build(
                        problem, dtype_name, use_scaled
                    )
                    fp = _fingerprint(problem, dtype_name, use_scaled)
                    restarts_at_dtype = 0
                    escalated = True
            action = (f"escalate->{dtype_name}" if escalated
                      else f"restart@{dtype_name}")
            history.append((int(state.k), FLAG_NAMES.get(flag, str(flag)),
                            action))
            obs.inc("resilient.restarts")
            if escalated:
                obs.inc("resilient.escalations")
            obs.event("resilient.restart",
                      iteration=int(state.k),
                      verdict=FLAG_NAMES.get(flag, str(flag)),
                      action=action, restart=restarts,
                      from_iteration=last_good[1])
            warnings.warn(
                f"solve {FLAG_NAMES.get(flag, str(flag))} at iteration "
                f"{iterations_scalar(state.k)}; {action} from last good iterate "
                f"(iteration {last_good[1]})",
                RuntimeWarning, stacklevel=2,
            )
            w_good = jnp.asarray(last_good[0], jnp.dtype(dtype_name))
            state = restart_state(ops, rhs, w_good)._replace(
                k=jnp.asarray(last_good[1], jnp.int32)
            )
    except KeyboardInterrupt:
        if watchdog is not None:
            watchdog.raise_if_fired()   # timeout → typed SolveTimeout
        raise
    finally:
        if watchdog is not None:
            watchdog.stop()

    if (checkpoint_path and int(state.flag) == FLAG_CONVERGED
            and not keep_checkpoint):
        remove_generations(checkpoint_path, keep_last)

    # Recovery provenance rides on the result: a solve that restarted
    # (or escalated) and then converged used to be indistinguishable
    # from a clean one — the history only ever surfaced inside
    # DivergenceError. Counters (resilient.*) record the same facts
    # process-wide for the metrics snapshot.
    w = state.w * aux if use_scaled else state.w
    flag_out = state.flag
    if deadline_hit and int(state.flag) != FLAG_CONVERGED:
        # Host-stamped, result-only: the persisted state keeps its honest
        # in-loop verdict so a resume gets a clean slate.
        flag_out = jnp.asarray(FLAG_DEADLINE, jnp.int32)
    return PCGResult(
        w=w, iterations=state.k, diff=state.diff, residual_dot=state.zr,
        flag=flag_out,
        restarts=restarts, recovery_history=tuple(history),
    )
