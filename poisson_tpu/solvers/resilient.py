"""Self-healing single-device solve: divergence recovery with precision
escalation.

The reference pipeline assumes every iteration succeeds; the in-loop
classification in ``solvers.pcg`` only *detects* that one didn't. This
module closes the loop. The solve runs in chunks (the checkpoint
machinery's chunk driver), and after every chunk the host inspects the
termination verdict:

- **converged** — done; the checkpoint (if any) is cleaned up;
- **non-finite / breakdown / stagnation** — the Krylov history is what
  went bad, so it is discarded and CG is restarted from the last good
  iterate (``solvers.pcg.restart_state``): the accumulated solution ``w``
  is kept, r/z/p/ζ are re-derived from it. CG restarted from a good
  iterate converges from where it left off;
- **repeated failure at the same precision** — the precision itself is
  the likely culprit (the fp32 viability of this problem class is
  conditional on symmetric scaling; bf16 is never more than a gamble), so
  the state is escalated one rung up the bf16 → f32 → f64 ladder and
  restarted there;
- **restart budget exhausted** — :class:`DivergenceError`, carrying full
  diagnostics, rather than an endless restart loop.

Faults are injected between chunks via the same ``on_chunk`` hook the
checkpointed solvers take (``testing.faults``), which is how the recovery
path is exercised on CPU in tier-1.
"""

from __future__ import annotations

import dataclasses
import os
import warnings
from typing import Optional

import jax
import jax.numpy as jnp

from poisson_tpu import obs
from poisson_tpu.config import Problem
from poisson_tpu.solvers.checkpoint import (
    _fingerprint,
    remove_generations,
    save_state,
)
from poisson_tpu.solvers.pcg import (
    FLAG_CONVERGED,
    FLAG_DEADLINE,
    FLAG_INTEGRITY,
    FLAG_NAMES,
    FLAG_NONE,
    FLAG_NONFINITE,
    PCGResult,
    host_setup,
    iterations_scalar,
    restart_state,
    resolve_dtype,
    resolve_scaled,
    resolve_verify_tol,
)

# Escalation ladder, low to high. A resilient solve enters at its
# requested dtype and only ever moves up.
_LADDER = ("bfloat16", "float32", "float64")


class DivergenceError(RuntimeError):
    """The solve kept failing after every recovery the policy allows.
    ``diagnostics`` records the restart/escalation history for the
    post-mortem."""

    def __init__(self, message: str, diagnostics: Optional[dict] = None):
        super().__init__(message)
        self.diagnostics = diagnostics or {}


@dataclasses.dataclass(frozen=True)
class RecoveryPolicy:
    """What the resilient driver may do about a failing solve.

    max_restarts: total recovery attempts (restarts + escalations) before
        giving up with DivergenceError.
    escalate: allow moving up the precision ladder after a repeated
        failure at the same precision (f64 requires jax_enable_x64; an
        unavailable rung is skipped).
    stagnation_window: in-loop stagnation detection — iterations without
        a new best ‖Δw‖ before the loop stops with FLAG_STAGNATED
        (0 disables; see ``solvers.pcg.make_pcg_body``).
    """

    max_restarts: int = 3
    escalate: bool = True
    stagnation_window: int = 200


def _rungs_above(dtype_name: str) -> list:
    """Ladder rungs strictly above ``dtype_name`` that this runtime can
    actually execute (f64 needs x64)."""
    if dtype_name not in _LADDER:
        return []
    rungs = list(_LADDER[_LADDER.index(dtype_name) + 1:])
    if not jax.config.jax_enable_x64:
        rungs = [r for r in rungs if r != "float64"]
    return rungs


def _build(problem: Problem, dtype_name: str, scaled: bool,
           chunk: int, stagnation_window: int, stream_every: int,
           verify_every: int, verify_tol: float,
           preconditioner: str = "jacobi", mg_config=None):
    """Fields + ops + chunk advance for one precision rung, routed
    through the shared preconditioner seam
    (``checkpoint._chunk_ops_advance``) so MG recovery/escalation
    rebuilds the hierarchy at the new dtype like every other operand."""
    from poisson_tpu.solvers.checkpoint import _chunk_ops_advance

    a, b, rhs, aux = host_setup(problem, dtype_name, scaled)
    ops, advance, init = _chunk_ops_advance(
        problem, dtype_name, scaled, a, b, aux, rhs, chunk,
        stagnation_window, stream_every, verify_every, verify_tol,
        preconditioner=preconditioner, mg_config=mg_config)
    return a, b, rhs, aux, ops, advance, init


def _load_any_rung(path: str, problem: Problem, dtype_name: str,
                   scaled: bool, keep_last: int,
                   preconditioner: str = "jacobi", mg_config=None):
    """Resume across an earlier run's escalation: accept the NEWEST
    loadable generation whose fingerprint matches the requested precision
    or any higher rung (a previous resilient run may have escalated before
    it was interrupted — its escalated checkpoint outranks the stale
    pre-escalation generation behind it, so generations are walked outermost
    and rungs innermost — exactly ``load_state_any``'s walk order)."""
    from poisson_tpu.solvers.checkpoint import load_state_any

    rungs = [dtype_name] + _rungs_above(dtype_name)
    found = load_state_any(
        path,
        [_fingerprint(problem, dn, scaled, preconditioner, mg_config)
         for dn in rungs],
        keep_last,
    )
    if found is None:
        return None, dtype_name
    state, index = found
    return state, rungs[index]


def pcg_solve_resilient(problem: Problem, dtype=None, scaled=None,
                        chunk: int = 100,
                        policy: Optional[RecoveryPolicy] = None,
                        checkpoint_path: Optional[str] = None,
                        keep_last: int = 2,
                        keep_checkpoint: bool = False,
                        stream_every: int = 0,
                        watchdog=None,
                        on_chunk=None,
                        deadline=None,
                        verify_every: int = 0,
                        verify_tol=None,
                        preconditioner: str = "jacobi",
                        mg_config=None) -> PCGResult:
    """Single-device solve that survives NaN blow-ups, Krylov breakdowns
    and stagnation by restarting from the last good iterate, escalating
    precision when a restart alone does not help.

    ``verify_every`` > 0 additionally arms the silent-data-corruption
    defense (``poisson_tpu.integrity``): the in-loop drift probe runs
    inside every chunk, the driver re-verifies each chunk-boundary
    state (``integrity.checks``) and carries the newest *verified-good*
    iterate as a device-resident snapshot — distinct from checkpoint
    files, which a corrupt state is never written to. A FLAG_INTEGRITY
    stop (``integrity.detections``) restarts from that verified
    snapshot (``integrity.verified_restarts``) WITHOUT burning a
    precision escalation: a flipped bit is a hardware event, not a
    precision problem, and escalating would treble the cost of every
    later iteration for nothing. A detection the driver's recheck
    cannot reproduce is a counted ``integrity.false_alarms`` and the
    solve resumes from the very state that fired it — a misfiring
    detector costs one recheck, never a restart.

    Converging solves run the exact same iterations as ``pcg_solve`` —
    recovery only engages on states that could no longer converge. With
    ``checkpoint_path`` the solve additionally persists hardened
    checkpoints every ``chunk`` iterations (and resumes from them, even
    ones written at an escalated precision by an interrupted earlier run).
    ``watchdog``/``on_chunk`` are the chunk-boundary hooks documented on
    ``solvers.checkpoint.run_chunked``. ``deadline`` (duck-typed:
    ``expired() -> bool``) bounds the whole recovery effort: once it
    expires at a chunk boundary, no further chunk or restart is started
    and the partial iterate returns with ``flag == FLAG_DEADLINE`` — a
    deadline never turns into a DivergenceError, and recovery never runs
    on borrowed time.
    """
    if chunk < 1:
        raise ValueError(f"chunk must be >= 1, got {chunk}")
    policy = policy or RecoveryPolicy()
    dtype_name = resolve_dtype(dtype)
    use_scaled = resolve_scaled(scaled, dtype_name)

    if checkpoint_path:
        saved, dtype_name = _load_any_rung(
            checkpoint_path, problem, dtype_name, use_scaled, keep_last,
            preconditioner, mg_config,
        )
    else:
        saved = None

    verify_every = int(verify_every)
    v_tol = (resolve_verify_tol(verify_tol, dtype_name)
             if verify_every > 0 else 0.0)
    if preconditioner not in (None, "jacobi"):
        obs.inc("mg.solves")   # entry only — escalation rebuilds are
        #                        the SAME solve, not a new dispatch
    a, b, rhs, aux, ops, advance, init = _build(
        problem, dtype_name, use_scaled, chunk,
        policy.stagnation_window, stream_every, verify_every, v_tol,
        preconditioner=preconditioner, mg_config=mg_config)
    state = saved if saved is not None else init()

    cap = problem.iteration_cap
    restarts = 0
    restarts_at_dtype = 0
    history = []            # (iteration, verdict, action)
    last_good = (state.w, int(state.k))   # device-resident (immutable)
    # The verified-good snapshot (poisson_tpu.integrity): the newest
    # chunk-boundary iterate whose residual drift passed the recheck.
    # Distinct from last_good (a finite state may already be silently
    # corrupt) and from checkpoint files (never written corrupt, but
    # disk-shaped); the integrity recovery path restarts from HERE.
    # The entry state is trivially verified: r = b − Aw by
    # construction at init, CRC-sealed on a resume.
    last_verified = (state.w, int(state.k))
    fp = _fingerprint(problem, dtype_name, use_scaled, preconditioner,
                      mg_config)
    chunks_done = 0

    def diagnostics(flag: int) -> dict:
        # iterations_scalar / jnp.max: format scalar AND per-member-vector
        # states (a batched result fed back through this driver's reporting
        # must degrade to the honest max, not crash the post-mortem).
        return {
            "problem": f"{problem.M}x{problem.N}",
            "verdict": FLAG_NAMES.get(flag, str(flag)),
            "iteration": iterations_scalar(state.k),
            "dtype": dtype_name,
            "restarts": restarts,
            "history": list(history),
            "diff": float(jnp.max(state.diff)),
            "residual_dot": float(jnp.max(state.zr)),
        }

    deadline_hit = False
    if watchdog is not None:
        watchdog.start()
    try:
        while True:
            if deadline is not None and deadline.expired():
                # Checked before a chunk OR a recovery starts: recovery on
                # borrowed time would just blow the deadline further.
                deadline_hit = True
                obs.inc("resilient.deadline_stops")
                obs.event("resilient.deadline_stop", iteration=int(state.k),
                          restarts=restarts, chunks=chunks_done)
                break
            state = advance(state)
            jax.block_until_ready(state)
            chunks_done += 1
            if watchdog is not None:
                watchdog.beat(k=int(state.k), diff=float(state.diff),
                              dtype=dtype_name, restarts=restarts)
            flag = int(state.flag)

            if flag == FLAG_CONVERGED:
                break
            if flag == FLAG_NONE:
                # The in-loop checks watch the reduced scalars (diff, ζ);
                # a NaN confined to the solution grid w never enters a
                # reduction, so validate the would-be snapshot — as a
                # device-side reduction (one scalar crosses to the host,
                # not the grid) — before trusting it as "last good".
                if not bool(jnp.isfinite(state.w).all()):
                    flag = FLAG_NONFINITE
            if flag == FLAG_NONE and verify_every > 0:
                # Boundary verification: the in-loop probe only fires on
                # its stride, so a flip in the chunk's tail could slip
                # into the snapshot unverified. One drift recheck per
                # boundary (one stencil application) promotes the state
                # to verified-good — or catches what the stride missed.
                from poisson_tpu.integrity.probe import recheck_state

                obs.inc("integrity.checks")
                drifted, _ = recheck_state(ops, state.w, state.r, rhs,
                                           v_tol)
                if drifted:
                    flag = FLAG_INTEGRITY
                else:
                    last_verified = (state.w, int(state.k))
            if flag == FLAG_NONE:
                # Healthy chunk boundary: snapshot, persist, inject.
                # jax arrays are immutable, so holding the reference is a
                # free device-resident snapshot; it only crosses to the
                # host if a restart or checkpoint write needs it.
                last_good = (state.w, int(state.k))
                if checkpoint_path:
                    save_state(checkpoint_path, state, fp,
                               keep_last=keep_last)
                if on_chunk is not None:
                    replacement = on_chunk(state, chunks_done)
                    if replacement is not None:
                        state = replacement
                if int(state.k) >= cap:
                    break  # budget exhausted, unconverged: like pcg_solve
                continue

            if flag == FLAG_INTEGRITY:
                # Silent-data-corruption verdict: recover from the last
                # VERIFIED iterate, never escalate precision (the bit
                # flip was hardware, not arithmetic), and classify
                # detector misfires honestly before burning a restart.
                from poisson_tpu.integrity import probe as _iprobe

                obs.inc("integrity.detections")
                drifted, drift_rel = _iprobe.recheck_state(
                    ops, state.w, state.r, rhs, v_tol)
                # Update-norm verdicts (convergence jump, mid-solve
                # collapse) stop with a CONSISTENT recurrence (a
                # corrupted search direction updates w and r in step),
                # so the drift recheck saying "clean" does not clear
                # them — reproduce the anomaly from the stop state
                # instead: the body froze the PRE-flip best, so a
                # genuine verdict carries best well above the collapsed
                # ‖Δw‖, while ANY clean state has best ≤ diff (best is
                # the running minimum). Half the collapse ratio keeps
                # the weakest genuine collapse (best may sit under the
                # pre-flip diff by CG's own ≤2× oscillation) confirmed.
                # isfinite guards the first probed iteration after an
                # init/restart, where best is still ∞ (the corrupt
                # verdict freezes the PRE-step best): a drift misfire
                # there must still classify as a false alarm, not read
                # ∞ > anything as confirmation.
                import math as _math

                jump_stop = (_math.isfinite(float(state.best))
                             and float(state.best)
                             > _iprobe.default_verify_collapse(
                                 preconditioner or "jacobi") / 2
                             * float(state.diff))
                if not drifted and not jump_stop:
                    obs.inc("integrity.false_alarms")
                    obs.event("integrity.false_alarm",
                              iteration=int(state.k), drift=drift_rel)
                    warnings.warn(
                        f"integrity probe fired at iteration "
                        f"{int(state.k)} but the recheck measures drift "
                        f"{drift_rel:.2e} under tolerance {v_tol:.2e}; "
                        f"resuming without a restart",
                        RuntimeWarning, stacklevel=2,
                    )
                    state = state._replace(
                        done=jnp.asarray(False),
                        flag=jnp.asarray(FLAG_NONE, jnp.int32),
                    )
                    continue
                restarts += 1
                if restarts > policy.max_restarts:
                    raise DivergenceError(
                        f"solve kept failing integrity verification "
                        f"(detection at iteration "
                        f"{iterations_scalar(state.k)}, dtype "
                        f"{dtype_name}) and the recovery budget "
                        f"({policy.max_restarts} restarts) is exhausted "
                        f"— the device is likely producing silent data "
                        f"corruption",
                        diagnostics=diagnostics(flag),
                    )
                w_src, k_src = last_verified
                history.append((int(state.k), "integrity",
                                f"verified-restart@{k_src}"))
                obs.inc("resilient.restarts")
                obs.inc("integrity.verified_restarts")
                obs.event("integrity.verified_restart",
                          iteration=int(state.k), from_iteration=k_src,
                          drift=drift_rel, restart=restarts)
                warnings.warn(
                    f"integrity check failed at iteration "
                    f"{int(state.k)} (relative drift {drift_rel:.2e}); "
                    f"restarting from the last verified iterate "
                    f"(iteration {k_src})",
                    RuntimeWarning, stacklevel=2,
                )
                w_good = jnp.asarray(w_src, jnp.dtype(dtype_name))
                state = restart_state(ops, rhs, w_good)._replace(
                    k=jnp.asarray(k_src, jnp.int32)
                )
                continue

            # flag is a failure verdict: recover or give up.
            restarts += 1
            restarts_at_dtype += 1
            if restarts > policy.max_restarts:
                diag = diagnostics(flag)
                raise DivergenceError(
                    f"solve failed ({FLAG_NAMES.get(flag, flag)} at "
                    f"iteration {iterations_scalar(state.k)}, "
                    f"dtype {dtype_name}) and "
                    f"the recovery budget ({policy.max_restarts} restarts) "
                    f"is exhausted",
                    diagnostics=diag,
                )
            escalated = False
            if policy.escalate and restarts_at_dtype > 1:
                rungs = _rungs_above(dtype_name)
                if rungs:
                    dtype_name = rungs[0]
                    if verify_every > 0:
                        # The drift floor moved with the precision.
                        v_tol = resolve_verify_tol(verify_tol, dtype_name)
                    a, b, rhs, aux, ops, advance, init = _build(
                        problem, dtype_name, use_scaled, chunk,
                        policy.stagnation_window, stream_every,
                        verify_every, v_tol,
                        preconditioner=preconditioner,
                        mg_config=mg_config,
                    )
                    fp = _fingerprint(problem, dtype_name, use_scaled,
                                      preconditioner, mg_config)
                    restarts_at_dtype = 0
                    escalated = True
            action = (f"escalate->{dtype_name}" if escalated
                      else f"restart@{dtype_name}")
            history.append((int(state.k), FLAG_NAMES.get(flag, str(flag)),
                            action))
            obs.inc("resilient.restarts")
            if escalated:
                obs.inc("resilient.escalations")
            obs.event("resilient.restart",
                      iteration=int(state.k),
                      verdict=FLAG_NAMES.get(flag, str(flag)),
                      action=action, restart=restarts,
                      from_iteration=last_good[1])
            warnings.warn(
                f"solve {FLAG_NAMES.get(flag, str(flag))} at iteration "
                f"{iterations_scalar(state.k)}; {action} from last good iterate "
                f"(iteration {last_good[1]})",
                RuntimeWarning, stacklevel=2,
            )
            w_good = jnp.asarray(last_good[0], jnp.dtype(dtype_name))
            state = restart_state(ops, rhs, w_good)._replace(
                k=jnp.asarray(last_good[1], jnp.int32)
            )
    except KeyboardInterrupt:
        if watchdog is not None:
            watchdog.raise_if_fired()   # timeout → typed SolveTimeout
        raise
    finally:
        if watchdog is not None:
            watchdog.stop()

    if (checkpoint_path and int(state.flag) == FLAG_CONVERGED
            and not keep_checkpoint):
        remove_generations(checkpoint_path, keep_last)

    # Recovery provenance rides on the result: a solve that restarted
    # (or escalated) and then converged used to be indistinguishable
    # from a clean one — the history only ever surfaced inside
    # DivergenceError. Counters (resilient.*) record the same facts
    # process-wide for the metrics snapshot.
    w = state.w * aux if use_scaled else state.w
    flag_out = state.flag
    if deadline_hit and int(state.flag) != FLAG_CONVERGED:
        # Host-stamped, result-only: the persisted state keeps its honest
        # in-loop verdict so a resume gets a clean slate.
        flag_out = jnp.asarray(FLAG_DEADLINE, jnp.int32)
    return PCGResult(
        w=w, iterations=state.k, diff=state.diff, residual_dot=state.zr,
        flag=flag_out,
        restarts=restarts, recovery_history=tuple(history),
    )
