"""Fixed-budget diagnostic solve: per-iteration convergence history.

The reference's final report plots the L2-error-vs-iteration curve as its
accuracy control (``итоговый отчёт/Этап_4_1213.pdf`` p.1; no code survives —
SURVEY §4.2). This module recreates that capability as a ``lax.scan`` over
the shared PCG body (``solvers.pcg.make_pcg_body``): a fixed iteration
budget, recording ‖Δw‖, ζ = (z, r), and optionally the L2(D) error against
the analytic solution at every iteration — all device-resident, one fused
program, no per-iteration host traffic.

Once the δ-criterion (or a degenerate direction) fires, the state freezes:
trailing scan steps are identity, so the recorded curve is flat after
convergence and ``iterations`` matches :func:`solvers.pcg.pcg_solve`.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from poisson_tpu.config import Problem
from poisson_tpu.models.fictitious_domain import analytic_solution, is_in_domain
from poisson_tpu.solvers.pcg import (
    _select,
    host_setup,
    init_state,
    make_pcg_body,
    resolve_dtype,
    resolve_scaled,
    scaled_single_device_ops,
    single_device_ops,
)


class HistoryResult(NamedTuple):
    w: jnp.ndarray            # final solution, full grid, unscaled
    iterations: jnp.ndarray   # iterations until convergence (or budget)
    diffs: jnp.ndarray        # ‖w(k+1)−w(k)‖ per iteration, shape (budget,)
    residual_dots: jnp.ndarray  # ζ per iteration
    l2_errors: Optional[jnp.ndarray]  # L2(D) error per iteration (or None)


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3))
def _history(problem: Problem, budget: int, scaled: bool, record_error: bool,
             a, b, rhs, aux):
    ops = (
        scaled_single_device_ops(problem, a, b, aux)
        if scaled
        else single_device_ops(problem, a, b, aux)
    )
    body = make_pcg_body(
        ops, delta=problem.delta, weighted_norm=problem.weighted_norm,
        h1=problem.h1, h2=problem.h2,
    )

    if record_error:
        dtype = rhs.dtype
        u = analytic_solution(problem, dtype=dtype)
        i = jnp.arange(problem.M + 1)
        j = jnp.arange(problem.N + 1)
        x = (problem.x_min + i.astype(dtype) * problem.h1)[:, None]
        y = (problem.y_min + j.astype(dtype) * problem.h2)[None, :]
        mask = is_in_domain(x, y)

        def l2_err(w):
            err2 = jnp.where(mask, (w - u) ** 2, 0.0)
            return jnp.sqrt(jnp.sum(err2) * (problem.h1 * problem.h2))

    def step(s, _):
        s = _select(s.done, s, body(s))
        w = s.w * aux if scaled else s.w
        err = l2_err(w) if record_error else jnp.zeros((), rhs.dtype)
        return s, (s.diff, s.zr, err)

    s0 = init_state(ops, rhs)
    final, (diffs, zrs, errs) = lax.scan(step, s0, None, length=budget)
    w = final.w * aux if scaled else final.w
    return w, final.k, diffs, zrs, errs


def pcg_solve_history(problem: Problem, budget: int, dtype=None, scaled=None,
                      record_error: bool = True) -> HistoryResult:
    """Run exactly ``budget`` scan steps (iteration stops early only
    logically — converged state freezes) and return per-iteration curves."""
    dtype_name = resolve_dtype(dtype)
    use_scaled = resolve_scaled(scaled, dtype_name)
    a, b, rhs, aux = host_setup(problem, dtype_name, use_scaled)
    w, k, diffs, zrs, errs = _history(
        problem, budget, use_scaled, record_error, a, b, rhs, aux
    )
    return HistoryResult(
        w=w, iterations=k, diffs=diffs, residual_dots=zrs,
        l2_errors=errs if record_error else None,
    )
