"""Session-step solvers: warm-started and implicit-Euler-shifted solves.

A *session* (``poisson_tpu.serve.session``) is an ordered stream of
dependent solves against slowly-varying canvases — heat-equation
implicit-Euler time stepping, or a shape-design gradient loop. The
stream's whole performance case is that consecutive operators are
*nearby*: the previous step's iterate is an excellent initial guess, so
each step restarts CG from it (:func:`solvers.pcg.restart_state` — the
same primitive the recovery driver uses) instead of from zero.

Correctness discipline, in order of precedence:

- **The cold path is the historical program.** A step without a usable
  warm iterate (first step, validity-gate fallback, crash recovery)
  delegates to the literal :func:`solvers.pcg.pcg_solve` → ``_solve``
  executable — byte-identical HLO, pinned by the contracts ledger
  (``session.step_cold_f64`` asserts fingerprint equality with
  ``solve.jacobi_f64``).
- **Warm starts are gated, and fall back audibly.** A warm iterate is
  only trusted when (a) the geometry drift between the iterate's
  operator and this step's operator is bounded (:func:`warm_drift` —
  fingerprint equality, or per-parameter drift within
  ``drift_bound`` for closed-form ellipses) and (b) one eager stencil
  application confirms the warm residual is finite and within
  ``residual_factor`` of the RHS scale. A rejected warm start counts
  ``session.warm.fallbacks`` (+ a ``session.warm.fallback`` event with
  the reason) and runs cold — converging fast against the *wrong*
  operator is the failure mode this gate exists to prevent.
- **Warm iterates never cross a crash.** The serve layer journals which
  step a warm start came from, but never the iterate itself: recovery
  re-enqueues mid-step work cold (unreplayed device state is not
  evidence — the PR 14 deflation-cache precedent).

The implicit-Euler heat step solves ``(A + m·I) u⁺ = B + m·uⁿ`` on the
interior, ``m = 1/Δt`` (Glowinski/Pan/Périaux's moving-domain setting,
PAPERS.md). The mass shift CANNOT ride the coefficient canvases — a/b
are *edge* blend coefficients and ``apply_A`` has no zeroth-order term
— so the shifted step gets its own jitted programs
(:func:`_solve_shifted`): matvec ``A·w + m·w`` on the interior, Jacobi
diagonal ``D + m``, and for the scaled system a recomputed
``(D + m)^{-1/2}`` symmetrizer. Both shifted programs are ledgered
(``session.heat_cold_f64`` / ``session.heat_warm_f64``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from poisson_tpu import obs
from poisson_tpu.config import Problem
from poisson_tpu.geometry.dsl import Ellipse, fingerprint_of
from poisson_tpu.models.fictitious_domain import build_fields
from poisson_tpu.ops.stencil import apply_A, diag_D, interior, pad_interior
from poisson_tpu.solvers.pcg import (
    PCGResult,
    init_state,
    make_pcg_body,
    pcg_solve,
    resolve_dtype,
    resolve_scaled,
    restart_state,
    scaled_single_device_ops,
    single_device_ops,
    solve_setup,
)

# Warm-validity defaults (overridable per call / via SessionPolicy):
# geometry parameter drift beyond this bound means the warm iterate
# solved a meaningfully different operator — restarting from it could
# converge to δ against a stale A before the true residual recovers.
DEFAULT_DRIFT_BOUND = 0.05
# One eager stencil application sanity-checks the warm guess: its true
# residual must be finite and within this factor of the RHS scale
# (catches NaN-poisoned iterates and grid/problem mismatches the drift
# bound cannot see).
DEFAULT_RESIDUAL_FACTOR = 100.0


# -- warm-start validity -------------------------------------------------

def warm_drift(prev_spec, spec):
    """Geometry drift between the operator a warm iterate solved and the
    operator this step will solve. Returns a non-negative float, or
    ``None`` when the pair is incomparable (different families, sampled
    specs) — incomparable means *invalid*, never "assume close".

    Fingerprint equality (including the None/None reference-ellipse
    pair) is drift 0.0; closed-form ellipse pairs compare per-parameter
    (max over |Δcx|, |Δcy|, |Δrx|, |Δry|) — exactly the parameters the
    session's design loop / moving-domain schedule varies.
    """
    if fingerprint_of(prev_spec) == fingerprint_of(spec):
        return 0.0
    if isinstance(prev_spec, Ellipse) and isinstance(spec, Ellipse):
        return max(
            abs(float(spec.cx) - float(prev_spec.cx)),
            abs(float(spec.cy) - float(prev_spec.cy)),
            abs(float(spec.rx) - float(prev_spec.rx)),
            abs(float(spec.ry) - float(prev_spec.ry)),
        )
    return None


def warm_validity(prev_spec, spec,
                  drift_bound: float = DEFAULT_DRIFT_BOUND):
    """(valid, reason) for the geometry half of the warm gate. Reasons:
    ``""`` (valid), ``"family"`` (incomparable specs), ``"drift"``
    (parameter drift beyond the bound)."""
    d = warm_drift(prev_spec, spec)
    if d is None:
        return False, "family"
    if d > float(drift_bound):
        return False, "drift"
    return True, ""


@functools.partial(jax.jit, static_argnums=(0, 1))
def _residual_norms(problem: Problem, scaled: bool, a, b, rhs, aux,
                    w0, m):
    """Fused ‖B − (A + m·I)w₀‖ / ‖B‖ for the warm gate — one jitted
    program instead of ~20 eager dispatches (the gate runs on EVERY
    warm-offered step, so its overhead prices the whole session).
    ``m`` is a traced scalar: the Poisson gate passes 0.0 and shares
    the compiled program with the heat gate."""
    Aw = apply_A(w0, a, b, problem.h1, problem.h2)
    Aw = Aw + m * pad_interior(interior(w0))
    r0 = rhs - (Aw * aux if scaled else Aw)
    return jnp.sqrt(jnp.sum(r0 * r0)), jnp.sqrt(jnp.sum(rhs * rhs))


def _residual_ok(problem: Problem, a, b, rhs, aux, scaled: bool, w0,
                 mass_shift: float,
                 factor: float = DEFAULT_RESIDUAL_FACTOR) -> bool:
    """Residual sanity on a warm initial guess: one stencil
    application (jitted — :func:`_residual_norms`). ``rhs`` is the
    system RHS in the system the solve runs (scaled b̃ when
    ``scaled``); ``w0`` is always a w-space grid. The w-space residual
    maps into the scaled system by one multiply with ``aux``
    (r̃ = sc·(B − A·w)), so both systems share the check."""
    r0, bnorm = _residual_norms(problem, bool(scaled), a, b, rhs, aux,
                                jnp.asarray(w0, rhs.dtype),
                                jnp.asarray(float(mass_shift), rhs.dtype))
    r0n = float(r0)
    bn = float(bnorm)
    return bool(np.isfinite(r0n) and r0n <= float(factor) * max(bn, 1e-300))


# -- jitted session programs --------------------------------------------

@functools.partial(jax.jit, static_argnums=(0, 1))
def _solve_warm(problem: Problem, scaled: bool, a, b, rhs, aux,
                w0) -> PCGResult:
    """Warm-started single-device solve: the historical flags-off PCG
    iteration body, initialized by :func:`restart_state` from the
    w-space iterate ``w0`` instead of zero. Same operands contract as
    ``_solve`` plus the guess; ledgered as ``session.warm_f64``."""
    ops = (scaled_single_device_ops(problem, a, b, aux) if scaled
           else single_device_ops(problem, a, b, aux))
    if scaled:
        # The scaled system iterates y = D^{1/2}w; aux is D^{-1/2} with a
        # zero ring, so the ring maps to 0 rather than dividing by it.
        y0 = jnp.where(aux > 0, w0 / jnp.where(aux > 0, aux, 1.0), 0.0)
    else:
        y0 = w0
    body = make_pcg_body(ops, delta=problem.delta,
                         weighted_norm=problem.weighted_norm,
                         h1=problem.h1, h2=problem.h2)

    def cond(s):
        return (~s.done) & (s.k < problem.iteration_cap)

    s = lax.while_loop(cond, body, restart_state(ops, rhs, y0))
    w = s.w * aux if scaled else s.w
    return PCGResult(w=w, iterations=s.k, diff=s.diff,
                     residual_dot=s.zr, flag=s.flag)


def _shifted_ops(problem: Problem, a, b, aux, m, scaled: bool):
    """PCGOps for the implicit-Euler operator ``A + m·I`` (interior).

    The mass shift cannot live in the a/b canvases (edge coefficients —
    ``apply_A`` has no zeroth-order term), so the matvec adds
    ``m·w`` on the interior explicitly. ``aux`` must already embed the
    SHIFTED diagonal: ``D + m`` (unscaled) or ``(D + m)^{-1/2}``
    (scaled) — :func:`shifted_setup` builds exactly that."""
    h1, h2 = problem.h1, problem.h2
    if not scaled:
        base = single_device_ops(problem, a, b, aux)
        return base._replace(
            apply_A=lambda p: (apply_A(p, a, b, h1, h2)
                               + m * pad_interior(interior(p))))
    base = scaled_single_device_ops(problem, a, b, aux)

    def apply_shifted(p):
        w = p * aux
        return (apply_A(w, a, b, h1, h2)
                + m * pad_interior(interior(w))) * aux

    return base._replace(apply_A=apply_shifted)


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def _solve_shifted(problem: Problem, scaled: bool, warm: bool,
                   a, b, rhs0, aux, m, u_prev, w0) -> PCGResult:
    """One implicit-Euler step ``(A + m·I) u⁺ = B + m·uⁿ``, jitted.

    ``rhs0`` is the UNSCALED forcing canvas B (the per-step transient
    term and, in the scaled system, the symmetrization are composed
    in-graph so one program serves every step of the session);
    ``u_prev`` is uⁿ (w-space); ``warm``, a trace-time constant, selects
    restart-from-``w0`` vs the historical zero init. Ledgered as
    ``session.heat_{cold,warm}_f64``."""
    rhs = rhs0 + m * pad_interior(interior(u_prev))
    if scaled:
        rhs = rhs * aux
    ops = _shifted_ops(problem, a, b, aux, m, scaled)
    body = make_pcg_body(ops, delta=problem.delta,
                         weighted_norm=problem.weighted_norm,
                         h1=problem.h1, h2=problem.h2)

    def cond(s):
        return (~s.done) & (s.k < problem.iteration_cap)

    if warm:
        y0 = (jnp.where(aux > 0, w0 / jnp.where(aux > 0, aux, 1.0), 0.0)
              if scaled else w0)
        init = restart_state(ops, rhs, y0)
    else:
        init = init_state(ops, rhs)
    s = lax.while_loop(cond, body, init)
    w = s.w * aux if scaled else s.w
    return PCGResult(w=w, iterations=s.k, diff=s.diff,
                     residual_dot=s.zr, flag=s.flag)


# -- shifted-operator setup cache ---------------------------------------

# Keyed like geometry_setup plus the mass shift: a session's heat steps
# share one setup (and one compiled program) for the whole stream.
_SHIFT_CACHE: dict = {}
_SHIFT_CACHE_CAP = 32


def reset_session_cache() -> None:
    """Drop the shifted-setup cache (tests / chaos registry resets)."""
    _SHIFT_CACHE.clear()


def shifted_setup(problem: Problem, geometry, dtype_name: str,
                  scaled: bool, mass_shift: float):
    """Device-resident (a, b, rhs0, aux) for the shifted operator
    ``A + m·I``: the session analog of ``solvers.pcg.host_setup``.

    Unlike ``host_setup``/``geometry_setup``, ``rhs0`` here is the
    UNSCALED forcing canvas B — the transient term ``m·uⁿ`` changes
    every step, so the scaled system's b̃ is composed inside
    :func:`_solve_shifted` rather than baked into the cache. ``aux``
    embeds the SHIFTED diagonal (``D + m`` unscaled,
    ``(D + m)^{-1/2}`` scaled), derived on the host in fp64 like every
    setup in this repo. Counts ``session.setup.hits``/``misses``."""
    m = float(mass_shift)
    key = (problem, fingerprint_of(geometry), dtype_name, bool(scaled), m)
    hit = _SHIFT_CACHE.get(key)
    if hit is not None:
        obs.inc("session.setup.hits")
        return hit
    obs.inc("session.setup.misses")
    if geometry is None:
        a64, b64, rhs64 = build_fields(problem, dtype=np.float64, xp=np)
    else:
        from poisson_tpu.geometry.canvas import build_geometry_fields

        a64, b64, rhs64 = build_geometry_fields(problem, geometry)
    dm = diag_D(a64, b64, problem.h1, problem.h2) + m
    aux64 = np.pad(1.0 / np.sqrt(dm), 1) if scaled else np.pad(dm, 1)
    dt = jnp.dtype(dtype_name)
    out = (jnp.asarray(a64, dt), jnp.asarray(b64, dt),
           jnp.asarray(rhs64, dt), jnp.asarray(aux64, dt))
    if len(_SHIFT_CACHE) >= _SHIFT_CACHE_CAP:
        _SHIFT_CACHE.pop(next(iter(_SHIFT_CACHE)))
    _SHIFT_CACHE[key] = out
    return out


# -- the session step entry point ---------------------------------------

def session_step_solve(problem: Problem, dtype=None, scaled=None,
                       geometry=None, warm=None, warm_geometry=None,
                       mass_shift: float = 0.0, u_prev=None,
                       rhs_gate=None,
                       drift_bound: float = DEFAULT_DRIFT_BOUND,
                       residual_factor: float = DEFAULT_RESIDUAL_FACTOR):
    """One session step. Returns ``(PCGResult, info)`` where ``info`` is
    ``{"warm_used": bool, "fallback": reason}``.

    ``mass_shift == 0`` is a Poisson step of the (possibly moved)
    domain; with a valid ``warm`` iterate it runs :func:`_solve_warm`,
    otherwise it delegates to the literal :func:`pcg_solve` — the
    byte-identical historical executable. ``mass_shift = 1/Δt > 0`` is
    one implicit-Euler heat step with transient RHS ``B + m·uⁿ``
    (``u_prev``; zero when omitted — a cold start from rest).

    ``warm`` is the previous step's w-space solution grid;
    ``warm_geometry`` is the spec that solution solved (the validity
    gate's drift input). An invalid warm start runs cold and is audible:
    ``session.warm.fallbacks`` + a reasoned ``session.warm.fallback``
    event. A *used* warm start counts ``session.warm.hits``.
    """
    dtype_name = resolve_dtype(dtype)
    use_scaled = resolve_scaled(scaled, dtype_name)
    m = float(mass_shift)
    if m < 0.0:
        raise ValueError(f"mass_shift must be >= 0, got {m} "
                         "(it is 1/dt of an implicit-Euler step)")
    obs.inc("session.steps")

    def _gate(a, b, rhs, aux):
        """(warm_ok, w0, reason) against the already-built canvases."""
        if warm is None:
            return False, None, "none"
        ok, reason = warm_validity(warm_geometry, geometry, drift_bound)
        if not ok:
            return False, None, reason
        w0 = jnp.asarray(warm, rhs.dtype)
        if w0.shape != rhs.shape:
            return False, None, "shape"
        if not _residual_ok(problem, a, b, rhs, aux, use_scaled, w0,
                            m, residual_factor):
            return False, None, "residual"
        return True, w0, ""

    def _audit(used: bool, reason: str) -> dict:
        if used:
            obs.inc("session.warm.hits")
        elif warm is not None:
            # A warm start was OFFERED and rejected: the audible
            # fallback contract. (warm=None is a deliberate cold step,
            # not a fallback.)
            obs.inc("session.warm.fallbacks")
            obs.event("session.warm.fallback", reason=reason,
                      geometry=fingerprint_of(geometry),
                      warm_geometry=fingerprint_of(warm_geometry))
        return {"warm_used": used, "fallback": "" if used else reason}

    if m != 0.0:
        a, b, rhs0, aux = shifted_setup(problem, geometry, dtype_name,
                                        use_scaled, m)
        if rhs_gate is not None:
            rhs0 = rhs0 * jnp.asarray(rhs_gate, rhs0.dtype)
        up = (jnp.zeros_like(rhs0) if u_prev is None
              else jnp.asarray(u_prev, rhs0.dtype))
        # Gate against the true transient RHS (B + m·uⁿ, scaled into the
        # solve's system) — the residual check must see the operator and
        # RHS the solve will actually run.
        rhs_step = rhs0 + jnp.asarray(m, rhs0.dtype) * pad_interior(
            interior(up))
        if use_scaled:
            rhs_step = rhs_step * aux
        used, w0, reason = _gate(a, b, rhs_step, aux)
        md = jnp.asarray(m, rhs0.dtype)
        result = _solve_shifted(
            problem, use_scaled, used, a, b, rhs0, aux, md, up,
            w0 if used else jnp.zeros_like(rhs0))
        return result, _audit(used, reason)

    a, b, rhs, aux = solve_setup(problem, dtype_name, use_scaled,
                                 geometry=geometry)
    if rhs_gate is not None:
        rhs = rhs * jnp.asarray(rhs_gate, rhs.dtype)
    used, w0, reason = _gate(a, b, rhs, aux)
    if used:
        result = _solve_warm(problem, use_scaled, a, b, rhs, aux, w0)
        return result, _audit(True, "")
    # Cold path: the literal historical entry point — byte-identical
    # executable (ledger: session.step_cold_f64 == solve.jacobi_f64).
    result = pcg_solve(problem, dtype=dtype_name, scaled=use_scaled,
                       rhs_gate=rhs_gate, geometry=geometry)
    return result, _audit(False, reason)


def design_step(problem: Problem, params, target, lr: float,
                dtype=None, scaled=None):
    """One gradient-descent step of the server-driven shape-design loop.

    ``params`` is a dict with keys among ``cx, cy, rx, ry`` (the
    differentiable ellipse parameters — ``geometry.canvas.traced_
    fields``); ``target`` is the solution grid to match; the loss is the
    mean squared interior mismatch. Returns ``(new_params, loss,
    grads)`` — one forward solve + one implicit adjoint solve
    (:func:`solvers.adjoint.shape_gradient`), whatever the iteration
    counts. The serve session's ``kind="design"`` steps call this."""
    from poisson_tpu.solvers.adjoint import shape_gradient

    target = jnp.asarray(target)

    def spec_fn(p):
        return Ellipse(cx=p["cx"], cy=p["cy"], rx=p["rx"], ry=p["ry"])

    def loss_fn(w):
        d = interior(w) - interior(target)
        return jnp.mean(d * d)

    loss, grads = shape_gradient(problem, spec_fn, params, loss_fn,
                                 dtype=dtype, scaled=scaled)
    new_params = {k: float(params[k]) - float(lr) * float(grads[k])
                  for k in params}
    obs.inc("session.design.steps")
    return new_params, float(loss), {k: float(v) for k, v in grads.items()}
