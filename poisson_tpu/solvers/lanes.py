"""Lane-carry stepping for continuous batching: the resumable batched PCG.

``solvers.batched`` runs a whole bucket to completion in one fused
``while_loop`` — batch-drain: a member that converges at iteration 40
holds its lane idle until the slowest member stops. This module is the
solver half of the Orca-style fix (iteration-level scheduling, PAPERS.md):
the same vmapped body, the same per-member masking, but driven as a
**resumable stepping program** — ``step()`` advances every lane by at most
``chunk`` iterations and returns to the host, where converged lanes can be
retired and fresh right-hand sides spliced into the freed slots of the
*same* compiled executable. No recompile, no restart of in-flight members.

Three facts make the splice sound, and the tests pin all of them:

1. **Per-member independence.** Every reduction in the ops bundle is
   per-member (trailing-axes sums), so lane *i*'s iterate trajectory is a
   pure function of lane *i*'s state — writing a new member into lane *j*
   cannot perturb lane *i* by even an ULP.
2. **Chunk-invariance.** The stepping body freezes a member at its own
   ``stop_at = min(k + chunk, cap)``; re-entering the loop from carried
   state continues the exact sequence (the same argument that makes
   ``checkpoint.run_chunked`` bit-exact, vectorized per lane).
3. **Identity conservation.** ``origin[lane]`` carries the member id
   through every splice/retire; a retired lane's result is attributable
   to exactly one id, and an EMPTY lane (``origin[lane] is None``) is a
   pre-stopped zero member the loop never advances.

The lane lifecycle (state diagram in README "Solve service"):
EMPTY → (splice) → ACTIVE → (converged/cap/deadline/verdict at a chunk
boundary) → RETIRING → (result read, slot cleared) → EMPTY. RETIRING is
host-synchronous — it exists between ``step()`` returning and
``retire()`` clearing the slot — which is what makes "nothing is ever
lost" checkable: a lane is only ever EMPTY or attributed.
"""

from __future__ import annotations

import functools
from typing import List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from poisson_tpu.config import Problem
from poisson_tpu.solvers.pcg import (
    FLAG_NAMES,
    PCGState,
    host_setup,
    init_state,
    make_pcg_body,
    resolve_dtype,
    resolve_scaled,
    scaled_single_device_ops,
    single_device_ops,
    solve_setup,
)


@functools.partial(jax.jit, static_argnums=(0, 1))
def _member_init(problem: Problem, scaled: bool, a, b, aux,
                 rhs) -> PCGState:
    """One member's ``init_state`` under jit — the same compiled
    arithmetic as the fused solvers' inits, so a spliced member starts
    from byte-identical state."""
    ops = (
        scaled_single_device_ops(problem, a, b, aux)
        if scaled
        else single_device_ops(problem, a, b, aux)
    )
    return init_state(ops, rhs)


# The lane index is a TRACED operand in both lane-slot programs: one
# compiled executable serves every lane of a bucket (a static Python
# index would compile bucket × leaf-count tiny programs and turn each
# splice/retire into a stack of dispatches — measured ~16 ms per
# operation on CPU, dwarfing the chunk compute it brackets).

@jax.jit
def _set_lane(state: PCGState, lane, member: PCGState) -> PCGState:
    """Write ``member``'s per-lane state into slot ``lane``."""
    return jax.tree_util.tree_map(
        lambda full, one: full.at[lane].set(one), state, member)


@jax.jit
def _set_field_lane(stack, lane, field):
    """Write one member's 2D canvas into slot ``lane`` of a stacked
    coefficient field (the multi-geometry splice: new canvases enter a
    RUNNING bucket program as operands — the executables never change)."""
    return stack.at[lane].set(field)


@jax.jit
def _take_field_lane(stack, lane):
    """Read slot ``lane``'s 2D canvas out of a stacked field (retire
    needs the member's own aux to unscale its iterate)."""
    return stack[lane]


@jax.jit
def _take_lane(state: PCGState, lane,
               blank: PCGState) -> tuple[PCGState, PCGState]:
    """Read slot ``lane`` out and clear it to ``blank`` in one program:
    (member_state, state_with_lane_emptied)."""
    member = jax.tree_util.tree_map(lambda leaf: leaf[lane], state)
    cleared = jax.tree_util.tree_map(
        lambda full, one: full.at[lane].set(one), state, blank)
    return member, cleared


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def _step_lanes(problem: Problem, scaled: bool, chunk: int,
                a, b, aux, state: PCGState) -> PCGState:
    """Advance every lane by at most ``chunk`` of ITS OWN iterations.

    Exactly ``solvers.batched.pcg_loop_batched``'s masked vmapped body,
    but the stop line is per-member and relative to the carried state:
    ``stop_at[i] = min(k[i] + chunk, cap)``. A lane that was spliced in
    mid-flight (k=0) and a lane 200 iterations deep each get ``chunk``
    more iterations; done lanes (converged, verdict, or EMPTY) stay
    frozen. Compiled once per (bucket, grid, dtype, scaled, chunk) — the
    executable every refill of the same bucket reuses.
    """
    ops = (
        scaled_single_device_ops(problem, a, b, aux)
        if scaled
        else single_device_ops(problem, a, b, aux)
    )
    body = make_pcg_body(
        ops, delta=problem.delta, weighted_norm=problem.weighted_norm,
        h1=problem.h1, h2=problem.h2,
    )
    vbody = jax.vmap(body)
    stop_at = jnp.minimum(state.k + chunk, problem.iteration_cap)

    def masked_body(s: PCGState) -> PCGState:
        stepped = vbody(s)
        frozen = s.done | (s.k >= stop_at)

        def keep(old, new):
            pred = frozen.reshape(frozen.shape + (1,) * (new.ndim - 1))
            return jnp.where(pred, old, new)

        return jax.tree_util.tree_map(keep, s, stepped)

    def cond(s: PCGState):
        return jnp.any((~s.done) & (s.k < stop_at))

    return lax.while_loop(cond, masked_body, state)


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4))
def _step_lanes_verify(problem: Problem, scaled: bool, chunk: int,
                       verify_every: int, verify_tol: float,
                       a, b, aux, rhs_stack, state: PCGState) -> PCGState:
    """:func:`_step_lanes` with the PER-LANE integrity probe armed
    (``poisson_tpu.integrity``): the pair-form body
    (``make_pcg_member_body``) is vmapped with ``rhs_stack`` so each
    lane's drift invariant checks its OWN right-hand side — a flipped
    bit stops only the corrupted lane with FLAG_INTEGRITY; its
    co-residents' trajectories are untouched (masked like every other
    per-lane stop). A separate jitted program on purpose: the flag-off
    :func:`_step_lanes` keeps its historical operand signature and HLO
    byte-for-byte."""
    from poisson_tpu.solvers.pcg import make_pcg_member_body

    ops = (
        scaled_single_device_ops(problem, a, b, aux)
        if scaled
        else single_device_ops(problem, a, b, aux)
    )
    member = make_pcg_member_body(
        ops, delta=problem.delta, weighted_norm=problem.weighted_norm,
        h1=problem.h1, h2=problem.h2,
        verify_every=verify_every, verify_tol=verify_tol,
    )
    vbody = jax.vmap(member, in_axes=(0, 0))
    stop_at = jnp.minimum(state.k + chunk, problem.iteration_cap)

    def masked_body(s: PCGState) -> PCGState:
        stepped = vbody(s, rhs_stack)
        frozen = s.done | (s.k >= stop_at)

        def keep(old, new):
            pred = frozen.reshape(frozen.shape + (1,) * (new.ndim - 1))
            return jnp.where(pred, old, new)

        return jax.tree_util.tree_map(keep, s, stepped)

    def cond(s: PCGState):
        return jnp.any((~s.done) & (s.k < stop_at))

    return lax.while_loop(cond, masked_body, state)


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def _step_lanes_geo(problem: Problem, scaled: bool, chunk: int,
                    a_stack, b_stack, aux_stack,
                    state: PCGState) -> PCGState:
    """:func:`_step_lanes` with PER-LANE coefficient canvases: a/b/aux
    carry a leading (bucket, …) axis and are vmapped with the state, so
    every lane solves its own fictitious domain
    (``poisson_tpu.geometry``) inside the same stepping executable.
    Canvases are operands — splicing a NEW geometry into a freed lane
    reuses this exact compiled program, no recompile. The vmapped
    masked body is :func:`batched.pcg_step_batched_fields` — the SAME
    construction as the fused solve, run to the per-lane stop line."""
    from poisson_tpu.solvers.batched import pcg_step_batched_fields

    stop_at = jnp.minimum(state.k + chunk, problem.iteration_cap)
    return pcg_step_batched_fields(
        problem, scaled, a_stack, b_stack, aux_stack, state, stop_at,
        delta=problem.delta, weighted_norm=problem.weighted_norm,
        h1=problem.h1, h2=problem.h2)


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4))
def _step_lanes_geo_verify(problem: Problem, scaled: bool, chunk: int,
                           verify_every: int, verify_tol: float,
                           a_stack, b_stack, aux_stack, rhs_stack,
                           state: PCGState) -> PCGState:
    """:func:`_step_lanes_geo` with the per-lane integrity probe armed:
    canvases AND right-hand sides ride per-lane stacks, so each lane's
    drift invariant checks its own domain's true residual. Separate
    program for the same reason as :func:`_step_lanes_verify` — the
    flag-off geo stepping executable stays byte-identical."""
    from poisson_tpu.solvers.batched import pcg_step_batched_fields

    stop_at = jnp.minimum(state.k + chunk, problem.iteration_cap)
    return pcg_step_batched_fields(
        problem, scaled, a_stack, b_stack, aux_stack, state, stop_at,
        delta=problem.delta, weighted_norm=problem.weighted_norm,
        h1=problem.h1, h2=problem.h2,
        verify_every=verify_every, verify_tol=verify_tol,
        rhs_stack=rhs_stack)


class LaneResult(NamedTuple):
    """One retired lane's attributable outcome (host-side values)."""

    member_id: object         # the id given at splice time — never None
    lane: int
    w: jnp.ndarray            # solution grid, scaling already unapplied
    iterations: int
    diff: float
    residual_dot: float
    flag: int                 # solvers.pcg FLAG_* verdict at retirement

    @property
    def flag_name(self) -> str:
        return FLAG_NAMES.get(self.flag, str(self.flag))


class LaneBatch:
    """A fixed-width bucket of solve lanes driven chunk by chunk.

    ``splice(member_id, rhs_gate)`` loads a member into a free lane (its
    RHS is the problem's, scaled by ``rhs_gate`` — byte-identical to what
    ``solve_batched(problem, rhs_gates=[g])`` would build, so a spliced
    member's iterates match an unrefilled solve of the same member
    bit-for-bit); ``step()`` advances every lane by at most ``chunk``
    iterations; ``lane_view()`` reads the per-lane (k, done, flag, diff)
    truth; ``retire(lane)`` extracts the attributed result and returns
    the lane to EMPTY. The caller owns the schedule — this class only
    guarantees that any interleaving of splice/step/retire conserves
    lane identity and member trajectories.
    """

    def __init__(self, problem: Problem, bucket: int, *, dtype=None,
                 scaled=None, chunk: int = 50, on_boundary=None,
                 multi_geometry: bool = False, verify_every: int = 0,
                 verify_tol=None, preconditioner: str = "jacobi",
                 mg_config=None, device=None):
        if bucket < 1:
            raise ValueError(f"bucket must be >= 1, got {bucket}")
        if chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        # Bound device (serve.placement): the lane state lives — and the
        # stepping/splice programs compile and run — on this jax.Device.
        # None keeps the historical default-device behavior exactly.
        self.device = device
        # MG lanes (poisson_tpu.mg): the stepping program's member body
        # carries one V-cycle in apply_Dinv against the SHARED level
        # hierarchy — decided at construction like multi_geometry (an
        # occupied program's operand signature never changes). Mixed
        # per-lane geometries would each need their own hierarchy, so
        # the combination is rejected (the service dispatches
        # geometry+MG requests solo).
        self.preconditioner = "jacobi"
        self._mg_config = None
        self._hier = None
        if preconditioner not in (None, "jacobi"):
            from poisson_tpu.mg import (
                DEFAULT_MG,
                resolve_preconditioner,
                validate_mg_problem,
            )

            resolve_preconditioner(preconditioner)
            if multi_geometry:
                raise ValueError(
                    "preconditioner='mg' lanes do not carry per-lane "
                    "geometries yet; build a jacobi table or dispatch "
                    "geometry+MG requests solo")
            self.preconditioner = "mg"
            self._mg_config = mg_config or DEFAULT_MG
            validate_mg_problem(problem, self._mg_config)
        # Multi-geometry lanes (poisson_tpu.geometry): the coefficient
        # canvases become PER-LANE stacks spliced alongside the state,
        # so different fictitious domains share the one stepping
        # executable. Decided at construction — a homogeneous table
        # keeps the historical unstacked programs byte-for-byte, and an
        # occupied program's operand signature can never change.
        self.multi_geometry = bool(multi_geometry)
        # Chunk-boundary event hook (the flight recorder's seam): called
        # host-side after each step() with the step accounting
        # ({"step", "active", "idle", "chunk"}). Purely host-side — the
        # traced/jitted programs are the same objects with or without a
        # hook, so the flag-off path is byte-identical and golden
        # iteration counts are structurally unchanged.
        self.on_boundary = on_boundary
        self.problem = problem
        self.bucket = int(bucket)
        self.chunk = int(chunk)
        self.dtype_name = resolve_dtype(dtype)
        self.use_scaled = resolve_scaled(scaled, self.dtype_name)
        # f_val never enters the traced program (the RHS is a traced
        # operand) — normalize it out of the static jit key exactly like
        # solve_batched, so lane programs share executables across RHS
        # magnitudes.
        self._jit_problem = problem.with_(f_val=1.0)
        a, b, rhs, aux = host_setup(problem, self.dtype_name,
                                    self.use_scaled)
        self._a, self._b, self._aux = a, b, aux
        self._rhs = rhs               # includes problem.f_val
        if self.preconditioner == "mg":
            from poisson_tpu.mg.hierarchy import device_hierarchy
            from poisson_tpu.mg.preconditioner import mg_ops

            self._hier = device_hierarchy(
                problem, self.dtype_name, self.use_scaled,
                config=self._mg_config)
            self._ops = mg_ops(self._jit_problem, a, b, aux, self._hier,
                               self._mg_config, self.use_scaled)
        else:
            self._ops = (
                scaled_single_device_ops(self._jit_problem, a, b, aux)
                if self.use_scaled
                else single_device_ops(self._jit_problem, a, b, aux)
            )
        # All lanes start EMPTY: a zero member, pre-stopped, never advanced.
        zeros = jnp.zeros((self.bucket,) + problem.grid_shape,
                          jnp.dtype(self.dtype_name))
        init = jax.vmap(functools.partial(init_state, self._ops))(zeros)
        self.state: PCGState = init._replace(
            done=jnp.ones((self.bucket,), bool))
        self._blank = jax.tree_util.tree_map(lambda leaf: leaf[0],
                                             self.state)
        if self.multi_geometry:
            # Per-lane canvas stacks, seeded with the default (ellipse)
            # canvases; EMPTY lanes keep whatever canvases last occupied
            # them (they are frozen width either way).
            wide = (self.bucket,) + problem.grid_shape
            self._a_stack = jnp.broadcast_to(a, wide) + 0
            self._b_stack = jnp.broadcast_to(b, wide) + 0
            self._aux_stack = jnp.broadcast_to(aux, wide) + 0
        # In-loop integrity probe (poisson_tpu.integrity), per lane:
        # each lane's drift invariant needs that lane's OWN right-hand
        # side, so a verified table carries a per-lane RHS stack spliced
        # alongside the state. verify_every=0 (the default) allocates
        # nothing and steps through the exact historical executables.
        self.verify_every = int(verify_every)
        if self.verify_every > 0:
            from poisson_tpu.solvers.pcg import resolve_verify_tol

            self.verify_tol = resolve_verify_tol(verify_tol,
                                                 self.dtype_name)
            self._rhs_stack = zeros      # EMPTY lanes: zero RHS
        else:
            self.verify_tol = 0.0
        self.origin: List[object] = [None] * self.bucket
        self.steps = 0                # chunk steps executed
        self.idle_lane_steps = 0      # Σ over steps of non-ACTIVE lanes

    def _on_device(self):
        """Placement context: computations (and the executables they
        compile) target the bound device. A null context when unbound —
        the historical default-device path, untouched."""
        if self.device is None:
            import contextlib

            return contextlib.nullcontext()
        return jax.default_device(self.device)

    # -- occupancy -----------------------------------------------------

    def free_lanes(self) -> List[int]:
        return [i for i, m in enumerate(self.origin) if m is None]

    def active_lanes(self) -> List[int]:
        return [i for i, m in enumerate(self.origin) if m is not None]

    def occupied(self) -> bool:
        return any(m is not None for m in self.origin)

    # -- the state machine ---------------------------------------------

    def splice(self, member_id, rhs_gate: float = 1.0,
               lane: Optional[int] = None, geometry=None) -> int:
        """EMPTY → ACTIVE: load ``member_id``'s solve into a free lane.

        The member's init state is the sequential solver's ``init_state``
        of ``rhs · rhs_gate`` — the same arrays ``solve_batched`` stacks,
        so per-member independence (module docstring) makes the spliced
        trajectory identical to an unrefilled solve. Returns the lane.

        ``geometry`` (multi-geometry tables only) splices the member's
        OWN fingerprint-cached canvases into the lane with its state —
        a new fictitious domain enters the running bucket executable as
        operands, never as a recompile. ``None`` is the problem's
        default (ellipse) canvases either way.
        """
        if member_id is None:
            raise ValueError("member_id must not be None (None marks an "
                             "EMPTY lane)")
        if member_id in self.origin:
            raise ValueError(f"member {member_id!r} already occupies lane "
                             f"{self.origin.index(member_id)}")
        if geometry is not None and not self.multi_geometry:
            raise ValueError(
                "this LaneBatch was built single-geometry; construct it "
                "with multi_geometry=True to splice per-member domains")
        if lane is None:
            free = self.free_lanes()
            if not free:
                raise ValueError("no EMPTY lane to splice into")
            lane = free[0]
        elif self.origin[lane] is not None:
            raise ValueError(f"lane {lane} is ACTIVE (member "
                             f"{self.origin[lane]!r})")
        if geometry is not None:
            ga, gb, grhs, gaux = solve_setup(
                self.problem, self.dtype_name, self.use_scaled,
                geometry=geometry)
        else:
            ga, gb, grhs, gaux = self._a, self._b, self._rhs, self._aux
        with self._on_device():
            rhs = grhs * jnp.asarray(rhs_gate, grhs.dtype)
            if self.preconditioner == "mg":
                from poisson_tpu import obs
                from poisson_tpu.mg.preconditioner import _member_init_mg

                # One splice = one MG-preconditioned member solve (the
                # lane-engine leg of the mg.solves rollout counter).
                obs.inc("mg.solves")
                member = _member_init_mg(self._jit_problem,
                                         self.use_scaled,
                                         self._mg_config, ga, gb, gaux,
                                         self._hier, rhs)
            else:
                member = _member_init(self._jit_problem, self.use_scaled,
                                      ga, gb, gaux, rhs)
            lane_idx = jnp.asarray(lane, jnp.int32)
            self.state = _set_lane(self.state, lane_idx, member)
            if self.verify_every > 0:
                self._rhs_stack = _set_field_lane(self._rhs_stack,
                                                  lane_idx, rhs)
            if self.multi_geometry:
                self._a_stack = _set_field_lane(self._a_stack, lane_idx,
                                                ga)
                self._b_stack = _set_field_lane(self._b_stack, lane_idx,
                                                gb)
                self._aux_stack = _set_field_lane(self._aux_stack,
                                                  lane_idx, gaux)
        self.origin[lane] = member_id
        return lane

    def step(self) -> dict:
        """Advance every ACTIVE lane by at most ``chunk`` iterations.

        Returns host-side accounting: ``{"active": n, "idle": n}`` for
        the step just taken (idle lanes are EMPTY slots whose width the
        fused program still computes — the utilization cost continuous
        refill exists to keep low).
        """
        active = len(self.active_lanes())
        idle = self.bucket - active
        if active:
            with self._on_device():
                self._step_active()
            self.steps += 1
            self.idle_lane_steps += idle
            if self.on_boundary is not None:
                self.on_boundary({"step": self.steps, "active": active,
                                  "idle": idle, "chunk": self.chunk})
        return {"active": active, "idle": idle}

    def _step_active(self) -> None:
        """One chunk over the live state, on the bound device (the
        dispatch body of :meth:`step` — split out so the placement
        context wraps exactly the compiled work)."""
        if self.preconditioner == "mg":
            from poisson_tpu.mg.preconditioner import _step_lanes_mg

            self.state = _step_lanes_mg(
                self._jit_problem, self.use_scaled, self.chunk,
                self._mg_config, self.verify_every, self.verify_tol,
                self._a, self._b, self._aux, self._hier,
                (self._rhs_stack if self.verify_every > 0 else None),
                self.state)
        elif self.verify_every > 0 and self.multi_geometry:
            self.state = _step_lanes_geo_verify(
                self._jit_problem, self.use_scaled, self.chunk,
                self.verify_every, self.verify_tol,
                self._a_stack, self._b_stack, self._aux_stack,
                self._rhs_stack, self.state)
        elif self.verify_every > 0:
            self.state = _step_lanes_verify(
                self._jit_problem, self.use_scaled, self.chunk,
                self.verify_every, self.verify_tol,
                self._a, self._b, self._aux, self._rhs_stack,
                self.state)
        elif self.multi_geometry:
            self.state = _step_lanes_geo(
                self._jit_problem, self.use_scaled, self.chunk,
                self._a_stack, self._b_stack, self._aux_stack,
                self.state)
        else:
            self.state = _step_lanes(self._jit_problem,
                                     self.use_scaled,
                                     self.chunk, self._a, self._b,
                                     self._aux, self.state)

    def lane_view(self) -> List[dict]:
        """Host-readable per-lane truth after a step: one dict per lane
        with ``lane``/``member_id``/``k``/``done``/``flag``/``diff``
        (EMPTY lanes included, ``member_id=None``)."""
        ks = np.asarray(self.state.k)
        dones = np.asarray(self.state.done)
        flags = np.asarray(self.state.flag)
        diffs = np.asarray(self.state.diff)
        return [
            {"lane": i, "member_id": self.origin[i], "k": int(ks[i]),
             "done": bool(dones[i]), "flag": int(flags[i]),
             "diff": float(diffs[i])}
            for i in range(self.bucket)
        ]

    def retire(self, lane: int) -> LaneResult:
        """ACTIVE → RETIRING → EMPTY: extract the lane's attributed
        result and clear the slot for the next splice. The caller decides
        *when* (converged, verdict, cap, deadline) — retirement itself is
        unconditional so a poisoned or deadlined member can always be
        pulled out with its partial iterate intact."""
        member_id = self.origin[lane]
        if member_id is None:
            raise ValueError(f"lane {lane} is already EMPTY")
        member, self.state = _take_lane(self.state,
                                        jnp.asarray(lane, jnp.int32),
                                        self._blank)
        if self.use_scaled:
            aux = (_take_field_lane(self._aux_stack,
                                    jnp.asarray(lane, jnp.int32))
                   if self.multi_geometry else self._aux)
            w = member.w * aux
        else:
            w = member.w
        result = LaneResult(
            member_id=member_id, lane=lane, w=w,
            iterations=int(member.k),
            diff=float(member.diff),
            residual_dot=float(member.zr),
            flag=int(member.flag),
        )
        self.origin[lane] = None
        return result
