"""Differentiable solves: implicit adjoint differentiation through PCG.

A capability the reference's C++ stack cannot express: the solve
``w = A⁻¹ B`` as a differentiable JAX operation. Because the
fictitious-domain operator A is symmetric (shared edge coefficients make
the assembled 5-point matrix SPD), the vector–Jacobian product of the
solve is itself a solve with the cotangent as right-hand side:

    w = A⁻¹ b     ⇒     ∂L/∂b = A⁻¹ (∂L/∂w)

so the backward pass reuses the forward solver unchanged (implicit
differentiation — no unrolling of the CG iteration, O(1) memory in the
iteration count, gradients exact to solver tolerance δ). This turns the
solver into a building block for PDE-constrained optimisation: source
identification, RHS calibration, end-to-end learning against solution
functionals.

:func:`differentiable_solve` differentiates the right-hand side against
the baked reference geometry. :func:`differentiable_geometry_solve` goes
further: the coefficient canvases themselves are built IN-GRAPH from a
closed-form :mod:`poisson_tpu.geometry` spec whose parameters may be
tracers, so ``jax.grad`` flows through the ε-blend into the shape
parameters — ∂w/∂(cx, cy, rx, ry) via the same implicit adjoint (the
JVP of ``custom_linear_solve`` is dw = A⁻¹(db − dA·w), and dA is the
canvas builder's parameter derivative). Every geometry request thereby
becomes a differentiable design scenario: shape optimisation against
any solution functional, at O(1) memory in the iteration count. The
blend is piecewise-smooth — within a blend class a cut face's ℓ varies
smoothly with the shape; the measure-zero class-transition boundaries
carry subgradients, the standard situation for embedded-boundary shape
differentiation (Glowinski, Pan & Périaux 1994, PAPERS.md). Sampled
families (polygons, composites, raw SDFs) are built by host-side
bisection and are deliberately rejected rather than returning silent
zero gradients.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from poisson_tpu.config import Problem
from poisson_tpu.ops.stencil import apply_A, diag_D, interior, pad_interior
from poisson_tpu.solvers.pcg import (
    _solve,
    host_setup,
    resolve_dtype,
    resolve_scaled,
)


@functools.lru_cache(maxsize=8)
def _make_differentiable(problem: Problem, dtype_name: str, scaled: bool):
    a, b, _, aux = host_setup(problem, dtype_name, scaled)
    h1, h2 = problem.h1, problem.h2

    def matvec(x):
        # A's action (zero outside the interior); symmetric by construction
        # (shared edge coefficients).
        return apply_A(x, a, b, h1, h2)

    def solve_fn(_matvec, rhs):
        # rhs arrives ring-projected; the scaled system takes b̃ = sc·B.
        r = rhs * aux if scaled else rhs
        return _solve(problem, scaled, 0, 0, 0.0, False, 0,
                      a, b, r, aux).w

    def solve(rhs_grid):
        rhs_proj = pad_interior(interior(rhs_grid))
        # symmetric=True makes the transpose solve the same solve, giving
        # correct jvp, vjp, and linear_transpose without a custom rule.
        return lax.custom_linear_solve(
            matvec, rhs_proj, solve_fn, symmetric=True
        )

    return solve


def differentiable_solve(problem: Problem, rhs_grid, dtype=None,
                         scaled=None):
    """``w = A⁻¹ rhs`` on the full (M+1, N+1) grid, differentiable in
    ``rhs_grid`` under ``jax.grad``/``jax.vjp``/``jax.jvp``/
    ``jax.linear_transpose``.

    The standard problem's RHS is ``models.fictitious_domain.build_fields``'
    B; any other interior source works. Ring entries of ``rhs_grid`` are
    ignored (Dirichlet)."""
    dtype_name = resolve_dtype(dtype)
    use_scaled = resolve_scaled(scaled, dtype_name)
    solve = _make_differentiable(problem, dtype_name, use_scaled)
    return solve(jnp.asarray(rhs_grid, jnp.dtype(dtype_name)))


def differentiable_geometry_solve(problem: Problem, spec, dtype=None,
                                  scaled=None):
    """``w(spec)`` on the full (M+1, M+1) grid, differentiable in the
    SHAPE parameters of a closed-form geometry spec.

    ``spec`` is an :class:`~poisson_tpu.geometry.dsl.Ellipse` or
    :class:`~poisson_tpu.geometry.dsl.Rectangle` whose numeric fields
    may be jax tracers (build it inside the function being
    differentiated). The canvases (a, b, B) come from
    ``geometry.canvas.traced_fields`` — pure jnp, so their parameter
    Jacobian exists — and the solve itself is wrapped in
    ``lax.custom_linear_solve(symmetric=True)``: gradients are implicit
    (one extra solve per cotangent), never an unroll of the CG loop.

    The RHS indicator contributes no derivative (it is piecewise
    constant in the parameters); the shape sensitivity flows through
    the blend coefficients, which is exactly the fictitious-domain
    shape derivative.
    """
    from poisson_tpu.geometry.canvas import traced_fields

    dtype_name = resolve_dtype(dtype)
    use_scaled = resolve_scaled(scaled, dtype_name)
    dt = jnp.dtype(dtype_name)
    h1, h2 = problem.h1, problem.h2
    a, b, rhs = traced_fields(problem, spec, dtype=dt)
    d = diag_D(a, b, h1, h2)
    if use_scaled:
        aux = pad_interior(1.0 / jnp.sqrt(d))
    else:
        aux = pad_interior(d)

    def matvec(x):
        return apply_A(x, a, b, h1, h2)

    def solve_fn(_matvec, r):
        # Primal/transpose solves reuse the jitted PCG machinery on the
        # same (traced) canvases; custom_linear_solve differentiates
        # around it implicitly, so the solver is a black box here.
        ru = r * aux if use_scaled else r
        return _solve(problem, use_scaled, 0, 0, 0.0, False, 0,
                      a, b, ru, aux).w

    rhs_proj = pad_interior(interior(rhs))
    return lax.custom_linear_solve(matvec, rhs_proj, solve_fn,
                                   symmetric=True)


def shape_gradient(problem: Problem, spec_fn, params, loss_fn,
                   dtype=None, scaled=None):
    """(loss, ∂loss/∂params) for a shape-design objective.

    ``spec_fn(params)`` builds the closed-form geometry from a pytree of
    parameters (e.g. ``lambda p: Ellipse(rx=p["rx"], ry=p["ry"])``);
    ``loss_fn(w)`` scores the solution grid. One forward solve + one
    adjoint solve, whatever the iteration counts — each solve request is
    a differentiable design scenario."""

    def objective(p):
        w = differentiable_geometry_solve(problem, spec_fn(p),
                                          dtype=dtype, scaled=scaled)
        return loss_fn(w)

    return jax.value_and_grad(objective)(params)
