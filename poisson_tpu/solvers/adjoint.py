"""Differentiable solves: implicit adjoint differentiation through PCG.

A capability the reference's C++ stack cannot express: the solve
``w = A⁻¹ B`` as a differentiable JAX operation. Because the
fictitious-domain operator A is symmetric (shared edge coefficients make
the assembled 5-point matrix SPD), the vector–Jacobian product of the
solve is itself a solve with the cotangent as right-hand side:

    w = A⁻¹ b     ⇒     ∂L/∂b = A⁻¹ (∂L/∂w)

so the backward pass reuses the forward solver unchanged (implicit
differentiation — no unrolling of the CG iteration, O(1) memory in the
iteration count, gradients exact to solver tolerance δ). This turns the
solver into a building block for PDE-constrained optimisation: source
identification, RHS calibration, end-to-end learning against solution
functionals.

Only the right-hand side is differentiated; the geometry coefficients are
baked per ``Problem`` (differentiating the domain shape would require the
ε-blend's derivative, which the fictitious-domain method does not define
smoothly at face transitions).
"""

from __future__ import annotations

import functools

import jax.numpy as jnp
from jax import lax

from poisson_tpu.config import Problem
from poisson_tpu.ops.stencil import apply_A, interior, pad_interior
from poisson_tpu.solvers.pcg import (
    _solve,
    host_setup,
    resolve_dtype,
    resolve_scaled,
)


@functools.lru_cache(maxsize=8)
def _make_differentiable(problem: Problem, dtype_name: str, scaled: bool):
    a, b, _, aux = host_setup(problem, dtype_name, scaled)
    h1, h2 = problem.h1, problem.h2

    def matvec(x):
        # A's action (zero outside the interior); symmetric by construction
        # (shared edge coefficients).
        return apply_A(x, a, b, h1, h2)

    def solve_fn(_matvec, rhs):
        # rhs arrives ring-projected; the scaled system takes b̃ = sc·B.
        r = rhs * aux if scaled else rhs
        return _solve(problem, scaled, 0, a, b, r, aux).w

    def solve(rhs_grid):
        rhs_proj = pad_interior(interior(rhs_grid))
        # symmetric=True makes the transpose solve the same solve, giving
        # correct jvp, vjp, and linear_transpose without a custom rule.
        return lax.custom_linear_solve(
            matvec, rhs_proj, solve_fn, symmetric=True
        )

    return solve


def differentiable_solve(problem: Problem, rhs_grid, dtype=None,
                         scaled=None):
    """``w = A⁻¹ rhs`` on the full (M+1, N+1) grid, differentiable in
    ``rhs_grid`` under ``jax.grad``/``jax.vjp``/``jax.jvp``/
    ``jax.linear_transpose``.

    The standard problem's RHS is ``models.fictitious_domain.build_fields``'
    B; any other interior source works. Ring entries of ``rhs_grid`` are
    ignored (Dirichlet)."""
    dtype_name = resolve_dtype(dtype)
    use_scaled = resolve_scaled(scaled, dtype_name)
    solve = _make_differentiable(problem, dtype_name, use_scaled)
    return solve(jnp.asarray(rhs_grid, jnp.dtype(dtype_name)))
