"""Mixed-precision iterative refinement: fp64 accuracy from the fp32 path.

The reference runs everything in fp64 (SURVEY §2.5) — on TPU, fp64 is
emulated and slow, so this framework's device path is fp32 on the
symmetrically-scaled system (golden-count exact, ``solvers.pcg`` module
doc). That leaves a gap the reference does not have: the *algebraic*
residual of the fp32 solution floors around unit-roundoff of fp32. This
module closes it with classic iterative refinement (Wilkinson; the standard
mixed-precision HPC recipe):

    w ← fp32_solve(b)                        # TPU speed
    repeat:
        r ← b − A·w        in fp64, on host  # exact residual
        e ← fp32_solve(r)                    # TPU speed
        w ← w + e          in fp64

Each pass multiplies the residual by O(ε₃₂·κ), so 2-3 passes reach the
fp64 floor while every inner solve runs at fp32 throughput. The inner
solver is the fused Pallas path's arbitrary-RHS hook
(``ops.pallas_cg.pallas_cg_solve_rhs``), built for exactly this driver.

**Which residual.** The fictitious-domain operator carries 1/ε
coefficients outside D (ε = max(h)², SURVEY §2.1) — its stiff directions
turn a harmless O(ε) perturbation of the (≈0) fictitious-region solution
into an O(1) raw residual, which is also why the reference's convergence
criterion is the update norm, not the residual. The meaningful algebraic
measure is the residual of the symmetrically-scaled system
Ã = D^{-1/2}AD^{-1/2} (unit diagonal, O(1) spectrum away from 1/ε):
      r̃ = D^{-1/2}·(b − A·w),   converge on ‖r̃‖/‖D^{-1/2}b‖ ≤ tol.
Refinement drives THAT to the fp64 floor (~1e-15 reachable; default tol
1e-10), far below the single-fp32-solve floor (tests/test_refine.py).
Measured contraction is ~25-30× per pass (400×600: 7.3e-5 → 8.2e-15 over
7 corrections) — governed by the inner solver's δ=1e-6 update-norm
criterion, not by fp32 limits, so passes are cheap-ish (a few hundred CG
iterations each) and the default budget is 8.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from poisson_tpu.config import Problem
from poisson_tpu.solvers.pcg import host_fields64


class RefineResult(NamedTuple):
    w: np.ndarray                 # fp64 solution, full (M+1, N+1) grid
    residual_norms: tuple         # weighted L2 of D^{-1/2}(b − A·w) per pass
    inner_iterations: tuple       # PCG iterations of each inner solve
    refinements: int
    relative_residual: float      # final ‖D^{-1/2}(b−A·w)‖ / ‖D^{-1/2}b‖
    converged: bool               # relative_residual <= tol was reached


def apply_A64_host(problem: Problem, a64, b64, w64) -> np.ndarray:
    """The 5-point variable-coefficient operator in fp64 numpy, interior
    points only (zero ring preserved) — the host-side exact-residual
    oracle. Mirrors ``ops.stencil.apply_A`` (and the reference's ``mat_A``,
    ``stage0/Withoutopenmp1.cpp:75-88``) with numpy slices."""
    h1sq, h2sq = problem.h1 ** 2, problem.h2 ** 2
    out = np.zeros_like(w64)
    c = w64[1:-1, 1:-1]
    ax = a64[1:-1, 1:-1]        # a[i, j]   (south face of point (i, j))
    axn = a64[2:, 1:-1]         # a[i+1, j] (north face)
    bw = b64[1:-1, 1:-1]        # b[i, j]   (west face)
    be = b64[1:-1, 2:]          # b[i, j+1] (east face)
    out[1:-1, 1:-1] = (
        -(axn * (w64[2:, 1:-1] - c) - ax * (c - w64[:-2, 1:-1])) / h1sq
        - (be * (w64[1:-1, 2:] - c) - bw * (c - w64[1:-1, :-2])) / h2sq
    )
    return out


def _weighted_norm(problem: Problem, v64) -> float:
    return float(np.sqrt(np.sum(v64 * v64) * problem.h1 * problem.h2))


def refined_solve(problem: Problem, tol: float = 1e-10,
                  max_refinements: int = 8,
                  bm: int | None = None, bn: int | None = None,
                  interpret: bool | None = None,
                  parallel: bool = False,
                  backend: str = "fused") -> RefineResult:
    """Solve A w = B to relative *scaled-system* residual ``tol``
    (module doc: the raw residual is 1/ε-stiffness-dominated and
    meaningless here) using fp32 device solves plus fp64 host residuals.

    Stops when ‖D^{-1/2}(b − A·w)‖ / ‖D^{-1/2}b‖ ≤ tol or after
    ``max_refinements`` correction passes. Geometry/scheduling knobs are
    forwarded to the fused inner solver. ``backend="resident"`` runs each
    inner correction solve as one VMEM-resident kernel launch
    (``ops.pallas_resident``; grids that fit only — the geometry knobs
    do not apply there).
    """
    if backend == "resident":
        if bm is not None or bn is not None or parallel:
            raise ValueError(
                "bm/bn/parallel shape the fused streaming kernels; the "
                "resident backend has a fixed single-strip geometry"
            )
        from poisson_tpu.ops.pallas_resident import resident_cg_solve_rhs

        def _inner(problem, rhs, **_kw):
            return resident_cg_solve_rhs(problem, rhs, interpret=interpret)

        pallas_cg_solve_rhs = _inner
    elif backend == "fused":
        from poisson_tpu.ops.pallas_cg import pallas_cg_solve_rhs
    else:
        raise ValueError(f"unknown refine backend {backend!r}")

    a64, b64, rhs64, sc64 = _fields(problem)
    bt_norm = _weighted_norm(problem, sc64 * rhs64)   # ‖b̃‖
    if bt_norm == 0.0:
        return RefineResult(np.zeros_like(rhs64), (0.0,), (), 0, 0.0, True)

    w64 = np.zeros_like(rhs64)
    norms = []
    inner = []
    residual = rhs64
    rt_norm = bt_norm
    for k in range(max_refinements + 1):
        # The inner solver stops on an ABSOLUTE update norm (the
        # reference's δ=1e-6 criterion); a correction RHS is orders of
        # magnitude smaller than b, so normalize it to b's scale before the
        # solve and scale the correction back (exact by linearity) — each
        # pass then does the same well-conditioned amount of work.
        scale = bt_norm / rt_norm
        e64, iters = pallas_cg_solve_rhs(
            problem, residual * scale, bm=bm, interpret=interpret,
            parallel=parallel, bn=bn,
        )
        w64 = w64 + e64 / scale
        inner.append(iters)
        residual = rhs64 - apply_A64_host(problem, a64, b64, w64)
        rt_norm = _weighted_norm(problem, sc64 * residual)
        norms.append(rt_norm)
        if rt_norm / bt_norm <= tol or rt_norm == 0.0:
            break
    rel = rt_norm / bt_norm
    return RefineResult(
        w=w64, residual_norms=tuple(norms),
        inner_iterations=tuple(inner), refinements=len(inner) - 1,
        relative_residual=rel, converged=bool(rel <= tol),
    )


def _fields(problem: Problem):
    """(a, b, B, sc) in fp64: the UNSCALED operator fields the residual is
    exact for, plus the scaling vector sc = D^{-1/2} (zero ring) defining
    the residual metric."""
    a64, b64, rhs64, _ = host_fields64(problem, False)
    _, _, _, sc64 = host_fields64(problem, True)
    return a64, b64, rhs64, sc64
