from poisson_tpu.solvers.adjoint import differentiable_solve
from poisson_tpu.solvers.batched import solve_batched
from poisson_tpu.solvers.checkpoint import pcg_solve_checkpointed
from poisson_tpu.solvers.history import pcg_solve_history
from poisson_tpu.solvers.pcg import (
    PCGResult,
    iterations_scalar,
    pcg_solve,
    pcg_step_fn,
)
from poisson_tpu.solvers.refine import RefineResult, refined_solve
from poisson_tpu.solvers.resilient import (
    DivergenceError,
    RecoveryPolicy,
    pcg_solve_resilient,
)

__all__ = [
    "DivergenceError",
    "PCGResult",
    "RecoveryPolicy",
    "RefineResult",
    "differentiable_solve",
    "iterations_scalar",
    "pcg_solve",
    "pcg_solve_checkpointed",
    "pcg_solve_history",
    "pcg_solve_resilient",
    "pcg_step_fn",
    "refined_solve",
    "solve_batched",
]
