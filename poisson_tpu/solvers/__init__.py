from poisson_tpu.solvers.pcg import PCGResult, pcg_solve, pcg_step_fn

__all__ = ["PCGResult", "pcg_solve", "pcg_step_fn"]
