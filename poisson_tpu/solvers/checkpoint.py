"""Checkpoint/resume for long solves.

The reference has no checkpointing (SURVEY §5: a solve runs to convergence
in one shot and the solution never touches disk). At pod scale a preempted
job restarts from iteration zero, so this framework adds the missing
subsystem: the solve runs as fixed-size chunks of the shared PCG body, and
after each chunk the five-array CG state (w, r, z, p, ζ) plus iteration
counter is persisted. A restart with the same problem resumes from the
last chunk boundary and converges to the same answer — CG's iterate
sequence is a pure function of its state, so chunked and one-shot solves
are identical to round-off.

Format: a single ``.npz`` (numpy, host-side) with a problem fingerprint;
a mismatched fingerprint refuses to resume rather than silently solving a
different problem.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from poisson_tpu.config import Problem
from poisson_tpu.solvers.pcg import (
    PCGResult,
    PCGState,
    host_setup,
    init_state,
    make_pcg_body,
    resolve_dtype,
    resolve_scaled,
    scaled_single_device_ops,
    single_device_ops,
)

_STATE_KEYS = ("k", "done", "w", "r", "z", "p", "zr", "diff")


def _fingerprint(problem: Problem, dtype_name: str, scaled: bool) -> str:
    # Bind problem identity, not the stopping budget: max_iter is excluded
    # so a run capped by --max-iter (or preempted) can resume with a larger
    # budget — the natural recovery workflow.
    fields = {
        f.name: getattr(problem, f.name)
        for f in dataclasses.fields(problem)
        if f.name != "max_iter"
    }
    return repr((sorted(fields.items()), dtype_name, scaled))


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def _run_chunk(problem: Problem, scaled: bool, chunk: int,
               a, b, aux, state: PCGState) -> PCGState:
    """Advance the solve by at most ``chunk`` iterations (device-resident)."""
    ops = (
        scaled_single_device_ops(problem, a, b, aux)
        if scaled
        else single_device_ops(problem, a, b, aux)
    )
    body = make_pcg_body(
        ops, delta=problem.delta, weighted_norm=problem.weighted_norm,
        h1=problem.h1, h2=problem.h2,
    )
    stop_at = jnp.minimum(state.k + chunk, problem.iteration_cap)

    def cond(s: PCGState):
        return (~s.done) & (s.k < stop_at)

    return lax.while_loop(cond, body, state)


def run_chunked(state, *, advance, to_portable, path: str, fingerprint: str,
                cap: int, keep_checkpoint: bool, primary=None, sync=None):
    """The one chunked-checkpoint driver loop, shared by all four
    checkpointed solvers (single/sharded × XLA/fused): advance until done
    or cap, persist the portable full-grid state after every chunk, clean
    up a *converged* run's checkpoint (a cap-hit keeps it for resume).

    ``state`` must expose ``.done`` and ``.k``; ``advance(state)`` runs one
    chunk; ``to_portable(state)`` produces the PCGState ``save_state``
    writes. ``primary``/``sync`` gate the file write to one process and
    barrier-order it against other processes' later reads (multi-process
    meshes); they default to single-process no-ops.
    """
    primary = primary if primary is not None else (lambda: True)
    sync = sync if sync is not None else (lambda name: None)
    while (not bool(state.done)) and int(state.k) < cap:
        state = advance(state)
        jax.block_until_ready(state)
        if bool(state.done) and not keep_checkpoint:
            # The chunk just converged and the file would be deleted below:
            # skip the full-grid gather (an all-gather collective on
            # multi-process meshes) and the disk write outright. ``done`` is
            # replicated, so every process skips in step.
            break
        portable = to_portable(state)   # collective when multi-process
        if primary():
            save_state(path, portable, fingerprint)
        sync("poisson_ckpt_save")       # write lands before anyone reads it
    if bool(state.done) and not keep_checkpoint and primary() \
            and os.path.exists(path):
        os.remove(path)
    sync("poisson_ckpt_done")           # removal precedes any follow-up solve
    return state


def save_state(path: str, state: PCGState, fingerprint: str) -> None:
    arrays = {key: np.asarray(val) for key, val in zip(_STATE_KEYS, state)}
    # np.savez appends '.npz' to names without it — keep the temp name
    # suffixed so the atomic-replace source path is what savez wrote.
    tmp = f"{path}.{os.getpid()}.tmp.npz"
    np.savez(tmp, fingerprint=np.asarray(fingerprint), **arrays)
    os.replace(tmp, path)


def load_state(path: str, fingerprint: str) -> Optional[PCGState]:
    """Returns the saved state, or None if absent; raises on a
    fingerprint mismatch (wrong problem/precision for this checkpoint)."""
    if not os.path.exists(path):
        return None
    with np.load(path) as data:
        saved = str(data["fingerprint"])
        if saved != fingerprint:
            raise ValueError(
                f"checkpoint {path} was written for a different problem "
                f"configuration:\n  saved:     {saved}\n  requested: "
                f"{fingerprint}"
            )
        vals = {key: data[key] for key in _STATE_KEYS}
    as_dev = lambda x: jnp.asarray(x)
    return PCGState(**{key: as_dev(val) for key, val in vals.items()})


def pcg_solve_checkpointed(problem: Problem, checkpoint_path: str,
                           chunk: int = 200, dtype=None, scaled=None,
                           keep_checkpoint: bool = False) -> PCGResult:
    """Solve with periodic state persistence and automatic resume.

    Every ``chunk`` iterations the CG state is written to
    ``checkpoint_path``; if that file already exists (same problem
    fingerprint) the solve resumes from it instead of starting over. On
    convergence the checkpoint is removed unless ``keep_checkpoint``.
    """
    if chunk < 1:
        raise ValueError(f"chunk must be >= 1, got {chunk}")
    dtype_name = resolve_dtype(dtype)
    use_scaled = resolve_scaled(scaled, dtype_name)
    a, b, rhs, aux = host_setup(problem, dtype_name, use_scaled)
    fp = _fingerprint(problem, dtype_name, use_scaled)

    ops = (
        scaled_single_device_ops(problem, a, b, aux)
        if use_scaled
        else single_device_ops(problem, a, b, aux)
    )
    state = load_state(checkpoint_path, fp)
    if state is None:
        state = init_state(ops, rhs)

    state = run_chunked(
        state,
        advance=lambda s: _run_chunk(problem, use_scaled, chunk, a, b, aux, s),
        to_portable=lambda s: s,
        path=checkpoint_path, fingerprint=fp, cap=problem.iteration_cap,
        keep_checkpoint=keep_checkpoint,
    )

    w = state.w * aux if use_scaled else state.w
    return PCGResult(
        w=w, iterations=state.k, diff=state.diff, residual_dot=state.zr
    )
