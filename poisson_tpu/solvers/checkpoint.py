"""Checkpoint/resume for long solves.

The reference has no checkpointing (SURVEY §5: a solve runs to convergence
in one shot and the solution never touches disk). At pod scale a preempted
job restarts from iteration zero, so this framework adds the missing
subsystem: the solve runs as fixed-size chunks of the shared PCG body, and
after each chunk the five-array CG state (w, r, z, p, ζ) plus iteration
counter is persisted. A restart with the same problem resumes from the
last chunk boundary and converges to the same answer — CG's iterate
sequence is a pure function of its state, so chunked and one-shot solves
are identical to round-off.

Format: a single ``.npz`` (numpy, host-side) with a problem fingerprint;
a mismatched fingerprint refuses to resume rather than silently solving a
different problem.

Hardening (this layer is the recovery path, so it must survive the same
faults it exists for):

- writes are atomic (tmp + ``os.replace``) and CRC-sealed — a payload
  checksum over every array is stored in the file and verified on load, so
  a truncated or bit-flipped checkpoint is *detected*, never resumed;
- the previous ``keep_last − 1`` generations are retained as
  ``<path>.1 ≥ <path>.2 ≥ …`` (newest first) and ``load_state`` falls back
  through them when the newest generation is corrupt or was written for a
  different problem;
- a state whose in-loop verdict is FLAG_NONFINITE is never persisted —
  the last good generation survives a divergence for the recovery driver
  (``solvers.resilient``) to restart from.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import warnings
import zlib
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from poisson_tpu.config import Problem
from poisson_tpu.solvers.pcg import (
    FLAG_CONVERGED,
    FLAG_DEADLINE,
    FLAG_INTEGRITY,
    FLAG_NONE,
    FLAG_NONFINITE,
    PCGResult,
    PCGState,
    host_setup,
    init_state,
    make_pcg_body,
    resolve_dtype,
    resolve_scaled,
    resolve_verify_tol,
    scaled_single_device_ops,
    single_device_ops,
)

_STATE_KEYS = ("k", "done", "w", "r", "z", "p", "zr", "diff",
               "flag", "best", "stall")
# Verdict fields are absent in checkpoints written before hardening (and
# in portable states produced by the fused solvers); they resume as a
# clean slate rather than failing the load.
_OPTIONAL_DEFAULTS = {"flag": np.int32(0), "best": np.inf,
                      "stall": np.int32(0)}


class CorruptCheckpointError(RuntimeError):
    """The checkpoint file exists but cannot be trusted: unreadable npz,
    missing payload keys, or CRC mismatch."""


def _fingerprint(problem: Problem, dtype_name: str, scaled: bool,
                 preconditioner: str = "jacobi", mg_config=None) -> str:
    # Bind problem identity, not the stopping budget: max_iter is excluded
    # so a run capped by --max-iter (or preempted) can resume with a larger
    # budget — the natural recovery workflow.
    fields = {
        f.name: getattr(problem, f.name)
        for f in dataclasses.fields(problem)
        if f.name != "max_iter"
    }
    if preconditioner not in (None, "jacobi"):
        # The preconditioner is solve identity: z/p in a persisted state
        # are M⁻¹-derived, so resuming a Jacobi-written state under MG
        # (or vice versa) would splice two different Krylov recurrences —
        # and so would resuming one MGConfig's state under another (the
        # cycle config IS the M⁻¹), so the config joins the tuple too.
        # Appended only for non-default preconditioners — historical
        # Jacobi fingerprints stay byte-identical and keep resuming.
        from poisson_tpu.mg import DEFAULT_MG

        return repr((sorted(fields.items()), dtype_name, scaled,
                     preconditioner, mg_config or DEFAULT_MG))
    return repr((sorted(fields.items()), dtype_name, scaled))


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4, 5, 6))
def _run_chunk(problem: Problem, scaled: bool, chunk: int,
               stagnation_window: int, stream_every: int,
               verify_every: int, verify_tol: float,
               a, b, aux, rhs, state: PCGState) -> PCGState:
    """Advance the solve by at most ``chunk`` iterations
    (device-resident). ``verify_every``/``verify_tol`` are the static
    integrity-probe knobs (``poisson_tpu.integrity``); ``rhs`` is the
    probe's true-residual reference — callers pass None when the probe
    is off, so flag-off programs keep their historical operand
    signature (and HLO) exactly."""
    ops = (
        scaled_single_device_ops(problem, a, b, aux)
        if scaled
        else single_device_ops(problem, a, b, aux)
    )
    body = make_pcg_body(
        ops, delta=problem.delta, weighted_norm=problem.weighted_norm,
        h1=problem.h1, h2=problem.h2,
        stagnation_window=stagnation_window, stream_every=stream_every,
        verify_every=verify_every, verify_tol=verify_tol,
        verify_rhs=rhs,
    )
    stop_at = jnp.minimum(state.k + chunk, problem.iteration_cap)

    def cond(s: PCGState):
        return (~s.done) & (s.k < stop_at)

    return lax.while_loop(cond, body, state)


def _chunk_ops_advance(problem: Problem, dtype_name: str, scaled: bool,
                       a, b, aux, rhs, chunk: int,
                       stagnation_window: int, stream_every: int,
                       verify_every: int, verify_tol: float,
                       preconditioner: str = "jacobi", mg_config=None,
                       geometry=None):
    """The (ops, advance, init) triple every chunked driver loops on:
    the historical Jacobi chunk program, or its MG twin with the level
    hierarchy bound in (``poisson_tpu.mg``). One seam so the
    checkpointed, deadline-chunked and resilient paths all route the
    preconditioner identically. ``init`` builds the fresh start state —
    JITTED on the MG path (the V-cycle that computes z₀ must run as a
    compiled program, or eager-vs-compiled rounding costs the
    chunked-equals-one-shot bit-parity contract; the Jacobi init is
    elementwise and keeps its historical eager form)."""
    if preconditioner not in (None, "jacobi"):
        from poisson_tpu.mg import (
            DEFAULT_MG,
            resolve_preconditioner,
            validate_mg_problem,
        )
        from poisson_tpu.mg.hierarchy import device_hierarchy
        from poisson_tpu.mg.preconditioner import _run_chunk_mg, mg_ops

        resolve_preconditioner(preconditioner)
        cfg = mg_config or DEFAULT_MG
        validate_mg_problem(problem, cfg)
        from poisson_tpu.mg.preconditioner import _member_init_mg

        hier = device_hierarchy(problem, dtype_name, scaled,
                                geometry=geometry, config=cfg)
        ops = mg_ops(problem, a, b, aux, hier, cfg, scaled)
        advance = lambda s: _run_chunk_mg(
            problem, scaled, chunk, cfg, stagnation_window,
            int(stream_every), verify_every, verify_tol, a, b, aux,
            rhs if verify_every else None, hier, s)
        init = lambda: _member_init_mg(problem, scaled, cfg, a, b, aux,
                                       hier, rhs)
        return ops, advance, init
    ops = (
        scaled_single_device_ops(problem, a, b, aux)
        if scaled
        else single_device_ops(problem, a, b, aux)
    )
    advance = lambda s: _run_chunk(
        problem, scaled, chunk, stagnation_window, int(stream_every),
        verify_every, verify_tol, a, b, aux,
        rhs if verify_every else None, s)
    return ops, advance, (lambda: init_state(ops, rhs))


def _state_flag(state) -> Optional[int]:
    """Termination verdict of any solver state, or None for state types
    (the fused pallas loops) that do not track one."""
    flag = getattr(state, "flag", None)
    return None if flag is None else int(flag)


def _converged(state) -> bool:
    """True only for a genuinely converged stop. Solvers with verdict
    tracking require FLAG_CONVERGED — a breakdown/divergence/stagnation
    stop also sets ``done`` but must keep its checkpoint for recovery;
    verdict-less states keep the historical done-means-converged reading."""
    if not bool(state.done):
        return False
    flag = _state_flag(state)
    return True if flag is None else flag == FLAG_CONVERGED


def run_chunked(state, *, advance, to_portable, path: Optional[str],
                fingerprint: str,
                cap: int, keep_checkpoint: bool, primary=None, sync=None,
                keep_last: int = 2, watchdog=None, on_chunk=None,
                deadline=None, history: bool = False):
    """The one chunked-checkpoint driver loop, shared by all four
    checkpointed solvers (single/sharded × XLA/fused): advance until done
    or cap, persist the portable full-grid state after every chunk, clean
    up a *converged* run's checkpoint (a cap-hit keeps it for resume).
    ``path=None`` runs the same loop persistence-free (the deadline-only
    chunked mode the solve service uses — see :func:`pcg_solve_chunked`).

    ``state`` must expose ``.done`` and ``.k``; ``advance(state)`` runs one
    chunk; ``to_portable(state)`` produces the PCGState ``save_state``
    writes. ``primary``/``sync`` gate the file write to one process and
    barrier-order it against other processes' later reads (multi-process
    meshes); they default to single-process no-ops.

    ``deadline`` (duck-typed: anything with ``expired() -> bool``, e.g.
    ``poisson_tpu.serve.Deadline``) makes the chunking deadline-aware: the
    loop refuses to START a chunk once the deadline has expired, so a
    deadlined solve returns its partial state within one chunk of the
    cutoff instead of hanging to convergence. The caller stamps the
    result flag (FLAG_DEADLINE); the persisted state never carries it, so
    a later run can resume with a fresh budget. The deadline is checked
    at chunk boundaries only — overshoot is bounded by one chunk, which
    is what sizes ``chunk`` for deadline-sensitive callers.

    Resilience hooks:

    - ``keep_last`` generations of the checkpoint are retained (see
      :func:`save_state`);
    - ``watchdog`` (``parallel.watchdog.Watchdog``) is armed for the whole
      loop and beaten at every chunk boundary — a chunk that wedges (the
      multihost collective hang this repo has lived through) trips its
      timeout instead of stalling silently forever;
    - ``on_chunk(state, chunks_done)`` runs after each chunk is persisted
      and may return a replacement state or raise (fault injection — see
      ``testing.faults``);
    - ``history`` feeds each chunk boundary's ``(k, ‖Δw‖)`` into the
      forecast residual-history buffer (``obs.forecast``) — host-side
      only, the traced program is untouched, so the chunked dispatch
      path reports convergence rate without recompilation;
    - a state that went non-finite is *not* persisted and the stop is not
      treated as convergence: the newest good generation survives for the
      recovery driver.
    """
    primary = primary if primary is not None else (lambda: True)
    sync = sync if sync is not None else (lambda name: None)
    if watchdog is not None:
        watchdog.start()
    chunks_done = 0
    try:
        while (not bool(state.done)) and int(state.k) < cap:
            if deadline is not None and deadline.expired():
                # Don't start a chunk the deadline has already disowned:
                # the last persisted generation is the partial answer.
                from poisson_tpu import obs

                obs.inc("checkpoint.deadline_stops")
                obs.event("checkpoint.deadline_stop", k=int(state.k),
                          chunks=chunks_done)
                break
            state = advance(state)
            jax.block_until_ready(state)
            chunks_done += 1
            if watchdog is not None:
                watchdog.beat(k=int(state.k), diff=float(state.diff))
            if history:
                from poisson_tpu.obs.forecast import history_tap

                history_tap(int(state.k), float(state.diff))
            flag = _state_flag(state)
            if flag in (FLAG_NONFINITE, FLAG_INTEGRITY):
                # Poisoned state: saving it would overwrite the last good
                # generation with NaNs — or, for an integrity verdict
                # (poisson_tpu.integrity), with silently corrupted
                # buffers the CRC would then happily seal. ``flag`` is
                # mesh-replicated, so every process skips in step.
                break
            if _converged(state) and not keep_checkpoint:
                # The chunk just converged and the file would be deleted
                # below: skip the full-grid gather (an all-gather collective
                # on multi-process meshes) and the disk write outright.
                break
            if path:
                portable = to_portable(state)  # collective if multi-process
                if primary():
                    save_state(path, portable, fingerprint,
                               keep_last=keep_last)
                sync("poisson_ckpt_save")  # write lands before any read
            if on_chunk is not None:
                state = _apply_hook(on_chunk, state, chunks_done)
    except KeyboardInterrupt:
        if watchdog is not None:
            watchdog.raise_if_fired()   # timeout → typed SolveTimeout
        raise
    finally:
        if watchdog is not None:
            watchdog.stop()
    if path and _converged(state) and not keep_checkpoint and primary():
        remove_generations(path, keep_last)
    sync("poisson_ckpt_done")           # removal precedes any follow-up solve
    return state


def _apply_hook(on_chunk, state, chunks_done):
    replacement = on_chunk(state, chunks_done)
    return state if replacement is None else replacement


def checkpoint_generations(path: str, keep_last: int = 2) -> list:
    """Candidate checkpoint paths, newest first: ``path``, ``path.1``, …"""
    keep_last = max(1, int(keep_last))
    return [path] + [f"{path}.{i}" for i in range(1, keep_last)]


def remove_generations(path: str, keep_last: int = 2) -> None:
    """Delete every retained checkpoint generation (the converged-solve
    cleanup, shared by all chunked drivers)."""
    for candidate in checkpoint_generations(path, keep_last):
        if os.path.exists(candidate):
            os.remove(candidate)


def _payload_crc(fingerprint: str, arrays: dict) -> int:
    crc = zlib.crc32(fingerprint.encode())
    for key in sorted(arrays):
        a = np.ascontiguousarray(arrays[key])
        crc = zlib.crc32(key.encode(), crc)
        crc = zlib.crc32(str(a.dtype).encode(), crc)
        crc = zlib.crc32(str(a.shape).encode(), crc)
        # The array itself is a C-contiguous buffer: same CRC as
        # tobytes(), without materializing a full byte-copy per array
        # per checkpoint write/load.
        crc = zlib.crc32(a, crc)
    return crc & 0xFFFFFFFF


def save_state(path: str, state: PCGState, fingerprint: str,
               keep_last: int = 2) -> None:
    """Atomically persist ``state``: write to a tmp file, seal it with a
    CRC32 over the full payload, rotate the previous generations
    (``path`` → ``path.1`` → …, keeping ``keep_last`` total), then
    ``os.replace`` into place. A kill at any point leaves either the old
    generation chain or the new one — never a partial file at ``path``."""
    from poisson_tpu import obs

    arrays = {key: np.asarray(val) for key, val in zip(_STATE_KEYS, state)}
    # np.savez appends '.npz' to names without it — keep the temp name
    # suffixed so the atomic-replace source path is what savez wrote.
    tmp = f"{path}.{os.getpid()}.tmp.npz"
    try:
        with obs.span("checkpoint.write", fence=False, path=path):
            np.savez(
                tmp,
                fingerprint=np.asarray(fingerprint),
                crc32=np.uint32(_payload_crc(fingerprint, arrays)),
                **arrays,
            )
            generations = checkpoint_generations(path, keep_last)
            for older, newer in zip(reversed(generations[1:]),
                                    reversed(generations[:-1])):
                if os.path.exists(newer):
                    os.replace(newer, older)
            os.replace(tmp, path)
        obs.inc("checkpoint.writes")
        obs.event("checkpoint.write", path=path, k=int(arrays["k"]))
    finally:
        if os.path.exists(tmp):   # savez died mid-write: no partials left
            os.remove(tmp)


def _read_state(path: str, fingerprint: str) -> PCGState:
    """Read and verify one checkpoint file. Raises CorruptCheckpointError
    for anything untrustworthy, ValueError for a fingerprint mismatch."""
    try:
        with np.load(path) as data:
            if "fingerprint" not in data:
                raise CorruptCheckpointError(
                    f"checkpoint {path} has no fingerprint record"
                )
            saved = str(data["fingerprint"])
            vals = {}
            for key in _STATE_KEYS:
                if key in data:
                    vals[key] = data[key]
                elif key in _OPTIONAL_DEFAULTS:
                    vals[key] = np.asarray(_OPTIONAL_DEFAULTS[key])
                else:
                    raise CorruptCheckpointError(
                        f"checkpoint {path} is missing state array {key!r}"
                    )
            stored_crc = int(data["crc32"]) if "crc32" in data else None
    except CorruptCheckpointError:
        raise
    except Exception as e:
        # Anything raised while parsing the file is corruption: np.load
        # surfaces truncated zips as ValueError/OSError, but a bit-flip in
        # an npy *header* escapes as SyntaxError/TokenError from numpy's
        # header parser — the failure set is open-ended by construction.
        # (The fingerprint-mismatch ValueError is raised after this block.)
        from poisson_tpu import obs

        obs.inc("checkpoint.corrupt")
        obs.event("checkpoint.corrupt", path=path, error=type(e).__name__)
        raise CorruptCheckpointError(
            f"checkpoint {path} is unreadable: {type(e).__name__}: {e}"
        ) from e
    if saved != fingerprint:
        raise ValueError(
            f"checkpoint {path} was written for a different problem "
            f"configuration:\n  saved:     {saved}\n  requested: "
            f"{fingerprint}"
        )
    if stored_crc is not None:
        actual = _payload_crc(saved, {k: np.asarray(v)
                                      for k, v in vals.items()})
        if actual != stored_crc:
            from poisson_tpu import obs

            obs.inc("checkpoint.crc_failures")
            obs.event("checkpoint.crc_failure", path=path,
                      stored=f"{stored_crc:#010x}",
                      payload=f"{actual:#010x}")
            raise CorruptCheckpointError(
                f"checkpoint {path} failed its integrity check "
                f"(stored CRC32 {stored_crc:#010x}, payload "
                f"{actual:#010x}) — the file was corrupted after writing"
            )
    # Normalize the scalar dtypes so a resumed while_loop carry is stable
    # regardless of which solver/precision wrote the file.
    state_dtype = vals["w"].dtype
    as_dev = lambda x: jnp.asarray(x)
    state = PCGState(**{key: as_dev(val) for key, val in vals.items()})
    return state._replace(
        k=jnp.asarray(vals["k"], jnp.int32),
        done=jnp.asarray(bool(vals["done"])),
        zr=jnp.asarray(vals["zr"], state_dtype),
        diff=jnp.asarray(vals["diff"], state_dtype),
        flag=jnp.asarray(vals["flag"], jnp.int32),
        best=jnp.asarray(vals["best"], state_dtype),
        stall=jnp.asarray(vals["stall"], jnp.int32),
    )


def load_state_any(path: str, fingerprints, keep_last: int = 2,
                   ) -> Optional[tuple[PCGState, int]]:
    """The one generation-walk loader: newest generation first, and
    within each generation the given ``fingerprints`` in preference
    order. Returns ``(state, index-of-matched-fingerprint)``, or None if
    no generation exists or every generation is corrupt (a corrupt-only
    chain warns and starts over rather than crashing the resume). A
    corrupt or mismatched newest generation falls back to ``path.1``,
    ``path.2``, …; a mismatch with no loadable older generation raises
    (the checkpoint belongs to a different problem — resuming would
    silently solve the wrong one). An unreadable/corrupt generation is
    skipped outright — no fingerprint could rescue it."""
    fingerprints = list(fingerprints)
    mismatch: Optional[ValueError] = None
    existed = 0
    for candidate in checkpoint_generations(path, keep_last):
        if not os.path.exists(candidate):
            continue
        existed += 1
        for index, fingerprint in enumerate(fingerprints):
            try:
                state = _read_state(candidate, fingerprint)
            except CorruptCheckpointError as e:
                warnings.warn(
                    f"{e} — falling back to the previous checkpoint "
                    f"generation", RuntimeWarning, stacklevel=3,
                )
                break   # unreadable regardless of fingerprint
            except ValueError as e:
                mismatch = mismatch or e
                continue
            if candidate != path:
                from poisson_tpu import obs

                obs.inc("checkpoint.generation_fallbacks")
                obs.event("checkpoint.generation_fallback", path=candidate)
                warnings.warn(
                    f"resuming from older checkpoint generation "
                    f"{candidate} (newest was corrupt or mismatched)",
                    RuntimeWarning, stacklevel=3,
                )
            return state, index
    if mismatch is not None:
        raise mismatch
    if existed:
        warnings.warn(
            f"all {existed} checkpoint generation(s) at {path} are "
            f"corrupt; starting the solve from iteration zero",
            RuntimeWarning, stacklevel=3,
        )
    return None


def load_state(path: str, fingerprint: str,
               keep_last: int = 2) -> Optional[PCGState]:
    """Returns the newest trustworthy saved state for ``fingerprint``, or
    None (see :func:`load_state_any` for the fallback semantics)."""
    found = load_state_any(path, [fingerprint], keep_last)
    return None if found is None else found[0]


def _deadline_flag(state, deadline):
    """The result flag for a chunked run: the state's own verdict, or the
    host-stamped FLAG_DEADLINE when the run was still healthy (verdict
    ``running``) and stopped only because its deadline expired. A solve
    that diverged (nonfinite/breakdown/stagnated) keeps that verdict even
    when the deadline has also lapsed — stamping over it would make the
    service hand a diverged iterate out as a usable partial result and
    skip the retry/escalation path. Never persisted — result-only
    provenance."""
    if (deadline is not None and deadline.expired()
            and _state_flag(state) in (None, FLAG_NONE)):
        return jnp.asarray(FLAG_DEADLINE, jnp.int32)
    return state.flag


def pcg_solve_checkpointed(problem: Problem, checkpoint_path: str,
                           chunk: int = 200, dtype=None, scaled=None,
                           keep_checkpoint: bool = False,
                           keep_last: int = 2,
                           stagnation_window: int = 0,
                           stream_every: int = 0,
                           watchdog=None,
                           on_chunk=None,
                           deadline=None,
                           verify_every: int = 0,
                           verify_tol=None,
                           preconditioner: str = "jacobi",
                           mg_config=None) -> PCGResult:
    """Solve with periodic state persistence and automatic resume.

    Every ``chunk`` iterations the CG state is written to
    ``checkpoint_path`` (atomic, CRC-sealed, ``keep_last`` generations —
    see :func:`save_state`); if a trustworthy checkpoint already exists
    (same problem fingerprint) the solve resumes from it instead of
    starting over, falling back to an older generation when the newest is
    corrupt. On convergence the checkpoint is removed unless
    ``keep_checkpoint``; a cap-hit or divergence stop (``PCGResult.flag``)
    keeps it. ``watchdog``/``on_chunk``/``deadline`` are the
    chunk-boundary resilience hooks documented on :func:`run_chunked`; a
    deadline expiry returns the partial iterate with
    ``flag == FLAG_DEADLINE`` (the checkpoint survives for a resume with
    a fresh budget). ``verify_every``/``verify_tol`` arm the in-loop
    integrity probe (``poisson_tpu.integrity``); a FLAG_INTEGRITY stop
    is never persisted — the last good generation survives for the
    verified-restart driver (``solvers.resilient``).
    ``preconditioner="mg"`` chunks the V-cycle-preconditioned solve
    (:mod:`poisson_tpu.mg`); its checkpoints carry the preconditioner
    in their fingerprint, so a Jacobi checkpoint never resumes under MG
    (two different Krylov recurrences) or vice versa.
    """
    if chunk < 1:
        raise ValueError(f"chunk must be >= 1, got {chunk}")
    dtype_name = resolve_dtype(dtype)
    use_scaled = resolve_scaled(scaled, dtype_name)
    a, b, rhs, aux = host_setup(problem, dtype_name, use_scaled)
    fp = _fingerprint(problem, dtype_name, use_scaled, preconditioner,
                      mg_config)
    if preconditioner not in (None, "jacobi"):
        # One driver call = one MG solve (the rollout-fraction counter,
        # obs.metrics "mg.solves", must cover every dispatch path).
        from poisson_tpu import obs

        obs.inc("mg.solves")

    verify_every = int(verify_every)
    v_tol = (resolve_verify_tol(verify_tol, dtype_name)
             if verify_every > 0 else 0.0)
    ops, advance, init = _chunk_ops_advance(
        problem, dtype_name, use_scaled, a, b, aux, rhs, chunk,
        stagnation_window, stream_every, verify_every, v_tol,
        preconditioner=preconditioner, mg_config=mg_config)
    state = load_state(checkpoint_path, fp, keep_last=keep_last)
    if state is None:
        state = init()

    state = run_chunked(
        state,
        advance=advance,
        to_portable=lambda s: s,
        path=checkpoint_path, fingerprint=fp, cap=problem.iteration_cap,
        keep_checkpoint=keep_checkpoint, keep_last=keep_last,
        watchdog=watchdog, on_chunk=on_chunk, deadline=deadline,
    )

    w = state.w * aux if use_scaled else state.w
    return PCGResult(
        w=w, iterations=state.k, diff=state.diff, residual_dot=state.zr,
        flag=_deadline_flag(state, deadline),
    )


def pcg_solve_chunked(problem: Problem, chunk: int = 100, dtype=None,
                      scaled=None, rhs_gate=None,
                      stagnation_window: int = 0, stream_every: int = 0,
                      watchdog=None, on_chunk=None,
                      deadline=None, geometry=None,
                      verify_every: int = 0, verify_tol=None,
                      preconditioner: str = "jacobi",
                      mg_config=None, history: bool = False) -> PCGResult:
    """Chunked single-device solve WITHOUT persistence: the same
    chunk-boundary loop as :func:`pcg_solve_checkpointed` (watchdog beats,
    fault hooks, deadline awareness) minus the disk. This is the dispatch
    primitive the solve service (``poisson_tpu.serve``) uses for
    deadline-carrying requests — a request must be interruptible at chunk
    boundaries, but a short-lived service request has no resume story, so
    writing checkpoints for it would just burn disk on the hot path.

    Converging runs produce the exact ``pcg_solve`` iterate sequence
    (chunking never changes the iterates, only where the host observes
    them). ``rhs_gate`` mirrors ``pcg_solve``'s RHS multiplier; so does
    ``geometry`` (a :mod:`poisson_tpu.geometry` spec swaps the canvases,
    the chunked program is unchanged — the service's deadline-carrying
    geometry requests dispatch through here). A deadline expiry returns
    the partial iterate with ``flag == FLAG_DEADLINE``.
    ``verify_every``/``verify_tol`` arm the in-loop integrity probe
    (``poisson_tpu.integrity``) — the solve service's defensive
    verification rides this path for chunked dispatches. ``history``
    taps each chunk boundary into the forecast residual-history buffer
    (see :func:`run_chunked`).
    """
    from poisson_tpu.solvers.pcg import solve_setup

    if chunk < 1:
        raise ValueError(f"chunk must be >= 1, got {chunk}")
    dtype_name = resolve_dtype(dtype)
    use_scaled = resolve_scaled(scaled, dtype_name)
    a, b, rhs, aux = solve_setup(problem, dtype_name, use_scaled,
                                 geometry=geometry)
    if rhs_gate is not None:
        rhs = rhs * jnp.asarray(rhs_gate, rhs.dtype)
    if preconditioner not in (None, "jacobi"):
        from poisson_tpu import obs

        obs.inc("mg.solves")   # one driver call = one MG solve
    verify_every = int(verify_every)
    v_tol = (resolve_verify_tol(verify_tol, dtype_name)
             if verify_every > 0 else 0.0)
    ops, advance, init = _chunk_ops_advance(
        problem, dtype_name, use_scaled, a, b, aux, rhs, chunk,
        stagnation_window, stream_every, verify_every, v_tol,
        preconditioner=preconditioner, mg_config=mg_config,
        geometry=geometry)
    state = run_chunked(
        init(),
        advance=advance,
        to_portable=lambda s: s,
        path=None, fingerprint="", cap=problem.iteration_cap,
        keep_checkpoint=False,
        watchdog=watchdog, on_chunk=on_chunk, deadline=deadline,
        history=history,
    )
    w = state.w * aux if use_scaled else state.w
    return PCGResult(
        w=w, iterations=state.k, diff=state.diff, residual_dot=state.zr,
        flag=_deadline_flag(state, deadline),
    )
