"""Diagonally-preconditioned conjugate gradients as a ``lax.while_loop``.

TPU-native re-design of the reference's host-driven iteration
(``stage0/Withoutopenmp1.cpp:106-172`` ``solve``;
``stage2-mpi/poisson_mpi_decomp.cpp:356-460`` ``solve_mpi``;
``stage4-mpi+cuda/poisson_mpi_cuda_f.cu:688-983`` ``gradient_solver_mpi``):
the whole solve — setup, iteration, convergence test — is one traced program.
Unlike stage4, which synchronises the host after every kernel and round-trips
partial sums over PCIe for each dot product (SURVEY §3.3), nothing here leaves
the device until the loop exits.

The reference implements this loop five separate times (serial, OpenMP, MPI,
hybrid, CUDA). Here the loop skeleton exists once, parameterised by a
:class:`PCGOps` bundle: the single-device bundle has a no-op halo exchange and
plain sums; the sharded bundle (``parallel.pcg_sharded``) plugs in ``ppermute``
halo exchange and ``psum`` reductions. Same controller, different backend —
the factoring the reference never did.

Iteration structure (exactly the reference's, ``stage2:…cpp:400-457``):
    w0 = 0;  r0 = B;  z0 = D⁻¹r0;  p0 = z0;  ζ0 = (z0,r0)
    repeat k = 1, 2, …:
        Ap   = A p                      (halo exchange first, when sharded)
        den  = (Ap, p);  stop if |den| < 1e-15 (degenerate, state kept)
        α    = ζ/den
        w   += αp;  r −= αAp;  diff = ‖αp‖  (weighted or not, Problem.weighted_norm)
        z    = D⁻¹r;  ζ' = (z, r)
        stop if diff < δ  (this iteration counts, updates kept)
        β    = ζ'/ζ;  p = z + βp
"""

from __future__ import annotations

import functools
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from poisson_tpu.config import Problem
from poisson_tpu.models.fictitious_domain import build_fields
from poisson_tpu.ops.stencil import (
    apply_A,
    apply_Dinv,
    diag_D,
    dot_weighted,
)

_DENOM_TOL = 1e-15  # degenerate-direction guard (stage2:…cpp:414)

# Termination verdicts recorded in PCGState.flag / PCGResult.flag. The
# reference's loop knows only "converged or budget" — at production scale a
# solve must also say *why* it stopped (NaN blow-up, Krylov breakdown,
# stagnation) so the recovery driver (solvers.resilient) can decide between
# restart, precision escalation, and failing loudly.
FLAG_NONE = 0        # still running, or a solver that does not track verdicts
FLAG_CONVERGED = 1   # ‖Δw‖ < δ
FLAG_BREAKDOWN = 2   # |（Ap, p)| below the degenerate-direction guard
FLAG_NONFINITE = 3   # NaN/Inf reached the residual or update norm
FLAG_STAGNATED = 4   # no best-‖Δw‖ improvement for a full stagnation window
# Host-stamped only, never set inside the fused loop: the chunked drivers
# (solvers.checkpoint / solvers.resilient) stamp it on the RESULT when a
# per-request deadline expired at a chunk boundary before convergence —
# the partial-result-with-flag contract of the solve service
# (poisson_tpu.serve). The persisted PCGState never carries it, so a
# deadline-stopped solve resumes cleanly with a larger budget.
FLAG_DEADLINE = 5    # deadline expired mid-solve; w is the partial iterate
# In-loop integrity verdict (poisson_tpu.integrity): the verification
# probe (verify_every > 0) found the recurrence residual drifting from
# the true residual, or a convergence event that jumped implausibly —
# the silent-data-corruption fingerprint (a flipped bit in w/r/p, or a
# corrupted stencil application). The iterate is SUSPECT, not NaN: the
# recovery driver restarts from the last *verified-good* snapshot
# instead of escalating precision, and the solve service types it as an
# ``integrity`` error class with suspect-cohort taint.
FLAG_INTEGRITY = 6   # verification probe detected silent corruption

FLAG_NAMES = {
    FLAG_NONE: "running",
    FLAG_CONVERGED: "converged",
    FLAG_BREAKDOWN: "breakdown",
    FLAG_NONFINITE: "nonfinite",
    FLAG_STAGNATED: "stagnated",
    FLAG_DEADLINE: "deadline",
    FLAG_INTEGRITY: "integrity",
}


class PCGOps(NamedTuple):
    """Backend bundle consumed by the shared PCG loop.

    apply_A:   p (halo-fresh) → Ap, zero outside owned interior
    apply_Dinv: r → D⁻¹r, zero outside owned interior
    dot:       (u, v) → *global* weighted inner product h1·h2·Σ u·v
    sqnorm:    u → *global* Σ_interior u², unweighted (the convergence sum;
               weighting applied by the loop per Problem.weighted_norm)
    exchange:  p → p with refreshed halos (identity on a single device)
    """

    apply_A: Callable
    apply_Dinv: Callable
    dot: Callable
    sqnorm: Callable
    exchange: Callable


class PCGState(NamedTuple):
    """Loop state. The trailing three fields default so solvers that carry
    their own state types (the fused pallas paths) can build the portable
    checkpoint state without tracking them."""

    k: jnp.ndarray        # iterations completed (reference's `iter`)
    done: jnp.ndarray     # converged, degenerate, or diverged
    w: jnp.ndarray
    r: jnp.ndarray
    z: jnp.ndarray
    p: jnp.ndarray
    zr: jnp.ndarray       # ζ = (z, r)
    diff: jnp.ndarray     # last ‖w(k+1)−w(k)‖
    flag: jnp.ndarray = np.int32(FLAG_NONE)   # termination verdict
    best: jnp.ndarray = np.inf                # best ‖Δw‖ seen so far
    stall: jnp.ndarray = np.int32(0)          # iterations since best improved


class PCGResult(NamedTuple):
    """Solve result. Scalar solves fill the historical scalar fields; the
    batched driver (``solvers.batched``) returns the SAME type with a
    leading batch axis on ``w``/``iterations``/``diff``/``residual_dot``/
    ``flag`` — ``iterations`` is then the per-member truth (a vector), and
    ``max_iterations`` carries the scalar the wall clock actually paid for
    (the fused loop runs until the slowest member stops)."""

    w: jnp.ndarray           # full (…, M+1, N+1) solution grid(s)
    iterations: jnp.ndarray  # per-solve count; vector on batched results
    diff: jnp.ndarray        # final update norm
    residual_dot: jnp.ndarray  # final ζ = (D⁻¹r, r)
    flag: jnp.ndarray = np.int32(FLAG_NONE)  # termination verdict (FLAG_*)
    # Recovery provenance, set by the resilient driver on host-side
    # results only (None/() are empty pytree nodes, so jitted solvers
    # returning the defaults stay valid jit outputs). A solve that
    # recovered and then converged is no longer silent about it.
    restarts: object = None            # int: recovery attempts taken
    recovery_history: tuple = ()       # ((iteration, verdict, action), …)
    # Batched solves only: scalar max over the member iteration vector
    # (None on scalar solves, an empty pytree node under jit).
    max_iterations: object = None
    # Batched solves only: per-member origin identities (a tuple aligned
    # with the leading batch axis, padding members already sliced off).
    # Defaults to (0, 1, …, B−1); the solve service passes request ids so
    # a member re-enqueued into a different bucket keeps its identity.
    # Host-side metadata (ints/strings, not traced arrays).
    origin: object = None
    # Block-mode solves only (poisson_tpu.krylov.block): scalar bool —
    # the B×B coefficient solves truncated a rank-deficient direction
    # at some iteration (graceful degradation, not a failure; the
    # service counts it as ``krylov.block.rank_deficient``). None (an
    # empty pytree node) on every other solver's results.
    deficient: object = None


def iterations_scalar(iterations) -> int:
    """Collapse an ``iterations`` field to one honest scalar: the value
    itself for scalar solves, the max over members for batched vectors —
    the iteration count the fused loop actually ran (and the wall clock
    paid for), which is what every report line historically meant."""
    arr = np.asarray(iterations)
    return int(arr.max()) if arr.ndim else int(arr)


def _select(pred, new, old):
    return jax.tree_util.tree_map(
        lambda n, o: lax.select(jnp.broadcast_to(pred, n.shape), n, o), new, old
    )


def init_state(ops: PCGOps, rhs) -> PCGState:
    """w=0, r=B, z=D⁻¹r, p=z, ζ=(z,r)  (stage2:…cpp:384-396)."""
    w = jnp.zeros_like(rhs)
    r = rhs
    z = ops.apply_Dinv(r)
    p = z
    zr = ops.dot(z, r)
    return PCGState(
        k=jnp.zeros((), jnp.int32),
        done=jnp.asarray(False),
        w=w, r=r, z=z, p=p, zr=zr,
        diff=jnp.asarray(jnp.inf, rhs.dtype),
        flag=jnp.asarray(FLAG_NONE, jnp.int32),
        best=jnp.asarray(jnp.inf, rhs.dtype),
        stall=jnp.zeros((), jnp.int32),
    )


def restart_state(ops: PCGOps, rhs, w) -> PCGState:
    """Fresh CG restart from an existing iterate: r = B − Aw, z = M⁻¹r,
    p = z. The recovery driver (``solvers.resilient``) uses this to resume
    from the last good iterate after a divergence — the Krylov history is
    discarded (it is what went bad), the accumulated solution is kept.

    Constructed directly rather than via ``init_state(ops, rhs)``: the
    init's own ``z = M⁻¹·rhs`` would be computed only to be thrown away
    by the restart's replacements — harmless when M⁻¹ is the elementwise
    Jacobi diagonal, a full wasted (and eagerly dispatched) V-cycle when
    it is the MG preconditioner (``poisson_tpu.mg``)."""
    r = rhs - ops.apply_A(ops.exchange(w))
    z = ops.apply_Dinv(r)
    zr = ops.dot(z, r)
    return PCGState(
        k=jnp.zeros((), jnp.int32),
        done=jnp.asarray(False),
        w=w, r=r, z=z, p=z, zr=zr,
        diff=jnp.asarray(jnp.inf, rhs.dtype),
        flag=jnp.asarray(FLAG_NONE, jnp.int32),
        best=jnp.asarray(jnp.inf, rhs.dtype),
        stall=jnp.zeros((), jnp.int32),
    )


def make_pcg_member_body(ops: PCGOps, *, delta: float, weighted_norm: bool,
                         h1: float, h2: float, stagnation_window: int = 0,
                         stream_every: int = 0, verify_every: int = 0,
                         verify_tol: float = 0.0,
                         verify_jump: Optional[float] = None,
                         verify_colsum=None,
                         preconditioner: str = "jacobi",
                         history_every: int = 0):
    """The PCG iteration as a ``body(state, rhs) -> state`` pair-form —
    the verification-capable core :func:`make_pcg_body` wraps. The
    second argument is ONLY read when ``verify_every > 0`` (the in-loop
    integrity probe needs the RHS to recompute the true residual); the
    batched/lane drivers vmap this form with ``in_axes=(0, 0)`` so each
    member's probe checks its OWN right-hand side and only the
    corrupted member trips FLAG_INTEGRITY.

    With ``verify_every == 0`` (the default) no probe is traced and the
    body is the exact historical iteration — byte-identical HLO, golden
    iteration counts bit-for-bit (pinned by tests/test_integrity.py).

    When verifying, every ``verify_every``-th iteration AND every
    convergence event runs the residual-drift invariant
    (``poisson_tpu.integrity.probe``): ``‖(b − Aw) − r‖`` beyond
    ``verify_tol`` relative to the residual/RHS scale stamps
    FLAG_INTEGRITY and stops the member. A convergence whose previous
    best ‖Δw‖ sat more than ``verify_jump`` (default
    ``integrity.DEFAULT_VERIFY_JUMP``) above this step's own ‖Δw‖ is
    classified corrupt too, as is a one-step ‖Δw‖ collapse beyond
    ``integrity.DEFAULT_VERIFY_COLLAPSE`` without converging — the two
    faces of a flipped search direction, which keeps the recurrence
    consistent and is invisible to the drift check. ``verify_colsum``
    (the precomputed ``A·𝟙``) additionally enables the checksum-row
    ABFT identity on the stencil application at each probe.
    """
    if verify_every > 0:
        from poisson_tpu.integrity.probe import (
            default_verify_collapse,
            default_verify_jump,
        )

        # The update-norm guard ratios are PRECONDITIONER-specific:
        # MG-preconditioned CG legitimately contracts ‖Δw‖ several-fold
        # per iteration, so the Jacobi-calibrated ratios would false-
        # alarm on clean MG solves (measured — see integrity.probe).
        if verify_jump is None:
            verify_jump = default_verify_jump(preconditioner)
        verify_collapse = default_verify_collapse(preconditioner)

    def body(s: PCGState, vrhs=None) -> PCGState:
        p = ops.exchange(s.p)
        Ap = ops.apply_A(p)
        denom = ops.dot(Ap, p)
        degenerate = jnp.abs(denom) < _DENOM_TOL
        alpha = s.zr / jnp.where(degenerate, 1.0, denom)

        dw = alpha * p
        w_new = s.w + dw
        r_new = s.r - alpha * Ap
        sq = ops.sqnorm(dw)
        diff = jnp.sqrt(sq * (h1 * h2)) if weighted_norm else jnp.sqrt(sq)

        z_new = ops.apply_Dinv(r_new)
        zr_new = ops.dot(z_new, r_new)
        converged = diff < delta

        if stream_every > 0:
            from poisson_tpu.obs.stream import emit_every

            emit_every(stream_every, s.k + 1, diff)

        if history_every > 0:
            from poisson_tpu.obs.forecast import emit_history

            emit_history(history_every, s.k + 1, diff)

        beta = zr_new / jnp.where(s.zr == 0.0, 1.0, s.zr)
        p_new = z_new + beta * p

        # In-loop health classification. NaN/Inf anywhere in the scalars
        # poisons every later iterate, so stopping is strictly better than
        # looping to the cap; a converged verdict requires finite scalars
        # (NaN < δ is False anyway, but be explicit about precedence).
        nonfinite = ~(jnp.isfinite(diff) & jnp.isfinite(zr_new))
        improved = diff < s.best
        best_new = jnp.minimum(s.best, diff)
        stall_new = jnp.where(improved, 0, s.stall + 1).astype(jnp.int32)
        if stagnation_window > 0:
            stagnated = (~converged) & (stall_new >= stagnation_window)
        else:
            stagnated = jnp.asarray(False)
        if verify_every > 0:
            # The integrity probe: due every verify_every iterations and
            # on every convergence event (a corrupted solve must never
            # hand out a "converged" iterate unverified). lax.cond keeps
            # the extra stencil application off the non-probe
            # iterations; the probe only READS — clean solves keep
            # their golden iteration counts (iterates agree with the
            # unverified program to round-off: the probe's presence can
            # shift XLA's fusion choices by an ULP).
            from poisson_tpu.integrity.probe import (
                abft_drift_exceeds,
                drift_exceeds,
            )

            due = (((s.k + 1) % verify_every) == 0) | converged

            def _probe():
                bad = drift_exceeds(ops, w_new, r_new, vrhs, verify_tol)
                if verify_colsum is not None:
                    bad = bad | abft_drift_exceeds(verify_colsum, p, Ap,
                                                   verify_tol)
                return bad

            corrupt = lax.cond(due, _probe,
                               lambda: jnp.zeros_like(converged))
            # The false-convergence jump guard: genuine update-norm
            # convergence is gradual (the best ‖Δw‖ approaches δ before
            # crossing it, so the final step's ratio is single digits);
            # a convergence whose previous best sat ``verify_jump``
            # times above THIS step's ‖Δw‖ is a collapsed α from a
            # corrupted search direction. Ratio against diff, not δ: a
            # flip late in the solve collapses from wherever best was,
            # which an absolute δ-multiple would miss. isfinite(best)
            # exempts a legitimate first-iteration convergence (best
            # still ∞).
            suspicious = (converged & jnp.isfinite(s.best)
                          & (s.best > verify_jump * diff))
            # The mid-solve collapse guard: the SAME flipped-direction
            # physics when the collapsed ‖Δw‖ lands ABOVE δ — no
            # convergence event, so the jump guard never looks, and the
            # recurrence stays consistent, so the drift probe is blind
            # in principle. A one-step drop beyond verify_collapse
            # (clean CG measures ≤ 1.4×; the flip's gain factor is
            # ×2¹⁶ and up) is corruption. isfinite(s.diff) exempts the
            # first iteration after init/restart (diff starts at ∞).
            collapsed = ((~converged) & jnp.isfinite(s.diff)
                         & (s.diff > verify_collapse * diff))
            corrupt = (corrupt | suspicious | collapsed) & ~nonfinite
            # A corrupt verdict freezes the member; keep the PRE-flip
            # best so the recovery driver's recheck can reproduce the
            # jump condition (the collapsed diff would otherwise have
            # just overwritten its own evidence) and so a false-alarm
            # resume keeps the honest progress floor.
            best_new = jnp.where(corrupt, s.best, best_new)
            flag = jnp.where(
                nonfinite, FLAG_NONFINITE,
                jnp.where(corrupt, FLAG_INTEGRITY,
                          jnp.where(converged, FLAG_CONVERGED,
                                    jnp.where(stagnated, FLAG_STAGNATED,
                                              FLAG_NONE))),
            ).astype(jnp.int32)
            stop = (degenerate | converged | nonfinite | stagnated
                    | corrupt)
        else:
            flag = jnp.where(
                nonfinite, FLAG_NONFINITE,
                jnp.where(converged, FLAG_CONVERGED,
                          jnp.where(stagnated, FLAG_STAGNATED, FLAG_NONE)),
            ).astype(jnp.int32)
            stop = degenerate | converged | nonfinite | stagnated

        # Degenerate break happens before any update (stage2:…cpp:410-415):
        # keep the old state entirely. Convergence break keeps this
        # iteration's w/r/z updates (p is then irrelevant).
        candidate = PCGState(
            k=s.k + 1,
            done=stop,
            w=w_new, r=r_new, z=z_new, p=p_new,
            zr=zr_new, diff=diff,
            flag=flag, best=best_new, stall=stall_new,
        )
        kept = s._replace(
            k=s.k + 1, done=jnp.asarray(True),
            flag=jnp.asarray(FLAG_BREAKDOWN, jnp.int32),
        )
        return _select(degenerate, kept, candidate)

    return body


def make_pcg_body(ops: PCGOps, *, delta: float, weighted_norm: bool,
                  h1: float, h2: float, stagnation_window: int = 0,
                  stream_every: int = 0, verify_every: int = 0,
                  verify_tol: float = 0.0,
                  verify_jump: Optional[float] = None,
                  verify_rhs=None, verify_colsum=None,
                  preconditioner: str = "jacobi",
                  history_every: int = 0):
    """One PCG iteration as a pure state→state function — shared by the
    convergence ``while_loop`` (:func:`pcg_loop`) and the fixed-budget
    diagnostic ``scan`` (``solvers.history``).

    Every iteration classifies its own outcome into ``flag`` so a failing
    solve stops at the iteration that went bad instead of burning the rest
    of its budget on NaNs: a non-finite residual/update norm sets
    FLAG_NONFINITE, the degenerate-direction break FLAG_BREAKDOWN, and —
    when ``stagnation_window`` > 0 — ``stagnation_window`` consecutive
    iterations without a new best ‖Δw‖ set FLAG_STAGNATED. The checks only
    ever stop iterations that could no longer converge, so converging
    solves keep their golden iteration counts bit-for-bit.

    ``stream_every`` > 0 additionally ships (k, ‖Δw‖) to the host-side
    telemetry sink every that many iterations (``obs.stream``) via an
    unordered ``jax.debug.callback`` — progress visibility out of the
    fused loop. It is a trace-time constant: at the default 0 no
    callback exists in the program and the iterations are untouched.

    ``verify_every`` > 0 threads the in-loop integrity probe
    (``poisson_tpu.integrity``) into the body against ``verify_rhs``
    (the RHS this state's true residual is checked against — required
    when verifying); a detected drift stamps FLAG_INTEGRITY. Like
    ``stream_every`` it is a trace-time constant: at the default 0 the
    body is the exact historical program, byte-identical HLO. See
    :func:`make_pcg_member_body` for the semantics (and for the
    ``body(state, rhs)`` pair form the batched drivers vmap).

    ``history_every`` > 0 ships (k, ‖Δw‖) to the forecast history sink
    (``obs.forecast``) every that many iterations — the mid-flight
    convergence-rate seam. Identical trace-time-constant contract:
    at the default 0 no callback is traced and the program is
    byte-identical."""
    if verify_every > 0 and verify_rhs is None:
        raise ValueError(
            "verify_every > 0 needs verify_rhs — the in-loop integrity "
            "probe recomputes the true residual b - Aw against it"
        )
    member = make_pcg_member_body(
        ops, delta=delta, weighted_norm=weighted_norm, h1=h1, h2=h2,
        stagnation_window=stagnation_window, stream_every=stream_every,
        verify_every=verify_every, verify_tol=verify_tol,
        verify_jump=verify_jump, verify_colsum=verify_colsum,
        preconditioner=preconditioner, history_every=history_every,
    )
    if verify_every == 0:
        return member     # vrhs defaults to None and is never read
    return lambda s: member(s, verify_rhs)


def pcg_loop(ops: PCGOps, rhs, *, delta: float, max_iter: int,
             weighted_norm: bool, h1: float, h2: float,
             stagnation_window: int = 0, stream_every: int = 0,
             verify_every: int = 0, verify_tol: float = 0.0,
             verify_abft: bool = False,
             preconditioner: str = "jacobi",
             history_every: int = 0) -> PCGState:
    """Run the PCG while_loop to convergence; backend-agnostic.
    ``verify_every``/``verify_tol`` arm the in-loop integrity probe
    against this solve's own RHS; ``verify_abft`` additionally traces
    the checksum-row ABFT identity (the column-sum vector is computed
    once here, outside the loop)."""
    colsum = None
    if verify_every > 0 and verify_abft:
        from poisson_tpu.integrity.probe import abft_colsum

        colsum = abft_colsum(ops, rhs)
    body = make_pcg_body(
        ops, delta=delta, weighted_norm=weighted_norm, h1=h1, h2=h2,
        stagnation_window=stagnation_window, stream_every=stream_every,
        verify_every=verify_every, verify_tol=verify_tol,
        verify_rhs=(rhs if verify_every > 0 else None),
        verify_colsum=colsum, preconditioner=preconditioner,
        history_every=history_every,
    )

    def cond(s: PCGState):
        return (~s.done) & (s.k < max_iter)

    return lax.while_loop(cond, body, init_state(ops, rhs))


def single_device_ops(problem: Problem, a, b, aux) -> PCGOps:
    """Stage0/stage1-equivalent backend: whole grid on one device.

    ``aux`` is the Jacobi diagonal embedded in the full grid's zero ring —
    the same full-grid layout ``scaled_single_device_ops`` takes, so both
    backends consume :func:`host_setup`'s aux unchanged.

    Every op accepts leading batch axes (the ``ops.stencil`` convention):
    reductions sum only the trailing grid axes, so a (B, M+1, N+1) state
    stack gets per-member dots/norms — usable either directly or under
    ``vmap`` (the batched driver, ``solvers.batched``). a/b/aux may
    themselves carry leading batch axes (per-member geometry canvases,
    ``poisson_tpu.geometry``)."""
    h1, h2 = problem.h1, problem.h2
    # ndim dispatch like ops.stencil._cslice: 2D aux keeps the literal
    # historical slice (unbatched jaxpr unchanged); stacked aux
    # (per-member geometry diagonals) slices under an Ellipsis.
    d = aux[1:-1, 1:-1] if aux.ndim == 2 else aux[..., 1:-1, 1:-1]
    return PCGOps(
        apply_A=lambda p: apply_A(p, a, b, h1, h2),
        apply_Dinv=lambda r: apply_Dinv(r, d),
        dot=lambda u, v: dot_weighted(u, v, h1, h2),
        sqnorm=lambda u: jnp.sum(
            u[..., 1:-1, 1:-1] * u[..., 1:-1, 1:-1], axis=(-2, -1)
        ),
        exchange=lambda p: p,
    )


def scaled_single_device_ops(problem: Problem, a, b, sc) -> PCGOps:
    """Symmetrically-scaled backend: plain CG on Ã = D^{-1/2} A D^{-1/2}.

    Mathematically identical to Jacobi-PCG on A (same iterates under the
    substitution y = D^{1/2}w, z = D⁻¹r ↔ r̃, (z,r) = (r̃,r̃)), but the scaled
    operator has unit diagonal and O(1) entries, collapsing the ~1/ε·h⁻²
    dynamic range of the fictitious-domain matrix. This is what makes fp32
    viable on TPU: unscaled fp32 diverges at 800×1200 (κ ~ 1e11), scaled
    fp32 reproduces the fp64 golden iteration counts exactly.

    ``sc`` is D^{-1/2} on the full grid (zero ring). The preconditioner
    becomes the identity; the convergence norm is mapped back to w-space via
    ‖Δw‖ = ‖sc·Δy‖; the caller maps the solution back with w = sc·y.
    Batch-polymorphic like :func:`single_device_ops` (sc broadcasts over
    leading axes; reductions are per-member).
    """
    h1, h2 = problem.h1, problem.h2
    return PCGOps(
        apply_A=lambda p: apply_A(p * sc, a, b, h1, h2) * sc,
        apply_Dinv=lambda r: r,
        dot=lambda u, v: dot_weighted(u, v, h1, h2),
        sqnorm=lambda u: jnp.sum((u * sc)[..., 1:-1, 1:-1] ** 2,
                                 axis=(-2, -1)),
        exchange=lambda p: p,
    )


@functools.lru_cache(maxsize=8)
def host_fields64(problem: Problem, scaled: bool):
    """Build the problem fields on the host in fp64 (numpy) — the single
    source of the precision policy's setup derivation, shared by the
    single-device and sharded solvers.

    The reference also runs setup on the CPU (even in the CUDA stage,
    ``stage4:…cu:717``). Doing it in numpy fp64 keeps setup precision
    independent of the device's x64 support: on TPU the solver state may be
    fp32 while coefficients, the Jacobi diagonal, and the scaling vector are
    derived in fp64 and cast once.

    Returns (a, b, rhs_use, aux) as fp64 numpy arrays on the full (M+1,N+1)
    grid; ``aux`` is the zero-ring embedding of D (unscaled) or of
    D^{-1/2} (scaled), and ``rhs_use`` is B or the scaled b̃ = D^{-1/2}B.
    """
    import numpy as np

    a64, b64, rhs64 = build_fields(problem, dtype=np.float64, xp=np)
    d64 = diag_D(a64, b64, problem.h1, problem.h2)
    if not scaled:
        return a64, b64, rhs64, np.pad(d64, 1)
    inv_sqrt_d = 1.0 / np.sqrt(d64)
    return a64, b64, np.pad(rhs64[1:-1, 1:-1] * inv_sqrt_d, 1), np.pad(
        inv_sqrt_d, 1
    )


@functools.lru_cache(maxsize=8)
def host_setup(problem: Problem, dtype_name: str, scaled: bool):
    """Device-resident fields cast from :func:`host_fields64`. Cached so
    repeated solves of the same problem (e.g. a benchmark's timed loop) pay
    for setup and transfer once."""
    dtype = jnp.dtype(dtype_name)
    a64, b64, rhs64, aux64 = host_fields64(problem, scaled)
    return (
        jnp.asarray(a64, dtype),
        jnp.asarray(b64, dtype),
        jnp.asarray(rhs64, dtype),
        jnp.asarray(aux64, dtype),
    )


def solve_setup(problem: Problem, dtype_name: str, scaled: bool,
                geometry=None):
    """The one setup seam every solver entry point routes through:
    ``geometry=None`` is :func:`host_setup` (the reference ellipse,
    byte-identical arrays to every prior release); a geometry spec swaps
    in the fingerprint-cached canvases of ``geometry.canvas`` — same
    shapes, same dtype, same (a, b, rhs, aux) contract, so the jitted
    solve programs are shared across domains (the canvases are operands,
    never part of the jit key)."""
    if geometry is None:
        return host_setup(problem, dtype_name, scaled)
    from poisson_tpu.geometry.canvas import geometry_setup

    return geometry_setup(problem, geometry, dtype_name, scaled)


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4, 5, 6))
def _solve(problem: Problem, scaled: bool, stream_every: int,
           verify_every: int, verify_tol: float, verify_abft: bool,
           history_every: int,
           a, b, rhs, aux) -> PCGResult:
    """jitted solve; ``aux`` is the zero-ring-embedded D (unscaled) or
    D^{-1/2} (scaled) on the full grid. ``stream_every`` is the static
    telemetry stride (0 = no callback traced in — see ``obs.stream``);
    ``verify_every``/``verify_tol``/``verify_abft`` are the static
    integrity-probe knobs (0 = no probe traced in — see
    ``poisson_tpu.integrity``); ``history_every`` is the static
    forecast-history stride (0 = no callback traced in — see
    ``obs.forecast``). All strides are part of the compile cache key,
    so flag-off programs are the exact historical executables."""
    ops = (
        scaled_single_device_ops(problem, a, b, aux)
        if scaled
        else single_device_ops(problem, a, b, aux)
    )
    s = pcg_loop(
        ops, rhs,
        delta=problem.delta, max_iter=problem.iteration_cap,
        weighted_norm=problem.weighted_norm,
        h1=problem.h1, h2=problem.h2,
        stream_every=stream_every,
        verify_every=verify_every, verify_tol=verify_tol,
        verify_abft=verify_abft,
        history_every=history_every,
    )
    w = s.w * aux if scaled else s.w
    return PCGResult(w=w, iterations=s.k, diff=s.diff, residual_dot=s.zr,
                     flag=s.flag)


def resolve_dtype(dtype) -> str:
    """Resolve the requested precision, refusing a silent fp64→fp32 downcast.

    JAX downcasts float64 arrays to float32 unless ``jax_enable_x64`` is on;
    an explicit fp64 request would then quietly run fp32 against δ=1e-6 and
    miss the golden iteration counts. ``None`` picks the best available.
    """
    if dtype is None:
        return "float64" if jax.config.jax_enable_x64 else "float32"
    name = jnp.dtype(dtype).name
    if name == "float64" and not jax.config.jax_enable_x64:
        raise ValueError(
            "float64 requested but jax_enable_x64 is off — the solve would "
            "silently run in float32. Call "
            "jax.config.update('jax_enable_x64', True) first, or pass an "
            "explicit 32-bit dtype."
        )
    return name


def resolve_scaled(scaled, dtype_name: str) -> bool:
    """Default precision policy: sub-64-bit state uses the symmetrically
    scaled system (required for correctness at fine grids); fp64 runs the
    reference's literal Jacobi-PCG for oracle parity."""
    if scaled is None:
        return dtype_name != "float64"
    return bool(scaled)


def resolve_verify_tol(verify_tol, dtype_name: str) -> float:
    """The integrity probe's relative drift tolerance: the caller's
    explicit value, else the dtype-aware default
    (``integrity.probe.default_verify_tol`` — sized for zero false
    alarms on clean golden solves while exponent-class corruption lands
    orders of magnitude above the line)."""
    if verify_tol is not None:
        return float(verify_tol)
    from poisson_tpu.integrity.probe import default_verify_tol

    return default_verify_tol(dtype_name)


def pcg_solve(problem: Problem, dtype=None, scaled=None,
              rhs_gate=None, stream_every: int = 0,
              geometry=None, verify_every: int = 0,
              verify_tol=None, verify_abft: bool = False,
              preconditioner: str = "jacobi",
              mg_config=None, history_every: int = 0) -> PCGResult:
    """Single-device solve (the stage0/stage1 workload, SURVEY §3.1).

    The iteration is jit-compiled end to end; setup runs on the host in fp64
    (see :func:`host_setup`). ``dtype`` selects the state precision (fp64 for
    oracle parity on CPU, fp32 for TPU throughput; default: fp64 when x64 is
    enabled, else fp32). ``scaled`` selects symmetric diagonal scaling
    (default: on for sub-64-bit dtypes — see :func:`scaled_single_device_ops`).
    ``rhs_gate``, if given, is a traced scalar the RHS is multiplied by —
    pass exactly 1.0 to chain benchmark solves with a data dependency
    (serialized, bit-identical result). ``stream_every`` > 0 streams
    (k, ‖Δw‖) to the telemetry sink every that many iterations
    (``obs.stream``; 0 = off, the program is byte-identical).
    ``geometry`` swaps the reference ellipse for any
    :mod:`poisson_tpu.geometry` spec (same grid, same compiled program —
    only the coefficient canvases change; fingerprint-cached, see
    ``geom.cache.*``). Omitted, the solve is byte-identical to every
    prior release.

    ``verify_every`` > 0 arms the in-loop integrity probe
    (``poisson_tpu.integrity``): every that many iterations (and on
    every convergence event) the loop recomputes the true residual and
    stops the solve with ``flag == FLAG_INTEGRITY`` when it drifts from
    the recurrence beyond ``verify_tol`` (default: dtype-aware) —
    silent-data-corruption detection for one extra stencil application
    per check. ``verify_abft`` adds the checksum-row ABFT identity on
    the stencil application. At 0 (the default) no probe is traced:
    byte-identical program, bit-for-bit golden counts.

    ``preconditioner`` selects the M⁻¹ the CG recurrence runs with:
    ``"jacobi"`` (the default) is the historical diagonal path —
    byte-identical executables, golden counts bit-for-bit;
    ``"mg"`` swaps in one geometric V-cycle per iteration
    (:mod:`poisson_tpu.mg` — near-flat iteration counts in resolution;
    the grid must coarsen, see ``mg.validate_mg_problem``).
    ``mg_config`` tunes the cycle (``mg.MGConfig``; None = defaults).

    ``history_every`` > 0 ships (k, ‖Δw‖) to the forecast history sink
    (``obs.forecast``) every that many iterations — the mid-flight
    convergence-rate seam the ETA estimator reads. Same trace-time
    contract as ``stream_every``: 0 (the default) traces no callback
    and the program is byte-identical.
    """
    dtype_name = resolve_dtype(dtype)
    use_scaled = resolve_scaled(scaled, dtype_name)
    verify_every = int(verify_every)
    history_every = int(history_every)
    tol = (resolve_verify_tol(verify_tol, dtype_name)
           if verify_every > 0 else 0.0)
    if preconditioner not in (None, "jacobi"):
        from poisson_tpu import obs
        from poisson_tpu.mg import (
            DEFAULT_MG,
            resolve_preconditioner,
            validate_mg_problem,
        )
        from poisson_tpu.mg.preconditioner import _solve_mg, mg_solve_setup

        resolve_preconditioner(preconditioner)   # raises on unknown
        cfg = mg_config or DEFAULT_MG
        validate_mg_problem(problem, cfg)
        if verify_abft:
            raise ValueError(
                "verify_abft is wired for the jacobi path only; drop it "
                "or use preconditioner='jacobi'"
            )
        if history_every > 0:
            raise ValueError(
                "history_every is wired for the jacobi path only; drop "
                "it or use preconditioner='jacobi'"
            )
        a, b, rhs, aux, hier = mg_solve_setup(
            problem, dtype_name, use_scaled, geometry=geometry,
            config=cfg)
        if rhs_gate is not None:
            rhs = rhs * jnp.asarray(rhs_gate, rhs.dtype)
        obs.inc("mg.solves")
        return _solve_mg(problem, use_scaled, cfg, int(stream_every),
                         verify_every, tol, a, b, rhs, aux, hier)
    a, b, rhs, aux = solve_setup(problem, dtype_name, use_scaled,
                                 geometry=geometry)
    if rhs_gate is not None:
        rhs = rhs * jnp.asarray(rhs_gate, rhs.dtype)
    return _solve(problem, use_scaled, int(stream_every), verify_every,
                  tol, bool(verify_abft and verify_every > 0),
                  history_every, a, b, rhs, aux)


def iteration_program(problem: Problem, dtype=None, scaled=None,
                      preconditioner: str = "jacobi"):
    """The one-iteration PCG body as a (jittable fn, example state) pair
    — the per-iteration cost-attribution anchor (``obs.costs``).

    XLA's HLO cost analysis counts a ``while_loop`` body once regardless
    of trip count, so per-iteration FLOPs/bytes can only be read off a
    compiled executable by compiling the body alone; this packages
    exactly the body :func:`pcg_loop` runs (same ops bundle, same
    coefficient closure, so the compiled program's operand traffic is
    the solve's per-iteration truth). Precision/scaling policy matches
    :func:`pcg_solve`.
    """
    dtype_name = resolve_dtype(dtype)
    use_scaled = resolve_scaled(scaled, dtype_name)
    a, b, rhs, aux = host_setup(problem, dtype_name, use_scaled)
    if preconditioner not in (None, "jacobi"):
        # The MG iteration body: the same loop body with one V-cycle in
        # apply_Dinv — the per-iteration cost anchor the bytes/iter
        # model for MG cohorts (obs.costs.mg_vcycle_cost) is checked
        # against.
        from poisson_tpu.mg import (
            DEFAULT_MG,
            device_hierarchy,
            resolve_preconditioner,
        )
        from poisson_tpu.mg.preconditioner import mg_ops

        resolve_preconditioner(preconditioner)
        hier = device_hierarchy(problem, dtype_name, use_scaled)
        ops = mg_ops(problem, a, b, aux, hier, DEFAULT_MG, use_scaled)
    else:
        ops = (
            scaled_single_device_ops(problem, a, b, aux)
            if use_scaled
            else single_device_ops(problem, a, b, aux)
        )
    body = make_pcg_body(
        ops, delta=problem.delta, weighted_norm=problem.weighted_norm,
        h1=problem.h1, h2=problem.h2,
    )
    return body, init_state(ops, rhs)


def pcg_step_fn(problem: Problem, scaled: bool = True):
    """One fused PCG iteration for the flagship single-device problem —
    the jittable 'forward step' exposed to the harness (__graft_entry__).
    ``aux`` is D (unscaled) or D^{-1/2} on the grid (scaled), matching
    :func:`host_setup`. Assumes a non-degenerate search direction (driven
    pre-convergence; the full loop adds the |denom| guard)."""

    def step(w, r, z, p, zr, a, b, aux):
        ops = (
            scaled_single_device_ops(problem, a, b, aux)
            if scaled
            else single_device_ops(problem, a, b, aux)
        )
        Ap = ops.apply_A(p)
        denom = ops.dot(Ap, p)
        alpha = zr / denom
        w = w + alpha * p
        r = r - alpha * Ap
        z = ops.apply_Dinv(r)
        zr_new = ops.dot(z, r)
        beta = zr_new / zr
        p = z + beta * p
        return w, r, z, p, zr_new

    return step
