"""Geometry as a request: shape DSL + canvas compiler + fingerprint cache.

Public surface:

- the spec algebra (:mod:`geometry.dsl`): :class:`Ellipse`,
  :class:`Rectangle`, :class:`Polygon`, :class:`Union`,
  :class:`Intersection`, :class:`Difference`, :class:`SDF`,
  :data:`DEFAULT_ELLIPSE`, :func:`parse_geometry`,
  :func:`fingerprint_of`;
- the canvas compiler and cache (:mod:`geometry.canvas`):
  :func:`geometry_setup` (device canvases, ``geom.cache.{hits,misses}``
  keyed by fingerprint), :func:`build_geometry_fields` (host fp64),
  :func:`render_ascii`, :func:`reset_geometry_cache`;
- the accuracy gate (:mod:`geometry.manufactured`): one
  manufactured-solution oracle per family, the same L2-at-the-floor
  rule BENCH.md applies to the ellipse.

See README "Geometry requests" for the JSON grammar and the
co-batching semantics (different geometries on the same grid share one
bucket executable — only the canvases differ per member).
"""

from poisson_tpu.geometry.canvas import (
    build_geometry_fields,
    cut_face_mask,
    geometry_face_lengths,
    geometry_setup,
    render_ascii,
    reset_geometry_cache,
)
from poisson_tpu.geometry.dsl import (
    DEFAULT_ELLIPSE,
    Difference,
    Ellipse,
    GeometrySpec,
    Intersection,
    Polygon,
    Rectangle,
    SDF,
    Union,
    fingerprint_of,
    parse_geometry,
)

__all__ = [
    "GeometrySpec", "Ellipse", "Rectangle", "Polygon", "Union",
    "Intersection", "Difference", "SDF", "DEFAULT_ELLIPSE",
    "parse_geometry", "fingerprint_of", "geometry_setup",
    "build_geometry_fields", "cut_face_mask", "geometry_face_lengths",
    "render_ascii", "reset_geometry_cache",
]
