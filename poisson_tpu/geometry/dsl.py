"""Geometry DSL: the domain as a request parameter.

Every layer above the operator historically assumed the reference's one
ellipse ``x² + 4y² < 1`` — but the fictitious-domain method never needed
it: the domain only ever enters as the blend-coefficient canvases ``a``,
``b`` and the RHS indicator (``models.fictitious_domain``). This module
makes the domain a *value*: a small spec algebra

    Ellipse(cx, cy, rx, ry)        — general axis-aligned ellipse
    Rectangle(x0, y0, x1, y1)      — the axis-aligned polygon special case
    Polygon(vertices)              — general simple polygon
    Union(shapes) / Intersection(shapes) / Difference(shape, hole)
    SDF(fn, name=…)                — raw signed-distance(-like) callable

each of which exposes

    contains(x, y, xp) — exact membership (open set; drives the RHS
                         indicator and the inside-the-domain error mask)
    sdf(x, y, xp)      — a continuous level-set function, negative inside,
                         zero on the boundary (drives the adaptive face
                         sampling in ``geometry.canvas`` — it need not be
                         a true distance, only continuous with the right
                         zero set)
    normalize()        — the canonical form of the spec (flattened and
                         fingerprint-sorted boolean children, canonical
                         polygon start/orientation, ordered rectangle
                         corners), so equivalent specs are *equal*
    fingerprint        — a stable hash of the normalized spec: the key of
                         the canvas cache (``geometry.canvas``), the
                         co-batching taint key of the solve service
                         (``serve.service``), and the flight-trace
                         attribute that makes mixed-geometry buckets
                         attributable per member

The JSON grammar round-trips through :func:`parse_geometry` /
``GeometrySpec.to_json`` (see README "Geometry requests"); ``SDF`` specs
serialize their declared ``name`` but cannot be parsed back (a callable
does not survive JSON — requests carrying raw SDFs are in-process only).

``DEFAULT_ELLIPSE`` is exactly the reference's domain; the canvas
compiler reproduces ``models.fictitious_domain.build_fields`` for it
bit-for-bit (asserted in tests), so "no geometry" and "the default
ellipse spec" are the same solve to the last ULP.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Callable, Optional, Tuple

__all__ = [
    "GeometrySpec", "Ellipse", "Rectangle", "Polygon", "Union",
    "Intersection", "Difference", "SDF", "DEFAULT_ELLIPSE",
    "parse_geometry", "fingerprint_of",
]


def _np():
    import numpy as np

    return np


def _canon_float(v) -> float:
    """Canonical float for fingerprints: plain ``float()`` so ints,
    numpy scalars, and floats that compare equal hash equal."""
    return float(v)


class GeometrySpec:
    """Base of the spec algebra. Subclasses are frozen dataclasses —
    hashable values, safe as dict keys and dataclass request fields."""

    # -- geometry protocol (subclasses override) -----------------------

    def contains(self, x, y, xp=None):
        """Exact open-set membership, elementwise over broadcast x, y."""
        xp = xp or _np()
        return self.sdf(x, y, xp) < 0.0

    def sdf(self, x, y, xp=None):
        raise NotImplementedError

    def normalize(self) -> "GeometrySpec":
        return self

    def to_obj(self) -> dict:
        raise NotImplementedError

    # -- derived -------------------------------------------------------

    def to_json(self) -> str:
        return json.dumps(self.normalize().to_obj(), sort_keys=True)

    @property
    def fingerprint(self) -> str:
        """Stable hash of the normalized spec — the canvas-cache key,
        keyed the way the jit cache keys shapes: equivalent specs
        (permuted unions, rotated polygon vertex lists) share it.
        Memoized on the instance: the serve layer reads it per refill
        decision (taint checks, flight attrs), and specs are frozen."""
        fp = self.__dict__.get("_fp")
        if fp is None:
            digest = hashlib.sha256(self.to_json().encode()).hexdigest()
            fp = f"g{digest[:16]}"
            object.__setattr__(self, "_fp", fp)
        return fp

    def __str__(self) -> str:  # debugging convenience
        return self.to_json()


@dataclasses.dataclass(frozen=True)
class Ellipse(GeometrySpec):
    """Axis-aligned ellipse ((x−cx)/rx)² + ((y−cy)/ry)² < 1. The default
    parameters are the reference's domain x² + 4y² < 1."""

    cx: float = 0.0
    cy: float = 0.0
    rx: float = 1.0
    ry: float = 0.5

    def __post_init__(self):
        # Concrete parameters are validated eagerly; traced leaves (the
        # adjoint shape-gradient path, solvers.adjoint) skip the check —
        # a tracer has no truth value.
        if isinstance(self.rx, (int, float)) and \
                isinstance(self.ry, (int, float)) and \
                not (self.rx > 0 and self.ry > 0):
            raise ValueError(f"ellipse radii must be > 0, got "
                             f"rx={self.rx} ry={self.ry}")

    def contains(self, x, y, xp=None):
        tx = (x - self.cx) / self.rx
        ty = (y - self.cy) / self.ry
        return tx * tx + ty * ty < 1.0

    def sdf(self, x, y, xp=None):
        # Implicit-function level set (not a true distance): continuous,
        # negative inside, zero exactly on the boundary — all the face
        # sampler needs.
        tx = (x - self.cx) / self.rx
        ty = (y - self.cy) / self.ry
        return tx * tx + ty * ty - 1.0

    def normalize(self) -> "Ellipse":
        return Ellipse(_canon_float(self.cx), _canon_float(self.cy),
                       _canon_float(self.rx), _canon_float(self.ry))

    def to_obj(self) -> dict:
        return {"type": "ellipse", "cx": self.cx, "cy": self.cy,
                "rx": self.rx, "ry": self.ry}


DEFAULT_ELLIPSE = Ellipse()
"""The reference's fictitious domain, as a spec."""


@dataclasses.dataclass(frozen=True)
class Rectangle(GeometrySpec):
    """Open axis-aligned box (x0, x1) × (y0, y1)."""

    x0: float
    y0: float
    x1: float
    y1: float

    def __post_init__(self):
        # Tracer-tolerant like Ellipse: only concrete corners validate.
        if all(isinstance(v, (int, float)) for v in
               (self.x0, self.y0, self.x1, self.y1)) and \
                not (self.x1 > self.x0 and self.y1 > self.y0):
            raise ValueError(
                f"rectangle needs x1 > x0 and y1 > y0, got "
                f"({self.x0},{self.y0})..({self.x1},{self.y1})")

    def contains(self, x, y, xp=None):
        return (x > self.x0) & (x < self.x1) & (y > self.y0) & (y < self.y1)

    def sdf(self, x, y, xp=None):
        xp = xp or _np()
        cx, cy = 0.5 * (self.x0 + self.x1), 0.5 * (self.y0 + self.y1)
        hx, hy = 0.5 * (self.x1 - self.x0), 0.5 * (self.y1 - self.y0)
        return xp.maximum(xp.abs(x - cx) - hx, xp.abs(y - cy) - hy)

    def normalize(self) -> "Rectangle":
        x0, x1 = sorted((_canon_float(self.x0), _canon_float(self.x1)))
        y0, y1 = sorted((_canon_float(self.y0), _canon_float(self.y1)))
        return Rectangle(x0, y0, x1, y1)

    def to_obj(self) -> dict:
        return {"type": "rect", "x0": self.x0, "y0": self.y0,
                "x1": self.x1, "y1": self.y1}


@dataclasses.dataclass(frozen=True)
class Polygon(GeometrySpec):
    """Simple polygon (no self-intersections assumed) with vertices as a
    tuple of (x, y) pairs. Membership is even-odd ray crossing; the level
    set is the min-distance-to-edges with the membership sign."""

    vertices: Tuple[Tuple[float, float], ...]

    def __post_init__(self):
        verts = tuple((float(x), float(y)) for x, y in self.vertices)
        if len(verts) < 3:
            raise ValueError(f"polygon needs >= 3 vertices, got "
                             f"{len(verts)}")
        object.__setattr__(self, "vertices", verts)

    def _edges(self, xp):
        v = xp.asarray(self.vertices, dtype=float)
        return v, xp.roll(v, -1, axis=0)

    def contains(self, x, y, xp=None):
        xp = xp or _np()
        x = xp.asarray(x, dtype=float)
        y = xp.asarray(y, dtype=float)
        px, py = xp.broadcast_arrays(x, y)
        a, b = self._edges(xp)
        # Even-odd crossing count of a +x ray, vectorized points × edges.
        ax, ay = a[:, 0], a[:, 1]
        bx, by = b[:, 0], b[:, 1]
        P = px[..., None]
        Q = py[..., None]
        straddles = (ay <= Q) != (by <= Q)
        # x-coordinate where the edge crosses the horizontal line y=Q.
        t = (Q - ay) / (by - ay + (ay == by))     # guarded; masked below
        cross_x = ax + t * (bx - ax)
        hits = straddles & (P < cross_x)
        return (hits.sum(axis=-1) % 2) == 1

    def sdf(self, x, y, xp=None):
        xp = xp or _np()
        x = xp.asarray(x, dtype=float)
        y = xp.asarray(y, dtype=float)
        px, py = xp.broadcast_arrays(x, y)
        a, b = self._edges(xp)
        ax, ay = a[:, 0], a[:, 1]
        bx, by = b[:, 0], b[:, 1]
        ex, ey = bx - ax, by - ay
        ee = ex * ex + ey * ey
        P = px[..., None] - ax
        Q = py[..., None] - ay
        t = xp.clip((P * ex + Q * ey) / ee, 0.0, 1.0)
        dx = P - t * ex
        dy = Q - t * ey
        d = xp.sqrt((dx * dx + dy * dy).min(axis=-1))
        return xp.where(self.contains(px, py, xp), -d, d)

    def normalize(self) -> "Polygon":
        verts = [( _canon_float(x), _canon_float(y))
                 for x, y in self.vertices]
        # Canonical orientation: counter-clockwise (positive signed area).
        area2 = sum(x0 * y1 - x1 * y0
                    for (x0, y0), (x1, y1)
                    in zip(verts, verts[1:] + verts[:1]))
        if area2 < 0:
            verts = verts[::-1]
        # Canonical start: rotate the lexicographically smallest vertex
        # to the front, so the same ring hashes equal from any start.
        k = min(range(len(verts)), key=lambda i: verts[i])
        verts = verts[k:] + verts[:k]
        return Polygon(tuple(verts))

    def to_obj(self) -> dict:
        return {"type": "polygon",
                "vertices": [[x, y] for x, y in self.vertices]}


def _norm_children(shapes, flatten_type) -> tuple:
    """Normalize boolean children: recurse, flatten same-type nests,
    dedupe, and sort by fingerprint — permuted unions hash equal."""
    flat = []
    for s in shapes:
        n = s.normalize()
        if isinstance(n, flatten_type):
            flat.extend(n.shapes)
        else:
            flat.append(n)
    seen, out = set(), []
    for s in flat:
        fp = s.fingerprint
        if fp not in seen:
            seen.add(fp)
            out.append(s)
    return tuple(sorted(out, key=lambda s: s.fingerprint))


@dataclasses.dataclass(frozen=True)
class Union(GeometrySpec):
    shapes: Tuple[GeometrySpec, ...]

    def __post_init__(self):
        object.__setattr__(self, "shapes", tuple(self.shapes))
        if len(self.shapes) < 1:
            raise ValueError("union needs at least one shape")

    def contains(self, x, y, xp=None):
        out = self.shapes[0].contains(x, y, xp)
        for s in self.shapes[1:]:
            out = out | s.contains(x, y, xp)
        return out

    def sdf(self, x, y, xp=None):
        xp = xp or _np()
        out = self.shapes[0].sdf(x, y, xp)
        for s in self.shapes[1:]:
            out = xp.minimum(out, s.sdf(x, y, xp))
        return out

    def normalize(self) -> GeometrySpec:
        children = _norm_children(self.shapes, Union)
        return children[0] if len(children) == 1 else Union(children)

    def to_obj(self) -> dict:
        return {"type": "union",
                "shapes": [s.to_obj() for s in self.shapes]}


@dataclasses.dataclass(frozen=True)
class Intersection(GeometrySpec):
    shapes: Tuple[GeometrySpec, ...]

    def __post_init__(self):
        object.__setattr__(self, "shapes", tuple(self.shapes))
        if len(self.shapes) < 1:
            raise ValueError("intersection needs at least one shape")

    def contains(self, x, y, xp=None):
        out = self.shapes[0].contains(x, y, xp)
        for s in self.shapes[1:]:
            out = out & s.contains(x, y, xp)
        return out

    def sdf(self, x, y, xp=None):
        xp = xp or _np()
        out = self.shapes[0].sdf(x, y, xp)
        for s in self.shapes[1:]:
            out = xp.maximum(out, s.sdf(x, y, xp))
        return out

    def normalize(self) -> GeometrySpec:
        children = _norm_children(self.shapes, Intersection)
        return (children[0] if len(children) == 1
                else Intersection(children))

    def to_obj(self) -> dict:
        return {"type": "intersection",
                "shapes": [s.to_obj() for s in self.shapes]}


@dataclasses.dataclass(frozen=True)
class Difference(GeometrySpec):
    """``shape`` minus (the closure of) ``hole``."""

    shape: GeometrySpec
    hole: GeometrySpec

    def contains(self, x, y, xp=None):
        return self.shape.contains(x, y, xp) & ~self.hole.contains(x, y, xp)

    def sdf(self, x, y, xp=None):
        xp = xp or _np()
        return xp.maximum(self.shape.sdf(x, y, xp),
                          -self.hole.sdf(x, y, xp))

    def normalize(self) -> "Difference":
        return Difference(self.shape.normalize(), self.hole.normalize())

    def to_obj(self) -> dict:
        return {"type": "difference", "shape": self.shape.to_obj(),
                "hole": self.hole.to_obj()}


@dataclasses.dataclass(frozen=True)
class SDF(GeometrySpec):
    """Raw level-set callable ``fn(x, y) -> array`` (negative inside,
    continuous, zero on the boundary). ``name`` is mandatory and IS the
    fingerprint identity — a callable has no stable content hash, so two
    SDFs with the same name are treated as the same geometry (cache
    sharing included). Not JSON-parseable: in-process requests only."""

    fn: Callable = dataclasses.field(compare=False, hash=False)
    name: str = ""

    def __post_init__(self):
        if not self.name:
            raise ValueError(
                "SDF specs need a name=: the fingerprint (canvas-cache "
                "and co-batching key) cannot hash a callable")

    def contains(self, x, y, xp=None):
        return self.fn(x, y) < 0.0

    def sdf(self, x, y, xp=None):
        return self.fn(x, y)

    def to_obj(self) -> dict:
        return {"type": "sdf", "name": self.name}


_PARSERS = {}


def _parse_ellipse(o):
    return Ellipse(o.get("cx", 0.0), o.get("cy", 0.0),
                   o.get("rx", 1.0), o.get("ry", 0.5))


def _parse_rect(o):
    return Rectangle(o["x0"], o["y0"], o["x1"], o["y1"])


def _parse_polygon(o):
    verts = []
    for v in o["vertices"]:
        if not isinstance(v, (list, tuple)) or len(v) != 2:
            raise ValueError(
                f"polygon vertices must be [x, y] pairs, got {v!r}")
        verts.append((v[0], v[1]))
    return Polygon(tuple(verts))


def _parse_union(o):
    return Union(tuple(_parse_obj(s) for s in o["shapes"]))


def _parse_intersection(o):
    return Intersection(tuple(_parse_obj(s) for s in o["shapes"]))


def _parse_difference(o):
    return Difference(_parse_obj(o["shape"]), _parse_obj(o["hole"]))


def _parse_sdf(o):
    raise ValueError(
        "SDF specs carry a Python callable and cannot be parsed from "
        "JSON; construct geometry.SDF(fn, name=...) in-process instead")


_PARSERS.update({
    "ellipse": _parse_ellipse, "rect": _parse_rect,
    "rectangle": _parse_rect, "polygon": _parse_polygon,
    "union": _parse_union, "intersection": _parse_intersection,
    "difference": _parse_difference, "sdf": _parse_sdf,
})

# Per-type key whitelists: a misspelled parameter ("Rx", "radius") must
# NOT fall through to a default and silently solve the wrong domain.
_FIELDS = {
    "ellipse": {"type", "cx", "cy", "rx", "ry"},
    "rect": {"type", "x0", "y0", "x1", "y1"},
    "rectangle": {"type", "x0", "y0", "x1", "y1"},
    "polygon": {"type", "vertices"},
    "union": {"type", "shapes"},
    "intersection": {"type", "shapes"},
    "difference": {"type", "shape", "hole"},
    "sdf": {"type", "name"},
}


def _parse_obj(o) -> GeometrySpec:
    if not isinstance(o, dict) or "type" not in o:
        raise ValueError(f"geometry spec must be an object with a "
                         f"'type' key, got {o!r}")
    t = str(o["type"]).lower()
    if t not in _PARSERS:
        raise ValueError(
            f"unknown geometry type {t!r}; known: "
            f"{', '.join(sorted(k for k in _PARSERS if k != 'rectangle'))}")
    unknown = set(o) - _FIELDS[t]
    if unknown:
        raise ValueError(
            f"geometry type {t!r} got unknown field(s) "
            f"{', '.join(sorted(map(repr, unknown)))}; allowed: "
            f"{', '.join(sorted(_FIELDS[t] - {'type'}))}")
    try:
        return _PARSERS[t](o)
    except KeyError as e:
        raise ValueError(f"geometry type {t!r} is missing field {e}")


def parse_geometry(spec) -> GeometrySpec:
    """Coerce ``spec`` (GeometrySpec | dict | JSON string) into a
    normalized :class:`GeometrySpec`."""
    if isinstance(spec, GeometrySpec):
        return spec.normalize()
    if isinstance(spec, str):
        try:
            spec = json.loads(spec)
        except json.JSONDecodeError as e:
            raise ValueError(f"geometry spec is not valid JSON: {e}")
    return _parse_obj(spec).normalize()


def fingerprint_of(spec: Optional[GeometrySpec]) -> str:
    """The taint/attribution key the serve layer uses: a spec's
    fingerprint, or the sentinel ``"default"`` for requests with no
    geometry (the reference ellipse path)."""
    return spec.fingerprint if spec is not None else "default"
