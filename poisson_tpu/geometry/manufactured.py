"""Manufactured-solution accuracy gate, per geometry family.

The ellipse path has an analytic oracle — u = (1 − x² − 4y²)/10 solves
−Δu = 1 on the reference domain — and BENCH.md gates every backend on
its L2-vs-analytic landing at the discretisation floor. A new geometry
family ships under the SAME rule: each family below pairs a spec with an
exact solution u (vanishing on ∂D) and the forcing f = −Δu, the
fictitious-domain solve runs against f·1_D, and the weighted L2 error
over nodes strictly inside D must land at the floor the penalty method
allows (O(√ε·‖u‖) boundary-layer error, ε = max(h1,h2)² — first order
in h).

Coverage is one case per DSL node type, each reduced to a domain with a
closed-form solution:

- ``ellipse`` — the reference domain itself (the existing oracle);
- ``ellipse-offset`` — a translated, rescaled ellipse (quadratic u);
- ``rectangle`` — closed-form canvas path, sine-product u;
- ``polygon`` — the SAME rectangle entered as a 4-vertex polygon: the
  adaptive sampler must reproduce the closed-form family's accuracy;
- ``union`` / ``intersection`` / ``difference`` — boolean composites
  whose result is (a disjoint pair of / exactly one) rectangle(s), so
  the sine-product u still applies while the canvases exercise the
  composite SDF sampling;
- ``sdf`` — a raw-callable circle, quadratic u.

``manufactured_error`` runs one case end to end and reports absolute +
relative weighted L2; tests gate ``rel`` against per-family floors
measured on CPU with 2× headroom (tests/test_geometry_dsl.py).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, Optional

import numpy as np

from poisson_tpu.config import Problem
from poisson_tpu.geometry.dsl import (
    DEFAULT_ELLIPSE,
    Difference,
    Ellipse,
    GeometrySpec,
    Intersection,
    Polygon,
    Rectangle,
    SDF,
    Union,
)


@dataclasses.dataclass(frozen=True)
class ManufacturedCase:
    """A geometry family's accuracy oracle: exact u inside D (zero is
    assumed outside), and the forcing f = −Δu (None → the constant
    ``problem.f_val``, i.e. the standard indicator RHS)."""

    name: str
    spec: GeometrySpec
    u: Callable                      # (x, y) -> exact solution
    f: Optional[Callable] = None     # (x, y) -> forcing; None = f_val


def _quad_ellipse(e: Ellipse):
    """u = c·(1 − tx² − ty²) with −Δu = 2c(1/rx² + 1/ry²) ≡ 1."""
    c = 1.0 / (2.0 * (1.0 / e.rx ** 2 + 1.0 / e.ry ** 2))

    def u(x, y):
        tx = (x - e.cx) / e.rx
        ty = (y - e.cy) / e.ry
        return c * (1.0 - tx * tx - ty * ty)

    return u


def _sine_rect(r: Rectangle, c: float = 0.1):
    """u = c·sin(π(x−x0)/Lx)·sin(π(y−y0)/Ly) on the box, with
    f = −Δu = c·π²(1/Lx² + 1/Ly²)·sin·sin."""
    lx, ly = r.x1 - r.x0, r.y1 - r.y0
    k = c * math.pi ** 2 * (1.0 / lx ** 2 + 1.0 / ly ** 2)

    def shape_fn(scale):
        def fn(x, y):
            sx = np.sin(np.pi * (x - r.x0) / lx)
            sy = np.sin(np.pi * (y - r.y0) / ly)
            val = scale * sx * sy
            inside = (x > r.x0) & (x < r.x1) & (y > r.y0) & (y < r.y1)
            return np.where(inside, val, 0.0)
        return fn

    return shape_fn(c), shape_fn(k)


def _sum_fns(*fns):
    def fn(x, y):
        out = fns[0](x, y)
        for g in fns[1:]:
            out = out + g(x, y)
        return out
    return fn


def cases() -> list:
    """One manufactured case per shipped geometry family."""
    out = []

    # ellipse: the reference oracle itself, through the geometry path.
    out.append(ManufacturedCase(
        "ellipse", DEFAULT_ELLIPSE, _quad_ellipse(DEFAULT_ELLIPSE)))

    off = Ellipse(cx=0.15, cy=-0.05, rx=0.6, ry=0.35)
    out.append(ManufacturedCase("ellipse-offset", off, _quad_ellipse(off)))

    rect = Rectangle(-0.7, -0.4, 0.5, 0.3)
    u, f = _sine_rect(rect)
    out.append(ManufacturedCase("rectangle", rect, u, f))

    # The same box as a polygon ring: the sampler vs the closed form.
    poly = Polygon(((-0.7, -0.4), (0.5, -0.4), (0.5, 0.3), (-0.7, 0.3)))
    out.append(ManufacturedCase("polygon", poly, u, f))

    r1 = Rectangle(-0.85, -0.35, -0.15, 0.25)
    r2 = Rectangle(0.1, -0.3, 0.8, 0.3)
    u1, f1 = _sine_rect(r1)
    u2, f2 = _sine_rect(r2)
    out.append(ManufacturedCase(
        "union", Union((r1, r2)), _sum_fns(u1, u2), _sum_fns(f1, f2)))

    # Overlapping boxes whose intersection is exactly a rectangle.
    ia = Rectangle(-0.8, -0.45, 0.3, 0.35)
    ib = Rectangle(-0.4, -0.3, 0.7, 0.5)
    ir = Rectangle(-0.4, -0.3, 0.3, 0.35)
    ui, fi = _sine_rect(ir)
    out.append(ManufacturedCase(
        "intersection", Intersection((ia, ib)), ui, fi))

    # A bite that spans the big box's full y-extent, so what remains is
    # exactly a rectangle again.
    big = Rectangle(-0.8, -0.4, 0.6, 0.3)
    bite = Rectangle(0.0, -0.5, 0.9, 0.4)
    rem = Rectangle(-0.8, -0.4, 0.0, 0.3)
    ud, fd = _sine_rect(rem)
    out.append(ManufacturedCase(
        "difference", Difference(big, bite), ud, fd))

    r = 0.45
    circle = SDF(lambda x, y: x * x + y * y - r * r, name=f"circle-{r}")

    def u_circ(x, y):
        return 0.25 * (r * r - x * x - y * y)     # −Δu = 1

    out.append(ManufacturedCase("sdf", circle, u_circ))
    return out


def case_by_name(name: str) -> ManufacturedCase:
    for c in cases():
        if c.name == name:
            return c
    raise KeyError(name)


def manufactured_error(case: ManufacturedCase, M: int, N: int,
                       dtype=None, preconditioner: str = "jacobi",
                       krylov=None) -> dict:
    """Run ``case`` end to end on an M×N grid and measure the weighted
    L2 error over nodes strictly inside D (the BENCH.md oracle rule,
    applied to the family's own exact solution).

    Returns ``{"l2", "rel", "iterations", "flag"}`` — ``rel`` is the
    error relative to ‖u‖, the number the per-family floor gates.

    ``preconditioner="mg"`` runs the SAME oracle through the V-cycle-
    preconditioned solve (:mod:`poisson_tpu.mg`) — the hierarchy is
    built from exactly the case's own canvases — which is how every
    geometry family gates MG at its established L2 floor before MG may
    serve that family (the PR 9 gating rule, generalized verbatim).

    ``krylov`` (a :class:`poisson_tpu.krylov.KrylovPolicy`) runs the
    SAME oracle through the Krylov-memory programs — the same gating
    rule, generalized once more. ``mode="block"`` solves a 3-member
    gated block (the case's forcing at gates 1.0/1.35/0.75 — a
    rank-deficient block by construction, exercising the
    breakdown-free remedy) and reports the WORST member's relative
    error against its gate-scaled exact solution (the operator is
    linear: u(g·f) = g·u). ``deflation=True`` runs the cold
    harvest-enabled solve on the case's forcing, builds the deflation
    basis from exactly that solve, then reports the WARM deflated
    solve at gate 1.4 (``cold_iterations`` rides the report so tests
    can assert warm-beats-cold at the floor)."""
    import jax.numpy as jnp

    from poisson_tpu.geometry.canvas import build_geometry_fields
    from poisson_tpu.ops.stencil import diag_D
    from poisson_tpu.solvers.pcg import (
        _solve,
        resolve_dtype,
        resolve_scaled,
    )

    problem = Problem(M=M, N=N)
    dtype_name = resolve_dtype(dtype)
    use_scaled = resolve_scaled(None, dtype_name)
    a64, b64, rhs64 = build_geometry_fields(problem, case.spec,
                                            rhs_fn=case.f)
    d64 = diag_D(a64, b64, problem.h1, problem.h2)
    if use_scaled:
        inv = 1.0 / np.sqrt(d64)
        rhs_use = np.pad(rhs64[1:-1, 1:-1] * inv, 1)
        aux64 = np.pad(inv, 1)
    else:
        rhs_use = rhs64
        aux64 = np.pad(d64, 1)
    dt = jnp.dtype(dtype_name)

    def rel_l2(w64, gate=1.0):
        """Weighted L2 of (w − gate·u) over nodes strictly inside D,
        relative to ‖gate·u‖ — the BENCH.md oracle rule (linearity:
        the exact solution of gate·f is gate·u)."""
        i_idx = np.arange(problem.M + 1)
        j_idx = np.arange(problem.N + 1)
        x = (problem.x_min
             + i_idx.astype(np.float64) * problem.h1)[:, None]
        y = (problem.y_min
             + j_idx.astype(np.float64) * problem.h2)[None, :]
        mask = case.spec.contains(x, y, np)
        u = np.where(mask, gate * case.u(x, y), 0.0)
        werr = np.where(mask, (w64 - u) ** 2, 0.0)
        wnorm = np.where(mask, u ** 2, 0.0)
        scale = problem.h1 * problem.h2
        l2 = float(np.sqrt(werr.sum() * scale))
        norm = float(np.sqrt(wnorm.sum() * scale))
        return l2, (l2 / norm if norm else float("inf"))

    if krylov is not None:
        from poisson_tpu.krylov import KRYLOV_BLOCK, resolve_krylov

        kp = resolve_krylov(krylov)
        if preconditioner not in (None, "jacobi"):
            raise ValueError(
                "the krylov oracle gate runs the jacobi body (block/"
                "deflated programs have no preconditioner composition "
                f"yet); got preconditioner={preconditioner!r}")
        A = jnp.asarray(a64, dt)
        Bc = jnp.asarray(b64, dt)
        rhs_dev = jnp.asarray(rhs_use, dt)
        aux_dev = jnp.asarray(aux64, dt)
        if kp.mode == KRYLOV_BLOCK:
            from poisson_tpu.krylov.block import _solve_block

            gates = (1.0, 1.35, 0.75)
            stack = jnp.stack([rhs_dev * g for g in gates])
            result = _solve_block(problem, use_scaled, A, Bc, stack,
                                  aux_dev)
            w = np.asarray(result.w, np.float64)
            per = [rel_l2(w[j], g) for j, g in enumerate(gates)]
            worst = max(range(len(gates)), key=lambda j: per[j][1])
            return {
                "case": case.name,
                "l2": per[worst][0],
                "rel": per[worst][1],
                "iterations": int(np.asarray(result.max_iterations)),
                "flag": int(np.asarray(result.flag).max()),
                "flags": [int(f) for f in np.asarray(result.flag)],
                "deficient": bool(np.asarray(result.deficient)),
            }
        # deflation: cold harvest on the case's forcing, then the warm
        # deflated solve of the SAME operator at a different gate.
        from poisson_tpu.krylov.recycle import (
            _solve_deflated,
            _solve_harvest,
            build_basis,
        )

        cold, y_w, V = _solve_harvest(problem, use_scaled, kp.harvest,
                                      A, Bc, rhs_dev, aux_dev)
        basis = build_basis(problem, use_scaled, A, Bc, aux_dev, y_w, V,
                            int(cold.iterations), kp)
        if basis is None:
            raise RuntimeError(
                f"harvest produced no usable basis for {case.name} "
                f"(cold flag {int(cold.flag)})")
        gate = 1.4
        result = _solve_deflated(problem, use_scaled, A, Bc,
                                 rhs_dev * gate, aux_dev, *basis)
        w = np.asarray(result.w, np.float64)
        l2, rel = rel_l2(w, gate)
        return {
            "case": case.name,
            "l2": l2,
            "rel": rel,
            "iterations": int(np.asarray(result.iterations)),
            "flag": int(np.asarray(result.flag)),
            "cold_iterations": int(np.asarray(cold.iterations)),
            "basis_vectors": int(basis[0].shape[0]),
        }
    if preconditioner not in (None, "jacobi"):
        from poisson_tpu.mg import (
            DEFAULT_MG,
            hierarchy_from_fields,
            resolve_preconditioner,
        )
        from poisson_tpu.mg.preconditioner import _solve_mg

        resolve_preconditioner(preconditioner)
        hier = hierarchy_from_fields(problem, a64, b64, dtype_name,
                                     use_scaled, DEFAULT_MG)
        result = _solve_mg(problem, use_scaled, DEFAULT_MG, 0, 0, 0.0,
                           jnp.asarray(a64, dt), jnp.asarray(b64, dt),
                           jnp.asarray(rhs_use, dt),
                           jnp.asarray(aux64, dt), hier)
    else:
        result = _solve(problem, use_scaled, 0, 0, 0.0, False, 0,
                        jnp.asarray(a64, dt), jnp.asarray(b64, dt),
                        jnp.asarray(rhs_use, dt), jnp.asarray(aux64, dt))

    l2, rel = rel_l2(np.asarray(result.w, np.float64))
    return {
        "case": case.name,
        "l2": l2,
        "rel": rel,
        "iterations": int(np.asarray(result.iterations)),
        "flag": int(np.asarray(result.flag)),
    }
