"""Canvas compiler: GeometrySpec → fictitious-domain coefficient fields.

The solver never sees a geometry — it sees the blend-coefficient
canvases ``a``, ``b`` and the RHS indicator that
``models.fictitious_domain`` bakes for the reference ellipse. This
module generalises that bake to any :mod:`geometry.dsl` spec, with the
SAME face-intersection blend rule (``_blend``: full face → 1, empty
face → 1/ε, cut face → ℓ/h + (1−ℓ/h)/ε with ε = max(h1,h2)²):

- **exact closed-form segment lengths** where the face ∩ domain
  intersection has one (:class:`~poisson_tpu.geometry.dsl.Ellipse` —
  the reference's own formula generalised to (cx, cy, rx, ry), bit-
  compatible with ``fictitious_domain`` for the default spec — and
  :class:`~poisson_tpu.geometry.dsl.Rectangle`);
- **adaptive face sampling of the level set** everywhere else
  (polygons, boolean composites, raw SDFs): each face is probed at
  ``samples+1`` uniform points, fully-inside subintervals are counted
  exactly, and every sign-changing subinterval is refined by vectorised
  bisection of the spec's continuous ``sdf`` down to ~h·2⁻⁴⁴ — so the
  sampled ℓ is exact up to features narrower than h/samples.

Like the reference (and ``solvers.pcg.host_fields64``), canvases are
built on the host in numpy fp64 and cast once; they are **never stored
below fp32** — bf16 coefficient storage was measured and rejected for
exactly these canvases (BENCH.md "Precision of the coefficient
canvases").

The **canvas cache** is keyed by ``(geometry fingerprint, grid box,
f_val, dtype, scaled)`` — the same discipline as the jit cache's static
shape key, with the fingerprint standing in for the canvas *content* —
and surfaces its traffic as ``geom.cache.{hits,misses}``: a mixed-
geometry serving load that re-uses K families shows hits ≫ misses, and
a second family landing on an already-compiled bucket executable is
visible as a ``geom.cache.miss`` + ``batched.bucket_cache.hit`` pair
(new canvases, no recompile).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Optional

import numpy as np

from poisson_tpu import obs
from poisson_tpu.config import Problem
from poisson_tpu.geometry.dsl import (
    DEFAULT_ELLIPSE,
    Ellipse,
    GeometrySpec,
    Rectangle,
    parse_geometry,
)
from poisson_tpu.models.fictitious_domain import _blend

# Face-sampling defaults: 16 uniform probes classify each face, 44
# bisection steps pin every boundary crossing to ~h·2e-14. Canvases are
# built once per fingerprint and cached, so this cost is off the solve
# path entirely.
DEFAULT_SAMPLES = 16
DEFAULT_BISECT_ITERS = 44

_CACHE_CAP = 64
_CACHE: "OrderedDict" = OrderedDict()


def reset_geometry_cache() -> None:
    """Forget every cached canvas (tests; pair with
    ``obs.metrics.reset()`` — the ``geom.cache.*`` counters and this
    cache must move together or hit/miss arithmetic goes stale)."""
    _CACHE.clear()


def _ellipse_lengths(spec: Ellipse, const, start, end, vertical, xp):
    """Closed-form face ∩ ellipse length — the reference's
    ``cal_seg_len_in_D`` generalised to (cx, cy, rx, ry). For the
    default spec every operation reduces to the reference's expression
    under exact power-of-two float scalings, so the result is
    bit-identical to ``fictitious_domain.segment_length_in_domain``
    (asserted in tests).

    The half-width uses the double-where guard instead of a bare
    ``sqrt(max(0, v))``: values are identical (sqrt(0)=0 either way) but
    the derivative at v ≤ 0 becomes 0 instead of 0·inf = NaN — required
    by the traced shape-gradient path (``solvers.adjoint``)."""

    def _half(v, r):
        pos = v > 0.0
        return r * xp.where(pos, xp.sqrt(xp.where(pos, v, 1.0)), 0.0)

    if vertical:
        t = (const - spec.cx) / spec.rx
        half = _half(1.0 - t * t, spec.ry)
        lo, hi = spec.cy - half, spec.cy + half
    else:
        t = (const - spec.cy) / spec.ry
        half = _half(1.0 - t * t, spec.rx)
        lo, hi = spec.cx - half, spec.cx + half
    return xp.maximum(0.0, xp.minimum(end, hi) - xp.maximum(start, lo))


def _rectangle_lengths(spec: Rectangle, const, start, end, vertical, xp):
    """Closed-form face ∩ box length: interval clip, gated on the fixed
    coordinate lying strictly inside the box's other extent."""
    if vertical:
        inside = (const > spec.x0) & (const < spec.x1)
        lo, hi = spec.y0, spec.y1
    else:
        inside = (const > spec.y0) & (const < spec.y1)
        lo, hi = spec.x0, spec.x1
    clip = xp.maximum(0.0, xp.minimum(end, hi) - xp.maximum(start, lo))
    return xp.where(inside, clip, xp.zeros_like(clip))


def closed_form_lengths(spec: GeometrySpec, const, start, end,
                        vertical: bool, xp):
    """Exact segment length for specs that have one, else None."""
    if isinstance(spec, Ellipse):
        return _ellipse_lengths(spec, const, start, end, vertical, xp)
    if isinstance(spec, Rectangle):
        return _rectangle_lengths(spec, const, start, end, vertical, xp)
    return None


def _sampled_lengths(sdf_line: Callable, const_flat, start_flat,
                     h: float, samples: int, iters: int):
    """Adaptive face sampling: probe each face uniformly, count the
    fully-inside subintervals, bisect every sign change.

    ``sdf_line(c, t)`` evaluates the spec's level set along the face
    family (c = the fixed coordinate, t = the running one), vectorised
    over same-shape arrays. Misses only features narrower than
    h/samples — sub-probe tunnels through a face, which at solve
    resolution means geometry the grid could not represent anyway.
    """
    n = const_flat.size
    dt = h / samples
    ts = start_flat[:, None] + dt * np.arange(samples + 1)[None, :]
    F = sdf_line(np.broadcast_to(const_flat[:, None], ts.shape), ts)
    inside = F < 0.0
    li, ri = inside[:, :-1], inside[:, 1:]
    lengths = (li & ri).sum(axis=1) * dt
    cross = li != ri
    if cross.any():
        fi, si = np.nonzero(cross)
        lo = ts[fi, si].astype(float)
        hi = ts[fi, si + 1].astype(float)
        c = const_flat[fi]
        lo_inside = li[fi, si]
        for _ in range(iters):
            mid = 0.5 * (lo + hi)
            mid_inside = sdf_line(c, mid) < 0.0
            take_lo = mid_inside == lo_inside
            lo = np.where(take_lo, mid, lo)
            hi = np.where(take_lo, hi, mid)
        crossing = 0.5 * (lo + hi)
        contrib = np.where(lo_inside, crossing - ts[fi, si],
                           ts[fi, si + 1] - crossing)
        np.add.at(lengths, fi, contrib)
    return lengths


def geometry_face_lengths(problem: Problem, spec: GeometrySpec,
                          samples: int = DEFAULT_SAMPLES,
                          bisect_iters: int = DEFAULT_BISECT_ITERS):
    """Face-intersection lengths (la, lb) on the full (M+1, N+1) grid,
    numpy fp64. ``la[i,j]`` is the vertical face at
    (x_i − h1/2, [y_j − h2/2, y_j + h2/2]); ``lb`` the horizontal one —
    the same face convention as ``fictitious_domain.coefficient_fields``."""
    h1, h2 = problem.h1, problem.h2
    i_idx = np.arange(problem.M + 1)
    j_idx = np.arange(problem.N + 1)
    x = (problem.x_min + i_idx.astype(np.float64) * h1)[:, None]
    y = (problem.y_min + j_idx.astype(np.float64) * h2)[None, :]

    la = closed_form_lengths(spec, x - 0.5 * h1, y - 0.5 * h2,
                             y + 0.5 * h2, True, np)
    lb = closed_form_lengths(spec, y - 0.5 * h2, x - 0.5 * h1,
                             x + 0.5 * h1, False, np)
    shape = (problem.M + 1, problem.N + 1)
    if la is None:
        const = np.broadcast_to(x - 0.5 * h1, shape).ravel()
        start = np.broadcast_to(y - 0.5 * h2, shape).ravel()
        la = _sampled_lengths(
            lambda c, t: spec.sdf(c, t, np), const, start, h2,
            samples, bisect_iters).reshape(shape)
    else:
        la = np.broadcast_to(la, shape)
    if lb is None:
        const = np.broadcast_to(y - 0.5 * h2, shape).ravel()
        start = np.broadcast_to(x - 0.5 * h1, shape).ravel()
        lb = _sampled_lengths(
            lambda c, t: spec.sdf(t, c, np), const, start, h1,
            samples, bisect_iters).reshape(shape)
    else:
        lb = np.broadcast_to(lb, shape)
    return np.asarray(la, np.float64), np.asarray(lb, np.float64)


def build_geometry_fields(problem: Problem, spec: GeometrySpec,
                          rhs_fn: Optional[Callable] = None,
                          samples: int = DEFAULT_SAMPLES,
                          bisect_iters: int = DEFAULT_BISECT_ITERS):
    """Full-grid (a, b, B) for ``spec`` — the geometry-general
    ``fictitious_domain.build_fields``, host numpy fp64.

    ``rhs_fn(x, y) -> f`` overrides the constant ``problem.f_val``
    forcing (the manufactured-solution gate needs non-constant f); the
    indicator and interior masks apply either way.
    """
    spec = parse_geometry(spec)
    h1, h2, eps = problem.h1, problem.h2, problem.eps
    la, lb = geometry_face_lengths(problem, spec, samples, bisect_iters)
    a = _blend(la, h2, eps, np).astype(np.float64)
    b = _blend(lb, h1, eps, np).astype(np.float64)

    i_idx = np.arange(problem.M + 1)
    j_idx = np.arange(problem.N + 1)
    x = (problem.x_min + i_idx.astype(np.float64) * h1)[:, None]
    y = (problem.y_min + j_idx.astype(np.float64) * h2)[None, :]
    inside = spec.contains(x, y, np)
    interior = ((i_idx >= 1) & (i_idx <= problem.M - 1))[:, None] & (
        (j_idx >= 1) & (j_idx <= problem.N - 1))[None, :]
    f = (np.float64(problem.f_val) if rhs_fn is None
         else np.asarray(rhs_fn(x, y), np.float64))
    rhs = np.where(inside & interior, f, np.float64(0.0))
    return a, b, rhs


def _fields64(problem: Problem, spec: GeometrySpec, scaled: bool):
    """(a, b, rhs_use, aux) fp64 numpy — the geometry-general
    ``solvers.pcg.host_fields64`` (same scaled-system derivation)."""
    from poisson_tpu.ops.stencil import diag_D

    a64, b64, rhs64 = build_geometry_fields(problem, spec)
    d64 = diag_D(a64, b64, problem.h1, problem.h2)
    if not scaled:
        return a64, b64, rhs64, np.pad(d64, 1)
    inv_sqrt_d = 1.0 / np.sqrt(d64)
    return a64, b64, np.pad(rhs64[1:-1, 1:-1] * inv_sqrt_d, 1), np.pad(
        inv_sqrt_d, 1)


def _canvas_key(problem: Problem) -> tuple:
    """The Problem fields the canvases actually depend on — solver
    knobs (delta, max_iter, weighted_norm) are normalized away so
    requests differing only in stopping policy share canvases."""
    return (problem.M, problem.N, problem.x_min, problem.x_max,
            problem.y_min, problem.y_max, problem.f_val)


def geometry_setup(problem: Problem, spec, dtype_name: str,
                   scaled: bool):
    """Device-resident (a, b, rhs, aux) for ``spec`` — the geometry
    analog of ``solvers.pcg.host_setup``, fingerprint-cache-keyed.

    Every call counts ``geom.cache.hits`` or ``geom.cache.misses``; a
    miss pays the fp64 host build + cast + transfer once, after which
    every request of the same (fingerprint, grid, dtype, scaled) —
    including members of *different* buckets and lane splices — reuses
    the same device arrays."""
    import jax.numpy as jnp

    spec = parse_geometry(spec)
    key = (spec.fingerprint, _canvas_key(problem), dtype_name,
           bool(scaled))
    hit = _CACHE.get(key)
    if hit is not None:
        _CACHE.move_to_end(key)
        obs.inc("geom.cache.hits")
        return hit
    obs.inc("geom.cache.misses")
    a64, b64, rhs64, aux64 = _fields64(problem, spec, scaled)
    dtype = jnp.dtype(dtype_name)
    out = (jnp.asarray(a64, dtype), jnp.asarray(b64, dtype),
           jnp.asarray(rhs64, dtype), jnp.asarray(aux64, dtype))
    _CACHE[key] = out
    while len(_CACHE) > _CACHE_CAP:
        _CACHE.popitem(last=False)
    return out


def traced_fields(problem: Problem, spec: GeometrySpec, dtype=None):
    """(a, b, rhs) built IN-GRAPH with jax.numpy — the differentiable
    canvas path for shape-parameter gradients (``solvers.adjoint``).

    Only the closed-form families qualify (:class:`Ellipse`,
    :class:`Rectangle`): their face lengths are smooth functions of the
    shape parameters wherever a face stays in its blend class, so
    ``jax.grad`` through the ε-blend is meaningful. The sampled families
    go through host-side bisection, whose output carries no parameter
    derivative — asking for their gradient raises instead of silently
    returning zeros. ``spec`` may carry traced leaves; it is used as
    given (normalization/fingerprints need concrete floats)."""
    import jax.numpy as jnp

    if not isinstance(spec, (Ellipse, Rectangle)):
        raise ValueError(
            "traced_fields (shape gradients) supports the closed-form "
            "families Ellipse and Rectangle; "
            f"got {type(spec).__name__} — sampled canvases are built by "
            "host-side bisection and carry no parameter derivative")
    h1, h2, eps = problem.h1, problem.h2, problem.eps
    dt = jnp.dtype(dtype) if dtype is not None else jnp.asarray(0.0).dtype
    i_idx = jnp.arange(problem.M + 1)
    j_idx = jnp.arange(problem.N + 1)
    x = (problem.x_min + i_idx.astype(dt) * h1)[:, None]
    y = (problem.y_min + j_idx.astype(dt) * h2)[None, :]
    la = closed_form_lengths(spec, x - 0.5 * h1, y - 0.5 * h2,
                             y + 0.5 * h2, True, jnp)
    lb = closed_form_lengths(spec, y - 0.5 * h2, x - 0.5 * h1,
                             x + 0.5 * h1, False, jnp)
    shape = (problem.M + 1, problem.N + 1)
    a = jnp.broadcast_to(_blend(la, h2, eps, jnp), shape).astype(dt)
    b = jnp.broadcast_to(_blend(lb, h1, eps, jnp), shape).astype(dt)
    inside = spec.contains(x, y, jnp)
    interior = ((i_idx >= 1) & (i_idx <= problem.M - 1))[:, None] & (
        (j_idx >= 1) & (j_idx <= problem.N - 1))[None, :]
    rhs = jnp.where(inside & interior,
                    jnp.asarray(problem.f_val, dt), jnp.zeros((), dt))
    return a, b, jnp.broadcast_to(rhs, shape)


def cut_face_mask(a64, b64, eps):
    """Nodes touching a cut face: a blend coefficient strictly between
    the full-face value (1) and the empty-face value (1/eps). Bounds are
    relative — an absolute midpoint would drop low-coverage cut faces."""
    hi = (1.0 / eps) * (1.0 - 1e-9)
    return ((a64 > 1.0 + 1e-9) & (a64 < hi)) | (
        (b64 > 1.0 + 1e-9) & (b64 < hi))


def render_ascii(problem: Problem, spec, width: int = 64,
                 height: int = 24) -> str:
    """Downsampled ASCII canvas preview for spec debugging
    (``python -m poisson_tpu geometry SPEC --render``): '#' fully
    inside, '+' cut faces touching the node, '.' outside."""
    spec = parse_geometry(spec)
    a64, b64, rhs64 = build_geometry_fields(problem, spec)
    cut = cut_face_mask(a64, b64, problem.eps)
    inside = rhs64 != 0.0
    rows = []
    ii = np.linspace(0, problem.M, num=min(width, problem.M + 1),
                     dtype=int)
    jj = np.linspace(0, problem.N, num=min(height, problem.N + 1),
                     dtype=int)
    for j in jj[::-1]:                     # y up, like a plot
        row = []
        for i in ii:
            if inside[i, j]:
                row.append("#")
            elif cut[i, j]:
                row.append("+")
            else:
                row.append(".")
        rows.append("".join(row))
    return "\n".join(rows)
