"""Problem configuration.

The reference bakes these in as compile-time constants and positional argv
(``stage2-mpi/poisson_mpi_decomp.cpp:9-11,470-481``); here they form one frozen
dataclass that every layer takes explicitly.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional


@dataclasses.dataclass(frozen=True)
class Problem:
    """2D Poisson problem on the box [x_min,x_max]×[y_min,y_max] with the
    elliptic domain x² + 4y² < 1 embedded by the fictitious-domain method.

    Grid: (M+1)×(N+1) nodes; unknowns live at interior nodes i=1..M-1,
    j=1..N-1 with homogeneous Dirichlet data on the box boundary
    (reference: ``stage0/Withoutopenmp1.cpp:106-119``).
    """

    M: int
    N: int
    x_min: float = -1.0
    x_max: float = 1.0
    y_min: float = -0.6
    y_max: float = 0.6
    f_val: float = 1.0
    delta: float = 1e-6
    max_iter: Optional[int] = None
    # Stage0 checks the unweighted Euclidean norm of w(k+1)-w(k)
    # (``stage0/Withoutopenmp1.cpp:154``); stages 1-4 weight by h1·h2
    # (``stage2-mpi/poisson_mpi_decomp.cpp:440``). Weighted is the default,
    # matching the distributed stages and the published iteration counts.
    weighted_norm: bool = True

    def __post_init__(self) -> None:
        if self.M < 2 or self.N < 2:
            raise ValueError(f"Grid must be at least 2x2, got M={self.M} N={self.N}")

    @property
    def h1(self) -> float:
        return (self.x_max - self.x_min) / self.M

    @property
    def h2(self) -> float:
        return (self.y_max - self.y_min) / self.N

    @property
    def eps(self) -> float:
        """Fictitious-domain penalty: ε = max(h1,h2)²
        (``stage0/Withoutopenmp1.cpp:108``)."""
        h = max(self.h1, self.h2)
        return h * h

    @property
    def iteration_cap(self) -> int:
        """Safety cap (M-1)(N-1), never hit in practice
        (``stage0/Withoutopenmp1.cpp:182``)."""
        if self.max_iter is not None:
            return self.max_iter
        return (self.M - 1) * (self.N - 1)

    @property
    def interior_shape(self) -> tuple[int, int]:
        return (self.M - 1, self.N - 1)

    @property
    def grid_shape(self) -> tuple[int, int]:
        return (self.M + 1, self.N + 1)

    @property
    def interior_points(self) -> int:
        return (self.M - 1) * (self.N - 1)

    def with_(self, **kw) -> "Problem":
        return dataclasses.replace(self, **kw)


FLAGSHIP = Problem(M=800, N=1200)
"""The headline benchmark configuration of the reference (BASELINE.md)."""
