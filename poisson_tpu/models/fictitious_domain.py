"""Problem setup: elliptic geometry and fictitious-domain coefficient fields.

TPU-native re-design of the reference's scalar setup loops
(``stage0/Withoutopenmp1.cpp:14-61`` ``if_is_in_D``/``cal_seg_len_in_D``/
``fic_reg``; distributed variant ``stage2-mpi/poisson_mpi_decomp.cpp:124-170``
``fic_reg_local``): everything here is closed-form and vectorised over index
meshes, so a device shard can build exactly its own block (+halo ring) of the
coefficient fields locally — the SPMD analog of ``fic_reg_local`` — with no
scatter/gather and no host loop.

Discretisation recap (matching the reference bit-for-bit in fp64):
  - Grid nodes x_i = x_min + i·h1, y_j = y_min + j·h2, i=0..M, j=0..N.
  - Edge coefficient a[i,j] sits on the *vertical* cell face at
    x = x_i − h1/2, y ∈ [y_j − h2/2, y_j + h2/2]; b[i,j] on the *horizontal*
    face at y = y_j − h2/2, x ∈ [x_i − h1/2, x_i + h1/2].
  - With ℓ the face length inside D = {x²+4y² < 1} and h the face length:
      coeff = 1               if |ℓ − h| < 1e-9   (face fully inside)
            = 1/ε             if ℓ < 1e-9         (face fully outside)
            = ℓ/h + (1−ℓ/h)/ε otherwise           (cut face)
    with ε = max(h1,h2)²   (``stage0/Withoutopenmp1.cpp:53-54,108``).
  - RHS B[i,j] = f_val · 1[(x_i,y_j) ∈ D]  (``stage0/Withoutopenmp1.cpp:57-60``).
"""

from __future__ import annotations

import jax.numpy as jnp

from poisson_tpu.config import Problem

# The reference's exact-hit tolerances (``stage0/Withoutopenmp1.cpp:53-54``).
_FACE_TOL = 1e-9


def is_in_domain(x, y):
    """Ellipse membership x² + 4y² < 1 (``stage0/Withoutopenmp1.cpp:14-16``)."""
    return x * x + 4.0 * y * y < 1.0


def segment_length_in_domain(const_coord, start_var, end_var, *,
                             vertical: bool, xp=jnp):
    """Length of an axis-aligned segment's intersection with the ellipse.

    Closed form via the ellipse half-width at the fixed coordinate
    (``stage0/Withoutopenmp1.cpp:19-39``), vectorised: all arguments may be
    arrays. The reference's |x0|≥1 / |2y0|≥1 early-outs coincide with the
    clamped square root, so no branch is needed.

    ``xp`` selects the array namespace (jnp on device; numpy for fp64 host
    setup when x64 is unavailable, e.g. on TPU — the reference also does its
    setup on the host, ``stage4:…cu:717``).
    """
    if vertical:
        half = xp.sqrt(xp.maximum(0.0, (1.0 - const_coord * const_coord) / 4.0))
    else:
        half = xp.sqrt(xp.maximum(0.0, 1.0 - 4.0 * const_coord * const_coord))
    return xp.maximum(
        0.0, xp.minimum(end_var, half) - xp.maximum(start_var, -half)
    )


def _blend(length, h, eps, xp=jnp):
    """ℓ → coefficient blend (full / empty / cut face), elementwise."""
    frac = length / h
    cut = frac + (1.0 - frac) / eps
    return xp.where(
        xp.abs(length - h) < _FACE_TOL,
        1.0,
        xp.where(length < _FACE_TOL, 1.0 / eps, cut),
    )


def _node_coords(problem: Problem, i_idx, j_idx, dtype):
    # Namespace-agnostic: inherits numpy/jnp from the index arrays.
    x = (problem.x_min + i_idx.astype(dtype) * problem.h1)[:, None]
    y = (problem.y_min + j_idx.astype(dtype) * problem.h2)[None, :]
    return x, y


def coefficient_fields(problem: Problem, i_idx, j_idx, dtype=jnp.float64,
                       xp=jnp):
    """Edge coefficients a, b evaluated at the index mesh i_idx × j_idx.

    ``i_idx``/``j_idx`` are 1-D integer arrays of *global* grid indices; the
    result has shape (len(i_idx), len(j_idx)). Passing a sub-range builds a
    shard's local block, the vectorised equivalent of
    ``stage2-mpi/poisson_mpi_decomp.cpp:124-170``.
    """
    h1, h2, eps = problem.h1, problem.h2, problem.eps
    x, y = _node_coords(problem, i_idx, j_idx, dtype)
    la = segment_length_in_domain(
        x - 0.5 * h1, y - 0.5 * h2, y + 0.5 * h2, vertical=True, xp=xp
    )
    lb = segment_length_in_domain(
        y - 0.5 * h2, x - 0.5 * h1, x + 0.5 * h1, vertical=False, xp=xp
    )
    a = _blend(la, h2, eps, xp).astype(dtype)
    b = _blend(lb, h1, eps, xp).astype(dtype)
    return a, b


def rhs_field(problem: Problem, i_idx, j_idx, dtype=jnp.float64, xp=jnp):
    """RHS B = f_val · 1[node ∈ D] at the index mesh, zero outside the
    interior index range 1..M-1 × 1..N-1 (``stage0/Withoutopenmp1.cpp:57-60``).

    Note: a sharded caller must additionally zero its local halo-ring
    positions (whose *global* indices are interior but belong to a
    neighbouring shard) — see ``parallel.pcg_sharded._local_fields``.
    """
    x, y = _node_coords(problem, i_idx, j_idx, dtype)
    inside = is_in_domain(x, y)
    interior_mask = (
        (i_idx >= 1) & (i_idx <= problem.M - 1)
    )[:, None] & ((j_idx >= 1) & (j_idx <= problem.N - 1))[None, :]
    f = xp.asarray(problem.f_val, dtype)
    return xp.where(inside & interior_mask, f, xp.zeros((), dtype))


def build_fields(problem: Problem, dtype=jnp.float64, xp=jnp):
    """Full-grid fields a, b, B of shape (M+1, N+1).

    Row/column 0 of a and b are never read by the operators (the stencil only
    touches indices ≥ 1) but are filled with the same closed form for shape
    regularity.
    """
    i_idx = xp.arange(problem.M + 1)
    j_idx = xp.arange(problem.N + 1)
    a, b = coefficient_fields(problem, i_idx, j_idx, dtype, xp)
    rhs = rhs_field(problem, i_idx, j_idx, dtype, xp)
    return a, b, rhs


def analytic_solution(problem: Problem, i_idx=None, j_idx=None,
                      dtype=jnp.float64, xp=jnp):
    """Exact solution u = (1 − x² − 4y²)/10 inside D, 0 outside.

    Satisfies −Δu = 1 in D, u = 0 on ∂D — the accuracy control used in the
    reference's final report (``итоговый отчёт/Этап_4_1213.pdf`` p.1; no code
    for it survives in the reference repo, SURVEY §4.2)."""
    if i_idx is None:
        i_idx = xp.arange(problem.M + 1)
    if j_idx is None:
        j_idx = xp.arange(problem.N + 1)
    x, y = _node_coords(problem, i_idx, j_idx, dtype)
    val = (1.0 - x * x - 4.0 * y * y) / 10.0
    return xp.where(is_in_domain(x, y), val, xp.zeros((), dtype))
