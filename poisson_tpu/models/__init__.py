from poisson_tpu.models.fictitious_domain import (
    analytic_solution,
    build_fields,
    coefficient_fields,
    is_in_domain,
    rhs_field,
    segment_length_in_domain,
)

__all__ = [
    "analytic_solution",
    "build_fields",
    "coefficient_fields",
    "is_in_domain",
    "rhs_field",
    "segment_length_in_domain",
]
