"""Distributed PCG: ``shard_map`` over a 2D device mesh.

TPU-native re-design of the reference's MPI solver
(``solve_mpi``, ``stage2-mpi/poisson_mpi_decomp.cpp:356-460``; CUDA variant
``gradient_solver_mpi``, ``stage4-mpi+cuda/poisson_mpi_cuda_f.cu:688-983``):

- one SPMD program over the mesh instead of per-rank processes;
- halo exchange = ``ppermute`` ICI shifts per iteration (parallel.halo);
- the 3 per-iteration ``MPI_Allreduce`` scalars (``stage2:…cpp:412,435,439``)
  become ``lax.psum`` over both mesh axes;
- the δ-convergence test stays *inside* the device-resident while_loop —
  every shard computes the same psum'd scalar, so all break together
  (the reference's synchronized termination, ``stage2:…cpp:437-448``) with
  no host round-trip per iteration, unlike stage4's host-synchronous loop.

Shard layout: the reference's ``decompose_2d`` balances blocks differing by
≤1 (``stage2:…cpp:75-111``); SPMD wants identical block shapes, so the
(M-1)×(N-1) interior is padded up to (Px·m̂)×(Py·n̂), m̂=⌈(M-1)/Px⌉, and padded
cells are masked out of every operator and reduction. Real cells adjacent to
the padding read zeros there — identical to the global Dirichlet condition.

Setup modes:
- ``setup='host'`` (default): fields built once on the host in fp64 (numpy)
  and sharded as halo-inclusive blocks — the reference's CPU-setup pattern
  (``stage4:…cu:717``), keeping setup precision independent of device dtype.
- ``setup='device'``: every shard builds its own coefficient block + halo
  ring locally from closed-form geometry (the vectorised ``fic_reg_local``,
  ``stage2:…cpp:124-170``) — no host memory, no transfer; setup precision
  follows the device dtype (fp64 only with x64).

Precision: like the single-device solver, sub-64-bit dtypes default to the
symmetrically-scaled system (unit-diagonal Ã = D^{-1/2}AD^{-1/2}) — plain CG
on it is iterate-identical to Jacobi-PCG but keeps fp32 viable at fine grids
(see ``solvers.pcg.scaled_single_device_ops``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from poisson_tpu.config import Problem
from poisson_tpu.models.fictitious_domain import coefficient_fields, rhs_field
from poisson_tpu.ops.stencil import apply_A, apply_Dinv, diag_D, pad_interior
from poisson_tpu.parallel.halo import exchange_halos
from poisson_tpu.parallel.mesh import X_AXIS, Y_AXIS, block_size
from poisson_tpu.solvers.pcg import (
    PCGOps,
    PCGResult,
    pcg_loop,
    resolve_dtype,
    resolve_scaled,
)
from poisson_tpu.utils.compat import shard_map


def _owned_mask(problem: Problem, m_blk: int, n_blk: int, dtype):
    """Owned-interior mask for this shard: local ring excluded, padded
    global range excluded. Local index li ∈ 0..m̂+1 maps to global grid index
    gi = px·m̂ + li — the local↔global mapping of ``fic_reg_local``
    (``stage2:…cpp:124-170``)."""
    px = lax.axis_index(X_AXIS)
    py = lax.axis_index(Y_AXIS)
    gi = px * m_blk + jnp.arange(m_blk + 2)
    gj = py * n_blk + jnp.arange(n_blk + 2)
    own_i = (jnp.arange(m_blk + 2) >= 1) & (jnp.arange(m_blk + 2) <= m_blk)
    own_j = (jnp.arange(n_blk + 2) >= 1) & (jnp.arange(n_blk + 2) <= n_blk)
    in_i = (gi >= 1) & (gi <= problem.M - 1)
    in_j = (gj >= 1) & (gj <= problem.N - 1)
    mask = ((own_i & in_i)[:, None] & (own_j & in_j)[None, :]).astype(dtype)
    return mask, gi, gj


def _device_local_fields(problem: Problem, m_blk: int, n_blk: int, dtype,
                         scaled: bool):
    """On-device per-shard field build (setup='device')."""
    mask, gi, gj = _owned_mask(problem, m_blk, n_blk, dtype)
    a, b = coefficient_fields(problem, gi, gj, dtype)
    rhs = rhs_field(problem, gi, gj, dtype) * mask
    d = diag_D(a, b, problem.h1, problem.h2)
    if not scaled:
        # Padded to the full local grid so both setup modes hand _sharded_ops
        # the same aux layout (it re-slices the interior).
        return a, b, rhs, pad_interior(d), mask
    sc = pad_interior(1.0 / jnp.sqrt(d))
    rhs_scaled = rhs * sc
    return a, b, rhs_scaled, sc, mask


@functools.lru_cache(maxsize=8)
def _host_shard_blocks(problem: Problem, px_size: int, py_size: int,
                       m_blk: int, n_blk: int, dtype_name: str, scaled: bool):
    """Host fp64 field build sharded into stacked halo-inclusive blocks.

    Fields come from ``solvers.pcg.host_fields64`` (the shared setup
    derivation). Returns arrays of shape (Px·Py, m̂+2, n̂+2), leading axis in
    mesh order (x-major), to be consumed with in_specs=P(('x','y')).
    Cached so repeated solves pay for setup and transfer once.
    """
    from poisson_tpu.solvers.pcg import host_fields64

    dtype = jnp.dtype(dtype_name)
    a64, b64, rhs_use, aux64 = host_fields64(problem, scaled)

    gm = px_size * m_blk + 2
    gn = py_size * n_blk + 2

    def blocks(global_grid):
        full = np.zeros((gm, gn), np.float64)
        full[: global_grid.shape[0], : global_grid.shape[1]] = global_grid
        out = np.empty((px_size * py_size, m_blk + 2, n_blk + 2), np.float64)
        for px in range(px_size):
            for py in range(py_size):
                out[px * py_size + py] = full[
                    px * m_blk : px * m_blk + m_blk + 2,
                    py * n_blk : py * n_blk + n_blk + 2,
                ]
        return jnp.asarray(out, dtype)

    return blocks(a64), blocks(b64), blocks(rhs_use), blocks(aux64)


def _sharded_ops(problem: Problem, a, b, aux, mask, px_size: int,
                 py_size: int, scaled: bool) -> PCGOps:
    h1, h2 = problem.h1, problem.h2
    axes = (X_AXIS, Y_AXIS)

    def exchange(p):
        return exchange_halos(p, px_size, py_size)

    if scaled:
        sc = aux

        def op_apply_A(p):
            # Fold the halo refresh around the scaling: neighbours need the
            # *scaled* field sc·p, whose interior values they own.
            return apply_A(exchange(p * sc), a, b, h1, h2) * sc * mask

        op_dinv = lambda r: r  # unit diagonal after symmetric scaling
        op_sqnorm = lambda u: lax.psum(jnp.sum((u * sc) ** 2 * mask), axes)
        loop_exchange = lambda p: p
    else:
        d_int = aux[1:-1, 1:-1]

        def op_apply_A(p):
            return apply_A(p, a, b, h1, h2) * mask

        op_dinv = lambda r: apply_Dinv(r, d_int) * mask
        op_sqnorm = lambda u: lax.psum(jnp.sum(u * u * mask), axes)
        loop_exchange = exchange

    def dot(u, v):
        # At least one operand of every loop dot is masked (Ap, z, r),
        # so the plain local sum is the owned-interior sum.
        return lax.psum(jnp.sum(u * v), axes) * (h1 * h2)

    return PCGOps(
        apply_A=op_apply_A,
        apply_Dinv=op_dinv,
        dot=dot,
        sqnorm=op_sqnorm,
        exchange=loop_exchange,
    )


def _run_shard_batched(problem: Problem, a, b, rhs_stack, aux, mask,
                       px_size, py_size, scaled: bool):
    """The batch×mesh composition, per shard: the SAME masked vmapped
    body ``solvers.batched.pcg_loop_batched`` runs on every shard over
    a (B, m̂+2, n̂+2) stack of local RHS blocks — vmap INSIDE the shard
    is exactly "vmap outside shard_map" spelled SPMD: the mesh splits
    the grid, the batch axis rides whole on every device, and each
    member's psum'd reductions are per-member mesh scalars (the vmapped
    ``lax.psum`` reduces elementwise over the batch axis). Per-member
    convergence masking is untouched, so a member's stop flag and
    iteration count follow the exact batched-driver semantics; halo
    exchange and coefficient traffic are paid once per iteration for
    the whole batch (the amortization this composition exists for)."""
    from poisson_tpu.solvers.batched import pcg_loop_batched

    ops = _sharded_ops(problem, a, b, aux, mask, px_size, py_size, scaled)
    s = pcg_loop_batched(
        ops, rhs_stack,
        delta=problem.delta, max_iter=problem.iteration_cap,
        weighted_norm=problem.weighted_norm,
        h1=problem.h1, h2=problem.h2,
    )
    w = s.w * aux if scaled else s.w
    return w[:, 1:-1, 1:-1], s.k, s.diff, s.zr, s.flag


def shard_rhs_stack(rhs_stack, px_size: int, py_size: int, m_blk: int,
                    n_blk: int):
    """A (B, M+1, N+1) full-grid RHS stack as halo-inclusive per-shard
    blocks (Px·Py, B, m̂+2, n̂+2), leading axis in mesh order — the
    batched mirror of :func:`_host_shard_blocks`' layout, consumed with
    ``in_specs=P(('x','y'))``."""
    arr = np.asarray(rhs_stack)
    nb = arr.shape[0]
    gm = px_size * m_blk + 2
    gn = py_size * n_blk + 2
    full = np.zeros((nb, gm, gn), arr.dtype)
    full[:, : arr.shape[1], : arr.shape[2]] = arr
    out = np.empty((px_size * py_size, nb, m_blk + 2, n_blk + 2),
                   arr.dtype)
    for px in range(px_size):
        for py in range(py_size):
            out[px * py_size + py] = full[
                :,
                px * m_blk : px * m_blk + m_blk + 2,
                py * n_blk : py * n_blk + n_blk + 2,
            ]
    return jnp.asarray(out)


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3))
def solve_batched_sharded(problem: Problem, mesh: Mesh, dtype_name: str,
                          scaled: bool, a_blk, b_blk, rhs_blk, aux_blk):
    """One fused dispatch solving B right-hand sides on an N-device
    mesh (the ``solve_batched(mesh=)`` engine): compiled once per
    (bucket, grid, dtype, scaled, mesh shape) — coefficient blocks and
    the per-member RHS blocks are operands, so every padded request set
    of a bucket reuses the executable exactly like the single-device
    driver. Returns a batched :class:`PCGResult` (leading batch axis on
    ``w``/``iterations``/``diff``/``residual_dot``/``flag``)."""
    dtype = jnp.dtype(dtype_name)
    px_size = mesh.shape[X_AXIS]
    py_size = mesh.shape[Y_AXIS]
    m_blk = block_size(problem.M - 1, px_size)
    n_blk = block_size(problem.N - 1, py_size)

    def shard_fn(a, b, rhs, aux):
        a, b, aux = a[0], b[0], aux[0]
        rhs = rhs[0]                      # (B, m̂+2, n̂+2) local stack
        mask, _, _ = _owned_mask(problem, m_blk, n_blk, dtype)
        rhs = rhs * mask                  # broadcasts over the batch
        return _run_shard_batched(
            problem, a, b, rhs, aux, mask, px_size, py_size, scaled
        )

    spec = P((X_AXIS, Y_AXIS))
    w_int, k, diff, zr, flag = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(spec, spec, spec, spec),
        out_specs=(P(None, X_AXIS, Y_AXIS), P(), P(), P(), P()),
        check_vma=False,
    )(a_blk, b_blk, rhs_blk, aux_blk)
    w = jax.vmap(pad_interior)(
        w_int[:, : problem.M - 1, : problem.N - 1])
    return PCGResult(w=w, iterations=k, diff=diff, residual_dot=zr,
                     flag=flag, max_iterations=jnp.max(k))


def _run_shard(problem: Problem, a, b, rhs, aux, mask, px_size, py_size,
               scaled: bool):
    ops = _sharded_ops(problem, a, b, aux, mask, px_size, py_size, scaled)
    s = pcg_loop(
        ops, rhs,
        delta=problem.delta, max_iter=problem.iteration_cap,
        weighted_norm=problem.weighted_norm,
        h1=problem.h1, h2=problem.h2,
    )
    w = s.w * aux if scaled else s.w
    # Every shard returns its owned interior block; k/diff/zr/flag are
    # mesh-replicated scalars (the ops psum every reduction, so all shards
    # compute the same convergence/divergence verdict in step — the
    # reference's synchronized termination extended to failure modes).
    return w[1:-1, 1:-1], s.k, s.diff, s.zr, s.flag


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3))
def _solve_device_setup(problem: Problem, mesh: Mesh, dtype_name: str,
                        scaled: bool) -> PCGResult:
    dtype = jnp.dtype(dtype_name)
    px_size = mesh.shape[X_AXIS]
    py_size = mesh.shape[Y_AXIS]
    m_blk = block_size(problem.M - 1, px_size)
    n_blk = block_size(problem.N - 1, py_size)

    def shard_fn():
        a, b, rhs, aux, mask = _device_local_fields(
            problem, m_blk, n_blk, dtype, scaled
        )
        return _run_shard(
            problem, a, b, rhs, aux, mask, px_size, py_size, scaled
        )

    w_int, k, diff, zr, flag = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(),
        out_specs=(P(X_AXIS, Y_AXIS), P(), P(), P(), P()),
        check_vma=False,
    )()
    w = pad_interior(w_int[: problem.M - 1, : problem.N - 1])
    return PCGResult(w=w, iterations=k, diff=diff, residual_dot=zr, flag=flag)


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3))
def _solve_host_setup(problem: Problem, mesh: Mesh, dtype_name: str,
                      scaled: bool, a_blk, b_blk, rhs_blk, aux_blk
                      ) -> PCGResult:
    dtype = jnp.dtype(dtype_name)
    px_size = mesh.shape[X_AXIS]
    py_size = mesh.shape[Y_AXIS]
    m_blk = block_size(problem.M - 1, px_size)
    n_blk = block_size(problem.N - 1, py_size)

    def shard_fn(a, b, rhs, aux):
        a, b = a[0], b[0]
        rhs, aux = rhs[0], aux[0]
        mask, _, _ = _owned_mask(problem, m_blk, n_blk, dtype)
        rhs = rhs * mask
        return _run_shard(
            problem, a, b, rhs, aux, mask, px_size, py_size, scaled
        )

    spec = P((X_AXIS, Y_AXIS))
    w_int, k, diff, zr, flag = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(spec, spec, spec, spec),
        out_specs=(P(X_AXIS, Y_AXIS), P(), P(), P(), P()),
        check_vma=False,
    )(a_blk, b_blk, rhs_blk, aux_blk)
    w = pad_interior(w_int[: problem.M - 1, : problem.N - 1])
    return PCGResult(w=w, iterations=k, diff=diff, residual_dot=zr, flag=flag)


def pcg_solve_sharded(problem: Problem, mesh: Mesh, dtype=None, scaled=None,
                      setup: str = "host") -> PCGResult:
    """Distributed solve over ``mesh`` (the stage2/3/4 workload, SURVEY §3.2-3.3).

    P=1 meshes reproduce the single-device path; any Px×Py works, matching
    the reference's size-agnostic MPI programs. See module docstring for
    ``setup`` and precision policy.
    """
    dtype_name = resolve_dtype(dtype)
    use_scaled = resolve_scaled(scaled, dtype_name)
    if setup == "device":
        return _solve_device_setup(problem, mesh, dtype_name, use_scaled)
    if setup != "host":
        raise ValueError(f"setup must be 'host' or 'device', got {setup!r}")
    px_size = mesh.shape[X_AXIS]
    py_size = mesh.shape[Y_AXIS]
    m_blk = block_size(problem.M - 1, px_size)
    n_blk = block_size(problem.N - 1, py_size)
    a_blk, b_blk, rhs_blk, aux_blk = _host_shard_blocks(
        problem, px_size, py_size, m_blk, n_blk, dtype_name, use_scaled
    )
    return _solve_host_setup(
        problem, mesh, dtype_name, use_scaled, a_blk, b_blk, rhs_blk, aux_blk
    )
