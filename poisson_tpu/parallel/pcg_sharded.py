"""Distributed PCG: ``shard_map`` over a 2D device mesh.

TPU-native re-design of the reference's MPI solver
(``solve_mpi``, ``stage2-mpi/poisson_mpi_decomp.cpp:356-460``; CUDA variant
``gradient_solver_mpi``, ``stage4-mpi+cuda/poisson_mpi_cuda_f.cu:688-983``):

- one SPMD program over the mesh instead of per-rank processes;
- each shard builds its own coefficient block + halo ring locally from
  closed-form geometry (the vectorised ``fic_reg_local``,
  ``stage2:…cpp:124-170``) — no broadcast, no scatter;
- halo exchange = 4 ``ppermute`` ICI shifts per iteration (parallel.halo);
- the 3 per-iteration ``MPI_Allreduce`` scalars (``stage2:…cpp:412,435,439``)
  become ``lax.psum`` over both mesh axes;
- the δ-convergence test stays *inside* the device-resident while_loop —
  every shard computes the same psum'd scalar, so all break together
  (the reference's synchronized termination, ``stage2:…cpp:437-448``) with
  no host round-trip per iteration, unlike stage4's host-synchronous loop.

Shard layout: the reference's ``decompose_2d`` balances blocks differing by
≤1 (``stage2:…cpp:75-111``); SPMD wants identical block shapes, so the
(M-1)×(N-1) interior is padded up to (Px·m̂)×(Py·n̂), m̂=⌈(M-1)/Px⌉, and padded
cells are masked out of every operator and reduction. Real cells adjacent to
the padding read zeros there — identical to the global Dirichlet condition.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from poisson_tpu.config import Problem
from poisson_tpu.models.fictitious_domain import coefficient_fields, rhs_field
from poisson_tpu.ops.stencil import apply_A, apply_Dinv, diag_D, pad_interior
from poisson_tpu.parallel.halo import exchange_halos
from poisson_tpu.parallel.mesh import X_AXIS, Y_AXIS, block_size
from poisson_tpu.solvers.pcg import PCGOps, PCGResult, pcg_loop


def _local_fields(problem: Problem, m_blk: int, n_blk: int, dtype):
    """This shard's (m̂+2)×(n̂+2) blocks of a, b, B, D and the interior mask.

    Local index li ∈ 0..m̂+1 maps to global grid index gi = px·m̂ + li
    (gi=0 ⇒ li on the Dirichlet/pad ring), the same local↔global mapping as
    ``fic_reg_local`` (``stage2:…cpp:124-170``).
    """
    px = lax.axis_index(X_AXIS)
    py = lax.axis_index(Y_AXIS)
    gi = px * m_blk + jnp.arange(m_blk + 2)
    gj = py * n_blk + jnp.arange(n_blk + 2)

    a, b = coefficient_fields(problem, gi, gj, dtype)
    # Owned-interior mask: local ring excluded, padded global range excluded.
    own_i = (jnp.arange(m_blk + 2) >= 1) & (jnp.arange(m_blk + 2) <= m_blk)
    own_j = (jnp.arange(n_blk + 2) >= 1) & (jnp.arange(n_blk + 2) <= n_blk)
    in_i = (gi >= 1) & (gi <= problem.M - 1)
    in_j = (gj >= 1) & (gj <= problem.N - 1)
    mask = ((own_i & in_i)[:, None] & (own_j & in_j)[None, :]).astype(dtype)

    rhs = rhs_field(problem, gi, gj, dtype) * mask
    d = diag_D(a, b, problem.h1, problem.h2)
    return a, b, rhs, d, mask


def _sharded_ops(problem: Problem, a, b, d, mask, px_size: int,
                 py_size: int) -> PCGOps:
    h1, h2 = problem.h1, problem.h2
    axes = (X_AXIS, Y_AXIS)

    def masked_apply_A(p):
        return apply_A(p, a, b, h1, h2) * mask

    def masked_dinv(r):
        return apply_Dinv(r, d) * mask

    def dot(u, v):
        # mask is already baked into every state array (zero on pad/halo),
        # so the plain local sum is the owned-interior sum.
        return lax.psum(jnp.sum(u * v), axes) * (h1 * h2)

    def sqnorm(u):
        return lax.psum(jnp.sum(u * u * mask), axes)

    def exchange(p):
        return exchange_halos(p, px_size, py_size)

    return PCGOps(
        apply_A=masked_apply_A,
        apply_Dinv=masked_dinv,
        dot=dot,
        sqnorm=sqnorm,
        exchange=exchange,
    )


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def _solve_sharded(problem: Problem, mesh: Mesh, dtype_name: str) -> PCGResult:
    dtype = jnp.dtype(dtype_name)
    px_size = mesh.shape[X_AXIS]
    py_size = mesh.shape[Y_AXIS]
    m_blk = block_size(problem.M - 1, px_size)
    n_blk = block_size(problem.N - 1, py_size)

    def shard_fn():
        a, b, rhs, d, mask = _local_fields(problem, m_blk, n_blk, dtype)
        ops = _sharded_ops(problem, a, b, d, mask, px_size, py_size)
        s = pcg_loop(
            ops, rhs,
            delta=problem.delta, max_iter=problem.iteration_cap,
            weighted_norm=problem.weighted_norm,
            h1=problem.h1, h2=problem.h2,
        )
        # Every shard returns its owned interior block; k/diff/zr are
        # mesh-replicated scalars.
        return s.w[1:-1, 1:-1], s.k, s.diff, s.zr

    w_int, k, diff, zr = jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(),
        out_specs=(P(X_AXIS, Y_AXIS), P(), P(), P()),
        check_vma=False,
    )()

    # Unpad to the real interior and restore the Dirichlet ring.
    w = pad_interior(w_int[: problem.M - 1, : problem.N - 1])
    return PCGResult(w=w, iterations=k, diff=diff, residual_dot=zr)


def pcg_solve_sharded(problem: Problem, mesh: Mesh,
                      dtype=jnp.float64) -> PCGResult:
    """Distributed solve over ``mesh`` (the stage2/3/4 workload, SURVEY §3.2-3.3).

    P=1 meshes reproduce the single-device path exactly; any Px×Py works,
    matching the reference's size-agnostic MPI programs.
    """
    return _solve_sharded(problem, mesh, jnp.dtype(dtype).name)
