"""Distributed fused-Pallas PCG: stage4's full combination, TPU-native.

The reference's final stage pairs accelerator kernels with distribution
(MPI+CUDA, ``stage4-mpi+cuda/poisson_mpi_cuda_f.cu:688-983``): CUDA kernels
per rank, host-staged halo exchange on the search direction p, Allreduce'd
scalars. This module is that combination re-designed for a TPU pod: the
fused two-sweep Pallas iteration (``ops.pallas_cg``) runs per shard inside
``shard_map`` over a 2D mesh, with ``ppermute`` halos and ``psum`` scalars.

**The halo exchange moves from p to r.** The reference refreshes p's ghost
ring every iteration because the stencil consumes p. But in the fused
restructuring the direction update ``p ← z + β·p`` runs *inside* the
stencil sweep, so a shard can compute its neighbour's edge values of the
new p by itself — z (= r on the scaled system) and the old p at the halo
ring suffice, and β is mesh-replicated. By induction the p halos stay
fresh without ever being communicated, provided r's halo ring is refreshed
once per iteration (r's halo cannot be recomputed locally: it would need a
second ghost ring for Ap). Per iteration the wire traffic is therefore the
same as the reference's — four thin ``ppermute`` slices (of r, not p) and
three ``psum`` scalars — while the arithmetic stays two HBM sweeps.

Shard canvas layout (cf. the single-device canvas, ``ops.pallas_cg``):

  - the shard owns m̂ interior rows × n̂ interior columns, with
    m̂ = ⌈(M−1)/Px⌉ rounded up to a multiple of the strip height bm (so the
    strip grid tiles the owned band exactly and the halo rows fall in the
    guard bands, outside every kernel reduction);
  - canvas row HALO+li ↔ global grid row ix·m̂+1+li; canvas column lj ↔
    global grid column iy·n̂+lj (column 0 / n̂+1 are the halo columns);
  - halo *columns* live inside the summed band, so kernel reductions take a
    (1, C) column mask; halo *rows* sit outside the written band and the
    guard rows are absorbed by the kernels' band gating;
  - canvas columns beyond n̂+1 (lane padding) are zeroed in every
    coefficient canvas — on a shard they would otherwise alias a further
    neighbour's data (the global grid continues past the halo).

Correctness of the zero-padded decomposition follows the same induction as
``parallel.pcg_sharded``: padded rows/columns have zero scaled coefficients
and zero RHS, so p, Ap, r stay identically zero there through every sweep.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from poisson_tpu.config import Problem
from poisson_tpu.ops.pallas_cg import (
    HALO,
    LANE,
    SUBLANE,
    Canvas,
    direction_and_stencil,
    fused_update,
    diagonal_residual_canvas,
    scaled_stencil_fields,
    strip_height,
)
from poisson_tpu.parallel.mesh import X_AXIS, Y_AXIS
from poisson_tpu.solvers.pcg import PCGResult, _DENOM_TOL

_AXES = (X_AXIS, Y_AXIS)


class ShardSpec(NamedTuple):
    """Static per-shard canvas geometry (hashable; jit static arg)."""

    cv: Canvas
    m_blk: int   # owned interior rows per shard (= cv.nb · cv.bm)
    n_blk: int   # owned interior cols per shard


def shard_spec(problem: Problem, px: int, py: int,
               bm: int | None = None) -> ShardSpec:
    n_blk = -(-(problem.N - 1) // py)
    cols = ((n_blk + 2 + LANE - 1) // LANE) * LANE
    if bm is None:
        bm = strip_height(cols, -(-(problem.M - 1) // px))
    if bm <= 0 or bm % SUBLANE != 0:
        raise ValueError(f"bm must be a positive multiple of {SUBLANE}, got {bm}")
    # Owned rows rounded up to the strip height: strips tile the owned band
    # exactly, so the halo rows stay outside every kernel reduction.
    m_min = -(-(problem.M - 1) // px)
    nb = -(-m_min // bm)
    m_blk = nb * bm
    cv = Canvas(bm=bm, nb=nb, rows=nb * bm + 2 * HALO, cols=cols)
    return ShardSpec(cv=cv, m_blk=m_blk, n_blk=n_blk)


@functools.lru_cache(maxsize=8)
def _shard_canvases(problem: Problem, px: int, py: int, spec: ShardSpec,
                    dtype_name: str):
    """Host fp64 setup → stacked per-shard canvases (mesh order, x-major).

    Returns (cs, cw, rhs, sc2) of shape (P, R, C), sc_int of shape
    (P, m̂, n̂) for solution extraction, and the (1, C) column mask."""
    cv = spec.cv
    m_blk, n_blk = spec.m_blk, spec.n_blk
    dtype = jnp.dtype(dtype_name)
    M, N = problem.M, problem.N

    gcs, gcw, sc2_64, rhs64, sc64 = scaled_stencil_fields(problem)

    # One zero-padded global scratch big enough for every shard's
    # (row0 + canvas extent) slice; canvas row HALO-1 maps to global grid
    # row ix·m̂, canvas col 0 to global grid col iy·n̂.
    height = (px - 1) * m_blk + (cv.rows - (HALO - 1)) + 1
    width = (py - 1) * n_blk + cv.cols + 1
    big = np.zeros((max(height, M + 1), max(width, N + 1)), np.float64)

    def stacked(field, zero_pad_cols: bool, zero_halo_cols: bool = False):
        big[:] = 0.0
        big[: M + 1, : N + 1] = field
        out = np.zeros((px * py, cv.rows, cv.cols), np.float64)
        for ix in range(px):
            for iy in range(py):
                sl = big[
                    ix * m_blk : ix * m_blk + cv.rows - (HALO - 1),
                    iy * n_blk : iy * n_blk + cv.cols,
                ]
                out[ix * py + iy, HALO - 1 :, :] = sl
        if zero_pad_cols:
            out[:, :, n_blk + 2 :] = 0.0
        if zero_halo_cols:
            out[:, :, 0] = 0.0
            out[:, :, n_blk + 1] = 0.0
        return out

    cs_st = stacked(gcs, zero_pad_cols=True)
    cw_st = stacked(gcw, zero_pad_cols=True)
    # Diagonal residual per shard, from its own canvases (fp64) — the
    # difference-form stencil weight (ops.pallas_cg.diagonal_residual_canvas).
    g_st = np.stack([
        diagonal_residual_canvas(cs_st[s], cw_st[s])
        for s in range(px * py)
    ])
    # rhs keeps real values in its halo ring: that ring seeds r's (and via
    # p0 = r0, p's) fresh halos at iteration 0.
    rhs_st = stacked(rhs64, zero_pad_cols=True)
    # sc2 is a pure reduction weight: restrict it to the owned interior.
    sc2_st = stacked(sc2_64, zero_pad_cols=True, zero_halo_cols=True)

    sc_int = np.zeros((px * py, m_blk, n_blk), np.float64)
    for ix in range(px):
        for iy in range(py):
            blk = sc64[
                1 + ix * m_blk : 1 + ix * m_blk + m_blk,
                1 + iy * n_blk : 1 + iy * n_blk + n_blk,
            ]
            sc_int[ix * py + iy, : blk.shape[0], : blk.shape[1]] = blk
    sc_int = jnp.asarray(sc_int, dtype)

    colmask = np.zeros((1, cv.cols), np.float64)
    colmask[0, 1 : n_blk + 1] = 1.0
    as_dev = lambda x: jnp.asarray(x, dtype)
    return (as_dev(cs_st), as_dev(cw_st), as_dev(g_st), as_dev(rhs_st),
            as_dev(sc2_st), sc_int, as_dev(colmask))


class _State(NamedTuple):
    k: jnp.ndarray
    done: jnp.ndarray
    w: jnp.ndarray
    r: jnp.ndarray
    p: jnp.ndarray
    zr: jnp.ndarray
    beta: jnp.ndarray
    diff: jnp.ndarray


def _exchange_r_halo(r, spec: ShardSpec, px: int, py: int):
    """Refresh r's halo ring: 4 thin ppermute slices (the reference's four
    MPI messages, ``stage2:…cpp:241-347`` — but of r, see module doc).
    Mesh-edge shards receive ppermute's zero fill = Dirichlet data."""
    from poisson_tpu.parallel.halo import _shift_down, _shift_up

    lo, hi = HALO, HALO + spec.m_blk
    top = _shift_down(r[hi - 1, :], X_AXIS, px)
    bot = _shift_up(r[lo, :], X_AXIS, px)
    r = r.at[lo - 1, :].set(top).at[hi, :].set(bot)
    left = _shift_down(r[:, spec.n_blk], Y_AXIS, py)
    right = _shift_up(r[:, 1], Y_AXIS, py)
    return r.at[:, 0].set(left).at[:, spec.n_blk + 1].set(right)


def _run_shard(problem: Problem, spec: ShardSpec, px: int, py: int,
               interpret: bool, cs, cw, g, rhs, sc2, sc_int, colmask):
    cv = spec.cv
    dtype = rhs.dtype
    h1h2 = jnp.float32(problem.h1 * problem.h2)
    norm_w = h1h2 if problem.weighted_norm else jnp.float32(1.0)
    band = (HALO - 1, HALO + spec.m_blk + 1)  # owned rows + halo ring
    lo, hi = HALO, HALO + spec.m_blk

    def psum(x):
        return lax.psum(x, _AXES)

    def body(s: _State) -> _State:
        beta = jnp.reshape(s.beta, (1, 1)).astype(dtype)
        pn, ap, denom_part = direction_and_stencil(
            cv, beta, s.r, s.p, cs, cw, g, interpret=interpret,
            band=band, colmask=colmask,
        )
        # Halo rows of the new direction: identical to what the row
        # neighbour computed for its own edge (z = r and old-p halos are
        # fresh, β is replicated). Halo *columns* were computed in-sweep.
        b = s.beta.astype(dtype)
        pn = pn.at[lo - 1, :].set(s.r[lo - 1, :] + b * s.p[lo - 1, :])
        pn = pn.at[hi, :].set(s.r[hi, :] + b * s.p[hi, :])

        denom = psum(jnp.sum(denom_part)) * h1h2
        degenerate = jnp.abs(denom) < _DENOM_TOL
        alpha32 = jnp.where(
            degenerate, 0.0, s.zr / jnp.where(degenerate, 1.0, denom)
        )
        alpha = jnp.reshape(alpha32, (1, 1)).astype(dtype)

        w, r, diff_part, zr_part = fused_update(
            cv, alpha, pn, ap, sc2, s.w, s.r, interpret=interpret,
            colmask=colmask,
        )
        diff = jnp.abs(alpha32) * jnp.sqrt(psum(jnp.sum(diff_part)) * norm_w)
        zr_new = psum(jnp.sum(zr_part)) * h1h2
        converged = diff < problem.delta

        r = _exchange_r_halo(r, spec, px, py)
        return _State(
            k=s.k + 1,
            done=degenerate | converged,
            w=w, r=r, p=pn,
            zr=zr_new,
            beta=zr_new / jnp.where(s.zr == 0.0, 1.0, s.zr),
            diff=diff,
        )

    def cond(s: _State):
        return (~s.done) & (s.k < problem.iteration_cap)

    zeros = jnp.zeros((cv.rows, cv.cols), dtype)
    center = rhs[lo:hi, :].astype(jnp.float32)
    zr0 = psum(jnp.sum(center * center * colmask.astype(jnp.float32))) * h1h2
    init = _State(
        k=jnp.zeros((), jnp.int32),
        done=jnp.asarray(False),
        w=zeros, r=rhs, p=zeros,
        zr=zr0,
        beta=jnp.float32(0.0),   # first iteration: p ← z + 0·p = z₀ = r₀
        diff=jnp.float32(jnp.inf),
    )
    s = lax.while_loop(cond, body, init)
    w_own = s.w[lo:hi, 1 : spec.n_blk + 1] * sc_int
    return w_own, s.k, s.diff, s.zr


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3))
def _solve(problem: Problem, mesh: Mesh, spec: ShardSpec, interpret: bool,
           cs, cw, g, rhs, sc2, sc_int, colmask) -> PCGResult:
    px = mesh.shape[X_AXIS]
    py = mesh.shape[Y_AXIS]

    def shard_fn(cs_b, cw_b, g_b, rhs_b, sc2_b, sc_int_b, colmask_b):
        return _run_shard(
            problem, spec, px, py, interpret,
            cs_b[0], cw_b[0], g_b[0], rhs_b[0], sc2_b[0], sc_int_b[0],
            colmask_b,
        )

    stacked = P((X_AXIS, Y_AXIS))
    w_int, k, diff, zr = jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(stacked, stacked, stacked, stacked, stacked, stacked, P()),
        out_specs=(P(X_AXIS, Y_AXIS), P(), P(), P()),
        check_vma=False,
    )(cs, cw, g, rhs, sc2, sc_int, colmask)
    w = jnp.pad(w_int[: problem.M - 1, : problem.N - 1], 1)
    return PCGResult(w=w, iterations=k, diff=diff, residual_dot=zr)


def pallas_cg_solve_sharded(problem: Problem, mesh: Mesh,
                            bm: int | None = None,
                            interpret: bool | None = None,
                            dtype_name: str = "float32",
                            rhs_gate=None) -> PCGResult:
    """Distributed solve on the fused Pallas path (fp32, scaled system).

    The stage4-equivalent configuration: per-shard fused kernels + mesh
    collectives. ``interpret`` defaults to True off-TPU so the kernels run
    (and are tested) on the virtual CPU mesh. ``rhs_gate`` as in
    ``pallas_cg_solve``.
    """
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    px = mesh.shape[X_AXIS]
    py = mesh.shape[Y_AXIS]
    spec = shard_spec(problem, px, py, bm)
    cs, cw, g, rhs, sc2, sc_int, colmask = _shard_canvases(
        problem, px, py, spec, dtype_name
    )
    if rhs_gate is not None:
        rhs = rhs * jnp.asarray(rhs_gate, rhs.dtype)
    return _solve(problem, mesh, spec, interpret,
                  cs, cw, g, rhs, sc2, sc_int, colmask)
