"""Distributed fused-Pallas PCG: stage4's full combination, TPU-native.

The reference's final stage pairs accelerator kernels with distribution
(MPI+CUDA, ``stage4-mpi+cuda/poisson_mpi_cuda_f.cu:688-983``): CUDA kernels
per rank, host-staged halo exchange on the search direction p, Allreduce'd
scalars. This module is that combination re-designed for a TPU pod: the
fused two-sweep Pallas iteration (``ops.pallas_cg``) runs per shard inside
``shard_map`` over a 2D mesh, with ``ppermute`` halos and ``psum`` scalars.

**The halo exchange moves from p to r.** The reference refreshes p's ghost
ring every iteration because the stencil consumes p. But in the fused
restructuring the direction update ``p ← z + β·p`` runs *inside* the
stencil sweep, so a shard can compute its neighbour's edge values of the
new p by itself — z (= r on the scaled system) and the old p at the halo
ring suffice, and β is mesh-replicated. By induction the p halos stay
fresh without ever being communicated, provided r's halo ring is refreshed
once per iteration (r's halo cannot be recomputed locally: it would need a
second ghost ring for Ap). Per iteration the wire traffic is therefore the
same as the reference's — four thin ``ppermute`` slices (of r, not p) and
three ``psum`` scalars — while the arithmetic stays two HBM sweeps.

Shard canvas layout (cf. the single-device canvas, ``ops.pallas_cg``):

  - the shard owns m̂ interior rows × n̂ interior columns, with
    m̂ = ⌈(M−1)/Px⌉ rounded up to a multiple of the strip height bm (so the
    strip grid tiles the owned band exactly and the halo rows fall in the
    guard bands, outside every kernel reduction);
  - canvas row HALO+li ↔ global grid row ix·m̂+1+li; canvas column lj ↔
    global grid column iy·n̂+lj (column 0 / n̂+1 are the halo columns);
  - halo *columns* live inside the summed band, so kernel reductions take a
    (1, C) column mask; halo *rows* sit outside the written band and the
    guard rows are absorbed by the kernels' band gating;
  - canvas columns beyond n̂+1 (lane padding) are zeroed in every
    coefficient canvas — on a shard they would otherwise alias a further
    neighbour's data (the global grid continues past the halo).

Correctness of the zero-padded decomposition follows the same induction as
``parallel.pcg_sharded``: padded rows/columns have zero scaled coefficients
and zero RHS, so p, Ap, r stay identically zero there through every sweep.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from poisson_tpu.config import Problem
from poisson_tpu.ops.pallas_cg import (
    HALO,
    LANE,
    SUBLANE,
    Canvas,
    _resolve_serial,
    direction_and_stencil,
    fused_update,
    diagonal_residual_canvas,
    scaled_stencil_fields,
    strip_height,
)
from poisson_tpu.parallel.mesh import X_AXIS, Y_AXIS
from poisson_tpu.solvers.pcg import PCGResult, _DENOM_TOL
from poisson_tpu.utils.compat import shard_map

_AXES = (X_AXIS, Y_AXIS)


class ShardSpec(NamedTuple):
    """Static per-shard canvas geometry (hashable; jit static arg)."""

    cv: Canvas
    m_blk: int   # owned interior rows per shard (= cv.nb · cv.bm)
    n_blk: int   # owned interior cols per shard


def shard_spec(problem: Problem, px: int, py: int,
               bm: int | None = None) -> ShardSpec:
    n_blk = -(-(problem.N - 1) // py)
    cols = ((n_blk + 2 + LANE - 1) // LANE) * LANE
    if bm is None:
        bm = strip_height(cols, -(-(problem.M - 1) // px))
    if bm <= 0 or bm % SUBLANE != 0:
        raise ValueError(f"bm must be a positive multiple of {SUBLANE}, got {bm}")
    # Owned rows rounded up to the strip height: strips tile the owned band
    # exactly, so the halo rows stay outside every kernel reduction.
    m_min = -(-(problem.M - 1) // px)
    nb = -(-m_min // bm)
    m_blk = nb * bm
    cv = Canvas(bm=bm, nb=nb, rows=nb * bm + 2 * HALO, cols=cols)
    return ShardSpec(cv=cv, m_blk=m_blk, n_blk=n_blk)


@functools.lru_cache(maxsize=8)
def _shard_canvases(problem: Problem, px: int, py: int, spec: ShardSpec,
                    dtype_name: str):
    """Host fp64 setup → stacked per-shard canvases (mesh order, x-major).

    Returns (cs, cw, rhs, sc2) of shape (P, R, C), sc_int of shape
    (P, m̂, n̂) for solution extraction, and the (1, C) column mask."""
    cv = spec.cv
    m_blk, n_blk = spec.m_blk, spec.n_blk
    dtype = jnp.dtype(dtype_name)
    M, N = problem.M, problem.N

    gcs, gcw, sc2_64, rhs64, sc64 = scaled_stencil_fields(problem)

    # One zero-padded global scratch big enough for every shard's
    # (row0 + canvas extent) slice; canvas row HALO-1 maps to global grid
    # row ix·m̂, canvas col 0 to global grid col iy·n̂.
    height = (px - 1) * m_blk + (cv.rows - (HALO - 1)) + 1
    width = (py - 1) * n_blk + cv.cols + 1
    big = np.zeros((max(height, M + 1), max(width, N + 1)), np.float64)

    def stacked(field, zero_pad_cols: bool, zero_halo_cols: bool = False):
        big[:] = 0.0
        big[: M + 1, : N + 1] = field
        out = np.zeros((px * py, cv.rows, cv.cols), np.float64)
        for ix in range(px):
            for iy in range(py):
                sl = big[
                    ix * m_blk : ix * m_blk + cv.rows - (HALO - 1),
                    iy * n_blk : iy * n_blk + cv.cols,
                ]
                out[ix * py + iy, HALO - 1 :, :] = sl
        if zero_pad_cols:
            out[:, :, n_blk + 2 :] = 0.0
        if zero_halo_cols:
            out[:, :, 0] = 0.0
            out[:, :, n_blk + 1] = 0.0
        return out

    cs_st = stacked(gcs, zero_pad_cols=True)
    cw_st = stacked(gcw, zero_pad_cols=True)
    # Diagonal residual per shard, from its own canvases (fp64) — the
    # difference-form stencil weight (ops.pallas_cg.diagonal_residual_canvas).
    g_st = np.stack([
        diagonal_residual_canvas(cs_st[s], cw_st[s])
        for s in range(px * py)
    ])
    # rhs keeps real values in its halo ring: that ring seeds r's (and via
    # p0 = r0, p's) fresh halos at iteration 0.
    rhs_st = stacked(rhs64, zero_pad_cols=True)
    # sc2 is a pure reduction weight: restrict it to the owned interior.
    sc2_st = stacked(sc2_64, zero_pad_cols=True, zero_halo_cols=True)

    sc_int = np.zeros((px * py, m_blk, n_blk), np.float64)
    for ix in range(px):
        for iy in range(py):
            blk = sc64[
                1 + ix * m_blk : 1 + ix * m_blk + m_blk,
                1 + iy * n_blk : 1 + iy * n_blk + n_blk,
            ]
            sc_int[ix * py + iy, : blk.shape[0], : blk.shape[1]] = blk
    sc_int = jnp.asarray(sc_int, dtype)

    colmask = np.zeros((1, cv.cols), np.float64)
    colmask[0, 1 : n_blk + 1] = 1.0
    as_dev = lambda x: jnp.asarray(x, dtype)
    return (as_dev(cs_st), as_dev(cw_st), as_dev(g_st), as_dev(rhs_st),
            as_dev(sc2_st), sc_int, as_dev(colmask))


class _State(NamedTuple):
    k: jnp.ndarray
    done: jnp.ndarray
    w: jnp.ndarray
    r: jnp.ndarray
    p: jnp.ndarray
    zr: jnp.ndarray
    beta: jnp.ndarray
    diff: jnp.ndarray


def _exchange_r_halo(r, spec: ShardSpec, px: int, py: int):
    """Refresh r's halo ring: 4 thin ppermute slices (the reference's four
    MPI messages, ``stage2:…cpp:241-347`` — but of r, see module doc).
    Mesh-edge shards receive ppermute's zero fill = Dirichlet data."""
    from poisson_tpu.parallel.halo import _shift_down, _shift_up

    lo, hi = HALO, HALO + spec.m_blk
    top = _shift_down(r[hi - 1, :], X_AXIS, px)
    bot = _shift_up(r[lo, :], X_AXIS, px)
    r = r.at[lo - 1, :].set(top).at[hi, :].set(bot)
    left = _shift_down(r[:, spec.n_blk], Y_AXIS, py)
    right = _shift_up(r[:, 1], Y_AXIS, py)
    return r.at[:, 0].set(left).at[:, spec.n_blk + 1].set(right)


def _make_shard_body(problem: Problem, spec: ShardSpec, px: int, py: int,
                     interpret: bool, cs, cw, g, sc2, colmask, dtype,
                     parallel: bool = False, serial: bool = False):
    """One fused sharded iteration as a pure state→state function — shared
    by the convergence while_loop and the chunked checkpointed solve."""
    cv = spec.cv
    h1h2 = jnp.float32(problem.h1 * problem.h2)
    norm_w = h1h2 if problem.weighted_norm else jnp.float32(1.0)
    band = (HALO - 1, HALO + spec.m_blk + 1)  # owned rows + halo ring
    lo, hi = HALO, HALO + spec.m_blk

    def psum(x):
        return lax.psum(x, _AXES)

    def body(s: _State) -> _State:
        beta = jnp.reshape(s.beta, (1, 1)).astype(dtype)
        pn, ap, denom_part = direction_and_stencil(
            cv, beta, s.r, s.p, cs, cw, g, interpret=interpret,
            band=band, colmask=colmask, parallel=parallel, serial=serial,
        )
        # Halo rows of the new direction: identical to what the row
        # neighbour computed for its own edge (z = r and old-p halos are
        # fresh, β is replicated). Halo *columns* were computed in-sweep.
        b = s.beta.astype(dtype)
        pn = pn.at[lo - 1, :].set(s.r[lo - 1, :] + b * s.p[lo - 1, :])
        pn = pn.at[hi, :].set(s.r[hi, :] + b * s.p[hi, :])

        denom = psum(jnp.sum(denom_part)) * h1h2
        degenerate = jnp.abs(denom) < _DENOM_TOL
        alpha32 = jnp.where(
            degenerate, 0.0, s.zr / jnp.where(degenerate, 1.0, denom)
        )
        alpha = jnp.reshape(alpha32, (1, 1)).astype(dtype)

        w, r, diff_part, zr_part = fused_update(
            cv, alpha, pn, ap, sc2, s.w, s.r, interpret=interpret,
            colmask=colmask, parallel=parallel, serial=serial,
        )
        diff = jnp.abs(alpha32) * jnp.sqrt(psum(jnp.sum(diff_part)) * norm_w)
        zr_new = psum(jnp.sum(zr_part)) * h1h2
        converged = diff < problem.delta

        r = _exchange_r_halo(r, spec, px, py)
        return _State(
            k=s.k + 1,
            done=degenerate | converged,
            w=w, r=r, p=pn,
            zr=zr_new,
            beta=zr_new / jnp.where(s.zr == 0.0, 1.0, s.zr),
            diff=diff,
        )

    return body


def _shard_init(problem: Problem, spec: ShardSpec, rhs, colmask) -> _State:
    """w=0, r=b̃ (halo ring seeded by the rhs canvas), p=0 with β=0."""
    cv = spec.cv
    lo, hi = HALO, HALO + spec.m_blk
    h1h2 = jnp.float32(problem.h1 * problem.h2)
    zeros = jnp.zeros((cv.rows, cv.cols), rhs.dtype)
    center = rhs[lo:hi, :].astype(jnp.float32)
    zr0 = lax.psum(
        jnp.sum(center * center * colmask.astype(jnp.float32)), _AXES
    ) * h1h2
    return _State(
        k=jnp.zeros((), jnp.int32),
        done=jnp.asarray(False),
        w=zeros, r=rhs, p=zeros,
        zr=zr0,
        beta=jnp.float32(0.0),   # first iteration: p ← z + 0·p = z₀ = r₀
        diff=jnp.float32(jnp.inf),
    )


def _run_shard(problem: Problem, spec: ShardSpec, px: int, py: int,
               interpret: bool, cs, cw, g, rhs, sc2, sc_int, colmask,
               parallel: bool = False, serial: bool = False):
    lo, hi = HALO, HALO + spec.m_blk
    body = _make_shard_body(problem, spec, px, py, interpret,
                            cs, cw, g, sc2, colmask, rhs.dtype, parallel,
                            serial)

    def cond(s: _State):
        return (~s.done) & (s.k < problem.iteration_cap)

    s = lax.while_loop(cond, body, _shard_init(problem, spec, rhs, colmask))
    w_own = s.w[lo:hi, 1 : spec.n_blk + 1] * sc_int
    return w_own, s.k, s.diff, s.zr


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 11, 12))
def _solve(problem: Problem, mesh: Mesh, spec: ShardSpec, interpret: bool,
           cs, cw, g, rhs, sc2, sc_int, colmask,
           parallel: bool = False, serial: bool = False) -> PCGResult:
    px = mesh.shape[X_AXIS]
    py = mesh.shape[Y_AXIS]

    def shard_fn(cs_b, cw_b, g_b, rhs_b, sc2_b, sc_int_b, colmask_b):
        return _run_shard(
            problem, spec, px, py, interpret,
            cs_b[0], cw_b[0], g_b[0], rhs_b[0], sc2_b[0], sc_int_b[0],
            colmask_b, parallel, serial,
        )

    stacked = P((X_AXIS, Y_AXIS))
    w_int, k, diff, zr = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(stacked, stacked, stacked, stacked, stacked, stacked, P()),
        out_specs=(P(X_AXIS, Y_AXIS), P(), P(), P()),
        check_vma=False,
    )(cs, cw, g, rhs, sc2, sc_int, colmask)
    w = jnp.pad(w_int[: problem.M - 1, : problem.N - 1], 1)
    return PCGResult(w=w, iterations=k, diff=diff, residual_dot=zr)


def pallas_cg_solve_sharded(problem: Problem, mesh: Mesh,
                            bm: int | None = None,
                            interpret: bool | None = None,
                            dtype_name: str = "float32",
                            rhs_gate=None,
                            parallel: bool = False,
                            serial: bool | None = None) -> PCGResult:
    """Distributed solve on the fused Pallas path (fp32, scaled system).

    The stage4-equivalent configuration: per-shard fused kernels + mesh
    collectives. ``interpret`` defaults to True off-TPU so the kernels run
    (and are tested) on the virtual CPU mesh. ``rhs_gate`` as in
    ``pallas_cg_solve``; ``parallel`` marks each shard's strip grid
    parallel (megacore TensorCore split within a chip).
    """
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    px = mesh.shape[X_AXIS]
    py = mesh.shape[Y_AXIS]
    spec = shard_spec(problem, px, py, bm)
    cs, cw, g, rhs, sc2, sc_int, colmask = _shard_canvases(
        problem, px, py, spec, dtype_name
    )
    if rhs_gate is not None:
        rhs = rhs * jnp.asarray(rhs_gate, rhs.dtype)
    return _solve(problem, mesh, spec, interpret,
                  cs, cw, g, rhs, sc2, sc_int, colmask, parallel,
                  _resolve_serial(serial, parallel))


# ---------------------------------------------------------------------------
# Checkpoint/resume on the distributed fused path. Same portable full-grid
# .npz format and (float32, scaled) fingerprint as every other checkpointed
# solver — a pod-scale fused solve can be resumed by the XLA paths, on a
# different mesh shape, or single-device (see ops.pallas_cg and
# parallel.checkpoint_sharded). Fused-state mapping as in ops.pallas_cg:
# save forms the updated direction d = r + β·p; resume sets p := d − r,
# β := 1. Halo rings are dropped at save and refreshed by one exchange at
# chunk start (idempotent for in-memory state: the exchanged values equal
# the locally-recomputed ones by the r-halo induction argument above).
# ---------------------------------------------------------------------------


def _gather_full(problem: Problem, spec, px: int, py: int,
                 stacked, col0: int = 1) -> np.ndarray:
    """Stacked per-shard canvases → owned interiors on the (M+1, N+1) grid.

    ``col0`` is the canvas column of a shard's first owned cell (1 on the
    fused layout's width-1 ring; 2 on the CA layout's width-2 ring —
    ``parallel.pallas_ca_sharded`` shares these helpers)."""
    M, N = problem.M, problem.N
    stacked = np.asarray(stacked)
    full = np.zeros((M + 1, N + 1), stacked.dtype)
    for ix in range(px):
        for iy in range(py):
            gi0, gj0 = 1 + ix * spec.m_blk, 1 + iy * spec.n_blk
            nr = min(spec.m_blk, M - gi0)
            nc = min(spec.n_blk, N - gj0)
            if nr <= 0 or nc <= 0:
                continue
            blk = stacked[ix * py + iy]
            full[gi0 : gi0 + nr, gj0 : gj0 + nc] = blk[
                HALO : HALO + nr, col0 : col0 + nc
            ]
    return full


def _scatter_canvases(problem: Problem, spec, px: int, py: int,
                      full, col0: int = 1) -> np.ndarray:
    """(M+1, N+1) grid → stacked per-shard canvases, owned interiors only
    (halo rings and padding zero; one exchange at chunk start refreshes).
    ``col0`` as in :func:`_gather_full`."""
    M, N = problem.M, problem.N
    cv = spec.cv
    full = np.asarray(full, np.float32)
    out = np.zeros((px * py, cv.rows, cv.cols), np.float32)
    for ix in range(px):
        for iy in range(py):
            gi0, gj0 = 1 + ix * spec.m_blk, 1 + iy * spec.n_blk
            nr = min(spec.m_blk, M - gi0)
            nc = min(spec.n_blk, N - gj0)
            if nr <= 0 or nc <= 0:
                continue
            out[ix * py + iy, HALO : HALO + nr, col0 : col0 + nc] = full[
                gi0 : gi0 + nr, gj0 : gj0 + nc
            ]
    return out


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4, 5, 6))
def _chunk_solve(problem: Problem, mesh: Mesh, spec: ShardSpec,
                 interpret: bool, chunk: int, parallel: bool, serial: bool,
                 cs, cw, g, sc2, colmask,
                 w_st, r_st, p_st, k, done, zr, beta, diff):
    px = mesh.shape[X_AXIS]
    py = mesh.shape[Y_AXIS]

    def shard_fn(cs_b, cw_b, g_b, sc2_b, colmask_b,
                 w_b, r_b, p_b, k, done, zr, beta, diff):
        body = _make_shard_body(problem, spec, px, py, interpret,
                                cs_b[0], cw_b[0], g_b[0], sc2_b[0],
                                colmask_b, w_b.dtype, parallel, serial)
        # Refresh halo rings (resume reconstructs them as zeros; for
        # in-memory state the exchange is value-idempotent).
        r = _exchange_r_halo(r_b[0], spec, px, py)
        p = _exchange_r_halo(p_b[0], spec, px, py)
        s0 = _State(k=k, done=done, w=w_b[0], r=r, p=p,
                    zr=zr, beta=beta, diff=diff)
        stop_at = jnp.minimum(k + chunk, problem.iteration_cap)

        def cond(s: _State):
            return (~s.done) & (s.k < stop_at)

        s = lax.while_loop(cond, body, s0)
        return (s.w[None], s.r[None], s.p[None],
                s.k, s.done, s.zr, s.beta, s.diff)

    stacked = P((X_AXIS, Y_AXIS))
    rep = P()
    return shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(stacked, stacked, stacked, stacked, rep,
                  stacked, stacked, stacked, rep, rep, rep, rep, rep),
        out_specs=(stacked, stacked, stacked, rep, rep, rep, rep, rep),
        check_vma=False,
    )(cs, cw, g, sc2, colmask, w_st, r_st, p_st, k, done, zr, beta, diff)


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def _init_stacked(problem: Problem, mesh: Mesh, spec: ShardSpec,
                  rhs, colmask):
    def shard_fn(rhs_b, colmask_b):
        s = _shard_init(problem, spec, rhs_b[0], colmask_b)
        return (s.w[None], s.r[None], s.p[None],
                s.k, s.done, s.zr, s.beta, s.diff)

    stacked = P((X_AXIS, Y_AXIS))
    rep = P()
    return shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(stacked, rep),
        out_specs=(stacked, stacked, stacked, rep, rep, rep, rep, rep),
        check_vma=False,
    )(rhs, colmask)


class _CkptState(NamedTuple):
    """Stacked-canvas solver state in the canonical field order shared by
    both sharded checkpointed drivers: ``w`` the (scaled) solution
    canvases, ``r`` the residual, ``p`` the direction material (fused:
    the pending-β direction; CA: the pending pair's p₁ — both resume as
    p := d − r, β := 1)."""

    w: jnp.ndarray
    r: jnp.ndarray
    p: jnp.ndarray
    k: jnp.ndarray
    done: jnp.ndarray
    zr: jnp.ndarray
    beta: jnp.ndarray
    diff: jnp.ndarray


def run_sharded_checkpointed(problem: Problem, mesh: Mesh,
                             checkpoint_path: str, chunk: int,
                             keep_checkpoint: bool, spec, col0: int,
                             canvases, make_runners,
                             keep_last: int = 2) -> PCGResult:
    """Shared scaffolding for the sharded checkpointed drivers (fused and
    CA — one copy of the multi-process wrapping, portable-state mapping,
    gather/scatter plumbing, and final unscale).

    ``canvases`` is the process-local ``(cs, cw, g, rhs, sc2, colmask)``
    tuple; ``make_runners(wrapped_canvases)`` returns ``(init, advance)``
    where ``init()`` produces the initial :class:`_CkptState` and
    ``advance(state)`` runs one ~``chunk``-iteration leg. ``col0`` is the
    canvas column of the first owned cell (driver-layout dependent).
    Multi-process meshes: state is gathered to every process before the
    primary-only write, with barrier-ordered file handoff."""
    if chunk < 1:
        raise ValueError(f"chunk must be >= 1, got {chunk}")
    from poisson_tpu.parallel.checkpoint_sharded import (
        _global_array,
        _multiprocess,
        _replicator,
        _sync,
    )
    from poisson_tpu.parallel.multihost import is_primary
    from poisson_tpu.solvers.checkpoint import (
        _fingerprint,
        load_state,
        run_chunked,
    )
    from poisson_tpu.solvers.pcg import PCGState, host_fields64

    px = mesh.shape[X_AXIS]
    py = mesh.shape[Y_AXIS]
    cs, cw, g, rhs, sc2, colmask = canvases
    stacked_sp = P((X_AXIS, Y_AXIS))
    if _multiprocess():
        # Re-wrap the process-local canvases as global arrays (sc_int is
        # not used on this path — solution unscaling is host-side).
        wrap = lambda c, sp: _global_array(np.asarray(c), mesh, sp)
        cs, cw, g, rhs, sc2 = (
            wrap(c, stacked_sp) for c in (cs, cw, g, rhs, sc2)
        )
        colmask = wrap(colmask, P())
    fp = _fingerprint(problem, "float32", True)
    init, advance = make_runners((cs, cw, g, rhs, sc2, colmask))

    def stacked_state(full_state) -> _CkptState:
        d = np.asarray(full_state.p, np.float32)
        r = np.asarray(full_state.r, np.float32)
        as_global = lambda host: (
            _global_array(host, mesh, stacked_sp)
            if _multiprocess() else jnp.asarray(host)
        )
        scalar = lambda x, dt: (
            _global_array(np.asarray(x, dt), mesh, P())
            if _multiprocess() else jnp.asarray(np.asarray(x, dt))
        )
        scat = lambda full: _scatter_canvases(
            problem, spec, px, py, full, col0=col0
        )
        return _CkptState(
            w=as_global(scat(full_state.w)),
            r=as_global(scat(r)),
            p=as_global(scat(d - r)),
            k=scalar(full_state.k, np.int32),
            done=scalar(full_state.done, bool),
            zr=scalar(full_state.zr, np.float32),
            beta=scalar(1.0, np.float32),      # β := 1 with p := d − r
            diff=scalar(full_state.diff, np.float32),
        )

    saved = load_state(checkpoint_path, fp, keep_last=keep_last)
    state = init() if saved is None else stacked_state(saved)

    def fetch(x):
        return _replicator(mesh)(x) if _multiprocess() else x

    def gather(x):
        return _gather_full(problem, spec, px, py, fetch(x), col0=col0)

    def to_portable(s: _CkptState) -> PCGState:
        r_full = gather(s.r)
        d_full = r_full + float(s.beta) * gather(s.p)
        return PCGState(
            k=np.asarray(s.k), done=np.asarray(s.done),
            w=gather(s.w), r=r_full, z=r_full, p=d_full,
            zr=np.asarray(s.zr), diff=np.asarray(s.diff),
        )

    state = run_chunked(
        state,
        advance=advance,
        to_portable=to_portable,
        path=checkpoint_path, fingerprint=fp, cap=problem.iteration_cap,
        keep_checkpoint=keep_checkpoint, primary=is_primary, sync=_sync,
        keep_last=keep_last,
    )

    # Solution: gather owned w interiors and unscale with sc on the host
    # (value-identical to the one-shot drivers' per-shard w·sc_int: same
    # fp32 operands, elementwise).
    _, _, _, aux64 = host_fields64(problem, True)
    w_y = gather(state.w)
    w = w_y * np.asarray(aux64, w_y.dtype)
    return PCGResult(w=jnp.asarray(w), iterations=state.k, diff=state.diff,
                     residual_dot=state.zr)


def pallas_cg_solve_sharded_checkpointed(
        problem: Problem, mesh: Mesh, checkpoint_path: str,
        chunk: int = 200, bm: int | None = None,
        interpret: bool | None = None,
        keep_checkpoint: bool = False,
        parallel: bool = False,
        serial: bool | None = None,
        keep_last: int = 2) -> PCGResult:
    """Distributed fused-path solve with periodic state persistence and
    automatic resume (portable format — see module comment; hardened
    format with ``keep_last`` retained generations). fp32 only."""
    serial = _resolve_serial(serial, parallel)
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    px = mesh.shape[X_AXIS]
    py = mesh.shape[Y_AXIS]
    spec = shard_spec(problem, px, py, bm)
    cs, cw, g, rhs, sc2, _, colmask = _shard_canvases(
        problem, px, py, spec, "float32"
    )

    def make_runners(wrapped):
        cs, cw, g, rhs, sc2, colmask = wrapped
        init = lambda: _CkptState(
            *_init_stacked(problem, mesh, spec, rhs, colmask)
        )
        advance = lambda s: _CkptState(*_chunk_solve(
            problem, mesh, spec, interpret, chunk, parallel, serial,
            cs, cw, g, sc2, colmask,
            s.w, s.r, s.p, s.k, s.done, s.zr, s.beta, s.diff,
        ))
        return init, advance

    return run_sharded_checkpointed(
        problem, mesh, checkpoint_path, chunk, keep_checkpoint, spec, 1,
        (cs, cw, g, rhs, sc2, colmask), make_runners, keep_last=keep_last,
    )

