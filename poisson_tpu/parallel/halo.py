"""Halo exchange over the device mesh: ``ppermute`` in place of MPI.

TPU-native re-design of the reference's ghost-layer machinery
(``exchange_halos_2d``: nonblocking Isend/Irecv ×4 + Waitall,
``stage2-mpi/poisson_mpi_decomp.cpp:241-347``; stage4's GPU variant stages
edges D2H, runs blocking ``MPI_Sendrecv``, copies H2D and memsets physical
boundaries, ``stage4-mpi+cuda/poisson_mpi_cuda_f.cu:331-500``):

- each shift is one ``lax.ppermute`` along a mesh axis, resident on ICI —
  no host staging, no per-direction tags, no explicit waits;
- ``MPI_PROC_NULL`` edges (``stage2:…cpp:249-252``) need no sentinel:
  a device absent from the permutation's source list receives *zeros*,
  which is exactly the homogeneous Dirichlet boundary value;
- stage4's ``cudaMemcpy2D`` strided-column staging has no analog — both
  axes slice contiguously out of VMEM/HBM-resident shards.

As in the reference, corners are not exchanged diagonally; the 5-point
stencil never reads them (SURVEY §2.4). Exchanged slices span the full
halo-inclusive extent, matching the reference's length-(local+2) messages.
"""

from __future__ import annotations

from jax import lax

from poisson_tpu.parallel.mesh import X_AXIS, Y_AXIS


def _shift_down(u_slice, axis_name: str, size: int):
    """Value from mesh coordinate c−1 (zeros at c=0)."""
    return lax.ppermute(
        u_slice, axis_name, [(i, i + 1) for i in range(size - 1)]
    )


def _shift_up(u_slice, axis_name: str, size: int):
    """Value from mesh coordinate c+1 (zeros at c=size−1)."""
    return lax.ppermute(
        u_slice, axis_name, [(i + 1, i) for i in range(size - 1)]
    )


def exchange_halos(u, px_size: int, py_size: int):
    """Refresh the width-1 halo ring of a local (m+2, n+2) block.

    Must be called inside ``shard_map`` over a mesh with axes (x, y).
    One ``ppermute`` per direction, 4 total per call — called once per PCG
    iteration on the search direction p, exactly like the reference
    (``stage2:…cpp:404``).
    """
    # x-axis: rows. First/last *interior* rows travel to the neighbours'
    # halo rows. Full width (n+2): corner values ride along, as in the
    # reference's halo-inclusive messages (never read by the stencil).
    top_halo = _shift_down(u[-2, :], X_AXIS, px_size)   # from x-neighbour above
    bot_halo = _shift_up(u[1, :], X_AXIS, px_size)      # from x-neighbour below
    u = u.at[0, :].set(top_halo).at[-1, :].set(bot_halo)
    # y-axis: columns.
    left_halo = _shift_down(u[:, -2], Y_AXIS, py_size)
    right_halo = _shift_up(u[:, 1], Y_AXIS, py_size)
    u = u.at[:, 0].set(left_halo).at[:, -1].set(right_halo)
    return u
