from poisson_tpu.parallel.mesh import choose_process_grid, make_solver_mesh
from poisson_tpu.parallel.pcg_sharded import pcg_solve_sharded

__all__ = ["choose_process_grid", "make_solver_mesh", "pcg_solve_sharded"]
