from poisson_tpu.parallel.mesh import choose_process_grid, make_solver_mesh
from poisson_tpu.parallel.pallas_sharded import pallas_cg_solve_sharded
from poisson_tpu.parallel.pcg_sharded import pcg_solve_sharded

__all__ = [
    "choose_process_grid",
    "make_solver_mesh",
    "pallas_cg_solve_sharded",
    "pcg_solve_sharded",
]
