from poisson_tpu.parallel.checkpoint_sharded import pcg_solve_sharded_checkpointed
from poisson_tpu.parallel.mesh import choose_process_grid, make_solver_mesh
from poisson_tpu.parallel.pcg_sharded import pcg_solve_sharded
from poisson_tpu.parallel.watchdog import SolveTimeout, Watchdog

__all__ = [
    "SolveTimeout",
    "Watchdog",
    "ca_cg_solve_sharded",
    "choose_process_grid",
    "make_solver_mesh",
    "pallas_cg_solve_sharded",
    "pallas_cg_solve_sharded_checkpointed",
    "pcg_solve_sharded",
    "pcg_solve_sharded_checkpointed",
]


def __getattr__(name):
    # Lazy: keep jax.experimental.pallas out of plain-XLA consumers'
    # import path (matching the deferred imports in bench/cli/sweep).
    if name in ("pallas_cg_solve_sharded",
                "pallas_cg_solve_sharded_checkpointed"):
        from poisson_tpu.parallel import pallas_sharded

        return getattr(pallas_sharded, name)
    if name == "ca_cg_solve_sharded":
        from poisson_tpu.parallel import pallas_ca_sharded

        return pallas_ca_sharded.ca_cg_solve_sharded
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
