"""Multi-host launch: the framework's analog of the reference's MPI world.

The reference scales across nodes with `mpirun` + Spectrum MPI over
InfiniBand (``stage4-mpi+cuda/Makefile:2``, SURVEY §2.4). On TPU pods the
same role is played by ``jax.distributed``: every host runs this same
program, JAX forms the global device view, and the existing solvers work
unchanged — ``make_solver_mesh()`` simply sees all chips in the pod, the
``ppermute`` halo shifts ride ICI within a slice and DCN across slices,
and ``psum`` spans the global mesh. Nothing else in the framework is
multi-host-aware, by design: SPMD means the per-host program is identical.

Usage (per host, e.g. under a pod scheduler):

    from poisson_tpu.parallel.multihost import initialize_multihost
    initialize_multihost()            # env-driven (TPU pods: automatic)
    mesh = make_solver_mesh()         # global mesh over every chip
    result = pallas_cg_solve_sharded(problem, mesh)

or explicitly for CPU/GPU clusters:

    initialize_multihost(coordinator="10.0.0.1:1234",
                         num_processes=4, process_id=rank)

Single-host validation of the multi-process code path: JAX supports
multiple CPU processes on one machine (each process owning a subset of
virtual devices), but the halo/psum logic is identical to the virtual
8-device mesh the test suite already exercises — multi-host adds only the
transport, which is XLA's, not ours.
"""

from __future__ import annotations

import time
import warnings
from typing import Optional

import jax

# Coordinator-connection failures that are worth retrying: the coordinator
# process on host 0 races every other host's startup, so early connection
# refusals/timeouts are expected during a pod launch (and during recovery
# from a preempted host) — they are not configuration errors.
_TRANSIENT_MARKERS = (
    "connect", "connection", "timeout", "timed out", "deadline",
    "unavailable", "refused", "temporar", "reset", "barrier",
)


def _is_transient(message: str) -> bool:
    msg = message.lower()
    if "already" in msg or "once" in msg:
        return False            # runtime formed elsewhere: not a failure
    if "backend" in msg or "before" in msg:
        return False            # ordering mistake: retrying cannot fix it
    return any(marker in msg for marker in _TRANSIENT_MARKERS)


def initialize_multihost(coordinator: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None,
                         max_retries: int = 3,
                         backoff_seconds: float = 1.0,
                         jitter: float = 0.5,
                         seed: Optional[int] = None,
                         sleep=time.sleep) -> int:
    """Join (or form) the distributed runtime; returns this process's index.

    With no arguments, relies on the environment (TPU pods populate
    everything automatically; see ``jax.distributed.initialize``). Must be
    the FIRST JAX call in the process — initializing the XLA backend first
    (even implicitly, e.g. via ``jax.devices()``) makes multi-host init
    impossible, and that mistake is surfaced as an error here rather than
    silently degrading to per-host solo solves. Calling again after a
    successful init, or in a single-process environment with no cluster
    configuration, is a harmless no-op.

    Transient failures (coordinator not yet listening, connection timeout —
    normal during a racing pod launch or a recovery restart) are retried
    ``max_retries`` times with exponential backoff starting at
    ``backoff_seconds``, jittered over ``[1 − jitter, 1]`` by a SEEDED
    RNG (``seed``; default: this process's ``process_id``, else its
    pid) — a whole fleet of hosts retrying a dead coordinator after a
    host drop would otherwise thunder back in lockstep at exactly 1 s,
    2 s, 4 s, re-creating the very connection storm the backoff exists
    to drain. Per-process seeding decorrelates the herd while keeping
    each host's retry schedule reproducible. When the retries are
    exhausted: an explicitly
    requested cluster (``coordinator`` given) raises — the caller asked for
    a specific world and silently not getting it would corrupt the run —
    while an env-driven init degrades gracefully to a single-host run with
    a warning, so a solve can still make progress on local devices.
    """
    global _initialized
    if _initialized:
        return jax.process_index()  # documented no-op on a second call
    import os
    import random

    if seed is None:
        seed = process_id if process_id is not None else os.getpid()
    rng = random.Random(seed)
    attempt = 0
    while True:
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=num_processes,
                process_id=process_id,
            )
        except RuntimeError as e:
            msg = str(e).lower()
            if "already" in msg or "once" in msg:
                pass  # runtime formed elsewhere: keep it
            elif "backend" in msg or "before" in msg:
                raise RuntimeError(
                    "initialize_multihost() must be the first JAX call in "
                    "the process — the XLA backend is already initialized, "
                    "so the distributed runtime can no longer form. Move "
                    "the call ahead of any jax.devices()/jnp use."
                ) from e
            elif _is_transient(msg) and attempt < max_retries:
                attempt += 1
                # Seeded jitter over [1 − jitter, 1]: never exceeds the
                # exponential envelope, never collapses to lockstep.
                delay = (backoff_seconds * (2.0 ** (attempt - 1))
                         * (1.0 - jitter * rng.random()))
                from poisson_tpu import obs

                obs.inc("multihost.init_retries")
                obs.event("multihost.init_retry", attempt=attempt,
                          max_retries=max_retries, delay_seconds=delay,
                          error=str(e)[:200])
                warnings.warn(
                    f"distributed init failed transiently ({e}); retry "
                    f"{attempt}/{max_retries} in {delay:.1f}s",
                    RuntimeWarning, stacklevel=2,
                )
                sleep(delay)
                continue
            elif coordinator is None and _is_transient(msg):
                # Env-driven cluster that never came up (retries spent):
                # degrade rather than wedge every host on a dead
                # coordinator. Checked before the quiet no-cluster branch —
                # transient messages often mention the coordinator too.
                from poisson_tpu import obs

                obs.inc("multihost.degraded")
                obs.event("multihost.degraded", retries=max_retries,
                          error=str(e)[:200])
                warnings.warn(
                    f"distributed init still failing after {max_retries} "
                    f"retries ({e}); continuing single-host — this "
                    "process will only see its local devices",
                    RuntimeWarning, stacklevel=2,
                )
            elif coordinator is None and (
                "coordinator" in msg or "environment" in msg
                or "detect" in msg
            ):
                pass  # no cluster configured: single-process run
            else:
                raise
        except ValueError:
            if coordinator is not None:
                raise  # explicit-cluster arguments were wrong: surface it
            # No cluster in the environment: single-process run.
        break
    _initialized = True
    return jax.process_index()


_initialized = False


def is_primary() -> bool:
    """True on the process that should print/persist results (the
    reference's rank-0 idiom, ``stage2:…cpp:493-498``)."""
    return jax.process_index() == 0
