"""Device-mesh construction: the TPU-native process grid.

The reference factorises the MPI world into a near-square Px×Py grid
(``choose_process_grid``, ``stage2-mpi/poisson_mpi_decomp.cpp:60-64``) and
assigns ranks row-major. Here the same factorisation chooses a 2D
``jax.sharding.Mesh`` with axes ('x', 'y'); every per-rank concept of the
reference (rank→(px,py), neighbour lookup, MPI_PROC_NULL edges) becomes a mesh
coordinate / ``ppermute`` edge mask.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

X_AXIS = "x"
Y_AXIS = "y"


def choose_process_grid(size: int) -> tuple[int, int]:
    """Near-square factorisation Px·Py = size, Px ≤ Py
    (``stage2-mpi/poisson_mpi_decomp.cpp:60-64``)."""
    px = int(math.isqrt(size))
    while px > 1 and size % px != 0:
        px -= 1
    return px, size // px


def make_solver_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    grid: Optional[tuple[int, int]] = None,
) -> Mesh:
    """2D mesh over ``devices`` (default: all) shaped by
    :func:`choose_process_grid`.

    On real TPU slices the device order from ``jax.devices()`` follows the
    physical torus, so neighbouring mesh coordinates sit on neighbouring
    chips and ``ppermute`` halo traffic rides single-hop ICI.
    """
    if devices is None:
        devices = jax.devices()
    if grid is None:
        grid = choose_process_grid(len(devices))
    px, py = grid
    if px * py != len(devices):
        raise ValueError(f"grid {grid} != #devices {len(devices)}")
    arr = np.asarray(devices).reshape(px, py)
    return Mesh(arr, (X_AXIS, Y_AXIS))


def block_size(total_interior: int, parts: int) -> int:
    """Uniform per-shard block: ceil((M-1)/Px).

    The reference balances blocks differing by ≤1
    (``decompose_2d``, ``stage2:…cpp:75-111``); SPMD needs identical shapes
    per shard, so we pad the interior to parts·block and mask the excess —
    same arithmetic on the real unknowns, see ``parallel.pcg_sharded``.
    """
    return -(-total_interior // parts)
