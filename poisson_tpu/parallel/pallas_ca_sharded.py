"""Distributed communication-avoiding (s=2) CG: the CA kernels over a mesh.

Completes the backend × distribution matrix: the fused 2-sweep kernels
have a sharded form (``parallel.pallas_sharded`` — stage4's combination,
``stage4-mpi+cuda/poisson_mpi_cuda_f.cu:688-983``, re-designed TPU-native)
and the CA pair iteration (``ops.pallas_ca``) is the framework's own
algorithmic traffic reducer; this module runs the CA sweeps per shard
inside ``shard_map`` with ``ppermute`` halos and one ``psum`` round per
sweep. Per PAIR of iterations the wire cost is: one 12-entry Gram
``psum`` + one Σr'² ``psum`` (vs the fused path's 3 scalar rounds per
iteration — a 3× reduction in reduction-latency rounds, the classic
s-step communication win) and two width-2 halo exchanges.

**Width-2 halos, corners included.** The basis sweep applies the stencil
twice: t2 at an owned cell reads t1 at ±1, which reads pn at ±2 and at
the (±1, ±1) diagonals — so unlike the 5-point fused path (width-1,
corners never read, ``parallel.halo`` module doc), the CA shard needs
its ``r``/``pprev`` rings fresh at depth 2 *and* at corner cells. The
exchange shifts rows first and then columns over the full canvas height,
so corner blocks transit two hops (row neighbour → column neighbour)
and arrive correct without diagonal ``ppermute`` edges. The fused path's
r-only induction (recompute p's ring locally) does not extend to s=2 —
reconstructing p₁'s ring would need t1 there, which needs pn on a ring
that grows by one per pair — so both arrays are exchanged explicitly.

Shard canvas layout (cf. ``parallel.pallas_sharded``): the shard owns
m̂ × n̂ interior cells, m̂ a multiple of the strip height (strips tile the
owned band; halo rows live in the HALO-deep guard bands). Columns shift
by one vs the fused layout: owned column lj sits at canvas column
2 + lj, leaving TWO halo columns on each side (0..1 and n̂+2..n̂+3).
Kernel reductions mask halo columns with the (1, C) column mask
(unweighted Gram entries; sc² is builder-restricted to the owned
interior for the weighted ones) and halo rows stay outside every
reduction because strips tile the owned band exactly. The basis sweep's
direction update runs on a band widened ±2 rows so pn is real on the
ring (``ops.pallas_ca._make_basis_kernel``).

Correctness of the zero-padded decomposition follows the same induction
as ``parallel.pcg_sharded``: padded rows/columns have zero scaled
coefficients and zero RHS, so every iterate stays identically zero there.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from poisson_tpu.config import Problem
from poisson_tpu.ops.pallas_ca import (
    _CA_BUFFERS,
    _CAState,
    assemble_pair_state,
    basis_sweep,
    pair_scalars,
    pair_update,
)
from poisson_tpu.ops.pallas_cg import (
    HALO,
    LANE,
    SUBLANE,
    Canvas,
    _resolve_serial,
    diagonal_residual_canvas,
    scaled_stencil_fields,
    strip_height,
)
from poisson_tpu.parallel.halo import _shift_down, _shift_up
from poisson_tpu.parallel.mesh import X_AXIS, Y_AXIS
from poisson_tpu.solvers.pcg import PCGResult
from poisson_tpu.utils.compat import shard_map

_AXES = (X_AXIS, Y_AXIS)
_RING = 2          # halo ring width (the s=2 stencil depth)
_COL0 = _RING      # first owned canvas column


class CAShardSpec(NamedTuple):
    """Static per-shard CA canvas geometry (hashable; jit static arg)."""

    cv: Canvas
    m_blk: int   # owned interior rows per shard (= cv.nb · cv.bm)
    n_blk: int   # owned interior cols per shard


def ca_shard_spec(problem: Problem, px: int, py: int,
                  bm: int | None = None) -> CAShardSpec:
    n_blk = -(-(problem.N - 1) // py)
    cols = ((n_blk + 2 * _RING + LANE - 1) // LANE) * LANE
    if bm is None:
        bm = strip_height(cols, -(-(problem.M - 1) // px),
                          buffers=_CA_BUFFERS)
    if bm <= 0 or bm % SUBLANE != 0:
        raise ValueError(
            f"bm must be a positive multiple of {SUBLANE}, got {bm}"
        )
    m_min = -(-(problem.M - 1) // px)
    nb = -(-m_min // bm)
    m_blk = nb * bm
    cv = Canvas(bm=bm, nb=nb, rows=nb * bm + 2 * HALO, cols=cols)
    return CAShardSpec(cv=cv, m_blk=m_blk, n_blk=n_blk)


@functools.lru_cache(maxsize=8)
def _ca_shard_canvases(problem: Problem, px: int, py: int,
                       spec: CAShardSpec, dtype_name: str):
    """Host fp64 setup → stacked per-shard canvases (mesh order, x-major).

    Canvas (row w, col c) of shard (ix, iy) holds global grid cell
    (ix·m̂ + w − HALO + 1, iy·n̂ + c − _RING + 1): owned rows at
    w ∈ [HALO, HALO+m̂), owned cols at c ∈ [_RING, _RING+n̂), and a
    2-deep ring of real neighbour/boundary values around them (the rhs
    ring seeds r's — and via p₀ = r₀, pprev's — halos at iteration 0).
    """
    cv = spec.cv
    m_blk, n_blk = spec.m_blk, spec.n_blk
    dtype = jnp.dtype(dtype_name)
    M, N = problem.M, problem.N

    gcs, gcw, sc2_64, rhs64, sc64 = scaled_stencil_fields(problem)

    # Zero-padded global scratch with a _RING-cell guard before the
    # origin so every shard's slice — including shard (0, 0)'s, whose
    # ring reaches global row/col −2 — stays in bounds.
    height = (px - 1) * m_blk + (cv.rows - (HALO - _RING)) + _RING + 1
    width = (py - 1) * n_blk + cv.cols + _RING + 1
    big = np.zeros((max(height, M + 1 + _RING), max(width, N + 1 + _RING)),
                   np.float64)

    def stacked(field, zero_pad_cols: bool, zero_halo_cols: bool = False,
                zero_halo_rows: bool = False):
        big[:] = 0.0
        big[_RING : _RING + M + 1, _RING : _RING + N + 1] = field
        out = np.zeros((px * py, cv.rows, cv.cols), np.float64)
        w0 = HALO - _RING   # first canvas row the slice fills
        for ix in range(px):
            for iy in range(py):
                # canvas (w0, 0) ↔ global (ix·m̂ + 1 − _RING, iy·n̂ + 1 − _RING)
                r0 = _RING + ix * m_blk + 1 - _RING
                c0 = _RING + iy * n_blk + 1 - _RING
                out[ix * py + iy, w0:, :] = big[
                    r0 : r0 + cv.rows - w0, c0 : c0 + cv.cols
                ]
        if zero_pad_cols:
            out[:, :, n_blk + 2 * _RING :] = 0.0
        if zero_halo_cols:
            out[:, :, :_COL0] = 0.0
            out[:, :, _COL0 + n_blk :] = 0.0
        if zero_halo_rows:
            out[:, : HALO, :] = 0.0
            out[:, HALO + m_blk :, :] = 0.0
        return out

    cs_st = stacked(gcs, zero_pad_cols=True)
    cw_st = stacked(gcw, zero_pad_cols=True)
    g_st = np.stack([
        diagonal_residual_canvas(cs_st[s], cw_st[s])
        for s in range(px * py)
    ])
    rhs_st = stacked(rhs64, zero_pad_cols=True)
    # sc2 is a pure reduction weight: restrict to the owned interior
    # (halo rows AND columns zeroed — the weighted Gram entries then
    # need no separate mask).
    sc2_st = stacked(sc2_64, zero_pad_cols=True, zero_halo_cols=True,
                     zero_halo_rows=True)

    sc_int = np.zeros((px * py, m_blk, n_blk), np.float64)
    for ix in range(px):
        for iy in range(py):
            blk = sc64[
                1 + ix * m_blk : 1 + ix * m_blk + m_blk,
                1 + iy * n_blk : 1 + iy * n_blk + n_blk,
            ]
            sc_int[ix * py + iy, : blk.shape[0], : blk.shape[1]] = blk
    sc_int = jnp.asarray(sc_int, dtype)

    colmask = np.zeros((1, cv.cols), np.float64)
    colmask[0, _COL0 : _COL0 + n_blk] = 1.0
    as_dev = lambda x: jnp.asarray(x, dtype)
    return (as_dev(cs_st), as_dev(cw_st), as_dev(g_st), as_dev(rhs_st),
            as_dev(sc2_st), sc_int, as_dev(colmask))


def _exchange_ring2(u, spec: CAShardSpec, px: int, py: int):
    """Refresh the width-2 halo ring: 4 ``ppermute`` shifts of 2-wide
    slices. Rows first, then columns over the FULL canvas height — the
    just-received halo rows ride along in the column slices, so corner
    blocks arrive correct via two hops (module doc). Mesh-edge shards
    receive ppermute's zero fill = Dirichlet data."""
    lo, hi = HALO, HALO + spec.m_blk
    c0, c1 = _COL0, _COL0 + spec.n_blk
    top = _shift_down(u[hi - _RING : hi, :], X_AXIS, px)
    bot = _shift_up(u[lo : lo + _RING, :], X_AXIS, px)
    u = u.at[lo - _RING : lo, :].set(top).at[hi : hi + _RING, :].set(bot)
    left = _shift_down(u[:, c1 - _RING : c1], Y_AXIS, py)
    right = _shift_up(u[:, c0 : c0 + _RING], Y_AXIS, py)
    return u.at[:, c0 - _RING : c0].set(left) \
            .at[:, c1 : c1 + _RING].set(right)


def _make_ca_shard_body(problem: Problem, spec: CAShardSpec, px: int,
                        py: int, interpret: bool, cs, cw, g, sc2, colmask,
                        dtype, parallel: bool, serial: bool):
    """One CA pair as a pure state→state function on shard canvases."""
    cv = spec.cv
    h1h2 = jnp.float32(problem.h1 * problem.h2)
    band = (HALO - _RING, HALO + spec.m_blk + _RING)

    def body(s: _CAState) -> _CAState:
        beta = jnp.reshape(s.beta, (1, 1)).astype(dtype)
        pn, t1, t2, t3, gram = basis_sweep(
            cv, beta, s.pprev, s.r, cs, cw, g, sc2,
            interpret=interpret, parallel=parallel, serial=serial,
            band=band, colmask=colmask,
        )
        gsum = lax.psum(jnp.sum(gram, axis=0), _AXES) * h1h2
        d = pair_scalars(problem, s.rr, s.k, gsum, dtype)
        x, r, p1, rr_part = pair_update(
            cv, d.coefs, pn, t1, t2, t3, s.x, s.r,
            interpret=interpret, parallel=parallel, serial=serial,
            colmask=colmask,
        )
        rr2 = lax.psum(jnp.sum(rr_part), _AXES) * h1h2
        pprev = jnp.where(d.only1, pn, p1)
        # Both 2-rings refreshed per pair. Deeper guard rows of pn/p1
        # are UNDEFINED in compiled mode (non-aliased pallas outputs,
        # guard rows never written; interpret mode zero-fills, so CPU
        # tests cannot see this) — safe only because the basis kernel's
        # in_band where() discards every read outside the ±2 band. Do
        # not read pprev beyond the ring. r's deep guards stay zero
        # (aliased through kernel D from the zero-initialised canvas).
        r = _exchange_ring2(r, spec, px, py)
        pprev = _exchange_ring2(pprev, spec, px, py)
        return assemble_pair_state(problem, s, d, x, r, pprev, rr2)

    return body


def _ca_shard_init(problem: Problem, spec: CAShardSpec, rhs,
                   colmask) -> _CAState:
    """x=0, r=b̃ (2-ring seeded by the rhs canvas), β=0 — the first basis
    sweep then forms pn ← r + 0 = r₀, real on the ring."""
    cv = spec.cv
    lo, hi = HALO, HALO + spec.m_blk
    h1h2 = jnp.float32(problem.h1 * problem.h2)
    zeros = jnp.zeros((cv.rows, cv.cols), rhs.dtype)
    center = rhs[lo:hi, :].astype(jnp.float32)
    rr0 = lax.psum(
        jnp.sum(center * center * colmask.astype(jnp.float32)), _AXES
    ) * h1h2
    return _CAState(
        k=jnp.zeros((), jnp.int32),
        done=jnp.asarray(False),
        x=zeros, r=rhs, pprev=zeros,
        rr=rr0,
        beta=jnp.float32(0.0),
        diff=jnp.float32(jnp.inf),
    )


def _run_ca_shard(problem: Problem, spec: CAShardSpec, px: int, py: int,
                  interpret: bool, cs, cw, g, rhs, sc2, sc_int, colmask,
                  parallel: bool, serial: bool):
    lo, hi = HALO, HALO + spec.m_blk
    body = _make_ca_shard_body(problem, spec, px, py, interpret,
                               cs, cw, g, sc2, colmask, rhs.dtype,
                               parallel, serial)

    def cond(s: _CAState):
        return (~s.done) & (s.k < problem.iteration_cap)

    s = lax.while_loop(
        cond, body, _ca_shard_init(problem, spec, rhs, colmask)
    )
    x_own = s.x[lo:hi, _COL0 : _COL0 + spec.n_blk] * sc_int
    return x_own, s.k, s.diff, s.rr


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 11, 12))
def _ca_solve_sharded(problem: Problem, mesh: Mesh, spec: CAShardSpec,
                      interpret: bool, cs, cw, g, rhs, sc2, sc_int,
                      colmask, parallel: bool = False,
                      serial: bool = False) -> PCGResult:
    px = mesh.shape[X_AXIS]
    py = mesh.shape[Y_AXIS]

    def shard_fn(cs_b, cw_b, g_b, rhs_b, sc2_b, sc_int_b, colmask_b):
        return _run_ca_shard(
            problem, spec, px, py, interpret,
            cs_b[0], cw_b[0], g_b[0], rhs_b[0], sc2_b[0], sc_int_b[0],
            colmask_b, parallel, serial,
        )

    stacked = P((X_AXIS, Y_AXIS))
    w_int, k, diff, rr = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(stacked, stacked, stacked, stacked, stacked, stacked,
                  P()),
        out_specs=(P(X_AXIS, Y_AXIS), P(), P(), P()),
        check_vma=False,
    )(cs, cw, g, rhs, sc2, sc_int, colmask)
    w = jnp.pad(w_int[: problem.M - 1, : problem.N - 1], 1)
    return PCGResult(w=w, iterations=k, diff=diff, residual_dot=rr)


def ca_cg_solve_sharded(problem: Problem, mesh: Mesh,
                        bm: int | None = None,
                        interpret: bool | None = None,
                        dtype_name: str = "float32",
                        rhs_gate=None,
                        parallel: bool = False,
                        serial: bool | None = None) -> PCGResult:
    """Distributed solve on the communication-avoiding CA(s=2) path.

    Same system, same convergence criterion, same golden iteration
    counts as every other backend; ≈10.1 canvas passes and ONE Gram +
    ONE norm reduction round per pair of iterations (module doc).
    ``interpret`` defaults to True off-TPU so the kernels run (and are
    tested) on the virtual CPU mesh; ``rhs_gate``/``parallel`` as in
    ``ops.pallas_ca.ca_cg_solve``.
    """
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    px = mesh.shape[X_AXIS]
    py = mesh.shape[Y_AXIS]
    spec = ca_shard_spec(problem, px, py, bm)
    cs, cw, g, rhs, sc2, sc_int, colmask = _ca_shard_canvases(
        problem, px, py, spec, dtype_name
    )
    if rhs_gate is not None:
        rhs = rhs * jnp.asarray(rhs_gate, rhs.dtype)
    return _ca_solve_sharded(problem, mesh, spec, interpret,
                             cs, cw, g, rhs, sc2, sc_int, colmask,
                             parallel, _resolve_serial(serial, parallel))


# ---------------------------------------------------------------------------
# Checkpoint/resume on the distributed CA path. Same portable full-grid
# .npz format and (float32, scaled) fingerprint as every other fp32 path:
# the CA pending pair (pprev, β) maps to the stored updated direction
# d = r + β·pprev (resume sets pprev := d − r, β := 1), exactly like the
# single-device CA driver — so a pod-scale CA solve resumes on the fused
# sharded, single-device, or XLA paths and vice versa. Halo rings are
# dropped at save and refreshed by one width-2 exchange at chunk start
# (value-idempotent for in-memory state: the exchanged values equal the
# owned values the neighbour would send again).
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4, 5, 6))
def _ca_chunk_sharded(problem: Problem, mesh: Mesh, spec: CAShardSpec,
                      interpret: bool, chunk: int, parallel: bool,
                      serial: bool, cs, cw, g, sc2, colmask,
                      x_st, r_st, pprev_st, k, done, rr, beta, diff):
    """Advance the sharded CA solve by ~``chunk`` iterations (a pair
    straddling the chunk boundary overshoots by one — chunking must not
    change the iterate sequence, so only the global cap truncates)."""
    px = mesh.shape[X_AXIS]
    py = mesh.shape[Y_AXIS]

    def shard_fn(cs_b, cw_b, g_b, sc2_b, colmask_b,
                 x_b, r_b, p_b, k, done, rr, beta, diff):
        body = _make_ca_shard_body(problem, spec, px, py, interpret,
                                   cs_b[0], cw_b[0], g_b[0], sc2_b[0],
                                   colmask_b, x_b.dtype, parallel, serial)
        r = _exchange_ring2(r_b[0], spec, px, py)
        pprev = _exchange_ring2(p_b[0], spec, px, py)
        s0 = _CAState(k=k, done=done, x=x_b[0], r=r, pprev=pprev,
                      rr=rr, beta=beta, diff=diff)
        stop_at = jnp.minimum(k + chunk, problem.iteration_cap)

        def cond(s: _CAState):
            return (~s.done) & (s.k < stop_at)

        s = lax.while_loop(cond, body, s0)
        return (s.x[None], s.r[None], s.pprev[None],
                s.k, s.done, s.rr, s.beta, s.diff)

    stacked = P((X_AXIS, Y_AXIS))
    rep = P()
    return shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(stacked, stacked, stacked, stacked, rep,
                  stacked, stacked, stacked, rep, rep, rep, rep, rep),
        out_specs=(stacked, stacked, stacked, rep, rep, rep, rep, rep),
        check_vma=False,
    )(cs, cw, g, sc2, colmask, x_st, r_st, pprev_st, k, done, rr, beta,
      diff)


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def _ca_init_stacked(problem: Problem, mesh: Mesh, spec: CAShardSpec,
                     rhs, colmask):
    def shard_fn(rhs_b, colmask_b):
        s = _ca_shard_init(problem, spec, rhs_b[0], colmask_b)
        return (s.x[None], s.r[None], s.pprev[None],
                s.k, s.done, s.rr, s.beta, s.diff)

    stacked = P((X_AXIS, Y_AXIS))
    return shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(stacked, P()),
        out_specs=(stacked, stacked, stacked, P(), P(), P(), P(), P()),
        check_vma=False,
    )(rhs, colmask)


def ca_cg_solve_sharded_checkpointed(
        problem: Problem, mesh: Mesh, checkpoint_path: str,
        chunk: int = 200, bm: int | None = None,
        interpret: bool | None = None,
        keep_checkpoint: bool = False,
        parallel: bool = False,
        serial: bool | None = None,
        keep_last: int = 2) -> PCGResult:
    """Distributed CA solve with periodic state persistence and automatic
    resume (portable cross-backend, cross-mesh, cross-ALGORITHM format —
    module comment above). fp32 only. All scaffolding is the shared
    sharded driver (``parallel.pallas_sharded.run_sharded_checkpointed``)
    with this layout's column offset; only the init/advance legs are
    CA-specific."""
    from poisson_tpu.parallel.pallas_sharded import (
        _CkptState,
        run_sharded_checkpointed,
    )

    serial = _resolve_serial(serial, parallel)
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    px = mesh.shape[X_AXIS]
    py = mesh.shape[Y_AXIS]
    spec = ca_shard_spec(problem, px, py, bm)
    cs, cw, g, rhs, sc2, _, colmask = _ca_shard_canvases(
        problem, px, py, spec, "float32"
    )

    def make_runners(wrapped):
        cs, cw, g, rhs, sc2, colmask = wrapped
        init = lambda: _CkptState(
            *_ca_init_stacked(problem, mesh, spec, rhs, colmask)
        )
        advance = lambda s: _CkptState(*_ca_chunk_sharded(
            problem, mesh, spec, interpret, chunk, parallel, serial,
            cs, cw, g, sc2, colmask,
            s.w, s.r, s.p, s.k, s.done, s.zr, s.beta, s.diff,
        ))
        return init, advance

    return run_sharded_checkpointed(
        problem, mesh, checkpoint_path, chunk, keep_checkpoint, spec,
        _COL0, (cs, cw, g, rhs, sc2, colmask), make_runners,
        keep_last=keep_last,
    )
