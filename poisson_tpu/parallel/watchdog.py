"""Heartbeat watchdog for long-running chunked/multihost solves.

The failure mode this guards against is real in this repo's history: the
tunnel-probe log records multi-hour hangs where a wedged collective left a
solve blocked in ``block_until_ready`` with no host-side progress signal
at all. The reference had nothing comparable — an MPI job that wedged
simply sat until the scheduler killed it.

Design: the chunked solve drivers (``solvers.checkpoint.run_chunked``)
call :meth:`Watchdog.beat` at every chunk boundary. The watchdog

- writes a small JSON heartbeat file (atomic tmp+rename) on every beat —
  with BOTH wall (``at_unix``) and monotonic (``at_mono``) timestamps, so
  an *external* supervisor — or a human with ``cat`` — can tell a slow
  solve from a dead one without attaching a debugger, and a host clock
  jump can neither fake nor mask a stall;
- mirrors every beat and stall into the unified telemetry stream
  (``poisson_tpu.obs``: ``watchdog.beats``/``watchdog.stalls`` counters +
  events), so the event log carries the same liveness record; and
- optionally arms a monitor thread with a timeout: if no beat lands within
  ``timeout`` seconds (measured on the monotonic clock), it writes a
  diagnostics file next to the heartbeat (last-known iteration, residual,
  monotonic AND wall elapsed, plus the last N telemetry events — what the
  solve was actually doing) and invokes ``on_timeout`` — by default
  logging the diagnostics to stderr and interrupting the main thread so
  the solve aborts with a clean ``SolveTimeout`` traceback instead of
  hanging forever.

The monitor thread is a daemon and holds no JAX state; a wedged device
call cannot block it. Note the first beat only lands after the first
chunk, which includes compilation — size ``timeout`` generously (or call
:meth:`beat` once after warmup).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Callable, Optional

import _thread


class SolveTimeout(RuntimeError):
    """A watchdog timeout fired: no heartbeat within the configured
    window. Carries the diagnostics dict as ``.diagnostics``."""

    def __init__(self, message: str, diagnostics: Optional[dict] = None):
        super().__init__(message)
        self.diagnostics = diagnostics or {}


def _default_on_timeout(diagnostics: dict) -> None:
    print(
        "poisson_tpu watchdog: no heartbeat for "
        f"{diagnostics.get('elapsed_seconds', '?')}s — aborting the solve. "
        f"Diagnostics: {json.dumps(diagnostics, sort_keys=True)}",
        file=sys.stderr, flush=True,
    )
    # Interrupts the main thread at its next opportunity; the chunked
    # drivers convert that interrupt into SolveTimeout (see
    # ``raise_if_fired``) so callers catch a typed abort, not a bare
    # KeyboardInterrupt. A hard-wedged C call may never reach that
    # opportunity; the diagnostics file is already on disk either way,
    # which is what the post-mortem needs.
    _thread.interrupt_main()


class Watchdog:
    """Chunk-boundary heartbeat with optional stall timeout.

    ``heartbeat_path``: JSON heartbeat file, written atomically on every
    beat (None: keep heartbeats in memory only). ``timeout``: seconds
    without a beat before the monitor declares the solve wedged (None: no
    monitor — heartbeat file only). ``on_timeout``: called once with the
    diagnostics dict when the timeout fires (default: log + interrupt the
    main thread). Re-entrant: ``start``/``stop`` nest safely, and the
    object is a context manager.
    """

    def __init__(self, heartbeat_path: Optional[str] = None,
                 timeout: Optional[float] = None,
                 on_timeout: Optional[Callable[[dict], None]] = None,
                 poll_interval: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        if timeout is not None and timeout <= 0:
            raise ValueError(f"timeout must be positive, got {timeout}")
        self.heartbeat_path = heartbeat_path
        self.timeout = timeout
        self.on_timeout = on_timeout or _default_on_timeout
        self.poll_interval = poll_interval or (
            min(timeout / 4, 1.0) if timeout else 1.0
        )
        self._clock = clock
        self._lock = threading.Lock()
        self._last_beat = None
        self._last_beat_wall = None
        self._last_info: dict = {}
        self._beats = 0
        self._fired = False
        self.fired_diagnostics: Optional[dict] = None
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._depth = 0

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "Watchdog":
        with self._lock:
            self._depth += 1
            if self._depth > 1:
                return self
            self._fired = False
            self._last_beat = self._clock()
            self._last_beat_wall = time.time()
            self._stop_event.clear()
            if self.timeout is not None:
                self._thread = threading.Thread(
                    target=self._monitor, name="poisson-tpu-watchdog",
                    daemon=True,
                )
                self._thread.start()
        self._write_heartbeat()
        return self

    def stop(self) -> None:
        with self._lock:
            if self._depth == 0:
                return
            self._depth -= 1
            if self._depth > 0:
                return
            self._stop_event.set()
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5.0)

    def __enter__(self) -> "Watchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- heartbeat -----------------------------------------------------

    def beat(self, **info) -> None:
        """Record liveness (called at every chunk boundary). ``info`` is
        free-form progress metadata (iteration, residual, …) included in
        the heartbeat file and in any timeout diagnostics. Each beat is
        also a telemetry event (``watchdog.beat`` counter + event with
        wall AND monotonic timestamps), so the unified event log carries
        the same liveness record the heartbeat file does."""
        from poisson_tpu import obs

        with self._lock:
            self._last_beat = self._clock()
            self._last_beat_wall = time.time()
            self._last_info = dict(info)
            self._beats += 1
            beats = self._beats
        self._write_heartbeat()
        obs.inc("watchdog.beats")
        obs.event("watchdog.beat", beats=beats, **info)

    def elapsed_since_beat(self) -> float:
        with self._lock:
            if self._last_beat is None:
                return 0.0
            return self._clock() - self._last_beat

    @property
    def fired(self) -> bool:
        return self._fired

    def check(self) -> Optional[dict]:
        """Synchronous stall check for cooperative supervisors with
        injected clocks (``serve.fleet``): no monitor thread is armed —
        the supervisor itself asks "has this worker beaten within the
        timeout?" after every step. Fires at most once per watchdog
        instance (like the monitor), writing the same diagnostics file
        and counting the same ``watchdog.stalls``; returns the
        diagnostics dict when the stall verdict lands, None otherwise.
        A virtual clock advanced past the timeout mid-step is detected
        exactly like a wall-clock hang — which is what makes the fleet's
        hang drills deterministic."""
        from poisson_tpu import obs

        with self._lock:
            if (self.timeout is None or self._last_beat is None
                    or self._fired):
                return None
            elapsed = self._clock() - self._last_beat
            if elapsed <= self.timeout:
                return None
            self._fired = True
            diag = self._diagnostics(elapsed)
            self.fired_diagnostics = diag
        obs.inc("watchdog.stalls")
        obs.event("watchdog.stall",
                  elapsed_seconds=diag["elapsed_seconds"],
                  timeout_seconds=self.timeout,
                  beats=diag["beats"])
        self._write_diagnostics(diag)
        return diag

    def raise_if_fired(self) -> None:
        """Convert a watchdog-induced main-thread interrupt into the typed
        abort: the chunked drivers call this from their KeyboardInterrupt
        handlers, so a timeout surfaces as SolveTimeout (with diagnostics
        attached) while a genuine Ctrl-C stays a KeyboardInterrupt."""
        if self._fired:
            diag = self.fired_diagnostics or {}
            raise SolveTimeout(
                f"watchdog timeout: no heartbeat within "
                f"{self.timeout}s (last progress: "
                f"{diag.get('last_progress', {})})",
                diagnostics=diag,
            )

    def _write_heartbeat(self) -> None:
        if not self.heartbeat_path:
            return
        # Both clocks: wall for humans/cross-host alignment, monotonic so
        # stall arithmetic survives a host clock jump (NTP step, VM
        # migration) — a jump can neither fake nor mask a stall.
        payload = {
            "at_unix": time.time(),
            "at_mono": time.monotonic(),
            "pid": os.getpid(),
            "beats": self._beats,
            **self._last_info,
        }
        tmp = f"{self.heartbeat_path}.{os.getpid()}.tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(payload, f, sort_keys=True)
            os.replace(tmp, self.heartbeat_path)
        except OSError:
            # A failing heartbeat disk must not take the solve down with it.
            try:
                if os.path.exists(tmp):
                    os.remove(tmp)
            except OSError:
                pass

    # -- monitor -------------------------------------------------------

    def _diagnostics(self, elapsed: float) -> dict:
        from poisson_tpu import obs

        # elapsed_seconds is MONOTONIC (the default clock): the stall
        # verdict itself cannot be faked or masked by a host clock jump.
        # The wall-clock view is recorded alongside — a large disagreement
        # between the two is itself diagnostic (the clock jumped).
        wall_elapsed = (
            time.time() - self._last_beat_wall
            if self._last_beat_wall is not None else None
        )
        return {
            "elapsed_seconds": round(elapsed, 3),
            "elapsed_wall_seconds": (
                round(wall_elapsed, 3) if wall_elapsed is not None else None
            ),
            "at_unix": time.time(),
            "at_mono": time.monotonic(),
            "timeout_seconds": self.timeout,
            "beats": self._beats,
            "pid": os.getpid(),
            "last_progress": dict(self._last_info),
            # The last N unified-telemetry events (spans, checkpoint
            # writes, restarts, …): what the solve was actually doing
            # when it stopped beating — the round-5 forensic gap.
            "recent_events": obs.recent_events(),
        }

    def _monitor(self) -> None:
        from poisson_tpu import obs

        while not self._stop_event.wait(self.poll_interval):
            with self._lock:
                elapsed = self._clock() - self._last_beat
                expired = elapsed > self.timeout and not self._fired
                if expired:
                    self._fired = True
                    diag = self._diagnostics(elapsed)
                    self.fired_diagnostics = diag
            if expired:
                obs.inc("watchdog.stalls")
                obs.event("watchdog.stall",
                          elapsed_seconds=diag["elapsed_seconds"],
                          timeout_seconds=self.timeout,
                          beats=diag["beats"])
                self._write_diagnostics(diag)
                self.on_timeout(diag)
                return

    def _write_diagnostics(self, diag: dict) -> None:
        if not self.heartbeat_path:
            return
        path = f"{self.heartbeat_path}.stalled.json"
        try:
            with open(path, "w") as f:
                json.dump(diag, f, sort_keys=True, indent=2)
        except OSError:
            pass
