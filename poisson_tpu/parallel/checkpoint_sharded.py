"""Checkpoint/resume for the distributed solver.

The reference has no checkpointing at any stage (SURVEY §5: a solve runs to
convergence in one shot — ``stage2-mpi/poisson_mpi_decomp.cpp:400-460`` —
and an interrupted MPI job restarts from iteration zero). The framework's
single-device subsystem (``solvers.checkpoint``) names pod scale as its
motivation; this module delivers that: the sharded PCG loop runs as
fixed-size chunks of the shared body inside ``shard_map``, and at every
chunk boundary the gathered CG state is persisted in the *same* full-grid
``.npz`` format the single-device solver writes.

Same format + same fingerprint = portable checkpoints: a solve interrupted
on one mesh resumes on a different mesh shape, a different device count, or
on the single-device solver (and vice versa) — elastic recovery the
reference's MPI world could not express (a P-rank run could only ever be
restarted as the same P ranks, from scratch).

Why gathering the owned interiors is sufficient state: every sharded array
either keeps its halo ring zero by invariant (r, z and w are masked to the
owned interior each iteration — ``pcg_sharded._sharded_ops``) or has it
refreshed before use (the loop exchanges p's halos at the top of the body;
the scaled path exchanges sc·p inside ``apply_A``). Reconstructing blocks
with zero halo rings on resume is therefore exact, and the iterate sequence
is a pure function of the saved state.

Multi-process meshes (``jax.distributed`` — the real pod case): state
arrays span non-addressable devices, so before every save they are
resharded to fully-replicated (an all-gather every process participates
in), only the primary process writes the file (the reference's rank-0
idiom), and a cross-process sync orders the write before any later read.
``checkpoint_path`` must be on a filesystem every process can read.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from poisson_tpu.config import Problem
from poisson_tpu.parallel.mesh import X_AXIS, Y_AXIS, block_size
from poisson_tpu.parallel.pcg_sharded import (
    _host_shard_blocks,
    _owned_mask,
    _sharded_ops,
)
from poisson_tpu.solvers.checkpoint import (
    _fingerprint,
    load_state,
    run_chunked,
)
from poisson_tpu.solvers.pcg import (
    PCGResult,
    PCGState,
    host_fields64,
    init_state,
    make_pcg_body,
    resolve_dtype,
    resolve_scaled,
)
from poisson_tpu.utils.compat import shard_map

_STACKED = P((X_AXIS, Y_AXIS))   # (P, m̂+2, n̂+2) field blocks, mesh order
_BLOCKED = P(X_AXIS, Y_AXIS)     # (Px·m̂, Py·n̂) padded-global state arrays


def _multiprocess() -> bool:
    return jax.process_count() > 1


def _sync(name: str) -> None:
    """Cross-process barrier: orders the primary's host-side file write
    before any other process's subsequent read. No-op single-process."""
    if _multiprocess():
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


def _global_array(host: np.ndarray, mesh: Mesh, spec) -> jnp.ndarray:
    """Host array (identical on every process) → global jax.Array sharded
    per ``spec`` over a possibly multi-process mesh. Single-process keeps
    the plain device-put path."""
    if not _multiprocess():
        return jnp.asarray(host)
    sharding = NamedSharding(mesh, spec)
    return jax.make_array_from_callback(
        host.shape, sharding, lambda idx: host[idx]
    )


@functools.lru_cache(maxsize=8)
def _replicator(mesh: Mesh):
    """Cached jitted identity that reshards its argument to fully-replicated
    — one trace/compile per mesh, not per checkpoint boundary."""
    return jax.jit(lambda x: x, out_shardings=NamedSharding(mesh, P()))


def _fetchable(state: PCGState, mesh: Mesh) -> PCGState:
    """Reshard the state arrays to fully-replicated so ``np.asarray`` is
    legal on every process (multi-process state spans non-addressable
    devices). All processes must call this together — it is a collective."""
    if not _multiprocess():
        return state
    rep = _replicator(mesh)
    return state._replace(w=rep(state.w), r=rep(state.r),
                          z=rep(state.z), p=rep(state.p))


def _geometry(problem: Problem, mesh: Mesh):
    px_size = mesh.shape[X_AXIS]
    py_size = mesh.shape[Y_AXIS]
    m_blk = block_size(problem.M - 1, px_size)
    n_blk = block_size(problem.N - 1, py_size)
    return px_size, py_size, m_blk, n_blk


def _interiors(s: PCGState):
    inner = lambda x: x[1:-1, 1:-1]
    return (inner(s.w), inner(s.r), inner(s.z), inner(s.p),
            s.k, s.done, s.zr, s.diff, s.flag, s.best, s.stall)


def _state_specs():
    return (_BLOCKED, _BLOCKED, _BLOCKED, _BLOCKED,
            P(), P(), P(), P(), P(), P(), P())


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def _init_sharded(problem: Problem, mesh: Mesh, scaled: bool,
                  a_blk, b_blk, rhs_blk, aux_blk):
    """Initial CG state over the mesh — the exact init ``pcg_solve_sharded``
    runs (same ops, same reductions), as padded-global interior arrays."""
    px_size, py_size, m_blk, n_blk = _geometry(problem, mesh)

    def shard_fn(a, b, rhs, aux):
        a, b, rhs, aux = a[0], b[0], rhs[0], aux[0]
        mask, _, _ = _owned_mask(problem, m_blk, n_blk, a.dtype)
        ops = _sharded_ops(problem, a, b, aux, mask, px_size, py_size, scaled)
        return _interiors(init_state(ops, rhs * mask))

    out = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(_STACKED, _STACKED, _STACKED, _STACKED),
        out_specs=_state_specs(),
        check_vma=False,
    )(a_blk, b_blk, rhs_blk, aux_blk)
    w, r, z, p, k, done, zr, diff, flag, best, stall = out
    return PCGState(k=k, done=done, w=w, r=r, z=z, p=p, zr=zr, diff=diff,
                    flag=flag, best=best, stall=stall)


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4))
def _chunk_sharded(problem: Problem, mesh: Mesh, scaled: bool, chunk: int,
                   stagnation_window: int,
                   a_blk, b_blk, aux_blk, state: PCGState) -> PCGState:
    """Advance the sharded solve by at most ``chunk`` iterations."""
    px_size, py_size, m_blk, n_blk = _geometry(problem, mesh)

    def shard_fn(a, b, aux, w, r, z, p, k, done, zr, diff, flag, best, stall):
        a, b, aux = a[0], b[0], aux[0]
        mask, _, _ = _owned_mask(problem, m_blk, n_blk, a.dtype)
        ops = _sharded_ops(problem, a, b, aux, mask, px_size, py_size, scaled)
        body = make_pcg_body(
            ops, delta=problem.delta, weighted_norm=problem.weighted_norm,
            h1=problem.h1, h2=problem.h2,
            stagnation_window=stagnation_window,
        )
        pad1 = lambda x: jnp.pad(x, 1)   # zero halo ring (exact: see module doc)
        s0 = PCGState(k=k, done=done, w=pad1(w), r=pad1(r), z=pad1(z),
                      p=pad1(p), zr=zr, diff=diff,
                      flag=flag, best=best, stall=stall)
        stop_at = jnp.minimum(k + chunk, problem.iteration_cap)

        def cond(s: PCGState):
            return (~s.done) & (s.k < stop_at)

        return _interiors(lax.while_loop(cond, body, s0))

    out = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(_STACKED, _STACKED, _STACKED) + _state_specs(),
        out_specs=_state_specs(),
        check_vma=False,
    )(a_blk, b_blk, aux_blk, state.w, state.r, state.z, state.p,
      state.k, state.done, state.zr, state.diff,
      state.flag, state.best, state.stall)
    w, r, z, p, k, done, zr, diff, flag, best, stall = out
    return PCGState(k=k, done=done, w=w, r=r, z=z, p=p, zr=zr, diff=diff,
                    flag=flag, best=best, stall=stall)


def _to_full_grid(state: PCGState, problem: Problem) -> PCGState:
    """Padded-global interiors → the single-device full-grid ``.npz`` layout
    ((M+1, N+1) arrays, zero ring)."""
    M, N = problem.M, problem.N

    def full(x):
        x = np.asarray(x)
        out = np.zeros((M + 1, N + 1), x.dtype)
        out[1:M, 1:N] = x[: M - 1, : N - 1]
        return out

    return state._replace(w=full(state.w), r=full(state.r),
                          z=full(state.z), p=full(state.p))


def _to_padded_global(state: PCGState, problem: Problem, gm: int, gn: int,
                      mesh: Mesh) -> PCGState:
    """Full-grid ``.npz`` layout → this mesh's padded-global interiors.
    Also accepts a checkpoint written by a *different* mesh shape or by the
    single-device solver — the format is identical."""
    M, N = problem.M, problem.N

    def padded(x):
        x = np.asarray(x)
        out = np.zeros((gm, gn), x.dtype)
        out[: M - 1, : N - 1] = x[1:M, 1:N]
        return _global_array(out, mesh, _BLOCKED)

    def scalar(x):
        return _global_array(np.asarray(x), mesh, P())

    return state._replace(w=padded(state.w), r=padded(state.r),
                          z=padded(state.z), p=padded(state.p),
                          k=scalar(state.k), done=scalar(state.done),
                          zr=scalar(state.zr), diff=scalar(state.diff),
                          flag=scalar(state.flag), best=scalar(state.best),
                          stall=scalar(state.stall))


def pcg_solve_sharded_checkpointed(problem: Problem, mesh: Mesh,
                                   checkpoint_path: str, chunk: int = 200,
                                   dtype=None, scaled=None,
                                   keep_checkpoint: bool = False,
                                   keep_last: int = 2,
                                   stagnation_window: int = 0,
                                   watchdog=None,
                                   on_chunk=None) -> PCGResult:
    """Distributed solve with periodic state persistence and automatic resume.

    Chunked counterpart of ``pcg_solve_sharded`` (host setup): every
    ``chunk`` iterations the gathered CG state is written to
    ``checkpoint_path`` (atomic replace, CRC-sealed, ``keep_last``
    generations retained — see ``solvers.checkpoint.save_state``); an
    existing checkpoint with the same problem fingerprint is resumed —
    including one written by the single-device ``pcg_solve_checkpointed``
    or by a run on a different mesh shape, and falling back to an older
    generation if the newest is corrupt. On convergence the checkpoint is
    removed unless ``keep_checkpoint``; an unconverged cap-hit (or a
    divergence stop — see ``PCGResult.flag``) keeps it.

    ``watchdog`` (``parallel.watchdog.Watchdog``) is beaten at every chunk
    boundary — the heartbeat/timeout guard for wedged collectives on the
    multihost path. ``on_chunk(state, chunks_done)`` runs after each
    persisted chunk (fault injection uses this; see ``testing.faults``).
    """
    if chunk < 1:
        raise ValueError(f"chunk must be >= 1, got {chunk}")
    from poisson_tpu.parallel.multihost import is_primary

    dtype_name = resolve_dtype(dtype)
    use_scaled = resolve_scaled(scaled, dtype_name)
    px_size, py_size, m_blk, n_blk = _geometry(problem, mesh)
    blocks = _host_shard_blocks(
        problem, px_size, py_size, m_blk, n_blk, dtype_name, use_scaled
    )
    if _multiprocess():
        # _host_shard_blocks builds identical host data on every process but
        # places it process-locally; re-wrap as global arrays for the mesh.
        blocks = tuple(
            _global_array(np.asarray(blk), mesh, _STACKED) for blk in blocks
        )
    a_blk, b_blk, rhs_blk, aux_blk = blocks
    fp = _fingerprint(problem, dtype_name, use_scaled)

    saved = load_state(checkpoint_path, fp)
    if saved is None:
        state = _init_sharded(problem, mesh, use_scaled,
                              a_blk, b_blk, rhs_blk, aux_blk)
    else:
        state = _to_padded_global(saved, problem,
                                  px_size * m_blk, py_size * n_blk, mesh)

    def to_portable(s):
        # The full-grid gather is the expensive part of a sharded
        # checkpoint (an all-gather collective on multi-process meshes) —
        # span it so slow checkpoints are visible on the timeline.
        from poisson_tpu import obs

        with obs.span("checkpoint.gather", fence=False,
                      mesh=f"{px_size}x{py_size}"):
            return _to_full_grid(_fetchable(s, mesh), problem)

    state = run_chunked(
        state,
        advance=lambda s: _chunk_sharded(problem, mesh, use_scaled, chunk,
                                         stagnation_window,
                                         a_blk, b_blk, aux_blk, s),
        to_portable=to_portable,
        path=checkpoint_path, fingerprint=fp, cap=problem.iteration_cap,
        keep_checkpoint=keep_checkpoint, primary=is_primary, sync=_sync,
        keep_last=keep_last, watchdog=watchdog, on_chunk=on_chunk,
    )

    # Solution extraction, matching pcg_solve_sharded: unscale with the same
    # cast-to-device-dtype scaling vector the sharded ops used.
    w_y = np.asarray(_to_full_grid(_fetchable(state, mesh), problem).w)
    if use_scaled:
        _, _, _, aux64 = host_fields64(problem, True)
        w_y = w_y * np.asarray(aux64, w_y.dtype)
    return PCGResult(
        w=jnp.asarray(w_y), iterations=state.k, diff=state.diff,
        residual_dot=state.zr, flag=state.flag,
    )
