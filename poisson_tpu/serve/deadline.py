"""Per-request deadlines, propagated into chunked solves.

A deadline is the request-level contract the ROADMAP's serving story
needs: "answer within N seconds, or say you could not" — never "hang
until the batch happens to finish". The chunked solve drivers
(``solvers.checkpoint.run_chunked``, ``solvers.resilient``) accept any
object with ``expired() -> bool`` / ``remaining() -> float|None`` and
check it at every chunk boundary; :class:`Deadline` is the canonical
implementation, clock-injectable so chaos scenarios
(``testing.chaos.VirtualClock``) can expire deadlines deterministically
without wall-clock sleeps.
"""

from __future__ import annotations

import time
from typing import Callable, Optional


class Deadline:
    """A monotonic-clock budget: ``Deadline(2.5)`` expires 2.5 seconds
    after construction. ``seconds=None`` never expires (the explicit
    no-deadline object, so call sites need no None-guards)."""

    __slots__ = ("seconds", "_clock", "_t0", "_expires_at")

    def __init__(self, seconds: Optional[float],
                 clock: Callable[[], float] = time.monotonic):
        if seconds is not None and seconds < 0:
            raise ValueError(f"deadline seconds must be >= 0, got {seconds}")
        self.seconds = seconds
        self._clock = clock
        self._t0 = clock()
        self._expires_at = None if seconds is None else self._t0 + seconds

    @classmethod
    def never(cls) -> "Deadline":
        return cls(None)

    def remaining(self) -> Optional[float]:
        """Seconds left (negative once blown); None for a never-expiring
        deadline."""
        if self._expires_at is None:
            return None
        return self._expires_at - self._clock()

    def expired(self) -> bool:
        rem = self.remaining()
        return rem is not None and rem <= 0

    def elapsed(self) -> float:
        """Seconds since the budget started — what a deadline-flagged
        outcome actually spent, for the flight recorder's timeline
        (``remaining()`` alone cannot say how much of a blown budget the
        request consumed before its verdict)."""
        return max(0.0, self._clock() - self._t0)

    def __repr__(self) -> str:  # readable in chaos reports / diagnostics
        if self._expires_at is None:
            return "Deadline(never)"
        return f"Deadline({self.seconds}s, {self.remaining():+.3f}s left)"
