"""Cost-model backend router with misprediction sentinels.

ROADMAP item 3: four correctness-proven backends with sharply
different cost profiles (≈8 / 14.7 / 10.1 / ~0 effective HBM passes
per iteration — ``obs/costs.py`` EFFECTIVE_PASSES, BENCH.md) were
picked by hand at every call site. ``ServicePolicy.router`` replaces
the hand pick with a two-regime decision per dispatch cohort:

**Cold** (no measured evidence for the cohort) the router follows the
analytic model's own structure, not an argmin over made-up fractions:

- VMEM-resident grids (``12 × (M+1)(N+1) × 4 ≤ 15 MiB``, the
  ``ops.pallas_resident.fits_resident`` arithmetic mirrored here so
  routing never imports a Pallas module) go to the persistent-resident
  kernel — ~zero HBM passes beats any streaming backend when the whole
  working set fits on-chip.
- Working sets on the HBM plateau (≥ :data:`CA_PLATEAU_BYTES`) go to
  the communication-avoiding s-step kernel — its 10.1-pass model beats
  xla's 8 only when fusion headroom, not bandwidth, is the binding
  constraint, which BENCH.md places at the large-grid plateau.
- Everything else goes to ``xla`` — the proven default.

**Warm** (a candidate's cohort has ≥ ``warm_min_samples`` measured
roofline samples) the router ranks candidates by modeled time per
iteration: ``effective_passes(backend) / measured fraction of peak``
(cold candidates rank with the :data:`DEFAULT_COLD_FRACTION` prior).
Measured evidence — the ``obs.roofline`` per-cohort profiles — beats
the model as soon as it exists.

**Sentinels.** After every measured dispatch the router grades the
roofline sample against the decision's expectation: a fraction below
``misprediction_fraction ×`` expected is a misprediction — a typed
``serve.router.misprediction`` event plus counter. ``demote_after``
consecutive mispredictions demote that (backend, device_id) *arm*
with the circuit breaker's state machine (cooldown → HALF_OPEN
re-probe → a good sample closes it as a ``serve.router.recover``).
``xla`` is the floor arm and never demotes — there is nothing below it
to route to. The degradation ladder gains a *backend-downshift* rung:
at ``downshift_at`` queue pressure every dispatch is forced onto the
proven xla arm (``serve.degraded.backend_downshift``) — experimenting
with alternative kernels is exactly what an overloaded service should
not be doing.

**Execution gate.** :func:`executor_backend` maps every routed choice
to the execution path that is actually proven on this host — today
that is ``"xla"`` for all arms, because the Pallas kernels are
correctness-proven but have no valid hardware measurement (BENCH.md).
Routing therefore changes *labels, telemetry, and evidence
accumulation* but not compiled programs; the contracts ledger pins the
routed default path byte-identical to the historical hand-picked
programs, and that pin's guard raises the moment this gate opens so
the pin is consciously re-made, not silently broken.

Counters (see ``obs/metrics.py``): ``serve.router.decisions`` with
``serve.router.{cold,warm}_decisions`` and per-arm
``serve.router.chosen.<backend>``; the sentinel family
``serve.router.{mispredictions,demotions,half_opens,recoveries}``;
``serve.router.executor_fallbacks`` (routed ≠ executed, the gate
above); and the ``serve.router.demoted_arms`` gauge.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from poisson_tpu import obs
from poisson_tpu.obs.costs import grid_points
from poisson_tpu.obs.roofline import (DEFAULT_COLD_FRACTION,
                                      RooflineModel, RooflineSample,
                                      effective_passes,
                                      roofline_cohort)

# Analytic mirror of ``ops.pallas_resident.fits_resident``: the
# persistent kernel keeps 12 fp32 grid-shaped arrays resident in a
# 15 MiB VMEM budget. Mirrored as arithmetic (not imported) so the
# router stays importable on hosts without the Pallas toolchain; a
# model constant that graduates to a measured capability probe when
# the kernel gate lands (BENCH.md "Backend router" note).
RESIDENT_EQUIV_ARRAYS = 12
RESIDENT_VMEM_BYTES = 15 * 2**20

# Working-set size past which BENCH.md's model places the s-step CA
# kernel's fusion win over xla's lower pass count (the HBM plateau —
# all residency gone, bandwidth-bound on every pass). Also graduates
# to a measured crossover when real-hardware fractions arrive.
CA_PLATEAU_BYTES = 64 * 2**20

# Arm states (the circuit breaker's vocabulary).
HEALTHY = "healthy"
DEMOTED = "demoted"
HALF_OPEN = "half_open"

# The backend names the router can emit. Executor gate: all of them
# currently execute on the xla path (see executor_backend).
BACKEND_XLA = "xla"
BACKEND_RESIDENT = "pallas_resident"
BACKEND_CA = "pallas_ca"


def available_backends(device_kind: Optional[str],
                       assume: Tuple[str, ...] = ()
                       ) -> Tuple[str, ...]:
    """Candidate arms for a device kind. ``xla`` is always available;
    the Pallas arms require a TPU device kind (or an explicit
    ``assume_available`` override — the chaos/test seam that lets the
    whole routing state machine run on CPU hosts)."""
    kinds = [BACKEND_XLA]
    kind = (device_kind or "").lower()
    on_tpu = "tpu" in kind or any(
        v in kind for v in ("v2", "v3", "v4", "v5", "v6"))
    for cand in (BACKEND_RESIDENT, BACKEND_CA):
        if on_tpu or cand in assume:
            kinds.append(cand)
    return tuple(kinds)


def fits_resident_bytes(M: int, N: int) -> bool:
    """The ``fits_resident`` arithmetic: the kernel's working set is
    fp32 regardless of request dtype (it downcasts on entry)."""
    return (RESIDENT_EQUIV_ARRAYS * grid_points(M, N) * 4
            <= RESIDENT_VMEM_BYTES)


def analytic_choice(M: int, N: int, dtype_bytes: int,
                    candidates: Tuple[str, ...]) -> str:
    """The cold policy table (module docstring): resident when the
    grid fits VMEM, CA on the HBM plateau, xla elsewhere."""
    if BACKEND_RESIDENT in candidates and fits_resident_bytes(M, N):
        return BACKEND_RESIDENT
    if (BACKEND_CA in candidates
            and grid_points(M, N) * dtype_bytes >= CA_PLATEAU_BYTES):
        return BACKEND_CA
    return BACKEND_XLA


def executor_backend(backend: str) -> str:
    """The execution path a routed choice actually runs on. Today this
    is ``"xla"`` for every arm: the Pallas kernels are
    correctness-proven but unmeasured on real hardware (BENCH.md), so
    routing accumulates evidence without changing compiled programs.
    The ``serve.routed_default_f64`` contract pin's build raises if
    this gate changes, forcing the byte-compat pin to be re-made
    deliberately."""
    return BACKEND_XLA


@dataclass(frozen=True)
class Decision:
    """One routing decision: the arm picked for a dispatch cohort,
    whether it came from the cold analytic table or warm measured
    evidence, and the roofline fraction the sentinel will grade the
    measurement against."""

    backend: str
    cohort: str
    expected_fraction: float
    cold: bool
    device_id: int
    forced_xla: bool = False


class _Arm:
    """Per-(backend, device_id) sentinel state — the circuit breaker's
    CLOSED/OPEN/HALF_OPEN machine with misprediction strikes in place
    of dispatch failures."""

    __slots__ = ("strikes", "state", "until", "probes_left")

    def __init__(self):
        self.strikes = 0
        self.state = HEALTHY
        self.until = 0.0
        self.probes_left = 0


class BackendRouter:
    """Routes dispatch cohorts across backend arms and grades every
    measured sample against its decision (see module docstring)."""

    def __init__(self, policy, roofline: RooflineModel,
                 clock=None):
        import time as _time
        self.policy = policy
        self.roofline = roofline
        self._clock = clock if clock is not None else _time.monotonic
        self._arms: Dict[Tuple[str, int], _Arm] = {}
        self._chosen: Dict[str, int] = {}
        self._decisions = 0
        self._cold = 0
        self._warm = 0
        self._mispredictions = 0
        self._demotions = 0
        self._recoveries = 0
        self._lock = threading.Lock()

    # -- arm state machine ----------------------------------------------

    def _arm(self, backend: str, device_id: int) -> _Arm:
        key = (backend, int(device_id))
        arm = self._arms.get(key)
        if arm is None:
            arm = self._arms[key] = _Arm()
        return arm

    def _probe_candidate(self, backend: str, device_id: int,
                         consume: bool) -> bool:
        """True when ``backend``'s arm is due a half-open re-probe:
        DEMOTED with its cooldown expired, or already HALF_OPEN with
        probe budget left. With ``consume`` the state transition and
        probe decrement happen; the peek path only observes."""
        arm = self._arm(backend, device_id)
        if arm.state == DEMOTED and self._clock() >= arm.until:
            if consume:
                arm.state = HALF_OPEN
                arm.probes_left = max(1, int(
                    self.policy.half_open_probes))
                obs.inc("serve.router.half_opens")
                obs.event("serve.router.half_open", backend=backend,
                          device=int(device_id))
                arm.probes_left -= 1
            return True
        if arm.state == HALF_OPEN and arm.probes_left > 0:
            if consume:
                arm.probes_left -= 1
            return True
        return False

    def demoted_arms(self) -> Tuple[str, ...]:
        with self._lock:
            return tuple(sorted(
                f"{b}:{d}" for (b, d), arm in self._arms.items()
                if arm.state == DEMOTED))

    # -- decisions -------------------------------------------------------

    def peek(self, *, M: int, N: int, dtype_bytes: int, batch: int = 1,
             preconditioner: Optional[str] = None,
             verify_every: int = 0,
             device_kind: Optional[str] = None,
             device_id: int = 0) -> str:
        """The backend :meth:`route` would pick, without counters, arm
        probe consumption, or events — the pure variant the service's
        cohort labeler calls (labels must not tick decision counters)."""
        return self._choose(M, N, dtype_bytes, batch, preconditioner,
                            verify_every, device_kind, device_id,
                            consume=False)[0]

    def route(self, *, M: int, N: int, dtype_bytes: int, batch: int = 1,
              preconditioner: Optional[str] = None,
              verify_every: int = 0,
              device_kind: Optional[str] = None,
              device_id: int = 0,
              queue_fraction: float = 0.0) -> Decision:
        """Pick the arm for one dispatch and record the decision."""
        forced = False
        with self._lock:
            backend, cold, expected = self._choose(
                M, N, dtype_bytes, batch, preconditioner, verify_every,
                device_kind, device_id, consume=True)
            if (backend != BACKEND_XLA
                    and queue_fraction >= self.policy.downshift_at):
                # Backend-downshift rung: under pressure the service
                # runs only the proven floor arm.
                backend, forced = BACKEND_XLA, True
                expected, cold, _ = self._expectation(
                    backend, M, N, batch, dtype_bytes, preconditioner,
                    verify_every, device_kind)
            self._decisions += 1
            if cold:
                self._cold += 1
            else:
                self._warm += 1
            self._chosen[backend] = self._chosen.get(backend, 0) + 1
        obs.inc("serve.router.decisions")
        obs.inc("serve.router.cold_decisions" if cold
                else "serve.router.warm_decisions")
        obs.inc(f"serve.router.chosen.{backend}")
        if forced:
            obs.inc("serve.degraded.backend_downshift")
            obs.event("serve.degraded", rung="backend_downshift",
                      queue_fraction=round(queue_fraction, 3))
        cohort = roofline_cohort(backend, M, N, max(1, int(batch)),
                                 dtype_bytes, preconditioner,
                                 int(verify_every), device_kind)
        return Decision(backend=backend, cohort=cohort,
                        expected_fraction=expected, cold=cold,
                        device_id=int(device_id), forced_xla=forced)

    def _expectation(self, backend, M, N, batch, dtype_bytes,
                     preconditioner, verify_every, device_kind):
        cohort = roofline_cohort(backend, M, N, max(1, int(batch)),
                                 dtype_bytes, preconditioner,
                                 int(verify_every), device_kind)
        expected, cold, samples = self.roofline.expected_fraction(cohort)
        return expected, cold, samples

    def _choose(self, M, N, dtype_bytes, batch, preconditioner,
                verify_every, device_kind, device_id, consume):
        """(backend, cold, expected_fraction). Cold until some allowed
        candidate's cohort carries ``warm_min_samples`` measurements;
        then an argmin over modeled seconds/iteration —
        passes / fraction-of-peak — with cold candidates priced at the
        prior."""
        fixed = getattr(self.policy, "backend", "auto")
        candidates = available_backends(
            device_kind, tuple(self.policy.assume_available))
        if fixed and fixed != "auto":
            backend = fixed if fixed in candidates else BACKEND_XLA
            expected, cold, _ = self._expectation(
                backend, M, N, batch, dtype_bytes, preconditioner,
                verify_every, device_kind)
            return backend, cold, expected
        # Half-open re-probe: an arm past its cooldown that the
        # analytic model still prefers is probed ahead of warm
        # ranking — the measured evidence that demoted it would
        # otherwise keep it demoted forever. The probe is graded
        # against the cold prior, not the arm's own (bad) history.
        analytic = analytic_choice(M, N, dtype_bytes, candidates)
        if (analytic != BACKEND_XLA
                and self._probe_candidate(analytic, device_id,
                                          consume)):
            return analytic, True, DEFAULT_COLD_FRACTION
        allowed = [
            b for b in candidates
            if b == BACKEND_XLA
            or self._arm(b, device_id).state == HEALTHY
        ]
        scored = []
        warm_evidence = False
        for b in allowed:
            passes = effective_passes(b, preconditioner, M, N,
                                      dtype_bytes)
            if passes is None:
                continue
            expected, cold, samples = self._expectation(
                b, M, N, batch, dtype_bytes, preconditioner,
                verify_every, device_kind)
            if samples >= max(1, int(self.policy.warm_min_samples)):
                warm_evidence = True
            frac = expected if not cold else DEFAULT_COLD_FRACTION
            scored.append((passes / max(frac, 1e-9), b, cold,
                           expected))
        if not warm_evidence or not scored:
            backend = analytic_choice(M, N, dtype_bytes,
                                      tuple(allowed))
            expected, cold, _ = self._expectation(
                backend, M, N, batch, dtype_bytes, preconditioner,
                verify_every, device_kind)
            return backend, True, expected
        scored.sort(key=lambda t: (t[0], t[1]))
        _, backend, cold, expected = scored[0]
        return backend, False, expected

    # -- sentinel --------------------------------------------------------

    def grade(self, decision: Optional[Decision],
              sample: Optional[RooflineSample]) -> None:
        """Grade one measured dispatch against its decision. A None
        sample (unmeasurable dispatch — VirtualClock) is a no-op: the
        sentinel only ever acts on real measurements."""
        if decision is None or sample is None:
            return
        threshold = (self.policy.misprediction_fraction
                     * decision.expected_fraction)
        if sample.fraction < threshold:
            with self._lock:
                self._mispredictions += 1
            obs.inc("serve.router.mispredictions")
            obs.event("serve.router.misprediction",
                      backend=decision.backend,
                      cohort=decision.cohort,
                      device=decision.device_id,
                      fraction=round(sample.fraction, 6),
                      expected=round(decision.expected_fraction, 6),
                      threshold=round(threshold, 6))
            self._record_misprediction(decision)
        else:
            self._record_good(decision)
        obs.gauge("serve.router.demoted_arms",
                  len(self.demoted_arms()))

    def _record_misprediction(self, decision: Decision) -> None:
        if decision.backend == BACKEND_XLA:
            return  # the floor arm never demotes
        arm = self._arm(decision.backend, decision.device_id)
        arm.strikes += 1
        tripped = (arm.state == HALF_OPEN
                   or arm.strikes >= max(1, int(
                       self.policy.demote_after)))
        if tripped:
            arm.state = DEMOTED
            arm.strikes = 0
            arm.until = self._clock() + float(
                self.policy.cooldown_seconds)
            with self._lock:
                self._demotions += 1
            obs.inc("serve.router.demotions")
            obs.event("serve.router.demote",
                      backend=decision.backend,
                      device=decision.device_id,
                      cooldown_seconds=float(
                          self.policy.cooldown_seconds))

    def _record_good(self, decision: Decision) -> None:
        if decision.backend == BACKEND_XLA:
            return
        arm = self._arm(decision.backend, decision.device_id)
        arm.strikes = 0
        if arm.state == HALF_OPEN:
            arm.state = HEALTHY
            arm.probes_left = 0
            with self._lock:
                self._recoveries += 1
            obs.inc("serve.router.recoveries")
            obs.event("serve.router.recover",
                      backend=decision.backend,
                      device=decision.device_id)

    # -- reporting -------------------------------------------------------

    def stats(self) -> dict:
        """The ``stats()["router"]`` block: decision mix, sentinel
        tallies, demoted arms, and per-backend measured fractions."""
        with self._lock:
            chosen = dict(sorted(self._chosen.items()))
            out = {
                "decisions": self._decisions,
                "cold_decisions": self._cold,
                "warm_decisions": self._warm,
                "mispredictions": self._mispredictions,
                "demotions": self._demotions,
                "recoveries": self._recoveries,
                "chosen": chosen,
            }
        out["demoted_arms"] = list(self.demoted_arms())
        fractions = {}
        for b in chosen:
            f = self.roofline.backend_fraction(b)
            if f is not None:
                fractions[b] = round(f, 6)
        out["measured_fractions"] = fractions
        return out
