"""Device placement: the registry that binds fleet workers to silicon.

Until this module, the fleet's workers were dispatch contexts with no
location — every executable compiled wherever JAX's default device
happened to be, breaker/integrity cohorts were keyed per *process*, and
"a device died" was not a statement the serve layer could even make.
The suspect-cohort design of the SDC defense assumes hardware
granularity (Hochschild et al. 2021, PAPERS.md: *indict the part*), and
Orca's scheduler/engine split only pays off when engines map to real
silicon — so this module gives every :class:`~poisson_tpu.serve.fleet.
Worker` a concrete :class:`Placement`.

The unit of placement is a **fault domain**: a logical device slot
backed by a physical :class:`jax.Device`. On real hardware the mapping
is 1:1 (``DeviceRegistry()`` enumerates ``jax.devices()``); on a
single-device test host the registry *oversubscribes* — several logical
slots share one physical chip — so the supervision logic (who shares a
fate when slot 3 dies) is exercisable everywhere, while compile
targeting always lands on the slot's real backing device. CPU runs get
real multi-device topologies via
``XLA_FLAGS=--xla_force_host_platform_device_count=N`` (the test
suite's virtual 8-device mesh).

Topology is versioned: every :meth:`DeviceRegistry.lose` bumps the
**placement epoch**. Journal records carry the epoch and the bound
device id, so ``--recover`` on a *different* topology can tell that a
pending request's device no longer exists and remap it **audibly**
(``serve.placement.remapped`` + a ``placement_remapped`` flight point)
— never silently resume onto a device id that is gone. A placement
that cannot be satisfied at all (a pinned request whose device died,
a bind with no survivors) is a typed :class:`PlacementError`, not a
wedge.

The elastic degradation ladder for sharded dispatches lives here too
(:func:`elastic_plan`): mesh shrink → single device → shed, each rung
audible as a ``serve.degraded.*`` counter exactly like the PR 5 queue
ladder.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

from poisson_tpu import obs


class PlacementError(RuntimeError):
    """A placement that cannot be satisfied on the current topology —
    binding a worker with no surviving device, or recovering a request
    pinned to a device id that no longer exists. Typed so callers
    (submit validation, journal recovery) surface it as a loud error
    or a typed outcome instead of wedging on a missing chip."""


@dataclasses.dataclass(frozen=True)
class Placement:
    """Where a worker lives: the logical fault-domain slot it is bound
    to, the slot's backing physical device, and the epoch the binding
    was made under (stale epoch ⇒ the topology changed since)."""

    device_id: int            # logical fault-domain slot
    device_kind: str          # backing device's kind (hardware identity)
    epoch: int                # registry epoch at bind time
    device: object = dataclasses.field(compare=False, hash=False,
                                       default=None)  # jax.Device

    def label(self) -> str:
        return f"{self.device_kind}:{self.device_id}"


class DeviceRegistry:
    """The fleet's view of its device topology.

    ``count`` logical slots (default: one per physical device) are
    backed round-robin by ``devices`` (default: ``jax.devices()``).
    ``lose(device_id)`` marks a slot's silicon gone and bumps the
    placement epoch; ``bind`` hands out placements over the survivors
    and raises :class:`PlacementError` when none remain. All counters
    live under ``serve.placement.*`` (see ``obs.metrics``)."""

    def __init__(self, count: Optional[int] = None,
                 devices: Optional[Sequence] = None):
        if devices is None:
            import jax

            devices = jax.devices()
        if not devices:
            raise PlacementError("device registry needs at least one "
                                 "backing device")
        self._backing = list(devices)
        n = int(count) if count is not None else len(self._backing)
        if n < 1:
            raise ValueError(f"device count must be >= 1, got {n}")
        self._slots = [self._backing[i % len(self._backing)]
                       for i in range(n)]
        self._lost: set = set()
        self.epoch = 1
        self._rr = 0
        obs.gauge("serve.placement.devices", n)
        obs.gauge("serve.placement.epoch", self.epoch)

    # -- topology ------------------------------------------------------

    def __len__(self) -> int:
        return len(self._slots)

    def alive(self) -> List[int]:
        return [i for i in range(len(self._slots)) if i not in self._lost]

    def is_alive(self, device_id: int) -> bool:
        return 0 <= int(device_id) < len(self._slots) \
            and int(device_id) not in self._lost

    def device(self, device_id: int):
        """The backing :class:`jax.Device` of a slot (lost or alive —
        forensics may still want to name the silicon)."""
        return self._slots[int(device_id)]

    def kind(self, device_id: int) -> str:
        dev = self._slots[int(device_id)]
        return str(getattr(dev, "device_kind", getattr(dev, "platform",
                                                       "unknown")))

    def describe(self) -> dict:
        """JSON-ready topology summary — what the journal's topology
        record and the bench detail carry."""
        return {
            "devices": len(self._slots),
            "alive": len(self.alive()),
            "lost": sorted(self._lost),
            "epoch": self.epoch,
            "kinds": sorted({self.kind(i) for i in range(len(self._slots))}),
        }

    # -- binding -------------------------------------------------------

    def bind(self, worker_id: int) -> Placement:
        """Bind ``worker_id`` to the next surviving slot (round-robin —
        workers spread over the alive topology). Raises
        :class:`PlacementError` with no survivors."""
        alive = self.alive()
        if not alive:
            raise PlacementError(
                f"no surviving device to bind worker {worker_id} "
                f"({len(self._lost)}/{len(self._slots)} lost)")
        slot = alive[self._rr % len(alive)]
        self._rr += 1
        obs.inc("serve.placement.binds")
        return Placement(device_id=slot, device_kind=self.kind(slot),
                         epoch=self.epoch, device=self._slots[slot])

    def remap(self, device_id: Optional[int], worker_id: int = -1
              ) -> Placement:
        """A placement recorded under an older topology, mapped onto
        this one: alive → same slot rebound at the current epoch; gone
        → a surviving slot, counted ``serve.placement.remapped`` (the
        audible never-silently-resume contract)."""
        if device_id is not None and self.is_alive(int(device_id)):
            slot = int(device_id)
            return Placement(device_id=slot, device_kind=self.kind(slot),
                             epoch=self.epoch, device=self._slots[slot])
        placement = self.bind(worker_id)     # raises when none survive
        obs.inc("serve.placement.remapped")
        obs.event("serve.placement.remap", from_device=device_id,
                  to_device=placement.device_id, epoch=self.epoch)
        return placement

    # -- fault domains -------------------------------------------------

    def lose(self, device_id: int) -> bool:
        """Mark a slot's silicon gone. Bumps the placement epoch and
        returns True on the first loss of this slot (idempotent — a
        second report of the same dead device changes nothing)."""
        device_id = int(device_id)
        if not (0 <= device_id < len(self._slots)):
            raise PlacementError(
                f"device id {device_id} outside topology "
                f"0..{len(self._slots) - 1}")
        if device_id in self._lost:
            return False
        self._lost.add(device_id)
        self.epoch += 1
        obs.gauge("serve.placement.epoch", self.epoch)
        obs.gauge("serve.placement.alive", len(self.alive()))
        obs.event("serve.placement.device_lost", device=device_id,
                  kind=self.kind(device_id), epoch=self.epoch,
                  alive=len(self.alive()))
        return True


# -- elastic degradation for sharded dispatches --------------------------

RUNG_MESH = "mesh"
RUNG_SINGLE = "single"
RUNG_SHED = "shed"


def elastic_plan(registry: DeviceRegistry, want_devices: int) -> tuple:
    """Re-plan a sharded dispatch onto the surviving topology — the
    elastic degradation ladder for mesh work, counted like the PR 5
    queue ladder:

    - enough survivors for a multi-device mesh → ``("mesh", slots)``
      (shrunk below ``want_devices`` counts
      ``serve.degraded.mesh_shrink``);
    - exactly one survivor → ``("single", slot)`` — the dispatch
      downshifts to the single-device path,
      ``serve.degraded.single_device``;
    - none → ``("shed", None)`` — the work must shed or error, never
      silently run nowhere (``serve.degraded.mesh_shed``).

    The slots are logical fault domains; callers that actually build a
    :class:`jax.sharding.Mesh` map them through
    :meth:`DeviceRegistry.device` (requires distinct backing devices —
    true on real topologies and the forced-host test mesh).
    """
    alive = registry.alive()
    want = max(1, int(want_devices))
    if not alive:
        obs.inc("serve.degraded.mesh_shed")
        obs.event("serve.placement.replan", rung=RUNG_SHED, want=want,
                  alive=0)
        return (RUNG_SHED, None)
    if len(alive) == 1:
        if want > 1:
            obs.inc("serve.degraded.single_device")
        obs.event("serve.placement.replan", rung=RUNG_SINGLE, want=want,
                  alive=1)
        return (RUNG_SINGLE, alive[0])
    plan = alive[:want]
    if len(plan) < want:
        obs.inc("serve.degraded.mesh_shrink")
    obs.inc("serve.placement.replans")
    obs.event("serve.placement.replan", rung=RUNG_MESH, want=want,
              alive=len(alive), planned=len(plan))
    return (RUNG_MESH, plan)
