"""Resilient solve service: the request-lifecycle layer over the solvers.

PR 1 made a *single* solve survivable (divergence recovery, hardened
checkpoints, watchdog); PR 3 made *many* solves cheap (batched multi-RHS
dispatch). This package makes batched solves survivable **as a
service**: bounded admission with typed shedding, per-request deadlines
propagated into chunked solves, retry with exponential backoff + jitter
and poisoned-member bucket isolation, a circuit breaker per
(grid, dtype, backend) cohort, and a documented graceful-degradation
ladder — every mechanism audible as ``serve.*`` counters/spans
(``poisson_tpu.obs``) and exportable to Prometheus (``obs.export``).

PR 10 gave it a silent-data-corruption defense
(``poisson_tpu.integrity``): integrity failures are a typed
``integrity`` outcome class with retry through the verified-restart
driver, and the first detection taints the (backend, device_kind)
hardware cohort as SDC-suspect so later dispatches on it run
defensively verified (``ServicePolicy.integrity``,
``serve.integrity.*`` counters).

PR 8 made the service *durable*: a supervised worker fleet
(``serve.fleet`` — sticky executables, per-worker breakers, heartbeat
watchdogs, quarantine → warm-up restart) and a CRC-sealed write-ahead
journal (``serve.journal``) whose replay recovers queued and
lane-resident requests after a crash without double-admitting or
double-delivering.

The load-bearing invariant, asserted by the chaos campaign
(``poisson_tpu.testing.chaos``; ``python -m poisson_tpu chaos --all``):
every admitted request terminates with exactly one typed outcome —
result, typed error, or typed shed. ``admitted − (completed + errors +
shed) == 0``; no request is ever silently lost — now including across a
process kill/replay boundary, where the merged per-process ``serve.*``
snapshots close the same equation.

    from poisson_tpu.serve import SolveRequest, SolveService
    svc = SolveService()
    svc.submit(SolveRequest(request_id=0, problem=Problem(M=40, N=40)))
    outcomes = svc.drain()
"""

from poisson_tpu.serve.breaker import CLOSED, HALF_OPEN, OPEN, CircuitBreaker
from poisson_tpu.serve.deadline import Deadline
from poisson_tpu.serve.fleet import (
    WORKER_DEAD,
    WORKER_QUARANTINED,
    WORKER_RUNNING,
    DeviceLossError,
    Worker,
    WorkerCrashError,
    WorkerHangError,
    WorkerPool,
)
from poisson_tpu.serve.placement import (
    RUNG_MESH,
    RUNG_SHED,
    RUNG_SINGLE,
    DeviceRegistry,
    Placement,
    PlacementError,
    elastic_plan,
)
from poisson_tpu.serve.journal import (
    JournalReplay,
    PendingRequest,
    SessionReplay,
    SolveJournal,
    replay_journal,
    replay_sessions,
)
from poisson_tpu.serve.session import SessionHost, SolveSession
from poisson_tpu.serve.tenancy import (
    DEFAULT_TENANT,
    TenancyPolicy,
    TenantLedger,
    parse_tenant_spec,
)
from poisson_tpu.serve.service import (
    SolveService,
    p99_exemplar,
    slowest_requests,
)
from poisson_tpu.integrity.probe import IntegrityPolicy
from poisson_tpu.krylov import KrylovPolicy
from poisson_tpu.serve.types import (
    ERROR_DIVERGENCE,
    ERROR_INTEGRITY,
    ERROR_INTERNAL,
    ERROR_PLACEMENT,
    ERROR_TRANSIENT,
    OUTCOME_ERROR,
    OUTCOME_RESULT,
    OUTCOME_SHED,
    BreakerPolicy,
    DegradationPolicy,
    FleetPolicy,
    ForecastPolicy,
    Outcome,
    RetryPolicy,
    RouterPolicy,
    SCHED_CONTINUOUS,
    SCHED_DRAIN,
    ServicePolicy,
    SessionPolicy,
    SHED_BREAKER_OPEN,
    SHED_DEADLINE_EXPIRED,
    SHED_PREDICTED_DEADLINE,
    SHED_QUEUE_FULL,
    SHED_QUOTA_EXCEEDED,
    SLOPolicy,
    SolveRequest,
    TransientDispatchError,
)

__all__ = [
    "BreakerPolicy", "CircuitBreaker", "CLOSED", "Deadline",
    "DEFAULT_TENANT",
    "DegradationPolicy", "DeviceLossError", "DeviceRegistry",
    "ERROR_DIVERGENCE", "ERROR_INTEGRITY",
    "ERROR_INTERNAL", "ERROR_PLACEMENT",
    "ERROR_TRANSIENT", "FleetPolicy", "ForecastPolicy",
    "HALF_OPEN", "IntegrityPolicy",
    "JournalReplay", "KrylovPolicy",
    "OPEN", "Outcome", "OUTCOME_ERROR",
    "OUTCOME_RESULT", "OUTCOME_SHED", "PendingRequest", "Placement",
    "PlacementError", "RetryPolicy", "RouterPolicy",
    "RUNG_MESH", "RUNG_SHED", "RUNG_SINGLE",
    "SCHED_CONTINUOUS", "SCHED_DRAIN", "ServicePolicy",
    "SessionHost", "SessionPolicy", "SessionReplay",
    "SHED_BREAKER_OPEN", "SHED_DEADLINE_EXPIRED",
    "SHED_PREDICTED_DEADLINE", "SHED_QUEUE_FULL",
    "SHED_QUOTA_EXCEEDED",
    "SLOPolicy", "SolveJournal", "SolveRequest", "SolveService",
    "SolveSession", "TenancyPolicy", "TenantLedger",
    "TransientDispatchError", "WORKER_DEAD", "WORKER_QUARANTINED",
    "WORKER_RUNNING", "Worker", "WorkerCrashError", "WorkerHangError",
    "WorkerPool", "elastic_plan", "p99_exemplar", "parse_tenant_spec",
    "replay_journal",
    "replay_sessions", "slowest_requests",
]
