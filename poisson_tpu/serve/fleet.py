"""The solve fleet: supervised workers over the shared admission queue.

ROADMAP item 3 asks for "per-device/per-host workers pulling from the
shared admission queue … breaker/degradation state keyed per worker
cohort". This module is the worker half of that split: a
:class:`WorkerPool` of N :class:`Worker` dispatch contexts that the
service's pump loop schedules cooperatively — deterministic under an
injected clock, which is what lets the chaos campaign kill, hang and
poison workers mid-dispatch and still be a regression suite. (OS-thread
or per-process execution is a deployment mapping of the same states; the
supervisor API is execution-agnostic — Orca's scheduler/engine split,
PAPERS.md.)

Each worker owns:

- a **sticky set of bucket executables** (the cohorts it has dispatched
  — routing prefers the worker that already has the head's executable
  hot: ``serve.fleet.sticky_{hits,misses}``);
- its **own circuit-breaker registry** (a wedged worker trips *its*
  breakers, not the fleet's) and its own lane table in continuous mode;
- a **heartbeat watchdog** (``parallel.watchdog.Watchdog`` on the
  service clock, no monitor thread): the worker beats at every dispatch
  and chunk boundary, and the supervisor's synchronous
  :meth:`~poisson_tpu.parallel.watchdog.Watchdog.check` turns a
  too-long gap into a stall verdict.

Worker lifecycle (README "Solve fleet & durability" has the diagram)::

    RUNNING ──crash/hang/stall──▶ QUARANTINED ──cooldown──▶ RUNNING
       ▲                              │                    (restart
       └────────── warm-up ◀──────────┘                     counted)
                                      └─ max_restarts ──▶ DEAD

A quarantined worker's in-flight requests are recovered (mutual taint +
backoff, ``serve.fleet.recovered_requests``) and re-dispatched to the
survivors; the restart replays warm-up over the worker's sticky buckets
before it takes traffic again. When every worker is dead, the service
fails remaining work with typed internal errors — the ledger invariant
holds even through total fleet loss.
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional

from poisson_tpu import obs
from poisson_tpu.parallel.watchdog import Watchdog
from poisson_tpu.serve.placement import DeviceRegistry, PlacementError
from poisson_tpu.serve.types import FleetPolicy

WORKER_RUNNING = "running"
WORKER_QUARANTINED = "quarantined"
WORKER_DEAD = "dead"


class WorkerCrashError(RuntimeError):
    """The worker executing a dispatch died (process kill, device loss,
    injected chaos). Unlike :class:`~poisson_tpu.serve.types.
    TransientDispatchError` — a fault of the *dispatch* — this is a
    fault of the *worker*: the supervisor quarantines it, recovers its
    in-flight requests onto the survivors, and restarts it through
    warm-up."""


class WorkerHangError(RuntimeError):
    """The worker wedged mid-dispatch long enough for its heartbeat
    watchdog to fire (the injected-chaos analog of a stuck collective).
    Same recovery path as a crash, with the stall verdict landing on
    ``watchdog.stalls`` first."""


class DeviceLossError(WorkerCrashError):
    """The worker's *device* died mid-dispatch (the XLA
    device-unavailable shape: a chip dropping off the ICI, a host
    losing its PCIe link, injected chaos). A strict superset of a
    worker crash: the fault domain is the silicon, so the supervisor
    quarantines EVERY worker bound to the lost device — not just the
    one whose dispatch surfaced the loss — marks the device lost in
    the placement registry (epoch bump), and rebinds the quarantined
    workers to surviving devices at restart. ``device_id`` names the
    lost fault domain; None means "whatever the dispatching worker is
    bound to" (the bench churn injector's case)."""

    def __init__(self, message: str, device_id: Optional[int] = None):
        super().__init__(message)
        self.device_id = device_id


class Worker:
    """One dispatch context: sticky executables, breaker registry, lane
    table, heartbeat. Scheduled by the pool; stepped by the service."""

    __slots__ = ("id", "state", "breakers", "table", "watchdog",
                 "sticky", "restarts", "quarantined_until",
                 "quarantine_reason", "placement")

    def __init__(self, worker_id: int, timeout: float,
                 clock: Callable[[], float], placement=None):
        self.id = worker_id
        self.state = WORKER_RUNNING
        self.breakers: dict = {}
        self.table = None             # continuous mode's live LaneTable
        self.watchdog = Watchdog(timeout=timeout, clock=clock)
        self.watchdog.beat(worker=worker_id)
        # cohort -> {"problem", "dtype", "buckets": {widths dispatched}}
        self.sticky: dict = {}
        self.restarts = 0
        self.quarantined_until = 0.0
        self.quarantine_reason = ""
        # serve.placement.Placement: the device this worker is bound to
        # — sticky executables compile ON it, breaker/integrity cohorts
        # key on it, and a device loss quarantines every worker that
        # shares it (the fault domain).
        self.placement = placement


class WorkerPool:
    """Supervisor bookkeeping for the fleet. The pool owns worker
    lifecycle state and scheduling order; the *service* owns the queue,
    the ledger, and the dispatch machinery — a worker is somewhere for
    the service to run a dispatch, never a second source of truth."""

    def __init__(self, policy: FleetPolicy,
                 clock: Callable[[], float] = time.monotonic,
                 registry: Optional[DeviceRegistry] = None):
        if policy.workers < 1:
            raise ValueError("fleet.workers must be >= 1")
        if policy.max_restarts < 0:
            raise ValueError("fleet.max_restarts must be >= 0")
        self.policy = policy
        self._clock = clock
        # The placement registry binds every worker to a device slot at
        # construction (round-robin over the topology). The default —
        # one slot on the process's first device — reproduces the
        # pre-placement fleet exactly: every worker on the default
        # device, one fault domain.
        self.registry = registry if registry is not None else \
            DeviceRegistry(count=policy.devices
                           if policy.devices is not None else 1)
        self.workers: List[Worker] = [
            Worker(i, policy.heartbeat_timeout, clock,
                   placement=self.registry.bind(i))
            for i in range(policy.workers)
        ]
        self._rr = 0
        obs.gauge("serve.fleet.workers", policy.workers)
        self._publish()

    def workers_on_device(self, device_id: int) -> List[Worker]:
        """Every worker bound to fault domain ``device_id`` — who
        shares a fate when that silicon dies."""
        return [w for w in self.workers
                if w.placement is not None
                and w.placement.device_id == int(device_id)]

    # -- scheduling ----------------------------------------------------

    def running(self) -> List[Worker]:
        return [w for w in self.workers if w.state == WORKER_RUNNING]

    def all_dead(self) -> bool:
        return all(w.state == WORKER_DEAD for w in self.workers)

    def release_due(self) -> List[Worker]:
        """Quarantined workers whose cooldown has passed — the service
        restarts each through warm-up before scheduling it."""
        now = self._clock()
        return [w for w in self.workers
                if w.state == WORKER_QUARANTINED
                and w.quarantined_until <= now]

    def earliest_release(self) -> Optional[float]:
        times = [w.quarantined_until for w in self.workers
                 if w.state == WORKER_QUARANTINED]
        return min(times) if times else None

    def next_worker(self, head_cohort: Optional[str] = None
                    ) -> Optional[Worker]:
        """The next worker to step: sticky preference first (the worker
        whose executable cache already holds the queue head's cohort),
        else round-robin over RUNNING workers. None when nothing runs."""
        live = self.running()
        if not live:
            return None
        if head_cohort is not None and len(live) > 1:
            sticky = [w for w in live if head_cohort in w.sticky
                      or (w.table is not None
                          and w.table.cohort == head_cohort)]
            if sticky:
                obs.inc("serve.fleet.sticky_hits")
                return sticky[0]
            obs.inc("serve.fleet.sticky_misses")
        worker = live[self._rr % len(live)]
        self._rr += 1
        return worker

    # -- lifecycle -----------------------------------------------------

    def quarantine(self, worker: Worker, reason: str) -> None:
        """RUNNING → QUARANTINED (idempotent for an already-dead
        worker). The caller has already evicted/recovered the worker's
        in-flight entries — the pool only records the verdict."""
        if worker.state == WORKER_DEAD:
            return
        worker.state = WORKER_QUARANTINED
        worker.quarantined_until = (self._clock()
                                    + self.policy.quarantine_seconds)
        worker.quarantine_reason = reason
        worker.table = None
        obs.inc("serve.fleet.quarantines")
        obs.event("serve.fleet.quarantine", worker=worker.id,
                  reason=reason, restarts=worker.restarts)
        self._publish()

    def restart(self, worker: Worker) -> Optional[dict]:
        """QUARANTINED → RUNNING through warm-up, or → DEAD when the
        restart budget is spent. Returns the sticky map to warm (the
        service runs the compiles — the pool holds no solver imports),
        or None when the worker died instead.

        Topology-aware: a worker whose bound device died since the
        quarantine is REBOUND to a surviving device before it runs
        again (its sticky executables recompile there through the
        ordinary warm-up); with no survivor at all the worker dies —
        restarts cannot manufacture silicon."""
        if worker.restarts >= self.policy.max_restarts:
            worker.state = WORKER_DEAD
            obs.inc("serve.fleet.worker_deaths")
            obs.event("serve.fleet.worker_dead", worker=worker.id,
                      restarts=worker.restarts,
                      reason=worker.quarantine_reason)
            self._publish()
            return None
        if (worker.placement is not None
                and not self.registry.is_alive(worker.placement.device_id)):
            try:
                rebound = self.registry.bind(worker.id)
            except PlacementError:
                worker.state = WORKER_DEAD
                obs.inc("serve.fleet.worker_deaths")
                obs.event("serve.fleet.worker_dead", worker=worker.id,
                          restarts=worker.restarts, reason="no_devices")
                self._publish()
                return None
            obs.inc("serve.placement.rebinds")
            obs.event("serve.placement.rebind", worker=worker.id,
                      from_device=worker.placement.device_id,
                      to_device=rebound.device_id,
                      epoch=rebound.epoch)
            worker.placement = rebound
        worker.restarts += 1
        worker.state = WORKER_RUNNING
        # A fresh heartbeat watchdog: the stall verdict is one-shot per
        # instance, and the new incarnation starts with a clean record.
        worker.watchdog = Watchdog(timeout=self.policy.heartbeat_timeout,
                                   clock=self._clock)
        worker.watchdog.beat(worker=worker.id, restart=worker.restarts)
        obs.inc("serve.fleet.restarts")
        obs.event("serve.fleet.restart", worker=worker.id,
                  restarts=worker.restarts,
                  reason=worker.quarantine_reason)
        self._publish()
        return dict(worker.sticky) if self.policy.warm_restart else {}

    def _publish(self) -> None:
        obs.gauge("serve.fleet.live_workers", len(self.running()))
