"""Tenant isolation & overload fairness for the solve service.

The serve layer has per-request rails (deadlines, breakers, the
degradation ladder, journaled recovery) but — before this module — no
notion of *who* a request belongs to: admission was strict FIFO, so a
single hot client could starve the rest of the fleet while its
divergence-class retries amplified load exactly when the system was
most stressed.  This module supplies the three isolation mechanisms
(Dean & Barroso 2013, "The Tail at Scale" — PAPERS.md), all off by
default (``ServicePolicy.tenancy = None`` is byte-compatible with the
historical FIFO service):

**Admission quotas** — a token bucket per tenant, refilled at
``quota_rate × share`` admissions/second up to ``quota_burst × share``
tokens.  An over-quota submit burns zero compute: it sheds with the
typed reason ``quota_exceeded`` through the same ``_shed`` path as
``queue_full``, so the ledger invariant
``admitted − (completed + errors + shed) == 0`` closes unchanged.

**Weighted-fair draining** — both engines (drain and continuous
refill) promote the next dispatch head by *tenant share* rather than
arrival order, via smooth weighted round-robin: each scheduling round
every backlogged tenant's deficit counter grows by its share, the
largest counter wins the head, and the winner pays the round's total
back.  Over any window the dispatch mix converges to the share vector
regardless of arrival interleaving.  The lane-table refill applies the
same shares as a per-bucket lane cap so one tenant cannot monopolize a
bucket executable's lanes while another has eligible work waiting
(work-conserving: with no competing tenant the cap is void).

**Retry budgets** — retries spend from a per-tenant budget that only
successes replenish.  A poisoned tenant (every dispatch faulting)
exhausts the budget after ``retry_budget`` requeues and every later
retry converts into a typed error instead of a requeue, bounding its
total dispatch count by ``admitted + retry_budget`` — a retry storm
can no longer multiply load on a degraded fleet.

The ledger is deliberately clock-injected and pure-Python (no JAX):
it must be consultable from the admission path at nanosecond-scale
cost and replayable deterministically under the chaos campaign's
``VirtualClock``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

# The share assumed for any tenant not named in ``TenancyPolicy.shares``
# (and for requests with ``tenant=None`` when tenancy is on, which are
# pooled under this pseudo-tenant so anonymous traffic is itself one
# bounded client rather than an unpoliced side channel).
DEFAULT_TENANT = "default"


@dataclasses.dataclass(frozen=True)
class TenancyPolicy:
    """Per-tenant isolation knobs (``ServicePolicy.tenancy``).

    ``shares`` are relative weights — ``(("a", 1.0), ("b", 4.0))``
    gives tenant b 4× tenant a's dispatch bandwidth and quota rate.
    Tenants absent from the table get ``default_share``.  With
    ``quota_rate == 0`` the admission quota is off (fair draining and
    retry budgets still apply); with ``retry_budget is None`` retry
    budgeting is off.
    """

    # Relative weights per tenant name; unnamed tenants get
    # ``default_share``.  A tuple-of-pairs (not a dict) so the policy
    # stays hashable/frozen like every other serve policy.
    shares: Tuple[Tuple[str, float], ...] = ()
    default_share: float = 1.0
    # Token-bucket admission quota: tokens/second per unit share.
    # 0.0 disables the quota entirely.
    quota_rate: float = 0.0
    # Bucket capacity (burst) per unit share; buckets start full.
    quota_burst: float = 8.0
    # Retry tokens per tenant; each requeue spends one, each completed
    # solve refunds ``retry_refund`` (capped at the budget).  ``None``
    # disables budgeting (historical unbounded-retry behavior).
    retry_budget: Optional[int] = 8
    retry_refund: float = 1.0
    # When True, the queue-pressure degradation ladder applies its full
    # rung only to the offending tenant (largest backlog/share ratio);
    # every other tenant runs one rung gentler.
    isolate_degradation: bool = True


def parse_tenant_spec(spec: str) -> Tuple[Tuple[str, float], ...]:
    """Parse a ``name:weight,name:weight`` share spec (bench/CLI).

    Loud on garbage: empty names, non-numeric or non-positive weights,
    and duplicate names all raise ``ValueError`` naming the offending
    fragment — a typo'd tenant mix must never silently become a
    different experiment.
    """
    shares = []
    seen = set()
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            raise ValueError(f"empty tenant entry in spec {spec!r}")
        name, sep, weight_s = part.partition(":")
        name = name.strip()
        if not name:
            raise ValueError(f"tenant name missing in {part!r} (spec {spec!r})")
        if name in seen:
            raise ValueError(f"duplicate tenant {name!r} in spec {spec!r}")
        seen.add(name)
        if not sep:
            weight = 1.0
        else:
            try:
                weight = float(weight_s)
            except ValueError:
                raise ValueError(
                    f"tenant {name!r} has non-numeric weight {weight_s!r} "
                    f"(spec {spec!r})"
                ) from None
        if not weight > 0.0:
            raise ValueError(
                f"tenant {name!r} has non-positive weight {weight} "
                f"(spec {spec!r})"
            )
        shares.append((name, weight))
    if not shares:
        raise ValueError(f"empty tenant spec {spec!r}")
    return tuple(shares)


class _TenantState:
    """Mutable per-tenant ledger row (internal to ``TenantLedger``)."""

    __slots__ = ("name", "share", "tokens", "last_refill", "deficit",
                 "retry_tokens")

    def __init__(self, name: str, share: float, tokens: float,
                 now: float, retry_tokens: float):
        self.name = name
        self.share = share
        self.tokens = tokens          # admission-quota bucket
        self.last_refill = now
        self.deficit = 0.0            # smooth-WRR deficit counter
        self.retry_tokens = retry_tokens


class TenantLedger:
    """Clock-injected per-tenant state: quota buckets, deficit-weighted
    round-robin counters, and retry budgets.

    One instance lives on the service (built iff
    ``ServicePolicy.tenancy`` is set); the chaos campaign drives it
    through a ``VirtualClock`` so every decision is deterministic.
    """

    def __init__(self, policy: TenancyPolicy, clock) -> None:
        if policy.default_share <= 0.0:
            raise ValueError("TenancyPolicy.default_share must be > 0")
        if policy.quota_rate < 0.0:
            raise ValueError("TenancyPolicy.quota_rate must be >= 0")
        if policy.quota_burst <= 0.0:
            raise ValueError("TenancyPolicy.quota_burst must be > 0")
        if policy.retry_budget is not None and policy.retry_budget < 0:
            raise ValueError("TenancyPolicy.retry_budget must be >= 0")
        for name, share in policy.shares:
            if not share > 0.0:
                raise ValueError(
                    f"TenancyPolicy share for tenant {name!r} must be > 0, "
                    f"got {share}")
        self.policy = policy
        self._clock = clock
        self._shares: Dict[str, float] = dict(policy.shares)
        self._tenants: Dict[str, _TenantState] = {}

    # -- identity -------------------------------------------------------

    def resolve(self, tenant: Optional[str]) -> str:
        """Map a request's (possibly absent) tenant to a ledger key."""
        return str(tenant) if tenant else DEFAULT_TENANT

    def share_of(self, tenant: str) -> float:
        return self._shares.get(tenant, self.policy.default_share)

    def state(self, tenant: str) -> _TenantState:
        st = self._tenants.get(tenant)
        if st is None:
            share = self.share_of(tenant)
            budget = self.policy.retry_budget
            st = _TenantState(
                tenant, share,
                # quota bucket starts full: a new tenant gets its burst.
                tokens=self.policy.quota_burst * share,
                now=float(self._clock()),
                retry_tokens=float(budget) if budget is not None else 0.0,
            )
            self._tenants[tenant] = st
        return st

    # -- admission quota ------------------------------------------------

    def admit(self, tenant: str) -> bool:
        """Spend one quota token; False ⇒ shed ``quota_exceeded``."""
        st = self.state(tenant)
        if self.policy.quota_rate <= 0.0:
            return True
        now = float(self._clock())
        cap = self.policy.quota_burst * st.share
        if now > st.last_refill:
            st.tokens = min(
                cap,
                st.tokens + (now - st.last_refill)
                * self.policy.quota_rate * st.share)
        st.last_refill = now
        if st.tokens >= 1.0:
            st.tokens -= 1.0
            return True
        return False

    # -- weighted-fair head selection -----------------------------------

    def pick(self, backlogged: Sequence[str]) -> str:
        """Smooth weighted round-robin over the tenants with backlog.

        Every candidate's deficit counter grows by its share; the
        largest counter wins and repays the round's total share, so
        the long-run pick frequency of tenant *t* converges to
        ``share_t / Σ shares`` over the backlogged set.  Ties break to
        the lexicographically-first tenant (callers pass a sorted
        sequence) for determinism under a fixed seed.
        """
        best: Optional[_TenantState] = None
        total = 0.0
        for name in backlogged:
            st = self.state(name)
            st.deficit += st.share
            total += st.share
            if best is None or st.deficit > best.deficit:
                best = st
        assert best is not None, "pick() needs a non-empty backlog"
        best.deficit -= total
        return best.name

    # -- retry budgets --------------------------------------------------

    def spend_retry(self, tenant: str) -> bool:
        """Spend one retry token; False ⇒ the retry becomes a typed
        error instead of a requeue (budget exhausted)."""
        if self.policy.retry_budget is None:
            return True
        st = self.state(tenant)
        if st.retry_tokens >= 1.0:
            st.retry_tokens -= 1.0
            return True
        return False

    def credit_success(self, tenant: str) -> None:
        """A completed solve refunds retry tokens (capped at budget)."""
        if self.policy.retry_budget is None:
            return
        st = self.state(tenant)
        st.retry_tokens = min(float(self.policy.retry_budget),
                              st.retry_tokens + self.policy.retry_refund)

    def charge_attempts(self, tenant: str, attempts: int) -> None:
        """Recovery replay: re-charge journaled dispatch attempts so a
        poisoned tenant cannot reset its amplification cap by crashing
        the process mid-storm."""
        if self.policy.retry_budget is None or attempts <= 0:
            return
        st = self.state(tenant)
        st.retry_tokens = max(0.0, st.retry_tokens - float(attempts))

    # -- degradation offender -------------------------------------------

    def offender(self, backlog: Dict[str, int]) -> Optional[str]:
        """The tenant whose backlog most exceeds its share — the one
        the degradation ladder downshifts first.  None when fewer than
        two tenants are backlogged (nobody to spare)."""
        if len(backlog) < 2:
            return None
        best_name, best_ratio = None, -1.0
        for name in sorted(backlog):
            ratio = backlog[name] / self.share_of(name)
            if ratio > best_ratio:
                best_name, best_ratio = name, ratio
        return best_name

    # -- introspection --------------------------------------------------

    def tenants(self) -> Tuple[str, ...]:
        return tuple(sorted(self._tenants))

    def describe(self) -> Dict[str, Dict[str, float]]:
        """Stats/gauge snapshot: one row per tenant the ledger has
        seen, JSON-ready."""
        out: Dict[str, Dict[str, float]] = {}
        budget = self.policy.retry_budget
        for name in sorted(self._tenants):
            st = self._tenants[name]
            out[name] = {
                "share": float(st.share),
                "quota_tokens": round(float(st.tokens), 6),
                "retry_tokens": (round(float(st.retry_tokens), 6)
                                 if budget is not None else -1.0),
            }
        return out
