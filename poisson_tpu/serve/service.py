"""The solve service: request lifecycle over the existing solvers.

Everything below PR 3's batched dispatch already exists — one traced
program, hundreds of Poisson problems per dispatch — but a fault
mid-batch lost every co-batched request with it, and nothing bounded how
much work could pile up behind a wedged cohort. This module adds the
request level (the shape Orca, PAPERS.md, gives a serving stack):

- **bounded admission** — a queue of at most ``policy.capacity``
  requests; admission beyond it is a typed ``queue_full`` shed, never
  unbounded growth;
- **deadlines** — propagated into chunked solves (chunk-boundary checks,
  ``solvers.checkpoint``); expiry returns the partial iterate flagged
  ``deadline``, and a request whose budget dies while queued is shed
  without burning a dispatch;
- **retry with exponential backoff + jitter** — transient dispatch
  faults re-enqueue every member into a *different* bucket (mutual
  taint: one poisoned member cannot re-kill its batchmates);
  divergence-class member failures escalate through the self-healing
  driver (``solvers.resilient``);
- **circuit breaking** — per (grid, dtype, backend) cohort
  (``serve.breaker``), trip / cooldown / half-open probes;
- **graceful degradation** — the documented policy ladder
  (``types.DegradationPolicy``) driven by queue depth, every step
  audible as ``serve.degraded.*`` counters;
- **the ledger invariant** — every admitted request terminates with
  exactly one typed outcome; ``stats()['lost']`` is computed, asserted
  by the chaos campaign, and exported with the ``serve.*`` counters.

The service is deliberately single-threaded and clock/sleep-injectable:
the dispatch loop IS the unit under chaos test, and determinism (seeded
jitter, virtual clocks) is what makes the chaos campaign a regression
suite instead of a flake generator.
"""

from __future__ import annotations

import random
import time
from collections import deque
from typing import Callable, List, Optional, Sequence, Union

import numpy as np

from poisson_tpu import obs
from poisson_tpu.serve.breaker import CircuitBreaker
from poisson_tpu.serve.deadline import Deadline
from poisson_tpu.serve.types import (
    ERROR_DIVERGENCE,
    ERROR_INTERNAL,
    ERROR_TRANSIENT,
    OUTCOME_ERROR,
    OUTCOME_RESULT,
    OUTCOME_SHED,
    Outcome,
    ServicePolicy,
    SHED_BREAKER_OPEN,
    SHED_DEADLINE_EXPIRED,
    SHED_QUEUE_FULL,
    SolveRequest,
    TransientDispatchError,
)


class _Entry:
    """Queue-resident lifecycle state for one admitted request."""

    __slots__ = ("request", "admitted_at", "deadline", "attempts",
                 "taint", "not_before", "escalate", "last_failure")

    def __init__(self, request: SolveRequest, admitted_at: float,
                 deadline: Optional[Deadline]):
        self.request = request
        self.admitted_at = admitted_at
        self.deadline = deadline
        self.attempts = 0          # dispatches so far
        self.taint: set = set()    # request_ids never to co-batch with again
        self.not_before = 0.0      # backoff gate (service clock)
        self.escalate = False      # next dispatch via the resilient driver
        self.last_failure = ""


def _percentile(sorted_vals: Sequence[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted sample."""
    if not sorted_vals:
        return 0.0
    idx = max(0, min(len(sorted_vals) - 1,
                     int(np.ceil(q * len(sorted_vals))) - 1))
    return float(sorted_vals[idx])


class SolveService:
    """Single-process solve service over the JAX solver stack.

    ``submit`` admits a request (or sheds it, typed, immediately);
    ``drain`` runs the dispatch loop until every admitted request has its
    outcome. ``clock``/``sleep`` default to real monotonic time; chaos
    scenarios inject a :class:`testing.chaos.VirtualClock` pair.
    ``dispatch_fault`` is the service-level fault seam: called with the
    entry batch immediately before the solver runs, it may raise
    :class:`TransientDispatchError` (a device-level batch kill) or stall
    on the injected clock (a slow worker).
    """

    def __init__(self, policy: Optional[ServicePolicy] = None, *,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Optional[Callable[[float], None]] = None,
                 seed: int = 0,
                 dispatch_fault: Optional[Callable] = None):
        self.policy = policy or ServicePolicy()
        if self.policy.capacity < 1:
            raise ValueError("service capacity must be >= 1")
        if self.policy.retry.max_attempts < 1:
            raise ValueError("retry.max_attempts must be >= 1")
        self._clock = clock
        self._sleep = sleep if sleep is not None else time.sleep
        self._rng = random.Random(seed)
        self._dispatch_fault = dispatch_fault
        self._queue: deque = deque()
        self._delayed: List[_Entry] = []
        self._pending_ids: set = set()  # ids queued or backing off
        self._breakers: dict = {}
        self._outcomes: dict = {}
        self._order: List = []          # outcome completion order
        self._latencies: List[float] = []
        self._counts = {"admitted": 0, "completed": 0, "errors": 0,
                        "shed": 0}

    # -- admission -----------------------------------------------------

    def submit(self, request: SolveRequest) -> Optional[Outcome]:
        """Admit ``request`` into the ledger. Returns the typed Outcome
        immediately iff the request was shed at admission (queue full);
        None when it was queued — its outcome arrives via :meth:`drain`.
        Either way the request is admitted for accounting: exactly one
        typed outcome will exist for it."""
        if (request.request_id in self._outcomes
                or request.request_id in self._pending_ids):
            raise ValueError(
                f"duplicate request_id {request.request_id!r} — the "
                "one-outcome-per-request ledger needs unique ids"
            )
        self._counts["admitted"] += 1
        obs.inc("serve.admitted")
        now = self._clock()
        deadline = (Deadline(request.deadline_seconds, clock=self._clock)
                    if request.deadline_seconds is not None else None)
        entry = _Entry(request, now, deadline)
        depth = len(self._queue) + len(self._delayed)
        if depth >= self.policy.capacity:
            return self._shed(entry, SHED_QUEUE_FULL,
                              "admission queue at capacity "
                              f"({self.policy.capacity})")
        self._pending_ids.add(request.request_id)
        self._queue.append(entry)
        obs.gauge("serve.queue_depth", len(self._queue) + len(self._delayed))
        return None

    # -- lifecycle loop ------------------------------------------------

    def drain(self) -> List[Outcome]:
        """Run the dispatch loop until no admitted request is pending;
        returns every outcome reached during this drain, in completion
        order. Publishes the ``serve.*`` stats gauges afterwards."""
        start = len(self._order)
        while self._step():
            pass
        self._publish_stats()
        return [self._outcomes[rid] for rid in self._order[start:]]

    def _step(self) -> bool:
        self._pump_delayed()
        if not self._queue:
            if not self._delayed:
                return False
            # Everything pending is backing off: advance to the earliest
            # ready time (virtual clocks advance instantly; real clocks
            # sleep). Force-promote afterwards so a coarse injected clock
            # can never wedge the loop.
            wait = max(0.0, min(e.not_before for e in self._delayed)
                       - self._clock())
            self._sleep(wait)
            self._pump_delayed()
            if not self._queue and self._delayed:
                self._delayed.sort(key=lambda e: e.not_before)
                self._queue.append(self._delayed.pop(0))
        head = self._queue.popleft()
        if head.deadline is not None and head.deadline.expired():
            obs.inc("serve.deadline.expired_in_queue")
            self._shed(head, SHED_DEADLINE_EXPIRED,
                       "deadline expired while queued")
            return True
        # Load is measured at dispatch-cycle start (head included), BEFORE
        # batch formation empties the queue — degradation responds to the
        # pressure the service is under, not to the hole a big batch just
        # carved out of it.
        level = self._load_level(len(self._queue) + len(self._delayed) + 1)
        batch = self._form_batch(head)
        breaker = self._breaker(self._cohort(head.request))
        if not breaker.allow():
            for entry in batch:
                self._shed(entry, SHED_BREAKER_OPEN,
                           f"circuit breaker open for cohort "
                           f"{self._cohort(entry.request)}")
            return True
        self._dispatch(batch, breaker, level)
        return True

    def _pump_delayed(self) -> None:
        now = self._clock()
        ready = [e for e in self._delayed if e.not_before <= now]
        if ready:
            self._delayed = [e for e in self._delayed
                             if e.not_before > now]
            self._queue.extend(ready)

    # -- batching ------------------------------------------------------

    def _cohort(self, request: SolveRequest) -> str:
        p = request.problem
        return f"{p.M}x{p.N}:{request.dtype or 'auto'}:xla"

    def _breaker(self, cohort: str) -> CircuitBreaker:
        if cohort not in self._breakers:
            self._breakers[cohort] = CircuitBreaker(
                self.policy.breaker, clock=self._clock, cohort=cohort)
        return self._breakers[cohort]

    def _solo(self, entry: _Entry) -> bool:
        """Chunked single-request dispatch classes: deadline-carrying
        (expiry needs chunk boundaries), explicitly chunked, or escalated
        divergence retries (the resilient driver is single-request)."""
        return (entry.deadline is not None
                or entry.request.chunk is not None
                or entry.escalate)

    def _form_batch(self, head: _Entry) -> List[_Entry]:
        if self._solo(head):
            return [head]
        cohort = self._cohort(head.request)
        batch = [head]
        ids = {head.request.request_id}
        taints = set(head.taint)
        kept = deque()
        while self._queue and len(batch) < self.policy.max_batch:
            e = self._queue.popleft()
            compatible = (
                not self._solo(e)
                and self._cohort(e.request) == cohort
                and e.request.request_id not in taints
                and not (ids & e.taint)
            )
            if compatible:
                batch.append(e)
                ids.add(e.request.request_id)
                taints |= e.taint
            else:
                kept.append(e)
        kept.extend(self._queue)
        self._queue = kept
        return batch

    def _load_level(self, depth: int) -> int:
        frac = depth / self.policy.capacity
        d = self.policy.degradation
        if frac >= d.downshift_precision_at:
            return 3
        if frac >= d.cap_iterations_at:
            return 2
        if frac >= d.shrink_padding_at:
            return 1
        return 0

    # -- dispatch ------------------------------------------------------

    def _dispatch(self, batch: List[_Entry], breaker: CircuitBreaker,
                  level: int) -> None:
        from poisson_tpu.solvers.pcg import resolve_dtype

        policy = self.policy
        obs.gauge("serve.load_level", level)
        head = batch[0]
        problem = head.request.problem
        dtype = head.request.dtype
        exact_bucket = False
        if level >= 1:
            exact_bucket = True
            obs.inc("serve.degraded.padding")
        if level >= 2:
            cap = min(problem.iteration_cap,
                      policy.degradation.degraded_iteration_cap)
            problem = problem.with_(max_iter=cap)
            obs.inc("serve.degraded.iteration_cap")
        if level >= 3 and resolve_dtype(dtype) == "float64":
            dtype = "float32"
            obs.inc("serve.degraded.precision")
        if level > 0:
            obs.event("serve.degraded", level=level,
                      batch=len(batch), exact_bucket=exact_bucket,
                      iteration_cap=problem.iteration_cap, dtype=dtype)

        obs.inc("serve.dispatches")
        obs.inc("serve.batch_members", len(batch))
        cohort = self._cohort(head.request)
        try:
            with obs.span("serve.dispatch", fence=False, cohort=cohort,
                          batch=len(batch), level=level):
                if self._dispatch_fault is not None:
                    self._dispatch_fault([e.request for e in batch],
                                         {e.request.request_id: e.attempts
                                          for e in batch})
                if len(batch) == 1 and self._solo(head):
                    member_failed = self._dispatch_solo(head, problem,
                                                        dtype)
                else:
                    member_failed = self._dispatch_batched(
                        batch, problem, dtype, exact_bucket)
        except TransientDispatchError as e:
            breaker.record_failure()
            co_ids = {entry.request.request_id for entry in batch}
            for entry in batch:
                self._retry_or_fail(entry, ERROR_TRANSIENT, str(e),
                                    co_ids - {entry.request.request_id})
            return
        except Exception as e:  # internal: surfaced, never retried
            breaker.record_failure()
            for entry in batch:
                self._error(entry, ERROR_INTERNAL,
                            f"{type(e).__name__}: {e}")
            return
        if member_failed:
            breaker.record_failure()
        else:
            breaker.record_success()

    def _dispatch_batched(self, batch: List[_Entry], problem, dtype,
                          exact_bucket: bool) -> bool:
        from poisson_tpu.solvers.batched import solve_batched

        result = solve_batched(
            problem,
            rhs_gates=[e.request.rhs_gate for e in batch],
            member_ids=[e.request.request_id for e in batch],
            dtype=dtype,
            bucket=(len(batch) if exact_bucket else None),
        )
        co_ids = {e.request.request_id for e in batch}
        iters = np.asarray(result.iterations)
        flags = np.asarray(result.flag)
        diffs = np.asarray(result.diff)
        any_failed = False
        for i, entry in enumerate(batch):
            assert result.origin[i] == entry.request.request_id
            failed = self._classify_member(
                entry, int(flags[i]), int(iters[i]), float(diffs[i]),
                restarts=0, cap=problem.iteration_cap,
                co_ids=co_ids - {entry.request.request_id},
            )
            any_failed = any_failed or failed
        return any_failed

    def _dispatch_solo(self, entry: _Entry, problem, dtype) -> bool:
        from poisson_tpu.solvers.checkpoint import pcg_solve_chunked
        from poisson_tpu.solvers.resilient import (
            DivergenceError,
            pcg_solve_resilient,
        )

        req = entry.request
        chunk = req.chunk or self.policy.default_chunk
        # The RHS gate folds into f_val so both solo drivers see it the
        # same way (the batched path uses rhs_gates for the shared-setup
        # win; a solo dispatch has nothing to share).
        solo_problem = problem.with_(f_val=problem.f_val * req.rhs_gate)
        if entry.escalate and self.policy.retry.escalate_divergence:
            obs.inc("serve.escalations")
            try:
                result = pcg_solve_resilient(
                    solo_problem, dtype=dtype, chunk=chunk,
                    deadline=entry.deadline, on_chunk=req.on_chunk,
                )
            except DivergenceError as e:
                self._error(entry, ERROR_DIVERGENCE, str(e))
                return True
        else:
            result = pcg_solve_chunked(
                solo_problem, chunk=chunk, dtype=dtype,
                deadline=entry.deadline, on_chunk=req.on_chunk,
            )
        return self._classify_member(
            entry, int(result.flag), int(result.iterations),
            float(np.max(np.asarray(result.diff))),
            restarts=int(getattr(result, "restarts", 0) or 0),
            cap=problem.iteration_cap, co_ids=set(),
        )

    # -- outcome classification ----------------------------------------

    def _classify_member(self, entry: _Entry, flag: int, iterations: int,
                         diff: float, restarts: int, cap: int,
                         co_ids: set) -> bool:
        """Turn one member's stop verdict into an outcome or a retry.
        Returns True iff this member counts as a dispatch failure for the
        breaker."""
        from poisson_tpu.solvers.pcg import (
            FLAG_CONVERGED,
            FLAG_DEADLINE,
            FLAG_NAMES,
            FLAG_NONE,
        )

        name = FLAG_NAMES.get(flag, str(flag))
        if flag == FLAG_CONVERGED:
            self._complete(entry, name, True, False, iterations, restarts,
                           diff)
            return False
        if flag == FLAG_DEADLINE:
            obs.inc("serve.deadline.expired_mid_solve")
            self._complete(entry, name, False, True, iterations, restarts,
                           diff)
            return False
        if flag == FLAG_NONE:
            # Budget exhausted without a failure verdict (incl. the
            # degraded iteration cap): the partial iterate is the answer
            # the policy bought.
            self._complete(entry, "cap_hit", False, True, iterations,
                           restarts, diff)
            return False
        # breakdown / nonfinite / stagnated: divergence-class failure.
        self._retry_or_fail(entry, ERROR_DIVERGENCE,
                            f"solver stopped: {name} at iteration "
                            f"{iterations}", co_ids)
        return True

    def _retry_or_fail(self, entry: _Entry, error_type: str, message: str,
                       co_ids: set) -> None:
        entry.attempts += 1
        entry.last_failure = error_type
        max_attempts = (entry.request.max_attempts
                        or self.policy.retry.max_attempts)
        if entry.attempts >= max_attempts:
            self._error(entry, error_type,
                        f"{message} (attempt {entry.attempts}/"
                        f"{max_attempts})")
            return
        delay = self._backoff_delay(entry.attempts)
        if entry.deadline is not None:
            remaining = entry.deadline.remaining()
            if remaining is not None and remaining <= delay:
                obs.inc("serve.deadline.expired_in_queue")
                self._shed(entry, SHED_DEADLINE_EXPIRED,
                           f"deadline cannot survive the {delay:.3f}s "
                           f"retry backoff after: {message}")
                return
        # Mutual taint: this member never shares a bucket with its failed
        # batchmates again (and vice versa, applied on their entries) —
        # a poisoned member cannot re-kill the same cohort twice.
        entry.taint |= co_ids
        entry.escalate = (error_type == ERROR_DIVERGENCE
                          and self.policy.retry.escalate_divergence)
        entry.not_before = self._clock() + delay
        obs.inc("serve.retries")
        obs.inc("serve.backoff_seconds", delay)
        if co_ids:
            obs.inc("serve.requeued.isolated")
        obs.event("serve.retry", request_id=str(entry.request.request_id),
                  attempt=entry.attempts, delay=round(delay, 4),
                  error=error_type, escalate=entry.escalate)
        self._delayed.append(entry)

    def _backoff_delay(self, attempt: int) -> float:
        r = self.policy.retry
        base = min(r.backoff_base * (2 ** (attempt - 1)), r.backoff_cap)
        # Jitter over [1-jitter, 1]: decorrelates retries without ever
        # exceeding the cap. Seeded RNG — deterministic campaigns.
        return base * (1.0 - r.jitter * self._rng.random())

    # -- outcome recording ---------------------------------------------

    def _record(self, outcome: Outcome) -> Outcome:
        self._pending_ids.discard(outcome.request_id)
        self._outcomes[outcome.request_id] = outcome
        self._order.append(outcome.request_id)
        self._latencies.append(outcome.latency_seconds)
        obs.gauge("serve.queue_depth",
                  len(self._queue) + len(self._delayed))
        return outcome

    def _latency(self, entry: _Entry) -> float:
        return max(0.0, self._clock() - entry.admitted_at)

    def _complete(self, entry: _Entry, flag: str, converged: bool,
                  partial: bool, iterations: int, restarts: int,
                  diff: float) -> Outcome:
        self._counts["completed"] += 1
        obs.inc("serve.completed")
        if partial:
            obs.inc("serve.completed.partial")
        if restarts:
            obs.inc("serve.completed.recovered")
        return self._record(Outcome(
            request_id=entry.request.request_id, kind=OUTCOME_RESULT,
            flag=flag, converged=converged, partial=partial,
            iterations=iterations, restarts=restarts,
            attempts=entry.attempts + 1,
            latency_seconds=self._latency(entry), diff=diff,
        ))

    def _error(self, entry: _Entry, error_type: str, message: str
               ) -> Outcome:
        self._counts["errors"] += 1
        obs.inc("serve.errors")
        obs.inc(f"serve.errors.{error_type}")
        obs.event("serve.error", request_id=str(entry.request.request_id),
                  error=error_type, message=message[:200])
        return self._record(Outcome(
            request_id=entry.request.request_id, kind=OUTCOME_ERROR,
            error_type=error_type, message=message,
            attempts=max(1, entry.attempts),
            latency_seconds=self._latency(entry),
        ))

    def _shed(self, entry: _Entry, reason: str, message: str) -> Outcome:
        self._counts["shed"] += 1
        obs.inc("serve.shed")
        obs.inc(f"serve.shed.{reason}")
        obs.event("serve.shed", request_id=str(entry.request.request_id),
                  reason=reason)
        return self._record(Outcome(
            request_id=entry.request.request_id, kind=OUTCOME_SHED,
            shed_reason=reason, message=message,
            attempts=entry.attempts,
            latency_seconds=self._latency(entry),
        ))

    # -- accounting ----------------------------------------------------

    def outcomes(self) -> List[Outcome]:
        """Every outcome so far, in completion order."""
        return [self._outcomes[rid] for rid in self._order]

    def stats(self) -> dict:
        """The ledger: admitted vs terminated (the no-lost-request
        invariant is ``lost == 0`` once the queue is drained), latency
        percentiles on the service clock, and the shed rate."""
        c = dict(self._counts)
        pending = len(self._queue) + len(self._delayed)
        lats = sorted(self._latencies)
        return {
            "admitted": c["admitted"],
            "completed": c["completed"],
            "errors": c["errors"],
            "shed": c["shed"],
            "pending": pending,
            "lost": c["admitted"] - (c["completed"] + c["errors"]
                                     + c["shed"]) - pending,
            "latency_seconds": {
                "p50": _percentile(lats, 0.50),
                "p95": _percentile(lats, 0.95),
                "p99": _percentile(lats, 0.99),
            },
            "shed_rate": (c["shed"] / c["admitted"] if c["admitted"]
                          else 0.0),
            "breakers": {cohort: b.state
                         for cohort, b in self._breakers.items()},
        }

    def _publish_stats(self) -> None:
        s = self.stats()
        obs.gauge("serve.latency_seconds", s["latency_seconds"])
        obs.gauge("serve.p99_latency_seconds",
                  s["latency_seconds"]["p99"])
        obs.gauge("serve.shed_rate", round(s["shed_rate"], 6))
        obs.gauge("serve.queue_depth", s["pending"])
        obs.gauge("serve.lost_requests", s["lost"])
