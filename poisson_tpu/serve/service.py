"""The solve service: request lifecycle over the existing solvers.

Everything below PR 3's batched dispatch already exists — one traced
program, hundreds of Poisson problems per dispatch — but a fault
mid-batch lost every co-batched request with it, and nothing bounded how
much work could pile up behind a wedged cohort. This module adds the
request level (the shape Orca, PAPERS.md, gives a serving stack):

- **bounded admission** — a queue of at most ``policy.capacity``
  requests; admission beyond it is a typed ``queue_full`` shed, never
  unbounded growth;
- **deadlines** — propagated into chunked solves (chunk-boundary checks,
  ``solvers.checkpoint``); expiry returns the partial iterate flagged
  ``deadline``, and a request whose budget dies while queued is shed
  without burning a dispatch;
- **retry with exponential backoff + jitter** — transient dispatch
  faults re-enqueue every member into a *different* bucket (mutual
  taint: one poisoned member cannot re-kill its batchmates);
  divergence-class member failures escalate through the self-healing
  driver (``solvers.resilient``);
- **circuit breaking** — per (grid, dtype, backend) cohort
  (``serve.breaker``), trip / cooldown / half-open probes;
- **graceful degradation** — the documented policy ladder
  (``types.DegradationPolicy``) driven by queue depth, every step
  audible as ``serve.degraded.*`` counters;
- **the ledger invariant** — every admitted request terminates with
  exactly one typed outcome; ``stats()['lost']`` is computed, asserted
  by the chaos campaign, and exported with the ``serve.*`` counters;
- **flight recording** (``obs.flight``) — every admitted request gets a
  trace id and a causal span tree (admit → queue_wait → lane_resident
  with chunk-step points → backoff_wait/retry → one typed outcome leaf)
  on the JSONL rails, a latency decomposition on its Outcome
  (components summing to the measured wall), and SLO accounting
  (``serve.slo.*`` counters/histogram/burn rates) that the degradation
  ladder can consult (``SLOPolicy.degrade_on_burn``).

- **the solve fleet** (``serve.fleet``) — ``FleetPolicy(workers=N)``
  runs N supervised dispatch contexts over this one queue and ledger:
  sticky bucket executables, per-worker breaker cohorts and lane
  tables, heartbeat watchdogs; a crashed/hung worker is quarantined,
  its in-flight requests recovered onto the survivors, and it restarts
  through warm-up;
- **durability** (``serve.journal``) — an optional CRC-sealed
  write-ahead journal records every transition, and
  :meth:`SolveService.recover` replays it after a crash: prior outcomes
  are deduplicated, pending requests re-enqueue as ``serve.recovered``
  (never re-admitted), and the merged per-process snapshots close the
  invariant across the kill/replay boundary.

The service is deliberately single-threaded and clock/sleep-injectable:
the dispatch loop IS the unit under chaos test, and determinism (seeded
jitter, virtual clocks) is what makes the chaos campaign a regression
suite instead of a flake generator. Fleet workers are cooperatively
scheduled dispatch contexts on that same loop — the supervisor state
machine (quarantine, restart, recovery) is the deterministic substrate
chaos needs; mapping workers onto OS threads or processes is a
deployment concern the API does not preclude.
"""

from __future__ import annotations

import random
import time
from collections import deque
from typing import Callable, List, Optional, Sequence, Union

import numpy as np

from poisson_tpu import obs
from poisson_tpu.obs.costs import apportion_compute
from poisson_tpu.obs.flight import (
    POINT_DEADLINE,
    POINT_FORECAST_SHED,
    POINT_PLACEMENT,
    POINT_QUARANTINE,
    POINT_RECOVERED,
    POINT_REFORECAST,
    POINT_RETRY,
    POINT_WARM_FALLBACK,
    SPAN_BACKOFF,
    SPAN_QUEUE,
    SPAN_RESIDENT,
    FlightRecorder,
    SLOTracker,
)
from poisson_tpu.geometry.dsl import fingerprint_of
from poisson_tpu.serve.breaker import CircuitBreaker
from poisson_tpu.serve.deadline import Deadline
from poisson_tpu.serve.fleet import (
    WORKER_DEAD,
    WORKER_QUARANTINED,
    WORKER_RUNNING,
    DeviceLossError,
    Worker,
    WorkerCrashError,
    WorkerHangError,
    WorkerPool,
)
from poisson_tpu.serve.placement import PlacementError
from poisson_tpu.krylov import DEFAULT_KRYLOV as DEFAULT_KRYLOV_POLICY
from poisson_tpu.serve.types import (
    ERROR_DIVERGENCE,
    ERROR_INTEGRITY,
    ERROR_INTERNAL,
    ERROR_PLACEMENT,
    ERROR_TRANSIENT,
    OUTCOME_ERROR,
    OUTCOME_RESULT,
    OUTCOME_SHED,
    Outcome,
    SCHED_CONTINUOUS,
    SCHED_DRAIN,
    ServicePolicy,
    SHED_BREAKER_OPEN,
    SHED_DEADLINE_EXPIRED,
    SHED_PREDICTED_DEADLINE,
    SHED_QUEUE_FULL,
    SHED_QUOTA_EXCEEDED,
    SolveRequest,
    TransientDispatchError,
)


class _Entry:
    """Queue-resident lifecycle state for one admitted request."""

    __slots__ = ("request", "admitted_at", "deadline", "attempts",
                 "taint", "taint_fp", "not_before", "escalate",
                 "last_failure", "iter_cap", "recovered",
                 "eta", "history", "spi")

    def __init__(self, request: SolveRequest, admitted_at: float,
                 deadline: Optional[Deadline]):
        self.request = request
        self.admitted_at = admitted_at
        self.deadline = deadline
        self.attempts = 0          # dispatches so far
        self.taint: set = set()    # request_ids never to co-batch with again
        # Geometry FINGERPRINTS never to co-batch with again: taint keys
        # on (request, fingerprint), so a geometry family implicated in
        # a batch kill is excluded wholesale — a fresh request carrying
        # the same bad fingerprint cannot re-kill this entry either.
        self.taint_fp: set = set()
        self.not_before = 0.0      # backoff gate (service clock)
        self.escalate = False      # next dispatch via the resilient driver
        self.last_failure = ""
        self.iter_cap = None       # degraded per-member cap (lane splices)
        self.recovered = False     # pulled off a dead worker / the journal
        self.eta = None            # admission forecast p50 ETA (seconds)
        self.history = []          # (k, diff) lane-boundary residual ring
        self.spi = 0.0             # measured seconds/iteration (this entry)


def _geo_fps(entries) -> set:
    """The geometry fingerprints present in a batch of entries —
    the (request, fingerprint) taint unit. Requests with no geometry
    contribute nothing: the 'default' path is not a suspect family
    (request-id taint already isolates those pairs)."""
    return {fingerprint_of(e.request.geometry) for e in entries
            if e.request.geometry is not None}


def _percentile(sorted_vals: Sequence[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted sample."""
    if not sorted_vals:
        return 0.0
    idx = max(0, min(len(sorted_vals) - 1,
                     int(np.ceil(q * len(sorted_vals))) - 1))
    return float(sorted_vals[idx])


def p99_exemplar(outcomes) -> Optional[dict]:
    """The outcome whose latency IS the nearest-rank p99 — the exemplar
    trace id bench records and the fire drill attach, so a p99 number
    is always traceable to the request that paid it (the flight
    recorder's `trace` CLI renders it end to end)."""
    if not outcomes:
        return None
    ranked = sorted(outcomes, key=lambda o: o.latency_seconds)
    idx = max(0, min(len(ranked) - 1,
                     -(-99 * len(ranked) // 100) - 1))   # stdlib ceil
    o = ranked[idx]
    return {"request_id": o.request_id, "trace_id": o.trace_id,
            "latency_seconds": round(o.latency_seconds, 4)}


def slowest_requests(outcomes, n: int = 3) -> list:
    """Top-N slowest outcomes with their latency decompositions — the
    bench/fire-drill ``detail`` block that makes a bad percentile
    diagnosable (where did THIS request's latency go) instead of just
    reportable."""
    ranked = sorted(outcomes, key=lambda o: -o.latency_seconds)[:n]
    return [{"request_id": o.request_id, "trace_id": o.trace_id,
             "latency_seconds": round(o.latency_seconds, 4),
             "kind": o.kind,
             "decomposition": o.decomposition} for o in ranked]


class SolveService:
    """Single-process solve service over the JAX solver stack.

    ``submit`` admits a request (or sheds it, typed, immediately);
    ``drain`` runs the dispatch loop until every admitted request has its
    outcome. ``clock``/``sleep`` default to real monotonic time; chaos
    scenarios inject a :class:`testing.chaos.VirtualClock` pair.
    ``dispatch_fault`` is the service-level fault seam: called with the
    entry batch immediately before the solver runs, it may raise
    :class:`TransientDispatchError` (a device-level batch kill) or stall
    on the injected clock (a slow worker).
    """

    def __init__(self, policy: Optional[ServicePolicy] = None, *,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Optional[Callable[[float], None]] = None,
                 seed: int = 0,
                 dispatch_fault: Optional[Callable] = None,
                 worker_fault: Optional[Callable] = None,
                 journal=None):
        self.policy = policy or ServicePolicy()
        if self.policy.capacity < 1:
            raise ValueError("service capacity must be >= 1")
        if self.policy.retry.max_attempts < 1:
            raise ValueError("retry.max_attempts must be >= 1")
        if self.policy.scheduling not in (SCHED_DRAIN, SCHED_CONTINUOUS):
            raise ValueError(
                f"scheduling must be {SCHED_DRAIN!r} or "
                f"{SCHED_CONTINUOUS!r}, got {self.policy.scheduling!r}"
            )
        if self.policy.refill_chunk < 1:
            raise ValueError("refill_chunk must be >= 1")
        self._clock = clock
        self._sleep = sleep if sleep is not None else time.sleep
        self._rng = random.Random(seed)
        self._dispatch_fault = dispatch_fault
        # The worker-fault seam: called as (worker_id, requests,
        # attempts) right where dispatch_fault is, it may raise
        # WorkerCrashError/WorkerHangError — faults of the WORKER, which
        # quarantine it and recover its in-flight requests, where
        # dispatch faults only cost the dispatch.
        self._worker_fault = worker_fault
        # Write-ahead journal (serve.journal.SolveJournal or None):
        # every lifecycle transition below is recorded before the
        # in-memory ledger moves, so a crash can be replayed.
        self._journal = journal
        self._queue: deque = deque()
        self._delayed: List[_Entry] = []
        self._pending_ids: set = set()  # ids queued or backing off
        self._outcomes: dict = {}
        self._prior_outcomes: dict = {}  # journal-replayed (pre-crash)
        self._recovered_ids: set = set()  # str ids that came via replay
        self._order: List = []          # outcome completion order
        self._latencies: List[float] = []
        self._counts = {"admitted": 0, "completed": 0, "errors": 0,
                        "shed": 0, "recovered": 0}
        # SDC-suspect hardware cohorts (poisson_tpu.integrity): the
        # (backend, device_kind) pairs on which an integrity detection
        # has already fired. With integrity.verify_on_suspect, later
        # dispatches on a tainted cohort run defensively verified even
        # when the policy default is off — a core that miscomputed once
        # is the textbook mercurial core (Hochschild et al. 2021).
        self._suspect_hw: set = set()
        # Basis-holder stickiness (poisson_tpu.krylov.recycle): which
        # worker last harvested/used each geometry fingerprint's
        # deflation basis. Routing prefers the holder for
        # deflation-class heads (serve.krylov.sticky_{hits,misses}) —
        # the second stickiness axis beside bucket executables: on a
        # real fleet the basis lives in the holder's device memory.
        self._basis_holder: dict = {}
        # The worker pool: N dispatch contexts over this one queue and
        # ledger (serve.fleet; workers=1 is the classic single-worker
        # service — same scheduling decisions, same golden outcomes).
        # The pool's device registry (serve.placement) binds every
        # worker to a fault-domain slot; fleet.devices=None keeps the
        # pre-placement topology (one slot, the default device).
        self._pool = WorkerPool(self.policy.fleet, clock=clock)
        self._registry = self._pool.registry
        # The worker whose dispatch is currently on the hot path —
        # single-threaded by design, so hardware-cohort attribution
        # (suspect taint) can name the device without threading the
        # worker through every classification call.
        self._active_worker: Optional[Worker] = None
        # Flight recorder + SLO tracker (obs.flight): per-request causal
        # span trees on the service clock, latency decomposition on
        # every outcome, and the serve.slo.* accounting the degradation
        # ladder may consult. Host-side bookkeeping only — deterministic
        # under VirtualClock, no-op on the JSONL rails when telemetry is
        # unconfigured.
        self._flight = FlightRecorder(clock=clock)
        self._slo = SLOTracker(self.policy.slo, clock=clock)
        # Tenant ledger (serve.tenancy, ServicePolicy.tenancy): quota
        # buckets, deficit-weighted round-robin counters, and retry
        # budgets per tenant — plus one SLOTracker per tenant publishing
        # under serve.tenant.slo.<tenant> so a noisy neighbor's burn is
        # attributable without touching the global serve.slo.* surface.
        # None (the default) is the strict-FIFO service of every prior
        # release, byte-compatible.
        self._tenancy = None
        self._tenant_slo: dict = {}
        self._offender: Optional[str] = None
        if self.policy.tenancy is not None:
            from poisson_tpu.serve.tenancy import TenantLedger

            self._tenancy = TenantLedger(self.policy.tenancy, clock=clock)
        # Iteration forecaster (obs.forecast, ServicePolicy.forecast):
        # per-cohort iteration/cost estimator behind predicted-deadline
        # admission, lane re-forecast preemption, and the ETA backlog
        # gauge. Journal-adjacent snapshot warm-loads across restarts —
        # a recovered service predicts from its previous life's
        # calibration instead of re-entering the cold-model regime.
        self._forecast = None
        if self.policy.forecast is not None:
            from poisson_tpu.obs.forecast import (ForecastModel,
                                                  snapshot_path)

            self._forecast = ForecastModel()
            if self._journal is not None:
                self._forecast.load(snapshot_path(self._journal.path))
        # Roofline observatory (obs.roofline): always-on measured
        # bandwidth attribution — every measured dispatch and lane
        # chunk-step grades its achieved GB/s against the analytic
        # bytes/iter model for its cohort. Observation never changes
        # compiled programs (the counters-pillar rule), so unlike the
        # forecaster it does not hide behind a policy knob. Its
        # journal-adjacent snapshot warm-loads across restarts for the
        # same reason the forecaster's does: a recovered service routes
        # from its previous life's measured evidence.
        from poisson_tpu.obs.roofline import RooflineModel
        from poisson_tpu.obs.roofline import \
            snapshot_path as _roofline_snapshot

        self._roofline = RooflineModel()
        if self._journal is not None:
            self._roofline.load(_roofline_snapshot(self._journal.path))
        # Backend router (serve.router, ServicePolicy.router): cohort
        # backend choice from the analytic model cold and the roofline
        # profiles warm, with misprediction sentinels demoting
        # (backend, device) arms breaker-style. None = off = every
        # cohort string and program byte-identical to prior releases.
        self._router = None
        self._active_decision = None
        if self.policy.router is not None:
            from poisson_tpu.serve.router import BackendRouter

            self._router = BackendRouter(self.policy.router,
                                         self._roofline, clock=clock)
        if self._journal is not None:
            # The journal opens with this incarnation's topology, so a
            # recovery on a DIFFERENT topology can see the change and
            # remap audibly instead of resuming onto ghost device ids.
            self._journal.record("topology", **self._registry.describe())

    # -- admission -----------------------------------------------------

    def submit(self, request: SolveRequest) -> Optional[Outcome]:
        """Admit ``request`` into the ledger. Returns the typed Outcome
        immediately iff the request was shed at admission (queue full);
        None when it was queued — its outcome arrives via :meth:`drain`.
        Either way the request is admitted for accounting: exactly one
        typed outcome will exist for it.

        With ``policy.dedup`` on, a re-submitted ``request_id`` is an
        idempotent no-op: the original outcome comes back (None while
        still pending), a ``serve.dedup.hits`` is counted, and nothing
        is re-admitted — a client retry or a replayed submission can
        never double-enter the ledger."""
        # The journal stringifies ids, so a recovered/replayed request
        # lives under str(id): a client retry with the original (e.g.
        # int) id must still hit the guard. The str-spelling check is
        # scoped to ids that actually came through a replay
        # (_recovered_ids) — outside recovery, distinct ids that merely
        # collide under str() (1 vs "1") stay distinct requests.
        # Preconditioner validation happens AT ADMISSION, loudly: an MG
        # request on an uncoarsenable grid (odd dimensions) would
        # otherwise burn a dispatch and surface as an opaque internal
        # error; a typo'd preconditioner name must never silently run
        # jacobi. Same caller-bug contract as the duplicate-id check.
        pre = request.preconditioner or self.policy.preconditioner
        if pre not in (None, "jacobi"):
            from poisson_tpu.mg import (
                resolve_preconditioner,
                validate_mg_problem,
            )

            resolve_preconditioner(pre)
            validate_mg_problem(request.problem)
        # Krylov-memory validation, same loud-at-admission contract: an
        # unknown mode / block+deflation never enters the queue, and
        # the uncomposable combinations are caller bugs, not dispatch
        # surprises.
        kp = self._krylov(request)
        if kp != DEFAULT_KRYLOV_POLICY:
            from poisson_tpu.krylov import resolve_krylov

            resolve_krylov(kp)
            if kp.mode == "block" and pre not in (None, "jacobi"):
                raise ValueError(
                    "krylov mode='block' composes with the jacobi body "
                    f"only (preconditioner={pre!r} has no block "
                    "program)")
            if kp.deflation:
                if pre not in (None, "jacobi"):
                    raise ValueError(
                        "krylov deflation composes with the jacobi "
                        f"body only (preconditioner={pre!r} has no "
                        "deflated program)")
                if (request.deadline_seconds is not None
                        or request.chunk is not None):
                    raise ValueError(
                        "krylov deflation does not ride the chunked/"
                        "deadline path yet — drop deadline_seconds/"
                        "chunk or deflation")
        # Session-step validation, same loud-at-admission contract
        # (serve.session): a session step runs the fused session
        # programs — warm restart / implicit-Euler shift — which do not
        # compose with the chunked driver, non-jacobi preconditioner
        # bodies, or Krylov block/deflation memory; and the session
        # fields are meaningless outside a session.
        if request.session_id is not None:
            if kp != DEFAULT_KRYLOV_POLICY:
                raise ValueError(
                    "session steps do not compose with krylov "
                    f"block/deflation (session {request.session_id!r}) "
                    "— the warm-start seam IS the session's solver "
                    "memory")
            if pre not in (None, "jacobi"):
                raise ValueError(
                    "session steps run the fused jacobi session "
                    f"programs only (preconditioner={pre!r})")
            if request.chunk is not None:
                raise ValueError(
                    "session steps are fused single-program solves — "
                    "per-step deadlines are checked at step boundaries; "
                    "drop chunk")
        elif (request.warm_start is not None
              or request.warm_geometry is not None
              or request.session_step is not None
              or request.mass_shift):
            raise ValueError(
                "warm_start/warm_geometry/session_step/mass_shift "
                "require session_id — session semantics do not attach "
                "to per-request traffic")
        # A placement pin outside the fleet topology — or to a healthy
        # device no worker is bound to (the pin could never be served)
        # — is a caller bug, loud at admission (same contract as a
        # typo'd preconditioner). A pin to a device that DIED is
        # admitted and becomes a typed ``placement`` error at dispatch
        # — the silicon's fate is not the caller's mistake.
        if request.device_id is not None:
            pin = int(request.device_id)
            if not 0 <= pin < len(self._registry):
                raise ValueError(
                    f"device_id {request.device_id} outside the fleet "
                    f"topology (devices 0..{len(self._registry) - 1})")
            if (self._registry.is_alive(pin)
                    and not self._pool.workers_on_device(pin)):
                raise ValueError(
                    f"device_id {pin} has no worker bound to it "
                    "(workers bind round-robin over the device slots; "
                    "size fleet.workers >= the highest pinned slot + 1)")
        rid = request.request_id
        recovered_twin = str(rid) in self._recovered_ids
        seen = (rid in self._outcomes or rid in self._prior_outcomes
                or rid in self._pending_ids or recovered_twin)
        if seen:
            if not self.policy.dedup:
                raise ValueError(
                    f"duplicate request_id {request.request_id!r} — the "
                    "one-outcome-per-request ledger needs unique ids"
                )
            obs.inc("serve.dedup.hits")
            obs.event("serve.dedup.hit",
                      request_id=str(request.request_id))
            out = (self._outcomes.get(rid)
                   or self._prior_outcomes.get(rid))
            if out is None and recovered_twin:
                out = (self._outcomes.get(str(rid))
                       or self._prior_outcomes.get(str(rid)))
            return out
        self._counts["admitted"] += 1
        obs.inc("serve.admitted")
        tenant = self._tenant(request)
        if tenant is not None:
            obs.inc(f"serve.tenant.admitted.{tenant}")
        trace_id = self._flight.admit(request.request_id)  # trace root
        if self._journal is not None:
            self._journal.submit(request, trace_id)
        now = self._clock()
        deadline = (Deadline(request.deadline_seconds, clock=self._clock)
                    if request.deadline_seconds is not None else None)
        entry = _Entry(request, now, deadline)
        if self._tenancy is not None and not self._tenancy.admit(tenant):
            # Per-tenant token-bucket quota: over-quota is a typed shed
            # with ZERO compute burned — refused here, before any
            # dispatch, through the same _shed path as queue_full, so
            # the ledger invariant closes unchanged and one hot client
            # cannot convert its overload into everyone's queue time.
            obs.inc("serve.tenant.quota_sheds")
            return self._shed(
                entry, SHED_QUOTA_EXCEEDED,
                f"tenant {tenant!r} over admission quota "
                f"({self.policy.tenancy.quota_rate:g}/s × share "
                f"{self._tenancy.share_of(tenant):g})")
        depth = len(self._queue) + len(self._delayed)
        if depth >= self.policy.capacity:
            return self._shed(entry, SHED_QUEUE_FULL,
                              "admission queue at capacity "
                              f"({self.policy.capacity})")
        if self._forecast is not None:
            fc = self._forecast_predict(request)
            entry.eta = fc.eta_p50_seconds
            fp = self.policy.forecast
            if fp.admission_shed and deadline is not None:
                # Predicted-deadline admission: a request whose p90 ETA
                # already exceeds its budget is shed HERE, typed, with
                # zero compute burned — never admitted-then-doomed.
                obs.inc("serve.forecast.admission_checks")
                if fc.eta_p90_seconds * fp.margin > request.deadline_seconds:
                    self._flight.point(
                        request.request_id, POINT_FORECAST_SHED,
                        eta=round(fc.eta_p90_seconds, 6),
                        deadline=request.deadline_seconds)
                    return self._shed(
                        entry, SHED_PREDICTED_DEADLINE,
                        f"p90 ETA {fc.eta_p90_seconds:.3g}s exceeds "
                        f"deadline {request.deadline_seconds:.3g}s "
                        f"(cohort {fc.cohort}, "
                        f"{'cold' if fc.cold else 'calibrated'} model)")
        self._pending_ids.add(request.request_id)
        if tenant is not None:
            self._flight.begin(request.request_id, SPAN_QUEUE,
                               tenant=tenant)
        else:
            self._flight.begin(request.request_id, SPAN_QUEUE)
        self._queue.append(entry)
        obs.gauge("serve.queue_depth", len(self._queue) + len(self._delayed))
        return None

    # -- lifecycle loop ------------------------------------------------

    def drain(self) -> List[Outcome]:
        """Run the dispatch loop until no admitted request is pending;
        returns every outcome reached during this drain, in completion
        order. Publishes the ``serve.*`` stats gauges afterwards."""
        start = len(self._order)
        while self.pump():
            pass
        self._publish_stats()
        return [self._outcomes[rid] for rid in self._order[start:]]

    def pump(self) -> bool:
        """One scheduling step of the configured engine — a full
        dispatch in drain mode, one chunk-and-refill cycle in continuous
        mode. Returns False when no admitted request is pending. This is
        the open-loop seam: a load generator interleaves ``submit`` with
        ``pump`` so arrivals can join work already in flight
        (``bench.py --serve --arrival-rate``).

        With a multi-worker fleet, each pump schedules ONE worker
        (sticky-preferred, else round-robin), restarting due-quarantined
        workers through warm-up first; with no runnable worker the pump
        either waits out the earliest quarantine or — the whole fleet
        dead — fails the remaining backlog with typed internal errors,
        so the ledger invariant survives even total fleet loss."""
        self._restart_due_workers()
        if self._tenancy is not None:
            # Weighted-fair head selection happens ONCE per pump,
            # before any head-based routing (placement pin, basis
            # stickiness, sticky cohort) reads queue[0]: pull due
            # backed-off entries in first so the DWRR pick sees the
            # real backlog, then rotate the picked tenant's oldest
            # entry to the head. FIFO order *within* a tenant is
            # preserved — shares reorder across tenants only.
            self._pump_delayed()
            self._promote_tenant_head()
        pinned = self._pinned_head_worker()
        if pinned is not None:
            worker, verdict = pinned
            if worker is None:
                return verdict       # head errored typed / waited out
        else:
            worker = (self._basis_sticky_worker()
                      or self._pool.next_worker(self._head_cohort()))
        if worker is None:
            return self._no_worker_step()
        # Beat only when the step has work: the beat marks the step's
        # START (the baseline the post-step stall check measures from),
        # and an idle open-loop pump must neither flood the telemetry
        # rails with no-op beats nor let idle wait read as a stall.
        active = bool(self._queue or self._delayed
                      or (worker.table is not None
                          and worker.table.occupied()))
        if active:
            worker.watchdog.beat(worker=worker.id)
        # The scheduled worker is the hardware-attribution context for
        # everything this step does (dispatch, retire classification,
        # suspect-cohort taint) — see _hw_cohort.
        self._active_worker = worker
        try:
            if self.policy.scheduling == SCHED_CONTINUOUS:
                progressed = self._step_continuous(worker)
            else:
                progressed = self._step(worker)
        finally:
            self._active_worker = None
        if active:
            self._post_step_health(worker)
        return progressed

    # -- fleet supervision ---------------------------------------------

    @property
    def _table(self):
        """Worker 0's live lane table — the pre-fleet single-worker
        view (tables are per worker now; multi-worker callers inspect
        ``self._pool.workers[i].table``)."""
        return self._pool.workers[0].table

    def _head_cohort(self) -> Optional[str]:
        if not self._queue:
            return None
        return self._cohort(self._queue[0].request)

    # -- tenant isolation (serve.tenancy) ------------------------------

    def _tenant(self, request: SolveRequest) -> Optional[str]:
        """The request's ledger tenant — None iff tenancy is off (the
        tenant field is then inert metadata, costing nothing)."""
        if self._tenancy is None:
            return None
        return self._tenancy.resolve(request.tenant)

    def _tenant_slo_tracker(self, tenant: str) -> SLOTracker:
        tracker = self._tenant_slo.get(tenant)
        if tracker is None:
            tracker = SLOTracker(self.policy.slo, clock=self._clock,
                                 prefix=f"serve.tenant.slo.{tenant}")
            self._tenant_slo[tenant] = tracker
        return tracker

    def _promote_tenant_head(self) -> None:
        """Deficit-weighted round-robin head selection: rotate the
        picked tenant's oldest queued entry to the queue front. One
        pick per pump — over any window the dispatch-head mix
        converges to the share vector regardless of arrival order."""
        if len(self._queue) < 2:
            return
        backlogged = sorted({self._tenant(e.request) for e in self._queue})
        if len(backlogged) < 2:
            return
        pick = self._tenancy.pick(backlogged)
        if self._tenant(self._queue[0].request) == pick:
            return
        for i, entry in enumerate(self._queue):
            if self._tenant(entry.request) == pick:
                del self._queue[i]
                self._queue.appendleft(entry)
                obs.inc("serve.tenant.promotions")
                return

    def _tenant_offender(self) -> Optional[str]:
        """The tenant whose backlog most exceeds its share — the one
        the degradation ladder downshifts first (tenant-scoped
        Hochschild-style indictment: blame the client, not the
        queue)."""
        backlog: dict = {}
        for entry in list(self._queue) + self._delayed:
            t = self._tenant(entry.request)
            backlog[t] = backlog.get(t, 0) + 1
        return self._tenancy.offender(backlog)

    def _tenant_level(self, entry: _Entry, level: int,
                      count: bool = False) -> int:
        """Tenant-scoped degradation: the offending tenant pays the
        full queue-pressure rung, every other tenant runs one rung
        gentler (``TenancyPolicy.isolate_degradation``). ``count``
        makes the spared/charged decision audible — set only at the
        application sites (dispatch, lane splice), not in cohort
        probes, so the counters read as decisions, not scans."""
        if (self._tenancy is None or level <= 0
                or not self.policy.tenancy.isolate_degradation
                or self._offender is None):
            return level
        if self._tenant(entry.request) == self._offender:
            if count:
                obs.inc("serve.tenant.degraded_offender")
            return level
        if count:
            obs.inc("serve.tenant.degraded_spared")
        return max(0, level - 1)

    def _pinned_head_worker(self):
        """Placement-pinned head scheduling. None: the head is unpinned
        (or no head) — ordinary routing applies. Otherwise a
        ``(worker, progressed)`` pair: a live worker bound to the
        pinned device, or ``(None, True)`` when the step was consumed
        resolving the pin — a dead device or a worker-less domain is a
        typed ``placement`` error (never a wedge), a quarantined
        domain waits out the earliest release."""
        if not self._queue or self._queue[0].request.device_id is None:
            return None
        pin = int(self._queue[0].request.device_id)
        if not self._registry.is_alive(pin):
            head = self._queue.popleft()
            self._error(head, ERROR_PLACEMENT,
                        f"pinned device {pin} is lost (placement epoch "
                        f"{self._registry.epoch})")
            return (None, True)
        bound = self._pool.workers_on_device(pin)
        live = [w for w in bound if w.state == WORKER_RUNNING]
        if live:
            return (live[0], True)
        waiting = [w.quarantined_until for w in bound
                   if w.state == WORKER_QUARANTINED]
        if waiting:
            self._sleep(max(0.0, min(waiting) - self._clock()))
            return (None, True)
        head = self._queue.popleft()
        self._error(head, ERROR_PLACEMENT,
                    f"no live worker bound to pinned device {pin} "
                    f"({len(bound)} bound)")
        return (None, True)

    def _basis_sticky_worker(self):
        """Soft routing preference for deflation-class heads: the
        worker that last held this fingerprint's basis, when it is
        still RUNNING (serve.krylov.sticky_hits); otherwise ordinary
        routing applies (serve.krylov.sticky_misses — counted only for
        deflation heads with a recorded holder, so the ratio reads as
        basis-affinity effectiveness, not as generic routing traffic).
        None: not a deflation head, or no preference."""
        if not self._queue:
            return None
        head = self._queue[0]
        if not self._krylov(head.request).deflation:
            return None
        holder = self._basis_holder.get(
            fingerprint_of(head.request.geometry))
        if holder is None:
            return None
        for w in self._pool.workers:
            if w.id == holder and w.state == WORKER_RUNNING:
                obs.inc("serve.krylov.sticky_hits")
                return w
        obs.inc("serve.krylov.sticky_misses")
        return None

    def _restart_due_workers(self) -> None:
        for worker in self._pool.release_due():
            sticky = self._pool.restart(worker)
            if sticky:
                self._warm_worker(worker, sticky)

    def _note_sticky(self, worker: Worker, cohort: str, problem, dtype,
                     bucket=None, preconditioner: str = "jacobi") -> None:
        """Record that ``worker`` holds ``cohort``'s executable at
        ``bucket`` width — what routing prefers and restart warm-up
        recompiles (the preconditioner is executable identity, so the
        warm-up must rebuild the same program family)."""
        info = worker.sticky.setdefault(
            cohort, {"problem": problem, "dtype": dtype, "buckets": set(),
                     "preconditioner": preconditioner})
        if bucket:
            info["buckets"].add(int(bucket))

    def _on_device(self, worker: Worker):
        """Context manager targeting the worker's BOUND device: sticky
        executables, warm-up recompiles and lane programs all compile
        on the silicon the worker lives on — never implicitly on the
        process default device (which, after a restart or on a
        multi-device fleet, would cost a cross-device transfer plus a
        recompile on the first real dispatch)."""
        import contextlib

        if worker.placement is None or worker.placement.device is None:
            return contextlib.nullcontext()
        import jax

        return jax.default_device(worker.placement.device)

    def _warm_worker(self, worker: Worker, sticky: dict) -> None:
        """Restart warm-up: recompile (or jit-cache-hit) each sticky
        bucket executable — at the widths the worker actually
        dispatched, with degenerate zero-gate members, ON the worker's
        bound device (a rebound worker's executables must live where
        the worker now does) — before the worker takes traffic: a
        restarted worker must not absorb a compile spike into the
        first real request's latency. (Lane stepping programs
        recompile on first table build instead; with cooperative
        workers the process-wide jit cache usually makes all of this a
        cache hit — the warm-up is the guarantee, not the common
        cost.)"""
        from poisson_tpu.solvers.batched import solve_batched

        for cohort, info in sticky.items():
            for width in sorted(info["buckets"]) or [1]:
                try:
                    with self._on_device(worker):
                        solve_batched(info["problem"],
                                      rhs_gates=[0.0] * width,
                                      dtype=info["dtype"], bucket=width,
                                      preconditioner=info.get(
                                          "preconditioner", "jacobi"))
                    obs.inc("serve.fleet.warmup_solves")
                except Exception as e:   # warm-up is best-effort
                    obs.inc("serve.fleet.warmup_failures")
                    obs.event("serve.fleet.warmup_failure",
                              worker=worker.id, cohort=cohort,
                              bucket=width,
                              error=f"{type(e).__name__}: {e}")
        obs.event("serve.fleet.warmed", worker=worker.id,
                  cohorts=len(sticky),
                  device=(worker.placement.device_id
                          if worker.placement else None))

    def _post_step_health(self, worker: Worker) -> None:
        """After a step that did NOT raise a worker fault: the heartbeat
        may still show the step overran the watchdog (a slow wedge that
        eventually returned). Quarantine post hoc — outcomes the step
        produced stand; the worker does not take more traffic until it
        restarts."""
        if worker.state != WORKER_RUNNING:
            return
        if worker.watchdog.check() is not None:
            obs.inc("serve.fleet.hangs")
            self._quarantine_worker(worker, "stall")

    def _quarantine_worker(self, worker: Worker, reason: str) -> None:
        """Quarantine ``worker``, recovering any lane occupants it still
        holds (their in-flight progress died with the worker)."""
        evicted = []
        if worker.table is not None:
            evicted = worker.table.evict_all()
            worker.table = None
            for entry in evicted:
                self._flight.end(entry.request.request_id, SPAN_RESIDENT,
                                 error=reason)
        self._pool.quarantine(worker, reason)
        if evicted:
            self._recover_entries(worker, evicted, reason)

    def _recover_entries(self, worker: Worker, entries: List[_Entry],
                         reason: str) -> None:
        """Re-dispatch a fallen worker's in-flight requests to the
        survivors: mutual taint (the worker's death may have been one of
        them), recovery backoff, ``recovered``/``quarantine`` flight
        points — then the ordinary retry budget decides retry vs typed
        error."""
        co_ids = {e.request.request_id for e in entries}
        co_fps = _geo_fps(entries)
        for entry in entries:
            rid = entry.request.request_id
            entry.recovered = True
            obs.inc("serve.fleet.recovered_requests")
            self._flight.point(rid, POINT_QUARANTINE, worker=worker.id,
                               reason=reason)
            self._flight.point(rid, POINT_RECOVERED, worker=worker.id,
                               reason=reason)
            self._retry_or_fail(entry, ERROR_TRANSIENT,
                                f"worker {worker.id} {reason} "
                                "mid-dispatch", co_ids - {rid}, co_fps)

    def _handle_worker_fault(self, worker: Worker, exc: Exception,
                             entries: List[_Entry], did: str,
                             t0: float) -> None:
        """A dispatch raised a worker-level fault: close the affected
        flight spans, evict any lane occupants the worker still holds
        (a solo dispatch can crash a worker whose lane table is live),
        quarantine it, and recover everything onto the survivors. A
        :class:`DeviceLossError` widens the blast radius to the fault
        DOMAIN: the device is marked lost in the placement registry
        (epoch bump), every worker bound to it is quarantined with its
        lane occupants, and the quarantined workers rebind to
        surviving devices at restart."""
        hang = isinstance(exc, WorkerHangError)
        loss = isinstance(exc, DeviceLossError)
        reason = "device_loss" if loss else ("hang" if hang else "crash")
        if hang and worker.watchdog.check() is not None:
            obs.inc("serve.fleet.hangs")
        self._flight_dispatch_failed(entries, did, t0,
                                     type(exc).__name__)
        extra = []
        if worker.table is not None:
            known = {id(e) for e in entries}
            extra = [e for e in worker.table.evict_all()
                     if id(e) not in known]
            worker.table = None
            for entry in extra:
                self._flight.end(entry.request.request_id, SPAN_RESIDENT,
                                 error=type(exc).__name__)
        self._pool.quarantine(worker, reason)
        if loss:
            extra = extra + self._lose_device(worker, exc)
        self._recover_entries(worker, list(entries) + extra, reason)

    def _lose_device(self, worker: Worker, exc: DeviceLossError
                     ) -> List[_Entry]:
        """The fault domain died, not just the dispatching worker: mark
        the device lost (placement epoch bump, ``serve.fleet.
        device_losses``), quarantine every OTHER running worker bound
        to it, and return their evicted lane occupants — all of whom
        shared the silicon that is gone."""
        device_id = exc.device_id
        if device_id is None and worker.placement is not None:
            device_id = worker.placement.device_id
        if device_id is None:
            return []
        if self._registry.lose(int(device_id)):
            obs.inc("serve.fleet.device_losses")
            obs.event("serve.fleet.device_loss", device=int(device_id),
                      worker=worker.id, epoch=self._registry.epoch,
                      alive=len(self._registry.alive()))
        if self._journal is not None:
            self._journal.record("device_loss", device=int(device_id),
                                 epoch=self._registry.epoch)
        evicted: List[_Entry] = []
        for mate in self._pool.workers_on_device(int(device_id)):
            if mate is worker or mate.state != WORKER_RUNNING:
                continue
            if mate.table is not None:
                for entry in mate.table.evict_all():
                    self._flight.end(entry.request.request_id,
                                     SPAN_RESIDENT, error="device_loss")
                    evicted.append(entry)
                mate.table = None
            self._pool.quarantine(mate, "device_loss")
        return evicted

    def _no_worker_step(self) -> bool:
        """No runnable worker. Wait out the earliest quarantine when one
        will come back; with the whole fleet dead, every pending request
        still gets its one typed outcome — as an internal error."""
        release = self._pool.earliest_release()
        if release is not None:
            if not self._pending_ids:
                return False
            self._sleep(max(0.0, release - self._clock()))
            return True
        if not self._pool.all_dead():
            return bool(self._pending_ids)
        self._pump_delayed()
        while self._delayed:          # backoff cannot outlive the fleet
            self._queue.append(self._delayed.pop(0))
        progressed = False
        while self._queue:
            entry = self._queue.popleft()
            self._error(entry, ERROR_INTERNAL,
                        "no live workers: every worker in the fleet is "
                        "dead (restart budget exhausted)")
            progressed = True
        return progressed

    def _advance_past_backoff(self) -> bool:
        """Everything runnable is backing off: advance to the earliest
        ready time (virtual clocks advance instantly; real clocks
        sleep), force-promoting afterwards so a coarse injected clock
        can never wedge the loop. Returns False when nothing is pending
        at all."""
        if not self._delayed:
            return False
        wait = max(0.0, min(e.not_before for e in self._delayed)
                   - self._clock())
        self._sleep(wait)
        self._pump_delayed()
        if not self._queue and self._delayed:
            self._delayed.sort(key=lambda e: e.not_before)
            head = self._delayed.pop(0)
            self._end_backoff(head)
            self._queue.append(head)
        return True

    def _pop_live_head(self) -> Optional[_Entry]:
        """Pop the queue head; a head whose deadline died while queued
        is shed typed here (returns None — the ledger entry is closed)."""
        head = self._queue.popleft()
        if head.deadline is not None and head.deadline.expired():
            obs.inc("serve.deadline.expired_in_queue")
            self._flight.point(head.request.request_id, POINT_DEADLINE,
                               where="queued",
                               elapsed=round(head.deadline.elapsed(), 4))
            self._shed(head, SHED_DEADLINE_EXPIRED,
                       "deadline expired while queued")
            return None
        return head

    def _step(self, worker: Worker) -> bool:
        self._pump_delayed()
        if not self._queue and not self._advance_past_backoff():
            return False
        head = self._pop_live_head()
        if head is None:
            return True
        # Load is measured at dispatch-cycle start (head included), BEFORE
        # batch formation empties the queue — degradation responds to the
        # pressure the service is under, not to the hole a big batch just
        # carved out of it.
        level = self._load_level(len(self._queue) + len(self._delayed) + 1)
        batch = self._form_batch(head)
        breaker = self._breaker(worker, self._cohort(head.request))
        if not breaker.allow():
            for entry in batch:
                self._shed(entry, SHED_BREAKER_OPEN,
                           f"circuit breaker open for cohort "
                           f"{self._cohort(entry.request)}")
            return True
        self._dispatch(worker, batch, breaker, level)
        return True

    def _pump_delayed(self) -> None:
        now = self._clock()
        ready = [e for e in self._delayed if e.not_before <= now]
        if ready:
            self._delayed = [e for e in self._delayed
                             if e.not_before > now]
            for e in ready:
                self._end_backoff(e)
            self._queue.extend(ready)

    def _end_backoff(self, entry: _Entry) -> None:
        """Backoff over, back in line: the flight-recorder transition
        every promotion path (timer pump OR forced) must take."""
        rid = entry.request.request_id
        self._flight.end(rid, SPAN_BACKOFF)
        self._flight.begin(rid, SPAN_QUEUE, attempt=entry.attempts + 1)

    # -- batching ------------------------------------------------------

    def _precond(self, request: SolveRequest) -> str:
        """The request's effective preconditioner: its own knob, else
        the service default."""
        return request.preconditioner or self.policy.preconditioner

    def _krylov(self, request: SolveRequest):
        """The request's effective Krylov-memory policy
        (:mod:`poisson_tpu.krylov`): its own knob, else the service
        default."""
        return request.krylov or self.policy.krylov

    def _krylov_marker(self, request: SolveRequest) -> str:
        """The cohort suffix the Krylov policy contributes: ``:blk``
        (block bucket executables) / ``:defl`` (deflated solo
        dispatch) split executables, breakers, and — downstream —
        sentinel baselines, exactly like the ``:mg`` marker: a block
        or deflated rollout never indicts the independent fleet, and
        vice versa. The default policy contributes nothing — historical
        cohort strings byte-for-byte."""
        kp = self._krylov(request)
        if kp.mode == "block":
            return ":blk"
        if kp.deflation:
            return ":defl"
        return ""

    def _backend_token(self, request: SolveRequest) -> str:
        """The backend segment of the cohort string. Router off (the
        default) this is the literal ``"xla"`` every prior release
        wrote — cohorts stay byte-identical. Router on, it is the arm
        the router would pick for this request (the pure ``peek``:
        cohort labeling must not tick decision counters or consume
        half-open probes), so auto-routed traffic forms per-backend
        cohorts — its breakers, sentinel baselines, and regression
        records never blend with hand-picked ones."""
        if self._router is None:
            return "xla"
        return self._router.peek(**self._roofline_args(request),
                                 device_id=self._hw_cohort()[2])

    def _cohort(self, request: SolveRequest) -> str:
        p = request.problem
        base = (f"{p.M}x{p.N}:{request.dtype or 'auto'}:"
                f"{self._backend_token(request)}")
        # MG requests are their own cohort family: different
        # executables (V-cycle traced into the body), different cost
        # profile, so their own breaker state and — downstream — their
        # own sentinel baselines (benchmarks/regress.py): an MG rollout
        # never indicts the Jacobi fleet, and vice versa.
        if self._precond(request) == "mg":
            base += ":mg"
        base += self._krylov_marker(request)
        # Geometry requests form their own cohorts — the executable
        # family differs (stacked canvases) — but the FINGERPRINT stays
        # out of the key: different geometries on the same grid share
        # the cohort, the bucket executable, and the breaker, which is
        # the mixed-geometry co-batching seam. (Block cohorts are the
        # one exception to fingerprint-blind batch FORMATION — the
        # block recurrence needs one shared operator, so _form_batch
        # additionally requires fingerprint uniformity there — but the
        # cohort string still never carries the fingerprint.)
        return base + (":geo" if request.geometry is not None else "")

    # -- convergence forecasting (obs.forecast) ------------------------

    def _forecast_args(self, request: SolveRequest) -> dict:
        """The cohort-model keyword set for this request — the cold
        analytic seed needs the grid, precision, and device kind the
        dispatch would actually run with."""
        from poisson_tpu.solvers.pcg import resolve_dtype

        dtype = resolve_dtype(request.dtype)
        p = request.problem
        return {
            "M": p.M, "N": p.N,
            "dtype_bytes": 8 if dtype == "float64" else 4,
            "scaled": dtype != "float64",
            "device_kind": self._hw_cohort()[1],
        }

    def _forecast_predict(self, request: SolveRequest):
        return self._forecast.predict(self._cohort(request),
                                      **self._forecast_args(request))

    def _forecast_observe(self, entry: _Entry, iterations: int,
                          compute_s: float) -> None:
        """Feed one completed solve back into the cohort model and
        persist the snapshot beside the journal (best-effort, atomic) so
        a recovered service warm-starts its calibration."""
        self._forecast.observe(self._cohort(entry.request),
                               iterations, compute_s,
                               **self._forecast_args(entry.request))
        if self._journal is not None:
            from poisson_tpu.obs.forecast import snapshot_path

            self._forecast.save(snapshot_path(self._journal.path))

    def _forecast_backlog(self) -> float:
        """Predicted seconds of queued work — the sum of every waiting
        entry's admission-time p50 ETA. The degradation ladder's
        backlog-seconds rung keys on this, and it is published as
        ``serve.forecast.backlog_seconds`` either way."""
        backlog = sum(e.eta or 0.0 for e in self._queue)
        backlog += sum(e.eta or 0.0 for e in self._delayed)
        obs.gauge("serve.forecast.backlog_seconds", round(backlog, 6))
        return backlog

    # -- roofline observatory + backend router (obs.roofline) ----------

    def _roofline_args(self, request: SolveRequest, batch: int = 1,
                       verify_every: Optional[int] = None) -> dict:
        """The roofline-cohort keyword set for one request — the full
        dispatch identity the measured fraction is attributed to."""
        from poisson_tpu.solvers.pcg import resolve_dtype

        p = request.problem
        if verify_every is None:
            verify_every = self._verify_params()[0]
        return {
            "M": p.M, "N": p.N, "batch": max(1, int(batch)),
            "dtype_bytes": (8 if resolve_dtype(request.dtype)
                            == "float64" else 4),
            "preconditioner": self._precond(request) or "jacobi",
            "verify_every": int(verify_every),
            "device_kind": self._hw_cohort()[1],
        }

    def _observe_roofline(self, request: SolveRequest, *,
                          iterations: int, seconds: float,
                          batch: int = 1, verify_every: int = 0,
                          backend: Optional[str] = None) -> None:
        """Feed one measured dispatch into the roofline observatory,
        grade it through the router's misprediction sentinel (when the
        router made the call — lane chunk-steps always run the xla
        engine and are never graded against a routed arm), and persist
        the profile snapshot beside the journal. Unmeasurable
        dispatches (zero wall — VirtualClock) produce no sample, no
        grade, no write."""
        decision = self._active_decision
        if backend is None:
            backend = (decision.backend if decision is not None
                       else "xla")
        sample = self._roofline.observe(
            backend=backend, iterations=int(iterations),
            seconds=float(seconds),
            **self._roofline_args(request, batch=batch,
                                  verify_every=verify_every))
        if (self._router is not None and decision is not None
                and decision.backend == backend):
            self._router.grade(decision, sample)
        if sample is not None and self._journal is not None:
            from poisson_tpu.obs.roofline import snapshot_path

            self._roofline.save(snapshot_path(self._journal.path))

    def _reforecast_doomed(self, entry: _Entry, view, table) -> bool:
        """Mid-flight ETA check for a lane occupant: fit the convergence
        rate to the entry's lane-boundary residual history, extrapolate
        iterations-to-δ, and price them with the entry's own measured
        seconds/iteration (cohort/analytic model when unmeasured — the
        VirtualClock case). Unknown rate never preempts: blind eviction
        of converging work would be worse than a deadline partial."""
        from poisson_tpu.obs import forecast as fcast

        slope = fcast.log_residual_slope(entry.history)
        rem = fcast.remaining_iterations(float(view["diff"]),
                                         float(table.problem.delta),
                                         slope)
        if rem is None:
            return False
        spi = entry.spi
        if spi <= 0.0:
            spi = self._forecast_predict(
                entry.request).seconds_per_iteration
        eta = rem * spi
        remaining = entry.deadline.remaining()
        if remaining is None:
            return False
        rid = entry.request.request_id
        self._flight.annotate(
            rid, SPAN_RESIDENT, eta=round(eta, 6),
            progress=round(fcast.progress_fraction(
                int(view["k"]), int(view["k"]) + rem), 3))
        doomed = eta * self.policy.forecast.margin > max(0.0, remaining)
        if doomed:
            self._flight.point(rid, POINT_REFORECAST, k=int(view["k"]),
                               eta=round(eta, 6),
                               remaining=round(max(0.0, remaining), 6))
        return doomed

    def _hw_cohort(self) -> tuple:
        """The (backend, device_kind, device_id) triple integrity
        suspicion taints — hardware identity at placement granularity:
        a bit flip indicts the PART it ran on (Hochschild 2021), so the
        suspicion keys on the dispatching worker's bound fault domain,
        and only the request cohorts sharing that device inherit it —
        a flip on device 3 never arms defensive verification on device
        5's dispatches. Outside a dispatch (no active worker) the
        process default device stands in."""
        worker = self._active_worker
        if worker is not None and worker.placement is not None:
            p = worker.placement
            return ("xla", p.device_kind, p.device_id)
        if not hasattr(self, "_hw_cohort_cache"):
            import jax

            dev = jax.devices()[0]
            self._hw_cohort_cache = (
                "xla", str(getattr(dev, "device_kind", dev.platform)), 0)
        return self._hw_cohort_cache

    def _verify_params(self, entries=()) -> tuple:
        """The (verify_every, verify_tol) the next dispatch touching
        ``entries`` should run with: the policy's always-on stride when
        set; else — with ``verify_on_suspect`` — the defensive
        ``suspect_verify_every`` when this process's hardware cohort is
        already SDC-suspect or any entry is an integrity-class retry
        (its redo must be able to defend itself). (0, None) means no
        probe is traced: the flag-off executables are the exact
        historical programs."""
        pol = self.policy.integrity
        if pol.verify_every > 0:
            return int(pol.verify_every), pol.verify_tol
        suspect_retry = any(e.last_failure == ERROR_INTEGRITY
                            for e in entries)
        if pol.verify_on_suspect and (
                suspect_retry or self._hw_cohort() in self._suspect_hw):
            return int(pol.suspect_verify_every), pol.verify_tol
        return 0, None

    def _count_defensive_verify(self, verify_every: int) -> None:
        """A dispatch armed the probe only because of suspicion (the
        policy default is off) — the audible record of paying the
        defense after the first strike."""
        if verify_every and not self.policy.integrity.verify_every:
            obs.inc("serve.integrity.suspect_dispatches")

    def _taint_suspect_hw(self) -> None:
        """First integrity detection on this hardware cohort: taint it.
        Idempotent — the counter counts cohorts, not detections."""
        cohort = self._hw_cohort()
        if cohort not in self._suspect_hw:
            self._suspect_hw.add(cohort)
            obs.inc("serve.integrity.suspect_cohorts")
            obs.event("serve.integrity.suspect_cohort",
                      backend=cohort[0], device_kind=cohort[1],
                      device=cohort[2])
            # A deflation basis harvested on a flip-suspect part is not
            # evidence: drop it so warm solves rebuild on trusted
            # silicon (krylov.cache.invalidations, audible).
            from poisson_tpu.krylov.recycle import invalidate

            invalidate(hw=cohort, reason="sdc-suspect-cohort")

    def _breaker(self, worker: Worker, cohort: str) -> CircuitBreaker:
        """The ``worker``'s breaker for ``cohort``: breaker state is
        keyed per worker cohort (a wedged worker trips its own breakers,
        not the fleet's — ROADMAP item 3)."""
        if cohort not in worker.breakers:
            worker.breakers[cohort] = CircuitBreaker(
                self.policy.breaker, clock=self._clock, cohort=cohort)
        return worker.breakers[cohort]

    def _solo(self, entry: _Entry) -> bool:
        """Chunked single-request dispatch classes: deadline-carrying
        (expiry needs chunk boundaries), explicitly chunked, escalated
        divergence retries (the resilient driver is single-request),
        deflation-enabled requests (the fingerprint-keyed solver
        memory is a single-request program — ``krylov.recycle``),
        MG+geometry requests (per-member hierarchies do not co-batch —
        ``solvers.batched`` rejects the combination loudly, so the
        service routes it through the chunked solo path instead), or
        placement-pinned requests (the pin binds the dispatch to one
        worker's device; co-batched members would inherit it
        silently)."""
        return (entry.deadline is not None
                or entry.request.chunk is not None
                or entry.escalate
                or entry.request.device_id is not None
                or entry.request.session_id is not None
                or self._krylov(entry.request).deflation
                or (entry.request.geometry is not None
                    and self._precond(entry.request) == "mg"))

    def _form_batch(self, head: _Entry) -> List[_Entry]:
        if self._solo(head):
            return [head]
        cohort = self._cohort(head.request)
        # Block cohorts batch one OPERATOR: the block recurrence is
        # only defined for a shared A, so candidates must match the
        # head's geometry fingerprint exactly (the one deliberate
        # exception to fingerprint-blind batch formation).
        block = self._krylov(head.request).mode == "block"
        head_fp = fingerprint_of(head.request.geometry)
        batch = [head]
        ids = {head.request.request_id}
        taints = set(head.taint)
        # Fingerprint-keyed exclusion, both directions: the batch's
        # accumulated geometry fingerprints vs the candidate's taint
        # list, and the candidate's fingerprint vs the batch's.
        fps = {head_fp}
        taint_fps = set(head.taint_fp)
        kept = deque()
        while self._queue and len(batch) < self.policy.max_batch:
            e = self._queue.popleft()
            e_fp = fingerprint_of(e.request.geometry)
            compatible = (
                not self._solo(e)
                and self._cohort(e.request) == cohort
                and (not block or e_fp == head_fp)
                and e.request.request_id not in taints
                and not (ids & e.taint)
                and e_fp not in taint_fps
                and not (fps & e.taint_fp)
            )
            if compatible:
                batch.append(e)
                ids.add(e.request.request_id)
                taints |= e.taint
                fps.add(e_fp)
                taint_fps |= e.taint_fp
            else:
                kept.append(e)
        kept.extend(self._queue)
        self._queue = kept
        return batch

    def _load_level(self, depth: int) -> int:
        frac = depth / self.policy.capacity
        d = self.policy.degradation
        level = 0
        if frac >= d.shrink_padding_at:
            level = 1
        if frac >= d.cap_iterations_at:
            level = 2
        if frac >= d.downshift_precision_at:
            level = 3
        # SLO-driven rung (opt-in, SLOPolicy.degrade_on_burn): when the
        # multi-window burn rate asks for a deeper downshift than queue
        # depth does, the burn wins — the ladder responds to the
        # objective being missed, not only to backlog. Audible as its
        # own counter so an SLO-triggered downshift is attributable.
        slo_level = self._slo.degrade_level()
        if slo_level > level:
            obs.inc("serve.degraded.slo_driven")
            level = slo_level
        # Predicted-backlog rung (opt-in, ForecastPolicy
        # .backlog_degradation): the ladder can respond to SECONDS of
        # queued work, not only request count — ten 4096² solves are a
        # deeper backlog than a hundred 64² ones. The backlog objective
        # normalizes ETA-seconds onto the same fractional thresholds the
        # depth rungs use; audible as its own counter.
        fp = self.policy.forecast
        if self._forecast is not None and fp.backlog_degradation:
            bfrac = (self._forecast_backlog()
                     / max(1e-9, fp.backlog_objective_seconds))
            blevel = 0
            if bfrac >= d.shrink_padding_at:
                blevel = 1
            if bfrac >= d.cap_iterations_at:
                blevel = 2
            if bfrac >= d.downshift_precision_at:
                blevel = 3
            if blevel > level:
                obs.inc("serve.degraded.backlog_driven")
                level = blevel
        if self._tenancy is not None:
            # Recompute the degradation offender once per level read —
            # _tenant_level then consults the cached verdict at every
            # application site without rescanning the queue.
            self._offender = self._tenant_offender()
        return level

    # -- continuous batching (lane table + refill state machine) -------

    def _lane_eligible(self, entry: _Entry) -> bool:
        """Continuous mode: deadline-carrying requests ride lanes (the
        engine's chunk boundary IS the deadline check), so only
        explicitly-chunked requests, escalated divergence retries (the
        resilient driver is single-request), Krylov-memory requests
        (the block recurrence couples members — it cannot step
        per-lane; deflation is a single-request program), and
        MG+geometry requests (per-lane hierarchies do not exist yet)
        still dispatch through the drain-mode machinery."""
        kp = self._krylov(entry.request)
        return (entry.request.chunk is None and not entry.escalate
                and entry.request.device_id is None
                and entry.request.session_id is None
                and kp.mode == "independent" and not kp.deflation
                and not (entry.request.geometry is not None
                         and self._precond(entry.request) == "mg"))

    def _effective_dtype(self, entry: _Entry, level: int) -> str:
        """The dtype a lane splice would run this entry at — the
        degradation ladder's precision downshift applied at the refill
        decision, re-checked every time rather than once per batch."""
        dtype = entry.request.dtype or "auto"
        if level >= 3 and dtype == "float64":
            return "float32"
        return dtype

    def _lane_cohort(self, entry: _Entry, level: int) -> str:
        # Tenant-scoped rung first (no-op with tenancy off): a spared
        # tenant's float64 must not downshift — and must not be spliced
        # into a downshifted table — just because the offender's rung
        # says 3.
        level = self._tenant_level(entry, level)
        p = entry.request.problem
        base = f"{p.M}x{p.N}:{self._effective_dtype(entry, level)}:xla"
        if self._precond(entry.request) == "mg":
            base += ":mg"
        base += self._krylov_marker(entry.request)
        # Same rule as _cohort: the :geo marker splits executables, the
        # fingerprint never does — mixed geometries share the lane table.
        return base + (":geo" if entry.request.geometry is not None
                       else "")

    def _step_continuous(self, worker: Worker) -> bool:
        """One cycle of the refill engine: promote backed-off work,
        dispatch a solo-class head, refill EMPTY lanes from the queue
        (policy re-checked per splice), then advance every ACTIVE lane
        one chunk and retire what the boundary shows as finished."""
        self._pump_delayed()
        busy = worker.table is not None and worker.table.occupied()
        if not self._queue and not busy:
            # Another worker's lanes may still be live: this worker has
            # nothing, but the service does.
            if self._busy_elsewhere(worker):
                return True
            if not self._advance_past_backoff():
                worker.table = None
                return False
        # A solo-class head (escalated retry, explicit chunk) dispatches
        # between chunk steps through the drain-mode machinery — the
        # lane program pauses in wall time but burns no iterations.
        if self._queue and not self._lane_eligible(self._queue[0]):
            return self._dispatch_head_solo(worker)
        self._refill(worker)
        if worker.table is not None and worker.table.occupied():
            self._step_lane_table(worker)
            return True
        return bool(self._queue or self._delayed
                    or self._busy_elsewhere(worker))

    def _busy_elsewhere(self, worker: Worker) -> bool:
        return any(w.table is not None and w.table.occupied()
                   for w in self._pool.workers if w is not worker)

    def _dispatch_head_solo(self, worker: Worker) -> bool:
        head = self._pop_live_head()
        if head is None:
            return True
        level = self._load_level(len(self._queue) + len(self._delayed)
                                 + 1)
        breaker = self._breaker(worker, self._cohort(head.request))
        if not breaker.allow():
            self._shed(head, SHED_BREAKER_OPEN,
                       f"circuit breaker open for cohort "
                       f"{self._cohort(head.request)}")
            return True
        # A block-mode head is lane-ineligible (the recurrence couples
        # members) but NOT solo: it still wants its cohort co-batched,
        # so the continuous engine borrows drain-mode batch formation
        # for it between chunk steps.
        if (self._krylov(head.request).mode == "block"
                and not self._solo(head)):
            self._dispatch(worker, self._form_batch(head), breaker,
                           level)
            return True
        self._dispatch(worker, [head], breaker, level)
        return True

    def _refill(self, worker: Worker) -> None:
        """The refill decision: splice queued, lane-eligible requests
        into the live table's EMPTY lanes. Every policy is re-checked
        per splice — deadline liveness, taint-pair exclusion against the
        current occupants, the circuit breaker (denials counted as
        ``serve.refill.refill_denied_by_breaker``), and the degradation
        ladder (padding shrink at table creation, iteration cap and
        precision downshift per spliced member). With no program in
        flight, the table is (re)built for the queue head's cohort —
        the same bucket executable is reused for every later splice."""
        from poisson_tpu.serve.refill import LaneTable
        from poisson_tpu.solvers.batched import bucket_size

        if not self._queue:
            return
        level = self._load_level(len(self._queue) + len(self._delayed))
        obs.gauge("serve.load_level", level)
        head = self._queue[0]
        head_cohort = self._lane_cohort(head, level)
        from poisson_tpu.serve.breaker import OPEN

        if self._breaker(worker, head_cohort).state == OPEN:
            # An OPEN breaker (cooldown still running) can admit nothing
            # for this cohort: shed the head without paying lane-table
            # construction for a program no splice could ever enter.
            # (HALF_OPEN falls through — a probe splice is allowed.)
            obs.inc("serve.refill.refill_denied_by_breaker")
            entry = self._queue.popleft()
            self._shed(entry, SHED_BREAKER_OPEN,
                       f"circuit breaker open for cohort {head_cohort} "
                       f"at refill")
            return
        ready = sum(
            1 for e in self._queue
            if self._lane_eligible(e)
            and self._lane_cohort(e, level) == head_cohort
            and e.request.problem == head.request.problem
        )
        head_level = self._tenant_level(head, level)
        if head_level >= 1:
            # Padding shrink: size the table to the work actually
            # waiting — no speculative lanes when every real member
            # counts.
            bucket = min(max(1, ready), self.policy.max_batch)
        else:
            # Size to the backlog, plus one speculative EMPTY lane
            # (bucket ladder rounding) so an arrival can always join
            # the running program mid-flight — that in-flight join is
            # the continuous-batching win, and the idle width it costs
            # is audible as serve.refill.idle_lane_steps.
            bucket = bucket_size(
                min(max(ready + 1, 2), self.policy.max_batch))
        verify_every, verify_tol = self._verify_params([head])
        table = worker.table
        # An in-flight program is immutable (fixed executable width); an
        # EMPTY one is replaceable — on cohort change, to re-size the
        # bucket to the backlog the load has grown (or shrunk) into, or
        # when the integrity-probe stride changed (suspicion arrived:
        # the NEXT program runs defended; a live one is never
        # retrofitted).
        if table is not None and not table.occupied() and (
                table.cohort != head_cohort
                or table.problem != head.request.problem
                or table.bucket != bucket
                or table.verify_every != verify_every):
            table = worker.table = None
        if table is None:
            if head_level >= 1:
                obs.inc("serve.degraded.padding")
            self._count_defensive_verify(verify_every)
            eff_dtype = self._effective_dtype(head, head_level)
            table = worker.table = LaneTable(
                head_cohort, head.request.problem,
                None if eff_dtype == "auto" else eff_dtype,
                bucket, self.policy.refill_chunk,
                worker_id=worker.id,
                multi_geometry=head.request.geometry is not None,
                verify_every=verify_every, verify_tol=verify_tol,
                preconditioner=self._precond(head.request),
                device=(worker.placement.device
                        if worker.placement else None),
            )
            self._note_sticky(worker, head_cohort, head.request.problem,
                              None if eff_dtype == "auto" else eff_dtype,
                              bucket,
                              preconditioner=self._precond(head.request))
            obs.event("serve.refill.table", cohort=head_cohort,
                      bucket=bucket, level=level, worker=worker.id)
        if not table.free_lane_count():
            return
        lane_cap = None
        if self._tenancy is not None:
            # Per-bucket lane fair share: when more than one tenant has
            # lane-eligible work for THIS table's cohort, each tenant's
            # resident-lane count is capped at its share of the bucket
            # (ceil, min 1) — one tenant cannot monopolize a bucket
            # executable's lanes while a competitor waits. With a
            # single tenant present the cap is void (work-conserving:
            # fairness must never idle lanes nobody else wants).
            present = {self._tenant(e.request) for e in self._queue
                       if self._lane_eligible(e)
                       and self._lane_cohort(e, level) == table.cohort
                       and e.request.problem == table.problem}
            present |= {self._tenant(e.request)
                        for e in table.occupants()}
            if len(present) > 1:
                total_share = sum(self._tenancy.share_of(t)
                                  for t in present)
                lane_cap = {
                    t: max(1, int(np.ceil(
                        table.bucket * self._tenancy.share_of(t)
                        / total_share)))
                    for t in present}
        kept: deque = deque()
        while self._queue and table.free_lane_count():
            entry = self._queue.popleft()
            if (not self._lane_eligible(entry)
                    or self._lane_cohort(entry, level) != table.cohort
                    or entry.request.problem != table.problem):
                kept.append(entry)
                continue
            if entry.deadline is not None and entry.deadline.expired():
                obs.inc("serve.deadline.expired_in_queue")
                self._flight.point(entry.request.request_id,
                                   POINT_DEADLINE, where="refill_queue",
                                   elapsed=round(
                                       entry.deadline.elapsed(), 4))
                self._shed(entry, SHED_DEADLINE_EXPIRED,
                           "deadline expired while queued")
                continue
            if not table.taint_compatible(entry):
                kept.append(entry)     # waits for its taint partner
                continue
            tenant = self._tenant(entry.request)
            if lane_cap is not None:
                held = sum(1 for o in table.occupants()
                           if self._tenant(o.request) == tenant)
                if held >= lane_cap.get(tenant, table.bucket):
                    # Over fair share with a competitor waiting: defer
                    # (kept, re-offered next refill), never shed — the
                    # cap costs position, not the request.
                    obs.inc("serve.tenant.lane_deferred")
                    kept.append(entry)
                    continue
            breaker = self._breaker(worker, table.cohort)
            if not breaker.allow():
                obs.inc("serve.refill.refill_denied_by_breaker")
                self._shed(entry, SHED_BREAKER_OPEN,
                           f"circuit breaker open for cohort "
                           f"{table.cohort} at refill")
                continue
            eff_level = self._tenant_level(entry, level, count=True)
            if eff_level >= 2:
                entry.iter_cap = min(
                    entry.request.problem.iteration_cap,
                    self.policy.degradation.degraded_iteration_cap)
                obs.inc("serve.degraded.iteration_cap")
            else:
                # Re-checked at every refill decision: a cap set while
                # degraded must not stick to a retried entry splicing
                # into a now-healthy service.
                entry.iter_cap = None
            if (eff_level >= 3
                    and (entry.request.dtype or "auto") == "float64"):
                obs.inc("serve.degraded.precision")
            if tenant is not None:
                obs.inc(f"serve.tenant.dispatches.{tenant}")
            lane = table.splice(entry, entry.request.rhs_gate)
            rid = entry.request.request_id
            if self._journal is not None:
                self._journal.record(
                    "splice", request_id=str(rid), worker=worker.id,
                    lane=lane,
                    device=(worker.placement.device_id
                            if worker.placement else None),
                    epoch=self._registry.epoch)
            self._flight.end(rid, SPAN_QUEUE)
            attrs = dict(mode="lane", bucket=table.bucket, lane=lane,
                         level=level, worker=worker.id)
            if tenant is not None:
                attrs["tenant"] = tenant
            if entry.request.geometry is not None:
                attrs["geometry"] = fingerprint_of(entry.request.geometry)
            self._flight.begin(rid, SPAN_RESIDENT, **attrs)
        while kept:        # skipped entries return in arrival order
            self._queue.appendleft(kept.pop())

    def _step_lane_table(self, worker: Worker) -> None:
        """Advance the lane program one chunk through the dispatch-fault
        seam, then classify the boundary. A transient fault kills the
        device program: every occupant is evicted and retried with
        mutual taint (the batch-drain contract, applied to lanes); a
        worker fault quarantines the worker and recovers the occupants
        onto the survivors; an internal fault surfaces every occupant as
        a typed error."""
        table = worker.table
        breaker = self._breaker(worker, table.cohort)
        occupants = table.occupants()
        did = self._flight.next_dispatch_id()
        t_step = self._clock()
        try:
            with obs.span("serve.refill.step", fence=False,
                          cohort=table.cohort, active=len(occupants),
                          worker=worker.id):
                if self._worker_fault is not None:
                    self._worker_fault(worker.id,
                                       [e.request for e in occupants],
                                       {e.request.request_id: e.attempts
                                        for e in occupants})
                if self._dispatch_fault is not None:
                    self._dispatch_fault(
                        [e.request for e in occupants],
                        {e.request.request_id: e.attempts
                         for e in occupants})
                # No beat here: the pump-level beat marked the step's
                # START, and the post-step stall check must measure this
                # step's duration — a beat on completion would reset the
                # baseline and make a slow-but-returning step invisible.
                # (Placement targeting lives inside LaneBatch.step —
                # the table was built with the worker's bound device.)
                table.step()
        except (WorkerCrashError, WorkerHangError) as e:
            self._handle_worker_fault(worker, e, occupants, did, t_step)
            return
        except TransientDispatchError as e:
            breaker.record_failure()
            self._flight_dispatch_failed(occupants, did, t_step,
                                         type(e).__name__)
            evicted = table.evict_all()
            worker.table = None
            co_ids = {en.request.request_id for en in evicted}
            co_fps = _geo_fps(evicted)
            for en in evicted:
                self._retry_or_fail(en, ERROR_TRANSIENT, str(e),
                                    co_ids - {en.request.request_id},
                                    co_fps)
            return
        except Exception as e:  # internal: surfaced, never retried
            breaker.record_failure()
            self._flight_dispatch_failed(occupants, did, t_step,
                                         type(e).__name__)
            evicted = table.evict_all()
            worker.table = None
            for en in evicted:
                self._error(en, ERROR_INTERNAL,
                            f"{type(e).__name__}: {e}")
            return
        # Flight: one chunk step advanced every resident lane inside one
        # measured span; divide its wall by the iterations it bought
        # (apportion_compute) and stamp a chunk_step point per member.
        views = table.lane_view()
        secs = max(0.0, self._clock() - t_step)
        deltas = table.advance_marks(views)
        by_member = {table.entries[lane].request.request_id: dk
                     for lane, dk in deltas.items()}
        shares = apportion_compute(secs, by_member)
        # Roofline: one chunk step of the lane program, attributed to
        # the longest per-lane iteration delta. Lane tables always run
        # the xla engine (routed arms apply to drain/solo dispatches),
        # so the backend is pinned here and no sentinel grades it.
        if deltas and occupants:
            self._observe_roofline(
                occupants[0].request, backend="xla",
                iterations=max(deltas.values()), seconds=secs,
                batch=len(occupants),
                verify_every=self._verify_params(occupants)[0])
        for lane, dk in deltas.items():
            entry = table.entries[lane]
            rid = entry.request.request_id
            self._flight.add_step(rid, secs, dk, shares[rid], did,
                                  k=views[lane]["k"])
            # Per-member iteration delta on the resident span: timelines
            # render iterations/chunk without decoding the step points.
            self._flight.annotate(rid, SPAN_RESIDENT, dk=int(dk),
                                  k=int(views[lane]["k"]))
            if self._forecast is not None and dk > 0:
                # Lane-boundary residual history: each member reports
                # its own (k, ‖Δw‖) pair from the lane view — the
                # re-forecast slope rides chunk boundaries, LaneBatch
                # members individually.
                entry.history.append(
                    (int(views[lane]["k"]), float(views[lane]["diff"])))
                if len(entry.history) > 32:
                    del entry.history[0]
                if shares[rid] > 0.0:
                    entry.spi = shares[rid] / dk
        self._retire_boundary(table, breaker, views)

    def _retire_boundary(self, table, breaker, views) -> None:
        from poisson_tpu.solvers.pcg import FLAG_DEADLINE, FLAG_NONE

        co_ids = table.occupant_ids()
        co_fps = _geo_fps(table.occupants())
        any_failed = False
        any_clean = False
        for view in views:
            if view["member_id"] is None:
                continue
            entry = table.entries[view["lane"]]
            cap = (entry.iter_cap if entry.iter_cap is not None
                   else table.problem.iteration_cap)
            deadline_hit = (entry.deadline is not None
                            and entry.deadline.expired())
            if not (view["done"] or view["k"] >= cap or deadline_hit):
                # Lane-boundary re-forecast (ForecastPolicy.reforecast):
                # a converging-but-doomed occupant — remaining-iterations
                # ETA past its remaining budget — is preempted NOW,
                # freeing the lane for work that can still make its
                # deadline, instead of burning chunks to an inevitable
                # deadline-flagged partial.
                if (self._forecast is not None
                        and self.policy.forecast.reforecast
                        and entry.deadline is not None
                        and self._reforecast_doomed(entry, view, table)):
                    entry, result = table.retire(view["lane"])
                    if self._journal is not None:
                        self._journal.record(
                            "retire",
                            request_id=str(entry.request.request_id),
                            iterations=int(result.iterations),
                            flag=result.flag_name)
                    self._flight.end(entry.request.request_id,
                                     SPAN_RESIDENT,
                                     iterations=result.iterations,
                                     flag=result.flag_name)
                    obs.inc("serve.forecast.preempted")
                    # Preemption is a capacity decision, not a cohort
                    # fault: the breaker never hears about it.
                    self._shed(entry, SHED_PREDICTED_DEADLINE,
                               "re-forecast ETA exceeds remaining "
                               f"deadline budget at k={int(view['k'])}")
                continue               # still ACTIVE: rides the next chunk
            entry, result = table.retire(view["lane"])
            if self._journal is not None:
                self._journal.record(
                    "retire", request_id=str(entry.request.request_id),
                    iterations=int(result.iterations),
                    flag=result.flag_name)
            if deadline_hit:
                self._flight.point(entry.request.request_id,
                                   POINT_DEADLINE, where="lane",
                                   elapsed=round(
                                       entry.deadline.elapsed(), 4))
            self._flight.end(entry.request.request_id, SPAN_RESIDENT,
                             iterations=result.iterations,
                             flag=result.flag_name)
            flag = result.flag
            if deadline_hit and flag == FLAG_NONE:
                # A healthy lane overtaken by its budget: partial result,
                # deadline-flagged. Verdicts win over deadlines — the
                # same precedence as checkpoint._deadline_flag.
                flag = FLAG_DEADLINE
            failed = self._classify_member(
                entry, flag, result.iterations, result.diff,
                restarts=0, cap=cap,
                co_ids=co_ids - {entry.request.request_id},
                co_fps=co_fps,
            )
            any_failed = any_failed or failed
            any_clean = any_clean or not failed
        if any_failed:
            breaker.record_failure()
        elif any_clean:
            breaker.record_success()

    # -- dispatch ------------------------------------------------------

    def _dispatch(self, worker: Worker, batch: List[_Entry],
                  breaker: CircuitBreaker, level: int) -> None:
        from poisson_tpu.solvers.pcg import resolve_dtype

        policy = self.policy
        obs.gauge("serve.load_level", level)
        head = batch[0]
        # Tenant-scoped degradation: the batch is dispatched at the
        # head's effective rung (batches are cohort-homogeneous; a
        # spared tenant's head runs one rung gentler than the
        # offender's — serve.tenant.degraded_{offender,spared}).
        level = self._tenant_level(head, level, count=level > 0)
        problem = head.request.problem
        dtype = head.request.dtype
        exact_bucket = False
        if level >= 1:
            exact_bucket = True
            obs.inc("serve.degraded.padding")
        if level >= 2:
            cap = min(problem.iteration_cap,
                      policy.degradation.degraded_iteration_cap)
            problem = problem.with_(max_iter=cap)
            obs.inc("serve.degraded.iteration_cap")
        if level >= 3 and resolve_dtype(dtype) == "float64":
            dtype = "float32"
            obs.inc("serve.degraded.precision")
        if level > 0:
            obs.event("serve.degraded", level=level,
                      batch=len(batch), exact_bucket=exact_bucket,
                      iteration_cap=problem.iteration_cap, dtype=dtype)

        obs.inc("serve.dispatches")
        obs.inc("serve.batch_members", len(batch))
        cohort = self._cohort(head.request)
        if self._router is not None:
            # Route this dispatch cohort across the backend arms. The
            # backend-downshift rung rides the decision (queue pressure
            # forces the proven xla floor). Execution gate: every arm
            # still runs today's xla paths (router.executor_backend —
            # the Pallas kernels have no valid hardware measurement,
            # BENCH.md), so routing changes evidence and telemetry but
            # not compiled programs; a non-xla choice is counted as an
            # executor fallback to keep that gap audible.
            ve, _ = self._verify_params(batch)
            self._active_decision = self._router.route(
                **self._roofline_args(head.request, batch=len(batch),
                                      verify_every=ve),
                device_id=(worker.placement.device_id
                           if worker.placement else 0),
                queue_fraction=(len(self._queue)
                                / max(1, policy.capacity)))
            if self._active_decision.backend != "xla":
                obs.inc("serve.router.executor_fallbacks")
        # Sticky executables: this worker now holds the cohort's
        # compiled program at this bucket width — routing will prefer
        # it, and a restart warm-up recompiles exactly these widths.
        solo_head = len(batch) == 1 and self._solo(head)
        if solo_head:
            width = None          # chunked drivers, no bucket program
        elif exact_bucket:
            width = len(batch)
        else:
            from poisson_tpu.solvers.batched import bucket_size

            width = bucket_size(len(batch))
        self._note_sticky(worker, cohort, head.request.problem,
                          head.request.dtype, width,
                          preconditioner=self._precond(head.request))
        # Flight: members leave the queue and become resident in one
        # shared dispatch — the dispatch id is the causal parent linking
        # every member's residency span and chunk-step points.
        did = self._flight.next_dispatch_id()
        solo = solo_head
        mode = "solo" if solo else "drain"
        for entry in batch:
            rid = entry.request.request_id
            self._flight.end(rid, SPAN_QUEUE)
            attrs = dict(dispatch=did, mode=mode, batch=len(batch),
                         level=level, worker=worker.id)
            tenant = self._tenant(entry.request)
            if tenant is not None:
                obs.inc(f"serve.tenant.dispatches.{tenant}")
                attrs["tenant"] = tenant
            if entry.request.geometry is not None:
                # Fingerprint attribution: a mixed-geometry dispatch's
                # members are distinguishable in the causal trace.
                attrs["geometry"] = fingerprint_of(entry.request.geometry)
            self._flight.begin(rid, SPAN_RESIDENT, **attrs)
        if self._journal is not None:
            # The dispatch record carries the placement (device + epoch)
            # so a recovery on a different topology can see which
            # silicon the in-flight work was on and remap it audibly.
            self._journal.record(
                "dispatch", worker=worker.id, mode=mode,
                request_ids=[str(e.request.request_id) for e in batch],
                device=(worker.placement.device_id
                        if worker.placement else None),
                epoch=self._registry.epoch)
        t_disp = self._clock()
        try:
            with obs.span("serve.dispatch", fence=False, cohort=cohort,
                          batch=len(batch), level=level,
                          worker=worker.id):
                if self._worker_fault is not None:
                    self._worker_fault(worker.id,
                                       [e.request for e in batch],
                                       {e.request.request_id: e.attempts
                                        for e in batch})
                if self._dispatch_fault is not None:
                    self._dispatch_fault([e.request for e in batch],
                                         {e.request.request_id: e.attempts
                                          for e in batch})
                with self._on_device(worker):
                    if solo:
                        member_failed = self._dispatch_solo(
                            head, problem, dtype, did, t_disp)
                    else:
                        member_failed = self._dispatch_batched(
                            batch, problem, dtype, exact_bucket, did,
                            t_disp)
                # No completion beat — see _step_lane_table: the
                # post-step stall check measures from the pump-level
                # start-of-step beat.
        except (WorkerCrashError, WorkerHangError) as e:
            self._handle_worker_fault(worker, e, batch, did, t_disp)
            return
        except TransientDispatchError as e:
            breaker.record_failure()
            self._flight_dispatch_failed(batch, did, t_disp,
                                         type(e).__name__)
            co_ids = {entry.request.request_id for entry in batch}
            co_fps = _geo_fps(batch)
            for entry in batch:
                self._retry_or_fail(entry, ERROR_TRANSIENT, str(e),
                                    co_ids - {entry.request.request_id},
                                    co_fps)
            return
        except Exception as e:  # internal: surfaced, never retried
            breaker.record_failure()
            self._flight_dispatch_failed(batch, did, t_disp,
                                         type(e).__name__)
            for entry in batch:
                self._error(entry, ERROR_INTERNAL,
                            f"{type(e).__name__}: {e}")
            return
        finally:
            # The routing decision is scoped to this dispatch: a stale
            # one must never grade a later dispatch's measurement.
            self._active_decision = None
        if member_failed:
            breaker.record_failure()
        else:
            breaker.record_success()

    def _flight_dispatch_failed(self, batch: List[_Entry], did: str,
                                t_disp: float, error: str) -> None:
        """A whole dispatch died: the members' residency still happened
        (and is accounted), but no iterations can be attributed — the
        time they paid is lane-wait on a program that produced nothing."""
        secs = max(0.0, self._clock() - t_disp)
        for entry in batch:
            rid = entry.request.request_id
            self._flight.add_step(rid, secs, 0, 0.0, did)
            self._flight.end(rid, SPAN_RESIDENT, error=error)

    def _dispatch_batched(self, batch: List[_Entry], problem, dtype,
                          exact_bucket: bool, did: str,
                          t_disp: float) -> bool:
        from poisson_tpu.solvers.batched import solve_batched

        # Geometry cohorts dispatch with per-member canvases — mixed
        # fingerprints share the one stacked-canvas bucket executable.
        geoms = [e.request.geometry for e in batch]
        verify_every, verify_tol = self._verify_params(batch)
        # The batch is cohort-homogeneous (the :mg marker splits
        # cohorts), so the head's preconditioner is everyone's.
        # The batch is cohort-homogeneous in its Krylov mode too (the
        # :blk marker splits cohorts), so the head's mode is everyone's.
        kp = self._krylov(batch[0].request)
        if kp.mode == "block" and verify_every > 0:
            # The block recurrence has no per-member integrity probe
            # yet: when verification is demanded (always-on policy, or
            # a suspect cohort arming the defensive stride), the SDC
            # defense WINS — the batch dispatches through the VERIFIED
            # independent program instead (same members, same typed
            # outcomes, block acceleration suspended audibly). A
            # silent unverified block dispatch would bypass the PR 10
            # defense; passing the stride through would ValueError
            # into a non-retried internal error for every member.
            obs.inc("serve.krylov.verify_suspensions")
            obs.event("krylov.verify_suspended", mode="block",
                      batch=len(batch), verify_every=verify_every)
            kp = DEFAULT_KRYLOV_POLICY
        self._count_defensive_verify(verify_every)
        result = solve_batched(
            problem,
            rhs_gates=[e.request.rhs_gate for e in batch],
            member_ids=[e.request.request_id for e in batch],
            dtype=dtype,
            bucket=(len(batch) if exact_bucket and kp.mode != "block"
                    else None),
            geometries=(geoms if any(g is not None for g in geoms)
                        else None),
            verify_every=verify_every, verify_tol=verify_tol,
            preconditioner=self._precond(batch[0].request),
            mode=kp.mode,
        )
        if result.deficient is not None and bool(
                np.asarray(result.deficient)):
            # Graceful rank degradation inside the block recurrence —
            # audible, not a failure (near-parallel RHS columns).
            obs.inc("krylov.block.rank_deficient")
        co_ids = {e.request.request_id for e in batch}
        co_fps = _geo_fps(batch)
        iters = np.asarray(result.iterations)
        flags = np.asarray(result.flag)
        diffs = np.asarray(result.diff)
        # Flight: one fused dispatch advanced every member; its measured
        # wall divides among them by iteration count (the measured
        # per-iteration cost of the shared program — obs.costs).
        secs = max(0.0, self._clock() - t_disp)
        shares = apportion_compute(
            secs, {e.request.request_id: int(iters[i])
                   for i, e in enumerate(batch)})
        # Roofline: one fused program moved passes × grid × max(iters)
        # bytes (padding members ride the longest-running lane).
        self._observe_roofline(
            batch[0].request, iterations=int(iters.max()),
            seconds=secs, batch=len(batch), verify_every=verify_every)
        for i, entry in enumerate(batch):
            rid = entry.request.request_id
            self._flight.add_step(rid, secs, int(iters[i]),
                                  shares[rid], did, k=int(iters[i]))
            self._flight.end(rid, SPAN_RESIDENT,
                             iterations=int(iters[i]))
        any_failed = False
        for i, entry in enumerate(batch):
            assert result.origin[i] == entry.request.request_id
            failed = self._classify_member(
                entry, int(flags[i]), int(iters[i]), float(diffs[i]),
                restarts=0, cap=problem.iteration_cap,
                co_ids=co_ids - {entry.request.request_id},
                co_fps=co_fps,
            )
            any_failed = any_failed or failed
        return any_failed

    def _dispatch_solo(self, entry: _Entry, problem, dtype, did: str,
                       t_disp: float) -> bool:
        from poisson_tpu.solvers.checkpoint import pcg_solve_chunked
        from poisson_tpu.solvers.resilient import (
            DivergenceError,
            pcg_solve_resilient,
        )

        req = entry.request
        chunk = req.chunk or self.policy.default_chunk
        # The RHS gate rides rhs_gate (not f_val) when a geometry is
        # present — the canvas cache keys on f_val, and a gate folded
        # into it would fragment the cache per gate. Without geometry,
        # folding into f_val keeps the historical solo path unchanged.
        if req.geometry is not None:
            solo_problem = problem
        else:
            solo_problem = problem.with_(
                f_val=problem.f_val * req.rhs_gate)
        rid = req.request_id
        if req.session_id is not None:
            return self._dispatch_session(entry, problem, dtype, did,
                                          t_disp)
        verify_every, verify_tol = self._verify_params([entry])
        self._count_defensive_verify(verify_every)
        kp = self._krylov(req)
        if (kp.deflation and not entry.escalate
                and verify_every > 0):
            # The deflated program has no in-loop integrity probe yet:
            # when verification is demanded (always-on policy, or a
            # suspect hardware cohort arming the defensive stride),
            # the SDC defense WINS — the request falls through to the
            # verified chunked path below (cold, correct, defended)
            # and the suspension is audible. Silently running the
            # unverified warm program on flip-suspect silicon would
            # bypass the PR 10 defense for the whole :defl cohort.
            obs.inc("serve.krylov.verify_suspensions")
            obs.event("krylov.verify_suspended",
                      request_id=str(rid), mode="deflation",
                      verify_every=verify_every)
        elif kp.deflation and not entry.escalate:
            from poisson_tpu.geometry.dsl import fingerprint_of
            from poisson_tpu.krylov.recycle import solve_recycled

            # The fingerprint-keyed solver memory: warm solves deflate
            # against the cached basis, cold solves harvest one. The
            # dispatching worker becomes the family's basis holder —
            # the second stickiness axis routing prefers (see pump()).
            result = solve_recycled(
                problem, dtype=dtype, rhs_gate=req.rhs_gate,
                geometry=req.geometry, policy=kp,
                hw=self._hw_cohort(),
            )
            worker = self._active_worker
            if worker is not None:
                self._basis_holder[fingerprint_of(req.geometry)] = \
                    worker.id
            secs = max(0.0, self._clock() - t_disp)
            iters = int(result.iterations)
            self._flight.add_step(rid, secs, iters,
                                  secs if iters else 0.0, did, k=iters)
            self._flight.end(rid, SPAN_RESIDENT, iterations=iters)
            self._observe_roofline(req, iterations=iters, seconds=secs,
                                   verify_every=verify_every)
            return self._classify_member(
                entry, int(result.flag), iters,
                float(np.max(np.asarray(result.diff))),
                restarts=0, cap=problem.iteration_cap, co_ids=set(),
            )
        if entry.escalate and self.policy.retry.escalate_divergence:
            obs.inc("serve.escalations")
            try:
                # An integrity-class escalation rides the SAME resilient
                # driver as divergence — with the probe armed it IS the
                # verified-restart driver (restart from the last
                # verified-good iterate, no precision escalation); a
                # persistent detector exhausting the restart budget
                # surfaces as DivergenceError below, typed by the
                # entry's failure class.
                result = pcg_solve_resilient(
                    solo_problem, dtype=dtype, chunk=chunk,
                    deadline=entry.deadline, on_chunk=req.on_chunk,
                    verify_every=verify_every, verify_tol=verify_tol,
                    preconditioner=self._precond(req),
                )
            except DivergenceError as e:
                secs = max(0.0, self._clock() - t_disp)
                self._flight.add_step(rid, secs, 0, 0.0, did)
                self._flight.end(rid, SPAN_RESIDENT,
                                 error="DivergenceError")
                self._error(entry,
                            (ERROR_INTEGRITY
                             if entry.last_failure == ERROR_INTEGRITY
                             else ERROR_DIVERGENCE), str(e))
                return True
        else:
            result = pcg_solve_chunked(
                solo_problem, chunk=chunk, dtype=dtype,
                deadline=entry.deadline, on_chunk=req.on_chunk,
                geometry=req.geometry,
                rhs_gate=(req.rhs_gate if req.geometry is not None
                          else None),
                verify_every=verify_every, verify_tol=verify_tol,
                preconditioner=self._precond(req),
                history=(self._forecast is not None
                         and self.policy.forecast.history_every > 0),
            )
        # Flight: a solo dispatch's whole wall is this member's compute
        # (it shares the program with nobody).
        secs = max(0.0, self._clock() - t_disp)
        iters = int(result.iterations)
        self._flight.add_step(rid, secs, iters, secs if iters else 0.0,
                              did, k=iters)
        self._flight.end(rid, SPAN_RESIDENT, iterations=iters)
        self._observe_roofline(req, iterations=iters, seconds=secs,
                               verify_every=verify_every)
        return self._classify_member(
            entry, int(result.flag), int(result.iterations),
            float(np.max(np.asarray(result.diff))),
            restarts=int(getattr(result, "restarts", 0) or 0),
            cap=problem.iteration_cap, co_ids=set(),
        )

    def _dispatch_session(self, entry: _Entry, problem, dtype, did: str,
                          t_disp: float) -> bool:
        """One session step (``serve.session``): a fused solve through
        the warm-start seam. The warm iterate rides the request
        (``warm_start`` — process memory, never the journal: a replayed
        step arrives with the field at its default and runs COLD), the
        validity gate lives in the solver layer
        (:func:`solvers.session.session_step_solve`), and a gate
        fallback is audible here too (``warm_fallback`` flight point on
        the step's own trace). Per-step deadlines are enforced at step
        boundaries — an expired deadline sheds the step in the queue
        like any request; a step that finishes past its deadline still
        returns its (correct) result, with the miss counted
        (``session.step.deadline_misses``) and pointed on the trace."""
        from poisson_tpu.solvers.pcg import FLAG_CONVERGED
        from poisson_tpu.solvers.session import session_step_solve

        req = entry.request
        rid = req.request_id
        sp = self.policy.session
        result, info = session_step_solve(
            problem, dtype=dtype, geometry=req.geometry,
            warm=req.warm_start, warm_geometry=req.warm_geometry,
            mass_shift=req.mass_shift,
            # The previous iterate is the implicit-Euler step's uⁿ —
            # transient DATA, not just a guess (the gate only decides
            # whether it also seeds the restart).
            u_prev=(req.warm_start if req.mass_shift else None),
            rhs_gate=req.rhs_gate,
            drift_bound=sp.warm_drift_bound,
            residual_factor=sp.warm_residual_factor,
        )
        if not info["warm_used"] and req.warm_start is not None:
            self._flight.point(rid, POINT_WARM_FALLBACK,
                               reason=info["fallback"],
                               step=req.session_step,
                               session=str(req.session_id))
        secs = max(0.0, self._clock() - t_disp)
        iters = int(result.iterations)
        flag = int(result.flag)
        if flag == FLAG_CONVERGED and req.on_solution is not None:
            # Hand the converged iterate back to the session host (the
            # next step's warm-start source). A throwing hook must not
            # void the outcome — the step solved; the hook is the
            # caller's code.
            try:
                req.on_solution(np.asarray(result.w))
            except Exception:
                obs.inc("session.callback_errors")
        if entry.deadline is not None and entry.deadline.expired():
            obs.inc("session.step.deadline_misses")
            self._flight.point(rid, POINT_DEADLINE,
                               where="session_step",
                               elapsed=round(entry.deadline.elapsed(), 4))
        self._flight.add_step(rid, secs, iters, secs if iters else 0.0,
                              did, k=iters)
        self._flight.end(rid, SPAN_RESIDENT, iterations=iters,
                         warm=info["warm_used"])
        self._observe_roofline(req, iterations=iters, seconds=secs)
        return self._classify_member(
            entry, flag, iters, float(np.max(np.asarray(result.diff))),
            restarts=0, cap=problem.iteration_cap, co_ids=set(),
        )

    # -- outcome classification ----------------------------------------

    def _classify_member(self, entry: _Entry, flag: int, iterations: int,
                         diff: float, restarts: int, cap: int,
                         co_ids: set, co_fps: set = frozenset()) -> bool:
        """Turn one member's stop verdict into an outcome or a retry.
        Returns True iff this member counts as a dispatch failure for the
        breaker."""
        from poisson_tpu.solvers.pcg import (
            FLAG_CONVERGED,
            FLAG_DEADLINE,
            FLAG_INTEGRITY,
            FLAG_NAMES,
            FLAG_NONE,
        )

        name = FLAG_NAMES.get(flag, str(flag))
        if flag == FLAG_CONVERGED:
            self._complete(entry, name, True, False, iterations, restarts,
                           diff)
            return False
        if flag == FLAG_DEADLINE:
            obs.inc("serve.deadline.expired_mid_solve")
            self._complete(entry, name, False, True, iterations, restarts,
                           diff)
            return False
        if flag == FLAG_NONE:
            # Budget exhausted without a failure verdict (incl. the
            # degraded iteration cap): the partial iterate is the answer
            # the policy bought.
            self._complete(entry, "cap_hit", False, True, iterations,
                           restarts, diff)
            return False
        if flag == FLAG_INTEGRITY:
            # Silent-data-corruption verdict (poisson_tpu.integrity):
            # its own outcome class — the iterate is suspect, not
            # divergent, and the suspicion attaches to the HARDWARE
            # cohort (Hochschild 2021), so later dispatches on this
            # (backend, device_kind) run defensively verified even when
            # the policy default is off. The member itself is retried
            # (through the verified-restart resilient driver when it
            # can escalate), typed ``integrity`` once the budget runs
            # out.
            obs.inc("serve.integrity.detections")
            obs.event("serve.integrity.detection",
                      request_id=str(entry.request.request_id),
                      iteration=iterations)
            self._taint_suspect_hw()
            self._retry_or_fail(entry, ERROR_INTEGRITY,
                                f"integrity verification failed at "
                                f"iteration {iterations}", co_ids, co_fps)
            return True
        # breakdown / nonfinite / stagnated: divergence-class failure.
        self._retry_or_fail(entry, ERROR_DIVERGENCE,
                            f"solver stopped: {name} at iteration "
                            f"{iterations}", co_ids, co_fps)
        return True

    def _retry_or_fail(self, entry: _Entry, error_type: str, message: str,
                       co_ids: set, co_fps: set = frozenset()) -> None:
        entry.attempts += 1
        entry.last_failure = error_type
        max_attempts = (entry.request.max_attempts
                        or self.policy.retry.max_attempts)
        if entry.attempts >= max_attempts:
            self._error(entry, error_type,
                        f"{message} (attempt {entry.attempts}/"
                        f"{max_attempts})")
            return
        if self._tenancy is not None:
            # Per-tenant retry budget (Dean & Barroso 2013): every
            # requeue spends a token only successes refund. A poisoned
            # tenant exhausts it after retry_budget requeues and each
            # later retry converts into this typed error — its total
            # dispatch count is bounded by admitted + retry_budget, so
            # a retry storm cannot multiply load on a degraded fleet.
            tenant = self._tenant(entry.request)
            if not self._tenancy.spend_retry(tenant):
                obs.inc("serve.tenant.retry_exhausted")
                obs.event("serve.tenant.retry_exhausted",
                          request_id=str(entry.request.request_id),
                          tenant=tenant, error=error_type)
                self._error(entry, error_type,
                            f"{message} (tenant {tenant!r} retry budget "
                            "exhausted)")
                return
            obs.inc(f"serve.tenant.retries.{tenant}")
        delay = self._backoff_delay(entry.attempts)
        if entry.deadline is not None:
            remaining = entry.deadline.remaining()
            if remaining is not None and remaining <= delay:
                obs.inc("serve.deadline.expired_in_queue")
                self._shed(entry, SHED_DEADLINE_EXPIRED,
                           f"deadline cannot survive the {delay:.3f}s "
                           f"retry backoff after: {message}")
                return
        # Mutual taint: this member never shares a bucket with its failed
        # batchmates again (and vice versa, applied on their entries) —
        # a poisoned member cannot re-kill the same cohort twice. The
        # fingerprint half keys on the GEOMETRY: any request carrying a
        # co-failed member's geometry family is excluded too, so a bad
        # geometry never re-co-batches with its batchmates under a fresh
        # request id.
        entry.taint |= co_ids
        if co_fps:
            new_fps = (set(co_fps)
                       - {fingerprint_of(entry.request.geometry)}
                       - entry.taint_fp)
            if new_fps:
                entry.taint_fp |= new_fps
                obs.inc("serve.requeued.geometry_isolated")
        # Divergence AND integrity escalation run the single-request
        # resilient driver — for an integrity retry that driver, with
        # the probe armed by _verify_params, IS the verified-restart
        # recovery path. It solves the reference geometry, so a
        # geometry request must not escalate into solving the wrong
        # domain; it retries through the ordinary (geometry-aware,
        # defensively-verified) dispatch instead.
        entry.escalate = (error_type in (ERROR_DIVERGENCE,
                                         ERROR_INTEGRITY)
                          and self.policy.retry.escalate_divergence
                          and entry.request.geometry is None)
        # A deflation-class request whose solve went divergence/
        # integrity-bad implicates its cached basis: invalidate the
        # family so the retry (escalated or not) runs cold and
        # re-harvests on success — stale memory costs a rebuild, never
        # a second poisoned dispatch.
        if (self._krylov(entry.request).deflation
                and error_type in (ERROR_DIVERGENCE, ERROR_INTEGRITY)):
            from poisson_tpu.krylov.recycle import invalidate

            invalidate(
                fingerprint=fingerprint_of(entry.request.geometry),
                reason=f"escalation-{error_type}")
        entry.not_before = self._clock() + delay
        obs.inc("serve.retries")
        if error_type == ERROR_INTEGRITY:
            obs.inc("serve.integrity.retries")
        obs.inc("serve.backoff_seconds", delay)
        if co_ids:
            obs.inc("serve.requeued.isolated")
        if self._journal is not None:
            # Taint rides the record: the never-co-batch-again pairs
            # must survive a crash while the entry is backing off, or
            # replay would re-batch a poison with its old victims.
            self._journal.record(
                "requeue", request_id=str(entry.request.request_id),
                attempt=entry.attempts, error=error_type,
                recovered=entry.recovered,
                taint=sorted(str(t) for t in entry.taint),
                taint_fp=sorted(entry.taint_fp))
        obs.event("serve.retry", request_id=str(entry.request.request_id),
                  attempt=entry.attempts, delay=round(delay, 4),
                  error=error_type, escalate=entry.escalate)
        rid = entry.request.request_id
        self._flight.point(rid, POINT_RETRY, attempt=entry.attempts,
                           error=error_type, delay=round(delay, 4),
                           escalate=entry.escalate)
        self._flight.begin(rid, SPAN_BACKOFF, attempt=entry.attempts,
                           delay=round(delay, 4))
        self._delayed.append(entry)

    def _backoff_delay(self, attempt: int) -> float:
        r = self.policy.retry
        base = min(r.backoff_base * (2 ** (attempt - 1)), r.backoff_cap)
        # Jitter over [1-jitter, 1]: decorrelates retries without ever
        # exceeding the cap. Seeded RNG — deterministic campaigns.
        return base * (1.0 - r.jitter * self._rng.random())

    # -- outcome recording ---------------------------------------------

    def _record(self, outcome: Outcome) -> Outcome:
        self._pending_ids.discard(outcome.request_id)
        self._outcomes[outcome.request_id] = outcome
        self._order.append(outcome.request_id)
        self._latencies.append(outcome.latency_seconds)
        if self._journal is not None:
            self._journal.record(
                "outcome", request_id=str(outcome.request_id),
                outcome=outcome.kind,
                type=(outcome.error_type or outcome.shed_reason
                      or outcome.flag),
                attempts=outcome.attempts)
        obs.gauge("serve.queue_depth",
                  len(self._queue) + len(self._delayed))
        return outcome

    def _latency(self, entry: _Entry) -> float:
        return max(0.0, self._clock() - entry.admitted_at)

    def _close_flight(self, entry: _Entry, kind: str, type_: str,
                      latency: float, attempts: int,
                      good: bool) -> dict:
        """Close the request's causal trace (one typed outcome leaf, any
        open span folded into its accumulator) and score the SLO."""
        fo = self._flight.outcome(entry.request.request_id, kind=kind,
                                  type_=type_, attempts=attempts)
        self._slo.record(latency, good)
        return fo

    def _complete(self, entry: _Entry, flag: str, converged: bool,
                  partial: bool, iterations: int, restarts: int,
                  diff: float) -> Outcome:
        self._counts["completed"] += 1
        obs.inc("serve.completed")
        if partial:
            obs.inc("serve.completed.partial")
        if restarts:
            obs.inc("serve.completed.recovered")
        latency = self._latency(entry)
        # SLO-good: a converged result inside the latency objective.
        # Partial results and slow successes spend error budget.
        good = (converged and latency
                <= self.policy.slo.latency_objective_seconds)
        fo = self._close_flight(entry, OUTCOME_RESULT, flag, latency,
                                entry.attempts + 1, good)
        tenant = self._tenant(entry.request)
        if tenant is not None:
            obs.inc(f"serve.tenant.completed.{tenant}")
            self._tenancy.credit_success(tenant)
            self._tenant_slo_tracker(tenant).record(latency, good)
        if self._forecast is not None and converged and not partial:
            # Only full converged solves calibrate the cohort model —
            # a deadline partial's iteration count measures the budget,
            # not the problem. compute_s is the flight decomposition's
            # measured per-request compute share.
            self._forecast_observe(
                entry, int(iterations),
                float((fo.get("decomposition") or {})
                      .get("compute_s", 0.0)))
        return self._record(Outcome(
            request_id=entry.request.request_id, kind=OUTCOME_RESULT,
            flag=flag, converged=converged, partial=partial,
            iterations=iterations, restarts=restarts,
            attempts=entry.attempts + 1,
            latency_seconds=latency, diff=diff,
            trace_id=fo["trace_id"], decomposition=fo["decomposition"],
        ))

    def _error(self, entry: _Entry, error_type: str, message: str
               ) -> Outcome:
        self._counts["errors"] += 1
        obs.inc("serve.errors")
        obs.inc(f"serve.errors.{error_type}")
        obs.event("serve.error", request_id=str(entry.request.request_id),
                  error=error_type, message=message[:200])
        latency = self._latency(entry)
        fo = self._close_flight(entry, OUTCOME_ERROR, error_type,
                                latency, max(1, entry.attempts), False)
        tenant = self._tenant(entry.request)
        if tenant is not None:
            obs.inc(f"serve.tenant.errors.{tenant}")
            self._tenant_slo_tracker(tenant).record(latency, False)
        return self._record(Outcome(
            request_id=entry.request.request_id, kind=OUTCOME_ERROR,
            error_type=error_type, message=message,
            attempts=max(1, entry.attempts),
            latency_seconds=latency,
            trace_id=fo["trace_id"], decomposition=fo["decomposition"],
        ))

    def _shed(self, entry: _Entry, reason: str, message: str) -> Outcome:
        self._counts["shed"] += 1
        obs.inc("serve.shed")
        obs.inc(f"serve.shed.{reason}")
        obs.event("serve.shed", request_id=str(entry.request.request_id),
                  reason=reason)
        latency = self._latency(entry)
        fo = self._close_flight(entry, OUTCOME_SHED, reason, latency,
                                entry.attempts, False)
        tenant = self._tenant(entry.request)
        if tenant is not None:
            obs.inc(f"serve.tenant.shed.{tenant}")
            self._tenant_slo_tracker(tenant).record(latency, False)
        return self._record(Outcome(
            request_id=entry.request.request_id, kind=OUTCOME_SHED,
            shed_reason=reason, message=message,
            attempts=entry.attempts,
            latency_seconds=latency,
            trace_id=fo["trace_id"], decomposition=fo["decomposition"],
        ))

    # -- crash recovery (serve.journal) --------------------------------

    @classmethod
    def recover(cls, journal, policy: Optional[ServicePolicy] = None,
                **kwargs) -> "SolveService":
        """Rebuild a service from ``journal``'s write-ahead log after a
        crash: replay the log, re-enqueue every request that was queued
        or in-flight when the previous process died (``recovered``
        taint/backoff path, counted as ``serve.recovered`` — NOT as a
        fresh admission, so merged cross-process ``serve.*`` snapshots
        close the ledger invariant), remember every prior outcome (a
        replayed or retried submission can never double-admit), and
        keep journaling into the same file. The replay report rides on
        the returned service as ``.recovery``."""
        from poisson_tpu.krylov.recycle import invalidate
        from poisson_tpu.serve.journal import replay_journal

        # Journal-safe solver memory: bases live in device memory and
        # are NEVER journaled, so a recovered process must rebuild
        # them from fresh cold solves rather than trust whatever an
        # earlier life (or a same-process predecessor service) left in
        # the process-global cache — unreplayed device state is not
        # evidence. Audible (krylov.cache.invalidations).
        invalidate(all_entries=True, reason="journal-recovery")
        replay = replay_journal(journal.path)
        svc = cls(policy, journal=journal, **kwargs)
        svc._absorb_replay(replay)
        return svc

    def _absorb_replay(self, replay) -> None:
        self.recovery = replay
        for rid, kind in replay.outcomes.items():
            # Terminal truth from the previous life: enough to dedup
            # against; the full Outcome object died with its process.
            self._prior_outcomes.setdefault(
                rid, Outcome(request_id=rid, kind=kind,
                             message="replayed from journal"))
            self._recovered_ids.add(str(rid))
        self._recovered_ids.update(
            str(p.request.request_id) for p in replay.pending)
        now = self._clock()
        for pend in replay.pending:
            req = pend.request
            # Keep the original admission time when the journal clock is
            # comparable with ours (same monotonic epoch — true for a
            # same-boot restart and for shared virtual clocks): latency,
            # SLO scoring, and the flight decomposition then cover the
            # crash gap (it lands in overhead_s — nobody worked on the
            # request while the process was dead). A t_submit from an
            # incomparable clock (in the future) falls back to now.
            t_admit = (pend.t_submit
                       if 0.0 <= pend.t_submit <= now else now)
            entry = _Entry(
                req, t_admit,
                Deadline(req.deadline_seconds, clock=self._clock)
                if req.deadline_seconds is not None else None)
            entry.recovered = True
            entry.attempts = pend.attempts
            entry.taint = set(pend.taint)
            entry.taint_fp = set(getattr(pend, "taint_fp", ()) or ())
            if self._tenancy is not None:
                # Rebuild the tenant ledger from the journal: register
                # the tenant (share, fresh quota bucket) and re-charge
                # its journaled dispatch attempts beyond the first
                # against the retry budget — a poisoned tenant cannot
                # reset its amplification cap by crashing the process
                # mid-storm.
                tenant = self._tenant(req)
                self._tenancy.charge_attempts(tenant,
                                              max(0, pend.attempts - 1))
            self._counts["recovered"] += 1
            obs.inc("serve.recovered")
            self._pending_ids.add(req.request_id)
            rid = req.request_id
            if pend.trace_id:
                # Continue the crashed process's causal trace: same
                # trace id, span ids offset past the dead incarnation's.
                self._flight.adopt(rid, pend.trace_id, t_admit,
                                   span_base=1000 * pend.generation)
            else:
                self._flight.admit(rid)
            self._flight.point(rid, POINT_RECOVERED,
                               reason="journal_replay",
                               generation=pend.generation,
                               in_flight=pend.in_flight,
                               lost_hook=pend.lost_hook)
            if self._tenancy is not None:
                self._flight.begin(rid, SPAN_QUEUE, recovered=True,
                                   tenant=self._tenant(req))
            else:
                self._flight.begin(rid, SPAN_QUEUE, recovered=True)
            # Topology-aware recovery: work that was on a device this
            # topology no longer has is REMAPPED audibly — never
            # silently resumed onto a ghost device id. A hard pin that
            # cannot map is a typed ``placement`` error, not a wedge.
            dev = pend.device_id
            if req.device_id is not None and not self._registry.is_alive(
                    int(req.device_id)):
                self._flight.end(rid, SPAN_QUEUE)
                self._error(entry, ERROR_PLACEMENT,
                            f"recovered request pinned to device "
                            f"{req.device_id}, which does not exist on "
                            f"this topology "
                            f"({len(self._registry)} devices)")
                continue
            if dev is not None and not self._registry.is_alive(int(dev)):
                try:
                    placement = self._registry.remap(int(dev))
                except PlacementError as e:
                    self._flight.end(rid, SPAN_QUEUE)
                    self._error(entry, ERROR_PLACEMENT, str(e))
                    continue
                self._flight.point(rid, POINT_PLACEMENT,
                                   from_device=int(dev),
                                   to_device=placement.device_id,
                                   from_epoch=pend.epoch,
                                   epoch=self._registry.epoch)
            if self._journal is not None:
                self._journal.record("recover", request_id=str(rid),
                                     generation=pend.generation,
                                     in_flight=pend.in_flight)
            if pend.in_flight:
                # Mid-dispatch at the crash: back off before the redo —
                # the crash may have been this cohort's fault.
                entry.not_before = now + self.policy.fleet.recovery_backoff
                self._delayed.append(entry)
                self._flight.end(rid, SPAN_QUEUE)
                self._flight.begin(rid, SPAN_BACKOFF, recovered=True)
            else:
                self._queue.append(entry)
        obs.event("serve.recovery", recovered=len(replay.pending),
                  prior_outcomes=len(replay.outcomes),
                  torn=replay.torn_records)
        obs.gauge("serve.queue_depth",
                  len(self._queue) + len(self._delayed))

    # -- accounting ----------------------------------------------------

    def worker_device(self, worker_id: int) -> Optional[int]:
        """The fault-domain slot worker ``worker_id`` is bound to (None
        when unbound) — the placement lookup the device-loss chaos
        injectors use to target silicon rather than workers."""
        worker = self._pool.workers[int(worker_id)]
        return (worker.placement.device_id
                if worker.placement is not None else None)

    def outcomes(self) -> List[Outcome]:
        """Every outcome so far, in completion order."""
        return [self._outcomes[rid] for rid in self._order]

    def stats(self) -> dict:
        """The ledger: admitted vs terminated (the no-lost-request
        invariant is ``lost == 0`` once the queue is drained), latency
        percentiles on the service clock, and the shed rate.

        ``recovered`` counts requests adopted from a journal replay:
        they were admitted (and counted) by the crashed process, so this
        process's ledger balances admitted + recovered against outcomes
        — and the *merged* cross-process counters balance plain admitted
        against outcomes, which is how the chaos campaign asserts the
        invariant across a kill/replay boundary."""
        c = dict(self._counts)
        # Pending = every admitted request without an outcome yet —
        # queued, backing off, OR resident in a lane / mid-dispatch.
        # _pending_ids is exactly that set (discarded only when the
        # outcome is recorded), so the ledger stays honest when stats()
        # is read mid-flight between pump() calls (the open-loop seam).
        pending = len(self._pending_ids)
        lats = sorted(self._latencies)
        single = self.policy.fleet.workers == 1
        breakers = {}
        for w in self._pool.workers:
            for cohort, b in w.breakers.items():
                breakers[cohort if single else f"{cohort}@w{w.id}"] = \
                    b.state
        router = (self._router.stats() if self._router is not None
                  else None)
        tenants = None
        if self._tenancy is not None:
            tenants = self._tenancy.describe()
            for name, tracker in self._tenant_slo.items():
                row = tenants.setdefault(name, {})
                row["slo_budget_remaining"] = round(
                    tracker.budget_remaining(), 6)
        return {
            "admitted": c["admitted"],
            "completed": c["completed"],
            "errors": c["errors"],
            "shed": c["shed"],
            "recovered": c["recovered"],
            "pending": pending,
            **({"router": router} if router is not None else {}),
            **({"tenants": tenants} if tenants is not None else {}),
            "lost": (c["admitted"] + c["recovered"]
                     - (c["completed"] + c["errors"] + c["shed"])
                     - pending),
            "latency_seconds": {
                "p50": _percentile(lats, 0.50),
                "p95": _percentile(lats, 0.95),
                "p99": _percentile(lats, 0.99),
            },
            "shed_rate": (c["shed"] / c["admitted"] if c["admitted"]
                          else 0.0),
            "breakers": breakers,
            "workers": {w.id: w.state for w in self._pool.workers},
            "placement": {
                **self._registry.describe(),
                "bindings": {w.id: (w.placement.device_id
                                    if w.placement else None)
                             for w in self._pool.workers},
            },
        }

    def _publish_stats(self) -> None:
        s = self.stats()
        obs.gauge("serve.latency_seconds", s["latency_seconds"])
        obs.gauge("serve.p99_latency_seconds",
                  s["latency_seconds"]["p99"])
        obs.gauge("serve.shed_rate", round(s["shed_rate"], 6))
        obs.gauge("serve.queue_depth", s["pending"])
        obs.gauge("serve.lost_requests", s["lost"])
        if self._tenancy is not None:
            # Per-tenant gauges for the scoreboard's tenants pane —
            # flat scalar families (one suffix per tenant) so the
            # prefix scan renders them identically from a live
            # endpoint and a trace-dir snapshot.
            for name, row in self._tenancy.describe().items():
                obs.gauge(f"serve.tenant.share.{name}", row["share"])
                obs.gauge(f"serve.tenant.quota_tokens.{name}",
                          row["quota_tokens"])
                obs.gauge(f"serve.tenant.retry_tokens.{name}",
                          row["retry_tokens"])
            shortest = (min(self.policy.slo.burn_windows)
                        if self.policy.slo.burn_windows else None)
            for name, tracker in self._tenant_slo.items():
                tracker.publish()
                if shortest is not None:
                    obs.gauge(f"serve.tenant.slo_burn.{name}",
                              round(tracker.burn_rate(shortest), 4))
        if self._forecast is not None:
            self._forecast_backlog()
