"""Typed request/outcome taxonomy for the solve service.

The central invariant the whole ``poisson_tpu.serve`` layer exists to
uphold: **every admitted request terminates with exactly one typed
outcome** — a result (possibly partial), a typed error, or a typed shed.
No request is ever silently lost, and nothing about a request's fate has
to be reconstructed from logs: the outcome object says what happened,
after how many attempts, and in how long.

Outcome kinds:

- ``result`` — a solution grid came back. ``converged`` says whether it
  met δ; a deadline or degraded-iteration-cap stop returns the partial
  iterate with ``partial=True`` and the stop verdict in ``flag``
  (``solvers.pcg.FLAG_NAMES``) rather than pretending to have failed —
  the partial iterate of an elliptic solve is a usable warm start.
- ``error`` — the service gave up after its retry/escalation budget:
  ``error_type`` ∈ ``divergence`` (recovery exhausted, see
  ``solvers.resilient.DivergenceError``), ``transient`` (dispatch kept
  failing — device fault, injected chaos), ``integrity`` (the in-loop
  verification probe kept detecting silent data corruption —
  ``poisson_tpu.integrity``; the first detection also taints the
  (backend, device_kind) hardware cohort as SDC-suspect), ``internal``
  (a bug; never retried, always surfaced).
- ``shed`` — the service refused the work, by policy, with a reason:
  ``queue_full`` (bounded admission queue — overload never becomes
  unbounded memory growth), ``breaker_open`` (the request's cohort is
  circuit-broken), ``deadline_expired`` (the budget ran out while the
  request was still queued — dispatching it would burn capacity on an
  answer nobody is waiting for), ``predicted_deadline`` (the forecast
  guard priced the deadline hopeless before any compute), or
  ``quota_exceeded`` (the tenant is over its admission quota —
  ``ServicePolicy.tenancy``; one hot client's overload never becomes
  everyone's).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Tuple, Union

from poisson_tpu.config import Problem
from poisson_tpu.integrity.probe import IntegrityPolicy
from poisson_tpu.krylov import KrylovPolicy
from poisson_tpu.serve.tenancy import TenancyPolicy

OUTCOME_RESULT = "result"
OUTCOME_ERROR = "error"
OUTCOME_SHED = "shed"

ERROR_DIVERGENCE = "divergence"
ERROR_TRANSIENT = "transient"
ERROR_INTERNAL = "internal"
ERROR_INTEGRITY = "integrity"
# A placement that cannot be satisfied on the current topology: a
# request pinned to a device id that no longer exists (recovery on a
# smaller topology, a lost fault domain). Typed — never a wedge.
ERROR_PLACEMENT = "placement"

SHED_QUEUE_FULL = "queue_full"
SHED_BREAKER_OPEN = "breaker_open"
SHED_DEADLINE_EXPIRED = "deadline_expired"
# The forecast guard's verdict (ServicePolicy.forecast): the cohort's
# p90 ETA says this deadline cannot be met — refused at admission (or
# pre-empted at a lane boundary) BEFORE burning the compute, which is
# the whole point of forecasting.
SHED_PREDICTED_DEADLINE = "predicted_deadline"
# The tenant's token-bucket admission quota is empty
# (ServicePolicy.tenancy): refused at admission, zero compute burned —
# per-client overload is that client's problem, not the fleet's.
SHED_QUOTA_EXCEEDED = "quota_exceeded"


class TransientDispatchError(RuntimeError):
    """A dispatch-level fault that poisoned the whole batch (device
    crash, wedged transfer, injected chaos). Retryable: the service
    re-enqueues every member into a *different* bucket — one poisoned
    member must not re-kill its batchmates on the retry."""


@dataclasses.dataclass(frozen=True)
class SolveRequest:
    """One Poisson solve as a service request.

    ``rhs_gate`` scales the problem's RHS (the multi-tenant knob: many
    requests share one operator cohort and differ in forcing). Requests
    whose ``deadline_seconds``/``chunk`` is set are dispatched through
    the chunked single-request path (deadlines need chunk boundaries to
    be enforceable); the rest ride the batched multi-RHS path.
    ``on_chunk`` is the fault-injection seam (``testing.faults``) for
    chunked dispatches — None in production.

    ``geometry`` makes the DOMAIN a request parameter
    (:mod:`poisson_tpu.geometry`): a spec compiled to fingerprint-cached
    coefficient canvases. Geometry requests form their own ``…:geo``
    cohorts in which *different* geometries on the same grid co-batch
    inside one bucket executable (only the canvases differ per member);
    the fingerprint rides the flight trace for attribution, and
    poison-isolation taint keys on (request, fingerprint) — a geometry
    family implicated in a batch kill never re-co-batches with the
    batchmates it took down. ``None`` is the reference ellipse path,
    byte-identical to every prior release.

    ``preconditioner`` selects the request's M⁻¹ (``"jacobi"`` |
    ``"mg"`` — :mod:`poisson_tpu.mg`; None defers to
    ``ServicePolicy.preconditioner``). MG requests form their own
    ``…:mg`` cohorts — separate bucket executables, separate breakers,
    separate sentinel baselines — so an MG rollout can never indict (or
    hide behind) the Jacobi fleet; MG+geometry requests dispatch solo
    (per-member hierarchies do not co-batch yet).
    """

    request_id: Union[int, str]
    problem: Problem
    rhs_gate: float = 1.0
    dtype: Optional[str] = None
    deadline_seconds: Optional[float] = None
    chunk: Optional[int] = None
    max_attempts: Optional[int] = None
    on_chunk: Optional[Callable] = None
    geometry: Optional[object] = None     # geometry.dsl.GeometrySpec
    preconditioner: Optional[str] = None  # None -> policy default
    # Hard placement pin (serve.placement): the request may only run on
    # a worker bound to this fault-domain slot — the A/B-on-one-chip
    # and indict-the-part debugging knob. Validated alive at admission;
    # a pin whose device dies while the request is pending (or is gone
    # at journal recovery on a smaller topology) becomes a typed
    # ``placement`` error, never a wedge. None (default): any worker.
    device_id: Optional[int] = None
    # Krylov-memory knobs (:mod:`poisson_tpu.krylov`; None defers to
    # ``ServicePolicy.krylov``). ``mode="block"`` requests form their
    # own ``…:blk`` cohorts (block bucket executables — co-batched
    # members must share one operator, so block batches additionally
    # require fingerprint-uniform geometry); ``deflation=True``
    # requests form ``…:defl`` cohorts and dispatch solo through the
    # fingerprint-keyed basis cache (``krylov.recycle``), with routing
    # preferring the worker already holding the family's basis.
    # Validated at admission: an unknown mode, block+deflation, or
    # deflation combined with the chunked/deadline path is a loud
    # ValueError.
    krylov: Optional[KrylovPolicy] = None
    # Session stream identity (:mod:`poisson_tpu.serve.session`): a
    # request carrying ``session_id`` is step ``session_step`` of an
    # ordered stream of dependent solves. Session steps dispatch solo
    # (the warm-start seam is a single-request program) and journal
    # their session fields, so a recovery re-enqueues a killed step
    # into the SAME stream. ``warm_start`` is the previous step's
    # w-space iterate and ``warm_geometry`` the spec it solved — device
    # state, deliberately NEVER journaled: a recovered step always runs
    # cold (unreplayed device state is not evidence). ``mass_shift`` is
    # the implicit-Euler 1/Δt operator shift (0 = plain Poisson step).
    # ``on_solution`` hands the step's solution grid back to the
    # session host for the next step's warm start — a process handle,
    # like ``on_chunk`` it does not survive a crash (audibly).
    session_id: Optional[str] = None
    session_step: Optional[int] = None
    mass_shift: float = 0.0
    warm_start: Optional[object] = None
    warm_geometry: Optional[object] = None
    on_solution: Optional[Callable] = None
    # The client identity behind the request (``rhs_gate`` is the
    # multi-tenant *payload* knob; this is the multi-tenant *identity*
    # knob). With ``ServicePolicy.tenancy`` set it selects the tenant's
    # admission-quota bucket, fair-share weight, and retry budget
    # (``serve.tenancy``); it rides the journal and the flight trace.
    # None pools the request under the ``"default"`` pseudo-tenant —
    # and with tenancy off (the default) it is inert metadata.
    tenant: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class Outcome:
    """The one typed terminal record of a request's lifecycle."""

    request_id: Union[int, str]
    kind: str                     # result | error | shed
    flag: str = ""                # stop verdict name (result outcomes)
    converged: bool = False
    partial: bool = False         # deadline/cap-stopped result
    iterations: int = 0
    restarts: int = 0             # recovery attempts inside the solve
    attempts: int = 1             # service-level dispatch attempts
    latency_seconds: float = 0.0  # admission → outcome, service clock
    error_type: str = ""          # divergence | transient | internal
    shed_reason: str = ""         # queue_full | breaker_open |
    #                               deadline_expired | predicted_deadline |
    #                               quota_exceeded
    message: str = ""
    diff: Optional[float] = None  # final ‖Δw‖ (result outcomes)
    # Flight-recorder attribution (obs.flight): the request's causal
    # trace id (joins the JSONL span tree / `python -m poisson_tpu
    # trace`) and its latency decomposition — wall_s = queue_s +
    # compute_s + lane_wait_s + backoff_s + overhead_s on the service
    # clock, components summing to the measured wall.
    trace_id: str = ""
    decomposition: Optional[dict] = None

    @property
    def ok(self) -> bool:
        return self.kind == OUTCOME_RESULT and self.converged


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Retry-with-exponential-backoff-and-jitter for retryable failures.

    ``max_attempts`` counts dispatches (1 = never retry). Backoff delay
    for attempt *n* (1-based) is
    ``min(backoff_base · 2^(n−1), backoff_cap)``, jittered over
    ``[1 − jitter, 1]`` by the service's seeded RNG — deterministic under
    a fixed seed, decorrelated across requests. ``escalate_divergence``
    routes a divergence-class retry through the self-healing driver
    (``solvers.resilient``: restart from last good iterate, precision
    escalation) instead of a plain re-dispatch.
    """

    max_attempts: int = 3
    backoff_base: float = 0.05
    backoff_cap: float = 2.0
    jitter: float = 0.5
    escalate_divergence: bool = True


@dataclasses.dataclass(frozen=True)
class BreakerPolicy:
    """Per-cohort circuit breaker: trip after ``failure_threshold``
    consecutive dispatch failures, hold OPEN for ``cooldown_seconds``,
    then HALF_OPEN with ``half_open_probes`` probe dispatches."""

    failure_threshold: int = 3
    cooldown_seconds: float = 5.0
    half_open_probes: int = 1


@dataclasses.dataclass(frozen=True)
class DegradationPolicy:
    """The graceful-degradation policy ladder, driven by queue depth as a
    fraction of capacity. Each engaged step is audible as a
    ``serve.degraded.*`` counter and event — degradation that cannot be
    seen in the metrics is indistinguishable from silent data loss.

    1. ``shrink_padding_at`` — dispatch exact-size batches instead of
       power-of-two buckets: no padding-member work when every real
       member counts (costs executable-cache reuse, buys latency).
    2. ``cap_iterations_at`` — cap ``max_iterations`` at
       ``degraded_iteration_cap``: slow-converging requests return
       partial results instead of holding the queue hostage.
    3. ``downshift_precision_at`` — downshift float64 requests to
       float32 (symmetrically-scaled fp32 reproduces fp64 iteration
       counts on this problem class — README "Precision policy").
    """

    shrink_padding_at: float = 0.5
    cap_iterations_at: float = 0.75
    degraded_iteration_cap: int = 256
    downshift_precision_at: float = 0.9


@dataclasses.dataclass(frozen=True)
class FleetPolicy:
    """The durable-fleet knobs (``serve.fleet``): ``workers`` dispatch
    contexts pull from the shared admission queue, each owning its own
    sticky bucket executables, circuit-breaker cohort, lane table, and
    heartbeat watchdog (``parallel.watchdog``).

    A worker that crashes mid-dispatch (``WorkerCrashError`` from the
    worker-fault seam), hangs past ``heartbeat_timeout`` on the service
    clock (``WorkerHangError``, or a successful step that overran the
    watchdog), or keeps poisoning its dispatches is **quarantined** for
    ``quarantine_seconds``: its in-flight requests are recovered —
    mutual-tainted, ``recovery_backoff``-delayed, flight-marked
    ``recovered`` — and re-dispatched to the surviving workers. After
    cooldown the worker restarts through warm-up (``warm_restart``
    recompiles its sticky bucket executables before it takes traffic);
    after ``max_restarts`` restarts it is declared dead and never
    scheduled again. Every transition is audible as a
    ``serve.fleet.*`` counter/event.

    ``heartbeat_timeout`` is **opt-in** (None disables the stall
    verdict): it bounds one dispatch/chunk step on the service clock,
    and only the operator knows what "too long" means for their grids —
    a default would mistake a legitimately slow large-grid dispatch
    (cold compile included) for a hang and evict healthy lane progress.
    Size it well past the worst healthy step, like the PR 1 watchdog.

    ``devices`` spreads the fleet over real silicon
    (``serve.placement``): N fault-domain slots backed by
    ``jax.devices()`` (oversubscribed when fewer physical devices
    exist — CPU gets real topologies via ``XLA_FLAGS=--xla_force_host_
    platform_device_count``), workers bound round-robin, sticky bucket
    executables compiled ON the bound device, breaker/integrity
    cohorts keyed by ``(device_kind, device_id)``. A
    :class:`~poisson_tpu.serve.fleet.DeviceLossError` from the
    worker-fault seam quarantines EVERY worker in the lost fault
    domain and rebinds them to survivors at restart. None (default):
    one slot on the process default device — byte-for-byte the
    pre-placement fleet.
    """

    workers: int = 1
    heartbeat_timeout: Optional[float] = None
    quarantine_seconds: float = 0.5
    max_restarts: int = 3
    recovery_backoff: float = 0.05
    warm_restart: bool = True
    devices: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class SLOPolicy:
    """Declared service-level objectives, scored per outcome by the
    flight recorder's :class:`~poisson_tpu.obs.flight.SLOTracker`.

    An outcome is **good** iff it is a converged result delivered within
    ``latency_objective_seconds``; everything else — sheds, typed
    errors, partial results, and slow successes — spends error budget
    (budget = ``1 − availability_target``). The tracker publishes
    ``serve.slo.{good,bad}`` counters, the real latency histogram
    (``serve.slo.latency_seconds`` — Prometheus histogram exposition),
    ``serve.slo.budget_remaining``, and one burn-rate gauge per entry in
    ``burn_windows`` (seconds on the service clock; two windows is the
    classic short-says-now / long-says-not-a-blip pairing).

    ``degrade_on_burn`` lets the degradation ladder consult the burn
    rate: rung *i+1* engages when EVERY window burns at or above
    ``burn_degrade_thresholds[i]`` (multi-window rule), making
    downshifts SLO-driven rather than only queue-depth-driven. Off by
    default: burn-driven downshifts change scheduling decisions, so the
    operator opts in with the thresholds they mean.
    """

    latency_objective_seconds: float = 2.0
    availability_target: float = 0.999
    burn_windows: tuple = (60.0, 600.0)
    degrade_on_burn: bool = False
    burn_degrade_thresholds: tuple = (2.0, 6.0, 14.0)


@dataclasses.dataclass(frozen=True)
class SessionPolicy:
    """Durable-session knobs (:mod:`poisson_tpu.serve.session`).

    ``max_sessions`` bounds concurrently-open sessions (an open beyond
    it sheds, typed). ``shed_open_at`` is the session rung of the
    degradation ladder: a NEW session open sheds once queue depth
    reaches this fraction of capacity — deliberately *below* the
    queue-full threshold that sheds individual steps, because a
    half-finished stream is sunk cost (shed new sessions before steps
    of in-flight ones). ``warm_drift_bound``/``warm_residual_factor``
    parameterize the warm-start validity gate
    (``solvers.session.warm_validity``/residual sanity — a failing gate
    falls back cold, audibly). ``step_deadline_seconds`` is the
    default per-step deadline (enforced at step boundaries — the fused
    session programs do not chunk; a miss counts
    ``session.step.deadline_misses``). ``slo_seconds`` is the
    per-session wall objective scored at close on the session's own
    flight trace (``session.slo.{good,bad}``)."""

    max_sessions: int = 8
    shed_open_at: float = 0.75
    warm_drift_bound: float = 0.05
    warm_residual_factor: float = 100.0
    step_deadline_seconds: Optional[float] = None
    slo_seconds: float = 60.0


@dataclasses.dataclass(frozen=True)
class ForecastPolicy:
    """Predicted-deadline knobs (:mod:`poisson_tpu.obs.forecast`).

    ``admission_shed``: a request whose deadline is below the cohort's
    p90 ETA × ``margin`` at submit sheds as typed
    ``predicted_deadline`` — refused before any dispatch, never
    admitted-then-burned. ``reforecast``: at every lane/chunk boundary
    an admitted deadline request is re-forecast from its own measured
    log-residual slope; hopeless work is pre-empted there (also a
    typed ``predicted_deadline`` shed, plus
    ``serve.forecast.preempted``). ``backlog_degradation``: the
    degradation ladder consults ETA backlog-seconds (queued p50 ETAs
    against ``backlog_objective_seconds``) instead of only raw queue
    depth. The shed condition is ``eta_p90 × margin > deadline``, so
    ``margin`` > 1 demands head-room (sheds more eagerly) and < 1
    tolerates optimistic ETAs. ``history_every`` > 0 additionally traces
    the residual-history callback into chunked solo dispatches
    (``pcg_solve(history_every=…)``); 0 keeps every program
    byte-identical and estimates from lane-boundary samples only."""

    admission_shed: bool = True
    reforecast: bool = True
    backlog_degradation: bool = False
    backlog_objective_seconds: float = 60.0
    margin: float = 1.0
    history_every: int = 0


@dataclasses.dataclass(frozen=True)
class RouterPolicy:
    """Backend-router knobs (:mod:`poisson_tpu.serve.router`).

    ``backend``: ``"auto"`` routes per cohort (analytic model cold,
    measured roofline evidence warm); any explicit backend name pins
    every dispatch to that arm (falling back to ``xla`` where the arm
    is unavailable). ``misprediction_fraction``: a measured dispatch
    landing below this fraction of its cohort's expected roofline
    fraction is a misprediction (typed ``serve.router.misprediction``
    event). ``demote_after`` consecutive mispredictions demote the
    (backend, device_id) arm for ``cooldown_seconds``, then HALF_OPEN
    with ``half_open_probes`` probe dispatches — a good probe is a
    ``serve.router.recover``. ``warm_min_samples`` measured samples in
    a candidate's cohort graduate routing from the cold analytic table
    to warm measured ranking. ``assume_available`` force-lists Pallas
    arms on non-TPU hosts — the chaos/test seam that exercises the
    full routing state machine on CPU. ``downshift_at`` is the
    degradation ladder's backend-downshift rung: at that queue
    fraction every dispatch is forced onto the proven ``xla`` floor
    arm (``serve.degraded.backend_downshift``)."""

    backend: str = "auto"
    misprediction_fraction: float = 0.5
    demote_after: int = 2
    cooldown_seconds: float = 30.0
    half_open_probes: int = 1
    warm_min_samples: int = 3
    assume_available: Tuple[str, ...] = ()
    downshift_at: float = 0.95


# Scheduling modes (ServicePolicy.scheduling):
SCHED_DRAIN = "drain"            # PR 5 batch-drain: dispatch, wait, repeat
SCHED_CONTINUOUS = "continuous"  # lane table + refill state machine


@dataclasses.dataclass(frozen=True)
class ServicePolicy:
    """Top-level service knobs: bounded queue ``capacity`` (admission
    beyond it sheds — typed, immediate, never unbounded growth),
    ``max_batch`` members per fused dispatch, ``default_chunk``
    iterations between deadline checks on chunked dispatches.

    ``scheduling`` selects the dispatch engine: ``"drain"`` (the PR 5
    design — form a batch, run it to completion, form the next) or
    ``"continuous"`` (Orca-style in-flight refill — a lane table steps
    the fused program ``refill_chunk`` iterations at a time, retires
    converged lanes to their typed outcomes at each boundary, and
    splices queued RHS into the freed lanes of the same bucket
    executable; breaker/degradation/taint policies are re-checked at
    every refill decision). Both engines uphold the same ledger
    invariant; ``drain`` stays the default so the two are A/B-comparable
    (``bench.py --serve --arrival-rate`` measures exactly that).
    ``refill_chunk`` is the continuous engine's iterations-per-step —
    smaller means fresher refill decisions and tighter deadline
    enforcement, at more host round-trips.

    ``integrity`` is the silent-data-corruption defense
    (:class:`~poisson_tpu.integrity.IntegrityPolicy`): with
    ``verify_every`` > 0 every dispatch — batched, chunked solo, and
    lane-table programs — runs the in-loop drift probe and a
    FLAG_INTEGRITY member becomes a typed ``integrity`` retry; at the
    default 0 the probe only arms *defensively*, after a first
    detection has tainted the (backend, device_kind) hardware cohort
    as SDC-suspect (``verify_on_suspect``/``suspect_verify_every``) —
    the executables of an untainted flag-off service stay
    byte-identical to every prior release.

    ``fleet`` sizes and supervises the worker pool (:class:`FleetPolicy`
    — ``workers=1`` is the single-worker service every prior PR ran).
    ``dedup`` makes submission idempotent: a second ``submit`` with an
    already-seen ``request_id`` returns the original outcome (or None
    while it is still pending) and counts a ``serve.dedup.hits`` —
    instead of raising — so a client retry or a replayed submission can
    never double-admit. Off by default: with deduplication off, a
    recycled id is a caller bug and stays a loud ``ValueError``.

    ``preconditioner`` is the service-wide default M⁻¹ for requests
    that do not set their own (``"jacobi"`` keeps every prior release's
    executables; ``"mg"`` makes the V-cycle the fleet default —
    requests on uncoarsenable grids are then rejected loudly at
    submission rather than failing inside a dispatch).

    ``krylov`` is the service-wide Krylov-memory default
    (:class:`~poisson_tpu.krylov.KrylovPolicy`) for requests that do
    not set their own: the default (independent mode, no deflation)
    keeps every prior release's executables and cohorts byte-for-byte;
    ``mode="block"`` makes the block recurrence the fleet default for
    batchable dispatches (``…:blk`` cohorts), ``deflation=True`` routes
    every request through the fingerprint-keyed solver memory
    (``…:defl`` cohorts, solo dispatch, basis-holder sticky routing).

    ``session`` governs durable solver sessions
    (:class:`SessionPolicy` — ``poisson_tpu.serve.session``): open
    bounds, the shed-new-sessions-first degradation rung, warm-start
    validity, per-step deadlines, and the per-session SLO. The defaults
    change nothing for session-free traffic.

    ``forecast`` arms the convergence observatory
    (:class:`ForecastPolicy` — ``poisson_tpu.obs.forecast``):
    predicted-deadline admission, lane-boundary re-forecast
    pre-emption, and ETA-backlog degradation. None (the default)
    traces nothing, sheds nothing, and predicts nothing — byte- and
    behavior-identical to every prior release.

    ``router`` arms the cost-model backend router
    (:class:`RouterPolicy` — ``poisson_tpu.serve.router``): per-cohort
    backend choice (analytic model cold, measured roofline evidence
    warm), misprediction sentinels with breaker-style arm demotion,
    and the backend-downshift degradation rung. None (the default)
    routes nothing — every cohort string, program, and dispatch path
    stays byte-identical to every prior release (pinned by the
    ``serve.routed_default_f64`` contracts ledger entry).

    ``tenancy`` arms tenant isolation & overload fairness
    (:class:`~poisson_tpu.serve.tenancy.TenancyPolicy` —
    ``poisson_tpu.serve.tenancy``): per-tenant token-bucket admission
    quotas (typed ``quota_exceeded`` sheds), deficit-weighted
    round-robin head selection in both engines, per-bucket lane-share
    caps, retry budgets that convert a poisoned tenant's requeue storm
    into typed errors, and tenant-scoped degradation/SLO accounting
    (``serve.tenant.*``). None (the default) polices nothing — strict
    FIFO service, byte- and behavior-identical to every prior release.
    """

    capacity: int = 64
    max_batch: int = 32
    default_chunk: int = 50
    scheduling: str = SCHED_DRAIN
    refill_chunk: int = 25
    dedup: bool = False
    preconditioner: str = "jacobi"
    retry: RetryPolicy = RetryPolicy()
    breaker: BreakerPolicy = BreakerPolicy()
    degradation: DegradationPolicy = DegradationPolicy()
    slo: SLOPolicy = SLOPolicy()
    fleet: FleetPolicy = FleetPolicy()
    integrity: IntegrityPolicy = IntegrityPolicy()
    krylov: KrylovPolicy = KrylovPolicy()
    session: SessionPolicy = SessionPolicy()
    forecast: Optional[ForecastPolicy] = None
    router: Optional[RouterPolicy] = None
    tenancy: Optional[TenancyPolicy] = None
