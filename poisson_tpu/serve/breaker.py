"""Per-cohort circuit breaker.

A cohort — one (grid, dtype, backend) combination — that keeps failing
is usually failing for a structural reason: a grid that breaks a kernel,
a precision that diverges on this conditioning, a wedged device behind
one executable shape. Retrying every arriving request into it burns the
queue's capacity on work that will fail; the breaker converts "keeps
failing" into "fail fast, probe occasionally":

- **CLOSED** (healthy): dispatches flow; consecutive failures are
  counted, any success resets the count.
- **OPEN** (tripped, after ``failure_threshold`` consecutive failures):
  every request in the cohort is shed with the typed ``breaker_open``
  reason — cheap, immediate, and honest — for ``cooldown_seconds``.
- **HALF_OPEN** (after cooldown): ``half_open_probes`` real dispatches
  are let through as probes. A probe success closes the breaker; a probe
  failure re-trips it for another cooldown.

State transitions land on ``serve.breaker.{trips,half_opens,closes}``
counters and events, so a trip is visible in the metrics snapshot, not
just in per-request outcomes.
"""

from __future__ import annotations

import time
from typing import Callable

from poisson_tpu import obs
from poisson_tpu.serve.types import BreakerPolicy

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    """One breaker instance per cohort (the service keeps a registry).
    Clock-injectable for deterministic chaos scenarios. Single-threaded
    by design — the service's dispatch loop is the only caller."""

    def __init__(self, policy: BreakerPolicy,
                 clock: Callable[[], float] = time.monotonic,
                 cohort: str = ""):
        if policy.failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.policy = policy
        self.cohort = cohort
        self._clock = clock
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probes_left = 0

    @property
    def state(self) -> str:
        """Current state, accounting for cooldown expiry (reading the
        state can move OPEN → HALF_OPEN; it never moves anything else)."""
        if (self._state == OPEN
                and self._clock() - self._opened_at
                >= self.policy.cooldown_seconds):
            self._state = HALF_OPEN
            self._probes_left = self.policy.half_open_probes
            obs.inc("serve.breaker.half_opens")
            obs.event("serve.breaker.half_open", cohort=self.cohort)
        return self._state

    def allow(self) -> bool:
        """May a dispatch for this cohort proceed right now? HALF_OPEN
        consumes one probe slot per allowed dispatch."""
        state = self.state
        if state == CLOSED:
            return True
        if state == HALF_OPEN and self._probes_left > 0:
            self._probes_left -= 1
            return True
        return False

    def record_success(self) -> None:
        self._consecutive_failures = 0
        if self._state == HALF_OPEN:
            self._state = CLOSED
            obs.inc("serve.breaker.closes")
            obs.event("serve.breaker.close", cohort=self.cohort)

    def record_failure(self) -> None:
        self._consecutive_failures += 1
        tripping = (self._state == HALF_OPEN
                    or self._consecutive_failures
                    >= self.policy.failure_threshold)
        if tripping and self._state != OPEN:
            self._state = OPEN
            self._opened_at = self._clock()
            self._consecutive_failures = 0
            obs.inc("serve.breaker.trips")
            obs.event("serve.breaker.trip", cohort=self.cohort)
