"""The refill state machine: service-side lane table for continuous batching.

``solvers.lanes.LaneBatch`` is the solver half (a resumable stepping
program over a fixed bucket of lanes); this module is the service half —
a :class:`LaneTable` that binds each lane to the service's queue-resident
request entry and makes every transition of the lane lifecycle

    EMPTY ──splice──▶ ACTIVE ──verdict/deadline/cap──▶ RETIRING ──▶ EMPTY

audible as ``serve.refill.*`` counters:

- ``serve.refill.splices`` — queued RHS spliced into freed lanes;
- ``serve.refill.retired_lanes`` — lanes retired to a typed outcome
  (converged, partial, or failure verdict — eviction on a batch-killing
  fault is counted by the retry machinery instead);
- ``serve.refill.idle_lane_steps`` — Σ over chunk steps of EMPTY lanes
  the fused program still paid compute width for (the utilization loss
  refill exists to minimize);
- ``serve.refill.refill_denied_by_breaker`` — refill decisions refused
  because the cohort's circuit breaker was not accepting work
  (incremented by the service at the decision point).

The scheduling policy — breaker checks, degradation re-checks, taint
compatibility, retries — lives in ``serve.service``; this class only
guarantees occupancy bookkeeping: a lane is at all times either EMPTY or
attributed to exactly one request entry, and an entry leaves the table
only through ``retire`` (with its iterate) or ``evict_all`` (a dispatch
fault that owes every member a retry or a typed error). That is the
structural half of the no-lost-request invariant.
"""

from __future__ import annotations

from typing import List, Optional, Set, Tuple

from poisson_tpu import obs
from poisson_tpu.solvers.lanes import LaneBatch, LaneResult

LANE_EMPTY = "empty"
LANE_ACTIVE = "active"
LANE_RETIRING = "retiring"


class LaneTable:
    """A :class:`~poisson_tpu.solvers.lanes.LaneBatch` whose lanes carry
    the service's request entries. ``cohort``/``problem``/``dtype_name``
    pin what may splice in (checked by the service's refill decision);
    ``entries[lane]`` is the occupant (None = EMPTY)."""

    def __init__(self, cohort: str, problem, dtype, bucket: int,
                 chunk: int, worker_id: int = 0,
                 multi_geometry: bool = False, verify_every: int = 0,
                 verify_tol=None, preconditioner: str = "jacobi",
                 device=None):
        self.cohort = cohort
        self.problem = problem
        self.worker_id = worker_id
        # The owning worker's bound jax.Device (serve.placement): the
        # lane stepping program compiles and runs there, not on the
        # process default device.
        self.device = device
        self.multi_geometry = bool(multi_geometry)
        # The per-lane integrity probe (poisson_tpu.integrity): decided
        # at table construction like multi_geometry — an occupied
        # program's operand signature can never change, so a service
        # turning defensive verification on (suspect-cohort taint)
        # applies it to the NEXT table, never retrofits a running one.
        self.verify_every = int(verify_every)
        # The preconditioner is program identity too (the :mg cohort
        # marker means a table is only ever offered same-preconditioner
        # entries; carried here so the lane programs match the cohort).
        self.preconditioner = preconditioner or "jacobi"
        self.batch = LaneBatch(
            problem, bucket, dtype=dtype, chunk=chunk,
            multi_geometry=multi_geometry,
            verify_every=verify_every, verify_tol=verify_tol,
            preconditioner=self.preconditioner,
            device=device,
            # Chunk-boundary hook (solvers.lanes): each boundary is a
            # timeline event, so a wedged lane program's last boundary
            # is on disk for forensics — attributed to the worker that
            # owns the program (serve.fleet). Host-side only — flag-off
            # lane programs are byte-identical.
            on_boundary=lambda acc: obs.event(
                "serve.refill.chunk_boundary", cohort=cohort,
                worker=worker_id, **acc),
        )
        self.entries: List[Optional[object]] = [None] * self.batch.bucket
        self.dtype_name = self.batch.dtype_name
        # Per-lane iteration high-water marks: advance_marks() turns two
        # consecutive boundaries into per-member iteration deltas — the
        # flight recorder's compute-apportionment input.
        self._k_mark: List[int] = [0] * self.batch.bucket

    @property
    def bucket(self) -> int:
        return self.batch.bucket

    def occupied(self) -> bool:
        return any(e is not None for e in self.entries)

    def free_lane_count(self) -> int:
        return sum(1 for e in self.entries if e is None)

    def occupants(self) -> List[object]:
        return [e for e in self.entries if e is not None]

    def occupant_ids(self) -> Set:
        return {e.request.request_id for e in self.entries
                if e is not None}

    def occupant_taints(self) -> Set:
        taints: Set = set()
        for e in self.entries:
            if e is not None:
                taints |= e.taint
        return taints

    def occupant_fps(self) -> Set:
        from poisson_tpu.geometry.dsl import fingerprint_of

        return {fingerprint_of(e.request.geometry)
                for e in self.entries
                if e is not None and e.request.geometry is not None}

    def occupant_fp_taints(self) -> Set:
        taints: Set = set()
        for e in self.entries:
            if e is not None:
                taints |= e.taint_fp
        return taints

    def taint_compatible(self, entry) -> bool:
        """True iff ``entry`` may share lanes with the current occupants:
        none of them is on its never-co-batch list and it is on none of
        theirs — the taint-pair exclusion that must hold *across a
        splice*, not just at batch formation. Keys on (request,
        fingerprint): the request-id pairs AND the geometry-fingerprint
        pairs are both checked, so a bad geometry cannot rejoin its
        batchmates under a fresh request id either."""
        from poisson_tpu.geometry.dsl import fingerprint_of

        ids = self.occupant_ids()
        if (entry.taint & ids) or (
                entry.request.request_id in self.occupant_taints()):
            return False
        if entry.request.geometry is not None and \
                fingerprint_of(entry.request.geometry) in \
                self.occupant_fp_taints():
            return False
        return not (entry.taint_fp & self.occupant_fps())

    def splice(self, entry, rhs_gate: float = 1.0) -> int:
        """EMPTY → ACTIVE for ``entry``; returns the lane. On a
        multi-geometry table the entry's canvases splice in with its
        state (``solvers.lanes``) — same executable, new domain."""
        lane = self.batch.splice(
            entry.request.request_id, rhs_gate,
            geometry=(entry.request.geometry if self.multi_geometry
                      else None))
        self.entries[lane] = entry
        self._k_mark[lane] = 0      # a spliced member starts at k = 0
        obs.inc("serve.refill.splices")
        obs.event("serve.refill.splice", cohort=self.cohort, lane=lane,
                  request_id=str(entry.request.request_id),
                  occupancy=len(self.occupants()),
                  worker=self.worker_id)
        return lane

    def step(self) -> dict:
        """One chunk over every ACTIVE lane (EMPTY lanes ride as frozen
        width — counted as idle)."""
        accounting = self.batch.step()
        obs.inc("serve.refill.idle_lane_steps", accounting["idle"])
        obs.gauge("serve.refill.active_lanes", accounting["active"])
        return accounting

    def lane_view(self) -> List[dict]:
        """Per-lane host truth (``solvers.lanes.LaneBatch.lane_view``)
        with the lifecycle state attached."""
        views = self.batch.lane_view()
        for v in views:
            v["state"] = (LANE_EMPTY if v["member_id"] is None
                          else LANE_ACTIVE)
        return views

    def advance_marks(self, views: List[dict]) -> dict:
        """Iteration deltas since the previous boundary, per occupied
        lane (``{lane: dk}``), advancing the marks — what one chunk
        step actually bought each member, the flight recorder's
        compute-apportionment input (``obs.costs.apportion_compute``)."""
        deltas = {}
        for v in views:
            lane = v["lane"]
            if self.entries[lane] is None:
                continue
            deltas[lane] = max(0, v["k"] - self._k_mark[lane])
            self._k_mark[lane] = v["k"]
        return deltas

    def retire(self, lane: int) -> Tuple[object, LaneResult]:
        """ACTIVE → RETIRING → EMPTY: pull the lane's entry and its
        attributed solver result; the slot is EMPTY on return."""
        entry = self.entries[lane]
        if entry is None:
            raise ValueError(f"lane {lane} is EMPTY")
        result = self.batch.retire(lane)
        assert result.member_id == entry.request.request_id, (
            "lane identity drifted: lane result for "
            f"{result.member_id!r} but entry is "
            f"{entry.request.request_id!r}"
        )
        self.entries[lane] = None
        obs.inc("serve.refill.retired_lanes")
        obs.event("serve.refill.retire", cohort=self.cohort, lane=lane,
                  request_id=str(entry.request.request_id),
                  iterations=result.iterations, flag=result.flag_name,
                  worker=self.worker_id)
        return entry, result

    def evict_all(self) -> List[object]:
        """A dispatch-level fault killed the device program: clear every
        lane WITHOUT producing results (the members' in-flight progress
        died with the program) and hand the entries back — each one is
        owed a retry or a typed error by the caller."""
        evicted = []
        for lane, entry in enumerate(self.entries):
            if entry is None:
                continue
            self.batch.retire(lane)    # discard the poisoned iterate
            self.entries[lane] = None
            evicted.append(entry)
        return evicted
