"""Durable solver sessions: crash-safe ordered streams of dependent
solves admitted through the service.

A *session* is the workload ROADMAP item 5 names — transient
implicit-Euler time stepping and server-driven shape optimization are
sequences of requests against slowly-varying canvases
(Glowinski/Pan/Périaux's *possibly moving* domains, PAPERS.md). Each
step is an ordinary :class:`serve.types.SolveRequest` carrying session
identity (``session_id``/``session_step``) plus the session-only fields
(``mass_shift``, ``warm_start``/``warm_geometry``, ``on_solution``),
dispatched solo through :meth:`SolveService._dispatch_session` into the
fused session programs (``solvers.session``). The host in this module
owns everything *between* the steps:

- **Durability.** Every stream transition is journaled
  (``serve.journal`` ``session_*`` records): open (identity, kind,
  schedule params, problem dims, flight trace id), step submission
  (with warm-start PROVENANCE — the source step index, never the
  iterate), advance (the committed step boundary + the geometry it
  solved), close/shed. A killed process replays
  (:func:`serve.journal.replay_sessions` + :meth:`SessionHost.recover`)
  back to the exact step boundary: steps with a typed outcome are never
  re-run (the service's dedup guard holds across the crash), the
  mid-step request is re-enqueued COLD by the service's own recovery,
  and the stream continues from ``last_advanced + 1`` with no warm
  iterate — unreplayed device state is not evidence (the PR 14
  deflation-cache precedent). The ledger invariant
  ``admitted − (completed + errors + shed) == 0`` closes across the
  kill for the steps AND for the session root itself.

- **Ledger citizenship.** A session root is admitted like a request:
  ``open`` counts ``serve.admitted`` and roots one flight trace
  spanning the whole stream (``adopt()``-continued across crashes, span
  ids offset per generation); ``close`` counts ``serve.completed`` with
  one typed ``session`` outcome leaf; a shed open counts ``serve.shed``.
  One causal tree per stream, validated by the same
  ``flight.validate_trace`` contract as per-request traffic.

- **Warm-start handoff.** The previous step's converged iterate comes
  back through the request's ``on_solution`` hook (process memory) and
  rides the next step's ``warm_start``; the validity gate and its
  audible fallback live in the solver layer
  (``solvers.session.session_step_solve``).

- **The session rung of the degradation ladder.** A NEW session open
  sheds (``serve.session.shed_opens``) once queue depth crosses
  ``SessionPolicy.shed_open_at`` (default 0.75 of capacity) or
  ``max_sessions`` streams are already open — steps of in-flight
  sessions keep dispatching until the queue is actually full, because a
  half-finished stream is sunk cost.

- **Per-session SLO.** Scored at close on the session's own wall
  (``slo_seconds``, crash gap included via the adopted admit time):
  ``session.slo.good``/``session.slo.bad``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np

from poisson_tpu import obs
from poisson_tpu.config import Problem
from poisson_tpu.geometry.dsl import Ellipse, parse_geometry
from poisson_tpu.obs.flight import (
    POINT_RECOVERED,
    POINT_SESSION_STEP,
    SPAN_RESIDENT,
)
from poisson_tpu.serve.journal import replay_sessions
from poisson_tpu.serve.types import (
    OUTCOME_RESULT,
    OUTCOME_SHED,
    Outcome,
    SolveRequest,
)

SESSION_KINDS = ("poisson", "heat", "design")

# The problem fields a session_open record persists (recovery rebuilds
# the Problem from them — the same contract as the journal's submit
# records).
_PROBLEM_FIELDS = ("M", "N", "x_min", "x_max", "y_min", "y_max", "f_val",
                   "delta", "max_iter", "weighted_norm")

_UNSET = object()


@dataclasses.dataclass
class SolveSession:
    """One open stream's host-side state. The *durable* subset (identity,
    kind, schedule params, committed boundary, geometry) is journaled;
    the warm iterate and design target are process memory only."""

    session_id: str
    problem: Problem
    kind: str = "poisson"
    dtype: Optional[str] = None
    mass_shift: float = 0.0
    geometry: object = None          # the current step's geometry spec
    trace_id: str = ""
    t_open: float = 0.0
    next_step: int = 0
    advanced: int = -1               # committed step boundary
    errors: int = 0                  # typed error/shed step outcomes
    generation: int = 1              # 1 + prior crash recoveries
    closed: bool = False
    recovered: bool = False
    params: dict = dataclasses.field(default_factory=dict)  # journaled
    design_params: Optional[dict] = None   # kind="design": cx/cy/rx/ry
    warm: Optional[np.ndarray] = None      # last converged iterate
    warm_geometry: object = None           # the spec that iterate solved
    warm_from: int = -1                    # its source step (journaled)

    @property
    def steps(self) -> int:
        return self.next_step


class SessionHost:
    """The session lifecycle layer over one :class:`SolveService`.

    Single-threaded like the service itself; uses the service's own
    clock, journal, and flight recorder so session records interleave
    with the per-request ones in one log and one trace dir."""

    def __init__(self, service):
        self._svc = service
        self._sessions: Dict[str, SolveSession] = {}

    @property
    def policy(self):
        return self._svc.policy.session

    def open_sessions(self) -> List[str]:
        return sorted(self._sessions)

    def _journal(self, kind: str, **fields) -> None:
        if self._svc._journal is not None:
            self._svc._journal.record(kind, **fields)

    # -- admission -----------------------------------------------------

    def open(self, session_id: str, problem: Problem, *,
             kind: str = "poisson", geometry=None, dtype=None,
             mass_shift: float = 0.0, design_params: Optional[dict] = None,
             params: Optional[dict] = None) -> Optional[SolveSession]:
        """Admit a new stream. Returns its :class:`SolveSession` handle,
        or ``None`` when the open was shed (the session rung: audible,
        journaled, one typed ``shed`` outcome on its own flight trace —
        the ledger counts it like any shed admission)."""
        sid = str(session_id)
        if kind not in SESSION_KINDS:
            raise ValueError(f"unknown session kind {kind!r} "
                             f"(one of {SESSION_KINDS})")
        if kind == "heat" and not mass_shift > 0.0:
            raise ValueError("heat sessions need mass_shift = 1/dt > 0")
        if kind == "design":
            if not isinstance(geometry, Ellipse) and design_params is None:
                raise ValueError("design sessions optimize ellipse "
                                 "parameters — open with an Ellipse "
                                 "geometry or design_params")
            if design_params is None:
                design_params = {"cx": float(geometry.cx),
                                 "cy": float(geometry.cy),
                                 "rx": float(geometry.rx),
                                 "ry": float(geometry.ry)}
        if sid in self._sessions:
            raise ValueError(f"session {sid!r} is already open — "
                             "stream identity must be unique")
        svc = self._svc
        # The session root is a ledger citizen: admitted here, one typed
        # outcome at close/shed. (The service's internal request ledger
        # is untouched — sessions are not queue entries.)
        obs.inc("serve.admitted")
        trace_id = svc._flight.admit(sid)
        depth = len(svc._queue) + len(svc._delayed)
        frac = depth / svc.policy.capacity
        open_count = len(self._sessions)
        shed_reason = ""
        if open_count >= self.policy.max_sessions:
            shed_reason = "max_sessions"
        elif frac >= self.policy.shed_open_at:
            # The degradation ladder's session rung: new streams shed
            # well before the queue is full, so steps of in-flight
            # sessions (sunk cost) keep their headroom.
            shed_reason = "overload"
        if shed_reason:
            obs.inc("serve.shed")
            obs.inc("serve.session.shed_opens")
            obs.event("session.shed_open", session_id=sid,
                      reason=shed_reason, open_sessions=open_count,
                      queue_fraction=round(frac, 4))
            self._journal("session_shed", session_id=sid,
                          reason=shed_reason)
            svc._flight.outcome(sid, OUTCOME_SHED, shed_reason)
            return None
        obs.inc("session.opens")
        record_params = dict(params or {})
        record_params["dtype"] = dtype
        record_params["mass_shift"] = float(mass_shift)
        record_params["problem"] = {k: getattr(problem, k)
                                    for k in _PROBLEM_FIELDS}
        if design_params is not None:
            record_params["design"] = dict(design_params)
        self._journal(
            "session_open", session_id=sid, session_kind=kind,
            trace_id=trace_id, params=record_params,
            geometry=(geometry.to_json() if geometry is not None
                      else None))
        t_open = svc._clock()
        svc._flight.begin(sid, SPAN_RESIDENT, mode="session", kind=kind)
        sess = SolveSession(
            session_id=sid, problem=problem, kind=kind, dtype=dtype,
            mass_shift=float(mass_shift), geometry=geometry,
            trace_id=trace_id, t_open=t_open, params=record_params,
            design_params=design_params)
        self._sessions[sid] = sess
        return sess

    # -- stepping ------------------------------------------------------

    def step(self, sess: SolveSession, geometry=_UNSET,
             rhs_gate: Optional[float] = None) -> Outcome:
        """Submit and drive the stream's next step to its typed outcome.

        ``geometry`` moves the domain for this step (omitted = the
        session's current spec). The step is journaled before admission
        (with warm provenance), admitted through ``service.submit`` —
        which dedups it against a pre-crash outcome, so a replayed step
        is never executed twice — and advanced in the journal once its
        outcome exists. The converged iterate comes back through the
        request's ``on_solution`` hook and becomes the next step's warm
        start."""
        if sess.closed:
            raise ValueError(f"session {sess.session_id!r} is closed")
        svc = self._svc
        k = sess.next_step
        sid = sess.session_id
        rid = f"{sid}#{k:04d}"
        geo = sess.geometry if geometry is _UNSET else geometry
        sess.geometry = geo
        warm_from = sess.warm_from if sess.warm is not None else -1
        self._journal("session_step", session_id=sid, step=k,
                      request_id=rid, warm_from=warm_from)
        holder: dict = {}
        req = SolveRequest(
            request_id=rid, problem=sess.problem, dtype=sess.dtype,
            geometry=geo,
            rhs_gate=1.0 if rhs_gate is None else float(rhs_gate),
            session_id=sid, session_step=k,
            mass_shift=sess.mass_shift,
            warm_start=sess.warm, warm_geometry=sess.warm_geometry,
            on_solution=lambda w: holder.__setitem__("w", w),
            deadline_seconds=self.policy.step_deadline_seconds,
        )
        out = None
        if rid in svc._pending_ids:
            # The service's own journal recovery already re-enqueued
            # this step (COLD — warm fields never replay): drive it to
            # its outcome instead of re-admitting it.
            for o in svc.drain():
                if str(o.request_id) == rid:
                    out = o
        elif rid in svc._prior_outcomes:
            # Typed before the crash but not yet advanced in the
            # session records: fold the journal's outcome truth in —
            # never execute the step twice.
            out = svc._prior_outcomes[rid]
        else:
            out = svc.submit(req)
            if out is None:
                for o in svc.drain():
                    if str(o.request_id) == rid:
                        out = o
        if out is None:       # the service broke its own ledger contract
            raise RuntimeError(f"session step {rid} has no outcome")
        sess.next_step = k + 1
        ok = out.kind == OUTCOME_RESULT
        if not ok:
            sess.errors += 1
        svc._flight.point(sid, POINT_SESSION_STEP, step=k,
                          outcome=out.kind,
                          iterations=int(out.iterations),
                          warm_from=warm_from)
        # The committed boundary: this step has its one typed outcome —
        # a recovery must continue AFTER it, never re-run it.
        self._journal("session_advance", session_id=sid, step=k,
                      outcome=out.kind,
                      geometry=(geo.to_json() if geo is not None
                                else None))
        sess.advanced = k
        if ok and "w" in holder:
            sess.warm = holder["w"]
            sess.warm_geometry = geo
            sess.warm_from = k
        return out

    def design_step(self, sess: SolveSession, target, lr: float):
        """One server-driven shape-optimization step: differentiate the
        mismatch against ``target`` at the current ellipse parameters
        (``solvers.session.design_step`` — one forward + one adjoint
        solve), descend, then admit the solve at the MOVED ellipse as
        the session's next step (warm-started from the previous iterate
        when the move is within the drift bound). Returns
        ``(outcome, loss, grads)``; the moved parameters are journaled
        with the step's advance record, so recovery resumes the descent
        from the committed ellipse."""
        from poisson_tpu.solvers.session import design_step

        if sess.kind != "design":
            raise ValueError(f"session {sess.session_id!r} is "
                             f"kind={sess.kind!r}, not a design stream")
        new_params, loss, grads = design_step(
            sess.problem, sess.design_params, target, lr,
            dtype=sess.dtype)
        sess.design_params = new_params
        geo = Ellipse(cx=new_params["cx"], cy=new_params["cy"],
                      rx=new_params["rx"], ry=new_params["ry"])
        out = self.step(sess, geometry=geo)
        return out, loss, grads

    # -- termination ---------------------------------------------------

    def close(self, sess: SolveSession) -> dict:
        """Close the stream: one typed ``session`` outcome on its flight
        trace (spans folded, decomposition summing to the stream's
        wall), the per-session SLO scored, the journal's terminal
        record written, and the session root completed in the ledger."""
        if sess.closed:
            raise ValueError(f"session {sess.session_id!r} is closed")
        sess.closed = True
        sid = sess.session_id
        self._sessions.pop(sid, None)
        svc = self._svc
        wall = max(0.0, svc._clock() - sess.t_open)
        good = sess.errors == 0 and wall <= self.policy.slo_seconds
        obs.inc("session.slo.good" if good else "session.slo.bad")
        obs.inc("session.closes")
        self._journal("session_close", session_id=sid,
                      steps=sess.next_step, errors=sess.errors,
                      slo_good=good)
        obs.inc("serve.completed")
        fo = svc._flight.outcome(sid, OUTCOME_RESULT, "session",
                                 attempts=max(1, sess.next_step))
        obs.event("session.closed", session_id=sid,
                  steps=sess.next_step, errors=sess.errors,
                  wall_s=round(wall, 4), slo_good=good,
                  generation=sess.generation)
        return {"session_id": sid, "steps": sess.next_step,
                "errors": sess.errors, "wall_s": wall,
                "slo_good": good, "trace_id": fo["trace_id"],
                "decomposition": fo["decomposition"]}

    # -- crash recovery ------------------------------------------------

    def recover(self) -> List[SolveSession]:
        """Re-open every stream the journal shows open, at its exact
        committed boundary. Call on a service built by
        ``SolveService.recover`` (the per-request half: prior outcomes
        deduped, the mid-step request re-enqueued cold). Each recovered
        stream adopts its original flight trace (span ids offset one
        generation past the dead process's), re-journals its open (so a
        second crash recovers with the generation bumped again), and
        continues from ``last_advanced + 1`` with NO warm iterate —
        device state died with the process, and replaying it is not
        recovery, it is guessing."""
        svc = self._svc
        if svc._journal is None:
            return []
        reps = replay_sessions(svc._journal.path)
        now = svc._clock()
        recovered: List[SolveSession] = []
        for sid, rep in sorted(reps.items()):
            if not rep.open or sid in self._sessions:
                continue
            params = dict(rep.params)
            try:
                problem = Problem(**params["problem"])
            except (KeyError, TypeError, ValueError) as e:
                obs.inc("session.recovery_errors")
                obs.event("session.recovery_error", session_id=sid,
                          error=f"problem unreconstructable: {e}")
                continue
            geo = None
            if rep.advanced_geometry:
                try:
                    geo = parse_geometry(rep.advanced_geometry)
                except (KeyError, TypeError, ValueError):
                    obs.inc("session.recovery_errors")
                    geo = None
            obs.inc("session.recovered")
            t_open = rep.t_open if 0.0 <= rep.t_open <= now else now
            if rep.trace_id:
                svc._flight.adopt(sid, rep.trace_id, t_open,
                                  span_base=1000 * rep.generations)
                trace_id = rep.trace_id
            else:
                trace_id = svc._flight.admit(sid)
            svc._flight.point(sid, POINT_RECOVERED,
                              reason="journal_replay",
                              generation=rep.generations,
                              boundary=rep.last_advanced)
            svc._flight.begin(sid, SPAN_RESIDENT, mode="session",
                              kind=rep.kind, recovered=True)
            self._journal("session_open", session_id=sid,
                          session_kind=rep.kind, trace_id=trace_id,
                          params=params, recovered=True)
            design = params.get("design")
            if design is not None and isinstance(geo, Ellipse):
                # The committed ellipse IS the descent state: resume
                # the optimization from the last advanced step's
                # parameters, not the opening ones.
                design = {"cx": float(geo.cx), "cy": float(geo.cy),
                          "rx": float(geo.rx), "ry": float(geo.ry)}
            sess = SolveSession(
                session_id=sid, problem=problem, kind=rep.kind,
                dtype=params.get("dtype"),
                mass_shift=float(params.get("mass_shift", 0.0)),
                geometry=geo, trace_id=trace_id, t_open=t_open,
                next_step=rep.last_advanced + 1,
                advanced=rep.last_advanced,
                generation=rep.generations + 1,
                recovered=True, params=params,
                design_params=design)
            self._sessions[sid] = sess
            recovered.append(sess)
        return recovered
