"""Crash-safe request journal: a CRC-sealed, append-only write-ahead log
of every request lifecycle transition.

The service's ledger invariant — every admitted request terminates with
exactly one typed outcome — lived, until this module, only in process
memory: a crash (preemption, OOM, a wedged device taking the host down)
silently voided it for every request that was queued or lane-resident at
the moment of death. The journal is the durable half of the invariant:

- **append-only JSONL**, one record per transition (``submit``,
  ``dispatch``/``dispatch_end``, ``splice``/``retire``, ``requeue``,
  ``recover``, ``outcome``), each line sealed with a CRC32 over its
  canonical payload (the same zlib.crc32 sealing idiom as
  ``solvers.checkpoint``) and flushed before the transition is
  considered taken — a submit that was acknowledged is on disk;

- **replay** (:func:`replay_journal`) folds the log back into ledger
  truth: which requests got their one typed outcome, which were still
  queued or in flight when the log stops, and with how many dispatch
  attempts. Requests co-resident in an open dispatch at the crash are
  returned mutually tainted — the crash may have been one of them;

- **torn tails are tolerated audibly**, like ``obs.trace``'s
  ``merge_trace_dir``: a truncated final line (the crash landed
  mid-write) or a CRC-failing record is skipped, counted
  (``serve.journal.torn_records``), and reported in the replay — never
  silently trusted, never fatal. A torn *submit* means the client was
  never acknowledged, so dropping it is correct; a torn mid-file record
  degrades attempt/taint detail, never outcome truth, because outcomes
  are whole lines too.

``SolveService.recover`` re-enqueues every replayed pending request with
a ``recovered`` taint/backoff path and counts it as ``serve.recovered``
(NOT as a fresh admission — the original process already counted the
admission, so merged ``serve.*`` snapshots close the invariant across
the crash boundary: admitted − (completed + errors + shed) == 0).
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
import zlib
from typing import Callable, Dict, List, Optional, Set

from poisson_tpu import obs
from poisson_tpu.config import Problem
from poisson_tpu.serve.types import SolveRequest

SCHEMA = "poisson_tpu.serve.journal/1"

# The request fields a submit record persists (everything a recovery
# needs to rebuild the SolveRequest; ``on_chunk``/``on_solution`` hooks
# are process handles and deliberately do not survive — recovery notes
# their loss). Session identity (session_id/session_step/mass_shift)
# replays so a recovered step re-enters the SAME stream; the warm-start
# iterate (``warm_start``/``warm_geometry``) deliberately does NOT —
# mid-step work is re-enqueued COLD, never resumed from unreplayed
# device state.
_REQUEST_FIELDS = ("rhs_gate", "dtype", "deadline_seconds", "chunk",
                   "max_attempts", "device_id", "session_id",
                   "session_step", "mass_shift")
_PROBLEM_FIELDS = ("M", "N", "x_min", "x_max", "y_min", "y_max", "f_val",
                   "delta", "max_iter", "weighted_norm")


def _seal(payload: dict) -> int:
    """CRC32 over the canonical (sorted-key) JSON of ``payload``."""
    blob = json.dumps(payload, sort_keys=True, default=str)
    return zlib.crc32(blob.encode()) & 0xFFFFFFFF


class SolveJournal:
    """Append-only journal bound to one file. Single-writer by design —
    the service's dispatch loop is the only caller, exactly like the
    breaker registry. ``clock`` is the service clock (injectable, so
    chaos replays are deterministic); ``fsync`` forces each record to
    the device (the flush-only default survives process death, which is
    the failure mode the chaos drills exercise; fsync additionally
    survives kernel/power loss at a per-record cost)."""

    def __init__(self, path: str, *,
                 clock: Callable[[], float] = time.monotonic,
                 fsync: bool = False):
        self.path = path
        self._clock = clock
        self._fsync = fsync
        self._seq = 0
        directory = os.path.dirname(os.path.abspath(path))
        os.makedirs(directory, exist_ok=True)
        # Append mode: a recovery process continues the same file, so
        # the journal carries the whole multi-process history of the
        # ledger (replay_journal reads it end to end).
        self._fh = open(path, "a")

    def record(self, kind: str, **fields) -> None:
        """Seal and append one transition. Best-effort on OSError after
        open succeeds: a failing journal disk must not take the service
        down mid-dispatch (the in-memory ledger still holds; durability
        is degraded, audibly)."""
        self._seq += 1
        payload = {"seq": self._seq, "kind": kind,
                   "t": round(self._clock(), 6), **fields}
        payload["crc32"] = _seal(payload)
        try:
            self._fh.write(json.dumps(payload, sort_keys=True,
                                      default=str) + "\n")
            self._fh.flush()
            if self._fsync:
                os.fsync(self._fh.fileno())
        except (OSError, ValueError):
            obs.inc("serve.journal.write_errors")
            return
        obs.inc("serve.journal.records")

    def submit(self, request: SolveRequest, trace_id: str) -> None:
        req = {k: getattr(request, k) for k in _REQUEST_FIELDS}
        if request.tenant is not None:
            # Tenant identity rides the journal (only when set, so
            # tenancy-off journals stay byte-identical): a recovery
            # must rebuild each tenant's fair share and re-charge its
            # retry budget — a poisoned tenant cannot launder its
            # amplification cap through a process crash.
            req["tenant"] = request.tenant
        if request.geometry is not None:
            # The spec's canonical JSON reconstructs the geometry on
            # replay (raw-SDF specs serialize name-only and replay as
            # unreconstructable — audibly torn, never silently solved
            # as the wrong domain).
            req["geometry"] = request.geometry.to_json()
        if request.krylov is not None:
            # The request-level Krylov knobs replay too: a recovered
            # block/deflation request must re-dispatch through the SAME
            # cohort and program family it was admitted into (the basis
            # itself is never journaled — device state rebuilds,
            # poisson_tpu.krylov.recycle).
            import dataclasses as _dc

            req["krylov"] = _dc.asdict(request.krylov)
        self.record(
            "submit", request_id=str(request.request_id),
            trace_id=trace_id,
            problem={k: getattr(request.problem, k)
                     for k in _PROBLEM_FIELDS},
            request=req,
            has_hook=request.on_chunk is not None,
        )

    def close(self) -> None:
        try:
            self._fh.close()
        except OSError:
            pass


# Session lifecycle record kinds (poisson_tpu.serve.session): every
# step transition of a durable session is journaled so ``--recover``
# replays a killed process back to the exact step boundary.
#
# - ``session_open``  — the stream was admitted: identity, kind
#   (poisson|heat|design), base geometry JSON, schedule parameters, and
#   the session's flight trace id (adopt()-continued across crashes).
# - ``session_step``  — step k was submitted, with its request id and
#   warm-start PROVENANCE (``warm_from``: the step index the warm
#   iterate came from, or -1 for a cold step) — never the iterate.
# - ``session_advance`` — step k reached its typed outcome; the stream's
#   committed boundary moves to k.
# - ``session_close`` / ``session_shed`` — the stream's one terminal
#   record (a shed open is terminal too).
SESSION_RECORD_KINDS = ("session_open", "session_step",
                        "session_advance", "session_close",
                        "session_shed")


@dataclasses.dataclass
class SessionReplay:
    """One session's journal truth (:func:`replay_sessions`)."""

    session_id: str = ""
    kind: str = "poisson"
    trace_id: str = ""
    t_open: float = 0.0
    params: dict = dataclasses.field(default_factory=dict)
    steps_submitted: int = 0          # highest step with a session_step
    last_advanced: int = -1           # highest step with session_advance
    advanced_geometry: Optional[str] = None  # geometry JSON at that step
    closed: bool = False
    shed: bool = False
    generations: int = 1              # 1 + prior session recoveries

    @property
    def open(self) -> bool:
        return not (self.closed or self.shed)


def replay_sessions(path: str) -> Dict[str, SessionReplay]:
    """Fold the journal's ``session_*`` records into per-session truth:
    which streams are still open, the exact step boundary each one
    committed to (``last_advanced``), and the schedule parameters a
    recovery needs to continue the stream. Torn records are skipped
    like :func:`replay_journal` (the per-request ledger half already
    counts them)."""
    sessions: Dict[str, SessionReplay] = {}
    scratch = JournalReplay()
    try:
        with open(path) as fh:
            lines = fh.readlines()
    except OSError:
        return sessions
    for lineno, line in enumerate(lines, start=1):
        rec = _parse_line(line, lineno, scratch)
        if rec is None or rec.get("kind") not in SESSION_RECORD_KINDS:
            continue
        sid = str(rec.get("session_id", ""))
        kind = rec["kind"]
        if kind == "session_open":
            prior = sessions.get(sid)
            srep = SessionReplay(
                session_id=sid,
                kind=str(rec.get("session_kind", "poisson")),
                trace_id=str(rec.get("trace_id", "")),
                t_open=float(rec.get("t", 0.0)),
                params=dict(rec.get("params") or {}),
            )
            if prior is not None:
                # A recovery re-opened the stream: keep the committed
                # boundary, bump the generation (flight span offsets).
                srep.steps_submitted = prior.steps_submitted
                srep.last_advanced = prior.last_advanced
                srep.advanced_geometry = prior.advanced_geometry
                srep.generations = prior.generations + 1
                srep.trace_id = srep.trace_id or prior.trace_id
            sessions[sid] = srep
            continue
        srep = sessions.get(sid)
        if srep is None:
            continue
        if kind == "session_step":
            srep.steps_submitted = max(srep.steps_submitted,
                                       int(rec.get("step", 0)))
        elif kind == "session_advance":
            step = int(rec.get("step", 0))
            if step > srep.last_advanced:
                srep.last_advanced = step
                srep.advanced_geometry = rec.get("geometry")
        elif kind == "session_close":
            srep.closed = True
        elif kind == "session_shed":
            srep.shed = True
    return sessions


@dataclasses.dataclass
class PendingRequest:
    """One request the journal shows as admitted but not terminated —
    what a recovery re-enqueues."""

    request: SolveRequest
    trace_id: str
    t_submit: float
    attempts: int = 0
    in_flight: bool = False      # mid-dispatch / lane-resident at crash
    taint: Set[str] = dataclasses.field(default_factory=set)
    # Geometry-fingerprint taint (requeue-recorded): never-co-batch
    # families survive the crash like the request-id pairs do.
    taint_fp: Set[str] = dataclasses.field(default_factory=set)
    generation: int = 1          # 1 + prior recover records for this id
    lost_hook: bool = False      # an on_chunk hook did not survive
    # Placement at the crash (serve.placement): the fault-domain slot
    # the last dispatch/splice put this request on, and the placement
    # epoch it was recorded under — what lets a recovery on a DIFFERENT
    # topology see that the device is gone and remap audibly.
    device_id: Optional[int] = None
    epoch: int = 0


@dataclasses.dataclass
class JournalReplay:
    """What :func:`replay_journal` reconstructed."""

    records: int = 0
    torn_records: int = 0
    torn_detail: List[str] = dataclasses.field(default_factory=list)
    outcomes: Dict[str, str] = dataclasses.field(default_factory=dict)
    duplicate_outcomes: List[str] = dataclasses.field(default_factory=list)
    pending: List[PendingRequest] = dataclasses.field(default_factory=list)
    submitted: int = 0
    # The last topology record in the log (the crashed incarnation's
    # device view) — recovery compares it against its own registry.
    topology: Optional[dict] = None

    @property
    def lost(self) -> int:
        """Requests neither terminated nor recoverable — must be 0 for a
        readable journal (pending covers the difference by construction;
        anything else means torn submit records, which were never
        acknowledged and are not ledger debt)."""
        return self.submitted - len(self.outcomes) - len(self.pending)


def _parse_line(line: str, lineno: int, replay: JournalReplay
                ) -> Optional[dict]:
    line = line.strip()
    if not line:
        return None
    try:
        rec = json.loads(line)
    except ValueError:
        replay.torn_records += 1
        replay.torn_detail.append(f"line {lineno}: unparseable (torn tail)")
        return None
    if not isinstance(rec, dict):
        replay.torn_records += 1
        replay.torn_detail.append(f"line {lineno}: not an object")
        return None
    stored = rec.pop("crc32", None)
    if stored is None or _seal(rec) != stored:
        replay.torn_records += 1
        replay.torn_detail.append(
            f"line {lineno}: CRC mismatch "
            f"(stored {stored}, kind {rec.get('kind')!r})")
        return None
    return rec


def replay_journal(path: str) -> JournalReplay:
    """Fold the journal back into ledger truth. Torn/corrupt records are
    skipped audibly (``serve.journal.torn_records`` + the replay's
    ``torn_detail``); everything readable is folded in order."""
    replay = JournalReplay()
    submits: Dict[str, dict] = {}
    attempts: Dict[str, int] = {}
    open_dispatch: Dict[str, Set[str]] = {}   # request_id -> co-ids
    open_lanes: Dict[object, Set[str]] = {}   # worker -> resident ids
    taints: Dict[str, Set[str]] = {}          # requeue-recorded taint
    fp_taints: Dict[str, Set[str]] = {}       # geometry-fingerprint taint
    generations: Dict[str, int] = {}
    last_place: Dict[str, tuple] = {}         # rid -> (device, epoch)

    def _close(rid_: str) -> None:
        open_dispatch.pop(rid_, None)
        for resident in open_lanes.values():
            resident.discard(rid_)
    try:
        with open(path) as fh:
            lines = fh.readlines()
    except OSError as e:
        replay.torn_detail.append(f"journal unreadable: {e}")
        obs.inc("serve.journal.torn_records")
        return replay
    for lineno, line in enumerate(lines, start=1):
        rec = _parse_line(line, lineno, replay)
        if rec is None:
            continue
        replay.records += 1
        kind = rec.get("kind")
        rid = str(rec.get("request_id", ""))
        if kind == "submit":
            submits[rid] = rec
        elif kind == "topology":
            replay.topology = {k: rec.get(k) for k in
                               ("devices", "alive", "lost", "epoch",
                                "kinds")}
        elif kind in ("dispatch", "splice"):
            ids = ([str(i) for i in rec.get("request_ids", [])]
                   if kind == "dispatch" else [rid])
            place = (rec.get("device"), int(rec.get("epoch", 0) or 0))
            for i in ids:
                # Attempts = dispatches this request has burned (the
                # one open at the crash included: it died with the
                # process, which is exactly what an attempt costs).
                attempts[i] = attempts.get(i, 0) + 1
                open_dispatch[i] = set(ids) - {i}
                # Last-known placement: where this work last ran — the
                # recovery's remap input on a changed topology.
                last_place[i] = place
            if kind == "splice":
                open_lanes.setdefault(rec.get("worker"), set()).add(rid)
        elif kind in ("dispatch_end", "retire", "requeue"):
            ids = ([str(i) for i in rec.get("request_ids", [rid])]
                   if "request_ids" in rec else [rid])
            for i in ids:
                _close(i)
            if kind == "requeue":
                # Mutual-taint pairs established before the crash must
                # survive the replay (never-co-batch-again is forever) —
                # the geometry-fingerprint pairs included.
                taints[rid] = (taints.get(rid, set())
                               | {str(t) for t in rec.get("taint", ())})
                fp_taints[rid] = (fp_taints.get(rid, set())
                                  | {str(t) for t in
                                     rec.get("taint_fp", ())})
        elif kind == "recover":
            generations[rid] = generations.get(rid, 0) + 1
            _close(rid)
        elif kind == "outcome":
            if rid in replay.outcomes:
                replay.duplicate_outcomes.append(rid)
            replay.outcomes[rid] = str(rec.get("outcome", ""))
            _close(rid)
    # Lane co-residency at the crash is mutual taint too: everything
    # still resident on one worker shared the program that died.
    for resident in open_lanes.values():
        for rid in resident:
            open_dispatch[rid] = (open_dispatch.get(rid, set())
                                  | resident) - {rid}
    replay.submitted = len(submits)
    if replay.torn_records:
        obs.inc("serve.journal.torn_records", replay.torn_records)
        obs.event("serve.journal.torn_tail", path=path,
                  skipped=replay.torn_records,
                  detail="; ".join(replay.torn_detail[:5]))
    for rid, rec in submits.items():
        if rid in replay.outcomes:
            continue
        try:
            problem = Problem(**rec["problem"])
            req_fields = dict(rec.get("request") or {})
            geo_json = req_fields.pop("geometry", None)
            if geo_json:
                from poisson_tpu.geometry.dsl import parse_geometry

                # Raw-SDF specs raise here (a callable does not survive
                # JSON) and fall into the unreconstructable branch —
                # audible, never the wrong domain.
                req_fields["geometry"] = parse_geometry(geo_json)
            krylov_d = req_fields.pop("krylov", None)
            if krylov_d:
                from poisson_tpu.krylov import KrylovPolicy

                # Unknown keys (a future policy field) raise TypeError
                # into the unreconstructable branch — audible.
                req_fields["krylov"] = KrylovPolicy(**krylov_d)
            request = SolveRequest(request_id=rid, problem=problem,
                                   **req_fields)
        except (KeyError, TypeError, ValueError) as e:
            replay.torn_records += 1
            replay.torn_detail.append(
                f"submit {rid!r} unreconstructable: {e}")
            obs.inc("serve.journal.torn_records")
            continue
        device, epoch = last_place.get(rid, (None, 0))
        replay.pending.append(PendingRequest(
            request=request,
            trace_id=str(rec.get("trace_id", "")),
            t_submit=float(rec.get("t", 0.0)),
            attempts=attempts.get(rid, 0),
            in_flight=rid in open_dispatch,
            taint=(set(open_dispatch.get(rid, ()))
                   | taints.get(rid, set())),
            taint_fp=fp_taints.get(rid, set()),
            generation=generations.get(rid, 0) + 1,
            lost_hook=bool(rec.get("has_hook")),
            device_id=(int(device) if device is not None else None),
            epoch=epoch,
        ))
    obs.inc("serve.journal.replays")
    obs.event("serve.journal.replay", path=path,
              records=replay.records, outcomes=len(replay.outcomes),
              pending=len(replay.pending),
              torn=replay.torn_records)
    return replay
