"""The geometric V-cycle: transfers, smoothing, and the cycle itself.

Everything here is pure traced jnp over the ``ops.stencil`` array
convention — full grids (…, M+1, N+1) with an identically-zero Dirichlet
ring — and batch-polymorphic the same way the stencil library is:
ellipsis indexing everywhere, so one implementation serves the solo
solve, the leading-batch-axis stacks, and ``vmap``-ed per-member bodies
(the batched/lane drivers) unchanged.

The transfer pair is chosen for symmetry, not convenience: bilinear
prolongation P (coincident copy, ½ edges, ¼ centres) and full-weighting
restriction R (the 1/16·[1 2 1; 2 4 2; 1 2 1] stencil) satisfy
R = ¼·Pᵀ exactly, so the coarse-grid correction P·A_c⁻¹·R is symmetric
whenever A_c is — and weighted Jacobi is A-self-adjoint — making the
whole V-cycle an SPD operator that plain CG may precondition with
(Briggs/Henson/McCormick ch. 10, PAPERS.md).
"""

from __future__ import annotations

import jax.numpy as jnp

from poisson_tpu.mg.hierarchy import DEFAULT_MG, MGConfig, MGLevels
from poisson_tpu.ops.stencil import apply_A, pad_interior


def restrict_full_weighting(r):
    """Fine (…, M+1, N+1) → coarse (…, M/2+1, N/2+1) by the 9-point
    full-weighting stencil over interior coarse nodes (the ring stays
    zero). Coarse node (I, J) sits on fine node (2I, 2J); the stencil
    sums to 1, so the restricted residual keeps function-value
    semantics — the rediscretized coarse operator consumes it directly.
    """
    c = r[..., 2:-1:2, 2:-1:2]                 # (2I, 2J)
    up, dn = r[..., 1:-2:2, 2:-1:2], r[..., 3::2, 2:-1:2]
    lf, rt = r[..., 2:-1:2, 1:-2:2], r[..., 2:-1:2, 3::2]
    ul, ur = r[..., 1:-2:2, 1:-2:2], r[..., 1:-2:2, 3::2]
    dl, dr = r[..., 3::2, 1:-2:2], r[..., 3::2, 3::2]
    core = (4.0 * c + 2.0 * (up + dn + lf + rt)
            + (ul + ur + dl + dr)) / 16.0
    return pad_interior(core)


def prolong_bilinear(e):
    """Coarse (…, Mc+1, Nc+1) → fine (…, 2Mc+1, 2Nc+1) by bilinear
    interpolation: coincident fine nodes copy, edge midpoints average
    their 2 coarse neighbours, cell centres their 4 (as the tensor
    product of two 1D linear interpolations — an interleave by
    stack+reshape, which XLA lowers as cheap concatenation where the
    equivalent strided ``.at[].set`` scatter costs ~50× on CPU). The
    coarse ring is zero, so fine near-boundary nodes interpolate
    against the Dirichlet value — the result's ring is zero by
    construction."""
    mid_r = 0.5 * (e[..., :-1, :] + e[..., 1:, :])
    rows = jnp.stack([e[..., :-1, :], mid_r], axis=-2)
    rows = rows.reshape(e.shape[:-2]
                        + (2 * (e.shape[-2] - 1), e.shape[-1]))
    ex = jnp.concatenate([rows, e[..., -1:, :]], axis=-2)
    mid_c = 0.5 * (ex[..., :, :-1] + ex[..., :, 1:])
    cols = jnp.stack([ex[..., :, :-1], mid_c], axis=-1)
    cols = cols.reshape(ex.shape[:-1] + (2 * (ex.shape[-1] - 1),))
    return jnp.concatenate([cols, ex[..., :, -1:]], axis=-1)


def smooth_jacobi(x, rhs, a, b, dinv, h1: float, h2: float,
                  sweeps: int, omega: float, from_zero: bool = False):
    """``sweeps`` damped-Jacobi sweeps x ← x + ω·D⁻¹(rhs − Ax).

    ``dinv`` is the zero-ring-padded inverse diagonal, so the update is
    one fused elementwise expression and the ring stays untouched.
    ``from_zero`` starts from x = 0 and folds the first sweep into the
    cheap closed form ω·D⁻¹·rhs (no stencil application against a zero
    iterate). Unrolled: ``sweeps`` is a small static constant."""
    if from_zero:
        if sweeps <= 0:
            return jnp.zeros_like(rhs)
        x = omega * dinv * rhs
        sweeps -= 1
    for _ in range(sweeps):
        x = x + omega * dinv * (rhs - apply_A(x, a, b, h1, h2))
    return x


def coarse_solve(rhs, a, b, dinv, coarse_inv, h1: float, h2: float,
                 config: MGConfig):
    """The coarsest-level solve: the dense symmetrised inverse as one
    interior matvec when it was built (``coarse_dense_limit``), else
    ``coarse_sweeps`` smoother sweeps from zero. The matvec is
    deliberately a broadcast-multiply + trailing-axis reduce rather
    than a dot/einsum: XLA fuses it into one per-row accumulation loop
    whose order is the same in the solo program, under ``vmap`` (the
    batched/lane drivers), and inside any fusion context — a dot would
    dispatch to shape-dependent GEMV/GEMM kernels whose accumulation
    orders differ, and the bit-parity contract between the solo and
    batched MG solves (tests/test_mg.py) rests on this reduction."""
    if coarse_inv is None:
        return smooth_jacobi(None, rhs, a, b, dinv, h1, h2,
                             config.coarse_sweeps, config.omega,
                             from_zero=True)
    mc, nc = rhs.shape[-2] - 1, rhs.shape[-1] - 1
    flat = rhs[..., 1:-1, 1:-1].reshape(rhs.shape[:-2]
                                        + ((mc - 1) * (nc - 1),))
    e = jnp.sum(coarse_inv * flat[..., None, :], axis=-1)
    return pad_interior(e.reshape(rhs.shape[:-2] + (mc - 1, nc - 1)))


def v_cycle(hier: MGLevels, r, h1: float, h2: float,
            config: MGConfig = DEFAULT_MG):
    """One V(ν₁, ν₂) cycle applied to the residual ``r``: z ≈ A⁻¹r.

    Python recursion over the static level tuple — the cycle unrolls at
    trace time (≤ ~7 levels for every supported grid). ``h1``/``h2``
    are the finest spacings; each level doubles them. Symmetric by
    construction (module docstring), so the result is an SPD
    preconditioner application for the outer CG."""
    levels = hier.levels

    def cycle(lvl: int, rl):
        a, b, dinv = levels[lvl]
        h1l, h2l = h1 * (1 << lvl), h2 * (1 << lvl)
        if lvl == len(levels) - 1:
            return coarse_solve(rl, a, b, dinv, hier.coarse_inv,
                                h1l, h2l, config)
        x = smooth_jacobi(None, rl, a, b, dinv, h1l, h2l,
                          config.pre_smooth, config.omega,
                          from_zero=True)
        res = rl - apply_A(x, a, b, h1l, h2l)
        ec = cycle(lvl + 1, restrict_full_weighting(res))
        x = x + prolong_bilinear(ec)
        return smooth_jacobi(x, rl, a, b, dinv, h1l, h2l,
                             config.post_smooth, config.omega)

    return cycle(0, r)
