"""MG smoke check: ``python -m poisson_tpu.mg.selfcheck``.

Three checks, each a one-line verdict, exit 0 iff all pass:

1. **Two-grid convergence factor** — the stationary cycle
   x ← x + B⁻¹(0 − Ax) on the literature's model problem (unit
   coefficients, square domain, h1 = h2 — Briggs/Henson/McCormick
   ch. 4) with a depth-2 hierarchy (exact dense coarse solve) must
   contract by < 0.2 per cycle. This is the smoothing+coarse-correction
   identity working at all; measured ≈ 0.13. (The production domain is
   2:1.2 anisotropic, which degrades a point-smoother cycle to ≈ 0.4–0.7
   — the outer CG absorbs that, see README "Multigrid preconditioning";
   the model problem is where the algorithm has no excuses.)
2. **Deep V-cycle on the model problem** — the full hierarchy keeps the
   factor < 0.25 (depth must not break the cycle).
3. **Iteration wall** — ``preconditioner="mg"`` beats Jacobi's
   iteration count by ≥ 3× on the reference problem at two resolutions,
   converging to the same δ.
"""

from __future__ import annotations

import argparse
import sys

import numpy as np


def two_grid_factor(M: int, N: int, max_levels: int, cycles: int = 8,
                    ) -> float:
    """Worst per-cycle contraction of the stationary MG iteration on
    the isotropic unit-coefficient model problem."""
    import jax
    import jax.numpy as jnp

    from poisson_tpu.config import Problem
    from poisson_tpu.mg import MGConfig, hierarchy_from_fields, v_cycle
    from poisson_tpu.ops.stencil import apply_A

    p = Problem(M=M, N=N, x_min=-1.0, x_max=1.0, y_min=-1.0, y_max=1.0)
    cfg = MGConfig(max_levels=max_levels)
    ones = np.ones((p.M + 1, p.N + 1))
    dtype_name = ("float64" if jax.config.jax_enable_x64 else "float32")
    hier = hierarchy_from_fields(p, ones, ones, dtype_name, False, cfg)
    a = b = jnp.asarray(ones, jnp.dtype(dtype_name))
    rng = np.random.default_rng(0)
    x0 = np.zeros((p.M + 1, p.N + 1))
    x0[1:-1, 1:-1] = rng.standard_normal((p.M - 1, p.N - 1))
    x = jnp.asarray(x0, jnp.dtype(dtype_name))

    step = jax.jit(lambda x: x + v_cycle(
        hier, -apply_A(x, a, b, p.h1, p.h2), p.h1, p.h2, cfg))
    prev = float(jnp.linalg.norm(x))
    worst = 0.0
    for _ in range(cycles):
        x = step(x)
        cur = float(jnp.linalg.norm(x))
        worst = max(worst, cur / prev)
        prev = cur
    return worst


def run_selfcheck() -> int:
    from poisson_tpu.config import Problem
    from poisson_tpu.solvers.pcg import pcg_solve

    failures = 0

    tg = two_grid_factor(64, 64, max_levels=2)
    ok = tg < 0.2
    print(f"[{'ok' if ok else 'FAIL'}] two-grid contraction on the "
          f"model problem: {tg:.4f} (< 0.2 required)")
    failures += 0 if ok else 1

    deep = two_grid_factor(64, 64, max_levels=16)
    ok = deep < 0.25
    print(f"[{'ok' if ok else 'FAIL'}] deep V-cycle contraction on the "
          f"model problem: {deep:.4f} (< 0.25 required)")
    failures += 0 if ok else 1

    for M, N in ((32, 32), (64, 96)):
        p = Problem(M=M, N=N)
        rj = pcg_solve(p)
        rm = pcg_solve(p, preconditioner="mg")
        kj, km = int(rj.iterations), int(rm.iterations)
        ok = (int(rm.flag) == 1 and float(rm.diff) < p.delta
              and km * 3 <= kj)
        print(f"[{'ok' if ok else 'FAIL'}] iteration wall {M}x{N}: "
              f"jacobi {kj} -> mg {km} (>=3x fewer, converged, "
              f"flag={int(rm.flag)})")
        failures += 0 if ok else 1

    if failures:
        print(f"mg selfcheck: {failures} check(s) FAILED")
        return 1
    print("mg selfcheck OK")
    return 0


def main(argv=None) -> int:
    argparse.ArgumentParser(
        prog="python -m poisson_tpu.mg.selfcheck",
        description=__doc__.splitlines()[0],
    ).parse_args(argv)
    from poisson_tpu.utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()
    return run_selfcheck()


if __name__ == "__main__":
    sys.exit(main())
