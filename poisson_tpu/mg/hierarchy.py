"""Geometric multigrid level hierarchy over the fictitious-domain canvases.

The whole cost of a PCG solve is iterations × bytes/iteration, and the
Jacobi preconditioner's iteration count scales with resolution (989 at
800×1200, 1858 at 1600×2400 — BENCH_TPU_GOOD*.json): doubling the grid
doubles the iterations *and* quadruples the bytes. A geometric V-cycle
preconditioner (Briggs/Henson/McCormick, PAPERS.md) makes the count
near-flat in resolution, because every error frequency is smoothed on
the level where it is local.

This module builds the level data the V-cycle (``mg.cycle``) consumes:

- **Level plan** (:func:`plan_levels`): vertex-centred factor-2
  coarsening, (M, N) → (M/2, N/2), as long as both dimensions stay even
  and the coarser grid stays above ``MGConfig.min_size``. Power-of-two
  bench grids (400×600 … 3200×4800) all bottom out at the SAME 50×75
  coarsest level, which is what makes their iteration counts
  comparable.
- **Coefficient coarsening** (:func:`coarsen_a`/:func:`coarsen_b`):
  the face coefficients a/b are *flux* quantities, so a coarse face
  averages the fine faces it geometrically covers — the two in-line
  faces in series (arithmetic mean keeps the penalty region stiff: the
  fictitious-domain blend must stay ~1/ε outside D or the coarse
  correction would let the solution leak through the boundary) and the
  (¼, ½, ¼)-weighted transverse neighbours the doubled face length
  spans. Constant fields coarsen exactly to themselves. The SAME rule
  serves every :mod:`poisson_tpu.geometry` family — coarsening is
  canvas-only, it never needs the spec's closed form.
- **Coarsest-level solve**: below ``coarse_dense_limit`` interior
  unknowns the coarsest operator is materialised as a dense matrix and
  inverted ONCE on the host in fp64 (symmetrised, so the V-cycle stays
  an exact SPD preconditioner); the inverse is applied in-graph as one
  matmul — MXU-friendly on TPU, and exact coarse solves are what make
  the V-cycle contraction genuinely resolution-independent. Above the
  limit the coarsest level falls back to extra weighted-Jacobi sweeps
  (``coarse_sweeps``) — audibly, via the ``mg.coarse_dense`` gauge.

Everything is derived on the host in fp64 from the same ``a``/``b``
canvases the solve itself uses (``host_fields64`` for the reference
ellipse, ``geometry.canvas.build_geometry_fields`` for DSL specs) and
cast once — the ``host_fields64`` precision idiom. Device-side level
data is cached per (problem, dtype, scaled, geometry fingerprint,
config) with ``mg.hierarchy_cache.{hits,misses}`` counters, mirroring
the geometry canvas cache.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import numpy as np

from poisson_tpu.config import Problem


@dataclasses.dataclass(frozen=True)
class MGConfig:
    """The V-cycle knobs (hashable: rides jit static args).

    pre_smooth/post_smooth: weighted-Jacobi sweeps per level, down- and
        up-leg. Equal counts keep the cycle symmetric — weighted Jacobi
        is A-self-adjoint, so with the bilinear/full-weighting transfer
        pair (exact transposes up to the 2D factor 4) the V-cycle is an
        SPD preconditioner, which plain (non-flexible) CG requires.
    omega: Jacobi damping. 0.8 ≈ 4/5, the classic 2D 5-point choice.
    coarse_sweeps: smoother sweeps standing in for the coarsest solve
        when the dense inverse is over its size limit.
    coarse_dense_limit: max interior unknowns for the dense coarsest
        inverse (n² floats of host memory, one n³ fp64 factorisation).
    min_size: stop coarsening when min(M, N)/2 would fall below this.
    max_levels: hierarchy depth cap (the bench grids use 4–7).
    """

    pre_smooth: int = 2
    post_smooth: int = 2
    omega: float = 0.8
    coarse_sweeps: int = 32
    coarse_dense_limit: int = 4096
    min_size: int = 10
    max_levels: int = 16


DEFAULT_MG = MGConfig()

PRECONDITIONERS = ("jacobi", "mg")


def resolve_preconditioner(preconditioner) -> str:
    """Validate a preconditioner name; None means the default."""
    name = "jacobi" if preconditioner is None else str(preconditioner)
    if name not in PRECONDITIONERS:
        raise ValueError(
            f"unknown preconditioner {preconditioner!r}: expected one of "
            f"{PRECONDITIONERS}"
        )
    return name


def plan_levels(M: int, N: int,
                config: MGConfig = DEFAULT_MG) -> tuple:
    """The (M_l, N_l) ladder, finest first. Level l+1 exists iff both
    dimensions of level l are even and the halved grid stays at or above
    ``config.min_size`` (and the depth cap allows it)."""
    levels = [(int(M), int(N))]
    while len(levels) < config.max_levels:
        m, n = levels[-1]
        if m % 2 or n % 2 or min(m, n) // 2 < config.min_size:
            break
        levels.append((m // 2, n // 2))
    return tuple(levels)


def validate_mg_problem(problem: Problem,
                        config: MGConfig = DEFAULT_MG) -> tuple:
    """The level plan for ``problem``, or a loud ValueError when the
    grid cannot coarsen at all (odd dimensions, or too small) — an
    uncoarsenable 'multigrid' would silently be an expensive smoother."""
    levels = plan_levels(problem.M, problem.N, config)
    if len(levels) < 2:
        raise ValueError(
            f"preconditioner='mg' needs a grid that coarsens at least "
            f"once: {problem.M}x{problem.N} does not (both M and N must "
            f"be even, with min(M, N) >= {2 * config.min_size}). Use "
            f"preconditioner='jacobi' for this grid."
        )
    return levels


# -- coefficient coarsening ---------------------------------------------


def coarsen_a(a: np.ndarray) -> np.ndarray:
    """Coarsen the x-face coefficient field (…fine (M+1, N+1) →
    coarse (M/2+1, N/2+1)).

    The coarse face between coarse nodes (I−1, J) and (I, J) covers the
    two fine faces (2I−1, ·) and (2I, ·) in series along x (averaged
    arithmetically — the blend must stay stiff across the fictitious
    region) and spans transverse fine positions 2J−1, 2J, 2J+1 with
    weights ¼, ½, ¼ (the doubled face length covers the neighbouring
    fine lines by half each). Row 0 / columns 0 and N_c are never read
    by the operators and are filled by injection for shape regularity.
    """
    pair = 0.5 * (a[1::2, :] + a[2::2, :])        # series avg, I = 1..Mc
    core = (0.25 * pair[:, 1:-2:2] + 0.5 * pair[:, 2:-1:2]
            + 0.25 * pair[:, 3::2])               # J = 1..Nc-1
    ac = np.ascontiguousarray(a[::2, ::2])        # injection filler
    ac[1:, 1:-1] = core
    return ac


def coarsen_b(b: np.ndarray) -> np.ndarray:
    """Coarsen the y-face coefficient field — :func:`coarsen_a` with
    the axis roles transposed."""
    pair = 0.5 * (b[:, 1::2] + b[:, 2::2])        # series avg, J = 1..Nc
    core = (0.25 * pair[1:-2:2, :] + 0.5 * pair[2:-1:2, :]
            + 0.25 * pair[3::2, :])               # I = 1..Mc-1
    bc = np.ascontiguousarray(b[::2, ::2])
    bc[1:-1, 1:] = core
    return bc


def _dense_operator(a: np.ndarray, b: np.ndarray, h1: float,
                    h2: float) -> np.ndarray:
    """The 5-point operator on the interior as a dense (n, n) fp64
    matrix, row-major over (i, j) with j fastest — the coarsest-level
    materialisation the dense inverse factors."""
    from poisson_tpu.ops.stencil import diag_D

    M, N = a.shape[0] - 1, a.shape[1] - 1
    mi, nj = M - 1, N - 1
    n = mi * nj
    d = diag_D(a, b, h1, h2)
    A = np.zeros((n, n))
    A[np.arange(n), np.arange(n)] = d.ravel()
    # x-neighbours: (i, j) <-> (i+1, j), coefficient -a[i+1, j]/h1².
    off_x = (-a[2:-1, 1:-1] / (h1 * h1)).ravel()
    rows = np.arange(n - nj)
    A[rows, rows + nj] = off_x
    A[rows + nj, rows] = off_x
    # y-neighbours: (i, j) <-> (i, j+1), coefficient -b[i, j+1]/h2²;
    # the flat offset 1 wraps at row ends, so those links are masked.
    off_y = (-b[1:-1, 2:-1] / (h2 * h2)).ravel(order="C")
    rows_y = np.asarray([i * nj + j for i in range(mi)
                         for j in range(nj - 1)])
    A[rows_y, rows_y + 1] = off_y
    A[rows_y + 1, rows_y] = off_y
    return A


class MGLevels(NamedTuple):
    """Device-side level data, a pytree of jit operands.

    levels: one (a, b, dinv) triple per level, finest first — the
        coefficient canvases and the zero-ring-padded inverse Jacobi
        diagonal (the smoother reads it; the ring keeps smoothed
        iterates zero on the Dirichlet boundary for free).
    coarse_inv: the dense coarsest-operator inverse (n, n), or None
        when the coarsest level is over the dense limit (it then runs
        ``coarse_sweeps`` of the smoother instead).
    scinv: √d on the full grid (zero ring) — the w-space wrap for the
        symmetrically-scaled outer system, or None for unscaled solves.
    """

    levels: tuple
    coarse_inv: object = None
    scinv: object = None


def build_hierarchy64(problem: Problem, a64: np.ndarray, b64: np.ndarray,
                      config: MGConfig = DEFAULT_MG) -> dict:
    """All host-fp64 level data for ``problem``'s canvases: per-level
    (a, b, dinv_padded), the dense coarsest inverse when within the
    size limit, and √d for the scaled wrap. Derivation precision policy
    matches ``host_fields64`` — everything fp64, cast once by the
    caller."""
    from poisson_tpu.ops.stencil import diag_D

    dims = validate_mg_problem(problem, config)
    levels = []
    a, b = np.asarray(a64, np.float64), np.asarray(b64, np.float64)
    for lvl, (m, n) in enumerate(dims):
        h1 = (problem.x_max - problem.x_min) / m
        h2 = (problem.y_max - problem.y_min) / n
        d = diag_D(a, b, h1, h2)
        levels.append((a, b, np.pad(1.0 / d, 1)))
        if lvl + 1 < len(dims):
            a, b = coarsen_a(a), coarsen_b(b)
    mc, nc = dims[-1]
    coarse_inv = None
    if (mc - 1) * (nc - 1) <= config.coarse_dense_limit:
        ac, bc, _ = levels[-1]
        h1c = (problem.x_max - problem.x_min) / mc
        h2c = (problem.y_max - problem.y_min) / nc
        Ac = _dense_operator(ac, bc, h1c, h2c)
        inv = np.linalg.inv(Ac)
        coarse_inv = 0.5 * (inv + inv.T)   # exactly symmetric: SPD cycle
    d0 = diag_D(np.asarray(a64, np.float64), np.asarray(b64, np.float64),
                problem.h1, problem.h2)
    return {
        "dims": dims,
        "levels": levels,
        "coarse_inv": coarse_inv,
        "scinv": np.pad(np.sqrt(d0), 1),
    }


def _cast_levels(host: dict, dtype_name: str, scaled: bool) -> MGLevels:
    import jax.numpy as jnp

    dt = jnp.dtype(dtype_name)
    levels = tuple(
        (jnp.asarray(a, dt), jnp.asarray(b, dt), jnp.asarray(dinv, dt))
        for a, b, dinv in host["levels"]
    )
    coarse_inv = (None if host["coarse_inv"] is None
                  else jnp.asarray(host["coarse_inv"], dt))
    scinv = jnp.asarray(host["scinv"], dt) if scaled else None
    return MGLevels(levels=levels, coarse_inv=coarse_inv, scinv=scinv)


# Device hierarchies this process has built, keyed like the geometry
# canvas cache: (normalized problem, dtype, scaled, fingerprint, config).
# The blend canvases are f_val-independent, so the key normalizes it away
# — every RHS magnitude of a domain shares one hierarchy.
_HIERARCHIES: dict = {}


def reset_hierarchy_cache() -> None:
    """Forget cached device hierarchies (tests; pair with
    ``obs.metrics.reset()`` or the hit/miss arithmetic goes stale)."""
    _HIERARCHIES.clear()


def device_hierarchy(problem: Problem, dtype_name: str, scaled: bool,
                     geometry=None,
                     config: MGConfig = DEFAULT_MG) -> MGLevels:
    """The fingerprint-keyed device-resident hierarchy for ``problem``
    (+ optional :mod:`poisson_tpu.geometry` spec): host-fp64 build and
    dense coarsest factorisation paid once per domain, then cached —
    ``mg.hierarchy_cache.{hits,misses}``."""
    from poisson_tpu import obs

    fp = None
    if geometry is not None:
        from poisson_tpu.geometry.dsl import parse_geometry

        geometry = parse_geometry(geometry)
        fp = geometry.fingerprint
    key = (problem.with_(f_val=1.0), dtype_name, bool(scaled), fp, config)
    cached = _HIERARCHIES.get(key)
    if cached is not None:
        obs.inc("mg.hierarchy_cache.hits")
        return cached
    obs.inc("mg.hierarchy_cache.misses")
    if geometry is None:
        from poisson_tpu.solvers.pcg import host_fields64

        a64, b64, _, _ = host_fields64(problem.with_(f_val=1.0), False)
    else:
        from poisson_tpu.geometry.canvas import build_geometry_fields

        a64, b64, _ = build_geometry_fields(problem, geometry)
    host = build_hierarchy64(problem, a64, b64, config)
    hier = _cast_levels(host, dtype_name, scaled)
    _HIERARCHIES[key] = hier
    obs.gauge("mg.levels", len(hier.levels))
    obs.gauge("mg.coarse_dense", 1 if hier.coarse_inv is not None else 0)
    obs.event("mg.hierarchy", grid=f"{problem.M}x{problem.N}",
              levels=len(hier.levels),
              coarsest="x".join(map(str, host["dims"][-1])),
              dense_coarse=hier.coarse_inv is not None,
              fingerprint=fp)
    return hier


def hierarchy_from_fields(problem: Problem, a64: np.ndarray,
                          b64: np.ndarray, dtype_name: str, scaled: bool,
                          config: MGConfig = DEFAULT_MG) -> MGLevels:
    """Uncached hierarchy straight from explicit host canvases — the
    manufactured-solution oracle's path (``geometry.manufactured``
    builds its own fields and must precondition exactly those)."""
    return _cast_levels(build_hierarchy64(problem, a64, b64, config),
                        dtype_name, scaled)
