"""MG-preconditioned PCG drivers: the ops bundle and the jitted solves.

The preconditioner seam of the whole framework is ``PCGOps.apply_Dinv``
— the shared PCG body (``solvers.pcg.make_pcg_body``) only ever sees
``z = M⁻¹r`` through it. Plugging multigrid in is therefore an ops
construction, never a body change: the default ``"jacobi"`` programs are
the byte-identical historical executables (pinned by tests/test_mg.py),
and ``"mg"`` swaps one V-cycle per iteration in their place.

Scaled-system wrap: the fp32 production path runs CG on
Ã = D^{-1/2}·A·D^{-1/2} (``scaled_single_device_ops``). The V-cycle
works in w-space on the *unscaled* operator at every level, so the
scaled preconditioner is the congruence transform
``z̃ = √d · V(√d · r̃)`` — SPD whenever V is, and exactly equivalent to
MG-preconditioned CG on the unscaled system under y = D^{1/2}w.

Every jitted driver here is the MG twin of an existing flag-off program
(``_solve``, ``_solve_batched``, ``_run_chunk``, ``_member_init``,
``_step_lanes``) with the hierarchy riding as one extra pytree operand
and the cycle config as one extra static arg — separate executables by
construction, so flag-off callers keep their compile-cache identity.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from poisson_tpu.config import Problem
from poisson_tpu.mg.cycle import v_cycle
from poisson_tpu.mg.hierarchy import (
    DEFAULT_MG,
    MGConfig,
    MGLevels,
    device_hierarchy,
)
from poisson_tpu.solvers.pcg import (
    PCGOps,
    PCGResult,
    PCGState,
    init_state,
    make_pcg_body,
    make_pcg_member_body,
    pcg_loop,
    scaled_single_device_ops,
    single_device_ops,
)


def mg_ops(problem: Problem, a, b, aux, hier: MGLevels,
           config: MGConfig = DEFAULT_MG, scaled: bool = True) -> PCGOps:
    """The MG-preconditioned ops bundle: the standard backend bundle
    with ``apply_Dinv`` replaced by one V-cycle (scaled solves get the
    √d congruence wrap — ``hier.scinv``). Everything else — operator,
    dots, norms — is untouched, so the outer CG recurrence is exactly
    the historical one with a stronger M⁻¹."""
    base = (
        scaled_single_device_ops(problem, a, b, aux)
        if scaled
        else single_device_ops(problem, a, b, aux)
    )
    h1, h2 = problem.h1, problem.h2
    if scaled:
        scinv = hier.scinv

        def precond(rt):
            return scinv * v_cycle(hier, scinv * rt, h1, h2, config)
    else:
        def precond(r):
            return v_cycle(hier, r, h1, h2, config)

    return base._replace(apply_Dinv=precond)


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4, 5))
def _solve_mg(problem: Problem, scaled: bool, config: MGConfig,
              stream_every: int, verify_every: int, verify_tol: float,
              a, b, rhs, aux, hier: MGLevels) -> PCGResult:
    """The MG twin of ``solvers.pcg._solve``: same loop, same flags,
    same result contract — the hierarchy is an operand, the cycle
    config a static arg. ``verify_every`` arms the same in-loop
    integrity probe (drift is preconditioner-independent; the
    update-norm guards use the MG-calibrated collapse ratio —
    ``integrity.probe.default_verify_collapse``)."""
    ops = mg_ops(problem, a, b, aux, hier, config, scaled)
    s = pcg_loop(
        ops, rhs,
        delta=problem.delta, max_iter=problem.iteration_cap,
        weighted_norm=problem.weighted_norm,
        h1=problem.h1, h2=problem.h2,
        stream_every=stream_every,
        verify_every=verify_every, verify_tol=verify_tol,
        preconditioner="mg",
    )
    w = s.w * aux if scaled else s.w
    return PCGResult(w=w, iterations=s.k, diff=s.diff, residual_dot=s.zr,
                     flag=s.flag)


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4))
def _solve_batched_mg(problem: Problem, scaled: bool, config: MGConfig,
                      verify_every: int, verify_tol: float,
                      a, b, rhs_stack, aux, hier: MGLevels) -> PCGResult:
    """The MG twin of ``solvers.batched._solve_batched``: the shared
    member body (with the V-cycle inside ``apply_Dinv``) vmapped over a
    (B, M+1, N+1) RHS stack with the same per-member convergence
    masking — the hierarchy closes over the body and broadcasts, one
    coefficient load for the whole batch."""
    from poisson_tpu.solvers.batched import pcg_loop_batched

    ops = mg_ops(problem, a, b, aux, hier, config, scaled)
    s = pcg_loop_batched(
        ops, rhs_stack,
        delta=problem.delta, max_iter=problem.iteration_cap,
        weighted_norm=problem.weighted_norm,
        h1=problem.h1, h2=problem.h2,
        verify_every=verify_every, verify_tol=verify_tol,
        preconditioner="mg",
    )
    w = s.w * aux if scaled else s.w
    return PCGResult(w=w, iterations=s.k, diff=s.diff, residual_dot=s.zr,
                     flag=s.flag, max_iterations=jnp.max(s.k))


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def _member_init_mg(problem: Problem, scaled: bool, config: MGConfig,
                    a, b, aux, hier: MGLevels, rhs) -> PCGState:
    """One member's ``init_state`` with the MG preconditioner (z₀ is a
    V-cycle of r₀) — the lane splice twin of ``lanes._member_init``."""
    return init_state(mg_ops(problem, a, b, aux, hier, config, scaled),
                      rhs)


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4, 5))
def _step_lanes_mg(problem: Problem, scaled: bool, chunk: int,
                   config: MGConfig, verify_every: int, verify_tol: float,
                   a, b, aux, hier: MGLevels, rhs_stack,
                   state: PCGState) -> PCGState:
    """The MG twin of ``lanes._step_lanes`` (and, with
    ``verify_every`` > 0, of ``_step_lanes_verify``): advance every lane
    by at most ``chunk`` of its own iterations against the shared
    hierarchy. ``rhs_stack`` is only read when verifying (each lane's
    probe checks its OWN right-hand side); flag-off callers pass None —
    an empty pytree, so the operand signature stays honest."""
    ops = mg_ops(problem, a, b, aux, hier, config, scaled)
    if verify_every > 0:
        member = make_pcg_member_body(
            ops, delta=problem.delta, weighted_norm=problem.weighted_norm,
            h1=problem.h1, h2=problem.h2,
            verify_every=verify_every, verify_tol=verify_tol,
            preconditioner="mg",
        )
        vbody = jax.vmap(member, in_axes=(0, 0))
        step = lambda s: vbody(s, rhs_stack)
    else:
        body = make_pcg_body(
            ops, delta=problem.delta, weighted_norm=problem.weighted_norm,
            h1=problem.h1, h2=problem.h2,
        )
        vb = jax.vmap(body)
        step = lambda s: vb(s)
    stop_at = jnp.minimum(state.k + chunk, problem.iteration_cap)

    def masked_body(s: PCGState) -> PCGState:
        stepped = step(s)
        frozen = s.done | (s.k >= stop_at)

        def keep(old, new):
            pred = frozen.reshape(frozen.shape + (1,) * (new.ndim - 1))
            return jnp.where(pred, old, new)

        return jax.tree_util.tree_map(keep, s, stepped)

    def cond(s: PCGState):
        return jnp.any((~s.done) & (s.k < stop_at))

    return lax.while_loop(cond, masked_body, state)


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4, 5, 6, 7))
def _run_chunk_mg(problem: Problem, scaled: bool, chunk: int,
                  config: MGConfig, stagnation_window: int,
                  stream_every: int, verify_every: int, verify_tol: float,
                  a, b, aux, rhs, hier: MGLevels,
                  state: PCGState) -> PCGState:
    """The MG twin of ``checkpoint._run_chunk``: advance a chunked solve
    by at most ``chunk`` iterations. Drives the checkpointed, chunked
    (deadline-carrying) and resilient single-request paths."""
    ops = mg_ops(problem, a, b, aux, hier, config, scaled)
    body = make_pcg_body(
        ops, delta=problem.delta, weighted_norm=problem.weighted_norm,
        h1=problem.h1, h2=problem.h2,
        stagnation_window=stagnation_window, stream_every=stream_every,
        verify_every=verify_every, verify_tol=verify_tol,
        verify_rhs=rhs, preconditioner="mg",
    )
    stop_at = jnp.minimum(state.k + chunk, problem.iteration_cap)

    def cond(s: PCGState):
        return (~s.done) & (s.k < stop_at)

    return lax.while_loop(cond, body, state)


def mg_solve_setup(problem: Problem, dtype_name: str, scaled: bool,
                   geometry=None,
                   config: MGConfig = DEFAULT_MG):
    """(a, b, rhs, aux, hierarchy) for an MG solve — ``solve_setup``
    plus the fingerprint-cached device hierarchy."""
    from poisson_tpu.solvers.pcg import solve_setup

    a, b, rhs, aux = solve_setup(problem, dtype_name, scaled,
                                 geometry=geometry)
    hier = device_hierarchy(problem, dtype_name, scaled,
                            geometry=geometry, config=config)
    return a, b, rhs, aux, hier
