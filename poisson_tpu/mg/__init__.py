"""Geometric multigrid preconditioning (``preconditioner="mg"``).

Breaks the iteration wall: Jacobi-preconditioned CG pays iterations
that scale with resolution (989 @ 800×1200 → 1858 @ 1600×2400); one
V-cycle per CG iteration over coarsened copies of the same
fictitious-domain blend canvases makes the count near-flat in
resolution. See README "Multigrid preconditioning".

Layout:

- ``hierarchy`` — level planning, coefficient coarsening, the
  fingerprint-keyed device hierarchy cache, the dense coarsest inverse;
- ``cycle`` — full-weighting restriction, bilinear prolongation,
  weighted-Jacobi smoothing, the symmetric V-cycle;
- ``preconditioner`` — the ops bundle (``apply_Dinv`` = one V-cycle)
  and the jitted MG twins of every flag-off solve program;
- ``selfcheck`` — ``python -m poisson_tpu.mg.selfcheck``: the two-grid
  contraction smoke (< 0.2 on the model problem) plus an MG-vs-Jacobi
  iteration comparison.
"""

from poisson_tpu.mg.cycle import (                      # noqa: F401
    prolong_bilinear,
    restrict_full_weighting,
    smooth_jacobi,
    v_cycle,
)
from poisson_tpu.mg.hierarchy import (                  # noqa: F401
    DEFAULT_MG,
    MGConfig,
    MGLevels,
    PRECONDITIONERS,
    build_hierarchy64,
    coarsen_a,
    coarsen_b,
    device_hierarchy,
    hierarchy_from_fields,
    plan_levels,
    reset_hierarchy_cache,
    resolve_preconditioner,
    validate_mg_problem,
)
from poisson_tpu.mg.preconditioner import (             # noqa: F401
    mg_ops,
    mg_solve_setup,
)
