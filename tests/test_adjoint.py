"""Differentiable solve: implicit adjoint gradients through PCG."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from poisson_tpu.config import Problem
from poisson_tpu.models.fictitious_domain import build_fields
from poisson_tpu.solvers.adjoint import differentiable_solve
from poisson_tpu.solvers.pcg import pcg_solve


@pytest.fixture(scope="module")
def small():
    # Tight delta: gradients are exact only to solver tolerance, so the
    # finite-difference comparison needs convergence well below fd noise.
    p = Problem(M=20, N=20, delta=1e-12)
    _, _, rhs = build_fields(p)
    return p, rhs


def test_forward_matches_pcg_solve(small):
    p, rhs = small
    w = differentiable_solve(p, rhs)
    ref = pcg_solve(p)
    np.testing.assert_allclose(
        np.asarray(w), np.asarray(ref.w), rtol=0, atol=1e-10
    )


def test_linearity(small):
    p, rhs = small
    w1 = differentiable_solve(p, rhs)
    w2 = differentiable_solve(p, 2.0 * rhs)
    np.testing.assert_allclose(
        np.asarray(w2), 2.0 * np.asarray(w1), rtol=0, atol=1e-9
    )


def test_gradient_matches_finite_differences(small):
    """dJ/dB for J = Σ w² via the adjoint solve vs central differences."""
    p, rhs = small

    def loss(r):
        w = differentiable_solve(p, r)
        return jnp.sum(w * w)

    g = jax.grad(loss)(rhs)
    # Probe a few interior entries (inside and outside the ellipse).
    eps = 1e-4
    for (i, j) in [(10, 10), (5, 10), (14, 7), (2, 2)]:
        bump = jnp.zeros_like(rhs).at[i, j].set(eps)
        fd = (loss(rhs + bump) - loss(rhs - bump)) / (2 * eps)
        assert np.isclose(float(g[i, j]), float(fd), rtol=1e-4, atol=1e-9), (
            (i, j, float(g[i, j]), float(fd))
        )


def test_gradient_is_symmetric_solve(small):
    """The VJP of the solve is the solve itself (A = Aᵀ): vjp(g) == A⁻¹g."""
    p, rhs = small
    _, vjp = jax.vjp(lambda r: differentiable_solve(p, r), rhs)
    g = jnp.zeros_like(rhs).at[8, 12].set(1.0)
    (back,) = vjp(g)
    direct = differentiable_solve(p, g)
    np.testing.assert_allclose(
        np.asarray(back), np.asarray(direct), rtol=0, atol=1e-12
    )


def test_forward_mode_jvp(small):
    """custom_linear_solve supports forward-mode: the tangent of a linear
    solve with constant A is the solve of the tangent RHS."""
    p, rhs = small
    t = jnp.zeros_like(rhs).at[7, 9].set(1.0)
    _, w_dot = jax.jvp(lambda r: differentiable_solve(p, r), (rhs,), (t,))
    direct = differentiable_solve(p, t)
    np.testing.assert_allclose(
        np.asarray(w_dot), np.asarray(direct), rtol=0, atol=1e-12
    )


def test_ring_cotangent_ignored(small):
    """Dirichlet ring entries of the cotangent must not leak into the
    gradient (the solution ring is constitutively zero)."""
    p, rhs = small

    def loss(r):
        w = differentiable_solve(p, r)
        return jnp.sum(w[0, :]) + jnp.sum(w * w)

    g1 = jax.grad(loss)(rhs)
    g2 = jax.grad(lambda r: jnp.sum(differentiable_solve(p, r) ** 2))(rhs)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=1e-12)
