"""Distributed-runtime tests on a virtual 8-device CPU mesh.

The capability the reference lacked: multi-"node" testing without a cluster
(SURVEY §4.5 — it needed the real Polus machine). Conftest forces
``--xla_force_host_platform_device_count=8``, so every mesh shape up to 8
devices runs in-process, including non-square and 1D meshes.
"""

import jax
import numpy as np
import pytest

from poisson_tpu.config import Problem
from poisson_tpu.parallel import (
    choose_process_grid,
    make_solver_mesh,
    pcg_solve_sharded,
)
from poisson_tpu.solvers.pcg import pcg_solve


def test_choose_process_grid_matches_reference():
    # Near-square factorisation (stage2:…cpp:60-64).
    assert choose_process_grid(1) == (1, 1)
    assert choose_process_grid(2) == (1, 2)
    assert choose_process_grid(4) == (2, 2)
    assert choose_process_grid(6) == (2, 3)
    assert choose_process_grid(8) == (2, 4)
    assert choose_process_grid(12) == (3, 4)
    assert choose_process_grid(16) == (4, 4)
    assert choose_process_grid(7) == (1, 7)  # primes degrade to 1D


@pytest.mark.parametrize("ndev", [1, 2, 4, 8])
@pytest.mark.parametrize("setup", ["host", "device"])
def test_sharded_matches_single_device(ndev, setup):
    """Iteration-count and solution equality vs the single-device oracle —
    the reference's cross-implementation equivalence test (SURVEY §4.1),
    run on a virtual mesh instead of a cluster."""
    p = Problem(M=40, N=40)
    ref = pcg_solve(p)
    mesh = make_solver_mesh(jax.devices()[:ndev])
    got = pcg_solve_sharded(p, mesh, setup=setup)
    # Reduction order differs between mesh shapes; counts may drift ±1.
    assert abs(int(got.iterations) - int(ref.iterations)) <= 1
    np.testing.assert_allclose(
        np.asarray(got.w), np.asarray(ref.w), atol=1e-10
    )


def test_sharded_f32_scaled_matches_goldens():
    """The production TPU configuration: fp32 state, scaled system, host
    fp64 setup, on a 2×4 mesh."""
    import jax.numpy as jnp

    p = Problem(M=40, N=40)
    mesh = make_solver_mesh(jax.devices())
    got = pcg_solve_sharded(p, mesh, dtype=jnp.float32)
    ref = pcg_solve(p)
    assert int(got.iterations) == int(ref.iterations) == 50
    np.testing.assert_allclose(
        np.asarray(got.w, np.float64), np.asarray(ref.w), atol=1e-5
    )


def test_sharded_uneven_blocks():
    """Grid dims not divisible by the mesh: padding+masking must be exact."""
    p = Problem(M=37, N=29)  # interior 36×28 on a 2×4 mesh → pad to 36×28? no: 18,7
    ref = pcg_solve(p)
    mesh = make_solver_mesh(jax.devices()[:8])  # 2×4
    got = pcg_solve_sharded(p, mesh)
    assert abs(int(got.iterations) - int(ref.iterations)) <= 1
    np.testing.assert_allclose(np.asarray(got.w), np.asarray(ref.w), atol=1e-10)


def test_sharded_explicit_1d_mesh():
    """1D decompositions (Px=1) exercise the zero-fill Dirichlet edges of
    ppermute on one axis only."""
    p = Problem(M=24, N=24)
    ref = pcg_solve(p)
    mesh = make_solver_mesh(jax.devices()[:4], grid=(1, 4))
    got = pcg_solve_sharded(p, mesh)
    assert abs(int(got.iterations) - int(ref.iterations)) <= 1
    np.testing.assert_allclose(np.asarray(got.w), np.asarray(ref.w), atol=1e-10)
