"""Multi-chip dry-run contract at device counts beyond the suite's mesh.

The driver validates multi-chip sharding by running
``__graft_entry__.dryrun_multichip(n)`` under
``--xla_force_host_platform_device_count=n``. The suite's own process is
pinned to 8 virtual devices (conftest), so higher counts run in a
subprocess with their own XLA flags — the closest single-host stand-in for
a larger pod slice.
"""

import os
import pathlib
import subprocess
import sys

import pytest

_ROOT = pathlib.Path(__file__).resolve().parents[1]


@pytest.mark.slow
@pytest.mark.parametrize("n", [16, 32])
def test_dryrun_multichip_scales(n):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n}"
    env["PYTHONPATH"] = str(_ROOT)
    code = (
        "import jax; jax.config.update('jax_platforms','cpu');"
        "import __graft_entry__ as g;"
        f"g.dryrun_multichip({n});"
        "print('OK', len(jax.devices()))"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=_ROOT, env=env,
        capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert f"OK {n}" in proc.stdout
