"""Multi-chip dry-run contract at device counts beyond the suite's mesh.

The driver validates multi-chip sharding by running
``__graft_entry__.dryrun_multichip(n)`` under
``--xla_force_host_platform_device_count=n``. The suite's own process is
pinned to 8 virtual devices (conftest), so higher counts run in a
subprocess with their own XLA flags — the closest single-host stand-in for
a larger pod slice.
"""

import os
import pathlib
import subprocess
import sys

import pytest

_ROOT = pathlib.Path(__file__).resolve().parents[1]


@pytest.mark.slow
def test_dryrun_multichip_self_hosting_from_polluted_env(tmp_path):
    """The round-1 driver trap: dryrun_multichip called from a process whose
    ambient JAX environment is NOT a forced n-device CPU mesh (no
    JAX_PLATFORMS, no device-count flag, and a PYTHONPATH carrying a
    sitecustomize hook that poisons the platform selection — the axon
    plugin's hijack mechanism). The entry point must detect this and re-exec
    hermetically with the hook directory stripped; success at n=8 proves
    both, because the poisoned platform cannot initialize at all and the
    ambient process only ever sees 1 CPU device."""
    decoy = tmp_path / "plugin_site"
    decoy.mkdir()
    (decoy / "sitecustomize.py").write_text(
        "import os\nos.environ['JAX_PLATFORMS'] = 'bogus_remote_accel'\n"
    )
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = f"{_ROOT}{os.pathsep}{decoy}"
    code = (
        "import os, __graft_entry__ as g;"
        "assert os.environ['JAX_PLATFORMS'] == 'bogus_remote_accel';"
        "g.dryrun_multichip(8);"
        "print('OUTER_OK')"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=_ROOT, env=env,
        capture_output=True, text=True, timeout=1200,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "OUTER_OK" in proc.stdout


@pytest.mark.slow
def test_dryrun_gate_survives_config_poisoning_hook(tmp_path):
    """The round-2 driver trap, reproduced on the side that actually broke:
    the ENV says exactly what the driver sets (JAX_PLATFORMS=cpu + an
    8-device forced host count), but a sitecustomize hook has already
    rewritten ``jax.config.jax_platforms`` at interpreter startup — and
    config beats env, so any parent-side ``jax.devices()`` would initialize
    the bogus platform and die (for the real plugin: hang on a wedged
    tunnel). The gate must re-exec a hermetic child with the hook directory
    scrubbed and the config re-pinned, without ever touching the JAX
    runtime in the parent."""
    decoy = tmp_path / "plugin_site"
    decoy.mkdir()
    (decoy / "sitecustomize.py").write_text(
        "import jax\n"
        "jax.config.update('jax_platforms', 'bogus_remote_accel')\n"
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = f"{_ROOT}{os.pathsep}{decoy}"
    code = (
        "import jax, __graft_entry__ as g;"
        # Prove the poison took effect in the parent (the real hook does
        # this; an env-only test would pass even with the round-2 bug).
        "assert jax.config.jax_platforms == 'bogus_remote_accel';"
        "g.dryrun_multichip(8);"
        "print('OUTER_OK')"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=_ROOT, env=env,
        capture_output=True, text=True, timeout=1200,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "OUTER_OK" in proc.stdout


@pytest.mark.slow
@pytest.mark.parametrize("n", [16, 32])
def test_dryrun_multichip_scales(n):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n}"
    env["PYTHONPATH"] = str(_ROOT)
    code = (
        "import jax; jax.config.update('jax_platforms','cpu');"
        "import __graft_entry__ as g;"
        f"g.dryrun_multichip({n});"
        "print('OK', len(jax.devices()))"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=_ROOT, env=env,
        capture_output=True, text=True, timeout=1200,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert f"OK {n}" in proc.stdout
