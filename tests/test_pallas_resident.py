"""VMEM-resident persistent-kernel solver (interpret mode on CPU).

The claim under test: one kernel launch, whole PCG loop in-kernel, and
the arithmetic is the fused path's — so golden iteration counts are
exact and solutions match the streaming fused solver to fp32 noise.
"""

import numpy as np
import pytest

from poisson_tpu.config import Problem
from poisson_tpu.ops.pallas_cg import pallas_cg_solve
from poisson_tpu.ops.pallas_resident import (
    fits_resident,
    resident_cg_solve,
)
from poisson_tpu.solvers.pcg import pcg_solve


def test_golden_40x40_matches_fused():
    p = Problem(M=40, N=40)
    r = resident_cg_solve(p)
    ref = pallas_cg_solve(p)
    assert int(r.iterations) == int(ref.iterations) == 50
    np.testing.assert_allclose(
        np.asarray(r.w), np.asarray(ref.w), rtol=0, atol=1e-6
    )


def test_golden_400x600():
    """The largest small-tier published grid — the capacity target."""
    p = Problem(M=400, N=600)
    r = resident_cg_solve(p)
    assert int(r.iterations) == 546
    assert float(r.diff) < 1e-6
    ref = pcg_solve(p)  # fp64 oracle
    np.testing.assert_allclose(
        np.asarray(r.w, np.float64), np.asarray(ref.w), atol=2e-5
    )


def test_vmem_gate():
    assert fits_resident(Problem(M=400, N=600))
    assert not fits_resident(Problem(M=800, N=1200))
    with pytest.raises(ValueError, match="VMEM"):
        resident_cg_solve(Problem(M=800, N=1200))


def test_rhs_gate_is_bit_exact():
    p = Problem(M=40, N=40)
    r1 = resident_cg_solve(p)
    r2 = resident_cg_solve(p, rhs_gate=np.float32(1.0))
    assert int(r1.iterations) == int(r2.iterations)
    assert np.array_equal(np.asarray(r1.w), np.asarray(r2.w))


def test_iteration_cap_truncates():
    p = Problem(M=40, N=40, delta=1e-30, max_iter=12)
    r = resident_cg_solve(p)
    assert int(r.iterations) == 12


def test_unweighted_norm_matches_fused():
    """stage0's unweighted convergence norm flows through the in-kernel
    norm_w constant exactly like the streaming kernels'."""
    p = Problem(M=40, N=40, weighted_norm=False)
    r = resident_cg_solve(p)
    ref = pallas_cg_solve(p)
    assert int(r.iterations) == int(ref.iterations)
    np.testing.assert_allclose(
        np.asarray(r.w), np.asarray(ref.w), rtol=0, atol=1e-6
    )


def test_wide_grid_with_lane_padding():
    """M ≠ N with real lane padding (301 content cols → 384): padded
    columns must stay inert in the whole-array in-kernel reductions."""
    p = Problem(M=40, N=300)
    r = resident_cg_solve(p)
    ref = pallas_cg_solve(p)
    assert int(r.iterations) == int(ref.iterations)
    np.testing.assert_allclose(
        np.asarray(r.w), np.asarray(ref.w), rtol=0, atol=1e-6
    )
