"""Performance attribution & regression sentinel suite (CPU, tier-1).

Pins the three contracts ISSUE 4 introduced:

- the analytic 5-point-stencil cost model agrees with what XLA's
  ``cost_analysis()`` counts for a real compiled PCG iteration body,
  within ±25%, across dtype and scaling variants — the drift alarm that
  fires before any wall-clock regression does;
- the Prometheus exposition round-trips (names, types, values) through
  the textfile and the live ``/metrics`` endpoint;
- ``benchmarks/regress.py`` classifies the committed BENCH_r01–r05
  history as crash + platform fallbacks (never regressions against the
  TPU baseline) while flagging a synthetic 2× slowdown with a nonzero
  exit.
"""

from __future__ import annotations

import json
import sys
import urllib.request

import pytest

from poisson_tpu import obs
from poisson_tpu.config import Problem
from poisson_tpu.obs import costs, export, metrics

sys.path.insert(0, str(__import__("pathlib").Path(
    __file__).resolve().parents[1]))
from benchmarks import regress  # noqa: E402

pytestmark = pytest.mark.perf_obs


@pytest.fixture(autouse=True)
def _clean_registry():
    metrics.reset()
    yield
    metrics.reset()
    obs.shutdown()


# -- analytic model vs compiled executable -------------------------------


@pytest.mark.parametrize("scaled", [True, False])
def test_model_agrees_with_cost_analysis_f32(scaled):
    report = costs.measured_iteration_cost(
        Problem(M=64, N=64), dtype="float32", scaled=scaled
    )
    assert report["hlo_bytes_per_iter"] is not None
    assert report["hlo_flops_per_iter"] is not None
    # The acceptance invariant: bytes per iteration within +-25%.
    assert report["model_agreement"] == pytest.approx(1.0, abs=0.25)
    assert report["hlo_flops_per_iter"] == pytest.approx(
        report["model_flops_per_iter"], rel=0.25
    )
    # Gauges landed in the registry for the exposition path.
    snap = metrics.snapshot()["gauges"]
    assert snap["cost.hlo_bytes_per_iter"] == report["hlo_bytes_per_iter"]
    assert snap["cost.model_agreement"] == report["model_agreement"]


def test_model_tracks_dtype_bytes_f64():
    # fp64 state doubles bytes, not FLOPs; the model must scale with the
    # dtype and still agree with the compiled program.
    r32 = costs.measured_iteration_cost(
        Problem(M=64, N=96), dtype="float32", scaled=True
    )
    r64 = costs.measured_iteration_cost(
        Problem(M=64, N=96), dtype="float64", scaled=True
    )
    assert r64["model_bytes_per_iter"] == 2 * r32["model_bytes_per_iter"]
    assert r64["model_agreement"] == pytest.approx(1.0, abs=0.25)


def test_analytic_model_closed_form():
    model = costs.analytic_iteration_cost(64, 64, dtype_bytes=4,
                                          scaled=True)
    pts = 65 * 65
    assert model["bytes"] == model["passes"] * pts * 4
    assert model["flops"] == model["flops_per_point"] * pts
    assert sum(model["terms"].values()) == model["passes"]


def test_solve_program_costs_and_memory():
    report = costs.solve_program_costs(Problem(M=48, N=48),
                                       dtype="float32")
    assert report["flops"] and report["flops"] > 0
    assert report["bytes_accessed"] and report["bytes_accessed"] > 0
    assert report["peak_memory_bytes"] and report["peak_memory_bytes"] > 0
    snap = metrics.snapshot()["gauges"]
    assert snap["cost.solve.peak_memory_bytes"] > 0


def test_roofline_summary_known_and_unknown_ceiling(monkeypatch):
    monkeypatch.delenv("POISSON_TPU_PEAK_GBPS", raising=False)
    problem = Problem(M=800, N=1200)
    # The committed TPU record: 989 iterations in 0.0397 s on a v5e.
    rl = costs.roofline_summary(problem, "xla", 4, 989, 0.0397,
                                device_kind="TPU v5 lite")
    assert rl["peak_gbps"] == 820.0
    # BENCH.md's own sanity arithmetic puts this run near the ceiling.
    assert 0.7 < rl["fraction"] < 1.1
    unknown = costs.roofline_summary(problem, "xla", 4, 989, 0.0397,
                                     device_kind="SomeCPU")
    assert unknown["fraction"] is None
    assert unknown["achieved_gbps"] == rl["achieved_gbps"]
    # Env override supplies a ceiling for unlisted parts.
    monkeypatch.setenv("POISSON_TPU_PEAK_GBPS", "100")
    forced = costs.roofline_summary(problem, "xla", 4, 989, 0.0397,
                                    device_kind="SomeCPU")
    assert forced["peak_gbps"] == 100.0
    # No pass model for this backend -> all-None, never a guess.
    native = costs.roofline_summary(problem, "native", 8, 989, 0.5)
    assert native["achieved_gbps"] is None


def test_solve_report_carries_roofline_fields(monkeypatch):
    import time

    from poisson_tpu.solvers.pcg import pcg_solve
    from poisson_tpu.utils.timing import solve_report

    monkeypatch.setenv("POISSON_TPU_PEAK_GBPS", "40")
    problem = Problem(M=40, N=40)
    t0 = time.perf_counter()
    result = pcg_solve(problem, dtype="float32")
    report = solve_report(problem, result, time.perf_counter() - t0,
                          compile_seconds=0.0, dtype="float32",
                          backend="xla")
    assert report.bytes_per_iter_model == 8.0 * 41 * 41 * 4
    assert report.achieved_gbps is not None and report.achieved_gbps > 0
    assert report.roofline_fraction is not None
    assert "attribution:" in report.table()
    # An unmodelled backend leaves the fields None, not wrong.
    report2 = solve_report(problem, result, 0.1, compile_seconds=0.0,
                           dtype="float32", backend="native")
    assert report2.achieved_gbps is None


# -- Prometheus exposition ----------------------------------------------


def test_exposition_round_trip():
    metrics.inc("pcg.solves.converged", 3)
    metrics.inc("time.compile_seconds", 1.25)
    metrics.gauge("roofline.fraction", 0.93)
    metrics.gauge("bench.note", "strings-have-no-exposition")
    text = export.render()
    parsed = export.parse_text(text)
    assert parsed["poisson_tpu_pcg_solves_converged"] == {
        "type": "counter", "value": 3.0}
    assert parsed["poisson_tpu_time_compile_seconds"] == {
        "type": "counter", "value": 1.25}
    assert parsed["poisson_tpu_roofline_fraction"] == {
        "type": "gauge", "value": 0.93}
    assert "poisson_tpu_bench_note" not in parsed
    assert "# skipped non-numeric gauge 'bench.note'" in text


def test_exposition_textfile(tmp_path):
    metrics.inc("watchdog.beats", 7)
    path = tmp_path / "sub" / "metrics.prom"
    export.write_textfile(str(path))
    parsed = export.parse_text(path.read_text())
    assert parsed["poisson_tpu_watchdog_beats"]["value"] == 7.0


def test_metrics_http_endpoint():
    metrics.inc("pcg.solves.converged")
    server = export.start_http_server(port=0)
    try:
        port = server.server_port
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5
        ).read().decode()
        assert export.parse_text(body)[
            "poisson_tpu_pcg_solves_converged"]["value"] == 1.0
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/nope", timeout=5)
    finally:
        export.stop_http_server(server)


def test_configure_serves_and_snapshots(tmp_path):
    prom = tmp_path / "m.prom"
    obs.configure(prom_path=str(prom), metrics_port=0)
    obs.inc("pcg.solves.converged")
    port = int(metrics.snapshot()["gauges"]["export.http_port"])
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
    assert "poisson_tpu_pcg_solves_converged" in body
    obs.shutdown()
    assert "poisson_tpu_pcg_solves_converged" in prom.read_text()
    # Endpoint is down after shutdown.
    with pytest.raises(OSError):
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=1)


# -- profiler capture ----------------------------------------------------


def test_profile_capture_writes_artifacts(tmp_path):
    import os

    import jax
    import jax.numpy as jnp

    from poisson_tpu.obs import profile

    with profile.capture("unit", profile_dir=str(tmp_path)) as out:
        jax.block_until_ready(jnp.ones((16, 16)) * 2)
    files = sum(len(f) for _, _, f in os.walk(out))
    assert files > 0
    assert metrics.get("profile.captures") == 1


def test_profile_capture_noop_when_unconfigured():
    from poisson_tpu.obs import profile

    assert not profile.enabled()
    with profile.capture("unit") as out:
        assert out is None
    assert metrics.get("profile.captures") == 0


# -- regression sentinel -------------------------------------------------


def _bench_result(value, platform, *, fallback=False, grid=(800, 1200),
                  backend="xla", dtype="float32"):
    return {"metric": "mlups", "value": value, "unit": "MLUPS",
            "detail": {"grid": list(grid), "iterations": 989,
                       "solve_seconds": 0.04, "dtype": dtype,
                       "backend": backend, "devices": 1,
                       "platform": platform,
                       "platform_fallback": fallback}}


def _fixture_history():
    recs = []
    for i, v in enumerate([23840.0, 23600.0, 23950.0]):
        recs.append(regress.record_from_result(
            _bench_result(v, "tpu"), f"tpu-{i}"))
    recs.append(regress.record_from_result(
        _bench_result(160.0, "cpu", fallback=True), "cpu-fallback"))
    return recs


def test_regress_fallback_is_not_a_regression():
    verdict = regress.evaluate(_fixture_history())
    assert verdict["verdict"] == "ok"
    by_source = {v["source"]: v for v in verdict["records"]}
    # The CPU-fallback record is never judged against the TPU cohort.
    assert by_source["cpu-fallback"]["classification"] == \
        "platform_fallback"
    assert all(by_source[f"tpu-{i}"]["classification"] == "ok"
               for i in range(3))


def test_regress_flags_2x_slowdown():
    history = _fixture_history()
    history.append(regress.record_from_result(
        _bench_result(11900.0, "tpu"), "tpu-slow"))
    verdict = regress.evaluate(history)
    assert verdict["verdict"] == "regression"
    assert "tpu-slow" in verdict["regressions"]
    # The fallback record still is not part of the alarm.
    by_source = {v["source"]: v for v in verdict["records"]}
    assert by_source["cpu-fallback"]["classification"] == \
        "platform_fallback"


def test_regress_jitter_is_not_a_regression():
    history = _fixture_history()
    history.append(regress.record_from_result(
        _bench_result(22700.0, "tpu"), "tpu-jitter"))  # -5%
    verdict = regress.evaluate(history)
    assert verdict["verdict"] == "ok"


def test_regress_cohorts_split_by_backend_and_dtype():
    history = [
        regress.record_from_result(
            _bench_result(23840.0, "tpu"), "tpu-xla"),
        # A pallas record at ~1.3x xla must not make xla look slow, nor
        # vice versa: different cohort.
        regress.record_from_result(
            _bench_result(31000.0, "tpu", backend="pallas_fused"),
            "tpu-pallas"),
    ]
    verdict = regress.evaluate(history)
    by_source = {v["source"]: v for v in verdict["records"]}
    assert by_source["tpu-xla"]["classification"] == "no_baseline"
    assert by_source["tpu-pallas"]["classification"] == "no_baseline"


def test_regress_committed_history_classifies_r02_r05(capsys):
    # The acceptance scenario, on the real committed artifacts: r01 is a
    # crash, r02-r05 are CPU fallbacks from a wedged tunnel — none of
    # them a regression against the 23,840 MLUPS TPU baseline.
    rc = regress.main([])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["verdict"] == "ok"
    by_source = {v["source"]: v for v in out["records"]}
    assert by_source["BENCH_r01.json"]["classification"] == "failed_run"
    for n in (2, 3, 4, 5):
        assert by_source[f"BENCH_r0{n}.json"]["classification"] == \
            "platform_fallback", by_source[f"BENCH_r0{n}.json"]


def test_regress_main_nonzero_on_synthetic_slowdown(tmp_path, capsys):
    slow = {"n": 99, "cmd": "python bench.py", "rc": 0, "tail": "",
            "parsed": _bench_result(11900.0, "tpu")}
    art = tmp_path / "BENCH_r99.json"
    art.write_text(json.dumps(slow))
    root = str(__import__("pathlib").Path(__file__).resolve().parents[1])
    rc = regress.main([
        "--history", str(art), f"{root}/BENCH_TPU_GOOD.json",
        "--session", f"{root}/benchmarks/results/session.jsonl",
    ])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["verdict"] == "regression"
    assert "BENCH_r99.json" in out["regressions"]


def test_regress_loaders_on_committed_artifacts():
    root = __import__("pathlib").Path(__file__).resolve().parents[1]
    crashed = regress.load_driver_artifact(root / "BENCH_r01.json")
    assert crashed[0]["failed"]
    fell_back = regress.load_driver_artifact(root / "BENCH_r02.json")
    assert fell_back[0]["platform_fallback"]
    assert fell_back[0]["platform"] == "cpu"
    good = regress.load_good_artifact(root / "BENCH_TPU_GOOD.json")
    assert len(good) == 1              # flat legacy format, deduplicated
    assert good[0]["platform"] == "tpu"
    assert good[0]["value"] == 23839.9


# -- bench integration (subprocess: needs a single-device env) ----------


@pytest.mark.slow
def test_bench_record_carries_costs_and_fallback_bit(tmp_path):
    import os
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)     # single CPU device, like the driver
    env["POISSON_TPU_METRICS_OUT"] = str(tmp_path / "metrics.json")
    proc = subprocess.run(
        [sys.executable, "bench.py", "64", "64"],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=str(__import__("pathlib").Path(
            __file__).resolve().parents[1]),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    record = json.loads(proc.stdout.strip().splitlines()[-1])
    assert record["detail"]["platform_fallback"] is False
    block = record["costs"]
    assert block["model_agreement"] == pytest.approx(1.0, abs=0.25)
    assert block["hlo_bytes_per_iter"] > 0
    assert block["peak_memory_bytes"] > 0
    snap = json.loads((tmp_path / "metrics.json").read_text())
    assert snap["gauges"]["cost.model_agreement"] == pytest.approx(
        block["model_agreement"])
