"""Request flight recorder (`poisson_tpu.obs.flight`): per-request
causal traces, latency decomposition, and SLO accounting (tier-1, CPU;
-m flight).

The acceptance surface:

- every admitted request — across BOTH engines and all 14 chaos
  scenarios — yields a complete causal trace from the emitted JSONL
  (one admit root, one typed outcome leaf, no orphan spans), never from
  in-process state;
- the latency decomposition's components sum to the measured wall
  within tolerance for every request of a seeded open-loop run;
- the JSONL schema bump keeps v1 (PR 2–6) lines loading, and reserved-
  key collisions now ride the attrs block instead of being dropped;
- SLO accounting: good/bad scoring, the real histogram surviving
  Prometheus exposition, multi-window burn rates, and the opt-in
  SLO-driven degradation rung;
- with tracing in place the solver behavior is bit-for-bit unchanged
  (lane hook parity, golden counts);
- bench/regress: the new detail keys never enter the sentinel's cohort
  key and direction pins are untouched.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from poisson_tpu import obs
from poisson_tpu.config import Problem
from poisson_tpu.obs import flight, metrics
from poisson_tpu.obs.costs import apportion_compute
from poisson_tpu.obs.trace import load_events, merge_trace_dir
from poisson_tpu.testing.chaos import VirtualClock

pytestmark = pytest.mark.flight

PROBLEM = Problem(M=32, N=32)


@pytest.fixture(autouse=True)
def _clean_telemetry():
    obs.shutdown()
    metrics.reset()
    yield
    obs.shutdown()
    metrics.reset()


def _sum_parts(d: dict) -> float:
    return (d["queue_s"] + d["compute_s"] + d["lane_wait_s"]
            + d["backoff_s"] + d["overhead_s"])


def _assert_decomposition(outcome):
    d = outcome.decomposition
    assert d is not None and outcome.trace_id
    assert abs(_sum_parts(d) - d["wall_s"]) <= max(1e-6, 1e-3 * d["wall_s"])
    for key in ("queue_s", "compute_s", "lane_wait_s", "backoff_s"):
        assert d[key] >= 0.0, (key, d)
    assert d["overhead_s"] >= -1e-6, d


# ---------------------------------------------------------------------------
# Recorder unit behavior
# ---------------------------------------------------------------------------


def test_recorder_decomposition_arithmetic():
    vc = VirtualClock()
    fr = flight.FlightRecorder(clock=vc)
    tid = fr.admit("r")
    assert tid
    fr.begin("r", flight.SPAN_QUEUE)
    vc.advance(0.2)
    fr.end("r", flight.SPAN_QUEUE)
    fr.begin("r", flight.SPAN_RESIDENT, dispatch="d1")
    vc.advance(1.0)
    fr.add_step("r", 1.0, 40, 0.6, "d1", k=40)
    fr.end("r", flight.SPAN_RESIDENT)
    fr.begin("r", flight.SPAN_BACKOFF)
    vc.advance(0.3)
    fr.end("r", flight.SPAN_BACKOFF)
    vc.advance(0.1)    # host machinery → overhead
    out = fr.outcome("r", kind="result", type_="converged")
    d = out["decomposition"]
    assert out["trace_id"] == tid
    assert d["queue_s"] == pytest.approx(0.2)
    assert d["compute_s"] == pytest.approx(0.6)
    assert d["lane_wait_s"] == pytest.approx(0.4)
    assert d["backoff_s"] == pytest.approx(0.3)
    assert d["overhead_s"] == pytest.approx(0.1)
    assert d["wall_s"] == pytest.approx(1.6)
    assert d["iterations"] == 40 and d["dispatches"] == 1
    # The trace is popped: a second outcome is a defensive no-op.
    assert fr.outcome("r", "result", "x")["decomposition"] is None


def test_outcome_closes_open_spans():
    """A request shed while queued still gets a complete tree — the
    open queue_wait folds into queue_s at the outcome."""
    vc = VirtualClock()
    fr = flight.FlightRecorder(clock=vc)
    fr.admit("s")
    fr.begin("s", flight.SPAN_QUEUE)
    vc.advance(0.7)
    d = fr.outcome("s", kind="shed", type_="deadline_expired")
    assert d["decomposition"]["queue_s"] == pytest.approx(0.7)
    assert d["decomposition"]["wall_s"] == pytest.approx(0.7)


def test_unknown_request_ids_are_noops():
    fr = flight.FlightRecorder(clock=VirtualClock())
    fr.begin("ghost", flight.SPAN_QUEUE)
    assert fr.end("ghost", flight.SPAN_QUEUE) == 0.0
    fr.add_step("ghost", 1.0, 5, 0.5, "d1")
    fr.point("ghost", "retry")
    assert fr.outcome("ghost", "x", "y")["trace_id"] == ""


def test_apportion_compute_shares():
    shares = apportion_compute(1.0, {"a": 30, "b": 20, "c": 0})
    assert shares["a"] == pytest.approx(0.6)
    assert shares["b"] == pytest.approx(0.4)
    assert shares["c"] == 0.0
    assert sum(shares.values()) == pytest.approx(1.0)
    # No iterations advanced (killed dispatch): nobody gets compute.
    assert apportion_compute(2.0, {"a": 0}) == {"a": 0.0}
    assert apportion_compute(2.0, {}) == {}


# ---------------------------------------------------------------------------
# SLO accounting
# ---------------------------------------------------------------------------


def test_histogram_cumulative_snapshot():
    h = flight.LatencyHistogram(buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 0.7, 5.0):
        h.observe(v)
    snap = h.snapshot()
    assert snap["le"] == {"0.1": 1, "1": 3, "+Inf": 4}
    assert snap["count"] == 4
    assert snap["sum"] == pytest.approx(6.25)


def test_histogram_prometheus_round_trip():
    from poisson_tpu.obs import export

    h = flight.LatencyHistogram()
    h.observe(0.3)
    h.observe(7.0)
    metrics.gauge("serve.slo.latency_seconds", h.snapshot())
    metrics.inc("serve.slo.good")
    parsed = export.parse_text(export.render())
    key = 'poisson_tpu_serve_slo_latency_seconds_bucket{le="0.5"}'
    assert parsed[key]["value"] == 1.0
    assert parsed[key]["type"] == "histogram"
    assert parsed['poisson_tpu_serve_slo_latency_seconds_bucket'
                  '{le="+Inf"}']["value"] == 2.0
    assert parsed["poisson_tpu_serve_slo_latency_seconds_count"][
        "value"] == 2.0
    assert parsed["poisson_tpu_serve_slo_latency_seconds_sum"][
        "value"] == pytest.approx(7.3)
    assert parsed["poisson_tpu_serve_slo_good"]["type"] == "counter"


def test_slo_tracker_burn_windows_and_budget():
    from poisson_tpu.serve import SLOPolicy

    vc = VirtualClock()
    policy = SLOPolicy(latency_objective_seconds=1.0,
                       availability_target=0.9,
                       burn_windows=(10.0, 100.0))
    tr = flight.SLOTracker(policy, clock=vc)
    assert tr.budget_remaining() == 1.0
    for _ in range(8):
        tr.record(0.5, True)
        vc.advance(1.0)
    tr.record(2.0, False)
    vc.advance(1.0)
    tr.record(2.0, False)
    # Cumulative: 2 bad of 10 against a 0.1 budget → budget gone ×2.
    assert tr.budget_remaining() == pytest.approx(-1.0)
    # Short window (10s) holds the last ~10 samples → burn = 2/10/0.1.
    assert tr.burn_rate(10.0) == pytest.approx(2.0, rel=0.3)
    assert metrics.get("serve.slo.good") == 8
    assert metrics.get("serve.slo.bad") == 2
    snap = metrics.snapshot()["gauges"]
    assert "serve.slo.burn_rate.10s" in snap
    assert "serve.slo.burn_rate.100s" in snap
    assert snap["serve.slo.latency_seconds"]["count"] == 10
    # degrade_on_burn off (default): never asks for a rung.
    assert tr.degrade_level() == 0
    # A policy corner (no windows declared) must be a quiet 0, never an
    # exception out of telemetry into the dispatch loop.
    empty = flight.SLOTracker(
        SLOPolicy(burn_windows=(), degrade_on_burn=True), clock=vc)
    empty.record(0.1, False)
    assert empty.degrade_level() == 0


def test_slo_degrade_level_needs_every_window_burning():
    from poisson_tpu.serve import SLOPolicy

    vc = VirtualClock()
    policy = SLOPolicy(availability_target=0.999,
                       burn_windows=(10.0, 1000.0),
                       degrade_on_burn=True,
                       burn_degrade_thresholds=(2.0, 6.0, 14.0))
    tr = flight.SLOTracker(policy, clock=vc)
    # A long good history, then a fresh burst of bad: the short window
    # burns hard, the long window dilutes it — multi-window rule.
    for _ in range(200):
        tr.record(0.1, True)
        vc.advance(4.0)
    level_calm = tr.degrade_level()
    for _ in range(6):
        tr.record(5.0, False)
        vc.advance(1.0)
    assert level_calm == 0
    # Long window: 6 bad / ~206 → burn ≈ 29; short window: all bad →
    # burn 1000. min ≈ 29 ≥ 14 → deepest rung.
    assert tr.degrade_level() == 3


# ---------------------------------------------------------------------------
# Service integration: decomposition property under both engines
# ---------------------------------------------------------------------------


def _service(scheduling, fault_advance=0.25, **kw):
    from poisson_tpu.serve import DegradationPolicy, ServicePolicy, \
        SolveService

    vc = VirtualClock()
    kw.setdefault("degradation",
                  DegradationPolicy(shrink_padding_at=9.0,
                                    cap_iterations_at=9.0,
                                    downshift_precision_at=9.0))
    svc = SolveService(
        ServicePolicy(scheduling=scheduling, **kw),
        clock=vc, sleep=vc.sleep, seed=0,
        dispatch_fault=(lambda reqs, att: vc.advance(fault_advance))
        if fault_advance else None,
    )
    return svc, vc


@pytest.mark.parametrize("mode", ["drain", "continuous"])
def test_open_loop_decomposition_sums_to_wall(mode):
    """The property the whole decomposition stands on: for EVERY request
    of a seeded open-loop run — arrivals joining work already in flight
    — the components sum to the measured wall within tolerance, under
    both engines."""
    from poisson_tpu.serve import SolveRequest

    svc, vc = _service(mode, max_batch=4, refill_chunk=10, capacity=32)
    rng_gates = [1.0 + i / 11 for i in range(9)]
    for i in range(3):
        svc.submit(SolveRequest(request_id=i, problem=PROBLEM,
                                rhs_gate=rng_gates[i], dtype="float32"))
    svc.pump()
    svc.pump()                          # work is mid-flight
    for i in range(3, 9):               # open-loop joiners
        svc.submit(SolveRequest(request_id=i, problem=PROBLEM,
                                rhs_gate=rng_gates[i], dtype="float32"))
    svc.drain()
    outs = svc.outcomes()        # incl. any completed by the pumps
    assert len(outs) == 9 and svc.stats()["lost"] == 0
    for o in outs:
        _assert_decomposition(o)
        assert o.decomposition["iterations"] > 0
    if mode == "continuous":
        assert all(o.decomposition["chunk_steps"] >= 2 for o in outs)


def test_chunk_step_compute_shares_sum_to_step_wall():
    """Within one shared chunk step, the members' compute shares sum to
    the step's measured wall — compute is apportioned, never invented."""
    from poisson_tpu.serve import SolveRequest

    svc, vc = _service("continuous", fault_advance=0.3, max_batch=2,
                       refill_chunk=10)
    for i in range(2):
        svc.submit(SolveRequest(request_id=i, problem=PROBLEM,
                                rhs_gate=1.0 + i / 10, dtype="float32"))
    outs = svc.drain()
    assert sum(o.decomposition["chunk_steps"] for o in outs) > 0
    # Every step advances the virtual clock by exactly 0.3, and a step's
    # wall is fully apportioned: each member's compute + lane_wait must
    # equal its residency — 0.3 × the chunk steps it rode.
    for o in outs:
        d = o.decomposition
        assert d["compute_s"] + d["lane_wait_s"] == pytest.approx(
            0.3 * d["chunk_steps"])


def test_retry_backoff_is_attributed():
    """A poison-retried request's decomposition shows its backoff; the
    victim's shows the residency it paid on the killed dispatch."""
    from poisson_tpu.serve import RetryPolicy, SolveRequest
    from poisson_tpu.testing.faults import poison_batch_fault

    from poisson_tpu.serve import DegradationPolicy, ServicePolicy, \
        SolveService

    vc = VirtualClock()
    svc = SolveService(
        ServicePolicy(
            retry=RetryPolicy(max_attempts=3, backoff_base=0.01,
                              backoff_cap=0.05),
            degradation=DegradationPolicy(shrink_padding_at=9.0,
                                          cap_iterations_at=9.0,
                                          downshift_precision_at=9.0),
        ),
        clock=vc, sleep=vc.sleep, seed=0,
        dispatch_fault=poison_batch_fault({"poison"}),
    )
    svc.submit(SolveRequest(request_id="poison", problem=PROBLEM))
    svc.submit(SolveRequest(request_id="victim", problem=PROBLEM,
                            rhs_gate=1.1))
    outs = {o.request_id: o for o in svc.drain()}
    _assert_decomposition(outs["poison"])
    _assert_decomposition(outs["victim"])
    assert outs["poison"].kind == "error"
    assert outs["poison"].decomposition["backoff_s"] > 0
    assert outs["victim"].converged


def test_shed_at_admission_has_a_trace():
    from poisson_tpu.serve import ServicePolicy, SolveRequest, \
        SolveService

    vc = VirtualClock()
    svc = SolveService(ServicePolicy(capacity=1), clock=vc,
                       sleep=vc.sleep, seed=0)
    assert svc.submit(SolveRequest(request_id=0, problem=PROBLEM)) is None
    shed = svc.submit(SolveRequest(request_id=1, problem=PROBLEM))
    assert shed is not None and shed.kind == "shed"
    assert shed.trace_id and shed.decomposition is not None
    svc.drain()


def test_slo_driven_degradation_engages_the_ladder():
    """With degrade_on_burn on and the burn over every window, the
    load level rises even though the queue is shallow — the iteration
    cap engages and the downshift is attributed to the SLO."""
    from poisson_tpu.serve import (
        DegradationPolicy,
        RetryPolicy,
        ServicePolicy,
        SLOPolicy,
        SolveRequest,
        SolveService,
    )

    vc = VirtualClock()
    svc = SolveService(
        ServicePolicy(
            capacity=64,                 # queue never near thresholds
            degradation=DegradationPolicy(degraded_iteration_cap=10),
            retry=RetryPolicy(max_attempts=1),
            slo=SLOPolicy(latency_objective_seconds=0.05,
                          availability_target=0.999,
                          burn_windows=(5.0, 50.0),
                          degrade_on_burn=True,
                          burn_degrade_thresholds=(2.0, 6.0, 14.0)),
        ),
        clock=vc, sleep=vc.sleep, seed=0,
        # Every dispatch costs 0.2s — far over the 0.05s objective, so
        # every outcome is SLO-bad and the burn saturates both windows.
        dispatch_fault=lambda reqs, att: vc.advance(0.2),
    )
    for i in range(6):
        svc.submit(SolveRequest(request_id=i, problem=PROBLEM,
                                dtype="float32"))
        svc.drain()
        vc.advance(0.1)
    assert metrics.get("serve.slo.bad") >= 1
    assert metrics.get("serve.degraded.slo_driven") >= 1
    assert metrics.get("serve.degraded.iteration_cap") >= 1
    outs = svc.outcomes()
    assert any(o.partial and o.iterations == 10 for o in outs)
    # Off by default: the same load with the default policy never
    # touches the ladder (pinned so chaos determinism cannot drift).
    metrics.reset()
    vc2 = VirtualClock()
    svc2 = SolveService(ServicePolicy(capacity=64), clock=vc2,
                        sleep=vc2.sleep, seed=0,
                        dispatch_fault=lambda r, a: vc2.advance(0.2))
    svc2.submit(SolveRequest(request_id=0, problem=PROBLEM,
                             dtype="float32"))
    svc2.drain()
    assert metrics.get("serve.degraded.slo_driven") == 0


# ---------------------------------------------------------------------------
# JSONL: schema bump, loader tolerance, completeness from the file
# ---------------------------------------------------------------------------


def test_events_attrs_passthrough_and_reserved_keys(tmp_path):
    """The v1 silent-drop bug, fixed: a caller field shadowing a
    reserved envelope key survives in the attrs block, and request
    attribution rides every flight record."""
    rec = obs.configure(trace_dir=str(tmp_path))
    obs.event("flight.outcome", trace_id="t1", request_id="r1",
              kind="result", rank="shadowed")
    obs.finalize()
    records = load_events(str(tmp_path))
    (ev,) = [r for r in records if r["name"] == "flight.outcome"]
    assert ev["schema"] == 2
    assert ev["kind"] == "event"                 # envelope wins flat
    assert ev["attrs"]["kind"] == "result"       # caller field preserved
    assert ev["attrs"]["rank"] == "shadowed"
    assert ev["rank"] == rec.rank                # envelope rank intact
    assert ev["trace_id"] == "t1" and ev["request_id"] == "r1"


def test_load_events_tolerates_v1_lines(tmp_path):
    """Committed PR 2–6 artifact shapes (flat v1 lines) load next to v2
    lines through the same reader."""
    v1_span = {"at_unix": 1.0, "at_mono": 1.0, "rank": 0,
               "kind": "span_end", "name": "solve",
               "seconds": 0.5, "span_path": "solve"}
    v1_event = {"at_unix": 2.0, "at_mono": 2.0, "rank": 0,
                "kind": "event", "name": "solve.report",
                "M": 40, "N": 40, "iterations": 50, "mlups": 100.0}
    v2 = {"schema": 2, "at_unix": 3.0, "at_mono": 3.0, "rank": 0,
          "kind": "event", "name": "flight.admit",
          "attrs": {"trace_id": "t9", "request_id": "r9", "t": 0.0}}
    path = tmp_path / "events-rank0.jsonl"
    path.write_text("\n".join(json.dumps(r)
                              for r in (v1_span, v1_event, v2)) + "\n")
    records = load_events(str(tmp_path))
    assert [r["name"] for r in records] == ["solve", "solve.report",
                                           "flight.admit"]
    assert records[0]["seconds"] == 0.5          # v1 flat access intact
    assert records[1]["iterations"] == 50
    assert records[2]["trace_id"] == "t9"        # v2 flattened
    assert records[2]["attrs"]["trace_id"] == "t9"


def test_merge_trace_dir_tolerates_corrupt_rank_and_keeps_kinds(tmp_path):
    obs.configure(trace_dir=str(tmp_path), rank=0)
    with obs.span("phase", fence=False):
        obs.event("marker", k=1)
    obs.finalize()
    obs.shutdown()
    (tmp_path / "trace-rank7.trace.json").write_text("{torn")
    merged = merge_trace_dir(str(tmp_path))
    other = merged["otherData"]
    assert [s["file"] for s in other["skipped"]] == [
        "trace-rank7.trace.json"]
    # Both event kinds (span X + instant i) survive, tallied.
    assert other["event_kinds"].get("X", 0) >= 1
    assert other["event_kinds"].get("i", 0) >= 1


def test_service_trace_complete_from_jsonl(tmp_path):
    """End to end on the continuous engine: the causal tree is
    reconstructed and validated FROM THE EMITTED FILE, and the timeline
    renders every lifecycle stage."""
    from poisson_tpu.serve import SolveRequest

    obs.configure(trace_dir=str(tmp_path))
    svc, vc = _service("continuous", max_batch=4, refill_chunk=10)
    svc.submit(SolveRequest(request_id="a", problem=PROBLEM,
                            dtype="float32"))
    svc.pump()
    svc.pump()
    svc.submit(SolveRequest(request_id="b", problem=PROBLEM,
                            rhs_gate=1.2, dtype="float32"))
    outs = {o.request_id: o for o in svc.drain()}
    obs.finalize()
    events = load_events(str(tmp_path))
    report = flight.validate_events(events)
    assert report["traces"] == 2
    assert report["complete"], report["problems"]
    tid, recs = flight.find_trace(events, request_id="b")
    assert tid == outs["b"].trace_id
    timeline = flight.render_timeline(recs)
    for needle in ("admit", "queue_wait", "lane_resident", "chunk_step",
                   "outcome result:converged", "decomposition"):
        assert needle in timeline, timeline


@pytest.mark.parametrize("name", [
    "overload-shed", "breaker-trip", "deadline-mid-chunk",
    "poison-requeue", "slow-worker", "queue-burst-degradation",
    "divergence-escalate", "preempt-typed-error",
    "corrupt-checkpoint-resume", "stall-watchdog",
    "refill-poison-splice", "refill-deadline-mid-splice",
    "refill-taint-across-splice", "refill-preempt-occupied",
])
def test_chaos_scenario_traces_are_complete(name, tmp_path):
    """Every one of the 14 chaos scenarios yields a complete,
    orphan-free span tree per admitted request — one admit root,
    exactly one typed outcome leaf, decomposition summing to wall —
    asserted from the emitted JSONL with a clean registry."""
    from poisson_tpu.testing import chaos

    obs.configure(trace_dir=str(tmp_path))
    report = chaos.run_scenario(name, seed=0)
    assert report["ok"], report["checks"]
    obs.finalize()
    events = load_events(str(tmp_path))
    fr = flight.validate_events(events)
    assert fr["complete"], fr["problems"]
    admitted = report["metrics_snapshot"]["counters"].get(
        "serve.admitted", 0)
    assert fr["traces"] == admitted


# ---------------------------------------------------------------------------
# Bit-parity: tracing must never change solver behavior
# ---------------------------------------------------------------------------


def test_lane_boundary_hook_keeps_bit_parity():
    from poisson_tpu.solvers.lanes import LaneBatch

    boundaries = []
    plain = LaneBatch(PROBLEM, bucket=2, dtype="float32", chunk=10)
    hooked = LaneBatch(PROBLEM, bucket=2, dtype="float32", chunk=10,
                       on_boundary=boundaries.append)
    results = {}
    for lb, key in ((plain, "plain"), (hooked, "hooked")):
        lb.splice("m", 1.3)
        for _ in range(20):
            lb.step()
            view = lb.lane_view()[0]
            if view["done"]:
                results[key] = lb.retire(0)
                break
    assert boundaries and boundaries[0] == {
        "step": 1, "active": 1, "idle": 1, "chunk": 10}
    assert results["plain"].iterations == results["hooked"].iterations
    assert np.array_equal(np.asarray(results["plain"].w),
                          np.asarray(results["hooked"].w))


def test_traced_service_keeps_golden_counts(tmp_path):
    """With the recorder configured and flight tracing active, the
    service's answers are the sequential solver's, bit for bit."""
    from poisson_tpu.serve import SolveRequest
    from poisson_tpu.solvers.pcg import pcg_solve

    obs.configure(trace_dir=str(tmp_path))
    svc, _ = _service("continuous", max_batch=2, refill_chunk=15)
    gates = {i: 1.0 + i / 9 for i in range(4)}
    for i, g in gates.items():
        svc.submit(SolveRequest(request_id=i, problem=PROBLEM,
                                rhs_gate=g, dtype="float32"))
    outs = {o.request_id: o for o in svc.drain()}
    for i, g in gates.items():
        ref = pcg_solve(PROBLEM, dtype="float32", rhs_gate=g)
        assert outs[i].converged
        assert outs[i].iterations == int(ref.iterations)


def test_deadline_elapsed():
    from poisson_tpu.serve import Deadline

    vc = VirtualClock()
    d = Deadline(1.0, clock=vc)
    vc.advance(0.4)
    assert d.elapsed() == pytest.approx(0.4)
    assert not d.expired()
    vc.advance(1.0)
    assert d.expired() and d.elapsed() == pytest.approx(1.4)
    assert Deadline.never().elapsed() >= 0.0


# ---------------------------------------------------------------------------
# Bench / sentinel: new detail keys are attribution, never cohort
# ---------------------------------------------------------------------------


def _regress():
    import pathlib
    import sys

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
    from benchmarks import regress

    return regress


def test_regress_ignores_flight_detail_keys():
    """slowest_requests / p99_exemplar ride the record detail without
    cohort-key churn, and the direction pins stay untouched."""
    regress = _regress()
    detail = {"grid": [96, 144], "dtype": "float32",
              "backend": "xla_serve", "devices": 1, "platform": "cpu",
              "fault_load": "poison2"}
    plain = regress.record_from_result(
        {"metric": "serve.p99_latency", "value": 0.2, "detail": detail},
        source="plain")
    flighty = regress.record_from_result(
        {"metric": "serve.p99_latency", "value": 0.2,
         "detail": {**detail,
                    "p99_exemplar": {"request_id": 7, "trace_id": "f1-8",
                                     "latency_seconds": 0.2},
                    "slowest_requests": [{"request_id": 7,
                                          "decomposition": {}}]}},
        source="flighty")
    assert regress.cohort_key(plain) == regress.cohort_key(flighty)
    assert "p99_exemplar" not in plain and "p99_exemplar" not in flighty
    # Direction pins untouched by this PR.
    assert "serve.p99_latency" in regress._LOWER_IS_BETTER
    assert "serve.shed_rate" in regress._LOWER_IS_BETTER
    assert "serve.sustained_solves_per_sec" not in regress._LOWER_IS_BETTER


# ---------------------------------------------------------------------------
# CLI: the trace viewer + serve fire-drill attribution
# ---------------------------------------------------------------------------


def test_cli_trace_subcommand_smoke(tmp_path, capsys):
    from poisson_tpu.cli import main

    tdir = str(tmp_path / "tr")
    rc = main(["serve", "40", "40", "--requests", "2", "--vary-rhs",
               "--trace-dir", tdir, "--json"])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["p99_exemplar"]["trace_id"]
    assert rec["slowest_requests"][0]["decomposition"] is not None
    assert main(["trace", "1", "--telemetry", tdir]) == 0
    out = capsys.readouterr().out
    assert "admit" in out and "outcome result:converged" in out
    assert "decomposition" in out
    assert main(["trace", "no-such-request", "--telemetry", tdir]) == 1
    capsys.readouterr()
    # JSON mode: raw records for machine consumers.
    assert main(["trace", "1", "--telemetry", tdir, "--json"]) == 0
    lines = [json.loads(line) for line in
             capsys.readouterr().out.strip().splitlines()]
    assert any(r["name"] == "flight.outcome" for r in lines)
    # Both modes fail on a broken tree (an admit with no outcome leaf):
    # automation consuming --json needs the signal most of all.
    broken = tmp_path / "broken"
    broken.mkdir()
    (broken / "events-rank0.jsonl").write_text(json.dumps(
        {"schema": 2, "at_unix": 1.0, "at_mono": 1.0, "rank": 0,
         "kind": "event", "name": "flight.admit",
         "attrs": {"trace_id": "tX", "request_id": "rX", "t": 0.0}},
    ) + "\n")
    for extra in ([], ["--json"]):
        assert main(["trace", "rX", "--telemetry", str(broken)]
                    + extra) == 1
        assert "INCOMPLETE TRACE" in capsys.readouterr().err


def test_forensics_report_renders_flight_section(tmp_path):
    import subprocess
    import sys as _sys

    from poisson_tpu.cli import main

    tdir = str(tmp_path / "tr")
    assert main(["serve", "40", "40", "--requests", "2", "--vary-rhs",
                 "--trace-dir", tdir, "--json"]) == 0
    proc = subprocess.run(
        [_sys.executable, "benchmarks/summarize_session.py",
         "--telemetry", tdir],
        capture_output=True, text=True, cwd="/root/repo",
    )
    assert proc.returncode == 0, proc.stderr
    assert "Flight recorder" in proc.stdout
    assert "Slowest request timeline" in proc.stdout
    assert "SLO:" in proc.stdout
