"""Seeded randomized-geometry sweep over the distributed backends.

The deterministic tests pin specific grids and meshes; this sweep drives
the same correctness claim — every (grid, mesh) combination agrees with
the single-device fp64 oracle — through a seeded random sample of
geometries, hunting the seam bugs parameterized tests miss: odd/even
interiors, blocks thinner than the halo ring, LANE-straddling column
counts, strips that barely round up. Seeded (not hypothesis-random) so a
failure reproduces exactly; bounds keep the whole sweep a few seconds
per backend on the 8-device CPU mesh.
"""

import jax
import numpy as np
import pytest

from poisson_tpu.config import Problem
from poisson_tpu.parallel import make_solver_mesh
from poisson_tpu.parallel.pallas_ca_sharded import ca_cg_solve_sharded
from poisson_tpu.parallel.pallas_sharded import pallas_cg_solve_sharded
from poisson_tpu.parallel.pcg_sharded import pcg_solve_sharded
from poisson_tpu.solvers.pcg import pcg_solve

_MESHES = [(1, 2), (2, 1), (2, 2), (1, 4), (4, 2), (2, 4), (8, 1)]


def _cases(n: int):
    rng = np.random.RandomState(20260730)
    out = []
    for _ in range(n):
        # Interiors from 7×7 up to ~45×45: small enough to solve fast,
        # varied enough to hit uneven blocks on every mesh shape.
        M = int(rng.randint(8, 47))
        N = int(rng.randint(8, 47))
        grid = _MESHES[rng.randint(len(_MESHES))]
        out.append((M, N, grid))
    return out


@pytest.mark.parametrize("M,N,grid", _cases(6))
def test_sharded_backends_match_oracle(M, N, grid):
    p = Problem(M=M, N=N)
    ref = pcg_solve(p)  # fp64 oracle
    mesh = make_solver_mesh(jax.devices()[: grid[0] * grid[1]], grid=grid)
    for solve in (pcg_solve_sharded, pallas_cg_solve_sharded,
                  ca_cg_solve_sharded):
        got = solve(p, mesh)
        assert abs(int(got.iterations) - int(ref.iterations)) <= 1, (
            solve.__name__, M, N, grid, int(got.iterations),
            int(ref.iterations),
        )
        np.testing.assert_allclose(
            np.asarray(got.w, np.float64), np.asarray(ref.w), atol=3e-5,
            err_msg=f"{solve.__name__} {M}x{N} mesh {grid}",
        )
