"""Multi-process (multi-host analog) smoke test.

The reference forms its world with ``MPI_Init`` + ``mpirun -np N``
(``stage2-mpi/poisson_mpi_decomp.cpp:464-468``); the framework's analog is
``jax.distributed`` (``parallel/multihost.py``). JAX supports multiple CPU
processes on one machine — each owns a subset of virtual devices and
collectives cross process boundaries over gRPC — which is the closest
single-box stand-in for a pod: the ppermute halos and psum reductions in
``pcg_solve_sharded`` really do traverse the inter-process transport.

Runs 2 processes × 4 virtual CPU devices = the suite's usual 8-device mesh,
split across a process boundary, and checks the golden iteration count.
"""

import os
import pathlib
import socket
import subprocess
import sys

import pytest

_ROOT = pathlib.Path(__file__).resolve().parents[1]

_WORKER = """
import sys
import jax
jax.config.update("jax_enable_x64", True)

from poisson_tpu.parallel.multihost import initialize_multihost, is_primary

rank = initialize_multihost(
    coordinator=sys.argv[1], num_processes=2, process_id=int(sys.argv[2])
)
assert rank == int(sys.argv[2]), (rank, sys.argv[2])
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8, len(jax.devices())
assert len(jax.local_devices()) == 4, len(jax.local_devices())
assert is_primary() == (rank == 0)

# Second call is the documented no-op.
assert initialize_multihost() == rank

from poisson_tpu.config import Problem
from poisson_tpu.parallel import make_solver_mesh, pcg_solve_sharded

mesh = make_solver_mesh()  # global mesh: all 8 devices across both processes
result = pcg_solve_sharded(
    Problem(M=40, N=40), mesh, dtype="float64", setup="device"
)
iters = int(result.iterations)      # mesh-replicated: fetchable everywhere
assert iters == 50, iters           # the 40x40 weighted-norm golden
assert float(result.diff) < 1e-6

# Checkpointed sharded solve across the process boundary: host-setup blocks
# re-wrapped as global arrays, state all-gathered before the primary-only
# write, barrier-ordered file handoff, capped run resumed to convergence.
import os
from poisson_tpu.parallel import pcg_solve_sharded_checkpointed

ck = sys.argv[3]
p40 = Problem(M=40, N=40)
partial = pcg_solve_sharded_checkpointed(
    p40.with_(max_iter=20), mesh, ck, chunk=10, dtype="float64"
)
assert int(partial.iterations) == 20, int(partial.iterations)
assert os.path.exists(ck)           # unconverged cap-hit keeps the file
resumed = pcg_solve_sharded_checkpointed(
    p40, mesh, ck, chunk=10, dtype="float64"
)
assert int(resumed.iterations) == 50, int(resumed.iterations)
assert float(resumed.diff) < 1e-6
if is_primary():
    assert not os.path.exists(ck)   # converged -> primary cleaned up

# Fused (Pallas, interpret-mode) sharded checkpoint across the process
# boundary: global canvas wraps, replicated gathers, same file handoff.
from poisson_tpu.parallel import pallas_cg_solve_sharded_checkpointed

ck2 = ck + ".fused"
partial = pallas_cg_solve_sharded_checkpointed(
    p40.with_(max_iter=20), mesh, ck2, chunk=10
)
assert int(partial.iterations) == 20, int(partial.iterations)
assert os.path.exists(ck2)
resumed = pallas_cg_solve_sharded_checkpointed(p40, mesh, ck2, chunk=10)
assert int(resumed.iterations) == 50, int(resumed.iterations)
assert float(resumed.diff) < 1e-6

# CA (s=2) sharded across the process boundary: the width-2 ring
# ppermutes and the per-pair 12-entry Gram psum traverse the
# inter-process transport. The checkpointed driver is the multi-process
# entry point (it re-wraps the host canvases as global arrays; the
# one-shot driver, like the fused one-shot, is single-process).
from poisson_tpu.parallel.pallas_ca_sharded import (
    ca_cg_solve_sharded_checkpointed,
)

ck3 = ck + ".ca"
partial = ca_cg_solve_sharded_checkpointed(
    p40.with_(max_iter=20), mesh, ck3, chunk=10
)
assert int(partial.iterations) == 20, int(partial.iterations)
resumed = ca_cg_solve_sharded_checkpointed(p40, mesh, ck3, chunk=10)
assert int(resumed.iterations) == 50, int(resumed.iterations)
print(f"RANK{rank}_OK", flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_distributed_solve(tmp_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = str(_ROOT)
    coord = f"localhost:{_free_port()}"
    ck = str(tmp_path / "ck.npz")
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER, coord, str(rank), ck],
            cwd=_ROOT, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for rank in range(2)
    ]
    outs = []
    try:
        for rank, proc in enumerate(procs):
            out, err = proc.communicate(timeout=600)
            outs.append((rank, proc.returncode, out, err))
    finally:
        for proc in procs:
            proc.kill()
    for rank, rc, out, err in outs:
        assert rc == 0, f"rank {rank} rc={rc}:\n{err[-3000:]}"
        assert f"RANK{rank}_OK" in out, (rank, out, err[-1000:])


def _run_snippet(code: str) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(_ROOT)
    return subprocess.run(
        [sys.executable, "-c", code], cwd=_ROOT, env=env,
        capture_output=True, text=True, timeout=300,
    )


def test_single_process_is_noop():
    """No cluster in the environment → quiet single-process run, rank 0
    (the mpirun-less `./a.out` case of the reference)."""
    proc = _run_snippet(
        "from poisson_tpu.parallel.multihost import initialize_multihost, "
        "is_primary\n"
        "assert initialize_multihost() == 0\n"
        "assert is_primary()\n"
        "print('NOOP_OK')\n"
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "NOOP_OK" in proc.stdout


def test_late_init_is_diagnosed():
    """Initializing the XLA backend first must produce the actionable
    'must be the first JAX call' error, not a silent solo-solve degrade."""
    proc = _run_snippet(
        "import jax\n"
        "jax.devices()\n"
        "from poisson_tpu.parallel.multihost import initialize_multihost\n"
        "try:\n"
        "    initialize_multihost(coordinator='localhost:1',\n"
        "                         num_processes=2, process_id=0)\n"
        "except RuntimeError as e:\n"
        "    assert 'first JAX call' in str(e), str(e)\n"
        "    print('DIAG_OK')\n"
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "DIAG_OK" in proc.stdout
