"""Sharded fused-Pallas path (stage4's kernels+distribution combination)
on the virtual 8-device CPU mesh, interpret mode.

The decisive property under test: the p-halo recomputation scheme (module
doc of ``parallel.pallas_sharded``) must make every mesh shape — including
1D and uneven-block decompositions — agree with the single-device fp64
oracle on iteration count and solution, with only one r-halo exchange per
iteration.
"""

import jax
import numpy as np
import pytest

from poisson_tpu.config import Problem
from poisson_tpu.parallel import make_solver_mesh
from poisson_tpu.parallel.pallas_sharded import pallas_cg_solve_sharded
from poisson_tpu.solvers.pcg import pcg_solve


@pytest.mark.parametrize("ndev", [1, 2, 4, 8])
def test_matches_oracle_across_mesh_shapes(ndev):
    p = Problem(M=40, N=40)
    ref = pcg_solve(p)  # fp64 oracle
    mesh = make_solver_mesh(jax.devices()[:ndev])
    got = pallas_cg_solve_sharded(p, mesh)
    assert abs(int(got.iterations) - int(ref.iterations)) <= 1
    np.testing.assert_allclose(
        np.asarray(got.w, np.float64), np.asarray(ref.w), atol=2e-5
    )


def test_uneven_blocks_and_lane_padding():
    """Interior 36×28 over a 2×4 mesh: row padding from the bm round-up,
    column padding from LANE alignment, both must stay exactly zero."""
    p = Problem(M=37, N=29)
    ref = pcg_solve(p)
    mesh = make_solver_mesh(jax.devices()[:8])
    got = pallas_cg_solve_sharded(p, mesh)
    assert abs(int(got.iterations) - int(ref.iterations)) <= 1
    np.testing.assert_allclose(
        np.asarray(got.w, np.float64), np.asarray(ref.w), atol=2e-5
    )


def test_1d_mesh():
    p = Problem(M=24, N=24)
    ref = pcg_solve(p)
    mesh = make_solver_mesh(jax.devices()[:4], grid=(1, 4))
    got = pallas_cg_solve_sharded(p, mesh)
    assert abs(int(got.iterations) - int(ref.iterations)) <= 1
    np.testing.assert_allclose(
        np.asarray(got.w, np.float64), np.asarray(ref.w), atol=2e-5
    )


def test_matches_single_device_pallas():
    """A/B against the single-device fused path: same math, same fp32
    iterate sequence up to reduction order."""
    from poisson_tpu.ops.pallas_cg import pallas_cg_solve

    p = Problem(M=40, N=40)
    single = pallas_cg_solve(p)
    mesh = make_solver_mesh(jax.devices()[:4])
    sharded = pallas_cg_solve_sharded(p, mesh)
    assert abs(int(sharded.iterations) - int(single.iterations)) <= 1
    np.testing.assert_allclose(
        np.asarray(sharded.w), np.asarray(single.w), atol=2e-5
    )


@pytest.mark.slow
def test_golden_400x600_on_8dev_mesh():
    p = Problem(M=400, N=600)
    mesh = make_solver_mesh(jax.devices())
    got = pallas_cg_solve_sharded(p, mesh)
    assert int(got.iterations) == 546
    assert float(got.diff) < 1e-6


def test_parallel_grid_matches_sequential_sharded():
    """The parallel tile-grid hint on the sharded fused path is pure
    scheduling: bit-identical solution on the same mesh."""
    p = Problem(M=40, N=40)
    mesh = make_solver_mesh(jax.devices()[:4], grid=(2, 2))
    r_seq = pallas_cg_solve_sharded(p, mesh)
    r_par = pallas_cg_solve_sharded(p, mesh, parallel=True)
    assert int(r_par.iterations) == int(r_seq.iterations) == 50
    np.testing.assert_array_equal(np.asarray(r_par.w), np.asarray(r_seq.w))
