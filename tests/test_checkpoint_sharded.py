"""Sharded checkpoint/resume: chunked sharded solves equal one-shot sharded
solves, a killed run resumes from the last chunk boundary, and checkpoints
are portable across mesh shapes and between the sharded and single-device
solvers (elastic recovery — no reference analog, SURVEY §5)."""

import jax
import jax.numpy as jnp
import numpy as np

from poisson_tpu.config import Problem
from poisson_tpu.parallel import (
    make_solver_mesh,
    pcg_solve_sharded,
    pcg_solve_sharded_checkpointed,
)
from poisson_tpu.solvers.checkpoint import pcg_solve_checkpointed
from poisson_tpu.solvers.pcg import pcg_solve


def test_chunked_equals_oneshot_sharded(tmp_path):
    p = Problem(M=40, N=40)
    mesh = make_solver_mesh(jax.devices())
    ref = pcg_solve_sharded(p, mesh)
    got = pcg_solve_sharded_checkpointed(p, mesh, str(tmp_path / "ck.npz"),
                                         chunk=7)
    assert int(got.iterations) == int(ref.iterations)
    np.testing.assert_allclose(
        np.asarray(got.w), np.asarray(ref.w), rtol=0, atol=1e-12
    )
    assert not (tmp_path / "ck.npz").exists()  # converged → cleaned up


def test_kill_and_resume_on_mesh(tmp_path):
    """Simulated preemption on the 8-device mesh: cap the budget, then rerun
    uncapped — the resume converges to the one-shot answer."""
    p = Problem(M=40, N=40)
    mesh = make_solver_mesh(jax.devices())
    path = str(tmp_path / "ck.npz")

    partial = pcg_solve_sharded_checkpointed(p.with_(max_iter=20), mesh,
                                             path, chunk=10)
    assert int(partial.iterations) == 20
    assert (tmp_path / "ck.npz").exists()  # unconverged cap-hit keeps it

    ref = pcg_solve_sharded(p, mesh)
    resumed = pcg_solve_sharded_checkpointed(p, mesh, path, chunk=10)
    assert int(resumed.iterations) == int(ref.iterations)
    np.testing.assert_allclose(
        np.asarray(resumed.w), np.asarray(ref.w), rtol=0, atol=1e-12
    )
    assert not (tmp_path / "ck.npz").exists()


def test_chunked_fp32_scaled_path(tmp_path):
    p = Problem(M=40, N=40)
    mesh = make_solver_mesh(jax.devices())
    ref = pcg_solve_sharded(p, mesh, dtype=jnp.float32)
    got = pcg_solve_sharded_checkpointed(p, mesh, str(tmp_path / "ck.npz"),
                                         chunk=13, dtype=jnp.float32)
    assert int(got.iterations) == int(ref.iterations)
    np.testing.assert_allclose(
        np.asarray(got.w), np.asarray(ref.w), rtol=0, atol=1e-6
    )


def test_checkpoint_portable_across_mesh_shapes(tmp_path):
    """A solve interrupted on a 2x4 mesh resumes on a 4x2 mesh — the
    restart-shape elasticity the reference's fixed-P MPI world lacked."""
    p = Problem(M=40, N=40)
    path = str(tmp_path / "ck.npz")
    mesh_a = make_solver_mesh(jax.devices(), grid=(2, 4))
    mesh_b = make_solver_mesh(jax.devices(), grid=(4, 2))

    pcg_solve_sharded_checkpointed(p.with_(max_iter=20), mesh_a, path, chunk=10)
    ref = pcg_solve_sharded(p, mesh_b)
    resumed = pcg_solve_sharded_checkpointed(p, mesh_b, path, chunk=10)
    assert int(resumed.iterations) == int(ref.iterations)
    np.testing.assert_allclose(
        np.asarray(resumed.w), np.asarray(ref.w), rtol=0, atol=1e-9
    )


def test_checkpoint_portable_mesh_to_single_device(tmp_path):
    p = Problem(M=40, N=40)
    path = str(tmp_path / "ck.npz")
    mesh = make_solver_mesh(jax.devices())

    pcg_solve_sharded_checkpointed(p.with_(max_iter=15), mesh, path, chunk=5)
    ref = pcg_solve(p)
    resumed = pcg_solve_checkpointed(p, path, chunk=50)
    assert int(resumed.iterations) == int(ref.iterations)
    np.testing.assert_allclose(
        np.asarray(resumed.w), np.asarray(ref.w), rtol=0, atol=1e-9
    )


def test_checkpoint_portable_single_device_to_mesh(tmp_path):
    p = Problem(M=40, N=40)
    path = str(tmp_path / "ck.npz")
    mesh = make_solver_mesh(jax.devices())

    pcg_solve_checkpointed(p.with_(max_iter=15), path, chunk=5)
    ref = pcg_solve_sharded(p, mesh)
    resumed = pcg_solve_sharded_checkpointed(p, mesh, path, chunk=50)
    assert int(resumed.iterations) == int(ref.iterations)
    np.testing.assert_allclose(
        np.asarray(resumed.w), np.asarray(ref.w), rtol=0, atol=1e-9
    )
