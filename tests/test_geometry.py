"""Geometry & coefficient-field tests.

Cross-checks the vectorised closed forms in models.fictitious_domain against
an independent scalar re-derivation of the reference's setup
(``stage0/Withoutopenmp1.cpp:19-61``).
"""

import math

import jax.numpy as jnp
import numpy as np
import pytest

from poisson_tpu.config import Problem
from poisson_tpu.models.fictitious_domain import (
    analytic_solution,
    build_fields,
    is_in_domain,
    segment_length_in_domain,
)


def _scalar_seg_len(const_coord, start_var, end_var, vertical):
    """Scalar re-derivation of cal_seg_len_in_D (independent of the jnp path)."""
    # Expression order mirrors the C++ exactly for bit-parity.
    if vertical:
        if abs(const_coord) >= 1.0:
            return 0.0
        half = math.sqrt(max(0.0, (1.0 - const_coord * const_coord) / 4.0))
    else:
        if abs(2.0 * const_coord) >= 1.0:
            return 0.0
        half = math.sqrt(max(0.0, 1.0 - 4.0 * const_coord * const_coord))
    return max(0.0, min(end_var, half) - max(start_var, -half))


def _scalar_coeff(length, h, eps):
    if abs(length - h) < 1e-9:
        return 1.0
    if length < 1e-9:
        return 1.0 / eps
    return length / h + (1.0 - length / h) / eps


@pytest.mark.parametrize("vertical", [True, False])
def test_segment_length_matches_scalar(vertical):
    rng = np.random.default_rng(0)
    c = rng.uniform(-1.2, 1.2, size=200)
    s = rng.uniform(-0.8, 0.8, size=200)
    e = s + rng.uniform(0.0, 0.5, size=200)
    got = np.asarray(
        segment_length_in_domain(jnp.asarray(c), jnp.asarray(s), jnp.asarray(e),
                                 vertical=vertical)
    )
    want = [_scalar_seg_len(ci, si, ei, vertical) for ci, si, ei in zip(c, s, e)]
    # XLA contracts 1−c·c into an FMA on CPU; allow last-ulp drift vs libm.
    np.testing.assert_allclose(got, want, rtol=0, atol=1e-13)


def test_membership():
    assert bool(is_in_domain(0.0, 0.0))
    assert not bool(is_in_domain(1.0, 0.0))
    assert not bool(is_in_domain(0.0, 0.5))
    assert bool(is_in_domain(0.9, 0.1))


@pytest.mark.parametrize("M,N", [(10, 10), (17, 23)])
def test_coefficients_match_scalar_rederivation(M, N):
    p = Problem(M=M, N=N)
    a, b, rhs = build_fields(p)
    a, b, rhs = np.asarray(a), np.asarray(b), np.asarray(rhs)
    h1, h2, eps = p.h1, p.h2, p.eps
    for i in range(1, M + 1):
        for j in range(1, N + 1):
            x, y = p.x_min + i * h1, p.y_min + j * h2
            la = _scalar_seg_len(x - 0.5 * h1, y - 0.5 * h2, y + 0.5 * h2, True)
            lb = _scalar_seg_len(y - 0.5 * h2, x - 0.5 * h1, x + 0.5 * h1, False)
            # 1/eps amplifies the FMA-level drift in the face lengths; a
            # misclassified face (full/cut/empty) would still fail at O(1/eps).
            assert a[i, j] == pytest.approx(_scalar_coeff(la, h2, eps), abs=1e-9)
            assert b[i, j] == pytest.approx(_scalar_coeff(lb, h1, eps), abs=1e-9)
    # RHS: indicator of the ellipse at interior nodes only.
    for i in range(0, M + 1):
        for j in range(0, N + 1):
            x, y = p.x_min + i * h1, p.y_min + j * h2
            q = x * x + 4 * y * y
            if abs(q - 1.0) < 1e-12:
                # Node within an ulp of the ellipse boundary: membership is
                # legitimately compiler-dependent (FMA contraction), skip.
                continue
            want = (
                p.f_val
                if (q < 1.0 and 1 <= i <= M - 1 and 1 <= j <= N - 1)
                else 0.0
            )
            assert rhs[i, j] == want


def test_coefficient_bounds():
    p = Problem(M=40, N=40)
    a, b, _ = build_fields(p)
    # Coefficients lie in [1, 1/eps] by construction.
    for arr in (a, b):
        arr = np.asarray(arr)
        assert arr.min() >= 1.0 - 1e-12
        assert arr.max() <= 1.0 / p.eps + 1e-9


def test_analytic_solution_boundary_conditions():
    p = Problem(M=64, N=64)
    u = np.asarray(analytic_solution(p))
    assert u[0, :].max() == 0 and u[-1, :].max() == 0
    assert u.max() <= 0.1 + 1e-15
    # value at centre is 1/10
    # centre node exists when M, N even
    assert u[p.M // 2, p.N // 2] == pytest.approx(0.1, abs=1e-12)
