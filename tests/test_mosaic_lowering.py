"""Chip-free Mosaic-lowering regression tier (VERDICT r3 item 3).

``jax.export.export(jax.jit(fn), platforms=['tpu'])`` performs the full
Pallas→Mosaic *IR* lowering on any host platform. It does NOT run the
Mosaic machine-code compiler — a kernel can lower cleanly here and still
abort on the chip (the round-3 failure class) — but it is the only
chip-free guard available: every trace/lowering-class regression in a
kernel variant × reduction-layout combination is caught in the CPU suite
before any driver or TPU session becomes the first Mosaic contact.

Coverage: the fused 2-sweep kernels (full-width, column-blocked, parallel
tile grid), the communication-avoiding s=2 kernels (single-device, and
sharded with the ±2 band + column mask under ``shard_map``), and the masked
sharded fused kernels under ``shard_map`` (1×1 — the exact driver-session
configuration — and 2×2 with halo exchange), each in both reduction-partial
layouts
(per-strip ``(nb, 1)`` partials vs serial-Kahan) where the combination is
legal (the parallel tile grid requires the partial layout;
``_resolve_serial`` raises on the contradiction).

Reference analog: the stage4 Makefile was the reference's "does the kernel
build" gate (``/root/reference/stage4-mpi+cuda/Makefile:1-30``); this tier
is ours, minus the machine-code stage the chip keeps to itself.
"""

from __future__ import annotations

import jax
import pytest

from poisson_tpu.config import Problem
from poisson_tpu.ops import pallas_ca, pallas_cg
from poisson_tpu.parallel import make_solver_mesh
from poisson_tpu.parallel import pallas_ca_sharded, pallas_sharded

@pytest.fixture(autouse=True)
def _x64_off():
    """Lower in the hardware dtype regime. The suite enables x64 for
    oracle parity (conftest), but no TPU entry point does — and under x64
    Python-float promotion plants f64→f32 casts inside the kernels that
    Mosaic (correctly) refuses to lower, which are not present in the
    configuration that meets the chip."""
    old = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", False)
    yield
    jax.config.update("jax_enable_x64", old)


# (parallel tile grid, serial-Kahan reduction layout): serial=True with
# parallel=True is rejected by _resolve_serial, so it is not a case here.
LAYOUTS = [
    pytest.param(False, False, id="partials"),
    pytest.param(False, True, id="serial-kahan"),
    pytest.param(True, False, id="parallel-grid"),
]


def _export_tpu(fn, *args):
    """Lower for the TPU platform; any lowering failure raises here."""
    exported = jax.export.export(jax.jit(fn), platforms=["tpu"])(*args)
    assert exported.platforms == ("tpu",)
    return exported


@pytest.mark.parametrize("parallel,serial", LAYOUTS)
def test_fused_full_width_lowers(parallel, serial):
    # bm=8 forces nb=5 strips: multi-strip partial outputs are the shape
    # class that failed on hardware in round 3 (an auto bm at 40×40 gives
    # nb=1, whose degenerate partials lower even with per-cell maps).
    p = Problem(M=40, N=40)
    cv, cs, cw, g, rhs, sc2, _ = pallas_cg.build_canvases(
        p, 8, "float32", None
    )
    assert cv.nb > 1
    _export_tpu(
        lambda cs, cw, g, rhs, sc2: pallas_cg._fused_solve(
            p, cv, False, parallel, serial, cs, cw, g, rhs, sc2
        ),
        cs, cw, g, rhs, sc2,
    )


@pytest.mark.parametrize("parallel,serial", LAYOUTS)
def test_fused_column_blocked_lowers(parallel, serial):
    # bn=128 on a 40×300 grid: 3 column blocks, the blocked kernel variant
    # (_make_blocked_stencil_kernel) with its inter-block halo columns.
    p = Problem(M=40, N=300)
    cv, cs, cw, g, rhs, sc2, _ = pallas_cg.build_canvases(
        p, None, "float32", 128
    )
    assert cv.cg > 0, "expected the column-blocked geometry"
    _export_tpu(
        lambda cs, cw, g, rhs, sc2: pallas_cg._fused_solve(
            p, cv, False, parallel, serial, cs, cw, g, rhs, sc2
        ),
        cs, cw, g, rhs, sc2,
    )


@pytest.mark.parametrize("parallel,serial", LAYOUTS)
def test_ca_pair_iteration_lowers(parallel, serial):
    # bm=8 → nb=5: multi-strip Gram/partial outputs (see the fused test).
    p = Problem(M=40, N=40)
    cv, cs, cw, g, rhs, sc2, _ = pallas_cg.build_canvases(
        p, 8, "float32", None
    )
    assert cv.nb > 1
    _export_tpu(
        lambda cs, cw, g, rhs, sc2: pallas_ca._ca_solve(
            p, cv, False, parallel, serial, cs, cw, g, rhs, sc2
        ),
        cs, cw, g, rhs, sc2,
    )


@pytest.mark.parametrize("serial", [False, True],
                         ids=["partials", "serial-kahan"])
@pytest.mark.parametrize("grid", [(1, 1), (2, 2)],
                         ids=["mesh1x1", "mesh2x2"])
def test_sharded_masked_lowers(grid, serial):
    # (1, 1) is the exact configuration benchmarks/tpu_session.py
    # Mosaic-compiles on the single tunneled chip; (2, 2) adds the
    # ppermute halo exchange to the lowered module. Arrays travel as
    # explicit jit arguments (a nullary export whose operands are all
    # closure constants trips jit-cache pytree bookkeeping when the same
    # canvases are exported twice).
    p = Problem(M=40, N=40)
    px, py = grid
    mesh = make_solver_mesh(jax.devices()[: px * py], grid=grid)
    spec = pallas_sharded.shard_spec(p, px, py, bm=8)  # multi-strip shards
    assert spec.cv.nb > 1
    cs, cw, g, rhs, sc2, sc_int, colmask = pallas_sharded._shard_canvases(
        p, px, py, spec, "float32"
    )
    _export_tpu(
        lambda cs, cw, g, rhs, sc2, sc_int, colmask:
        pallas_sharded._solve(
            p, mesh, spec, False, cs, cw, g, rhs, sc2, sc_int, colmask,
            False, serial,
        ),
        cs, cw, g, rhs, sc2, sc_int, colmask,
    )


@pytest.mark.parametrize("serial", [False, True],
                         ids=["partials", "serial-kahan"])
@pytest.mark.parametrize("grid", [(1, 1), (2, 2)],
                         ids=["mesh1x1", "mesh2x2"])
def test_ca_sharded_masked_lowers(grid, serial):
    # The CA kernels with band widened ±2 and the column mask, under
    # shard_map with the width-2 ring exchange — the sharded-CA
    # configuration × both reduction layouts.
    p = Problem(M=40, N=40)
    px, py = grid
    mesh = make_solver_mesh(jax.devices()[: px * py], grid=grid)
    spec = pallas_ca_sharded.ca_shard_spec(p, px, py, bm=8)  # multi-strip
    assert spec.cv.nb > 1
    (cs, cw, g, rhs, sc2, sc_int,
     colmask) = pallas_ca_sharded._ca_shard_canvases(
        p, px, py, spec, "float32"
    )
    _export_tpu(
        lambda cs, cw, g, rhs, sc2, sc_int, colmask:
        pallas_ca_sharded._ca_solve_sharded(
            p, mesh, spec, False, cs, cw, g, rhs, sc2, sc_int, colmask,
            False, serial,
        ),
        cs, cw, g, rhs, sc2, sc_int, colmask,
    )


@pytest.mark.parametrize("grid", [(40, 40), (400, 600)],
                         ids=["40x40", "400x600"])
def test_resident_persistent_kernel_lowers(grid):
    # The whole-solve in-kernel while_loop with VMEM scratch state — the
    # persistent-kernel path at both grids it serves (400x600 is the
    # capacity target and the largest whole-array reduce).
    from poisson_tpu.ops import pallas_resident

    p = Problem(M=grid[0], N=grid[1])
    cv = pallas_resident.resident_canvas(p)
    _, cs, cw, g, rhs, sc2, _ = pallas_cg.build_canvases(
        p, cv.bm, "float32", 0
    )
    _export_tpu(
        lambda cs, cw, g, rhs, sc2: pallas_resident._resident_solve(
            p, cv, False, cs, cw, g, rhs, sc2
        ),
        cs, cw, g, rhs, sc2,
    )


@pytest.mark.slow
def test_flagship_geometry_lowers_both_layouts():
    """The shipping flagship configuration (800×1200, auto bm) — the
    geometry the driver's bench and the TPU session actually compile on
    hardware — must lower in BOTH reduction layouts. This is the chip-free
    shadow of the session's kernel_probe layout A/B gate."""
    p = Problem(M=800, N=1200)
    cv, cs, cw, g, rhs, sc2, _ = pallas_cg.build_canvases(
        p, None, "float32", None
    )
    for serial in (False, True):
        _export_tpu(
            lambda cs, cw, g, rhs, sc2: pallas_cg._fused_solve(
                p, cv, False, False, serial, cs, cw, g, rhs, sc2
            ),
            cs, cw, g, rhs, sc2,
        )
