"""Program-contract checker (``poisson_tpu.contracts``).

The contract under test, layer by layer:

- **every lint rule fires and suppresses** — one positive fixture and
  one suppressed-negative fixture per rule, through the
  ``lint_source`` seam (synthetic sources, no tree dependency);
- **the tree is clean** — ``run_lint`` + ``run_drift`` on this
  checkout report zero unsuppressed findings (the PR's own acceptance
  criterion: the lint lands with zero unexplained suppressions);
- **the ledger holds and bites** — the committed ``ledger.json``
  matches the current lowerings (round trip), and a deliberately
  mutated flag-off program (a stream callback forced in) is caught
  both structurally (forbidden ``custom_call``) and by fingerprint;
- **drift detection bites** — an injected bench detail key and an
  injected policy field each produce a finding, and the attribution /
  exemption allowlists silence them with a reason;
- **the gate is the gate** — ``python -m poisson_tpu.contracts
  --json`` exits 0 on this tree (the tier-1 hook: a contract break
  fails the suite, not just a human review).
"""

import json
import os
import subprocess
import sys

import pytest

from poisson_tpu.contracts.hlo import (
    CALLBACK_MARKERS,
    assert_no_forbidden,
    find_forbidden,
    hlo_fingerprint,
    strip_hlo_metadata,
)
from poisson_tpu.contracts.lint import (
    RULES,
    documented_metric_names,
    lint_source,
    repo_root,
    run_lint,
)

pytestmark = pytest.mark.contracts

ROOT = repo_root()


def _rules(findings, suppressed=None):
    return sorted({f.rule for f in findings
                   if suppressed is None or f.suppressed == suppressed})


# -- lint rules: positive + suppressed-negative fixtures ----------------


def test_callback_gate_fires_and_suppresses():
    bad = (
        "import jax\n"
        "def body(s):\n"
        "    jax.debug.print('k={}', s.k)\n"
        "    return s\n"
    )
    found = lint_source("poisson_tpu/solvers/pcg.py", bad)
    assert "callback-gate" in _rules(found, suppressed=False)

    gated = (
        "import jax\n"
        "def factory(stream_every):\n"
        "    def body(s):\n"
        "        if stream_every > 0:\n"
        "            jax.debug.print('k={}', s.k)\n"
        "        return s\n"
        "    return body\n"
    )
    assert not lint_source("poisson_tpu/solvers/pcg.py", gated)

    cond_gated = (
        "import jax\n"
        "from jax import lax\n"
        "def emit(due, k):\n"
        "    lax.cond(due, lambda: jax.debug.callback(print, k),\n"
        "             lambda: None)\n"
    )
    assert not lint_source("poisson_tpu/obs/stream.py", cond_gated)

    suppressed = (
        "import jax\n"
        "def body(s):\n"
        "    # contracts: allow=callback-gate -- diagnostic build only\n"
        "    jax.debug.print('k={}', s.k)\n"
        "    return s\n"
    )
    found = lint_source("poisson_tpu/solvers/pcg.py", suppressed)
    assert _rules(found, suppressed=True) == ["callback-gate"]
    assert found[0].reason == "diagnostic build only"


def test_traced_branch_fires_and_suppresses():
    bad = (
        "from jax import lax\n"
        "def loop(init, cap):\n"
        "    def body(s):\n"
        "        if s.done:\n"
        "            return s\n"
        "        return step(s)\n"
        "    def cond(s):\n"
        "        return s.k < cap\n"
        "    return lax.while_loop(cond, body, init)\n"
    )
    found = lint_source("poisson_tpu/solvers/pcg.py", bad)
    assert "traced-branch" in _rules(found, suppressed=False)

    ok = bad.replace("if s.done:", "if cap > 0:").replace(
        "            return s\n        return step(s)\n",
        "            return step(s)\n        return s\n")
    assert not lint_source("poisson_tpu/solvers/pcg.py", ok)

    sup = bad.replace(
        "        if s.done:",
        "        # contracts: allow=traced-branch -- concrete-only helper\n"
        "        if s.done:")
    found = lint_source("poisson_tpu/solvers/pcg.py", sup)
    assert _rules(found, suppressed=False) == []


def test_traced_while_fires():
    bad = (
        "from jax import lax\n"
        "def loop(init):\n"
        "    def body(s):\n"
        "        while s.k < 3:\n"
        "            s = step(s)\n"
        "        return s\n"
        "    return lax.while_loop(lambda s: s.k < 9, body, init)\n"
    )
    found = lint_source("poisson_tpu/solvers/pcg.py", bad)
    assert "traced-branch" in _rules(found, suppressed=False)


def test_static_default_fires_and_suppresses():
    bad = (
        "import functools, jax\n"
        "@functools.partial(jax.jit, static_argnums=(0,))\n"
        "def f(cfg=[], x=None):\n"
        "    return x\n"
    )
    found = lint_source("poisson_tpu/solvers/pcg.py", bad)
    assert "static-default" in _rules(found, suppressed=False)

    ok = bad.replace("cfg=[]", "cfg=()")
    assert not lint_source("poisson_tpu/solvers/pcg.py", ok)

    plain_mutable = (
        "def g(acc={}):\n"
        "    return acc\n"
    )
    found = lint_source("poisson_tpu/solvers/pcg.py", plain_mutable)
    assert "static-default" in _rules(found, suppressed=False)

    sup = bad.replace(
        "def f(cfg=[], x=None):",
        "def f(cfg=[], x=None):  "
        "# contracts: allow=static-default -- test fixture")
    assert not _rules(lint_source("poisson_tpu/solvers/pcg.py", sup),
                      suppressed=False)


def test_static_default_positional_only_and_kwonly():
    """args.defaults spans posonly+args and kw-only params carry their
    own defaults — neither placement hides a mutable default, and the
    posonly layout must not misattribute the finding."""
    posonly = (
        "def f(cfg=[], /, x=()):\n"
        "    return x\n"
    )
    found = lint_source("poisson_tpu/solvers/pcg.py", posonly)
    assert len(found) == 1 and "cfg" in found[0].message

    kwonly = (
        "def g(*, acc=[]):\n"
        "    return acc\n"
    )
    found = lint_source("poisson_tpu/solvers/pcg.py", kwonly)
    assert [f.rule for f in found] == ["static-default"]
    assert "acc" in found[0].message


def test_suppression_pattern_in_strings_is_inert():
    """The suppression syntax inside a docstring or string literal is
    documentation, not a live suppression — it must neither suppress a
    real finding nor fire suppression-reason."""
    doc_example = (
        '"""Docs.\n'
        "\n"
        "Example: # contracts: allow=wallclock\n"
        '"""\n'
        "def f():\n"
        "    return 1\n"
    )
    assert not lint_source("poisson_tpu/solvers/pcg.py", doc_example)

    fake_shield = (
        "import time\n"
        "def setup():\n"
        "    msg = '# contracts: allow=all -- x'\n"
        "    return time.time(), msg\n"
    )
    found = lint_source("poisson_tpu/solvers/pcg.py", fake_shield)
    assert _rules(found, suppressed=False) == ["wallclock"]


def test_wallclock_and_rng_fire_and_scope():
    bad = (
        "import time, random\n"
        "import numpy as np\n"
        "def setup():\n"
        "    t0 = time.time()\n"
        "    jitter = random.random()\n"
        "    noise = np.random.normal()\n"
        "    return t0 + jitter + noise\n"
    )
    found = lint_source("poisson_tpu/solvers/pcg.py", bad)
    assert _rules(found, suppressed=False) == ["rng", "wallclock"]
    # out of solver scope: the same source is fine in serve/
    assert not lint_source("poisson_tpu/serve/service.py", bad)
    # seeded generators pass
    seeded = (
        "import numpy as np\n"
        "def setup(seed):\n"
        "    return np.random.default_rng(seed).normal()\n"
    )
    assert not lint_source("poisson_tpu/solvers/pcg.py", seeded)
    # the watchdog is exempt: wall-clock supervision is its job
    assert not lint_source("poisson_tpu/parallel/watchdog.py", bad)


def test_counter_doc_fires_against_catalogue():
    ctx = {
        "metric_names": documented_metric_names(
            '"""Counters:\n'
            "- ``pcg.solves.<verdict>`` and ``serve.shed.{a,b}`` and\n"
            "  ``plain.counter``.\n"
            '"""\n'),
        "flight_kinds": set(),
    }
    src = (
        "from poisson_tpu import obs\n"
        "def f(tag):\n"
        "    obs.inc('plain.counter')\n"       # documented
        "    obs.inc('serve.shed.a')\n"        # brace-expanded
        "    obs.inc(f'pcg.solves.{tag}')\n"   # wildcard family
        "    obs.inc('rogue.counter')\n"       # undocumented
    )
    found = lint_source("poisson_tpu/serve/service.py", src, ctx)
    assert [f.rule for f in found] == ["counter-doc"]
    assert "rogue.counter" in found[0].message

    sup = src.replace(
        "    obs.inc('rogue.counter')\n",
        "    # contracts: allow=counter-doc -- migration shim\n"
        "    obs.inc('rogue.counter')\n")
    assert not _rules(lint_source("poisson_tpu/serve/service.py", sup,
                                  ctx), suppressed=False)


def test_flight_kind_fires_against_declared_kinds():
    ctx = {"metric_names": (set(), set()),
           "flight_kinds": {"queue_wait", "retry"}}
    src = (
        "def f(self, rid):\n"
        "    self._flight.begin(rid, 'queue_wait')\n"
        "    self._flight.point(rid, 'undeclared_kind')\n"
    )
    found = lint_source("poisson_tpu/serve/service.py", src, ctx)
    assert [f.rule for f in found] == ["flight-kind"]
    assert "undeclared_kind" in found[0].message
    # constants (Name refs) are fine — only rogue literals fire
    const = "def f(self, rid):\n    self._flight.point(rid, POINT_X)\n"
    assert not lint_source("poisson_tpu/serve/service.py", const, ctx)


def test_chaos_registry_fires_for_unregistered_scenario():
    src = (
        "def _registered(seed):\n"
        "    return {}\n"
        "def _forgotten(seed):\n"
        "    return {}\n"
    )
    src = ("@scenario('reg')\n" + src.split("def _forgotten")[0]
           + "def _forgotten" + src.split("def _forgotten")[1])
    found = lint_source("poisson_tpu/testing/chaos.py", src)
    assert [f.rule for f in found] == ["chaos-registry"]
    assert "_forgotten" in found[0].message
    # other files: the rule never looks
    assert not lint_source("poisson_tpu/serve/service.py", src)


def test_fingerprint_key_fires_in_key_builders():
    src = (
        "def dispatch(problem, spec, size, dtype_name):\n"
        "    key = (size, problem, dtype_name, spec.fingerprint)\n"
        "    return key\n"
    )
    found = lint_source("poisson_tpu/solvers/batched.py", src)
    assert [f.rule for f in found] == ["fingerprint-key"]

    clean = src.replace(", spec.fingerprint", ", 'geo'")
    assert not lint_source("poisson_tpu/solvers/batched.py", clean)

    cohort = (
        "def _cohort(self, request):\n"
        "    return request.geometry.fingerprint\n"
    )
    found = lint_source("poisson_tpu/serve/service.py", cohort)
    assert [f.rule for f in found] == ["fingerprint-key"]


def test_suppression_without_reason_is_a_finding():
    src = (
        "import time\n"
        "def setup():\n"
        "    # contracts: allow=wallclock\n"
        "    return time.time()\n"
    )
    found = lint_source("poisson_tpu/solvers/pcg.py", src)
    assert "suppression-reason" in _rules(found)
    # the reasonless allow still suppresses the underlying finding —
    # but leaves the louder meta-finding, so the gate stays red
    assert _rules(found, suppressed=False) == ["suppression-reason"]


# -- the tree itself is clean ------------------------------------------


def test_tree_lint_is_clean():
    rep = run_lint(ROOT)
    active = [f for f in rep["findings"] if not f["suppressed"]]
    assert active == [], "\n".join(
        f"{f['file']}:{f['line']}: [{f['rule']}] {f['message']}"
        for f in active)
    assert rep["counts"]["rules"] >= 8


def test_tree_drift_is_clean():
    from poisson_tpu.contracts.drift import run_drift

    rep = run_drift(ROOT)
    assert rep["findings"] == [], "\n".join(
        f"{f['file']}:{f['line']}: {f['message']}"
        for f in rep["findings"])


def test_every_rule_has_a_fixture_here():
    """The rule list and this test file move together."""
    src = open(__file__).read()
    for rule in RULES:
        assert rule in src, f"rule {rule} has no fixture in this file"


# -- canonicalization / structural helpers ------------------------------


def test_strip_hlo_metadata_both_dialects():
    compiled = 'add = f64[] add(a, b), metadata={op_name="jit(f)/add"}'
    assert strip_hlo_metadata(compiled) == "add = f64[] add(a, b)"
    stable = ('%0 = stablehlo.add %a, %b : tensor<f64> '
              'loc("jit(f)"("x.py":1:0))\n#loc1 = loc("x.py":2:0)\n')
    out = strip_hlo_metadata(stable)
    assert "loc(" not in out and "#loc" not in out
    assert "stablehlo.add" in out


def test_find_forbidden_and_assert():
    txt = "stablehlo.custom_call @xla_ffi_python_cpu_callback(...)"
    assert find_forbidden(txt, CALLBACK_MARKERS) \
        == ["custom_call", "callback"]
    with pytest.raises(AssertionError, match="custom_call"):
        assert_no_forbidden(txt, CALLBACK_MARKERS, context="fixture")
    assert_no_forbidden("stablehlo.add", CALLBACK_MARKERS)


def test_fingerprint_ignores_metadata_only_differences():
    a = 'op = f64[] add(a, b), metadata={op_name="x"}'
    b = 'op = f64[] add(a, b), metadata={op_name="y"}'
    assert hlo_fingerprint(a) == hlo_fingerprint(b)
    assert hlo_fingerprint(a) != hlo_fingerprint("op = f64[] add(a, c)")


# -- the HLO identity ledger -------------------------------------------


def test_ledger_round_trip_matches_committed():
    """Every registered program lowers to exactly the committed
    fingerprint — the 11-test-files' byte-pins, now one harness."""
    from poisson_tpu.contracts.manifest import run_ledger_check

    report = run_ledger_check()
    assert report["programs"] >= 6
    assert report["problems"] == [], report["problems"]


def test_ledger_detects_a_mutated_flag_off_program():
    """Force a callback into the flagship flag-off program (lower the
    jitted ``_solve`` with ``stream_every=5``): the ledger harness must
    catch it BOTH ways — structurally (forbidden custom_call/callback)
    and by fingerprint drift against the committed entry."""
    from poisson_tpu.contracts.manifest import load_ledger, markers_for
    from poisson_tpu.solvers.pcg import _solve, host_setup
    from poisson_tpu.config import Problem

    p = Problem(M=20, N=24)
    a, b, rhs, aux = host_setup(p, "float64", False)
    mutated = _solve.lower(p, False, 5, 0, 0.0, False, 0,
                           a, b, rhs, aux).as_text()
    assert find_forbidden(mutated, markers_for(("callbacks",)))
    committed = load_ledger()["entries"]["solve.jacobi_f64"]
    assert hlo_fingerprint(mutated) != committed["fingerprint"]


def test_ledger_update_writes_and_recheck_is_stable(tmp_path):
    from poisson_tpu.contracts.manifest import run_ledger_check

    path = str(tmp_path / "ledger.json")
    first = run_ledger_check(update=True, path=path)
    assert first["updated"] and os.path.exists(path)
    second = run_ledger_check(path=path)
    assert second["problems"] == []
    data = json.load(open(path))
    assert set(data["entries"]) == set(first["entries"])
    # determinism: the fingerprints reproduce within a process
    assert {k: v["fingerprint"] for k, v in data["entries"].items()} \
        == {k: v["fingerprint"] for k, v in second["entries"].items()}


def test_gate_exits_one_when_a_covered_program_drifts(tmp_path,
                                                      monkeypatch,
                                                      capsys):
    """The acceptance criterion end to end: tamper with a covered
    program's committed fingerprint (equivalent to its lowering having
    changed under the gate) and the `python -m poisson_tpu.contracts`
    entry point flips to exit 1 with a ledger-drift problem naming the
    program."""
    from poisson_tpu.contracts import manifest
    from poisson_tpu.contracts.__main__ import main

    data = dict(manifest.load_ledger())
    data["entries"] = dict(data["entries"])
    data["entries"]["solve.jacobi_f64"] = {
        **data["entries"]["solve.jacobi_f64"],
        "fingerprint": "f" * 64,
    }
    path = str(tmp_path / "tampered.json")
    json.dump(data, open(path, "w"))
    monkeypatch.setattr(manifest, "LEDGER_PATH", path)
    rc = main(["--json"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1 and report["ok"] is False
    drifted = [p for p in report["ledger"]["problems"]
               if p["kind"] == "ledger-drift"]
    assert [p["program"] for p in drifted] == ["solve.jacobi_f64"]


def test_absent_or_corrupt_ledger_fails_the_gate(tmp_path):
    """A gate that silently stopped producing evidence is not a
    passing gate: no committed ledger (or an unreadable one) is a
    ledger-absent problem, never a green check."""
    from poisson_tpu.contracts.manifest import run_ledger_check

    missing = run_ledger_check(path=str(tmp_path / "nope.json"))
    assert [p["kind"] for p in missing["problems"]] == ["ledger-absent"]
    corrupt = tmp_path / "corrupt.json"
    corrupt.write_text("{not json")
    report = run_ledger_check(path=str(corrupt))
    assert [p["kind"] for p in report["problems"]] == ["ledger-absent"]


def test_from_imports_cannot_evade_purity_rules():
    """`from time import perf_counter` / `from jax import debug` must
    resolve through the import bindings — the ordinary from-import
    idiom is not a lint bypass."""
    wall = (
        "from time import perf_counter\n"
        "def setup():\n"
        "    return perf_counter()\n"
    )
    found = lint_source("poisson_tpu/solvers/pcg.py", wall)
    assert _rules(found) == ["wallclock"]

    cb = (
        "from jax import debug\n"
        "def body(s):\n"
        "    debug.print('k={}', s.k)\n"
        "    return s\n"
    )
    found = lint_source("poisson_tpu/solvers/pcg.py", cb)
    assert "callback-gate" in _rules(found)

    aliased = (
        "from time import time as now\n"
        "def setup():\n"
        "    return now()\n"
    )
    found = lint_source("poisson_tpu/solvers/pcg.py", aliased)
    assert _rules(found) == ["wallclock"]


def test_drift_missing_sources_fail_loudly(tmp_path):
    """run_drift on a root without the checked files reports findings
    (drift-source-missing), never a crash and never a silent pass."""
    from poisson_tpu.contracts.drift import run_drift

    rep = run_drift(str(tmp_path))
    rules = {f["rule"] for f in rep["findings"]}
    assert rules == {"drift-source-missing"}
    assert len(rep["findings"]) == 4


def test_ledger_flags_missing_and_stale_entries(tmp_path):
    from poisson_tpu.contracts.manifest import (
        LEDGER_SCHEMA,
        load_ledger,
        run_ledger_check,
    )

    data = dict(load_ledger())
    entries = dict(data["entries"])
    victim = sorted(entries)[0]
    entries.pop(victim)
    entries["ghost.program"] = {"fingerprint": "0" * 64}
    path = str(tmp_path / "ledger.json")
    json.dump({**data, "schema": LEDGER_SCHEMA, "entries": entries},
              open(path, "w"))
    report = run_ledger_check(path=path)
    kinds = {p["kind"]: p["program"] for p in report["problems"]}
    assert kinds.get("ledger-missing") == victim
    assert kinds.get("ledger-stale") == "ghost.program"


# -- registry drift detection ------------------------------------------


BENCH_FIXTURE = (
    "record = {\n"
    "    'metric': 'mlups',\n"
    "    'detail': {\n"
    "        'grid': [M, N],\n"
    "        'dtype': 'float32',\n"
    "        'quantization': q,\n"     # the injected drift
    "    },\n"
    "}\n"
)
REGRESS_FIXTURE = (
    "def record_from_result(result, source, fallback_hint=False):\n"
    "    det = result.get('detail') or {}\n"
    "    return _mk_record(source, grid=det.get('grid'),\n"
    "                      dtype=det.get('dtype'))\n"
)


def test_bench_cohort_drift_fires_and_allowlists():
    from poisson_tpu.contracts.drift import check_bench_cohort

    found = check_bench_cohort(BENCH_FIXTURE, REGRESS_FIXTURE,
                               attribution_only={})
    assert [f.rule for f in found] == ["bench-detail-cohort"]
    assert "quantization" in found[0].message
    # declared attribution-only: silenced
    assert not check_bench_cohort(
        BENCH_FIXTURE, REGRESS_FIXTURE,
        attribution_only={"quantization": "payload"})
    # lifted into the cohort: silenced
    lifted = REGRESS_FIXTURE.replace(
        "dtype=det.get('dtype'))",
        "dtype=det.get('dtype'),\n"
        "                      quantization=det.get('quantization'))")
    assert not check_bench_cohort(BENCH_FIXTURE, lifted,
                                  attribution_only={})
    # an allowlist entry for a key bench no longer emits is rot
    found = check_bench_cohort(
        BENCH_FIXTURE, lifted,
        attribution_only={"ghost_key": "long gone"})
    assert [f.rule for f in found] == ["attribution-stale"]
    assert "ghost_key" in found[0].message


def test_policy_coverage_drift_fires_and_exempts():
    from poisson_tpu.contracts.drift import check_policy_coverage

    types_src = (
        "import dataclasses\n"
        "@dataclasses.dataclass(frozen=True)\n"
        "class ServicePolicy:\n"
        "    capacity: int = 64\n"
        "    novel_knob: int = 0\n"
    )
    chaos_src = "svc = SolveService(ServicePolicy(capacity=16))\n"
    found = check_policy_coverage(types_src, chaos_src, exempt={})
    assert [f.rule for f in found] == ["policy-chaos-coverage"]
    assert "novel_knob" in found[0].message
    assert not check_policy_coverage(
        types_src, chaos_src,
        exempt={"ServicePolicy.novel_knob": "covered elsewhere"})
    exercised = chaos_src.replace("capacity=16",
                                  "capacity=16, novel_knob=1")
    assert not check_policy_coverage(types_src, exercised, exempt={})
    # an exemption for a field that no longer exists is rot
    found = check_policy_coverage(
        types_src, exercised,
        exempt={"ServicePolicy.removed_knob": "was covered elsewhere"})
    assert [f.rule for f in found] == ["exemption-stale"]


# -- the gate -----------------------------------------------------------


def test_contracts_gate_exits_zero_on_this_tree():
    """The tier-1 hook: a contract break anywhere fails this test, not
    just a human review."""
    proc = subprocess.run(
        [sys.executable, "-m", "poisson_tpu.contracts", "--json"],
        capture_output=True, text=True, cwd=ROOT, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["ok"] is True
    assert report["counts"]["rules"] >= 8
    assert report["counts"]["findings"] == 0
    assert report["counts"]["ledger_problems"] == 0
    assert report["counts"]["ledger_programs"] >= 6


def test_contracts_lint_only_gate():
    proc = subprocess.run(
        [sys.executable, "-m", "poisson_tpu.contracts", "--lint-only",
         "--json"],
        capture_output=True, text=True, cwd=ROOT, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["ledger"] is None and report["ok"] is True


def test_contracts_gauges_stamped():
    from poisson_tpu.contracts.__main__ import run_contracts
    from poisson_tpu.obs import metrics

    report = run_contracts(ROOT, ledger=False)
    assert report["ok"]
    snap = metrics.snapshot()["gauges"]
    assert snap["contracts.findings"] == 0
    assert snap["contracts.rules"] >= 8
