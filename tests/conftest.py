"""Test harness configuration.

The reference had no tests and validated on a real cluster (SURVEY §4); here
every distributed path is exercised on a virtual 8-device CPU mesh via
``--xla_force_host_platform_device_count`` — set before JAX import, which is
why this lives at the top of conftest.
"""

import os
import sys

# The package is run from a checkout, not installed: make the suite
# cwd-independent by ensuring the repo root is importable.
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# fp64 for bit-parity with the reference oracle.
jax.config.update("jax_enable_x64", True)
# Some environments register remote-accelerator PJRT plugins that override
# jax_platforms at import time (and may hang at init if the remote side is
# unreachable); force the CPU backend for tests regardless.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running solve (large grids)")
    config.addinivalue_line(
        "markers",
        "xslow: minutes-long solve (largest grids); skipped unless "
        "RUN_XSLOW=1 or selected with -m xslow",
    )
    config.addinivalue_line(
        "markers",
        "faults: fault-injection suite for the resilience layer "
        "(CPU-fast; runs in tier-1, selectable with -m faults)",
    )
    config.addinivalue_line(
        "markers",
        "obs: unified-telemetry suite (spans/counters/streaming; "
        "CPU-fast; runs in tier-1, selectable with -m obs)",
    )
    config.addinivalue_line(
        "markers",
        "batched: batched multi-RHS driver suite (batch-vs-sequential "
        "bit-parity, bucketing, CLI/bench throughput mode; CPU-fast; "
        "runs in tier-1, selectable with -m batched)",
    )
    config.addinivalue_line(
        "markers",
        "perf_obs: performance attribution & regression sentinel suite "
        "(cost model vs cost_analysis, Prometheus exposition, regress.py "
        "verdicts; CPU-fast; runs in tier-1, selectable with -m perf_obs)",
    )
    config.addinivalue_line(
        "markers",
        "serve: solve-service & chaos-campaign suite (admission/"
        "deadline/retry/breaker/degradation lifecycle, seeded "
        "deterministic chaos scenarios, the no-lost-request invariant; "
        "CPU-fast; runs in tier-1, selectable with -m serve)",
    )
    config.addinivalue_line(
        "markers",
        "flight: request flight-recorder suite (per-request causal "
        "traces, latency decomposition summing to wall, SLO "
        "accounting/burn rates/histogram exposition, the trace CLI; "
        "CPU-fast; runs in tier-1, selectable with -m flight)",
    )
    config.addinivalue_line(
        "markers",
        "fleet: durable solve fleet suite (supervised workers — "
        "kill/hang/quarantine/restart, CRC-sealed request journal, "
        "torn-tail replay, crash-restart recovery preserving the "
        "ledger invariant; CPU-fast; runs in tier-1, selectable with "
        "-m fleet)",
    )
    config.addinivalue_line(
        "markers",
        "geom: geometry-as-a-request suite (DSL normalization/"
        "fingerprints, canvas compilation incl. ellipse bit-parity "
        "with the reference setup, manufactured-solution accuracy "
        "gates per family, mixed-geometry co-batching parity, shape "
        "gradients; CPU-fast; runs in tier-1, selectable with "
        "-m geom)",
    )
    config.addinivalue_line(
        "markers",
        "integrity: numerical-integrity / silent-data-corruption suite "
        "(seeded bit-flip campaign across buffers and precisions, "
        "zero-false-alarm pins on clean goldens, byte-identical-HLO "
        "pin for verify_every=0, per-member masking, SDC chaos "
        "scenarios, sentinel cohort pins; CPU-fast; runs in tier-1, "
        "selectable with -m integrity)",
    )
    config.addinivalue_line(
        "markers",
        "placement: device-placement & fault-domain suite (worker→"
        "device binding on the virtual 8-device mesh, batch×mesh "
        "solve_batched(mesh=) parity, device-loss quarantine/rebind, "
        "elastic mesh-shrink ladder, journal recovery across a "
        "topology change; CPU-fast; runs in tier-1, selectable with "
        "-m placement)",
    )
    config.addinivalue_line(
        "markers",
        "contracts: program-contract checker suite (trace-safety lint "
        "rules with positive/suppressed fixtures, HLO identity ledger "
        "round-trip incl. mutated-program detection, registry drift "
        "checks, the `python -m poisson_tpu.contracts` gate; CPU-fast; "
        "runs in tier-1, selectable with -m contracts)",
    )
    config.addinivalue_line(
        "markers",
        "krylov: Krylov-memory suite (block-CG batched mode incl. the "
        "default-path byte pin and rank-deficiency handling, "
        "deflation-basis harvest/cache/warm-start, per-family L2 "
        "floors, serve cohort splits, stale-basis chaos, sentinel "
        "pins; CPU-fast; runs in tier-1, selectable with -m krylov)",
    )
    config.addinivalue_line(
        "markers",
        "session: durable solver-session suite (journal replay to the "
        "committed step boundary, cold-path HLO pin vs the historical "
        "solve, stale-warm audible fallback, heat/design stepping, "
        "one-tree-per-session flight traces, session chaos "
        "invariants, sentinel cohort pins; CPU-fast; runs in tier-1, "
        "selectable with -m session)",
    )
    config.addinivalue_line(
        "markers",
        "mg: geometric-multigrid preconditioning suite "
        "(default-jacobi-path HLO/golden pins, two-grid convergence "
        "factor, V-cycle apply bit-parity under vmap, per-family "
        "manufactured L2 floors, batched/lane/chunked parity, "
        "iteration ~flatness across resolutions, serve cohort split, "
        "sentinel cohort/direction pins; CPU-fast; runs in tier-1, "
        "selectable with -m mg)",
    )
    config.addinivalue_line(
        "markers",
        "forecast: convergence-observatory suite (estimator "
        "arithmetic, snapshot CRC round-trip + torn-file audibility, "
        "history-flag-off HLO byte-pin + golden counts, "
        "predicted-deadline typed-shed ledger invariant under both "
        "engines, re-forecast preemption, calibration bound, "
        "scoreboard dual-source render, sentinel direction pins; "
        "CPU-fast; runs in tier-1, selectable with -m forecast)",
    )
    config.addinivalue_line(
        "markers",
        "router: backend-router & roofline-observatory suite (achieved-"
        "GB/s attribution arithmetic, snapshot CRC round-trip + torn "
        "audibility, analytic cold routing table, misprediction → "
        "demotion → half-open → recovery lifecycle, default-off cohort "
        "byte-compat, routed-backend regress cohort split, scoreboard "
        "dual-source render; CPU-fast; runs in tier-1, selectable "
        "with -m router)",
    )
    config.addinivalue_line(
        "markers",
        "tenancy: tenant-isolation & overload-fairness suite "
        "(default-off byte-compat pin, token-bucket quota arithmetic + "
        "zero-compute typed sheds, DWRR share convergence under both "
        "engines, retry-budget exhaustion typed error, tenant identity "
        "surviving journal replay/--recover with budgets "
        "reconstructed, per-tenant SLO burn, tenant_mix regress cohort "
        "pins, tenant-spec CLI validation; CPU-fast; runs in tier-1, "
        "selectable with -m tenancy)",
    )


def pytest_collection_modifyitems(config, items):
    markexpr = config.getoption("-m", default="")
    if "xslow" in markexpr or os.environ.get("RUN_XSLOW") == "1":
        return
    # slow tests run by default (they are the golden-count regressions);
    # xslow (the 1600×2400 / 2400×3200 goldens, ~2-3 min each) only on demand.
    skip = pytest.mark.skip(reason="xslow: set RUN_XSLOW=1 or -m xslow")
    for item in items:
        if "xslow" in item.keywords:
            item.add_marker(skip)
