"""Device placement & fault domains (PR 12).

Four rails under test, all on the virtual 8-device CPU mesh
(``--xla_force_host_platform_device_count=8``, tests/conftest.py):

1. **Batch×mesh composition** — ``solve_batched(mesh=)`` runs B RHS as
   ONE sharded dispatch and reproduces the unsharded batched driver's
   per-member iteration counts and stop flags exactly, with iterates
   agreeing to reduction-order ULPs (``psum`` of shard-local sums
   associates differently than one full-grid sum — the PR 11 parity
   precedent). The ``mesh=None`` path stays HLO-byte-identical with
   golden counts bit-for-bit.
2. **Placement registry** — worker→device binding, fault-domain
   bookkeeping, epoch versioning, the elastic re-plan ladder.
3. **Device-loss supervision** — a lost device quarantines its whole
   fault domain, recovery lands on survivors, restart rebinds.
4. **Topology-aware recovery** — journal replay across a topology
   change remaps audibly (``placement_remapped`` flight point +
   counter) and types the unmappable, never wedges.
"""

import os

import jax
import numpy as np
import pytest

from poisson_tpu.config import Problem
from poisson_tpu.obs import metrics as obs_metrics
from poisson_tpu.parallel.mesh import make_solver_mesh
from poisson_tpu.solvers.batched import (
    reset_bucket_cache,
    solve_batched,
)
from poisson_tpu.testing.chaos import VirtualClock

pytestmark = pytest.mark.placement


@pytest.fixture(autouse=True)
def _clean_registries():
    obs_metrics.reset()
    reset_bucket_cache()
    yield


def _problem():
    return Problem(M=40, N=40)


# -- batch×mesh composition ---------------------------------------------


@pytest.mark.parametrize("dtype", ["float64", "float32"])
def test_solve_batched_mesh_parity(dtype):
    """The acceptance pin: a forced-host 8-device mesh reproduces the
    unsharded batched per-member iteration counts and flags EXACTLY;
    iterates agree to the documented ULP tolerance (reduction-order
    differences only)."""
    p = _problem()
    mesh = make_solver_mesh()            # 2x4 over the virtual devices
    assert int(np.prod(list(mesh.shape.values()))) == 8
    gates = [1.0, 1.1, 1.3, 0.7]
    ref = solve_batched(p, rhs_gates=gates, dtype=dtype)
    got = solve_batched(p, rhs_gates=gates, dtype=dtype, mesh=mesh)
    assert np.array_equal(np.asarray(got.iterations),
                          np.asarray(ref.iterations))
    assert np.array_equal(np.asarray(got.flag), np.asarray(ref.flag))
    atol = 1e-12 if dtype == "float64" else 1e-5
    np.testing.assert_allclose(np.asarray(got.w), np.asarray(ref.w),
                               atol=atol)


def test_solve_batched_mesh_rhs_stack_and_bucket_cache():
    """The explicit rhs_stack form composes too, padding rides the
    same bucket ladder, and mesh buckets form their OWN bucket-cache
    key family (a sharded executable never claims single-device
    reuse)."""
    p = _problem()
    mesh = make_solver_mesh()
    rng = np.random.default_rng(0)
    stack = np.zeros((3, p.M + 1, p.N + 1))
    stack[:, 1:-1, 1:-1] = rng.normal(size=(3, p.M - 1, p.N - 1))
    ref = solve_batched(p, rhs_stack=stack)
    misses_before = obs_metrics.get("batched.bucket_cache.misses")
    got = solve_batched(p, rhs_stack=stack, mesh=mesh)
    assert obs_metrics.get("batched.bucket_cache.misses") \
        == misses_before + 1            # its own executable family
    got2 = solve_batched(p, rhs_stack=stack, mesh=mesh)
    assert obs_metrics.get("batched.bucket_cache.hits") >= 1
    assert np.array_equal(np.asarray(got.iterations),
                          np.asarray(ref.iterations))
    assert got.w.shape == (3, p.M + 1, p.N + 1)      # padding sliced
    assert np.array_equal(np.asarray(got.w), np.asarray(got2.w))


def test_mesh_none_path_untouched():
    """The flag-off contract: mesh=None lowers to byte-identical HLO
    (the executable key never sees the mesh machinery) and the golden
    count is bit-for-bit."""
    import functools

    from poisson_tpu.solvers.batched import _solve_batched
    from poisson_tpu.solvers.pcg import host_setup

    p = _problem().with_(f_val=1.0)
    a, b, rhs, aux = host_setup(p, "float64", False)
    stack = np.stack([np.asarray(rhs), np.asarray(rhs) * 1.1])
    from poisson_tpu.contracts.hlo import (
        COLLECTIVE_MARKERS,
        assert_no_forbidden,
    )

    lowered = jax.jit(
        functools.partial(_solve_batched.__wrapped__, p, False, 0, 0.0)
    ).lower(a, b, stack, aux).as_text()
    assert_no_forbidden(lowered, COLLECTIVE_MARKERS,
                        context="solve_batched(mesh=None)")
    res = solve_batched(p, rhs_stack=stack)
    assert np.asarray(res.iterations).tolist() == [50, 50]


# -- registry / elastic ladder ------------------------------------------


def test_registry_binding_loss_and_remap():
    from poisson_tpu.serve import DeviceRegistry, PlacementError

    reg = DeviceRegistry(count=4)
    placements = [reg.bind(i) for i in range(6)]   # wraps round-robin
    assert [pl.device_id for pl in placements] == [0, 1, 2, 3, 0, 1]
    assert all(pl.epoch == 1 for pl in placements)
    assert reg.lose(2) and not reg.lose(2)         # idempotent
    assert reg.epoch == 2 and reg.alive() == [0, 1, 3]
    remapped = reg.remap(2)                        # dead -> survivor
    assert remapped.device_id in (0, 1, 3)
    assert obs_metrics.get("serve.placement.remapped") == 1
    same = reg.remap(1)                            # alive -> same slot
    assert same.device_id == 1 and same.epoch == 2
    assert obs_metrics.get("serve.placement.remapped") == 1
    for d in (0, 1, 3):
        reg.lose(d)
    with pytest.raises(PlacementError):
        reg.bind(0)


def test_elastic_plan_ladder():
    from poisson_tpu.serve import (
        RUNG_MESH,
        RUNG_SHED,
        RUNG_SINGLE,
        DeviceRegistry,
        elastic_plan,
    )

    reg = DeviceRegistry(count=4)
    assert elastic_plan(reg, 4) == (RUNG_MESH, [0, 1, 2, 3])
    reg.lose(1)
    rung, plan = elastic_plan(reg, 4)
    assert rung == RUNG_MESH and plan == [0, 2, 3]
    assert obs_metrics.get("serve.degraded.mesh_shrink") == 1
    reg.lose(0)
    reg.lose(3)
    assert elastic_plan(reg, 4) == (RUNG_SINGLE, 2)
    assert obs_metrics.get("serve.degraded.single_device") == 1
    reg.lose(2)
    assert elastic_plan(reg, 4) == (RUNG_SHED, None)
    assert obs_metrics.get("serve.degraded.mesh_shed") == 1


# -- fleet supervision across device loss -------------------------------


def _fleet_policy(**kw):
    from poisson_tpu.serve import (
        DegradationPolicy,
        FleetPolicy,
        RetryPolicy,
        ServicePolicy,
    )

    quiet = DegradationPolicy(shrink_padding_at=9.0,
                              cap_iterations_at=9.0,
                              downshift_precision_at=9.0)
    fleet = FleetPolicy(workers=kw.pop("workers", 2),
                        devices=kw.pop("devices", 2),
                        quarantine_seconds=0.02,
                        recovery_backoff=0.02)
    return ServicePolicy(
        capacity=16, max_batch=4, degradation=quiet, fleet=fleet,
        retry=RetryPolicy(max_attempts=3, backoff_base=0.02,
                          backoff_cap=0.1), **kw)


def test_device_loss_quarantines_fault_domain_and_rebinds():
    """Two workers SHARE a device (oversubscribed fault domain): one
    DeviceLossError must quarantine both — the domain dies whole — and
    both must rebind to the surviving device at restart."""
    from poisson_tpu.serve import SolveRequest, SolveService
    from poisson_tpu.testing.faults import device_loss_fault

    vc = VirtualClock()
    holder = {}
    # 3 workers over 2 devices: workers 0 and 2 share device 0.
    svc = SolveService(
        _fleet_policy(workers=3, devices=2),
        clock=vc, sleep=vc.sleep, seed=0,
        worker_fault=device_loss_fault(
            {0}, lambda wid: holder["svc"].worker_device(wid)))
    holder["svc"] = svc
    assert [svc.worker_device(i) for i in range(3)] == [0, 1, 0]
    p = _problem()
    for i in range(4):
        svc.submit(SolveRequest(request_id=i, problem=p,
                                rhs_gate=1.0 + i / 10))
    outs = svc.drain()
    stats = svc.stats()
    assert stats["lost"] == 0 and all(o.converged for o in outs)
    assert obs_metrics.get("serve.fleet.device_losses") == 1
    # BOTH cohabitants of device 0 were quarantined by the one loss.
    assert obs_metrics.get("serve.fleet.quarantines") == 2
    assert stats["placement"]["lost"] == [0]
    # Rebinding happens at RESTART: release the quarantines (the drain
    # may finish on the survivor before the cooldown does) and let the
    # pump run the restarts.
    vc.advance(1.0)
    svc.pump()
    stats = svc.stats()
    assert set(stats["placement"]["bindings"].values()) == {1}
    assert obs_metrics.get("serve.placement.rebinds") == 2


def test_hw_cohort_keys_on_device():
    """SDC suspicion indicts the PART: the hardware cohort carries the
    dispatching worker's (device_kind, device_id), so suspicion on one
    device never arms defensive verification on another."""
    from poisson_tpu.serve import SolveService

    svc = SolveService(_fleet_policy(workers=2, devices=2))
    svc._active_worker = svc._pool.workers[0]
    c0 = svc._hw_cohort()
    svc._active_worker = svc._pool.workers[1]
    c1 = svc._hw_cohort()
    svc._active_worker = None
    assert c0 != c1 and c0[2] == 0 and c1[2] == 1
    svc._suspect_hw.add(c0)
    svc._active_worker = svc._pool.workers[1]
    assert svc._hw_cohort() not in svc._suspect_hw


def test_pinned_request_runs_on_its_device_or_types():
    from poisson_tpu.serve import SolveRequest, SolveService

    vc = VirtualClock()
    svc = SolveService(_fleet_policy(workers=2, devices=2),
                       clock=vc, sleep=vc.sleep, seed=0)
    p = _problem()
    svc.submit(SolveRequest(request_id="on1", problem=p, device_id=1))
    (out,) = svc.drain()
    assert out.converged
    with pytest.raises(ValueError, match="outside the fleet topology"):
        svc.submit(SolveRequest(request_id="bad", problem=p,
                                device_id=9))
    # Alive but unstaffed: the pin could never be served — a caller
    # bug, loud at admission.
    svc_small = SolveService(_fleet_policy(workers=1, devices=2),
                             clock=vc, sleep=vc.sleep, seed=0)
    with pytest.raises(ValueError, match="no worker bound"):
        svc_small.submit(SolveRequest(request_id="unstaffed", problem=p,
                                      device_id=1))
    svc._registry.lose(1)
    svc.submit(SolveRequest(request_id="ghost", problem=p, device_id=1))
    (ghost,) = svc.drain()
    assert ghost.kind == "error" and ghost.error_type == "placement"


# -- journal recovery across a topology change --------------------------


def test_recover_on_smaller_topology(tmp_path):
    """Kill with work in flight on an 8-slot topology, --recover on a
    4-slot one: the invariant closes, remapped requests carry a
    ``placement_remapped`` flight point on the JSONL rails, and an
    unmappable pin is a typed error, not a wedge."""
    from poisson_tpu import obs
    from poisson_tpu.obs import trace as obs_trace
    from poisson_tpu.serve import (
        SCHED_CONTINUOUS,
        SolveJournal,
        SolveRequest,
        SolveService,
        replay_journal,
    )

    trace_dir = str(tmp_path / "flight")
    obs.configure(trace_dir=trace_dir)
    try:
        p = _problem()
        path = str(tmp_path / "serve.journal")
        vc = VirtualClock()
        # Workers 4..5 land on devices 4..5 — slots a 4-device recovery
        # topology will NOT have.
        policy_a = _fleet_policy(workers=6, devices=8,
                                 scheduling=SCHED_CONTINUOUS,
                                 refill_chunk=10)
        journal_a = SolveJournal(path, clock=vc)
        svc_a = SolveService(policy_a, clock=vc, sleep=vc.sleep, seed=0,
                             journal=journal_a)
        # Pin work onto the high slots so its journal placement records
        # name devices the recovery topology lacks.
        svc_a.submit(SolveRequest(request_id="high", problem=p,
                                  device_id=5, chunk=10))
        svc_a.submit(SolveRequest(request_id="low", problem=p,
                                  rhs_gate=1.1))
        svc_a.pump()                       # "high" dispatches on dev 5
        # Wait — chunked solo dispatch runs to completion in one pump;
        # instead leave lane work resident: pump only once more so
        # "low" splices but does not finish.
        svc_a.pump()
        svc_a.submit(SolveRequest(request_id="pin5", problem=p,
                                  device_id=5))
        journal_a.close()                  # crash
        replay = replay_journal(path)
        pend = {pr.request.request_id: pr for pr in replay.pending}
        assert "pin5" in pend
        policy_b = _fleet_policy(workers=2, devices=4,
                                 scheduling=SCHED_CONTINUOUS,
                                 refill_chunk=10)
        journal_b = SolveJournal(path, clock=vc)
        svc_b = SolveService.recover(journal_b, policy_b, clock=vc,
                                     sleep=vc.sleep, seed=0)
        svc_b.drain()
        outs = {o.request_id: o for o in svc_b.outcomes()}
        stats = svc_b.stats()
        journal_b.close()
        assert stats["lost"] == 0
        assert outs["pin5"].kind == "error"
        assert outs["pin5"].error_type == "placement"
        assert "does not exist on this topology" in outs["pin5"].message
        # Any request the journal shows in flight on a dead slot was
        # remapped audibly.
        in_flight_high = [pr for pr in replay.pending
                          if pr.in_flight and pr.device_id is not None
                          and pr.device_id >= 4]
        assert obs_metrics.get("serve.placement.remapped") \
            == len(in_flight_high)
        final = replay_journal(path)
        assert not final.pending and not final.duplicate_outcomes
    finally:
        obs.finalize()
    if obs_metrics.get("serve.placement.remapped"):
        events = obs_trace.load_events(trace_dir)
        points = [e for e in events
                  if e.get("name") == "flight.point"
                  and e.get("point") == "placement_remapped"]
        assert points, "placement_remapped flight point missing from " \
                       "the JSONL rails"


def test_journal_records_carry_placement_epoch(tmp_path):
    from poisson_tpu.serve import (
        SolveJournal,
        SolveRequest,
        SolveService,
        replay_journal,
    )

    vc = VirtualClock()
    path = str(tmp_path / "epoch.journal")
    journal = SolveJournal(path, clock=vc)
    svc = SolveService(_fleet_policy(workers=2, devices=2),
                       clock=vc, sleep=vc.sleep, seed=0, journal=journal)
    p = _problem()
    svc.submit(SolveRequest(request_id="e0", problem=p))
    svc.drain()
    journal.close()
    import json

    kinds = {}
    with open(path) as fh:
        for line in fh:
            rec = json.loads(line)
            kinds.setdefault(rec["kind"], []).append(rec)
    assert kinds["topology"][0]["devices"] == 2
    assert kinds["topology"][0]["epoch"] == 1
    assert kinds["dispatch"][0]["epoch"] == 1
    assert kinds["dispatch"][0]["device"] in (0, 1)
    replay = replay_journal(path)
    assert replay.topology["devices"] == 2


# -- bench plumbing ------------------------------------------------------


def test_fleet_bench_device_churn_record(tmp_path):
    """bench.py --serve --workers --devices --kill-device-at: the run
    survives the loss with zero lost requests and the record carries
    the topology + fault-load cohort discriminators regress.py keys
    on."""
    import json
    import subprocess
    import sys

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    proc = subprocess.run(
        [sys.executable, "bench.py", "--serve", "8", "--workers", "2",
         "--devices", "2", "--kill-device-at", "0", "40", "40"],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stderr[-2000:]
    record = json.loads(proc.stdout.strip().splitlines()[-1])
    det = record["detail"]
    assert det["lost"] == 0 and det["every_request_accounted"]
    assert det["devices"] == 2
    assert det["device_topology"] == "2xcpu"
    assert det["device_losses"] == 1
    assert det["fault_load"] == "kill_device@0"
    # The sentinel cohorts on the topology: same record with a
    # different topology string is a DIFFERENT cohort.
    import pathlib
    import sys as _sys

    root = pathlib.Path(__file__).resolve().parents[1]
    if str(root) not in _sys.path:
        _sys.path.insert(0, str(root))
    from benchmarks import regress

    rec = regress.record_from_result(record, "test")
    other = dict(rec, device_topology="1xcpu")
    assert regress.cohort_key(rec) != regress.cohort_key(other)
