"""Sharded communication-avoiding (s=2) path on the virtual 8-device CPU
mesh, interpret mode.

The decisive property under test: the width-2 halo scheme — two-deep
rings on r and pprev, corners filled transitively by the rows-then-
columns exchange order (module doc of ``parallel.pallas_ca_sharded``) —
must make every mesh shape, including 1D and uneven-block
decompositions, agree with the single-device paths on iteration count
and solution. A corner or depth-2 bug would show up as a wrong count or
a solution error at shard boundaries.
"""

import jax
import numpy as np
import pytest

from poisson_tpu.config import Problem
from poisson_tpu.ops.pallas_ca import ca_cg_solve
from poisson_tpu.parallel import make_solver_mesh
from poisson_tpu.parallel.pallas_ca_sharded import ca_cg_solve_sharded
from poisson_tpu.solvers.pcg import pcg_solve


@pytest.mark.parametrize("ndev", [1, 2, 4, 8])
def test_matches_oracle_across_mesh_shapes(ndev):
    p = Problem(M=40, N=40)
    ref = pcg_solve(p)  # fp64 oracle
    mesh = make_solver_mesh(jax.devices()[:ndev])
    got = ca_cg_solve_sharded(p, mesh)
    assert abs(int(got.iterations) - int(ref.iterations)) <= 1
    np.testing.assert_allclose(
        np.asarray(got.w, np.float64), np.asarray(ref.w), atol=2e-5
    )


def test_matches_single_device_ca():
    """A/B against the single-device CA path: same pair recurrences
    (shared ``pair_scalars``), same fp32 iterate sequence up to
    reduction order."""
    p = Problem(M=40, N=40)
    single = ca_cg_solve(p)
    mesh = make_solver_mesh(jax.devices()[:4])
    sharded = ca_cg_solve_sharded(p, mesh)
    assert int(sharded.iterations) == int(single.iterations) == 50
    np.testing.assert_allclose(
        np.asarray(sharded.w), np.asarray(single.w), atol=2e-5
    )


def test_uneven_blocks_and_lane_padding():
    """Interior 36×28 over a 2×4 mesh: row padding from the bm round-up,
    column padding from LANE alignment, and a 2-deep ring crossing both
    kinds of seams."""
    p = Problem(M=37, N=29)
    ref = pcg_solve(p)
    mesh = make_solver_mesh(jax.devices()[:8])
    got = ca_cg_solve_sharded(p, mesh)
    assert abs(int(got.iterations) - int(ref.iterations)) <= 1
    np.testing.assert_allclose(
        np.asarray(got.w, np.float64), np.asarray(ref.w), atol=2e-5
    )


@pytest.mark.parametrize("grid", [(1, 4), (4, 1)])
def test_1d_meshes(grid):
    """1D decompositions exercise the ppermute zero-fill (Dirichlet)
    edges of the width-2 exchange on one axis at a time."""
    p = Problem(M=24, N=24)
    ref = pcg_solve(p)
    mesh = make_solver_mesh(jax.devices()[:4], grid=grid)
    got = ca_cg_solve_sharded(p, mesh)
    assert abs(int(got.iterations) - int(ref.iterations)) <= 1
    np.testing.assert_allclose(
        np.asarray(got.w, np.float64), np.asarray(ref.w), atol=2e-5
    )


@pytest.mark.slow
def test_golden_400x600_on_8dev_mesh():
    p = Problem(M=400, N=600)
    mesh = make_solver_mesh(jax.devices())
    got = ca_cg_solve_sharded(p, mesh)
    assert int(got.iterations) == 546
    assert float(got.diff) < 1e-6


def test_matches_sharded_fused():
    """Cross-algorithm A/B on the same mesh: the CA pair iteration and
    the fused 2-sweep path must agree on count and solution."""
    from poisson_tpu.parallel.pallas_sharded import pallas_cg_solve_sharded

    p = Problem(M=40, N=40)
    mesh = make_solver_mesh(jax.devices()[:4])
    ca = ca_cg_solve_sharded(p, mesh)
    fused = pallas_cg_solve_sharded(p, mesh)
    assert int(ca.iterations) == int(fused.iterations)
    np.testing.assert_allclose(
        np.asarray(ca.w), np.asarray(fused.w), atol=2e-5
    )


def test_parallel_grid_matches_sequential():
    """The parallel tile-grid hint on the sharded CA path is pure
    scheduling: bit-identical solution on the same mesh."""
    p = Problem(M=40, N=40)
    mesh = make_solver_mesh(jax.devices()[:4], grid=(2, 2))
    r_seq = ca_cg_solve_sharded(p, mesh)
    r_par = ca_cg_solve_sharded(p, mesh, parallel=True)
    assert int(r_par.iterations) == int(r_seq.iterations) == 50
    np.testing.assert_array_equal(np.asarray(r_par.w), np.asarray(r_seq.w))


def test_explicit_thin_strips():
    """bm=8 forces multi-strip shards (nb > 1): the whole-window Gram
    output and the ±2 band gating must hold across strip seams inside a
    shard, not only at shard boundaries."""
    from poisson_tpu.parallel.pallas_ca_sharded import ca_shard_spec

    p = Problem(M=40, N=40)
    mesh = make_solver_mesh(jax.devices()[:4], grid=(2, 2))
    assert ca_shard_spec(p, 2, 2, bm=8).cv.nb > 1
    ref = ca_cg_solve_sharded(p, mesh)
    got = ca_cg_solve_sharded(p, mesh, bm=8)
    assert int(got.iterations) == int(ref.iterations) == 50
    np.testing.assert_allclose(
        np.asarray(got.w), np.asarray(ref.w), rtol=0, atol=5e-6
    )


def test_rhs_gate_is_bit_exact():
    p = Problem(M=40, N=40)
    mesh = make_solver_mesh(jax.devices()[:4])
    r1 = ca_cg_solve_sharded(p, mesh)
    r2 = ca_cg_solve_sharded(p, mesh, rhs_gate=np.float32(1.0))
    assert int(r1.iterations) == int(r2.iterations)
    assert np.array_equal(np.asarray(r1.w), np.asarray(r2.w))


def test_checkpointed_chunked_equals_oneshot(tmp_path):
    from poisson_tpu.parallel.pallas_ca_sharded import (
        ca_cg_solve_sharded_checkpointed,
    )

    p = Problem(M=40, N=40)
    mesh = make_solver_mesh(jax.devices()[:4])
    ref = ca_cg_solve_sharded(p, mesh)
    got = ca_cg_solve_sharded_checkpointed(
        p, mesh, str(tmp_path / "ck.npz"), chunk=7
    )
    assert int(got.iterations) == int(ref.iterations) == 50
    np.testing.assert_array_equal(np.asarray(got.w), np.asarray(ref.w))
    assert not (tmp_path / "ck.npz").exists()


def test_checkpointed_kill_and_resume_cross_algorithm(tmp_path):
    """A partial FUSED-sharded checkpoint resumes on the sharded CA path
    (and the combined solve still converges at the golden count): the
    pending-pair ↔ updated-direction mapping keeps the portable format
    cross-ALGORITHM, not just cross-backend."""
    from poisson_tpu.parallel.pallas_ca_sharded import (
        ca_cg_solve_sharded_checkpointed,
    )
    from poisson_tpu.parallel.pallas_sharded import (
        pallas_cg_solve_sharded_checkpointed,
    )

    p = Problem(M=40, N=40)
    mesh = make_solver_mesh(jax.devices()[:4])
    path = str(tmp_path / "ck.npz")
    partial = pallas_cg_solve_sharded_checkpointed(
        p.with_(max_iter=20), mesh, path, chunk=10
    )
    assert int(partial.iterations) == 20
    ref = ca_cg_solve_sharded(p, mesh)
    resumed = ca_cg_solve_sharded_checkpointed(p, mesh, path, chunk=10)
    assert int(resumed.iterations) == int(ref.iterations) == 50
    np.testing.assert_allclose(
        np.asarray(resumed.w), np.asarray(ref.w), rtol=0, atol=1e-6
    )
    # ...and the reverse: a partial CA-sharded checkpoint resumes on the
    # single-device XLA path.
    from poisson_tpu.solvers.checkpoint import pcg_solve_checkpointed

    path2 = str(tmp_path / "ck2.npz")
    ca_cg_solve_sharded_checkpointed(
        p.with_(max_iter=15), mesh, path2, chunk=6
    )
    got = pcg_solve_checkpointed(p, path2, chunk=20, dtype="float32")
    assert int(got.iterations) == 50
    np.testing.assert_allclose(
        np.asarray(got.w), np.asarray(ref.w), rtol=0, atol=1e-6
    )
