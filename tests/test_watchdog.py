"""Watchdog heartbeat/timeout and multihost retry-with-backoff (tier-1,
CPU-only; part of the fault-injection suite)."""

import json
import os
import time
import warnings

import pytest

from poisson_tpu.parallel.watchdog import Watchdog

pytestmark = pytest.mark.faults


def _wait_for(predicate, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return predicate()


def test_heartbeat_file_written_atomically(tmp_path):
    hb = str(tmp_path / "hb.json")
    wd = Watchdog(heartbeat_path=hb)
    with wd:
        wd.beat(k=42, diff=1e-3)
        payload = json.loads(open(hb).read())
    assert payload["k"] == 42
    assert payload["beats"] == 1
    assert payload["pid"] == os.getpid()
    # No tmp droppings from the atomic replace.
    assert [f for f in os.listdir(tmp_path) if ".tmp" in f] == []


def test_regular_beats_keep_the_monitor_quiet():
    fired = []
    wd = Watchdog(timeout=0.3, poll_interval=0.05,
                  on_timeout=fired.append)
    with wd:
        for _ in range(8):
            time.sleep(0.05)
            wd.beat()
    assert not wd.fired
    assert fired == []


def test_stall_fires_timeout_with_diagnostics(tmp_path):
    hb = str(tmp_path / "hb.json")
    fired = []
    wd = Watchdog(heartbeat_path=hb, timeout=0.15, poll_interval=0.03,
                  on_timeout=fired.append)
    with wd:
        wd.beat(k=7, diff=0.5)
        assert _wait_for(lambda: wd.fired)     # no further beats: stall
    diag = fired[0]
    assert diag["timeout_seconds"] == 0.15
    # elapsed is rounded to 3 decimals in the diagnostics, so a fire at
    # exactly the timeout boundary can tie it — >= is the honest bound.
    assert diag["elapsed_seconds"] >= 0.15
    assert diag["last_progress"] == {"k": 7, "diff": 0.5}
    # Diagnostics file lands next to the heartbeat for the post-mortem.
    stalled = json.loads(open(hb + ".stalled.json").read())
    assert stalled["last_progress"]["k"] == 7


def test_timeout_fires_once_and_stop_joins():
    fired = []
    wd = Watchdog(timeout=0.1, poll_interval=0.02, on_timeout=fired.append)
    wd.start()
    assert _wait_for(lambda: wd.fired)
    time.sleep(0.15)                            # would double-fire if buggy
    wd.stop()
    assert len(fired) == 1


def test_raise_if_fired_converts_to_solve_timeout():
    """The chunked drivers turn a watchdog interrupt into the typed
    SolveTimeout (diagnostics attached); an unfired watchdog is a no-op."""
    from poisson_tpu.parallel.watchdog import SolveTimeout

    wd = Watchdog(timeout=0.1, poll_interval=0.02, on_timeout=lambda d: None)
    wd.raise_if_fired()                         # not fired: no-op
    with wd:
        assert _wait_for(lambda: wd.fired)
    with pytest.raises(SolveTimeout) as exc_info:
        wd.raise_if_fired()
    assert exc_info.value.diagnostics["timeout_seconds"] == 0.1


def test_watchdog_wired_into_chunked_solver(tmp_path):
    from poisson_tpu.config import Problem
    from poisson_tpu.solvers.checkpoint import pcg_solve_checkpointed

    hb = str(tmp_path / "hb.json")
    fired = []
    wd = Watchdog(heartbeat_path=hb, timeout=300.0,
                  on_timeout=fired.append)
    res = pcg_solve_checkpointed(
        Problem(M=40, N=40), str(tmp_path / "ck.npz"), chunk=10,
        watchdog=wd,
    )
    assert int(res.iterations) == 50
    assert fired == []
    payload = json.loads(open(hb).read())
    assert payload["beats"] >= 5                # one per chunk
    assert payload["k"] == 50
    # run_chunked stopped the watchdog: the monitor thread is gone.
    assert wd._thread is None


class TestMultihostRetry:
    """initialize_multihost retries transient coordinator failures with
    backoff, degrades to single-host when env-driven, and still fails
    loudly for explicit clusters (monkeypatched init — no real cluster)."""

    @pytest.fixture
    def multihost(self, monkeypatch):
        import poisson_tpu.parallel.multihost as mh

        monkeypatch.setattr(mh, "_initialized", False)
        return mh

    def test_transient_failure_retries_then_succeeds(self, multihost,
                                                     monkeypatch):
        import jax

        calls = {"n": 0}

        def flaky_init(**kw):
            calls["n"] += 1
            if calls["n"] < 3:
                raise RuntimeError("connection refused by coordinator")

        naps = []
        monkeypatch.setattr(jax.distributed, "initialize", flaky_init)
        with pytest.warns(RuntimeWarning, match="retry"):
            idx = multihost.initialize_multihost(
                backoff_seconds=0.1, sleep=naps.append, seed=0
            )
        assert idx == 0
        assert calls["n"] == 3
        # Exponential backoff with seeded jitter over [1-jitter, 1]:
        # each delay stays under its exponential envelope and above the
        # jitter floor — a fleet of hosts retrying a dead coordinator
        # must not thunder back in lockstep.
        assert len(naps) == 2
        for delay, envelope in zip(naps, (0.1, 0.2)):
            assert envelope * 0.5 <= delay <= envelope
        # Seeded → reproducible: the same seed yields the same schedule.
        calls["n"] = 0
        naps2 = []
        multihost._initialized = False
        with pytest.warns(RuntimeWarning, match="retry"):
            multihost.initialize_multihost(
                backoff_seconds=0.1, sleep=naps2.append, seed=0)
        assert naps2 == naps
        # Different seeds (different hosts) decorrelate.
        calls["n"] = 0
        naps3 = []
        multihost._initialized = False
        with pytest.warns(RuntimeWarning, match="retry"):
            multihost.initialize_multihost(
                backoff_seconds=0.1, sleep=naps3.append, seed=1)
        assert naps3 != naps

    def test_env_driven_exhaustion_degrades_to_single_host(self, multihost,
                                                           monkeypatch):
        import jax

        def always_down(**kw):
            raise RuntimeError("deadline exceeded connecting to coordinator")

        monkeypatch.setattr(jax.distributed, "initialize", always_down)
        with pytest.warns(RuntimeWarning, match="single-host"):
            idx = multihost.initialize_multihost(
                max_retries=2, backoff_seconds=0.01, sleep=lambda s: None
            )
        assert idx == 0                         # usable, local-only world

    def test_explicit_cluster_exhaustion_raises(self, multihost,
                                                monkeypatch):
        import jax

        def always_down(**kw):
            raise RuntimeError("connection timed out")

        monkeypatch.setattr(jax.distributed, "initialize", always_down)
        with pytest.raises(RuntimeError, match="timed out"), \
                warnings.catch_warnings():
            warnings.simplefilter("ignore")
            multihost.initialize_multihost(
                coordinator="10.0.0.1:1234", num_processes=4, process_id=1,
                max_retries=1, backoff_seconds=0.01, sleep=lambda s: None,
            )

    def test_config_errors_do_not_retry(self, multihost, monkeypatch):
        import jax

        calls = {"n": 0}

        def bad_config(**kw):
            calls["n"] += 1
            raise RuntimeError(
                "jax.distributed.initialize must be called before any "
                "backend is initialized"
            )

        monkeypatch.setattr(jax.distributed, "initialize", bad_config)
        with pytest.raises(RuntimeError, match="first JAX call"):
            multihost.initialize_multihost()
        assert calls["n"] == 1                  # no retry on ordering bugs
