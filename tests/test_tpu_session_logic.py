"""Unit tests for benchmarks/tpu_session.py's decision logic.

The session itself needs the real chip, but its three decision mechanisms
are pure logic that has already eaten review findings twice — these tests
pin them:

- ``decide_backend_chain``: which Pallas backends are credited as
  hardware-proven, in what order, when the forced re-measurements fire,
  and when the affirmative-negative empty chain is written.
- ``Session`` resume filtering: which prior log entries may satisfy a
  re-armed session.
- ``Session.run`` skip/replay behavior around the wedge-defense abort.
- ``bench._measured_chain``: artifact adoption, including corrupt and
  unknown-name artifacts.

No test here touches a JAX backend (no device, no tunnel).
"""

from __future__ import annotations

import importlib.util
import json
import sys

import pytest

_ROOT = __file__.rsplit("/tests/", 1)[0]
_spec = importlib.util.spec_from_file_location(
    "tpu_session", _ROOT + "/benchmarks/tpu_session.py"
)
tpu_session = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(tpu_session)


def _bench(backend, value, platform="tpu"):
    return {"value": value,
            "detail": {"backend": backend, "platform": platform}}


def _no_runner():
    raise AssertionError("forced bench runner must not be called")


def _decide(bench800, ca, fused_probe_ok=False,
            ca_runner=_no_runner, fused_runner=_no_runner,
            xla_runner=None):
    return tpu_session.decide_backend_chain(
        bench800, ca, fused_probe_ok, ca_runner, fused_runner,
        xla_runner=xla_runner,
    )


class TestDecideBackendChain:
    def test_fused_only(self):
        got = _decide(_bench("pallas_fused", 40000.0), {"ok": False})
        assert got["chain"] == ["pallas_fused"]
        assert got["evidence"] == {"pallas_fused": 40000.0}

    def test_ca_promoted_when_faster(self):
        ca = {"ok": True, "flagship_iters": 989}
        got = _decide(_bench("pallas_fused", 40000.0), ca,
                      ca_runner=lambda: _bench("pallas_ca", 55000.0))
        assert got["chain"] == ["pallas_ca", "pallas_fused"]
        assert got["evidence"] == {"pallas_ca": 55000.0,
                                   "pallas_fused": 40000.0}

    def test_ca_behind_when_slower(self):
        ca = {"ok": True, "flagship_iters": 989}
        got = _decide(_bench("pallas_fused", 40000.0), ca,
                      ca_runner=lambda: _bench("pallas_ca", 30000.0))
        assert got["chain"] == ["pallas_fused", "pallas_ca"]

    def test_bench_on_ca_does_not_credit_fused(self):
        # bench800 ran pallas_ca (a prior chain led with it); the CA probe
        # then timed out and the kernel probe was inconclusive. fused has
        # NO evidence this session and must not enter the chain.
        got = _decide(_bench("pallas_ca", 50000.0), {"timeout": True})
        assert got["chain"] == ["pallas_ca"]
        assert got["evidence"] == {"pallas_ca": 50000.0}

    def test_fused_probe_triggers_forced_measurement(self):
        # The ratchet-breaker: bench800 ran pallas_ca, but the kernel
        # probe proved the fused path healthy — fused gets a bench-grade
        # forced measurement and re-enters the chain.
        got = _decide(_bench("pallas_ca", 50000.0), {"timeout": True},
                      fused_probe_ok=True,
                      fused_runner=lambda: _bench("pallas_fused", 42000.0))
        assert got["chain"] == ["pallas_ca", "pallas_fused"]

    def test_forced_fused_demotion_is_not_credited(self):
        got = _decide(_bench("pallas_ca", 50000.0), {"timeout": True},
                      fused_probe_ok=True,
                      fused_runner=lambda: {"ok": False, "rc": 1})
        assert got["chain"] == ["pallas_ca"]

    def test_forced_ca_bench_demotion_is_not_credited(self):
        ca = {"ok": True, "flagship_iters": 989}
        got = _decide(_bench("pallas_fused", 40000.0), ca,
                      ca_runner=lambda: {"ok": False, "rc": 1})
        assert got["chain"] == ["pallas_fused"]

    def test_all_demoted_on_tpu_writes_empty_chain(self):
        got = _decide(_bench("xla", 23000.0), {"ok": False, "error": "x"})
        assert got["chain"] == []

    def test_probe_rescues_even_after_bench_demotion(self):
        # bench800 demoted to xla, but the kernel probe passed (e.g. the
        # gate switched layouts after bench800's chain had already
        # demoted): the forced measurement still gives fused its chance
        # before any negative verdict.
        got = _decide(_bench("xla", 23000.0), {"ok": False},
                      fused_probe_ok=True,
                      fused_runner=lambda: _bench("pallas_fused", 41000.0))
        assert got["chain"] == ["pallas_fused"]

    def test_xla_winning_empties_the_chain_with_evidence(self):
        got = _decide(_bench("pallas_fused", 20000.0), {"ok": False},
                      xla_runner=lambda: _bench("xla", 24000.0))
        assert got["chain"] == []
        assert got["evidence"] == {"pallas_fused": 20000.0, "xla": 24000.0}
        assert "xla measured fastest" in got["note"]

    def test_xla_losing_keeps_the_chain_and_the_comparison(self):
        got = _decide(_bench("pallas_fused", 40000.0), {"ok": False},
                      xla_runner=lambda: _bench("xla", 24000.0))
        assert got["chain"] == ["pallas_fused"]
        assert got["evidence"] == {"pallas_fused": 40000.0, "xla": 24000.0}

    def test_failed_xla_measurement_keeps_proven_chain(self):
        got = _decide(_bench("pallas_fused", 20000.0), {"ok": False},
                      xla_runner=lambda: {"ok": False, "timeout": True})
        assert got["chain"] == ["pallas_fused"]

    def test_cpu_downgraded_xla_run_is_not_hardware_evidence(self):
        # The forced xla bench wedged mid-session and CPU-downgraded:
        # its ~160 MLUPS number must not enter the artifact, and the
        # proven Pallas chain must not be compared against it.
        got = _decide(_bench("pallas_fused", 20000.0), {"ok": False},
                      xla_runner=lambda: _bench("xla", 160.0,
                                                platform="cpu"))
        assert got["chain"] == ["pallas_fused"]
        assert "xla" not in got["evidence"]

    def test_bench800_xla_value_reused_without_runner(self):
        # bench800 itself ran xla (demoted chain); a probe-rescued fused
        # measurement still gets compared against that xla number with no
        # second forced xla run.
        got = _decide(_bench("xla", 24000.0), {"ok": False},
                      fused_probe_ok=True,
                      fused_runner=lambda: _bench("pallas_fused", 20000.0),
                      xla_runner=_no_runner)
        assert got["chain"] == []
        assert got["evidence"] == {"pallas_fused": 20000.0, "xla": 24000.0}

    def test_cpu_fallback_makes_no_statement(self):
        got = _decide(_bench("xla", 160.0, platform="cpu"), None)
        assert got is None

    def test_bench_timeout_makes_no_statement(self):
        got = _decide({"ok": False, "timeout": True}, None)
        assert got is None

    def test_ca_suspect_iterations_not_probed_further(self):
        ca = {"ok": True, "flagship_iters": 1200}
        got = _decide(_bench("pallas_fused", 40000.0), ca)
        assert got["chain"] == ["pallas_fused"]

    def test_zero_valued_bench_is_still_evidence(self):
        # A legitimate 0-valued record must not be dropped by a
        # truthiness filter (round-4 advisor finding); with no xla
        # comparison available it still proves the backend ran.
        got = _decide(_bench("pallas_fused", 0.0), {"ok": False})
        assert got["chain"] == ["pallas_fused"]
        assert got["evidence"] == {"pallas_fused": 0.0}

    def test_hardware_record_without_value_logged_not_silent(self, capsys):
        rec = {"detail": {"backend": "pallas_fused", "platform": "tpu"}}
        got = _decide(rec, {"ok": False})
        assert got is None
        assert "record excluded" in capsys.readouterr().out


class TestMeasuredChainAdoption:
    @pytest.fixture()
    def bench_mod(self, tmp_path, monkeypatch):
        sys.path.insert(0, _ROOT)
        import bench
        monkeypatch.setattr(
            bench, "BACKEND_CHAIN_PATH", tmp_path / "backend_chain.json"
        )
        return bench

    def _write(self, bench_mod, content: str):
        bench_mod.BACKEND_CHAIN_PATH.write_text(content)

    def test_missing_artifact(self, bench_mod):
        assert bench_mod._measured_chain() is None

    def test_adopts_known_names_in_order(self, bench_mod):
        self._write(bench_mod, json.dumps(
            {"chain": ["pallas_ca", "bogus", "pallas_fused"], "at": "T"}
        ))
        assert bench_mod._measured_chain() == ["pallas_ca", "pallas_fused"]

    def test_explicit_empty_chain_is_negative_evidence(self, bench_mod):
        self._write(bench_mod, json.dumps({"chain": [], "at": "T"}))
        assert bench_mod._measured_chain() == []

    def test_unknown_names_only_falls_back_to_default(self, bench_mod):
        # Positive evidence this build cannot use is NOT negative
        # evidence: fall back to the static chain.
        self._write(bench_mod, json.dumps({"chain": ["pallas_v2"]}))
        assert bench_mod._measured_chain() is None

    @pytest.mark.parametrize("content", ["null", "3", '"x"', "{", "",
                                         '{"chain": 7}'])
    def test_corrupt_artifact_falls_back(self, bench_mod, content):
        self._write(bench_mod, content)
        assert bench_mod._measured_chain() is None

    def test_per_grid_good_paths(self, bench_mod):
        # Every published grid gets its own committed high-water-mark
        # artifact; the flagship keeps the legacy name (driver contract).
        assert bench_mod._grid_good_path(800, 1200) is bench_mod.GOOD_PATH
        assert bench_mod._grid_good_path(1600, 2400).name == \
            "BENCH_TPU_GOOD_1600x2400.json"
        assert bench_mod._grid_good_path(2400, 3200).name == \
            "BENCH_TPU_GOOD_2400x3200.json"

    def test_read_good_takes_a_path(self, bench_mod, tmp_path):
        p = tmp_path / "g.json"
        p.write_text(json.dumps({"value": 5.0}))
        got = bench_mod._read_good(p)
        assert got["last"]["value"] == 5.0 and got["best"]["value"] == 5.0
        assert bench_mod._read_good(tmp_path / "missing.json") == {}


class TestSummarizerBandwidthCheck:
    """The summarizer's passes-at-ceiling column is the working form of
    BENCH.md's physical-consistency rule; pin it against a real sane
    record and a round-2-style overlap artifact."""

    def _mod(self):
        spec = importlib.util.spec_from_file_location(
            "summarize_session",
            _ROOT + "/benchmarks/summarize_session.py",
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_sane_and_suspect_verdicts(self):
        m = self._mod()
        sane = {"grid": [800, 1200], "solve_seconds": 0.0397,
                "iterations": 989, "backend": "xla", "platform": "tpu",
                "device_kind": "TPU v5 lite"}
        budget, verdict = m._passes_budget(sane)
        assert float(budget) == pytest.approx(8.6, abs=0.1)
        assert verdict == " sane"
        # The withdrawn round-2 flagship row: 0.0211 s / 989 iters on the
        # fused kernels — admits ~4.5 passes where the kernels move 14.7.
        r2 = {"grid": [800, 1200], "solve_seconds": 0.0211,
              "iterations": 989, "backend": "pallas_fused",
              "platform": "tpu", "device_kind": "TPU v5e"}
        budget, verdict = m._passes_budget(r2)
        assert float(budget) < 5.0
        assert "SUSPECT" in verdict

    def test_verdict_gated_on_v5e(self):
        """The 0.82 TB/s ceiling is a v5e number; a session captured on
        another TPU generation prints the passes figure with no verdict
        instead of mislabeling every row (round-5 advice)."""
        m = self._mod()
        base = {"grid": [800, 1200], "solve_seconds": 0.0397,
                "iterations": 989, "backend": "xla", "platform": "tpu"}
        for kind in ("TPU v4", "TPU v5p", "TPU v5", "TPU v6e", None):
            budget, verdict = m._passes_budget({**base,
                                                "device_kind": kind})
            assert budget != "—"      # the number still prints
            assert verdict == "", kind
        # device_kind may also arrive from the enclosing record.
        _, verdict = m._passes_budget(base, "TPU v5 lite")
        assert verdict == " sane"

    def test_incomplete_records_stay_quiet(self):
        m = self._mod()
        assert m._passes_budget({}) == ("—", "")
        cpu = {"grid": [40, 40], "solve_seconds": 0.1, "iterations": 50,
               "backend": "xla", "platform": "cpu",
               "device_kind": "TPU v5e"}
        _, verdict = m._passes_budget(cpu)
        assert verdict == ""


class TestProbeSnippets:
    """The session's embedded probe programs only ever execute on a
    scarce healthy-tunnel window; a typo or a renamed import must be
    caught here, not there."""

    _NAMES = ("_KERNEL_PROBE", "_CA_PROBE", "_SHARDED_1X1",
              "_CA_SHARDED_1X1", "_RESIDENT_PROBE", "_BIG_GRID")

    @pytest.mark.parametrize("name", _NAMES)
    def test_parses_and_imports_resolve(self, name):
        import ast
        import importlib

        src = getattr(tpu_session, name)
        tree = ast.parse(src)
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module \
                    and node.module.startswith("poisson_tpu"):
                mod = importlib.import_module(node.module)
                for alias in node.names:
                    assert hasattr(mod, alias.name), (
                        f"{name}: {node.module}.{alias.name} missing"
                    )


class TestSessionResume:
    def _mklog(self, tmp_path, entries):
        log = tmp_path / "session.jsonl"
        log.write_text("".join(json.dumps(e) + "\n" for e in entries))
        return tmp_path

    def test_prior_filtering(self, tmp_path):
        outdir = self._mklog(tmp_path, [
            {"step": "old", "at": "2026-07-29T00:00:00+00:00", "ok": True,
             "result": {"v": 1}},
            {"step": "fresh", "at": "2026-07-30T06:00:00+00:00", "ok": True,
             "result": {"v": 2}},
            {"step": "failed", "at": "2026-07-30T06:01:00+00:00",
             "ok": False, "rc": 1},
            {"step": "identity", "at": "2026-07-30T06:02:00+00:00",
             "ok": True, "result": {"platform": "tpu"}},
            {"step": "nullres", "at": "2026-07-30T06:03:00+00:00",
             "ok": True, "result": None},
        ])
        s = tpu_session.Session(
            outdir, resume_after="2026-07-30T00:00:00+00:00"
        )
        # old (stale), failed, identity (always live), and null results
        # are all excluded; only the fresh ok step replays.
        assert set(s.prior) == {"fresh"}

    _LAYOUT_ENTRIES = [
        {"step": "kernel_probe", "at": "2026-07-30T06:00:00+00:00",
         "ok": True, "result": {"serial_reduce": True, "ok": True}},
        {"step": "kernel_probe_serial",
         "at": "2026-07-30T06:05:00+00:00",
         "ok": True, "result": {"serial_reduce": True, "ok": True}},
        # every layout-dependent step is filtered, not just the
        # probes (review finding): a CA number measured under
        # serial-Kahan is not evidence for a per-strip session
        {"step": "ca_probe", "at": "2026-07-30T06:10:00+00:00",
         "ok": True, "result": {"serial_reduce": True, "ok": True}},
        # bench.py records the layout under detail (review finding:
        # the filter must look there, not only at the top level) ...
        {"step": "bench_800x1200", "at": "2026-07-30T06:15:00+00:00",
         "ok": True, "result": {"value": 1.0, "detail":
                                {"backend": "pallas_fused",
                                 "serial_reduce": True}}},
        # ... an xla-demoted bench makes no layout claim (no Pallas
        # kernel ran; the stamp is just the ambient env) ...
        {"step": "bench_1600x2400", "at": "2026-07-30T06:17:00+00:00",
         "ok": True, "result": {"value": 2.0, "detail":
                                {"backend": "xla",
                                 "serial_reduce": True}}},
        # ... and roofline.py nests it per solver row
        {"step": "roofline_2400x3200", "at": "2026-07-30T06:20:00+00:00",
         "ok": True, "result": {"solver": [{"serial_reduce": True},
                                           {"serial_reduce": True}]}},
        # steps that record no layout replay regardless
        {"step": "curve_800x1200", "at": "2026-07-30T06:25:00+00:00",
         "ok": True, "result": {"rows": 989}},
    ]

    def _session(self, tmp_path, monkeypatch, artifact=None):
        import benchmarks.evidence_paths as ep

        target = tmp_path / "layout_decision.json"
        if artifact is not None:
            target.write_text(json.dumps(artifact))
        monkeypatch.setattr(ep, "LAYOUT_DECISION_PATH", target)
        outdir = self._mklog(tmp_path, self._LAYOUT_ENTRIES)
        return tpu_session.Session(
            outdir, resume_after="2026-07-30T00:00:00+00:00"
        )

    def test_replayed_layout_mismatch_is_dropped(self, tmp_path,
                                                 monkeypatch):
        # Steps recorded under serial-Kahan must not replay into a
        # launch that would run them per-strip: the gate would credit
        # the wrong layout and the evidence the wrong provenance
        # (round-4 advisor finding + review). Matching env: all stand.
        monkeypatch.delenv("POISSON_TPU_SERIAL_REDUCE", raising=False)
        s = self._session(tmp_path, monkeypatch)
        # env pins per-strip, no artifact: every serial-run Pallas step
        # is dropped wherever it recorded its layout; the explicitly-
        # serial A/B step, the layout-free curve step, and the
        # xla-demoted bench keep their replays.
        assert set(s.prior) == {"kernel_probe_serial", "bench_1600x2400",
                                "curve_800x1200"}
        monkeypatch.setenv("POISSON_TPU_SERIAL_REDUCE", "1")
        s = self._session(tmp_path, monkeypatch)
        assert set(s.prior) == {e["step"] for e in self._LAYOUT_ENTRIES}

    def test_bench_replay_honors_adopted_artifact(self, tmp_path,
                                                  monkeypatch):
        # bench.py adopts layout_decision.json when the env is unset, so
        # a serial-recorded bench replay IS what a live re-run would
        # measure when the artifact says serial — dropping it would burn
        # the window re-measuring identical numbers (review finding).
        # Probes and rooflines read the env only and are still dropped.
        monkeypatch.delenv("POISSON_TPU_SERIAL_REDUCE", raising=False)
        s = self._session(tmp_path, monkeypatch,
                          artifact={"serial_reduce": True, "reason": "ab"})
        assert set(s.prior) == {"kernel_probe_serial", "bench_800x1200",
                                "bench_1600x2400", "curve_800x1200"}

    def test_no_resume_means_no_prior(self, tmp_path):
        outdir = self._mklog(tmp_path, [
            {"step": "fresh", "at": "2026-07-30T06:00:00+00:00", "ok": True,
             "result": {"v": 2}},
        ])
        assert tpu_session.Session(outdir).prior == {}

    def test_replay_returns_prior_result(self, tmp_path):
        outdir = self._mklog(tmp_path, [
            {"step": "fresh", "at": "2026-07-30T06:00:00+00:00", "ok": True,
             "result": {"v": 2}},
        ])
        s = tpu_session.Session(
            outdir, resume_after="2026-07-30T00:00:00+00:00"
        )
        got = s.run("fresh", ["false"], timeout=5, parse_json_tail=True)
        assert got == {"v": 2}  # the subprocess ("false") never ran

    def test_abort_skips_subsequent_steps(self, tmp_path):
        s = tpu_session.Session(tmp_path)
        s.aborted = True
        got = s.run("anything", ["true"], timeout=5, parse_json_tail=True)
        assert got.get("skipped") and not got.get("timeout")

    def test_step_success_and_failure_recording(self, tmp_path):
        s = tpu_session.Session(tmp_path)
        ok = s.run("good", [sys.executable, "-c", "print('{\"x\": 1}')"],
                   timeout=30, parse_json_tail=True)
        assert ok == {"x": 1}
        bad = s.run("bad", [sys.executable, "-c",
                            "import sys; print('boom', file=sys.stderr); "
                            "sys.exit(3)"], timeout=30)
        assert bad == {"ok": False, "rc": 3}
        # full stderr rides along as a file for root-causing
        assert (tmp_path / "bad_stderr.txt").read_text().strip() == "boom"

    def test_extra_env_reaches_the_step(self, tmp_path):
        s = tpu_session.Session(tmp_path)
        got = s.run("env", [sys.executable, "-c",
                            "import os, json; "
                            "print(json.dumps({'b': os.environ.get('BENCH_BACKEND')}))"],
                    timeout=30, parse_json_tail=True,
                    extra_env={"BENCH_BACKEND": "pallas_ca"})
        assert got == {"b": "pallas_ca"}

    def test_decide_layout_artifact_semantics(self, tmp_path, monkeypatch):
        import benchmarks.evidence_paths as ep

        target = tmp_path / "layout_decision.json"
        monkeypatch.setattr(ep, "LAYOUT_DECISION_PATH", target)
        s = tpu_session.Session(tmp_path)
        s.decide_layout(False, "inconclusive", affirmative=False)
        assert not target.exists()  # no artifact without evidence
        s.decide_layout(True, "serial proved healthy")
        assert json.loads(target.read_text())["serial_reduce"] is True
