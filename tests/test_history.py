"""Per-iteration history (the report's L2-error-vs-iteration curve)."""

import numpy as np

from poisson_tpu.config import Problem
from poisson_tpu.solvers.history import pcg_solve_history
from poisson_tpu.solvers.pcg import pcg_solve


def test_history_matches_solver():
    p = Problem(M=40, N=40)
    ref = pcg_solve(p)
    h = pcg_solve_history(p, budget=60)
    assert int(h.iterations) == int(ref.iterations)
    np.testing.assert_allclose(
        np.asarray(h.w), np.asarray(ref.w), rtol=0, atol=1e-12
    )


def test_history_curves_shape_and_freeze():
    p = Problem(M=40, N=40)
    h = pcg_solve_history(p, budget=60)
    k = int(h.iterations)  # 50
    assert h.diffs.shape == (60,)
    # Frozen after convergence: tail equals the value at convergence.
    np.testing.assert_array_equal(
        np.asarray(h.diffs[k:]), np.asarray(h.diffs[k - 1])
    )
    # Final update norm is below delta, earlier ones above.
    assert float(h.diffs[k - 1]) < p.delta < float(h.diffs[k - 2])


def test_history_error_decreases_to_solver_accuracy():
    p = Problem(M=40, N=40)
    h = pcg_solve_history(p, budget=60)
    errs = np.asarray(h.l2_errors)
    # The error curve falls by >10x from start to convergence and ends at
    # the discretisation level.
    assert errs[0] / errs[-1] > 10
    assert errs[-1] < 5e-3


def test_history_without_error_recording():
    h = pcg_solve_history(Problem(M=20, N=20), budget=40, record_error=False)
    assert h.l2_errors is None
    assert int(h.iterations) > 0
