"""Chaos campaign: every named scenario runs green, deterministically,
asserting the no-lost-request invariant from the emitted serve.* metrics
snapshot (tier-1, CPU; -m serve)."""

import json

import pytest

from poisson_tpu.obs import metrics
from poisson_tpu.testing import chaos

pytestmark = pytest.mark.serve

# The acceptance matrix: the campaign must exercise each of these
# survival properties in at least one scenario.
REQUIRED = ("breaker-trip", "deadline-mid-chunk", "poison-requeue",
            "overload-shed")


@pytest.fixture(autouse=True)
def _fresh_registry():
    yield
    metrics.reset()


def test_required_scenarios_registered():
    names = chaos.scenario_names()
    for required in REQUIRED:
        assert required in names


@pytest.mark.parametrize("name", chaos.scenario_names())
def test_scenario_green_with_invariant(name):
    report = chaos.run_scenario(name, seed=0)
    assert report["ok"], report["checks"]
    # The invariant is read from the scenario's own metrics snapshot —
    # the emitted counters, not the service's in-memory ledger.
    snap = report["metrics_snapshot"]["counters"]
    admitted = snap.get("serve.admitted", 0)
    terminated = (snap.get("serve.completed", 0)
                  + snap.get("serve.errors", 0)
                  + snap.get("serve.shed", 0))
    assert admitted - terminated == 0
    assert report["invariant"]["lost"] == 0


def test_campaign_is_deterministic_under_a_seed():
    def fingerprint(campaign):
        return json.dumps(
            [{k: v for k, v in s.items() if k != "detail"}
             for s in campaign["scenarios"]],
            sort_keys=True, default=str,
        )

    a = chaos.run_campaign(["poison-requeue", "breaker-trip"], seed=3)
    b = chaos.run_campaign(["poison-requeue", "breaker-trip"], seed=3)
    assert a["ok"] and fingerprint(a) == fingerprint(b)


def test_campaign_writes_per_scenario_artifacts(tmp_path):
    out = tmp_path / "chaos"
    campaign = chaos.run_campaign(["overload-shed"], seed=0,
                                  out_dir=str(out))
    assert campaign["ok"]
    snap = json.loads((out / "metrics-overload-shed.json").read_text())
    assert snap["counters"]["serve.admitted"] == 14
    # Prometheus text of the same snapshot, parseable with the serve
    # counters intact.
    from poisson_tpu.obs import export

    parsed = export.parse_text(
        (out / "metrics-overload-shed.prom").read_text())
    assert parsed["poisson_tpu_serve_admitted"]["value"] == 14
    report = json.loads((out / "campaign.json").read_text())
    assert report["ok"] and len(report["scenarios"]) == 1


def test_unknown_scenario_raises():
    with pytest.raises(KeyError, match="unknown chaos scenario"):
        chaos.run_scenario("no-such-scenario")


def test_virtual_clock():
    vc = chaos.VirtualClock(start=5.0)
    assert vc() == 5.0
    vc.sleep(2.0)
    vc.advance(1.0)
    assert vc.now() == 8.0
    vc.sleep(-1.0)                     # sleeping never rewinds time
    assert vc.now() == 8.0


# -- CLI ----------------------------------------------------------------


def test_chaos_cli_list(capsys):
    from poisson_tpu.cli import main

    assert main(["chaos", "--list"]) == 0
    out = capsys.readouterr().out.split()
    for required in REQUIRED:
        assert required in out


def test_chaos_cli_named_scenario(capsys):
    from poisson_tpu.cli import main

    assert main(["chaos", "overload-shed", "--seed", "0"]) == 0
    out = capsys.readouterr().out
    assert "ok  overload-shed" in out
    assert "chaos campaign ok" in out


def test_chaos_cli_json_verdict(capsys):
    from poisson_tpu.cli import main

    assert main(["chaos", "poison-requeue", "--json"]) == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["ok"] and rec["scenarios"][0]["invariant"]["lost"] == 0


def test_chaos_cli_rejects_bad_usage():
    from poisson_tpu.cli import main

    with pytest.raises(SystemExit):
        main(["chaos"])                         # nothing to run
    with pytest.raises(SystemExit):
        main(["chaos", "--all", "overload-shed"])   # both forms
    with pytest.raises(SystemExit):
        main(["chaos", "no-such-scenario"])
