"""Precision-policy tests (SURVEY §7.3 — the hard correctness risk).

The reference is fp64-only; TPUs want fp32. The fictitious-domain matrix has
dynamic range ~1/ε·h⁻² (κ ~ 1e11 at 800×1200), so *unscaled* fp32 PCG
diverges. The framework's answer is symmetric diagonal scaling: plain CG on
Ã = D^{-1/2}AD^{-1/2} (unit diagonal, O(1) entries) is iterate-identical to
Jacobi-PCG on A, and in fp32 it reproduces the fp64 golden iteration counts
exactly. These tests pin that property.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from poisson_tpu.analysis import l2_error_vs_analytic
from poisson_tpu.config import Problem
from poisson_tpu.solvers.pcg import pcg_solve


def test_scaled_f64_is_iterate_identical_to_pcg():
    p = Problem(M=40, N=40)
    r_pcg = pcg_solve(p, dtype=jnp.float64, scaled=False)
    r_scl = pcg_solve(p, dtype=jnp.float64, scaled=True)
    assert int(r_pcg.iterations) == int(r_scl.iterations) == 50
    np.testing.assert_allclose(
        np.asarray(r_scl.w), np.asarray(r_pcg.w), atol=1e-12
    )


def test_scaled_f32_matches_f64_golden_small():
    p = Problem(M=40, N=40)
    r64 = pcg_solve(p, dtype=jnp.float64)
    r32 = pcg_solve(p, dtype=jnp.float32)  # scaled by default for f32
    assert int(r32.iterations) == int(r64.iterations) == 50
    np.testing.assert_allclose(
        np.asarray(r32.w, np.float64), np.asarray(r64.w), atol=1e-5
    )


@pytest.mark.slow
def test_scaled_f32_matches_f64_golden_large():
    p = Problem(M=400, N=600)
    r32 = pcg_solve(p, dtype=jnp.float32)
    assert int(r32.iterations) == 546
    err = float(l2_error_vs_analytic(p, r32.w.astype(jnp.float64)))
    # fp64 reference error is 3.06e-4; fp32-scaled must stay at that level.
    assert err < 4e-4


@pytest.mark.slow
def test_f32_setup_precision_is_the_hazard():
    """Canary documenting the precision policy: building the coefficient
    fields (1/ε blends, D, scaling) in fp32 degrades the *problem itself* —
    host fp64 setup is what keeps fp32 solves on the fp64 trajectory.
    If device-f32 setup ever matches host setup here, the default could be
    relaxed."""
    import jax

    from poisson_tpu.parallel import make_solver_mesh, pcg_solve_sharded

    p = Problem(M=400, N=600)
    mesh = make_solver_mesh(jax.devices()[:8])
    host = pcg_solve_sharded(p, mesh, dtype=jnp.float32, setup="host")
    dev = pcg_solve_sharded(p, mesh, dtype=jnp.float32, setup="device")
    e_host = float(l2_error_vs_analytic(p, host.w.astype(jnp.float64)))
    e_dev = float(l2_error_vs_analytic(p, dev.w.astype(jnp.float64)))
    assert int(host.iterations) == 546
    assert e_host < 4e-4  # fp64 reference level (3.1e-4)
    assert e_dev > 5 * e_host
