"""Golden-iteration-count and accuracy regressions for the PCG solver.

The reference's de-facto regression oracle is the grid-determined PCG
iteration count (SURVEY §4.1). Oracle values below were obtained by compiling
and running the reference programs directly (stage0 as-is; stage2 at P=1 with
a single-process MPI stub):

    stage0 (unweighted norm): 10×10→17, 20×20→31, 40×40→61
    stage2 (weighted norm):   40×40→50, 400×600→546, 800×1200→989

546/989 match the published tables (BASELINE.md). The committed 40×40 weighted
code gives 50, not the reports' 60 — the reports were generated from a variant
not in the repo; we pin the as-committed behaviour.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from poisson_tpu.config import Problem
from poisson_tpu.models.fictitious_domain import analytic_solution, is_in_domain
from poisson_tpu.solvers.pcg import pcg_solve


@pytest.mark.parametrize(
    "M,N,weighted,expected",
    [
        (10, 10, False, {17}),
        (20, 20, False, {31}),
        # {61,62}: with host-fp64 setup CPU XLA lands on the oracle's 61, but
        # the 61st unweighted diff sits within one ulp of δ, so a different
        # backend's jnp.sum reduction order can legitimately give 62.
        (40, 40, False, {61, 62}),
        (40, 40, True, {50}),
    ],
)
def test_golden_iterations_small(M, N, weighted, expected):
    r = pcg_solve(Problem(M=M, N=N, weighted_norm=weighted))
    assert int(r.iterations) in expected
    assert float(r.diff) < 1e-6


@pytest.mark.slow
@pytest.mark.parametrize("M,N,expected", [(400, 600, 546), (800, 1200, 989)])
def test_golden_iterations_large(M, N, expected):
    r = pcg_solve(Problem(M=M, N=N))
    assert int(r.iterations) == expected


@pytest.mark.xslow
def test_fp32_scaled_golden_1600x2400():
    """Precision policy at the reference's second-largest grid: fp32 on the
    scaled system must stay within one iteration of the fp64 oracle's 1858
    (SURVEY §7.3's hardest correctness risk)."""
    r = pcg_solve(Problem(M=1600, N=2400), dtype=jnp.float32)
    assert abs(int(r.iterations) - 1858) <= 1
    assert float(r.diff) < 1e-6


def _l2_error_inside(p: Problem, w) -> float:
    """L2(D) error vs u = (1−x²−4y²)/10, interior ellipse nodes only
    (the reference's analytic accuracy control, SURVEY §4.2)."""
    u = analytic_solution(p)
    i = jnp.arange(p.M + 1)
    j = jnp.arange(p.N + 1)
    x = (p.x_min + i * p.h1)[:, None]
    y = (p.y_min + j * p.h2)[None, :]
    mask = is_in_domain(x, y)
    err2 = jnp.where(mask, (w - u) ** 2, 0.0)
    return float(jnp.sqrt(jnp.sum(err2) * p.h1 * p.h2))


def test_analytic_accuracy_and_convergence_under_refinement():
    errs = []
    for M in (20, 40, 80):
        p = Problem(M=M, N=M)
        r = pcg_solve(p)
        errs.append(_l2_error_inside(p, r.w))
    # Fictitious-domain accuracy: error decreases under refinement.
    assert errs[1] < errs[0]
    assert errs[2] < errs[1]
    assert errs[2] < 2e-3


def test_solution_is_nonnegative_and_bounded():
    p = Problem(M=40, N=40)
    r = pcg_solve(p)
    w = np.asarray(r.w)
    assert w.min() > -1e-8
    assert w.max() < 0.12  # max of exact solution is 0.1


def test_float32_solves_same_problem():
    """Precision policy (SURVEY §7.3): f32 must converge to the same solution
    within f32-appropriate tolerance and a similar iteration count."""
    p = Problem(M=40, N=40, delta=1e-4)
    r64 = pcg_solve(p, dtype=jnp.float64)
    r32 = pcg_solve(p, dtype=jnp.float32)
    assert abs(int(r32.iterations) - int(r64.iterations)) <= 3
    np.testing.assert_allclose(
        np.asarray(r32.w), np.asarray(r64.w), atol=5e-4
    )
