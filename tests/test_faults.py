"""Fault-injection suite for the resilience layer (tier-1, CPU-only).

Every recovery path the framework claims — in-loop divergence detection,
restart-from-last-good-iterate, precision escalation, hardened checkpoint
fallback, preemption resume — is exercised here against injected faults
(``poisson_tpu.testing.faults``), on small grids so the whole suite stays
fast enough for tier-1.
"""

import glob
import os
import warnings

import numpy as np
import pytest

from poisson_tpu.config import Problem
from poisson_tpu.solvers import checkpoint as ckpt
from poisson_tpu.solvers.pcg import (
    FLAG_CONVERGED,
    FLAG_NONFINITE,
    FLAG_STAGNATED,
    pcg_solve,
    resolve_dtype,
    resolve_scaled,
)
from poisson_tpu.solvers.resilient import (
    DivergenceError,
    RecoveryPolicy,
    pcg_solve_resilient,
)
from poisson_tpu.testing.faults import (
    FaultPlan,
    PreemptionInjected,
    chunk_hook,
    corrupt_file,
    inject_nan,
)

pytestmark = pytest.mark.faults


def _fp(problem, dtype=None):
    d = resolve_dtype(dtype)
    return ckpt._fingerprint(problem, d, resolve_scaled(None, d))


# ---------------------------------------------------------------------------
# In-loop detection
# ---------------------------------------------------------------------------


def test_nonfinite_detection_stops_and_keeps_last_good(tmp_path):
    """An injected NaN is flagged within the next chunk instead of burning
    the rest of the iteration budget, and the poisoned state is never
    written over the last good checkpoint."""
    p = Problem(M=40, N=40)
    path = str(tmp_path / "ck.npz")
    hook = chunk_hook(FaultPlan(nan_at_iteration=15))
    res = ckpt.pcg_solve_checkpointed(p, path, chunk=10, on_chunk=hook)
    assert int(res.flag) == FLAG_NONFINITE
    # Detection fires on the first post-injection iteration (k=21), not at
    # the iteration cap.
    assert int(res.iterations) <= 25
    state = ckpt.load_state(path, _fp(p))
    assert int(state.k) == 20                       # the pre-fault boundary
    assert np.isfinite(np.asarray(state.w)).all()
    assert np.isfinite(np.asarray(state.r)).all()


def test_breakdown_detection_on_unreachable_tolerance(tmp_path):
    """An unreachable tolerance drives r → 0 until the degenerate-
    direction guard fires: the solve stops with FLAG_BREAKDOWN long
    before the (M-1)(N-1) cap, and the non-converged stop keeps its
    checkpoint for diagnosis (pre-hardening, done-means-converged cleanup
    would have deleted it)."""
    from poisson_tpu.solvers.pcg import FLAG_BREAKDOWN

    p = Problem(M=40, N=40, delta=1e-300)
    path = str(tmp_path / "ck.npz")
    res = ckpt.pcg_solve_checkpointed(p, path, chunk=50,
                                      stagnation_window=30)
    assert int(res.flag) == FLAG_BREAKDOWN
    assert int(res.iterations) < 100            # cap is (M-1)(N-1) = 1521
    assert os.path.exists(path)


def test_stagnation_detection_unit():
    """The stall counter at the make_pcg_body level: a synthetic backend
    whose update norm never improves stops with FLAG_STAGNATED exactly one
    iteration after the window closes (the real problem's diff improves
    every iteration until breakdown, so the mechanism needs a fake)."""
    import jax.numpy as jnp

    from poisson_tpu.solvers.pcg import PCGOps, pcg_loop

    ops = PCGOps(
        apply_A=lambda p: p,
        apply_Dinv=lambda r: r,
        dot=lambda u, v: jnp.asarray(1.0),      # no breakdown, no progress
        sqnorm=lambda u: jnp.asarray(1.0),      # constant ||dw||
        exchange=lambda p: p,
    )
    s = pcg_loop(ops, jnp.ones((4, 4)), delta=0.5, max_iter=1000,
                 weighted_norm=False, h1=1.0, h2=1.0, stagnation_window=25)
    assert int(s.flag) == FLAG_STAGNATED
    assert int(s.k) == 26                       # window + the first best
    # And the same loop without the window runs to its budget.
    s2 = pcg_loop(ops, jnp.ones((4, 4)), delta=0.5, max_iter=100,
                  weighted_norm=False, h1=1.0, h2=1.0)
    assert int(s2.k) == 100 and int(s2.flag) == 0


def test_converging_solves_keep_their_iteration_counts():
    """Detection must be observation-only for healthy solves: the golden
    40x40 count survives with stagnation detection armed."""
    p = Problem(M=40, N=40)
    ref = pcg_solve(p)
    res = pcg_solve_resilient(
        p, chunk=10, policy=RecoveryPolicy(stagnation_window=200),
    )
    assert int(res.iterations) == int(ref.iterations) == 50
    assert int(res.flag) == FLAG_CONVERGED
    np.testing.assert_allclose(
        np.asarray(res.w), np.asarray(ref.w), rtol=0, atol=1e-12
    )


# ---------------------------------------------------------------------------
# Recovery (acceptance: injected mid-run NaN recovers and converges to the
# same tolerance as an uninjected run)
# ---------------------------------------------------------------------------


def test_nan_injection_recovers_and_converges():
    p = Problem(M=40, N=40)
    ref = pcg_solve(p)
    hook = chunk_hook(FaultPlan(nan_at_iteration=15))
    with pytest.warns(RuntimeWarning, match="nonfinite.*restart"):
        res = pcg_solve_resilient(p, chunk=10, on_chunk=hook)
    assert int(res.flag) == FLAG_CONVERGED
    assert float(res.diff) < p.delta                # same tolerance met
    # Same answer to within the convergence tolerance (the recovered path
    # runs different iterates, so bit-equality is not expected).
    err = np.abs(np.asarray(res.w) - np.asarray(ref.w)).max()
    assert err < 50 * p.delta
    # Recovery restarted from iteration 20's iterate, not from scratch.
    assert int(res.iterations) > int(ref.iterations)


def test_nan_injection_into_solution_buffer_recovers():
    """The injected buffer need not be the residual: a poisoned solution
    grid w is equally recovered (the restart re-derives r from w_good)."""
    p = Problem(M=40, N=40)
    hook = chunk_hook(FaultPlan(nan_at_iteration=15, nan_buffer="w"))
    with pytest.warns(RuntimeWarning, match="restart"):
        res = pcg_solve_resilient(p, chunk=10, on_chunk=hook)
    assert int(res.flag) == FLAG_CONVERGED
    assert float(res.diff) < p.delta


def test_escalation_ladder_reaches_f64():
    """Two failures at the same precision escalate f32 -> f64 (restart
    alone first, then the ladder)."""
    p = Problem(M=40, N=40)
    count = {"n": 0}

    def hook(state, chunks_done):
        if count["n"] < 2 and int(state.k) >= 10:
            count["n"] += 1
            return inject_nan(state)
        return None

    with warnings.catch_warnings(record=True) as ws:
        warnings.simplefilter("always")
        res = pcg_solve_resilient(p, dtype="float32", chunk=10,
                                  on_chunk=hook)
    messages = [str(w.message) for w in ws]
    assert any("restart@float32" in m for m in messages)
    assert any("escalate->float64" in m for m in messages)
    assert int(res.flag) == FLAG_CONVERGED
    assert np.asarray(res.w).dtype == np.float64


def test_recovery_budget_exhaustion_raises_with_diagnostics():
    p = Problem(M=40, N=40)

    def hook(state, chunks_done):   # poison every boundary: unrecoverable
        return inject_nan(state)

    with pytest.raises(DivergenceError) as exc_info, \
            warnings.catch_warnings():
        warnings.simplefilter("ignore")
        pcg_solve_resilient(
            p, chunk=10, on_chunk=hook,
            policy=RecoveryPolicy(max_restarts=2, escalate=False),
        )
    diag = exc_info.value.diagnostics
    assert diag["verdict"] == "nonfinite"
    assert diag["restarts"] == 3        # the raising attempt included
    assert len(diag["history"]) == 2    # the two restarts that were granted
    assert diag["problem"] == "40x40"


# ---------------------------------------------------------------------------
# Preemption (acceptance: a chunked solve killed between chunks resumes
# from checkpoint and matches the uninterrupted final residual)
# ---------------------------------------------------------------------------


def test_preemption_resume_matches_uninterrupted(tmp_path):
    p = Problem(M=40, N=40)
    path = str(tmp_path / "ck.npz")
    hook = chunk_hook(FaultPlan(preempt_after_chunks=2))
    with pytest.raises(PreemptionInjected):
        ckpt.pcg_solve_checkpointed(p, path, chunk=10, on_chunk=hook)
    assert os.path.exists(path)         # the kill landed between chunks

    uninterrupted = ckpt.pcg_solve_checkpointed(
        p, str(tmp_path / "ref.npz"), chunk=10
    )
    resumed = ckpt.pcg_solve_checkpointed(p, path, chunk=10)
    assert int(resumed.iterations) == int(uninterrupted.iterations)
    assert float(resumed.diff) == float(uninterrupted.diff)
    np.testing.assert_array_equal(                  # exact resume
        np.asarray(resumed.w), np.asarray(uninterrupted.w)
    )
    assert not os.path.exists(path)     # converged run cleaned up


def test_sharded_preemption_resume_matches(tmp_path):
    """The same kill-between-chunks drill on the distributed solver (the
    virtual 8-device CPU mesh)."""
    from poisson_tpu.parallel import (
        make_solver_mesh,
        pcg_solve_sharded_checkpointed,
    )

    p = Problem(M=40, N=40)
    mesh = make_solver_mesh()
    path = str(tmp_path / "ck.npz")
    hook = chunk_hook(FaultPlan(preempt_after_chunks=2))
    with pytest.raises(PreemptionInjected):
        pcg_solve_sharded_checkpointed(p, mesh, path, chunk=10,
                                       on_chunk=hook)
    assert os.path.exists(path)
    uninterrupted = pcg_solve_sharded_checkpointed(
        p, mesh, str(tmp_path / "ref.npz"), chunk=10
    )
    resumed = pcg_solve_sharded_checkpointed(p, mesh, path, chunk=10)
    assert int(resumed.iterations) == int(uninterrupted.iterations)
    assert float(resumed.diff) == float(uninterrupted.diff)
    np.testing.assert_array_equal(
        np.asarray(resumed.w), np.asarray(uninterrupted.w)
    )


def test_resilient_resumes_across_preemption(tmp_path):
    """Preempt a checkpointed *resilient* solve, then finish it in a fresh
    call — the production recovery workflow end to end."""
    p = Problem(M=40, N=40)
    path = str(tmp_path / "ck.npz")
    hook = chunk_hook(FaultPlan(preempt_after_chunks=2))
    with pytest.raises(PreemptionInjected):
        pcg_solve_resilient(p, chunk=10, checkpoint_path=path,
                            on_chunk=hook)
    ref = pcg_solve(p)
    res = pcg_solve_resilient(p, chunk=10, checkpoint_path=path)
    assert int(res.flag) == FLAG_CONVERGED
    assert int(res.iterations) == int(ref.iterations)
    np.testing.assert_allclose(
        np.asarray(res.w), np.asarray(ref.w), rtol=0, atol=1e-12
    )


# ---------------------------------------------------------------------------
# Hardened checkpoints (acceptance: a corrupted latest checkpoint triggers
# fallback to the previous one)
# ---------------------------------------------------------------------------


def _two_generations(tmp_path, p):
    """Run 3 chunks of 10 with retention: newest generation at k=30,
    previous at k=20."""
    path = str(tmp_path / "ck.npz")
    ckpt.pcg_solve_checkpointed(p.with_(max_iter=30), path, chunk=10,
                                keep_checkpoint=True)
    assert os.path.exists(path) and os.path.exists(path + ".1")
    return path


@pytest.mark.parametrize("mode", ["flip", "truncate", "zero"])
def test_corrupt_latest_falls_back_to_previous(tmp_path, mode):
    p = Problem(M=40, N=40)
    path = _two_generations(tmp_path, p)
    corrupt_file(path, mode)
    with pytest.warns(RuntimeWarning, match="previous checkpoint"):
        state = ckpt.load_state(path, _fp(p))
    assert int(state.k) == 20           # the previous generation
    # And the fallback state actually finishes the solve correctly.
    ref = pcg_solve(p)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        res = ckpt.pcg_solve_checkpointed(p, path, chunk=10)
    assert int(res.iterations) == int(ref.iterations)
    np.testing.assert_allclose(
        np.asarray(res.w), np.asarray(ref.w), rtol=0, atol=1e-12
    )


def test_all_generations_corrupt_starts_over(tmp_path):
    p = Problem(M=40, N=40)
    path = _two_generations(tmp_path, p)
    corrupt_file(path, "truncate")
    corrupt_file(path + ".1", "zero")
    with pytest.warns(RuntimeWarning, match="starting the solve from"):
        state = ckpt.load_state(path, _fp(p))
    assert state is None


def test_crc_catches_silent_payload_corruption(tmp_path):
    """A bit-rot pattern that keeps the npz structurally valid — an array
    value changed, the stored CRC untouched — is caught by the integrity
    check, the case no structural parser can see."""
    p = Problem(M=40, N=40)
    path = str(tmp_path / "ck.npz")
    ckpt.pcg_solve_checkpointed(p.with_(max_iter=10), path, chunk=10,
                                keep_checkpoint=True, keep_last=1)
    with np.load(path) as d:
        data = {k: d[k] for k in d.files}
    data["w"] = data["w"].copy()
    data["w"][5, 5] += 1.0              # silent flip, CRC left stale
    np.savez(path, **data)
    with pytest.warns(RuntimeWarning, match="integrity"):
        assert ckpt.load_state(path, _fp(p), keep_last=1) is None


def test_atomic_write_leaves_no_partials_on_midwrite_kill(tmp_path,
                                                          monkeypatch):
    p = Problem(M=40, N=40)
    path = str(tmp_path / "ck.npz")
    ckpt.pcg_solve_checkpointed(p.with_(max_iter=10), path, chunk=10,
                                keep_checkpoint=True)
    good = ckpt.load_state(path, _fp(p))

    def dying_savez(file, **arrays):
        with open(file, "wb") as f:
            f.write(b"partial garbage")
        raise OSError("simulated kill mid-write")

    monkeypatch.setattr(ckpt.np, "savez", dying_savez)
    with pytest.raises(OSError, match="simulated kill"):
        ckpt.save_state(path, good, _fp(p))
    monkeypatch.undo()
    # No temp droppings, and the original checkpoint is intact.
    assert glob.glob(str(tmp_path / "*.tmp*")) == []
    reread = ckpt.load_state(path, _fp(p))
    assert int(reread.k) == int(good.k)
    np.testing.assert_array_equal(np.asarray(reread.w), np.asarray(good.w))


def test_fingerprint_mismatch_reported_clearly(tmp_path):
    p = Problem(M=40, N=40)
    path = str(tmp_path / "ck.npz")
    ckpt.pcg_solve_checkpointed(p.with_(max_iter=10), path, chunk=10,
                                keep_checkpoint=True)
    wrong = _fp(p.with_(delta=1e-4))
    with pytest.raises(ValueError) as exc_info:
        ckpt.load_state(path, wrong)
    msg = str(exc_info.value)
    # The report names the file and shows both fingerprints.
    assert "different problem" in msg
    assert "saved:" in msg and "requested:" in msg


def test_mismatched_newest_falls_back_to_matching_previous(tmp_path):
    """Retention also covers the mixed case: the newest generation belongs
    to another problem but an older one matches — resume from it (with a
    warning) instead of refusing outright."""
    p = Problem(M=40, N=40)
    path = str(tmp_path / "ck.npz")
    ckpt.pcg_solve_checkpointed(p.with_(max_iter=10), path, chunk=10,
                                keep_checkpoint=True)       # fp(p) at path
    # A newer generation written for a *different* problem rotates p's
    # file to .1 (same arrays; only the fingerprint matters here).
    state_a = ckpt.load_state(path, _fp(p))
    ckpt.save_state(path, state_a, _fp(p.with_(delta=1e-4)))
    with pytest.warns(RuntimeWarning, match="older checkpoint generation"):
        state = ckpt.load_state(path, _fp(p))
    assert state is not None and int(state.k) == 10


def test_escalated_checkpoint_outranks_stale_lower_precision(tmp_path):
    """Resume across an earlier run's escalation: the newest generation
    (written at an escalated precision) must win over the stale
    pre-escalation generation behind it, even though the latter matches
    the requested precision's fingerprint (review finding: the rung loop
    must be inside the generation walk, not outside)."""
    from poisson_tpu.solvers.resilient import _load_any_rung

    p = Problem(M=40, N=40)
    path = str(tmp_path / "ck.npz")
    scaled = resolve_scaled(None, "float32")    # fixed across the ladder

    def fp(dn):
        return ckpt._fingerprint(p, dn, scaled)

    # Era 1: an f32 run checkpoints at k=10 …
    ckpt.pcg_solve_checkpointed(p.with_(max_iter=10), path, chunk=10,
                                dtype="float32", scaled=scaled,
                                keep_checkpoint=True)
    # … then (simulated) escalates to f64 and checkpoints k=50, rotating
    # the f32 generation to .1.
    state32 = ckpt.load_state(path, fp("float32"))
    state64 = state32._replace(
        w=np.asarray(state32.w, np.float64),
        r=np.asarray(state32.r, np.float64),
        z=np.asarray(state32.z, np.float64),
        p=np.asarray(state32.p, np.float64),
        k=np.int32(50),
    )
    ckpt.save_state(path, state64, fp("float64"))

    state, dn = _load_any_rung(path, p, "float32", scaled, keep_last=2)
    assert dn == "float64"
    assert int(state.k) == 50                   # the escalated progress
    # And with the newest generation corrupted, the stale f32 one is still
    # a valid fallback.
    corrupt_file(path, "flip")
    with pytest.warns(RuntimeWarning, match="previous checkpoint"):
        state, dn = _load_any_rung(path, p, "float32", scaled, keep_last=2)
    assert dn == "float32" and int(state.k) == 10


def test_legacy_checkpoint_without_crc_or_flags_loads(tmp_path):
    """Pre-hardening files (no crc32, no verdict fields) still resume —
    the fleet's existing checkpoints must not be orphaned by an upgrade."""
    p = Problem(M=40, N=40)
    path = str(tmp_path / "ck.npz")
    ckpt.pcg_solve_checkpointed(p.with_(max_iter=10), path, chunk=10,
                                keep_checkpoint=True)
    with np.load(path) as d:
        data = {k: d[k] for k in d.files}
    legacy = {k: v for k, v in data.items()
              if k not in ("crc32", "flag", "best", "stall")}
    np.savez(path, **legacy)
    state = ckpt.load_state(path, _fp(p))
    assert int(state.k) == 10
    assert int(state.flag) == 0         # defaults backfilled


# ---------------------------------------------------------------------------
# CLI wiring
# ---------------------------------------------------------------------------


def test_cli_resilient_nan_injection(capsys):
    from poisson_tpu.cli import main

    assert main(["40", "40", "--backend", "xla", "--resilient",
                 "--chunk", "10", "--fault-nan-at", "15", "--json"]) == 0
    out = capsys.readouterr().out
    assert '"stopped": null' in out


def test_cli_preempt_resume_roundtrip(tmp_path, capsys):
    from poisson_tpu.cli import main

    ck = str(tmp_path / "ck.npz")
    rc = main(["40", "40", "--backend", "xla", "--checkpoint", ck,
               "--chunk", "10", "--fault-preempt-after", "2", "--json"])
    assert rc == 75                     # EX_TEMPFAIL: rerun to resume
    assert os.path.exists(ck)
    capsys.readouterr()
    assert main(["40", "40", "--backend", "xla", "--checkpoint", ck,
                 "--chunk", "10", "--json"]) == 0


def test_cli_corrupt_checkpoint_fallback(tmp_path, capsys):
    from poisson_tpu.cli import main

    ck = str(tmp_path / "ck.npz")
    assert main(["40", "40", "--backend", "xla", "--checkpoint", ck,
                 "--chunk", "10", "--fault-preempt-after", "2",
                 "--json"]) == 75
    capsys.readouterr()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        assert main(["40", "40", "--backend", "xla", "--checkpoint", ck,
                     "--chunk", "10", "--fault-corrupt-checkpoint", "flip",
                     "--json"]) == 0


def test_cli_fault_flags_need_a_chunked_driver():
    from poisson_tpu.cli import main

    with pytest.raises(SystemExit, match="chunk boundaries"):
        main(["40", "40", "--backend", "xla", "--fault-nan-at", "5"])
    with pytest.raises(SystemExit, match="retention"):
        main(["40", "40", "--backend", "xla", "--keep-last", "3"])
    with pytest.raises(SystemExit, match="native"):
        main(["40", "40", "--backend", "native", "--resilient"])
